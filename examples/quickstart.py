"""End-to-end tour of yuma_simulation_tpu.

Run from the repo root (or with the package installed):

    python examples/quickstart.py [--out-dir OUT]

Covers: one simulation, the reference artifacts (chart HTML + dividends
CSV), a vmap hyperparameter grid, and a sharded Monte-Carlo study with
checkpoint/resume. Everything runs on whatever JAX platform is available
(TPU if present, CPU otherwise).
"""

import argparse
import os
import pathlib
import sys

import numpy as np

import jax

# Self-locating like tools/*: `python examples/quickstart.py` works from
# anywhere without installing the package (PYTHONPATH cannot be used
# instead — setting it breaks the TPU plugin registration in some
# environments).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from yuma_simulation_tpu.models.config import (
    SimulationHyperparameters,
    YumaConfig,
    YumaSimulationNames,
)
from yuma_simulation_tpu.models.variants import canonical_versions
from yuma_simulation_tpu.parallel import make_mesh, montecarlo_total_dividends
from yuma_simulation_tpu.scenarios import create_case, get_cases
from yuma_simulation_tpu.simulation.engine import run_simulation
from yuma_simulation_tpu.simulation.sweep import config_grid, sweep_hyperparams
from yuma_simulation_tpu.utils import CheckpointedSweep, setup_logging, timed
from yuma_simulation_tpu.v1.api import (
    generate_chart_table,
    generate_total_dividends_table,
)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out-dir", type=pathlib.Path, default=pathlib.Path("quickstart_out"))
    args = parser.parse_args()
    args.out_dir.mkdir(parents=True, exist_ok=True)
    setup_logging()
    names = YumaSimulationNames()

    # 1. One scenario, one version - the reference's core operation.
    case = create_case("Case 3")
    dividends, bonds, incentives = run_simulation(case, names.YUMA2, YumaConfig())
    print("case 3 / yuma 2 total dividends:",
          {v: round(sum(series), 6) for v, series in dividends.items()})

    # 2. The reference's artifacts: dividends CSV + chart-table HTML.
    hp = SimulationHyperparameters(bond_penalty=0.99)
    df = generate_total_dividends_table(get_cases(), canonical_versions(), hp)
    csv_path = args.out_dir / "total_dividends_b0.99.csv"
    df.to_csv(csv_path, index=False, float_format="%.6f")
    html = generate_chart_table([case], canonical_versions()[:3], hp)
    html_path = args.out_dir / "chart_table.html"
    html_path.write_text(html.data, encoding="utf-8")
    print(f"wrote {csv_path} and {html_path}")

    # 3. A hyperparameter grid as ONE batched XLA computation.
    configs, points = config_grid(kappa=[0.4, 0.5, 0.6], bond_alpha=[0.05, 0.1])
    with timed("6-point grid", epochs=6 * case.num_epochs):
        ys = sweep_hyperparams(case, names.YUMA, configs)
    best = int(np.asarray(ys["dividends"]).sum(axis=(1, 2)).argmax())
    print("grid point with highest total dividends:", points[best])

    # 4. Sharded Monte-Carlo with checkpoint/resume.
    mesh = make_mesh()
    sweep = CheckpointedSweep(
        args.out_dir / "mc",
        num_chunks=4,
        tag="demo",
        config={"scenarios": 256, "epochs": 50, "V": 16, "M": 256, "seed": 0},
    )

    def chunk(i):
        return montecarlo_total_dividends(
            jax.random.key(i), 64, 50, 16, 256, names.YUMA, mesh=mesh
        )

    with timed("Monte-Carlo 256 scenarios", epochs=256 * 50):
        totals = sweep.run(chunk)
    print("MC dividend spread (std over scenarios):",
          np.round(totals.std(axis=0).mean(), 6))

    # 5. Throughput path: weights varying every epoch, epoch_impl="auto"
    # (on TPU this selects the single-Pallas-program scan — the bench.py
    # headline; elsewhere it falls back to the XLA epoch kernel).
    import jax.numpy as jnp

    from yuma_simulation_tpu.models.variants import variant_for_version
    from yuma_simulation_tpu.simulation.engine import simulate_scaled

    rng = np.random.default_rng(0)
    V, M, E = 16, 256, 200
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    scales = jnp.asarray(1.0 + 1e-6 * np.arange(E, dtype=np.float32))
    with timed(f"epoch-varying scan {V}x{M}", epochs=E):
        total, _ = simulate_scaled(
            W, S, scales, YumaConfig(), variant_for_version(names.YUMA),
            epoch_impl="auto",
        )
        np.asarray(total)
    print("varying-weights total dividends (sum):",
          float(np.asarray(total).sum().round(4)))


if __name__ == "__main__":
    main()
