"""Nox sessions mirroring the reference's dev workflow
(reference noxfile.py:130-206: format / lint / test / make_release) —
without its broken dependency-group residue (the reference installs pdm
groups its own pyproject never defines, SURVEY.md §2 last row; here every
session installs real extras/tools).

Run `nox -l` to list sessions. CI runs the same commands directly
(.github/workflows/ci.yml), so nox is a convenience for local dev parity,
not a second source of truth.
"""

from __future__ import annotations

import nox

nox.options.sessions = ["lint", "typecheck", "test"]

PY_VERSIONS = ["3.11", "3.12"]
LINT_TARGETS = (
    "yuma_simulation_tpu",
    "yuma_simulation",
    "scripts",
    "tests",
)


@nox.session(name="format")
def format_(session: nox.Session) -> None:
    """Auto-format with ruff (the reference uses ruff format + isort)."""
    session.install("ruff==0.8.4")
    session.run("ruff", "format", *LINT_TARGETS)
    session.run("ruff", "check", "--fix", *LINT_TARGETS)


@nox.session
def lint(session: nox.Session) -> None:
    """Static guarantees (README "Static guarantees"): the project's own
    whole-program TPU-discipline analyzer (tools/jaxlint — stdlib-ast,
    interprocedural since 0.15.0, --strict also fails on rotted
    suppressions) over all three roots, ruff, and mypy over the TPU
    package. The `analysis` session runs the full gate set (shapecheck,
    registry) with JSON artifacts."""
    session.install("ruff==0.8.4", "mypy==1.13.0", "-e", ".")
    session.run(
        "python", "-m", "tools.jaxlint",
        "yuma_simulation_tpu", "tools", "tests", "--strict",
    )
    session.run("ruff", "check", *LINT_TARGETS)
    session.run("mypy", "yuma_simulation_tpu")


@nox.session
def analysis(session: nox.Session) -> None:
    """Whole-program analysis lane (mirrors the CI `analysis` job):
    jaxlint --strict over yuma_simulation_tpu + tools + tests (tracing
    reach through the call graph, JX1xx concurrency discipline, JX2xx
    telemetry contracts, JX3xx wire contracts), wirecheck against the
    committed SCHEMAS.lock.json, the zero-compile shapecheck gate over
    the planner bucket grid, and the telemetry-registry runtime
    validation.
    JSON findings land in the session tmp dir, same schema CI uploads."""
    session.install("-e", ".[test]")
    import os

    tmp = session.create_tmp()
    session.run(
        "python", "-m", "tools.jaxlint",
        "yuma_simulation_tpu", "tools", "tests", "--strict",
        "--artifact", os.path.join(tmp, "jaxlint_findings.json"),
    )
    session.run(
        "python", "-m", "tools.wirecheck",
        "yuma_simulation_tpu", "tools", "tests", "--check", "--strict",
        "--artifact", os.path.join(tmp, "wirecheck_schemas.json"),
    )
    session.run(
        "python", "-m", "tools.shapecheck", "--check",
        "--artifact", os.path.join(tmp, "shapecheck_report.json"),
        env={"JAX_PLATFORMS": "cpu"},
    )
    session.run(
        "python", "-c",
        "from yuma_simulation_tpu.telemetry.registry import "
        "validate_registry; import sys; p = validate_registry(); "
        "print('\\n'.join(p)); sys.exit(1 if p else 0)",
    )


@nox.session
def typecheck(session: nox.Session) -> None:
    """mypy over the legacy compat package only — the TPU package is
    typechecked by the lint session above; keeping it out of here stops
    the default `nox` run paying the same mypy pass twice."""
    session.install("mypy==1.13.0", "-e", ".")
    session.run("mypy", "yuma_simulation")


#: Shard count for the tier-1 suite: several hundred distinct XLA-CPU
#: compilations in a single process eventually segfault inside
#: `backend_compile_and_load` on this toolchain (observed reproducibly
#: around the ~220th test; each shard alone is solid). The hand-curated
#: chunk lists this replaced (0.21.0 and earlier) silently DROPPED any
#: test file nobody remembered to register — scripts/tier1_shards.py
#: discovers the test tree and deals it round-robin instead, so a new
#: test file is in the lane the moment it exists.
TIER1_SHARDS = 4


@nox.session(python=PY_VERSIONS)
def test(session: nox.Session) -> None:
    """Fast lane: the virtual 8-device CPU mesh suite (no TPU needed),
    sharded into TIER1_SHARDS fresh processes (scripts/tier1_shards.py
    — discovery-based, memory-bounded, merged exit status)."""
    session.install("-e", ".[test]")
    session.run(
        "python", "scripts/tier1_shards.py",
        "--shards", str(TIER1_SHARDS),
        env={"JAX_PLATFORMS": "cpu"},
    )


@nox.session
def tier1(session: nox.Session) -> None:
    """Alias for the sharded tier-1 lane on the session's default
    interpreter (what the ROADMAP verify line and the CI test job
    run)."""
    session.install("-e", ".[test]")
    session.run(
        "python", "scripts/tier1_shards.py",
        "--shards", str(TIER1_SHARDS),
        env={"JAX_PLATFORMS": "cpu"},
    )


@nox.session
def soak(session: nox.Session) -> None:
    """Continuous-replay chaos soak (mirrors the CI `soak` job): the
    writer/controller/fleet-host process trio with SIGKILL, torn-blob,
    and stall injections, verdicts from durable artifacts only, then
    the same CLI gates CI runs on the resulting bundles."""
    session.install("-e", ".[test]")
    import os

    bundle = os.path.join(session.create_tmp(), "soak-bundle")
    session.run(
        "python", "-m", "yuma_simulation_tpu.replay", "--soak",
        "--bundle-dir", bundle,
        "--epochs-per-snapshot", "2", "--stride", "4",
        env={"JAX_PLATFORMS": "cpu"},
    )
    session.run("python", "-m", "tools.obsreport", bundle + "/store", "--check")
    session.run(
        "python", "-m", "tools.sloreport",
        bundle + "/store", "--check", "--require",
    )
    session.run(
        "python", "-m", "tools.obsreport", bundle + "/serve", "--check",
    )
    session.run(
        "python", "-m", "tools.incidentreport", bundle + "/store", "--check",
    )
    session.run(
        "python", "-m", "tools.incidentreport",
        bundle + "/serve", "--expect-none",
    )


@nox.session
def chaos(session: nox.Session) -> None:
    """Chaos lane (mirrors the CI `chaos` job): every deterministic
    recovery drill — fault-injection battery plus the supervisor's
    stall/device-loss/multi-fault drills — on the virtual 8-device CPU
    mesh."""
    session.install("-e", ".[test]")
    session.run(
        "python", "-m", "pytest", "tests/", "-q",
        "-m", "faultinject or chaos",
    )
    # Mirror the CI obsreport gate: drill a flight-recorder bundle and
    # fail if any ledger record lacks a resolvable span. The bundle
    # goes under the session's tmp dir — a fresh directory per run
    # (the drill refuses to resume a stale bundle) that never pollutes
    # the working tree.
    import os

    bundle = os.path.join(session.create_tmp(), "chaos-bundle")
    session.run(
        "python", "-m", "tools.obsreport", bundle, "--drill", "--check",
    )
    # The numerics drift gate: the unfaulted drill bundle's per-epoch
    # fingerprint stream must compare drift-clean.
    session.run(
        "python", "-m", "tools.driftreport", bundle, "--check", "--require",
    )
    # Incident gate: every typed fault the drill ledgered must belong
    # to a correlated incident with a cause candidate.
    session.run(
        "python", "-m", "tools.incidentreport", bundle, "--check",
    )


@nox.session
def fleet(session: nox.Session) -> None:
    """Fleet lane (mirrors the CI chaos job's fleet half): the
    in-process fabric battery (lease races, torn leases, steal/requeue
    history, at-most-once publish) plus the multiprocess pod-level
    chaos drill — one simulated host SIGKILLed, one lease torn, a
    stall and a NaN lane on a third — gated by the fleet-aware
    `obsreport --check` (run inside the drill test)."""
    session.install("-e", ".[test]")
    session.run(
        "python", "-m", "pytest",
        "tests/unit/test_fabric.py", "tests/unit/test_fleet_drill.py",
        "-q",
    )
    import os
    import shutil

    # Fresh target every run: nox reuses its tmp dir across sessions and
    # the fleet drill REFUSES a non-empty directory (a resumed drill
    # exercises none of its faults).
    bundle = os.path.join(session.create_tmp(), "fleet-bundle")
    shutil.rmtree(bundle, ignore_errors=True)
    session.run(
        "python", "-m", "tools.obsreport", bundle,
        "--fleet-drill", "--check",
    )
    session.run(
        "python", "-m", "tools.sloreport",
        os.path.join(bundle, "store"), "--check", "--require",
    )
    session.run(
        "python", "-m", "tools.driftreport",
        os.path.join(bundle, "store"), "--check", "--require",
    )


@nox.session
def serve(session: nox.Session) -> None:
    """Serve lane (mirrors the CI `serve` job): the serving-tier test
    battery, then the smoke drill — start a real HTTP server, fire one
    of each contract-defining request (happy path, structured admission
    rejection, quota shed with Retry-After, coalesced same-bucket pair)
    — gated by `obsreport --check` over the server's flight bundle."""
    session.install("-e", ".[test]")
    session.run("python", "-m", "pytest", "tests/unit/test_serve.py", "-q")
    import os
    import shutil

    bundle = os.path.join(session.create_tmp(), "serve-bundle")
    shutil.rmtree(bundle, ignore_errors=True)
    session.run(
        "python", "-m", "yuma_simulation_tpu.serve", "--smoke",
        "--bundle-dir", bundle, "--queue-limit", "16",
        "--tenant-burst", "4", "--coalesce-window", "0.3",
    )
    session.run("python", "-m", "tools.obsreport", bundle, "--check")
    session.run(
        "python", "-m", "tools.sloreport", bundle, "--check", "--require"
    )
    session.run(
        "python", "-m", "tools.driftreport", bundle, "--check", "--require"
    )


@nox.session
def serve_scaleout(session: nox.Session) -> None:
    """Scale-out lane (mirrors the CI chaos-job drill step): the pure
    claim-scoring/keyring/retry/autoscaler battery, then the
    multi-process drill — three warm workers behind the stateless
    router, affinity proven against a no-affinity control arm, one
    worker SIGKILLed under concurrent load with every in-flight
    request rerouted bitwise-invisibly, and the SLO-burn autoscaler
    spawn/retire round trip — gated over the merged fleet bundle.
    driftreport runs WITHOUT --require: the drill's numerics stream
    rides the workers' bundles and may be sparse under coalesce=0."""
    session.install("-e", ".[test]")
    session.run(
        "python", "-m", "pytest", "tests/unit/test_serve_scaleout.py", "-q"
    )
    import os
    import shutil

    bundle = os.path.join(session.create_tmp(), "scaleout-bundle")
    shutil.rmtree(bundle, ignore_errors=True)
    session.run(
        "python", "-m", "yuma_simulation_tpu.serve", "--scaleout-drill",
        "--bundle-dir", bundle,
    )
    session.run("python", "-m", "tools.obsreport", bundle, "--check")
    session.run(
        "python", "-m", "tools.sloreport", bundle, "--check", "--require"
    )
    session.run("python", "-m", "tools.driftreport", bundle, "--check")


@nox.session
def drift(session: nox.Session) -> None:
    """Numerics drift lane (mirrors the CI driftreport gates): the
    numerics flight-recorder battery — sketch invariance property tests
    (monolithic == streamed == sharded, bitwise), the injected-DriftFault
    end-to-end drill (engine_drift ledger event, driftreport exit != 0,
    serve /healthz degraded), and resume survival of numerics.jsonl."""
    session.install("-e", ".[test]")
    session.run(
        "python", "-m", "pytest", "tests/unit/test_numerics.py", "-q"
    )


@nox.session
def scenarios(session: nox.Session) -> None:
    """Scenario lane (mirrors the CI `scenarios` job): the foundry
    property suite (DSL bitwise pins, metagraph schema round-trips,
    adversarial dividend properties, Monte-Carlo carrier round-trips),
    then the generated-suite supervisor drill gated by obsreport and
    driftreport."""
    session.install("-e", ".[test]")
    session.run(
        "python", "-m", "pytest",
        "tests/unit/test_foundry_dsl.py",
        "tests/unit/test_foundry_metagraph.py",
        "tests/unit/test_foundry_properties.py",
        "tests/unit/test_foundry_montecarlo.py",
        "tests/unit/test_scenario_contract.py",
        "-q",
        env={"JAX_PLATFORMS": "cpu"},
    )
    tmp = session.create_tmp()
    import os

    bundle = os.path.join(tmp, "foundry-bundle")
    session.run(
        "python", "-m", "yuma_simulation_tpu.foundry", "--drill",
        "--bundle-dir", bundle, "--suite-size", "8",
        env={"JAX_PLATFORMS": "cpu"},
    )
    session.run("python", "-m", "tools.obsreport", bundle, "--check")
    session.run(
        "python", "-m", "tools.driftreport", bundle, "--check", "--require"
    )


@nox.session
def replay(session: nox.Session) -> None:
    """Replay lane (mirrors the CI `replay` job): the suffix-resume
    property suite (randomized checkpoint epochs bitwise on every
    engine rung + under streaming) and the chain-replay battery, then
    the drill — synthetic 3-snapshot timeline -> trailing-window fleet
    sweep -> two served what-ifs against one state cache (the second
    must be a state_cache_hit with zero AOT builds) — with the serve
    bundle and every fleet store gated by obsreport and driftreport."""
    session.install("-e", ".[test]")
    session.run(
        "python", "-m", "pytest",
        "tests/unit/test_suffix_resume.py",
        "tests/unit/test_replay.py",
        "-q",
        env={"JAX_PLATFORMS": "cpu"},
    )
    import glob
    import os

    bundle = os.path.join(session.create_tmp(), "replay-bundle")
    import shutil

    shutil.rmtree(bundle, ignore_errors=True)
    session.run(
        "python", "-m", "yuma_simulation_tpu.replay", "--drill",
        "--bundle-dir", bundle,
        env={"JAX_PLATFORMS": "cpu"},
    )
    session.run(
        "python", "-m", "tools.obsreport",
        os.path.join(bundle, "serve"), "--check",
    )
    for store in sorted(glob.glob(os.path.join(bundle, "store", "subnet_*", "*"))):
        session.run("python", "-m", "tools.obsreport", store, "--check")
        session.run(
            "python", "-m", "tools.driftreport", store,
            "--check", "--require",
        )


@nox.session
def slo(session: nox.Session) -> None:
    """SLO lane (mirrors the CI sloreport gates): the distributed-
    tracing + SLO test battery — sketch algebra property tests,
    burn-rate arithmetic against hand-computed windows, the SLO
    degradation drill, traceparent propagation round-trips and the
    stitched orphan-span gate."""
    session.install("-e", ".[test]")
    session.run(
        "python", "-m", "pytest",
        "tests/unit/test_slo.py", "tests/unit/test_propagation.py",
        "-q",
    )


@nox.session
def incidents(session: nox.Session) -> None:
    """Incident-intelligence lane (ISSUE 20): detector-math property
    tests (MAD single-outlier / level-shift / reseed, counter stall,
    saturation, the clean-run zero-firing bound), the order-independent
    time-series merge property, correlation per cause class with the
    clean-ledger zero-incident bound, durable incidents.jsonl state,
    the incidentreport tamper/malformed exit codes, and the O(new
    bytes) --follow regression."""
    session.install("-e", ".[test]")
    session.run(
        "python", "-m", "pytest", "tests/unit/test_incidents.py", "-q",
        env={"JAX_PLATFORMS": "cpu"},
    )


@nox.session
def perf(session: nox.Session) -> None:
    """Perf lane (mirrors the CI `perf` job): CPU bench smoke capture
    into a session-local history + the perfgate structural gate. The
    committed BENCH_HISTORY.jsonl is untouched — run `python bench.py`
    directly to append a real capture."""
    import os

    session.install("-e", ".[test]")
    history = os.path.join(session.create_tmp(), "BENCH_HISTORY.jsonl")
    session.run("python", "bench.py", "--smoke", "--history", history)
    session.run(
        "python", "-m", "tools.perfgate", "--check", "--structural",
        "--history", history,
    )


@nox.session(python=PY_VERSIONS)
def test_slow(session: nox.Session) -> None:
    """Slow lane: full 14x9 chart suite, f32-mode goldens, quickstart."""
    session.install("-e", ".[test]")
    session.run("python", "-m", "pytest", "tests/", "-q", "-m", "slow")


@nox.session
def tpu_parity(session: nox.Session) -> None:
    """On-chip golden parity artifacts (requires a TPU): the 14x9x4
    total-dividend surface through the XLA engine, the flagship fused
    case scan, and the parity-relaxed MXU variant."""
    session.install("-e", ".")
    session.run(
        "python", "tools/tpu_parity.py",
        "--impl", "xla", "--out", "TPU_PARITY.json", "--bound", "1.5e-6",
    )
    session.run(
        "python", "tools/tpu_parity.py",
        "--impl", "fused_scan", "--out", "TPU_PARITY_FUSED.json",
        "--bound", "1.5e-6",
    )
    session.run(
        "python", "tools/tpu_parity.py",
        "--impl", "fused_scan_mxu", "--out", "MXU_PARITY.json",
        "--bound", "1.5e-6",  # exact since r4: same bound as every path
    )


@nox.session
def make_release(session: nox.Session) -> None:
    """Build sdist+wheel. Publishing runs via the tag-triggered trusted
    publishing workflow (.github/workflows/publish.yml), not from a dev
    machine — push a `v*` tag to release."""
    session.install("build")
    session.run("python", "-m", "build")
