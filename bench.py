"""Headline benchmark: simulated epochs/sec at 256 validators x 4096 miners.

The reference's measured number for this config is ~0.54 epochs/s on CPU
(SURVEY.md §6, BASELINE.md: the per-miner bisection Python loop dominates).
Here the same workload — Yuma 1 epoch kernel, EMA bonds, carried state —
is one `lax.scan` over the jitted unified kernel (`simulate_constant`), so
the whole run is a single device computation with no host round-trips.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np

import jax
import jax.numpy as jnp

from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.simulation.engine import simulate_constant

BASELINE_EPOCHS_PER_SEC = 0.54  # reference CPU, 256v x 4096m (BASELINE.md)
V, M = 256, 4096


#: The sort-based closed-form consensus (identical values to the
#: reference's bisection — pinned by tests) is the fastest of the three
#: implementations on TPU: ~2x the vectorized bisection, which in turn is
#: ~45,000x the reference's per-miner Python loop.
_CONSENSUS_IMPL = "sorted"

#: The benchmark workload holds weights constant across epochs (as the
#: reference's measured baseline did), so the consensus front half is
#: epoch-invariant; hoisting it out of the scan is bit-identical to the
#: in-scan form (pinned by tests) and ~2x faster again.
_HOIST = True


def _run(n_epochs: int, W, S, config, spec):
    total, bonds = simulate_constant(
        W,
        S,
        n_epochs,
        config,
        spec,
        consensus_impl=_CONSENSUS_IMPL,
        hoist_invariant=_HOIST,
    )
    # np.asarray forces the device->host fetch of the [V] totals; on remote
    # TPU runtimes block_until_ready alone can return before execution.
    return np.asarray(total)


def main() -> None:
    rng = np.random.default_rng(42)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random((V,)) + 0.01, jnp.float32)
    config = YumaConfig()
    spec = variant_for_version("Yuma 1 (paper)")

    # Warm-up at the timed epoch count (scan length is static) to exclude
    # compile time, then calibrate the count so the timed run is >= ~2s.
    n = 2048
    _run(n, W, S, config, spec)
    t0 = time.perf_counter()
    _run(n, W, S, config, spec)
    dt = time.perf_counter() - t0
    if dt < 2.0:
        n = min(100_000, int(n * max(2.0, 2.5 / dt)))
        _run(n, W, S, config, spec)
        t0 = time.perf_counter()
        _run(n, W, S, config, spec)
        dt = time.perf_counter() - t0

    eps = n / dt
    print(
        json.dumps(
            {
                "metric": f"simulated epochs/sec, {V}v x {M}m, Yuma 1 kernel",
                "value": round(eps, 2),
                "unit": "epochs/s",
                "vs_baseline": round(eps / BASELINE_EPOCHS_PER_SEC, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
