"""Headline benchmark: simulated epochs/sec at 256 validators x 4096 miners.

The reference's measured number for this config is ~0.54 epochs/s on CPU
(SURVEY.md §6, BASELINE.md: the per-miner bisection Python loop dominates
reference yumas.py:175-282, re-executed every epoch by the driver loop at
simulation_utils.py:44).

The PRIMARY metric is the honest, PARITY-SAFE apples-to-apples
comparison: the FULL epoch kernel executed EVERY epoch, weights varying
per epoch so XLA cannot hoist any consensus work out of the scan, on the
single-Pallas-program scan with the EXACT MXU support contraction — the
same numerics `epoch_impl="auto"` ships by default. Since r4 the MXU
scan's consensus support is the exact limb-split integer sum (bitwise
identical to the VPU scan and the XLA engines, verified on chip;
MXU_PARITY.json pins the golden surface at the same 1.5e-6 bound as
every other parity-safe path), so the former "parity-relaxed" tier no
longer exists.

Secondary metrics (same JSON line, `secondary` field):
  - fused_scan_vpu:          the all-VPU variant of the primary workload
    (bitwise-identical outputs; what auto uses when V > 2^14)
  - full_epoch_xla:          same varying-weights workload, unfused XLA scan
  - true_weights_fused_scan: genuinely different W[e]/S[e] EVERY epoch
    (the reference's real workload shape, reference cases.py:51-597)
    streamed through the fused case scan — not scalar-scaled synthetics
  - true_weights_xla:        same true-weights workload, XLA scan
  - streamed_true_weights_10k: ~10k epochs of genuinely fresh per-epoch
    weights in [1024, V, M] device-generated slabs through
    simulate_streamed (beyond-HBM shape: the 10k-epoch stack is ~41 GiB;
    only ~2 slabs live) — generation, per-chunk dispatch round-trips and
    host fetches all included
  - batched_fused_scan_x4:   4 scenarios advanced per grid step
    (scenario-epochs/s — the chip-filling varying-weights configuration)
  - liquid_fused_scan:       the liquid-alpha variant of the primary
  - constant_weights_scan / constant_weights_hoisted: continuity with r1

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "secondary"}.
"""

import json
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from yuma_simulation_tpu.telemetry import RunContext, get_registry, record_epoch_rate
from yuma_simulation_tpu.utils import enable_compilation_cache, setup_logging
from yuma_simulation_tpu.utils.timing import time_best

enable_compilation_cache()

from yuma_simulation_tpu.models.config import YumaConfig, YumaParams
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.simulation.engine import (
    _simulate_scan,
    simulate_constant,
    simulate_scaled,
    simulate_scaled_batch,
)

BASELINE_EPOCHS_PER_SEC = 0.54  # reference CPU, 256v x 4096m (BASELINE.md)
V, M = 256, 4096
EPOCHS = 4096
MAX_EPOCHS = 65536
TRUE_E = 1024  # [TRUE_E, V, M] f32 = 4 GiB of genuinely per-epoch weights
BATCH = 4  # largest scenario batch the VMEM-resident fused scan admits here


def _time_best(run, n, max_n=MAX_EPOCHS, granularity=1):
    """The shared timing discipline (see utils/timing.py): warm, grow the
    epoch count until a timed run lasts >= 2 s, best-of-4."""
    rate, _, _ = time_best(run, n, max_n=max_n, granularity=granularity)
    return rate


@partial(jax.jit, static_argnames=("spec", "reps", "epoch_impl"))
def _true_weights_reps(W_e, S_e, config, spec, reps, epoch_impl):
    """`reps` sequential passes over a true per-epoch-weights workload
    (`W_e [E, V, M]`, `S_e [E, V]`) inside ONE dispatch, so the remote
    tunnel's per-call milliseconds amortize away. Each pass scales the
    stakes by a fresh near-1 factor: numerically neutral (the kernel
    normalizes stakes per epoch) but the operands differ, so XLA cannot
    CSE the passes into one; the accumulator chains them so none is
    dead-code-eliminated."""
    from yuma_simulation_tpu.ops.pallas_epoch import fused_case_scan
    from yuma_simulation_tpu.simulation.engine import fused_hparams

    ri = jnp.asarray(-1, jnp.int32)

    def body(r, carry):
        acc, scale = carry
        S_r = S_e * scale
        if epoch_impl in ("fused_scan", "fused_scan_mxu"):
            out = fused_case_scan(
                W_e,
                S_r,
                mode=spec.bonds_mode,
                mxu=epoch_impl == "fused_scan_mxu",
                save_bonds=False,
                save_incentives=False,
                **fused_hparams(config),
            )
            acc = acc + out["dividends_normalized"].sum()
        else:
            ys = _simulate_scan(
                W_e, S_r, ri, ri, config, spec,
                save_bonds=False, save_incentives=False,
            )
            acc = acc + ys["dividends"].sum()
        return acc, scale * 1.0000001

    acc, _ = lax.fori_loop(
        0, reps, body, (jnp.zeros((), W_e.dtype), jnp.ones((), W_e.dtype))
    )
    return acc


def main() -> None:
    # Operator stream + run-scoped telemetry: the bench is a run like
    # any sweep — its epoch rate lands on the metrics registry
    # (`epochs_total`/`epochs_per_sec`) and is emitted as exactly one
    # run-stamped `event=epoch_rate` record (stderr; the stdout JSON
    # line below stays byte-compatible).
    setup_logging()
    with RunContext():
        _bench()


def _bench() -> None:
    rng = np.random.default_rng(42)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random((V,)) + 0.01, jnp.float32)
    config = YumaConfig()
    liquid_config = YumaConfig(yuma_params=YumaParams(liquid_alpha=True))
    spec = variant_for_version("Yuma 1 (paper)")
    on_tpu = jax.default_backend() == "tpu"

    # Epoch-varying scales: numerically near-neutral (row normalization
    # divides the scalar back out) but opaque to the compiler.
    scales = jnp.asarray(
        1.0 + 1e-7 * np.arange(MAX_EPOCHS, dtype=np.float32), jnp.float32
    )

    def varying(impl, cfg=config):
        def run(n):
            total, _ = simulate_scaled(
                W, S, scales[:n], cfg, spec, epoch_impl=impl
            )
            return total

        return run

    def constant(hoist):
        def run(n):
            total, _ = simulate_constant(
                W, S, n, config, spec,
                consensus_impl="sorted", hoist_invariant=hoist,
            )
            return total

        return run

    # PRIMARY: the parity-safe single-Pallas-program scan with the exact
    # MXU support contraction (what epoch_impl="auto" selects on TPU —
    # bitwise the VPU scan; consensus bitwise across every engine).
    primary_impl = "fused_scan_mxu" if on_tpu else "xla"
    primary = _time_best(varying(primary_impl), EPOCHS)
    # Off-TPU the primary already IS the XLA path; don't time it twice.
    xla_eps = (
        _time_best(varying("xla"), EPOCHS) if primary_impl != "xla" else primary
    )
    secondary = {
        "full_epoch_xla": round(xla_eps, 1),
        "constant_weights_scan": round(_time_best(constant(False), EPOCHS), 1),
        "constant_weights_hoisted": round(
            _time_best(constant(True), 4 * EPOCHS), 1
        ),
    }

    if on_tpu:
        secondary["fused_scan_vpu"] = round(
            _time_best(varying("fused_scan"), EPOCHS), 1
        )
        secondary["liquid_fused_scan"] = round(
            _time_best(varying("fused_scan_mxu", liquid_config), EPOCHS), 1
        )

        # Scenario batch: BATCH runs advanced together per grid step;
        # scenario-epochs/s (work rate, not latency of one scenario).
        Wb = jnp.asarray(rng.random((BATCH, V, M)), jnp.float32)
        Sb = jnp.asarray(rng.random((BATCH, V)) + 0.01, jnp.float32)

        def batched(n):
            total, _ = simulate_scaled_batch(
                Wb, Sb, scales[:n], config, spec, epoch_impl="fused_scan_mxu"
            )
            return total

        secondary["batched_fused_scan_x4"] = round(
            BATCH * _time_best(batched, EPOCHS, max_n=MAX_EPOCHS // BATCH), 1
        )

        # TRUE per-epoch weights: the reference's real workload shape.
        # Generated on-device (4 GiB); timed as `reps` chained in-dispatch
        # passes so n epochs = reps * TRUE_E.
        kw, ks = jax.random.split(jax.random.PRNGKey(0))
        W_e = jax.random.uniform(kw, (TRUE_E, V, M), jnp.float32)
        S_e = jax.random.uniform(ks, (TRUE_E, V), jnp.float32) + 0.01

        def true_weights(impl):
            def run(n):
                reps = max(1, n // TRUE_E)
                return _true_weights_reps(W_e, S_e, config, spec, reps, impl)

            return run

        secondary["true_weights_fused_scan"] = round(
            _time_best(
                true_weights("fused_scan_mxu"), 4 * TRUE_E, granularity=TRUE_E
            ),
            1,
        )
        secondary["true_weights_xla"] = round(
            _time_best(true_weights("xla"), TRUE_E, granularity=TRUE_E), 1
        )

        # Chunked streaming (r4 verdict item 1): the beyond-HBM workload
        # shape — a 10k-epoch [E, V, M] stack would be ~41 GiB, so only
        # ~2 [TRUE_E, V, M] slabs may be live at a time. simulate_streamed
        # threads the (bonds, consensus) carry between per-chunk
        # dispatches, each chunk's genuinely fresh weights generated on
        # device by the host generator; the number INCLUDES on-device
        # generation, the per-chunk dispatch round-trip (~35 ms on this
        # tunnel runtime) and the async per-chunk host fetch of [E, V]
        # dividends — the honest end-to-end rate for the workload the
        # monolithic engines cannot hold. (simulate_generated's
        # one-dispatch chunk chain is not timed here: this runtime's
        # remote XLA compile of multi-chunk programs at this shape takes
        # tens of minutes — see the simulate_generated docstring.)
        from yuma_simulation_tpu.simulation.engine import simulate_streamed

        def streamed_host(n):
            def gen():
                for i in range(max(1, n // TRUE_E)):
                    ki, kj = jax.random.split(
                        jax.random.fold_in(jax.random.PRNGKey(7), i)
                    )
                    yield (
                        jax.random.uniform(ki, (TRUE_E, V, M), jnp.float32),
                        jax.random.uniform(kj, (TRUE_E, V), jnp.float32)
                        + 0.01,
                    )

            return simulate_streamed(
                gen(), "Yuma 1 (paper)", config, epoch_impl="fused_scan_mxu"
            ).dividends

        secondary["streamed_true_weights_10k"] = round(
            _time_best(streamed_host, 10 * TRUE_E, granularity=TRUE_E), 1
        )

        # Epoch-VARYING Monte-Carlo (r4 verdict item 4): 8 scenarios,
        # each drawing a FRESH weight perturbation every epoch inside the
        # shard (no [E, V, M] stack), through the full per-epoch XLA
        # kernel — the pod-scale study of the workload the headline
        # advertises, here on the 1-chip mesh. scenario-epochs/s.
        from yuma_simulation_tpu.parallel import (
            make_mesh,
            montecarlo_total_dividends,
        )

        mesh1 = make_mesh()
        MC_B = 8

        def mc_varying(n):
            return montecarlo_total_dividends(
                jax.random.PRNGKey(5),
                MC_B,
                max(1, n // MC_B),
                V,
                M,
                "Yuma 1 (paper)",
                mesh=mesh1,
                weights_mode="per_epoch",
                consensus_impl="bisect",
            )

        secondary["montecarlo_per_epoch_weights_x8"] = round(
            _time_best(mc_varying, 4096, max_n=MAX_EPOCHS, granularity=MC_B),
            1,
        )

    record_epoch_rate("bench_primary", epochs_per_sec=primary)
    # The secondary rates ride the registry snapshot as gauges so a
    # scrape of the bench process sees the full matrix, not just the
    # headline.
    registry = get_registry()
    for name, rate in secondary.items():
        registry.gauge(f"bench_{name}_epochs_per_sec").set(rate)
    print(
        json.dumps(
            {
                "metric": (
                    f"full-epoch simulated epochs/sec, {V}v x {M}m, weights "
                    f"varying every epoch, Yuma 1 "
                    f"({'single-Pallas-program epoch scan, exact MXU support (bitwise = VPU/XLA)' if on_tpu else 'XLA epoch kernel'})"
                ),
                "value": round(primary, 2),
                "unit": "epochs/s",
                "vs_baseline": round(primary / BASELINE_EPOCHS_PER_SEC, 1),
                "secondary": secondary,
            }
        )
    )


if __name__ == "__main__":
    main()
