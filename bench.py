"""Headline benchmark: simulated epochs/sec at 256 validators x 4096 miners.

The reference's measured number for this config is ~0.54 epochs/s on CPU
(SURVEY.md §6, BASELINE.md: the per-miner bisection Python loop dominates
reference yumas.py:175-282, re-executed every epoch by the driver loop at
simulation_utils.py:44).

The PRIMARY metric is the honest, PARITY-SAFE apples-to-apples
comparison: the FULL epoch kernel executed EVERY epoch, weights varying
per epoch so XLA cannot hoist any consensus work out of the scan, on the
single-Pallas-program scan with the EXACT MXU support contraction — the
same numerics `epoch_impl="auto"` ships by default. Since r4 the MXU
scan's consensus support is the exact limb-split integer sum (bitwise
identical to the VPU scan and the XLA engines, verified on chip;
MXU_PARITY.json pins the golden surface at the same 1.5e-6 bound as
every other parity-safe path), so the former "parity-relaxed" tier no
longer exists.

Secondary metrics (same JSON line, `secondary` field):
  - fused_scan_vpu:          the all-VPU variant of the primary workload
    (bitwise-identical outputs; what auto uses when V > 2^14)
  - full_epoch_xla:          same varying-weights workload, unfused XLA scan
  - true_weights_fused_scan: genuinely different W[e]/S[e] EVERY epoch
    (the reference's real workload shape, reference cases.py:51-597)
    streamed through the fused case scan — not scalar-scaled synthetics
  - true_weights_xla:        same true-weights workload, XLA scan
    (TRACKED on every backend — tools/perfgate.py TRACKED_SECONDARY)
  - streamed_true_weights:   genuinely fresh per-epoch weights in
    device-generated slabs through the DOUBLE-BUFFERED simulate_streamed
    (slab k+1's host->HBM staging overlaps the scan over slab k, carry
    donated; beyond-HBM shape on TPU: the 10k-epoch stack is ~41 GiB,
    only ~2 slabs live) — generation, per-chunk dispatch round-trips and
    host fetches all included; TRACKED on every backend. On TPU the
    pre-0.10.0 name streamed_true_weights_10k aliases the same number
    for history continuity.
  - montecarlo_per_epoch_weights: the per-epoch Monte-Carlo through the
    planner-chosen batched engine (sharded.montecarlo_per_epoch_batched:
    fused batched scan on TPU, batched XLA oracle elsewhere); TRACKED on
    every backend. The shard_map continuity line
    montecarlo_per_epoch_weights_x8 stays TPU-only.
  - batched_fused_scan_x4:   4 scenarios advanced per grid step
    (scenario-epochs/s — the chip-filling varying-weights configuration)
  - liquid_fused_scan:       the liquid-alpha variant of the primary
  - constant_weights_scan / constant_weights_hoisted: continuity with r1

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "secondary"}.

Every run ALSO appends one richer record to ``BENCH_HISTORY.jsonl``
(``--history`` to relocate, ``--no-history`` to skip): the stdout fields
plus per-metric timing dispersion (`cv`, from `utils.timing.time_best`),
the AOT cost report for every engine rung (flops / bytes / peak memory /
HLO fingerprint, nulls-with-reason on CPU — `telemetry.cost`) and the
roofline verdicts. ``python -m tools.perfgate --check`` diffs the latest
record against a noise-aware rolling baseline of that file; the CI perf
lane runs it with ``--smoke`` (short timing windows) + ``--structural``.
"""

import argparse
import json
import time
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from yuma_simulation_tpu.telemetry import RunContext, get_registry, record_epoch_rate
from yuma_simulation_tpu.utils import enable_compilation_cache, setup_logging
from yuma_simulation_tpu.utils.timing import time_best

enable_compilation_cache()

from yuma_simulation_tpu.models.config import YumaConfig, YumaParams
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.simulation.engine import (
    _simulate_scan,
    simulate_constant,
    simulate_scaled,
    simulate_scaled_batch,
)

BASELINE_EPOCHS_PER_SEC = 0.54  # reference CPU, 256v x 4096m (BASELINE.md)
BENCH_HISTORY = "BENCH_HISTORY.jsonl"  # beside the committed BENCH_r* lines
V, M = 256, 4096
EPOCHS = 4096
MAX_EPOCHS = 65536
TRUE_E = 1024  # [TRUE_E, V, M] f32 = 4 GiB of genuinely per-epoch weights
#: CPU-lane slab length for the per-epoch-weights metrics: the SAME
#: workload shape (genuinely fresh W[e]/S[e] at 256x4096), scaled so a
#: CI runner can hold the stack — rates never baseline across backends,
#: so the scaled CPU lines gate CPU-vs-CPU drift only.
TRUE_E_CPU = 64
BATCH = 4  # largest scenario batch the VMEM-resident fused scan admits here
MC_B = 8  # per-epoch Monte-Carlo scenario batch (the *_x8 continuity line)
#: Scenario batch for the montecarlo_per_epoch_fused line: the largest
#: batch at which the epoch-tiled varying scan admits a >= 2 epoch tile
#: at 256 x 4096 under the measured VMEM model (`_varying_scan_mats`:
#: streaming EMA needs (4T + 2 + temps) * B * 4 MiB <= 126 MiB — B = 2
#: fits T = 2, B = 3 fits nothing). The rung exists for workloads whose
#: per-epoch block underfills the chip, so the fused MC line measures
#: it where it is actually admissible; the MC_B=8 line above keeps
#: measuring the planner-auto path at the continuity batch.
MC_FUSED_B = 2

#: Per-rung attained-fraction floors declared into every history record
#: (tools/perfgate.py `check_attained`). The roofline prediction is an
#: amortization-OPTIMISTIC ceiling (XLA cost analysis counts a scan
#: body once — telemetry.cost.roofline's honesty note), so these are
#: deliberately coarse collapse backstops, not targets: they fail the
#: gate when a rung's measured rate falls to a rounding error of its
#: ceiling (driver bug, silent interpret-mode fallback, dead MXU path),
#: while the `attained:{rung}` rolling-baseline diff in perfgate
#: catches finer distance-to-ceiling drift commit-to-commit. Tighten as
#: on-chip history accumulates.
#: Ratcheted for r06 (ISSUE 15): the r05 on-chip capture put the fused
#: line at ~0.5 of its amortization-optimistic ceiling and the XLA scan
#: well above 1% of its, so the collapse backstops double — a rung that
#: falls below these is broken, not merely slow. The new epoch-tiled
#: varying rungs start at the fused backstop. tools/perfgate.py keeps
#: its own DEFAULT_ATTAINED_FLOORS at these values as a floor-of-floors,
#: so a future bench edit cannot silently loosen the gate.
ATTAINED_FLOORS = {
    "fused_varying_mxu": 0.02,
    "fused_varying": 0.02,
    "fused_scan_mxu": 0.02,
    "fused_scan": 0.02,
    "xla": 0.002,
}


#: Per-metric timing dispersion of the current run, keyed by the
#: secondary-metric name (+ "primary"): what perfgate reads to widen
#: tolerance on noisy metrics instead of false-failing.
_CVS: dict[str, float] = {}

#: Timing-window overrides (set by --smoke): short windows measure
#: dispatch more than throughput, so smoke records are flagged and
#: perfgate never baselines a real capture against them.
_WINDOW: dict = {}


def _time_best(run, n, max_n=MAX_EPOCHS, granularity=1, label=None):
    """The shared timing discipline (see utils/timing.py): warm, grow the
    epoch count until a timed run lasts >= 2 s, best-of-4. Stashes the
    repeat dispersion under `label` for the history record."""
    rate, _, _, cv = time_best(
        run, n, max_n=max_n, granularity=granularity, **_WINDOW
    )
    if label is not None:
        _CVS[label] = cv
    return rate


@partial(
    jax.jit,
    static_argnames=("spec", "reps", "epoch_impl", "capture_numerics"),
)
def _true_weights_reps(
    W_e, S_e, config, spec, reps, epoch_impl, capture_numerics=False
):
    """`reps` sequential passes over a true per-epoch-weights workload
    (`W_e [E, V, M]`, `S_e [E, V]`) inside ONE dispatch, so the remote
    tunnel's per-call milliseconds amortize away. Each pass scales the
    stakes by a fresh near-1 factor: numerically neutral (the kernel
    normalizes stakes per epoch) but the operands differ, so XLA cannot
    CSE the passes into one; the accumulator chains them so none is
    dead-code-eliminated.

    `capture_numerics=True` is the numerics-overhead twin (XLA rung
    only): the in-scan per-epoch sketch capture (telemetry.numerics)
    rides the same program, its leaves folded into the accumulator
    through a `* 0.0` (f32 `x * 0` is not foldable — NaN/Inf
    semantics — so XLA cannot dead-code-eliminate the capture while
    the measured value stays bit-identical)."""
    from yuma_simulation_tpu.ops.pallas_epoch import (
        fused_case_scan,
        fused_varying_scan,
    )
    from yuma_simulation_tpu.simulation.engine import fused_hparams
    from yuma_simulation_tpu.simulation.planner import (
        FUSED_CASE_RUNGS,
        rung_flags,
    )

    ri = jnp.asarray(-1, jnp.int32)

    def body(r, carry):
        acc, scale = carry
        S_r = S_e * scale
        if epoch_impl in FUSED_CASE_RUNGS:
            flags = rung_flags(epoch_impl)
            kernel = (
                fused_varying_scan if flags["varying"] else fused_case_scan
            )
            out = kernel(
                W_e,
                S_r,
                mode=spec.bonds_mode,
                mxu=flags["mxu"],
                save_bonds=False,
                save_incentives=False,
                **fused_hparams(config),
            )
            acc = acc + out["dividends_normalized"].sum()
        else:
            ys = _simulate_scan(
                W_e, S_r, ri, ri, config, spec,
                save_bonds=False, save_incentives=False,
                capture_numerics=capture_numerics,
            )
            acc = acc + ys["dividends"].sum()
            if capture_numerics:
                live = sum(
                    jnp.sum(leaf.astype(W_e.dtype))
                    for leaf in jax.tree.leaves(ys["numerics"])
                )
                acc = acc + live * jnp.asarray(0.0, W_e.dtype)
        return acc, scale * 1.0000001

    acc, _ = lax.fori_loop(
        0, reps, body, (jnp.zeros((), W_e.dtype), jnp.ones((), W_e.dtype))
    )
    return acc


#: Epoch count for the AOT cost capture. XLA's cost analysis amortizes
#: scan bodies (counted once regardless of trip count — see the honesty
#: note on `telemetry.cost.roofline`), so the choice mostly sizes the
#: [E, V, M] argument bytes; it is FIXED so history records stay
#: bitwise commit-to-commit comparable, which is what perfgate diffs.
COST_EPOCHS = 512

#: Shape for the cold-start drill: small enough that the CI lane's two
#: subprocesses stay cheap, real enough that the engine path (planner +
#: XLA scan + AOT cache seam) is the production one. FIXED so the
#: cold/warm pair stays commit-to-commit comparable.
COLD_START_SHAPE = (64, 32, 64)  # (epochs, V, M)

#: The fresh-subprocess driver for the cold-start metric: process start
#: (well, interpreter entry — the closest portable anchor) to the first
#: completed engine dispatch, with the executable cache joined via the
#: environment. Run twice against ONE cache directory, the pair is the
#: metric: run 1 is the true cold start, run 2 the cache-warm start the
#: autoscaler drill cares about.
_COLD_START_CHILD = r"""
import time
_t0 = time.perf_counter()
import json
import os

import numpy as np

from yuma_simulation_tpu.scenarios.base import Scenario
from yuma_simulation_tpu.simulation.engine import simulate

E, V, M = (int(d) for d in os.environ["YUMA_COLD_SHAPE"].split("x"))
validators = [f"v{i}" for i in range(V)]
scenario = Scenario(
    name="cold_start",
    validators=validators,
    base_validator=validators[0],
    weights=np.zeros((E, V, M), np.float32),
    stakes=np.ones((E, V), np.float32),
    num_epochs=E,
)
simulate(scenario, "Yuma 1 (paper)")
_t1 = time.perf_counter()
from yuma_simulation_tpu.simulation.aot import process_stats

print(json.dumps({"seconds": _t1 - _t0, "aot": process_stats().to_json()}))
"""


def _measure_cold_start() -> dict:
    """The `cold_start` history object: first-dispatch wall seconds of a
    fresh subprocess, cold (empty cache) vs cache-warm (second run over
    the same cache dir), plus run 2's AOT stats so the gate can assert
    the warm start actually hit the cache. A failed child yields an
    explicit error object — the perfgate structural gate then fails the
    record rather than silently shipping a history without the metric.

    Deliberately NOT skipped under --smoke: the structural gate demands
    the pair on every gated record, and at the fixed small
    :data:`COLD_START_SHAPE` the drill costs two seconds-scale
    subprocesses (``--skip-cold-start`` exists for local loops)."""
    import os
    import subprocess
    import sys
    import tempfile

    shape = "x".join(str(d) for d in COLD_START_SHAPE)
    runs = []
    with tempfile.TemporaryDirectory(prefix="yuma-coldstart-") as cache:
        env = dict(
            os.environ,
            YUMA_TPU_EXECUTABLE_CACHE=cache,
            YUMA_COLD_SHAPE=shape,
        )
        for _ in range(2):
            # EVERY child failure mode — nonzero exit, hang past the
            # timeout, empty or non-JSON stdout — must come back as the
            # error object, never a raise: the contract is that bench
            # always appends a record and perfgate's structural gate is
            # what fails it.
            try:
                proc = subprocess.run(
                    [sys.executable, "-c", _COLD_START_CHILD],
                    capture_output=True,
                    text=True,
                    env=env,
                    timeout=600,
                )
            except subprocess.TimeoutExpired:
                return {"shape": shape, "error": "child timed out (600s)"}
            if proc.returncode != 0:
                return {
                    "shape": shape,
                    "error": (proc.stderr or "no stderr")[-500:],
                }
            try:
                runs.append(json.loads(proc.stdout.splitlines()[-1]))
            except (IndexError, ValueError):
                return {
                    "shape": shape,
                    "error": (
                        "child emitted no JSON line (stdout: "
                        f"{proc.stdout[-200:]!r})"
                    ),
                }
    return {
        "shape": shape,
        "first_dispatch_seconds_cold": round(runs[0]["seconds"], 3),
        "first_dispatch_seconds_warm": round(runs[1]["seconds"], 3),
        "warm_aot": runs[1]["aot"],
    }


#: Shape for the what-if suffix-resume drill (epochs, V, M) and the
#: epoch the perturbation lands on. FIXED so the speedup line stays
#: commit-to-commit comparable. A stride-8 baseline checkpoints at 32,
#: so the what-if resumes there: 8 suffix epochs vs 40 full, epoch
#: ratio 5 — the in-record floor tools/perfgate.py's `check_whatif`
#: derives its bar from. The shape is deliberately CPU-lane sized
#: (the flagship 256x4096 costs seconds per epoch on a CI runner);
#: the epoch RATIO, which is what the gate normalizes by, matches the
#: flagship's 40-epoch window shape.
WHATIF_SHAPE = (40, 128, 1024)
WHATIF_RESUME_EPOCH = 32
WHATIF_STRIDE = 8


def _measure_whatif() -> dict:
    """The `whatif` history object: wall seconds of one what-if served
    by suffix resume from a cached epoch-state checkpoint vs the same
    perturbed world re-simulated end to end — both through the real
    :func:`yuma_simulation_tpu.replay.whatif.run_whatif` product path
    (baseline load, delta computation and telemetry included), warm
    programs (best-of-3 after a warmup rep, so compiles are excluded
    and the ratio measures the suffix economics, not jit). A failure
    yields an explicit error object — the perfgate structural gate
    fails the record rather than silently shipping a history without
    the metric."""
    import tempfile

    from yuma_simulation_tpu.replay.statecache import StateCache
    from yuma_simulation_tpu.replay.whatif import WhatIfSpec, run_whatif
    from yuma_simulation_tpu.scenarios.base import Scenario

    E, WV, WM = WHATIF_SHAPE
    version = "Yuma 1 (paper)"
    rng = np.random.default_rng(14)
    W = rng.random((E, WV, WM)).astype(np.float32)
    W /= W.sum(axis=2, keepdims=True)
    S = (rng.random((E, WV)) + 0.1).astype(np.float32)
    validators = [f"v{i}" for i in range(WV)]
    scenario = Scenario(
        name="bench_whatif",
        validators=validators,
        base_validator=validators[0],
        weights=W,
        stakes=S,
        num_epochs=E,
    )
    spec = WhatIfSpec(
        netuid=0,
        version=version,
        from_epoch=WHATIF_RESUME_EPOCH,
        stake_scale=((1, 2.0),),
    )
    try:
        with tempfile.TemporaryDirectory(prefix="yuma-whatif-") as root:
            cache = StateCache(root)
            meta = cache.build_baseline(
                scenario,
                version,
                scenario_fingerprint="bench_whatif",
                stride=WHATIF_STRIDE,
            )

            def cached():
                return run_whatif(
                    cache, meta, scenario, YumaConfig(), spec, use_cache=True
                )

            def full():
                return run_whatif(
                    cache, meta, scenario, YumaConfig(), spec, use_cache=False
                )

            result = cached()
            if not result.cache_hit:
                return {
                    "shape": f"{E}x{WV}x{WM}",
                    "error": "warmup what-if missed the state cache",
                }
            full()  # warm the full-length program too
            suffix_seconds = min(
                time_it(cached) for _ in range(3)
            )
            full_seconds = min(time_it(full) for _ in range(3))
    except Exception as exc:  # noqa: BLE001 — the record carries it
        return {"shape": f"{E}x{WV}x{WM}", "error": f"{type(exc).__name__}: {exc}"}
    ratio = E / (E - result.resume_epoch)
    return {
        "shape": f"{E}x{WV}x{WM}",
        "resume_epoch": int(result.resume_epoch),
        "epochs": E,
        "epoch_ratio": round(ratio, 3),
        "full_seconds": round(full_seconds, 6),
        "suffix_seconds": round(suffix_seconds, 6),
        "speedup": round(full_seconds / suffix_seconds, 3),
    }


def time_it(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="short timing windows (0.25 s, best-of-2) for the CPU CI "
        "perf lane; the history record is flagged smoke=true and "
        "perfgate baselines smoke runs only against smoke runs",
    )
    parser.add_argument(
        "--history",
        default=BENCH_HISTORY,
        help=f"JSONL perf-history sink (default {BENCH_HISTORY})",
    )
    parser.add_argument(
        "--no-history",
        action="store_true",
        help="do not append to the history file",
    )
    parser.add_argument(
        "--skip-costs",
        action="store_true",
        help="skip the AOT cost capture (it compiles each rung once); "
        "note the perfgate structural gate fails a cost-less record by "
        "design",
    )
    parser.add_argument(
        "--skip-cold-start",
        action="store_true",
        help="skip the fresh-subprocess cold-start measurement (two "
        "python startups); like --skip-costs, the structural gate "
        "fails a record without it by design",
    )
    parser.add_argument(
        "--skip-whatif",
        action="store_true",
        help="skip the what-if suffix-resume speedup measurement; like "
        "--skip-costs, the structural gate fails a record without it "
        "by design",
    )
    args = parser.parse_args(argv)
    if args.smoke:
        _WINDOW.update(target_seconds=0.25, reps=2)
    # Operator stream + run-scoped telemetry: the bench is a run like
    # any sweep — its epoch rate lands on the metrics registry
    # (`epochs_total`/`epochs_per_sec`) and is emitted as exactly one
    # run-stamped `event=epoch_rate` record (stderr; the stdout JSON
    # line below stays byte-compatible).
    setup_logging()
    with RunContext():
        _bench(args)


def _bench(args) -> None:
    rng = np.random.default_rng(42)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random((V,)) + 0.01, jnp.float32)
    config = YumaConfig()
    liquid_config = YumaConfig(yuma_params=YumaParams(liquid_alpha=True))
    spec = variant_for_version("Yuma 1 (paper)")
    on_tpu = jax.default_backend() == "tpu"

    # Epoch-varying scales: numerically near-neutral (row normalization
    # divides the scalar back out) but opaque to the compiler.
    scales = jnp.asarray(
        1.0 + 1e-7 * np.arange(MAX_EPOCHS, dtype=np.float32), jnp.float32
    )

    def varying(impl, cfg=config):
        def run(n):
            total, _ = simulate_scaled(
                W, S, scales[:n], cfg, spec, epoch_impl=impl
            )
            return total

        return run

    def constant(hoist):
        def run(n):
            total, _ = simulate_constant(
                W, S, n, config, spec,
                consensus_impl="sorted", hoist_invariant=hoist,
            )
            return total

        return run

    # PRIMARY: the parity-safe single-Pallas-program scan with the exact
    # MXU support contraction (what epoch_impl="auto" selects on TPU —
    # bitwise the VPU scan; consensus bitwise across every engine).
    primary_impl = "fused_scan_mxu" if on_tpu else "xla"
    primary = _time_best(varying(primary_impl), EPOCHS, label="primary")
    # Off-TPU the primary already IS the XLA path; don't time it twice.
    xla_eps = (
        _time_best(varying("xla"), EPOCHS, label="full_epoch_xla")
        if primary_impl != "xla"
        else primary
    )
    if primary_impl == "xla":
        _CVS["full_epoch_xla"] = _CVS["primary"]
    secondary = {
        "full_epoch_xla": round(xla_eps, 1),
        "constant_weights_scan": round(
            _time_best(constant(False), EPOCHS, label="constant_weights_scan"),
            1,
        ),
        "constant_weights_hoisted": round(
            _time_best(
                constant(True), 4 * EPOCHS, label="constant_weights_hoisted"
            ),
            1,
        ),
    }

    if on_tpu:
        secondary["fused_scan_vpu"] = round(
            _time_best(varying("fused_scan"), EPOCHS, label="fused_scan_vpu"),
            1,
        )
        secondary["liquid_fused_scan"] = round(
            _time_best(
                varying("fused_scan_mxu", liquid_config), EPOCHS,
                label="liquid_fused_scan",
            ),
            1,
        )

        # Scenario batch: BATCH runs advanced together per grid step;
        # scenario-epochs/s (work rate, not latency of one scenario).
        Wb = jnp.asarray(rng.random((BATCH, V, M)), jnp.float32)
        Sb = jnp.asarray(rng.random((BATCH, V)) + 0.01, jnp.float32)

        def batched(n):
            total, _ = simulate_scaled_batch(
                Wb, Sb, scales[:n], config, spec, epoch_impl="fused_scan_mxu"
            )
            return total

        secondary["batched_fused_scan_x4"] = round(
            BATCH
            * _time_best(
                batched, EPOCHS, max_n=MAX_EPOCHS // BATCH,
                label="batched_fused_scan_x4",
            ),
            1,
        )

    # ------------------------------------------------------------------
    # The per-epoch-weights tier: the three slowest BENCH lines, now
    # FIRST-CLASS perfgate-tracked on EVERY backend (tools/perfgate.py
    # TRACKED_SECONDARY — a record missing one is schema rot). The CPU
    # lane runs the same workload shapes scaled to TRUE_E_CPU slabs;
    # rates only ever baseline against the same backend+smoke class.

    # TRUE per-epoch weights: the reference's real workload shape,
    # generated on-device; timed as `reps` chained in-dispatch passes so
    # n epochs = reps * true_e.
    true_e = TRUE_E if on_tpu else TRUE_E_CPU
    kw, ks = jax.random.split(jax.random.PRNGKey(0))
    W_e = jax.random.uniform(kw, (true_e, V, M), jnp.float32)
    S_e = jax.random.uniform(ks, (true_e, V), jnp.float32) + 0.01

    def true_weights(impl):
        def run(n):
            reps = max(1, n // true_e)
            return _true_weights_reps(W_e, S_e, config, spec, reps, impl)

        return run

    if on_tpu:
        secondary["true_weights_fused_scan"] = round(
            _time_best(
                true_weights("fused_scan_mxu"), 4 * TRUE_E,
                granularity=TRUE_E, label="true_weights_fused_scan",
            ),
            1,
        )
    secondary["true_weights_xla"] = round(
        _time_best(
            true_weights("xla"), true_e, granularity=true_e,
            label="true_weights_xla",
        ),
        1,
    )

    # The varying-weights FUSED rung (ISSUE 15, perfgate-tracked on
    # every backend): the same true-per-epoch-weights workload through
    # the engine `plan_dispatch(auto)` ships for it — the epoch-tiled
    # `fused_varying_scan` on TPU; on CPU auto resolves to the XLA rung,
    # so the line re-uses the measured XLA rate (one workload, one
    # number — the CPU lane gates CPU-vs-CPU drift only, exactly like
    # the other per-epoch-weights lines).
    if on_tpu:
        secondary["true_weights_fused"] = round(
            _time_best(
                true_weights("fused_varying_mxu"), 4 * TRUE_E,
                granularity=TRUE_E, label="true_weights_fused",
            ),
            1,
        )
    else:
        secondary["true_weights_fused"] = secondary["true_weights_xla"]
        _CVS["true_weights_fused"] = _CVS["true_weights_xla"]

    # Numerics-capture overhead (0.14.0): the SAME true-weights XLA
    # workload with the in-scan per-epoch sketch capture ON — finite
    # fraction, min/max/absmax, bit-cast-u32 fingerprint per epoch
    # (telemetry.numerics, kept live against DCE inside the jit). The
    # acceptance bar is < 5% epochs/s overhead; perfgate gates
    # `numerics.overhead_frac` against that bar (cv-widened) on every
    # capture, structural lane included.
    def true_weights_numerics(n):
        reps = max(1, n // true_e)
        return _true_weights_reps(
            W_e, S_e, config, spec, reps, "xla", capture_numerics=True
        )

    numerics_on = _time_best(
        true_weights_numerics, true_e, granularity=true_e,
        label="true_weights_xla_numerics",
    )
    secondary["true_weights_xla_numerics"] = round(numerics_on, 1)
    numerics_off = secondary["true_weights_xla"]
    numerics_overhead = {
        "workload": "true_weights_xla",
        "epochs_per_sec_off": numerics_off,
        "epochs_per_sec_on": round(numerics_on, 1),
        "overhead_frac": (
            round(1.0 - numerics_on / numerics_off, 4)
            if numerics_off
            else None
        ),
    }

    # Dispatch-sketch overhead (0.23.0): the always-on per-dispatch
    # LatencySketch observation lives on the host side of EVERY
    # `simulate()` call (telemetry.slo.observe_dispatch — one O(1)
    # table update per dispatched region). This times the full
    # simulate() path — plan, ladder, dispatch, the seam itself — with
    # the observation ON vs OFF over the same small workload, so the
    # seam's cost is a tracked number, not an assumption; perfgate
    # gates `dispatch_sketch.overhead_frac` under the same < 5% bar as
    # the numerics capture.
    from yuma_simulation_tpu.scenarios.base import Scenario
    from yuma_simulation_tpu.simulation.engine import simulate
    from yuma_simulation_tpu.telemetry.slo import set_dispatch_observation

    sk_E, sk_V, sk_M = 64, 64, 256
    sk_validators = [f"sv{i}" for i in range(sk_V)]
    sk_rng = np.random.default_rng(23)
    sk_scenario = Scenario(
        name="dispatch_sketch_overhead",
        validators=sk_validators,
        base_validator=sk_validators[0],
        weights=sk_rng.random((sk_E, sk_V, sk_M)).astype(np.float32),
        stakes=np.ones((sk_E, sk_V), np.float32),
        num_epochs=sk_E,
    )

    def _sketch_runs(enabled):
        def run(n):
            prev = set_dispatch_observation(enabled)
            try:
                out = None
                for _ in range(max(1, n // sk_E)):
                    out = simulate(sk_scenario, "Yuma 1 (paper)")
                return out.dividends
            finally:
                set_dispatch_observation(prev)

        return run

    sketch_off = _time_best(
        _sketch_runs(False), sk_E, granularity=sk_E,
        label="dispatch_sketch_off",
    )
    sketch_on = _time_best(
        _sketch_runs(True), sk_E, granularity=sk_E,
        label="dispatch_sketch_on",
    )
    secondary["dispatch_sketch_off"] = round(sketch_off, 1)
    secondary["dispatch_sketch_on"] = round(sketch_on, 1)
    dispatch_sketch = {
        "workload": f"simulate() {sk_V}v x {sk_M}m, E={sk_E}",
        "epochs_per_sec_off": round(sketch_off, 1),
        "epochs_per_sec_on": round(sketch_on, 1),
        "overhead_frac": (
            round(1.0 - sketch_on / sketch_off, 4) if sketch_off else None
        ),
    }

    # DOUBLE-BUFFERED chunked streaming: the beyond-HBM workload shape —
    # a 10k-epoch [E, V, M] stack would be ~41 GiB, so only ~2 slabs may
    # be live at a time. simulate_streamed now overlaps slab k+1's
    # host->HBM staging with the scan over slab k (donated carry threaded
    # between dispatches, slab length capped by the planner's memory
    # plan); the number INCLUDES on-device generation, per-chunk dispatch
    # round-trips and the async per-chunk host fetch of [E, V] dividends —
    # the honest end-to-end rate for the workload the monolithic engines
    # cannot hold.
    from yuma_simulation_tpu.simulation.engine import simulate_streamed

    stream_impl = "fused_scan_mxu" if on_tpu else "xla"

    def streamed_host(n):
        def gen():
            for i in range(max(1, n // true_e)):
                ki, kj = jax.random.split(
                    jax.random.fold_in(jax.random.PRNGKey(7), i)
                )
                yield (
                    jax.random.uniform(ki, (true_e, V, M), jnp.float32),
                    jax.random.uniform(kj, (true_e, V), jnp.float32)
                    + 0.01,
                )

        return simulate_streamed(
            gen(), "Yuma 1 (paper)", config, epoch_impl=stream_impl
        ).dividends

    secondary["streamed_true_weights"] = round(
        _time_best(
            streamed_host,
            (10 * TRUE_E) if on_tpu else 2 * TRUE_E_CPU,
            granularity=true_e,
            label="streamed_true_weights",
        ),
        1,
    )
    if on_tpu:
        # Continuity alias: the pre-0.10.0 name for the same 10k-epoch
        # TPU workload, kept so the r4/r5 history keeps a baseline.
        secondary["streamed_true_weights_10k"] = secondary[
            "streamed_true_weights"
        ]
        _CVS["streamed_true_weights_10k"] = _CVS["streamed_true_weights"]

    # Per-epoch Monte-Carlo through the PLANNED batched engine
    # (parallel.sharded.montecarlo_per_epoch_batched): on TPU the whole
    # scenario batch rides the fused batched case scan on device-
    # generated slabs; on CPU the batched XLA oracle. scenario-epochs/s.
    from yuma_simulation_tpu.parallel.sharded import (
        montecarlo_per_epoch_batched,
    )

    def mc_batched(n):
        return montecarlo_per_epoch_batched(
            jax.random.PRNGKey(5),
            MC_B,
            max(1, n // MC_B),
            V,
            M,
            "Yuma 1 (paper)",
            consensus_impl="bisect",
        )

    secondary["montecarlo_per_epoch_weights"] = round(
        _time_best(
            mc_batched,
            4096 if on_tpu else MC_B,
            max_n=MAX_EPOCHS,
            granularity=MC_B,
            label="montecarlo_per_epoch_weights",
        ),
        1,
    )

    # The per-epoch Monte-Carlo pinned to the FUSED varying rung
    # (ISSUE 15, perfgate-tracked on every backend): device-RNG weight
    # slabs streamed through the epoch-tiled scan on TPU; on CPU the
    # planner's auto path IS the batched XLA oracle already measured
    # above, so the line re-uses that rate (same aliasing rule as
    # true_weights_fused).
    if on_tpu:

        def mc_fused(n):
            return montecarlo_per_epoch_batched(
                jax.random.PRNGKey(5),
                MC_FUSED_B,
                max(1, n // MC_FUSED_B),
                V,
                M,
                "Yuma 1 (paper)",
                consensus_impl="bisect",
                epoch_impl="fused_varying_mxu",
            )

        # mc_fused(n) advances n // B epochs x B scenarios = n
        # scenario-epochs, so the rate is scenario-epochs/s directly
        # (the same convention as montecarlo_per_epoch_weights).
        secondary["montecarlo_per_epoch_fused"] = round(
            _time_best(
                mc_fused,
                4096,
                max_n=MAX_EPOCHS,
                granularity=MC_FUSED_B,
                label="montecarlo_per_epoch_fused",
            ),
            1,
        )
    else:
        secondary["montecarlo_per_epoch_fused"] = secondary[
            "montecarlo_per_epoch_weights"
        ]
        _CVS["montecarlo_per_epoch_fused"] = _CVS[
            "montecarlo_per_epoch_weights"
        ]

    if on_tpu:
        # Epoch-VARYING Monte-Carlo through the shard_map tier (r4
        # verdict item 4), unchanged for continuity with the r4/r5
        # lines: 8 scenarios, each drawing a FRESH weight perturbation
        # every epoch inside the shard (no [E, V, M] stack), through the
        # full per-epoch XLA kernel on the 1-chip mesh.
        from yuma_simulation_tpu.parallel import (
            make_mesh,
            montecarlo_total_dividends,
        )

        mesh1 = make_mesh()

        def mc_varying(n):
            # epoch_impl="xla" pins the shard_map tier explicitly: the
            # single-device "auto" path now routes through the planned
            # batched driver (the montecarlo_per_epoch_fused line), and
            # this continuity line must keep measuring the shard tier.
            return montecarlo_total_dividends(
                jax.random.PRNGKey(5),
                MC_B,
                max(1, n // MC_B),
                V,
                M,
                "Yuma 1 (paper)",
                mesh=mesh1,
                weights_mode="per_epoch",
                consensus_impl="bisect",
                epoch_impl="xla",
            )

        secondary["montecarlo_per_epoch_weights_x8"] = round(
            _time_best(
                mc_varying, 4096, max_n=MAX_EPOCHS, granularity=MC_B,
                label="montecarlo_per_epoch_weights_x8",
            ),
            1,
        )

    record_epoch_rate(
        "bench_primary", epochs_per_sec=primary, cv=_CVS.get("primary")
    )
    # The secondary rates ride the registry snapshot as gauges so a
    # scrape of the bench process sees the full matrix, not just the
    # headline.
    registry = get_registry()
    for name, rate in secondary.items():
        registry.gauge(f"bench_{name}_epochs_per_sec").set(rate)
    line = {
        "metric": (
            f"full-epoch simulated epochs/sec, {V}v x {M}m, weights "
            f"varying every epoch, Yuma 1 "
            f"({'single-Pallas-program epoch scan, exact MXU support (bitwise = VPU/XLA)' if on_tpu else 'XLA epoch kernel'})"
        ),
        "value": round(primary, 2),
        "unit": "epochs/s",
        "vs_baseline": round(primary / BASELINE_EPOCHS_PER_SEC, 1),
        "secondary": secondary,
    }
    print(json.dumps(line))

    if not args.no_history:
        # The cold-start drill (ROADMAP item 1): fresh-subprocess first-
        # dispatch seconds, cold vs cache-warm over one executable-cache
        # dir — the number the autoscaler drill budgets against.
        cold_start = (
            {} if args.skip_cold_start else _measure_cold_start()
        )
        # The what-if suffix-resume economics (ISSUE 14): one cached
        # suffix what-if vs the same perturbed world end to end, warm.
        whatif = {} if args.skip_whatif else _measure_whatif()
        _append_history(line, primary_impl, primary, smoke=args.smoke,
                        skip_costs=args.skip_costs, history=args.history,
                        numerics=numerics_overhead, cold_start=cold_start,
                        whatif=whatif, dispatch_sketch=dispatch_sketch)


def _append_history(
    line: dict,
    primary_impl: str,
    primary: float,
    *,
    smoke: bool,
    skip_costs: bool,
    history: str,
    numerics: Optional[dict] = None,
    cold_start: Optional[dict] = None,
    whatif: Optional[dict] = None,
    dispatch_sketch: Optional[dict] = None,
) -> dict:
    """One richer record per run into the JSONL history perfgate gates
    on: the stdout fields + per-metric dispersion + the AOT cost report
    and roofline verdicts for every engine rung. Crash-safe append
    (whole-file atomic republish, tolerant reader — the ledger's
    contract), so a killed bench never leaves a torn history."""
    from yuma_simulation_tpu.telemetry.cost import (
        capture_engine_costs,
        resolve_device_spec,
        roofline,
    )
    from yuma_simulation_tpu.utils.checkpoint import (
        publish_atomic,
        read_jsonl_tolerant,
    )

    costs: dict = {}
    rooflines: dict = {}
    if not skip_costs:
        spec = resolve_device_spec()
        records = capture_engine_costs(V, M, COST_EPOCHS)
        # Every rung gets its own measured rate where this run timed the
        # matching workload, so the per-rung attained fractions (and the
        # perfgate attained-fraction gate + `attained:{rung}` baseline
        # lines over them) cover the whole ladder, not just the
        # headline's rung.
        measured = {
            "xla": line["secondary"].get("full_epoch_xla"),
            "fused_scan": line["secondary"].get("fused_scan_vpu"),
            # The varying rungs' measured line is the true-weights
            # workload itself (they exist for it); off-TPU the cost
            # record is a null-with-reason, so the fraction stays null.
            "fused_varying_mxu": line["secondary"].get("true_weights_fused"),
        }
        measured[primary_impl] = primary  # the headline's rung wins
        for engine, rec in records.items():
            costs[engine] = rec.to_json()
            rooflines[engine] = roofline(
                rec,
                spec,
                measured_epochs_per_sec=measured.get(engine),
            ).to_json()
    record = {
        "t": round(time.time(), 3),
        "backend": jax.default_backend(),
        "jax": jax.__version__,
        "smoke": smoke,
        **line,
        "cv": {k: v for k, v in sorted(_CVS.items())},
        "costs": costs,
        "rooflines": rooflines,
        # Numerics-capture overhead (in-scan sketch capture on vs off
        # over the same workload) — a tracked, perfgate-gated metric.
        "numerics": numerics if numerics is not None else {},
        # Cold-start first-dispatch seconds (fresh subprocess, cold vs
        # cache-warm) — a tracked, perfgate-gated metric (ISSUE 13).
        "cold_start": cold_start if cold_start is not None else {},
        # What-if suffix-resume speedup (cached carry vs full re-sim)
        # — a tracked, perfgate-gated metric (ISSUE 14).
        "whatif": whatif if whatif is not None else {},
        # Dispatch-sketch observation overhead (seam on vs off over the
        # same simulate() workload) — a tracked, perfgate-gated metric
        # (ISSUE 19, continuous telemetry).
        "dispatch_sketch": dispatch_sketch if dispatch_sketch is not None else {},
        # Declared floors for perfgate's attained-fraction gate: the
        # distance-to-ceiling itself is gated, not just absolute rates.
        "attained_floor": dict(ATTAINED_FLOORS),
    }
    import pathlib

    path = pathlib.Path(history)
    entries = read_jsonl_tolerant(path)
    entries.append(record)
    publish_atomic(
        path,
        "".join(json.dumps(e, sort_keys=True) + "\n" for e in entries).encode(),
    )
    return record


if __name__ == "__main__":
    main()
