"""Headline benchmark: simulated epochs/sec at 256 validators x 4096 miners.

The reference's measured number for this config is ~0.54 epochs/s on CPU
(SURVEY.md §6, BASELINE.md: the per-miner bisection Python loop dominates
reference yumas.py:175-282, re-executed every epoch by the driver loop at
simulation_utils.py:44).

The PRIMARY metric is the honest apples-to-apples comparison: the FULL
epoch kernel executed EVERY epoch, with weights varying per epoch so that
XLA cannot hoist any consensus work out of the scan. (With constant
weights, XLA's loop-invariant code motion silently hoists most of the
kernel even when our explicit `hoist_invariant` flag is off — measured
~3x optimistic. Round-1's 132k number was the explicitly hoisted path and
is now reported separately, not as the headline.)

Secondary metrics (same JSON line, `secondary` field):
  - full_epoch_xla:          same varying-weights workload, unfused XLA kernel
  - constant_weights_scan:   constant weights, hoist flag off (XLA still
                             hoists implicitly — kept for continuity with r1)
  - constant_weights_hoisted: constant weights, consensus hoisted explicitly
                             (the bonds-EMA recurrence is the whole scan)

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "secondary"}.
"""

import json
import time

import numpy as np

import jax
import jax.numpy as jnp

from yuma_simulation_tpu.utils import enable_compilation_cache

enable_compilation_cache()

from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.simulation.engine import simulate_constant, simulate_scaled

BASELINE_EPOCHS_PER_SEC = 0.54  # reference CPU, 256v x 4096m (BASELINE.md)
V, M = 256, 4096
EPOCHS = 4096
MAX_EPOCHS = 65536
TARGET_SECONDS = 2.0
REPS = 4


def _time_best(run, n):
    """Best-of-REPS wall time, with the epoch count grown until one timed
    run lasts >= TARGET_SECONDS (per-dispatch overhead through the remote
    TPU tunnel is milliseconds — a sub-second window would skew the
    result). np.asarray forces the device->host fetch; on the remote TPU
    runtime block_until_ready alone can return before execution finishes.
    """
    np.asarray(run(n))  # compile + warm up
    t0 = time.perf_counter()
    np.asarray(run(n))
    dt = time.perf_counter() - t0
    if dt < TARGET_SECONDS:
        n = min(MAX_EPOCHS, int(n * max(2.0, 1.25 * TARGET_SECONDS / dt)))
        np.asarray(run(n))  # recompile at the timed length
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        np.asarray(run(n))
        best = min(best, time.perf_counter() - t0)
    return n / best


def main() -> None:
    rng = np.random.default_rng(42)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random((V,)) + 0.01, jnp.float32)
    config = YumaConfig()
    spec = variant_for_version("Yuma 1 (paper)")
    on_tpu = jax.default_backend() == "tpu"

    # Epoch-varying scales: numerically near-neutral (row normalization
    # divides the scalar back out) but opaque to the compiler.
    scales = jnp.asarray(
        1.0 + 1e-7 * np.arange(MAX_EPOCHS, dtype=np.float32), jnp.float32
    )

    def varying(impl):
        def run(n):
            total, _ = simulate_scaled(
                W, S, scales[:n], config, spec, epoch_impl=impl
            )
            return total

        return run

    def constant(hoist):
        def run(n):
            total, _ = simulate_constant(
                W, S, n, config, spec,
                consensus_impl="sorted", hoist_invariant=hoist,
            )
            return total

        return run

    primary_impl = "fused_scan_mxu" if on_tpu else "xla"
    primary = _time_best(varying(primary_impl), EPOCHS)
    # Off-TPU the primary already IS the XLA path; don't time it twice.
    xla_eps = (
        _time_best(varying("xla"), EPOCHS) if primary_impl != "xla" else primary
    )
    secondary = {
        "full_epoch_xla": round(xla_eps, 1),
        "constant_weights_scan": round(_time_best(constant(False), EPOCHS), 1),
        "constant_weights_hoisted": round(
            _time_best(constant(True), 4 * EPOCHS), 1
        ),
    }

    print(
        json.dumps(
            {
                "metric": (
                    f"full-epoch simulated epochs/sec, {V}v x {M}m, weights "
                    f"varying every epoch, Yuma 1 "
                    f"({'single-Pallas-program epoch scan' if on_tpu else 'XLA epoch kernel'})"
                ),
                "value": round(primary, 2),
                "unit": "epochs/s",
                "vs_baseline": round(primary / BASELINE_EPOCHS_PER_SEC, 1),
                "secondary": secondary,
            }
        )
    )


if __name__ == "__main__":
    main()
