"""Repo-layout mirror of the packaged CLI (reference keeps its entry
scripts at `scripts/`, reference charts_table_generator.py)."""

from yuma_simulation_tpu.cli.charts_table_generator import main

if __name__ == "__main__":
    main()
