"""Entry-point scripts (installable console scripts, see pyproject.toml)."""
