"""Repo-layout mirror of the packaged CLI (reference keeps its entry
scripts at `scripts/`, reference total_dividends_sheet_generator.py)."""

from yuma_simulation_tpu.cli.total_dividends_sheet_generator import main

if __name__ == "__main__":
    main()
