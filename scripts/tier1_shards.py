#!/usr/bin/env python
"""Run the tier-1 test suite as K fresh-process pytest shards.

The full suite in ONE process exhausts memory before it finishes: JAX
compilation caches, the AOT executable cache, and the foundry's
synthetic metagraphs all accumulate per-process and none of them are
meant to be evicted mid-run (eviction would invalidate the very
warm-cache behavior the tests assert). Sharding by test FILE into
fresh interpreters bounds the peak to the largest shard while keeping
every test's process-level assumptions (fresh registries, cold caches)
identical to running its file alone.

Deterministic: files are discovered with ``git ls-files``-independent
sorted glob and dealt round-robin, so shard membership depends only on
the checked-in test tree and ``--shards``. Shards run CONCURRENTLY by
default (``--jobs``, default = all of them): the suite is mostly
wait-bound — multiprocess batteries, poll loops, lease TTLs — so
overlapping shards recovers most of that idle time even on one core,
and the fresh-process split is what bounds memory, not the schedule.
Each shard's output is buffered and flushed whole, in shard order, so
the combined log reads exactly like a sequential run (the repo's
verify line counts progress dots from it). Exit status is the worst
shard's; a shard whose files are all deselected (pytest exit 5) is not
a failure.

Usage::

    python scripts/tier1_shards.py [--shards K] [--jobs J] [--pytest-arg ...]
"""

from __future__ import annotations

import argparse
import pathlib
import re
import subprocess
import sys
import threading

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent

#: outcome keys summed across shards from pytest's summary line.
_SUMMARY_RE = re.compile(
    r"(\d+) (passed|failed|skipped|errors?|xfailed|xpassed|warnings?)"
)


def discover(tests_dir: pathlib.Path) -> list[pathlib.Path]:
    return sorted(tests_dir.rglob("test_*.py"))


def shard(files: list, shards: int) -> list[list]:
    out: list[list] = [[] for _ in range(shards)]
    for i, f in enumerate(files):
        out[i % shards].append(f)
    return [s for s in out if s]


def _run_shard(group: list, extra: list) -> tuple[int, list[str]]:
    cmd = [
        sys.executable, "-m", "pytest",
        *[str(f) for f in group],
        "-q", "-m", "not slow",
        "--continue-on-collection-errors",
        "-p", "no:cacheprovider",
        "-p", "no:xdist",
        "-p", "no:randomly",
        *extra,
    ]
    proc = subprocess.Popen(
        cmd,
        cwd=REPO_ROOT,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    assert proc.stdout is not None
    lines = list(proc.stdout)
    rc = proc.wait()
    if rc == 5:
        rc = 0  # every file in the shard deselected: not a failure
    return rc, lines


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument(
        "--shards", type=int, default=4,
        help="fresh pytest processes to split the files across",
    )
    parser.add_argument(
        "--jobs", type=int, default=0,
        help="shards running at once (0 = all; 1 = sequential)",
    )
    parser.add_argument(
        "--tests-dir", default=str(REPO_ROOT / "tests"),
    )
    parser.add_argument(
        "--pytest-arg", action="append", default=[],
        help="extra argument forwarded to every shard (repeatable)",
    )
    args = parser.parse_args(argv)

    files = discover(pathlib.Path(args.tests_dir))
    if not files:
        print(f"no test files under {args.tests_dir}", file=sys.stderr)
        return 2
    groups = shard(files, max(1, args.shards))
    jobs = args.jobs if args.jobs > 0 else len(groups)
    results: list = [None] * len(groups)
    gate = threading.Semaphore(jobs)

    def worker(i: int) -> None:
        with gate:
            results[i] = _run_shard(groups[i], args.pytest_arg)

    threads = [
        threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(len(groups))
    ]
    for t in threads:
        t.start()
    totals: dict[str, int] = {}
    worst = 0
    for i, t in enumerate(threads):
        t.join()
        rc, lines = results[i]
        print(
            f"--- tier1 shard {i + 1}/{len(groups)} "
            f"({len(groups[i])} files) ---",
            flush=True,
        )
        for line in lines:
            print(line, end="")
            for count, what in _SUMMARY_RE.findall(line):
                # Summary lines are terminal per shard; the totals line
                # below re-derives the merged counts from them.
                if line.strip().endswith(("s", ")")) and " in " in line:
                    totals[what] = totals.get(what, 0) + int(count)
        sys.stdout.flush()
        worst = max(worst, rc)
    merged = ", ".join(
        f"{totals[k]} {k}"
        for k in ("passed", "failed", "skipped", "error", "errors")
        if k in totals
    )
    print(
        f"=== tier1 shards merged: {merged or 'no summary parsed'} "
        f"across {len(groups)} shard(s), exit {worst} ===",
        flush=True,
    )
    return worst


if __name__ == "__main__":
    sys.exit(main())
