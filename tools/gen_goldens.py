"""Generate golden parity artifacts by running the REFERENCE implementation.

Outputs (committed, used by tests/):
  tests/golden/total_dividends_b{beta}.csv  - full 14x9x3 total-dividend surface per beta
  tests/golden/kernel_goldens.npz           - single-epoch kernel outputs on hand inputs
  tests/golden/trajectory_goldens.npz       - per-epoch dividend series + final bonds
                                              (Cases 5/9/11 x 9 versions, beta=0.99)
"""
import sys
sys.path.insert(0, "/root/reference/src")

import numpy as np
import torch

from yuma_simulation._internal.cases import cases
from yuma_simulation._internal.simulation_utils import generate_total_dividends_table
from yuma_simulation._internal.yumas import (
    SimulationHyperparameters, YumaParams, YumaSimulationNames, YumaConfig,
    Yuma, Yuma2, Yuma3, Yuma4, YumaRust,
)
from dataclasses import replace

def versions():
    # Matches the reference scripts' pairing exactly (reference
    # charts_table_generator.py:38-48): Yuma 4 runs with BASE params; only
    # the liquid variant carries the 0.025 / [0.9, 0.99] tuning.
    base = YumaParams()
    liquid = YumaParams(liquid_alpha=True)
    y4 = YumaParams(bond_alpha=0.025, alpha_high=0.99, alpha_low=0.9)
    y4l = replace(y4, liquid_alpha=True)
    n = YumaSimulationNames()
    return [
        (n.YUMA_RUST, base), (n.YUMA, base), (n.YUMA_LIQUID, liquid),
        (n.YUMA2, base), (n.YUMA3, base), (n.YUMA31, base), (n.YUMA32, base),
        (n.YUMA4, base), (n.YUMA4_LIQUID, y4l),
    ]

def main():
    torch.manual_seed(0)
    for beta in [0, 0.5, 0.99, 1.0]:
        hp = SimulationHyperparameters(bond_penalty=beta)
        df = generate_total_dividends_table(cases, versions(), hp)
        df.to_csv(f"tests/golden/total_dividends_b{beta}.csv", index=False, float_format="%.6f")
        # full precision copy for tight tolerance checks
        df.to_csv(f"tests/golden/total_dividends_b{beta}_full.csv", index=False, float_format="%.17g")
        print("done beta", beta, flush=True)

    # single-epoch kernel goldens on hand inputs
    rng = np.random.default_rng(42)
    out = {}
    W0 = torch.tensor([[1.0,0.0],[1.0,0.0],[1.0,0.0]])
    W1 = torch.tensor([[0.0,1.0],[1.0,0.0],[1.0,0.0]])
    Wr = torch.tensor(rng.random((4,5)), dtype=torch.float32)
    Sr = torch.tensor([0.4,0.3,0.2,0.1], dtype=torch.float32)
    S = torch.tensor([0.8,0.1,0.1])
    Bprev = torch.tensor(rng.random((4,5)), dtype=torch.float32)
    cfg = YumaConfig(simulation=SimulationHyperparameters(), yuma_params=YumaParams())
    cfg_liq = YumaConfig(simulation=SimulationHyperparameters(), yuma_params=YumaParams(liquid_alpha=True))
    cases_in = {
        "h0": (W0, S, None, cfg), "h1": (W1, S, None, cfg),
        "r_none": (Wr, Sr, None, cfg), "r_prev": (Wr, Sr, Bprev, cfg),
        "r_liq": (Wr, Sr, Bprev, cfg_liq),
    }
    for tag, (W, St, B, c) in cases_in.items():
        for kname, fn in [("rust", YumaRust), ("y1", Yuma), ("y3", Yuma3), ("y4", Yuma4)]:
            res = fn(W.clone(), St.clone(), None if B is None else B.clone(), c)
            for k, v in res.items():
                if isinstance(v, torch.Tensor):
                    out[f"{tag}/{kname}/{k}"] = v.detach().numpy()
        res = Yuma2(W.clone(), None, St.clone(), None if B is None else B.clone(), c)
        for k, v in res.items():
            if isinstance(v, torch.Tensor):
                out[f"{tag}/y2/{k}"] = v.detach().numpy()
        W_prev = torch.tensor(rng.random(W.shape), dtype=torch.float32)
        out[f"{tag}/y2p/__W_prev"] = W_prev.numpy()
        res = Yuma2(W.clone(), W_prev, St.clone(), None if B is None else B.clone(), c)
        for k, v in res.items():
            if isinstance(v, torch.Tensor):
                out[f"{tag}/y2p/{k}"] = v.detach().numpy()
    np.savez("tests/golden/kernel_goldens.npz", **out)
    print("kernel goldens:", len(out), "arrays")

    # Per-epoch trajectory goldens: full dividend time-series through the
    # reference driver, for cases exercising the carry logic (Case 5 has
    # reset metadata, Case 9 varies stakes over time, Case 11 resets with
    # non-default stakes) x all 9 versions.
    from yuma_simulation._internal.simulation_utils import run_simulation
    traj = {}
    case_by_name = {c.name.split(" -")[0]: c for c in cases}
    for short in ("Case 5", "Case 9", "Case 11"):
        case = case_by_name[short]
        for version, params in versions():
            cfg = YumaConfig(
                simulation=SimulationHyperparameters(bond_penalty=0.99),
                yuma_params=params,
            )
            div, bonds, _ = run_simulation(case, version, cfg)
            arr = np.asarray([[div[v][e] for v in case.validators]
                              for e in range(case.num_epochs)])
            traj[f"{short}/{version}/dividends"] = arr
            traj[f"{short}/{version}/final_bonds"] = bonds[-1].numpy()
    np.savez("tests/golden/trajectory_goldens.npz", **traj)
    print("trajectory goldens:", len(traj), "arrays")

if __name__ == "__main__":
    main()
