"""driftreport: render and gate a flight bundle's numerics stream.

The comparison half of the numerics flight recorder
(:mod:`yuma_simulation_tpu.telemetry.numerics` captures per-epoch
tensor stats + bit-cast-u32 reduction fingerprints inside the jitted
engines; this CLI reads the ``numerics.jsonl`` those captures publish
into every flight bundle and compares primary records against their
cross-engine canary re-executions). For each (unit, stream, label)
group it localizes the FIRST DIVERGENT EPOCH and the per-lane ulp
distance — a single-ulp lane flip moves the fingerprint delta by
exactly 1, so the render reads in ulps, not abstract hash mismatches.

Usage::

    python -m tools.driftreport BUNDLE_DIR            # render captures
    python -m tools.driftreport BUNDLE_DIR --check    # CI gate: exit 1
                                                      # on any UNEXPLAINED
                                                      # fingerprint
                                                      # divergence, exit 2
                                                      # on malformed
                                                      # records
    python -m tools.driftreport BUNDLE_DIR --json     # machine-readable

``--check`` semantics: a canary record whose fingerprints diverge from
its primary is confirmed cross-engine drift — the contract the paper's
engines promise is BITWISE identity, so any divergence fails unless the
canary record carries an ``expected`` field naming a documented
accepted-drift class (one ships today: the u16-quantize fallback
pairing of an EXPLICIT fused opt-in beyond the int32 dyadic bound —
``simulation.planner.EXPECTED_DRIFT_U16_FALLBACK``, ADVICE r5; auto
plans never pair those engines). A bundle
with no ``numerics.jsonl`` passes with a note (pre-0.14.0 bundles stay
valid) unless ``--require`` demands the stream. Fleet stores are
detected automatically: every host bundle under ``hosts/`` is gated.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(
    0, str(pathlib.Path(__file__).resolve().parent.parent)
)

def load_numerics(directory: str | pathlib.Path) -> list[dict]:
    """The bundle's numerics records, monolithic or segmented.

    Goes through :func:`telemetry.flight.load_bundle` so a bundle
    written under segment rotation (numerics land in
    ``segments/seg_*/numerics.jsonl``) reads identically to the
    classic root ``numerics.jsonl``."""
    from yuma_simulation_tpu.telemetry.flight import load_bundle

    return load_bundle(pathlib.Path(directory)).numerics


def _group_key(rec: dict) -> tuple:
    # `lanes` is part of the identity: a fleet unit's local supervisor
    # may emit several sub-unit records all re-stamped with the same
    # fleet unit index, distinguishable only by their lane windows —
    # the same key spelling flight.check_bundle merges by.
    return (
        rec.get("unit"),
        rec.get("label", ""),
        rec.get("stream"),
        tuple(rec.get("lanes") or ()),
    )


def check_records(records: list[dict]) -> list[str]:
    """Structural rot in the records themselves (exit 2 class) — the
    shared validator `telemetry.numerics.check_numerics_records`, so
    this gate and `flight.check_bundle`'s cross-check can never
    diverge."""
    from yuma_simulation_tpu.telemetry.numerics import (
        check_numerics_records,
    )

    return check_numerics_records(records)


def diff_bundle(records: list[dict]) -> list[dict]:
    """Every (unit, label, stream) group's primary-vs-canary verdict:
    ``{"unit", "label", "stream", "primary_engine", "canary_engine",
    "divergences": [{"lane", "first_divergent_epoch", "ulp_distance"}],
    "expected", "unmatched"}``. A canary with no primary in its group is
    reported ``unmatched`` (a comparison that never happened is not a
    pass)."""
    from yuma_simulation_tpu.telemetry.numerics import (
        diff_records,
        numerics_identity,
    )

    # Newest capture per identity wins FIRST — a live server's flushes
    # append without the close-time merge, so a crashed-before-close
    # bundle can hold superseded duplicates (e.g. a canary captured
    # before the breaker re-anchored the primary rung); comparing those
    # would fail a consistent system.
    latest: dict[tuple, dict] = {}
    for rec in records:
        latest[numerics_identity(rec)] = rec
    primaries: dict[tuple, dict] = {}
    canaries: dict[tuple, list] = {}
    for rec in latest.values():
        key = _group_key(rec)
        if rec.get("role") == "canary":
            canaries.setdefault(key, []).append(rec)
        else:
            primaries[key] = rec
    verdicts: list[dict] = []
    for key in sorted(
        canaries, key=lambda k: (str(k[1]), str(k[0]), str(k[2]), k[3])
    ):
        unit, label, stream, _lanes = key
        primary = primaries.get(key)
        for canary in canaries[key]:
            verdict = {
                "unit": unit,
                "label": label,
                "stream": stream,
                "canary_engine": canary.get("engine"),
                "expected": canary.get("expected"),
            }
            if primary is None:
                verdict["unmatched"] = True
                verdict["divergences"] = []
            else:
                lane0 = (primary.get("lanes") or [0, 0])[0]
                divergences = diff_records(primary, canary)
                for d in divergences:
                    d["lane"] += lane0  # sweep-global lane index
                verdict["unmatched"] = False
                verdict["primary_engine"] = primary.get("engine")
                verdict["divergences"] = divergences
            verdicts.append(verdict)
    return verdicts


def render(directory: str, records: list[dict], verdicts: list[dict]) -> str:
    lines = [f"drift report: {directory}"]
    if not records:
        lines.append(
            "no numerics.jsonl recorded (pre-0.14.0 bundle, or "
            "YUMA_NUMERICS=0 disabled capture)"
        )
        return "\n".join(lines)
    primaries = sum(1 for r in records if r.get("role") != "canary")
    lines.append(
        f"  {len(records)} record(s): {primaries} primary, "
        f"{len(records) - primaries} canary"
    )
    engines = sorted(
        {r.get("engine") for r in records if r.get("engine")}
    )
    lines.append(f"  engines captured: {', '.join(engines)}")
    if not verdicts:
        lines.append("  no canary comparisons recorded")
    for v in verdicts:
        where = f"unit={v['unit']} label={v['label']!r} stream={v['stream']}"
        if v["unmatched"]:
            lines.append(f"  [?] {where}: canary with NO primary record")
            continue
        pair = f"{v.get('primary_engine')} vs {v['canary_engine']}"
        if not v["divergences"]:
            lines.append(f"  [ ] {where}: {pair} bitwise identical")
            continue
        flag = "~" if v.get("expected") else "!"
        lines.append(
            f"  [{flag}] {where}: {pair} DIVERGED"
            + (f" (expected: {v['expected']})" if v.get("expected") else "")
        )
        for d in v["divergences"]:
            lines.append(
                f"        lane {d['lane']}: first divergent epoch "
                f"{d['first_divergent_epoch']}, ulp distance "
                f"{d['ulp_distance']:+d}"
            )
    return "\n".join(lines)


def _targets(directory: str) -> list[tuple[str, pathlib.Path]]:
    """The bundle directories to gate: the fleet store's per-host
    bundles (plus the store root, where a driver may publish), or the
    directory itself."""
    from yuma_simulation_tpu.fabric.store import FleetStore, is_fleet_store

    if is_fleet_store(directory):
        store = FleetStore(directory)
        targets = [
            (f"host {host_id}", store.host_dir(host_id))
            for host_id in store.host_ids()
        ]
        targets.append(("store", pathlib.Path(directory)))
        return targets
    return [("bundle", pathlib.Path(directory))]


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="driftreport", description=__doc__.split("\n\n")[0]
    )
    parser.add_argument("directory", help="flight bundle or fleet store")
    parser.add_argument(
        "--check",
        action="store_true",
        help="exit 1 on any unexplained fingerprint divergence (or a "
        "canary with no primary), exit 2 on malformed records",
    )
    parser.add_argument(
        "--require",
        action="store_true",
        help="with --check: a missing numerics.jsonl in every target is "
        "itself a failure",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit the verdicts as JSON"
    )
    args = parser.parse_args(argv)

    targets = _targets(args.directory)
    all_records: dict[str, list] = {}
    all_verdicts: dict[str, list] = {}
    structural: list[str] = []
    for label, path in targets:
        records = load_numerics(path)
        all_records[label] = records
        structural.extend(f"{label}: {p}" for p in check_records(records))
        all_verdicts[label] = diff_bundle(records)
    if args.json:
        print(
            json.dumps(
                {
                    label: {
                        "records": len(all_records[label]),
                        "verdicts": all_verdicts[label],
                    }
                    for label, _ in targets
                },
                indent=2,
                sort_keys=True,
            )
        )
    else:
        first = True
        for label, path in targets:
            if not first:
                print()
            first = False
            print(
                render(
                    f"{label} ({path})",
                    all_records[label],
                    all_verdicts[label],
                )
            )
    if args.check:
        if structural:
            print("\ndriftreport --check: MALFORMED records:", file=sys.stderr)
            for p in structural:
                print(f"  - {p}", file=sys.stderr)
            return 2
        failures: list[str] = []
        for label, _path in targets:
            for v in all_verdicts[label]:
                if v["unmatched"]:
                    failures.append(
                        f"{label}: unit={v['unit']} stream={v['stream']} "
                        "canary has no primary to compare against"
                    )
                elif v["divergences"] and not v.get("expected"):
                    first_d = v["divergences"][0]
                    failures.append(
                        f"{label}: unit={v['unit']} stream={v['stream']} "
                        f"{v.get('primary_engine')} vs {v['canary_engine']} "
                        f"diverged at epoch "
                        f"{first_d['first_divergent_epoch']} "
                        f"(lane {first_d['lane']}, "
                        f"ulp {first_d['ulp_distance']:+d})"
                    )
        recorded = sum(1 for recs in all_records.values() if recs)
        if args.require and recorded == 0:
            failures.append("no numerics.jsonl found in any target bundle")
        if failures:
            print("\ndriftreport --check FAILED:", file=sys.stderr)
            for f in failures:
                print(f"  - {f}", file=sys.stderr)
            return 1
        compared = sum(len(v) for v in all_verdicts.values())
        expected = sum(
            1
            for vs in all_verdicts.values()
            for v in vs
            if v["divergences"] and v.get("expected")
        )
        print(
            f"\ndriftreport --check: {recorded}/{len(targets)} target(s) "
            f"recorded numerics; {compared} canary comparison(s), "
            + (
                f"{expected} expected-class divergence(s), none unexplained"
                if expected
                else "none diverged"
            )
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
