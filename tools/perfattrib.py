"""perfattrib: roofline-gap attribution over measured dispatch timing.

perfgate answers "did the rate move?"; this CLI answers the next
question an operator asks — "where is the remaining gap to the
hardware ceiling, and what is eating it?". Inputs are the two halves
the telemetry plane already records:

- **Measured**: the always-on per-(engine rung x shape bucket x
  backend) dispatch timing sketches (`dispatch_sketches` on flight-
  bundle metrics lines — ``yuma_simulation_tpu.telemetry.slo
  .DispatchStats``). Snapshots are cumulative per process, so the join
  keeps the HIGHEST-count line per key and merges across keys of one
  rung. Without a bundle, the BENCH record's own roofline
  ``measured_epochs_per_sec`` is the measured side.
- **Predicted**: the AOT cost report + roofline verdicts bench.py
  appends to ``BENCH_HISTORY.jsonl`` (``yuma_simulation_tpu.telemetry
  .cost``) — flops/bytes per rung against the device's peak FLOP/s and
  HBM bandwidth.

The output is one row per engine rung: measured epochs/s, predicted
ceiling, attained fraction, compute- vs memory-bound, and a suspected
limiter derived from the sketch shape (dispatch-jitter p99/p50 spread,
per-dispatch overhead on small epoch batches, or the roofline bound
itself). Honesty is the contract: a rung with no measurement or no
roofline carries a TYPED reason (``reason_kind`` +  human sentence) —
"unmeasured, and here is why" must never be confusable with "forgot".

Usage::

    python -m tools.perfattrib                    # table from history
    python -m tools.perfattrib BUNDLE             # join a flight bundle's
                                                  # dispatch sketches
    python -m tools.perfattrib --check            # gate: exit 1 when any
                                                  # rung lacks BOTH a
                                                  # roofline resolution
                                                  # and a typed reason
    python -m tools.perfattrib --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_HISTORY = "BENCH_HISTORY.jsonl"

#: Attained fraction at/above which a rung is reported as sitting at its
#: (amortization-optimistic) roofline ceiling rather than attributed.
AT_ROOFLINE_FRACTION = 0.8

#: p99/p50 spread above which the sketch itself becomes the suspect:
#: the rung's median is fine but the tail is not — queueing/jitter, not
#: a steady-state roofline gap.
JITTER_SPREAD = 4.0

#: Mean epochs per dispatch below which fixed per-dispatch overhead
#: (host work, transfer, retrace checks) plausibly dominates the gap.
SMALL_BATCH_EPOCHS = 64

#: The typed reason vocabulary (``reason_kind``). Every row either
#: resolves to a roofline (measured + predicted) or carries one of
#: these — the --check contract.
REASON_KINDS = (
    "rung_unavailable",   # cost capture says why (CPU Pallas rungs...)
    "no_dispatches",      # no sketch and no bench measurement for rung
    "no_device_roofline",  # device spec lacks peak flops/bandwidth
    "no_cost_record",     # history record lacks the rung entirely
)


def load_history(path: str) -> list[dict]:
    from yuma_simulation_tpu.utils.checkpoint import read_jsonl_tolerant

    return read_jsonl_tolerant(path)


def collect_sketches(metrics_lines) -> dict:
    """The joined ``{key: entry}`` dispatch table from a bundle's
    metrics lines. Snapshots are CUMULATIVE per process, so per key the
    highest-``dispatches`` line wins (re-reading a growing segmented
    bundle never double-counts); distinct keys merge side by side."""
    best: dict[str, dict] = {}
    for line in metrics_lines or []:
        sketches = (line or {}).get("dispatch_sketches")
        if not isinstance(sketches, dict):
            continue
        for key, entry in sketches.items():
            if not isinstance(entry, dict):
                continue
            prior = best.get(key)
            if prior is None or int(entry.get("dispatches", 0)) >= int(
                prior.get("dispatches", 0)
            ):
                best[key] = entry
    return best


def _merge_rung_sketches(entries: list[dict]) -> dict:
    """Fold one rung's per-(bucket, backend) entries into rung totals
    plus a merged quantile sketch (sketch merge is exact count
    addition)."""
    from yuma_simulation_tpu.telemetry.slo import LatencySketch

    merged: Optional[LatencySketch] = None
    dispatches = epochs = 0
    seconds = 0.0
    for e in entries:
        dispatches += int(e.get("dispatches", 0))
        epochs += int(e.get("epochs_total", 0))
        seconds += float(e.get("seconds_total", 0.0))
        rec = e.get("sketch")
        if isinstance(rec, dict):
            try:
                sk = LatencySketch.from_json(rec)
            except Exception:
                continue
            merged = sk if merged is None else merged.merge(sk)
    out = {
        "dispatches": dispatches,
        "epochs_total": epochs,
        "seconds_total": seconds,
    }
    if merged is not None and dispatches:
        out["p50_seconds"] = merged.quantile(0.5)
        out["p99_seconds"] = merged.quantile(0.99)
    return out


def _suspect_limiter(row: dict) -> str:
    """The attribution heuristic for a resolved (measured + predicted)
    rung — deliberately a short, falsifiable sentence, not a verdict."""
    attained = row.get("attained_fraction")
    if attained is not None and attained >= AT_ROOFLINE_FRACTION:
        return "at roofline (ceiling is amortization-optimistic)"
    suspects: list[str] = []
    p50, p99 = row.get("p50_seconds"), row.get("p99_seconds")
    if p50 and p99 and p99 / p50 > JITTER_SPREAD:
        suspects.append(
            f"dispatch jitter (p99/p50 = {p99 / p50:.1f}x)"
        )
    dispatches = row.get("dispatches") or 0
    epochs = row.get("epochs_total") or 0
    if dispatches and epochs / dispatches < SMALL_BATCH_EPOCHS:
        suspects.append(
            "per-dispatch overhead "
            f"({epochs / dispatches:.0f} epochs/dispatch)"
        )
    bound = row.get("bound")
    if bound == "memory":
        suspects.append("memory-bound: HBM bandwidth")
    elif bound == "compute":
        suspects.append("compute-bound: MXU peak")
    return "; ".join(suspects) or "unattributed gap"


def attribute(record: dict, sketches: Optional[dict] = None) -> list[dict]:
    """One row per engine rung joining the BENCH record's cost/roofline
    verdicts against the measured dispatch sketches. Every row either
    resolves (measured AND predicted epochs/s, attained fraction,
    suspected limiter) or carries a typed reason from
    :data:`REASON_KINDS` — see the module docstring."""
    from yuma_simulation_tpu.telemetry.cost import ENGINE_RUNGS

    costs = record.get("costs") or {}
    rooflines = record.get("rooflines") or {}
    by_rung: dict[str, list[dict]] = {}
    for entry in (sketches or {}).values():
        engine = entry.get("engine") or ""
        by_rung.setdefault(engine, []).append(entry)

    rows: list[dict] = []
    for engine in ENGINE_RUNGS:
        cost = costs.get(engine)
        rl = rooflines.get(engine) or {}
        row: dict = {"engine": engine}
        measured = None
        entries = by_rung.get(engine)
        if entries:
            merged = _merge_rung_sketches(entries)
            row.update(merged)
            if merged["seconds_total"] > 0 and merged["epochs_total"] > 0:
                measured = merged["epochs_total"] / merged["seconds_total"]
                row["measured_source"] = "dispatch_sketches"
        if measured is None and isinstance(
            rl.get("measured_epochs_per_sec"), (int, float)
        ):
            measured = float(rl["measured_epochs_per_sec"])
            row["measured_source"] = "bench"
        row["measured_epochs_per_sec"] = measured
        predicted = rl.get("predicted_epochs_per_sec")
        row["predicted_epochs_per_sec"] = predicted
        row["bound"] = rl.get("bound")
        row["device"] = rl.get("device")

        if not isinstance(cost, dict):
            row["reason_kind"] = "no_cost_record"
            row["reason"] = (
                "history record carries no cost capture for this rung "
                "(bench ran --skip-costs?)"
            )
        elif measured is None:
            if cost.get("reason"):
                row["reason_kind"] = "rung_unavailable"
                row["reason"] = str(cost["reason"])
            else:
                row["reason_kind"] = "no_dispatches"
                row["reason"] = (
                    "no dispatch sketch observed this rung and the "
                    "bench record carries no measured rate for it"
                )
        elif not isinstance(predicted, (int, float)) or predicted <= 0:
            row["reason_kind"] = "no_device_roofline"
            row["reason"] = (
                f"device {rl.get('device', '?')!r} spec lacks peak "
                "FLOP/s or HBM bandwidth — the roofline ceiling is "
                "undefined (set YUMA_TPU_DEVICE_SPEC to attribute)"
            )
        else:
            row["attained_fraction"] = measured / float(predicted)
            row["limiter"] = _suspect_limiter(row)
        rows.append(row)
    return rows


def check_rows(rows: list[dict]) -> list[str]:
    """The --check contract: every rung either resolves to a roofline
    (attained fraction computed) or carries a typed reason. Empty list
    means the gate passes."""
    problems: list[str] = []
    for row in rows:
        if row.get("attained_fraction") is not None:
            continue
        kind = row.get("reason_kind")
        if kind not in REASON_KINDS or not row.get("reason"):
            problems.append(
                f"{row.get('engine')}: unresolved (no roofline "
                f"attribution) and no typed reason (reason_kind="
                f"{kind!r})"
            )
    return problems


def render_rows(rows: list[dict], out=None) -> None:
    out = out or sys.stdout
    for row in rows:
        engine = row["engine"]
        attained = row.get("attained_fraction")
        if attained is not None:
            line = (
                f"  {engine}: measured "
                f"{row['measured_epochs_per_sec']:.1f} epochs/s vs "
                f"predicted {row['predicted_epochs_per_sec']:.1f} "
                f"({attained:.1%} of roofline, "
                f"{row.get('bound') or 'unknown'}-bound) -> "
                f"{row.get('limiter')}"
            )
            if row.get("p50_seconds"):
                line += (
                    f" [p50 {row['p50_seconds'] * 1e3:.1f}ms"
                    f" p99 {row['p99_seconds'] * 1e3:.1f}ms"
                    f" over {row['dispatches']} dispatch(es)]"
                )
        else:
            measured = row.get("measured_epochs_per_sec")
            head = (
                f"measured {measured:.1f} epochs/s, "
                if isinstance(measured, (int, float))
                else ""
            )
            line = (
                f"  {engine}: {head}no attribution "
                f"[{row.get('reason_kind')}] {row.get('reason')}"
            )
        print(line, file=out)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="perfattrib", description=__doc__.split("\n\n")[0]
    )
    parser.add_argument(
        "bundle", nargs="?", default=None,
        help="flight-bundle directory whose metrics lines carry "
        "dispatch_sketches (segmented or monolithic); omitted = the "
        "bench record's own measured rates",
    )
    parser.add_argument(
        "--history", default=DEFAULT_HISTORY,
        help=f"bench history JSONL (default {DEFAULT_HISTORY})",
    )
    parser.add_argument(
        "--check", action="store_true",
        help="gate: exit 1 when any engine rung neither resolves to a "
        "roofline nor carries a typed reason, exit 2 when the history "
        "is unusable",
    )
    parser.add_argument("--json", action="store_true")
    parser.add_argument(
        "--report", default=None,
        help="also write the JSON rows to this path (CI artifact)",
    )
    args = parser.parse_args(argv)

    history = load_history(args.history)
    if not history:
        print(
            f"perfattrib: no records in {args.history!r} "
            "(run bench.py first)",
            file=sys.stderr,
        )
        return 2
    latest = history[-1]

    sketches: dict = {}
    if args.bundle:
        from yuma_simulation_tpu.telemetry.flight import load_bundle

        bundle = load_bundle(args.bundle)
        sketches = collect_sketches(bundle.metrics)

    rows = attribute(latest, sketches)
    problems = check_rows(rows)
    payload = json.dumps(
        {"history": args.history, "bundle": args.bundle, "rows": rows,
         "problems": problems},
        indent=2, sort_keys=True,
    )
    if args.report:
        from yuma_simulation_tpu.utils.checkpoint import publish_atomic

        publish_atomic(args.report, payload.encode())
    if args.json:
        print(payload)
    else:
        resolved = sum(
            1 for r in rows if r.get("attained_fraction") is not None
        )
        print(
            f"perfattrib: {len(rows)} rung(s), {resolved} resolved to a "
            f"roofline, {len(sketches)} dispatch key(s) joined "
            f"(backend={latest.get('backend')})"
        )
        render_rows(rows)
    if problems:
        for p in problems:
            print(f"perfattrib: UNRESOLVED: {p}", file=sys.stderr)
        if args.check:
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
