"""Pin golden parity of a chosen engine path ON REAL TPU HARDWARE.

Runs the full 14-case x 9-version x 4-beta total-dividend surface
(the same surface tests/unit/test_parity_golden.py pins on the CPU test
mesh) through one `simulate(..., epoch_impl=...)` path on the actual
chip and writes a JSON artifact with the worst deviation per version.

Usage (from the repo root, TPU visible):

    python tools/tpu_parity.py --impl auto --out TPU_PARITY.json
    python tools/tpu_parity.py --impl fused_scan_mxu --out MXU_PARITY.json

`--impl fused_scan` pins the flagship streamed Pallas scan
(`fused_case_scan`) — on TPU this is also what `auto` selects for these
shapes. `--impl fused_scan_mxu` pins the parity-RELAXED MXU variant: its
bf16x3 support sums can flip one 2^-17 consensus grid point, so its
artifact records the measured bound behind the "~4e-5, one grid point"
claim in ops/pallas_epoch.py instead of leaving it an anecdote.
"""

import argparse
import csv
import datetime
import json
import os
import sys

import numpy as np

# Runs as `python tools/tpu_parity.py` from the repo root; PYTHONPATH
# cannot be used instead — setting it breaks the TPU plugin registration
# in this environment.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from yuma_simulation_tpu.utils import enable_compilation_cache

enable_compilation_cache()

import jax  # noqa: E402

from yuma_simulation_tpu.models.config import (  # noqa: E402
    SimulationHyperparameters,
    YumaConfig,
)
from yuma_simulation_tpu.models.variants import canonical_versions  # noqa: E402
from yuma_simulation_tpu.scenarios import cases  # noqa: E402
from yuma_simulation_tpu.simulation import simulate  # noqa: E402

BETAS = (0, 0.5, 0.99, 1.0)
GOLDEN_DIR = os.path.join("tests", "golden")
STANDARD = ("Validator A", "Validator B", "Validator C")


def run_surface(impl: str) -> tuple[dict[str, float], int]:
    """Worst |deviation| from the golden CSVs per version, and the number
    of compared cells."""
    worst: dict[str, float] = {}
    cells = 0
    for beta in BETAS:
        path = os.path.join(GOLDEN_DIR, f"total_dividends_b{beta}_full.csv")
        with open(path) as f:
            golden = list(csv.DictReader(f))
        assert len(golden) == len(cases)
        for version, params in canonical_versions():
            config = YumaConfig(
                simulation=SimulationHyperparameters(bond_penalty=float(beta)),
                yuma_params=params,
            )
            for row, case in zip(golden, cases):
                assert row["Case"] == case.name, (row["Case"], case.name)
                res = simulate(
                    case,
                    version,
                    config,
                    save_bonds=False,
                    save_incentives=False,
                    epoch_impl=impl,
                )
                # Reference totals are Python-float sums of per-epoch
                # float32 values (reporting/tables.py:83-85).
                totals = np.asarray(res.dividends, np.float64).sum(axis=0)
                for j, std in enumerate(STANDARD):
                    want = float(row[f"{std} - {version}"])
                    diff = abs(float(totals[j]) - want)
                    worst[version] = max(worst.get(version, 0.0), diff)
                    cells += 1
    return worst, cells


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--impl",
        default="auto",
        choices=["auto", "xla", "fused_scan", "fused_scan_mxu"],
    )
    ap.add_argument("--out", default=None, help="artifact path (default stdout)")
    ap.add_argument(
        "--bound",
        type=float,
        default=None,
        help="fail (exit 1) if the worst deviation exceeds this",
    )
    args = ap.parse_args()

    dev = jax.devices()[0]
    worst, cells = run_surface(args.impl)
    overall = max(worst.values())
    artifact = {
        "artifact": (
            "golden parity of the full 14-case x 9-version x 4-beta "
            f"total-dividend surface through epoch_impl={args.impl!r}"
        ),
        "device": f"{dev.device_kind} ({dev.platform})",
        "mode": "x64" if jax.config.jax_enable_x64 else "f32 (TPU default)",
        "impl": args.impl,
        "cells_compared": cells,
        "worst_abs_deviation_per_version": worst,
        "worst_overall": overall,
        "captured": datetime.date.today().isoformat(),
        "notes": (
            "Deviations are vs the reference-generated golden CSVs "
            "(tests/golden/, 6-decimal totals). Every path — auto, xla, "
            "fused_scan AND fused_scan_mxu — shares the 1.5e-6 contract: "
            "since r4 the MXU scan's consensus support is the exact "
            "limb-split integer contraction, bitwise identical to the "
            "VPU scan (the former parity-relaxed tier no longer exists)."
        ),
    }
    text = json.dumps(artifact, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text)
    if args.bound is not None and overall > args.bound:
        print(f"FAIL: worst {overall} > bound {args.bound}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
