"""perfgate: the machine-checkable perf-regression gate over BENCH history.

The BENCH_r* trajectory (115k -> 62k epochs/s on the headline metric
between r4 and r5) regressed silently because nothing diffed one capture
against the last. `bench.py` now appends every run — rates, per-metric
timing dispersion (`cv`), the AOT cost report and roofline verdicts
(`yuma_simulation_tpu.telemetry.cost`) — to ``BENCH_HISTORY.jsonl``;
this CLI diffs the LATEST record against a noise-aware rolling baseline
of the prior ones.

Noise-awareness: a metric's tolerance is widened to
``noise_mult x max(cv_latest, median baseline cv)`` when the timing
dispersion exceeds the flat ``--tolerance`` — a noisy-but-flat metric
must not false-fail, and a tight metric must not hide a real 10% drop
behind a blanket 30% tolerance. Baselines never mix backends or smoke
flags: a TPU capture is not a baseline for a CPU run, and a
short-window ``--smoke`` capture is not a baseline for a real one.

Usage::

    python -m tools.perfgate                      # verdicts, exit 0
    python -m tools.perfgate --check              # exit 1 on regression,
                                                  # exit 2 on schema rot
    python -m tools.perfgate --check --structural # schema gate only (the
                                                  # CPU CI lane: absolute
                                                  # rates are machine-
                                                  # dependent, the record
                                                  # SHAPE is not)
    python -m tools.perfgate --json --report perfgate_report.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_HISTORY = "BENCH_HISTORY.jsonl"
DEFAULT_WINDOW = 5
DEFAULT_TOLERANCE = 0.15
DEFAULT_NOISE_MULT = 3.0

#: Fields every history record must carry (structural gate).
#: ``attained_floor`` (0.10.0) declares, per engine rung, the minimum
#: measured/roofline fraction the attained gate enforces.
REQUIRED_FIELDS = (
    "t", "backend", "smoke", "metric", "value", "unit", "secondary",
    "cv", "costs", "rooflines", "attained_floor", "numerics",
    "cold_start", "whatif", "dispatch_sketch",
)

#: Fields the ``cold_start`` object must carry as numbers (0.17.0:
#: fresh-subprocess first-dispatch seconds, cold vs executable-cache-
#: warm — bench.py `_measure_cold_start`). A record without them is
#: schema rot: the cold-start economics ROADMAP item 1 gates on cannot
#: silently drop out of the history again.
COLD_START_FIELDS = (
    "first_dispatch_seconds_cold",
    "first_dispatch_seconds_warm",
)

#: Fields the ``whatif`` object must carry as numbers (0.18.0: one
#: cached suffix-resume what-if vs the same perturbed world end to end
#: — bench.py `_measure_whatif`). A record without them is schema rot:
#: the chain-replay economics ISSUE 14 gates on cannot silently drop
#: out of the history.
WHATIF_FIELDS = (
    "full_seconds",
    "suffix_seconds",
    "speedup",
    "epoch_ratio",
)

#: The what-if speedup floor as a fraction of the record's own epoch
#: ratio: resuming at epoch k of E gives an ideal speedup of
#: ``E / (E - k)`` (the epoch ratio); fixed per-request costs (baseline
#: load, delta computation, dispatch) eat into it, so the gate demands
#: at least this fraction — a what-if that re-simulates only 20% of the
#: epochs must be measurably, not just theoretically, faster.
WHATIF_SPEEDUP_FLOOR_FRAC = 0.4

#: The numerics-capture overhead ceiling (ISSUE 10 acceptance: the
#: in-scan per-epoch sketch capture must cost < 5% epochs/s on the
#: bench smoke line). Widened by the timing dispersion of the on/off
#: pair exactly like the rolling-baseline tolerances — a noisy smoke
#: window must not false-fail a capture that is actually free.
NUMERICS_OVERHEAD_MAX = 0.05

#: The dispatch-sketch overhead ceiling (ISSUE 19 acceptance: the
#: always-on per-dispatch LatencySketch observation at the engine's
#: dispatch seam must cost < 5% epochs/s on the bench smoke line,
#: seam-on vs seam-off over the same simulate() workload). Widened by
#: the pair's timing dispersion like every other in-record comparison.
DISPATCH_SKETCH_OVERHEAD_MAX = 0.05

#: Every engine rung must appear in the cost report, and each must carry
#: these analysis fields — as numbers, or as explicit nulls with a
#: non-null ``reason`` (the CPU contract for the Pallas rungs).
COST_FIELDS = ("flops", "bytes_accessed", "peak_bytes")

#: The per-epoch-weights metrics ROADMAP item 5 exists to close — the
#: slowest BENCH lines — promoted to FIRST-CLASS tracked lines
#: (0.10.0): a record missing one is schema rot, exactly like a missing
#: cost rung, so none of them can silently drop out of the regression
#: baseline again. bench.py records all three on every backend (CPU
#: runs a scaled-down workload; rates only ever baseline against the
#: same backend+smoke class).
TRACKED_SECONDARY = (
    "true_weights_xla",
    "true_weights_fused",
    "streamed_true_weights",
    "montecarlo_per_epoch_weights",
    "montecarlo_per_epoch_fused",
)

#: Floor-of-floors for the attained-fraction gate (ISSUE 15 ratchet):
#: the effective floor per rung is ``max(record's declaration, this)``
#: — so a bench-side edit (or a hand-crafted history record) can only
#: TIGHTEN the roofline-distance backstop, never silently loosen it.
#: Values mirror bench.py's r06 ATTAINED_FLOORS; the CLI
#: ``--attained-floor`` override still wins outright (explicit operator
#: intent).
DEFAULT_ATTAINED_FLOORS = {
    "fused_varying_mxu": 0.02,
    "fused_varying": 0.02,
    "fused_scan_mxu": 0.02,
    "fused_scan": 0.02,
    "xla": 0.002,
}


def load_history(path: str) -> list[dict]:
    from yuma_simulation_tpu.utils.checkpoint import read_jsonl_tolerant

    return read_jsonl_tolerant(path)


def check_structure(record: dict) -> list[str]:
    """Schema problems in one history record (empty list = sound)."""
    from yuma_simulation_tpu.telemetry.cost import ENGINE_RUNGS

    problems: list[str] = []
    for field in REQUIRED_FIELDS:
        if field not in record:
            problems.append(f"record lacks required field {field!r}")
    value = record.get("value")
    if not isinstance(value, (int, float)) or value <= 0:
        problems.append(f"headline value must be a positive number, got "
                        f"{value!r}")
    for field in ("secondary", "cv", "costs", "rooflines"):
        if field in record and not isinstance(record[field], dict):
            problems.append(f"{field} must be an object")
    secondary = record.get("secondary")
    if isinstance(secondary, dict):
        for name in TRACKED_SECONDARY:
            value = secondary.get(name)
            if not isinstance(value, (int, float)) or value <= 0:
                problems.append(
                    f"tracked secondary metric {name!r} is "
                    + ("missing" if value is None else f"invalid ({value!r})")
                    + " — the per-epoch-weights lines are first-class "
                    "gated metrics"
                )
    floors = record.get("attained_floor")
    if "attained_floor" in record and not isinstance(floors, dict):
        problems.append("attained_floor must be an object")
    numerics = record.get("numerics")
    if "numerics" in record:
        if not isinstance(numerics, dict):
            problems.append("numerics must be an object")
        else:
            for field in ("epochs_per_sec_on", "overhead_frac"):
                if not isinstance(numerics.get(field), (int, float)):
                    problems.append(
                        f"numerics.{field} is "
                        + (
                            "missing"
                            if numerics.get(field) is None
                            else f"invalid ({numerics.get(field)!r})"
                        )
                        + " — the numerics-capture overhead is a "
                        "first-class gated metric"
                    )
    cold = record.get("cold_start")
    if "cold_start" in record:
        if not isinstance(cold, dict):
            problems.append("cold_start must be an object")
        else:
            for field in COLD_START_FIELDS:
                if not isinstance(cold.get(field), (int, float)):
                    problems.append(
                        f"cold_start.{field} is "
                        + (
                            "missing"
                            if cold.get(field) is None
                            else f"invalid ({cold.get(field)!r})"
                        )
                        + " — cold-start wall time is a first-class "
                        "gated metric"
                        + (
                            f" (measurement error: {cold['error']!r})"
                            if "error" in cold
                            else ""
                        )
                    )
    whatif = record.get("whatif")
    if "whatif" in record:
        if not isinstance(whatif, dict):
            problems.append("whatif must be an object")
        else:
            for field in WHATIF_FIELDS:
                if not isinstance(whatif.get(field), (int, float)):
                    problems.append(
                        f"whatif.{field} is "
                        + (
                            "missing"
                            if whatif.get(field) is None
                            else f"invalid ({whatif.get(field)!r})"
                        )
                        + " — the what-if suffix-resume speedup is a "
                        "first-class gated metric"
                        + (
                            f" (measurement error: {whatif['error']!r})"
                            if "error" in whatif
                            else ""
                        )
                    )
    costs = record.get("costs")
    if isinstance(costs, dict):
        # An empty report is schema rot, not a pass: the CI invariant is
        # that every rung is present with its fields (a --skip-costs
        # capture is fine locally but must not green the gate).
        for engine in ENGINE_RUNGS:
            rec = costs.get(engine)
            if not isinstance(rec, dict):
                problems.append(
                    f"cost report lacks engine rung {engine!r}"
                    if rec is None
                    else f"costs[{engine}] is not an object"
                )
                continue
            for field in COST_FIELDS:
                if field not in rec:
                    problems.append(f"costs[{engine}] lacks {field!r}")
                elif rec[field] is None and not rec.get("reason"):
                    problems.append(
                        f"costs[{engine}].{field} is null with no reason"
                    )
    return problems


def _baseline_records(history: list[dict], latest: dict, window: int):
    """The comparable prior records: same backend, same smoke flag, same
    unit, newest `window` of them."""
    prior = [
        r
        for r in history[:-1]
        if r.get("backend") == latest.get("backend")
        and bool(r.get("smoke")) == bool(latest.get("smoke"))
        and r.get("unit") == latest.get("unit")
    ]
    return prior[-window:]


def _metric_values(record: dict) -> dict[str, float]:
    """`{metric_key: rate}` for the headline (+ numeric secondaries,
    + per-rung attained roofline fractions). The headline rides under
    "primary" — the same key its cv uses. Attained fractions ride as
    ``attained:{engine}`` so the rolling-baseline diff gates the
    distance-to-ceiling itself: an absolute-rate regression that the
    noise tolerance absorbs still fails when the fraction of the
    hardware roofline actually hit drops."""
    out: dict[str, float] = {}
    if isinstance(record.get("value"), (int, float)):
        out["primary"] = float(record["value"])
    for key, value in (record.get("secondary") or {}).items():
        if isinstance(value, (int, float)):
            out[key] = float(value)
    for engine, rl in (record.get("rooflines") or {}).items():
        attained = (rl or {}).get("attained_fraction")
        if isinstance(attained, (int, float)):
            out[f"attained:{engine}"] = float(attained)
    return out


def check_attained(record: dict, floors: Optional[dict] = None) -> list[str]:
    """The attained-fraction gate: one failure line per engine rung
    whose measured/roofline fraction sits below its declared floor.

    Floors come from the record's own ``attained_floor`` declaration
    (bench.py writes conservative per-rung backstops — the roofline is
    an amortization-optimistic CEILING, so floors catch collapses, and
    the rolling-baseline diff on the ``attained:*`` metrics catches
    finer drift), RAISED to :data:`DEFAULT_ATTAINED_FLOORS` where the
    declaration sits below it (the ratchet: a record cannot loosen the
    backstop), overridden per rung by ``floors`` (the
    ``--attained-floor`` CLI). Rungs whose attained fraction is null
    (no measured rate, unknown device spec — every CPU build) are
    vacuously fine: the STRUCTURAL gate already demands the nulls be
    explicable, and inventing a fraction would gate noise."""
    declared = dict(record.get("attained_floor") or {})
    for engine, floor in DEFAULT_ATTAINED_FLOORS.items():
        prior = declared.get(engine)
        declared[engine] = (
            max(float(prior), floor)
            if isinstance(prior, (int, float))
            else floor
        )
    declared.update(floors or {})
    failures: list[str] = []
    for engine, rl in (record.get("rooflines") or {}).items():
        attained = (rl or {}).get("attained_fraction")
        floor = declared.get(engine)
        if (
            isinstance(attained, (int, float))
            and isinstance(floor, (int, float))
            and attained < floor
        ):
            failures.append(
                f"{engine}: attained {attained:.3g} of the roofline "
                f"prediction, below the declared floor {floor:.3g}"
            )
    return failures


def check_cold_start(
    record: dict, ceiling: Optional[float] = None
) -> list[str]:
    """The cold-start gate: the CACHE-WARM fresh-subprocess first
    dispatch must land under `ceiling` seconds (``--cold-start-ceiling``
    — the ROADMAP item 1 bar is "well under a second" on top of
    interpreter+jax import, so lanes declare their own budget). The
    cold run is deliberately ungated here: it is machine- and
    toolchain-priced; the rolling history keeps it for trend reading.
    Vacuous without a ceiling or without the measurement — the
    STRUCTURAL gate already fails a record that lacks it."""
    if ceiling is None:
        return []
    cold = record.get("cold_start")
    if not isinstance(cold, dict):
        return []
    warm = cold.get("first_dispatch_seconds_warm")
    if not isinstance(warm, (int, float)):
        return []
    if warm > ceiling:
        return [
            f"cache-warm first dispatch took {warm:.3f}s, above the "
            f"--cold-start-ceiling of {ceiling:.3f}s (cold run: "
            f"{cold.get('first_dispatch_seconds_cold')}s)"
        ]
    return []


def check_whatif(
    record: dict, floor_frac: float = WHATIF_SPEEDUP_FLOOR_FRAC
) -> list[str]:
    """The what-if suffix-resume gate: the record's measured speedup
    (full re-simulation seconds / cached suffix seconds) must reach at
    least ``floor_frac`` of the record's own epoch ratio — the floor is
    derived from the SAME record (resuming at epoch k of E bounds the
    ideal speedup at ``E / (E - k)``), so no cross-run baseline is
    needed and the gate is active in ``--structural`` too. Vacuous when
    the record carries no usable whatif object — the STRUCTURAL gate
    already fails that."""
    whatif = record.get("whatif")
    if not isinstance(whatif, dict):
        return []
    speedup = whatif.get("speedup")
    ratio = whatif.get("epoch_ratio")
    if not isinstance(speedup, (int, float)) or not isinstance(
        ratio, (int, float)
    ):
        return []
    floor = max(1.0, floor_frac * float(ratio))
    if speedup < floor:
        return [
            f"what-if suffix resume sped up only {speedup:.2f}x against "
            f"an epoch ratio of {ratio:.2f} (floor "
            f"{floor_frac:.0%} of ratio = {floor:.2f}x; full "
            f"{whatif.get('full_seconds')}s vs suffix "
            f"{whatif.get('suffix_seconds')}s) — the cached carry is "
            "not paying for itself"
        ]
    return []


def _numerics_noise(record: dict) -> float:
    """The capture-on/off pair's timing dispersion (max cv of the two
    lines) — what widens the overhead ceiling when the windows were
    noisy."""
    cv = record.get("cv") or {}
    return max(
        float(cv.get("true_weights_xla") or 0.0),
        float(cv.get("true_weights_xla_numerics") or 0.0),
    )


def check_numerics_overhead(
    record: dict, ceiling: float = NUMERICS_OVERHEAD_MAX
) -> list[str]:
    """The numerics-capture overhead gate: the record's measured
    ``numerics.overhead_frac`` (capture-on vs capture-off epochs/s over
    the same workload) must sit under the declared ceiling, widened to
    ``3 x`` the pair's timing dispersion when the windows were noisier
    than the ceiling itself (the rolling-baseline rule, applied to one
    in-record comparison). Vacuous when the record carries no numerics
    object — the STRUCTURAL gate already fails that."""
    numerics = record.get("numerics")
    if not isinstance(numerics, dict):
        return []
    overhead = numerics.get("overhead_frac")
    if not isinstance(overhead, (int, float)):
        return []
    noise = _numerics_noise(record)
    ceiling_eff = max(ceiling, DEFAULT_NOISE_MULT * noise)
    if overhead > ceiling_eff:
        return [
            f"numerics capture costs {overhead:.1%} epochs/s on "
            f"{numerics.get('workload', '?')}, above the "
            f"{ceiling_eff:.1%} ceiling (declared {ceiling:.1%}, "
            f"cv {noise:.4f})"
        ]
    return []


def _dispatch_sketch_noise(record: dict) -> float:
    """The seam-on/off pair's timing dispersion (max cv of the two
    lines) — what widens the overhead ceiling when the windows were
    noisy."""
    cv = record.get("cv") or {}
    return max(
        float(cv.get("dispatch_sketch_off") or 0.0),
        float(cv.get("dispatch_sketch_on") or 0.0),
    )


def check_dispatch_sketch_overhead(
    record: dict, ceiling: float = DISPATCH_SKETCH_OVERHEAD_MAX
) -> list[str]:
    """The dispatch-sketch overhead gate: the record's measured
    ``dispatch_sketch.overhead_frac`` (observation-on vs observation-off
    epochs/s over the same simulate() workload) must sit under the
    declared ceiling, noise-widened exactly like the numerics gate.
    Vacuous when the record carries no dispatch_sketch object — the
    STRUCTURAL gate already fails that."""
    sketch = record.get("dispatch_sketch")
    if not isinstance(sketch, dict):
        return []
    overhead = sketch.get("overhead_frac")
    if not isinstance(overhead, (int, float)):
        return []
    noise = _dispatch_sketch_noise(record)
    ceiling_eff = max(ceiling, DEFAULT_NOISE_MULT * noise)
    if overhead > ceiling_eff:
        return [
            f"dispatch-sketch observation costs {overhead:.1%} epochs/s "
            f"on {sketch.get('workload', '?')}, above the "
            f"{ceiling_eff:.1%} ceiling (declared {ceiling:.1%}, "
            f"cv {noise:.4f})"
        ]
    return []


def compare(
    history: list[dict],
    *,
    window: int = DEFAULT_WINDOW,
    tolerance: float = DEFAULT_TOLERANCE,
    noise_mult: float = DEFAULT_NOISE_MULT,
    min_baseline: int = 2,
) -> dict:
    """Diff the latest record against the rolling baseline.

    Returns ``{"latest_t", "backend", "smoke", "baseline_runs",
    "verdicts": {metric: {...}}}`` where each verdict carries the
    latest/baseline rates, the relative delta, the effective tolerance
    (noise-widened when the metric's cv demands it) and a status of
    ``regression`` / ``improvement`` / ``flat`` / ``no_baseline``.
    """
    latest = history[-1]
    baseline = _baseline_records(history, latest, window)
    latest_metrics = _metric_values(latest)
    latest_cv = latest.get("cv") or {}
    verdicts: dict[str, dict] = {}
    for key, value in sorted(latest_metrics.items()):
        base_values = [
            m[key] for m in (_metric_values(r) for r in baseline) if key in m
        ]
        if len(base_values) < min_baseline:
            verdicts[key] = {
                "status": "no_baseline",
                "latest": value,
                "baseline_runs": len(base_values),
            }
            continue
        base = statistics.median(base_values)
        base_cvs = [
            float((r.get("cv") or {}).get(key))
            for r in baseline
            if isinstance((r.get("cv") or {}).get(key), (int, float))
        ]
        noise = max(
            float(latest_cv.get(key) or 0.0),
            statistics.median(base_cvs) if base_cvs else 0.0,
        )
        tol_eff = max(tolerance, noise_mult * noise)
        rel = (value - base) / base if base else 0.0
        if rel < -tol_eff:
            status = "regression"
        elif rel > tol_eff:
            status = "improvement"
        else:
            status = "flat"
        verdicts[key] = {
            "status": status,
            "latest": value,
            "baseline": round(base, 2),
            "baseline_runs": len(base_values),
            "rel_delta": round(rel, 4),
            "tolerance": round(tol_eff, 4),
            "noise_cv": round(noise, 4),
        }
    return {
        "latest_t": latest.get("t"),
        "backend": latest.get("backend"),
        "smoke": bool(latest.get("smoke")),
        "baseline_runs": len(baseline),
        "verdicts": verdicts,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="perfgate", description=__doc__.split("\n\n")[0]
    )
    parser.add_argument(
        "--history", default=DEFAULT_HISTORY,
        help=f"bench history JSONL (default {DEFAULT_HISTORY})",
    )
    parser.add_argument(
        "--check", action="store_true",
        help="gate: exit 2 on structural problems, exit 1 on regressions",
    )
    parser.add_argument(
        "--structural", action="store_true",
        help="validate the record schema only — no baseline comparison "
        "(the CPU CI lane)",
    )
    parser.add_argument("--window", type=int, default=DEFAULT_WINDOW)
    parser.add_argument(
        "--tolerance", type=float, default=DEFAULT_TOLERANCE,
        help=f"flat relative tolerance (default {DEFAULT_TOLERANCE})",
    )
    parser.add_argument(
        "--noise-mult", type=float, default=DEFAULT_NOISE_MULT,
        help="tolerance widens to this multiple of the metric's timing "
        f"CV when noisier than --tolerance (default {DEFAULT_NOISE_MULT})",
    )
    parser.add_argument(
        "--min-baseline", type=int, default=2,
        help="prior comparable runs required before verdicts fire",
    )
    parser.add_argument(
        "--attained-floor", action="append", default=[], metavar="ENGINE=F",
        help="override an engine rung's attained-fraction floor (the "
        "record's own attained_floor declaration is the default); a "
        "rung whose measured/roofline fraction sits below its floor "
        "fails --check — in structural mode too (the gate is vacuous "
        "where the fraction is null, e.g. every CPU build)",
    )
    parser.add_argument(
        "--cold-start-ceiling", type=float, default=None, metavar="SECONDS",
        help="fail --check when the record's CACHE-WARM fresh-subprocess "
        "first dispatch exceeds this many seconds (active in "
        "--structural too: the cold_start pair is an in-record "
        "measurement, no baseline needed)",
    )
    parser.add_argument(
        "--whatif-floor-frac", type=float,
        default=WHATIF_SPEEDUP_FLOOR_FRAC, metavar="FRAC",
        help="fail --check when the what-if suffix-resume speedup falls "
        "below this fraction of the record's own epoch ratio (default "
        f"{WHATIF_SPEEDUP_FLOOR_FRAC}; active in --structural too: the "
        "pair is one in-record measurement)",
    )
    parser.add_argument("--json", action="store_true")
    parser.add_argument(
        "--report", default=None,
        help="also write the JSON verdict to this path (CI artifact)",
    )
    args = parser.parse_args(argv)
    floor_overrides: dict = {}
    for item in args.attained_floor:
        engine, _, value = item.partition("=")
        try:
            floor_overrides[engine] = float(value)
        except ValueError:
            parser.error(f"--attained-floor wants ENGINE=FLOAT, got {item!r}")

    history = load_history(args.history)
    if not history:
        print(
            f"perfgate: no records in {args.history!r} (run bench.py first)",
            file=sys.stderr,
        )
        return 2
    latest = history[-1]
    problems = check_structure(latest)
    attained_failures = check_attained(latest, floor_overrides)
    numerics_failures = check_numerics_overhead(latest)
    dispatch_sketch_failures = check_dispatch_sketch_overhead(latest)
    cold_start_failures = check_cold_start(
        latest, args.cold_start_ceiling
    )
    whatif_failures = check_whatif(latest, args.whatif_floor_frac)
    result: dict = {
        "history": args.history,
        "records": len(history),
        "structural_problems": problems,
        "attained_failures": attained_failures,
        "numerics_failures": numerics_failures,
        "dispatch_sketch_failures": dispatch_sketch_failures,
        "cold_start_failures": cold_start_failures,
        "whatif_failures": whatif_failures,
    }
    if not args.structural:
        result.update(
            compare(
                history,
                window=args.window,
                tolerance=args.tolerance,
                noise_mult=args.noise_mult,
                min_baseline=args.min_baseline,
            )
        )
    payload = json.dumps(result, indent=2, sort_keys=True)
    if args.report:
        from yuma_simulation_tpu.utils.checkpoint import publish_atomic

        publish_atomic(args.report, payload.encode())
    if args.json:
        print(payload)
    else:
        _render(result, latest)
    if problems:
        for p in problems:
            print(f"perfgate: STRUCTURAL: {p}", file=sys.stderr)
        if args.check:
            return 2
    if attained_failures:
        # Active in --structural too: the floor is declared against the
        # record's OWN roofline prediction, so no cross-run baseline is
        # needed for the distance-to-ceiling to be gateable.
        for f in attained_failures:
            print(f"perfgate: ATTAINED-FRACTION: {f}", file=sys.stderr)
        if args.check:
            return 1
    if numerics_failures:
        # Also active in --structural: the overhead is an in-record
        # on/off comparison, no cross-run baseline needed.
        for f in numerics_failures:
            print(f"perfgate: NUMERICS-OVERHEAD: {f}", file=sys.stderr)
        if args.check:
            return 1
    if dispatch_sketch_failures:
        # Also active in --structural: the seam-on/off overhead is one
        # in-record comparison, no cross-run baseline needed.
        for f in dispatch_sketch_failures:
            print(f"perfgate: DISPATCH-SKETCH-OVERHEAD: {f}", file=sys.stderr)
        if args.check:
            return 1
    if cold_start_failures:
        # Also active in --structural: the cold/warm pair is one
        # in-record measurement against a declared ceiling.
        for f in cold_start_failures:
            print(f"perfgate: COLD-START: {f}", file=sys.stderr)
        if args.check:
            return 1
    if whatif_failures:
        # Also active in --structural: the speedup-vs-epoch-ratio pair
        # is one in-record measurement, the floor derived from the
        # record itself.
        for f in whatif_failures:
            print(f"perfgate: WHATIF-SPEEDUP: {f}", file=sys.stderr)
        if args.check:
            return 1
    regressions = [
        k
        for k, v in result.get("verdicts", {}).items()
        if v["status"] == "regression"
    ]
    if regressions and args.check and not args.structural:
        print(
            f"perfgate: REGRESSION beyond tolerance: {', '.join(regressions)}",
            file=sys.stderr,
        )
        return 1
    return 0


def _render(result: dict, latest: dict) -> None:
    print(
        f"perfgate: {result['records']} record(s) in {result['history']}, "
        f"latest backend={latest.get('backend')} "
        f"smoke={bool(latest.get('smoke'))}"
    )
    if result["structural_problems"]:
        print(f"  schema: {len(result['structural_problems'])} problem(s)")
    else:
        print("  schema: sound")
    attained = result.get("attained_failures", [])
    if attained:
        print(f"  attained-fraction: {len(attained)} rung(s) below floor")
    elif latest.get("attained_floor"):
        print("  attained-fraction: within declared floors")
    cold = latest.get("cold_start") or {}
    if result.get("cold_start_failures"):
        print(
            f"  cold-start: ABOVE CEILING "
            f"(warm {cold.get('first_dispatch_seconds_warm')}s)"
        )
    elif isinstance(
        cold.get("first_dispatch_seconds_warm"), (int, float)
    ):
        print(
            f"  cold-start: cold "
            f"{cold.get('first_dispatch_seconds_cold')}s -> warm "
            f"{cold.get('first_dispatch_seconds_warm')}s"
        )
    whatif = latest.get("whatif") or {}
    if result.get("whatif_failures"):
        print(
            f"  whatif-speedup: BELOW FLOOR "
            f"({whatif.get('speedup')}x vs ratio "
            f"{whatif.get('epoch_ratio')})"
        )
    elif isinstance(whatif.get("speedup"), (int, float)):
        print(
            f"  whatif-speedup: {whatif.get('speedup')}x suffix resume "
            f"(epoch ratio {whatif.get('epoch_ratio')})"
        )
    numerics = result.get("numerics_failures", [])
    overhead = (latest.get("numerics") or {}).get("overhead_frac")
    if numerics:
        print(f"  numerics-overhead: ABOVE CEILING ({overhead})")
    elif isinstance(overhead, (int, float)):
        ceiling_eff = max(
            NUMERICS_OVERHEAD_MAX,
            DEFAULT_NOISE_MULT * _numerics_noise(latest),
        )
        print(
            f"  numerics-overhead: {overhead:.2%} "
            f"(ceiling {ceiling_eff:.1%})"
        )
    sketch_fails = result.get("dispatch_sketch_failures", [])
    sketch_overhead = (latest.get("dispatch_sketch") or {}).get(
        "overhead_frac"
    )
    if sketch_fails:
        print(
            f"  dispatch-sketch-overhead: ABOVE CEILING ({sketch_overhead})"
        )
    elif isinstance(sketch_overhead, (int, float)):
        ceiling_eff = max(
            DISPATCH_SKETCH_OVERHEAD_MAX,
            DEFAULT_NOISE_MULT * _dispatch_sketch_noise(latest),
        )
        print(
            f"  dispatch-sketch-overhead: {sketch_overhead:.2%} "
            f"(ceiling {ceiling_eff:.1%})"
        )
    verdicts = result.get("verdicts")
    if verdicts is None:
        return
    for key, v in verdicts.items():
        if v["status"] == "no_baseline":
            print(
                f"  {key}: no baseline ({v['baseline_runs']} comparable "
                f"prior run(s)) latest={v['latest']}"
            )
        else:
            print(
                f"  {key}: {v['status'].upper()} latest={v['latest']} "
                f"baseline={v['baseline']} delta={v['rel_delta']:+.1%} "
                f"tol={v['tolerance']:.1%} (cv={v['noise_cv']})"
            )


if __name__ == "__main__":
    raise SystemExit(main())
