"""shapecheck: the zero-compile shape-contract gate.

`python -m tools.shapecheck --check` abstractly traces (``jax.eval_shape``)
every jitted entry point of the package — the three engine rungs
(`_simulate_scan`, `_simulate_case_fused` VPU/MXU, per-epoch and
epoch-tiled varying), their
donated-carry streamed twins, the batched sweep body, the Monte-Carlo
helpers, and the throughput paths — over the planner's shape-bucket
grid, built from ``ShapeDtypeStruct``s only. It verifies, without a
single XLA compile:

- **output contracts**: every output's shape/dtype matches the declared
  contract for its bucket (``dividends [E, V] f32`` etc.) — a refactor
  that silently transposes an axis, drops a stream, or promotes a dtype
  fails here in milliseconds instead of in a minutes-scale TPU compile;
- **donation validity**: the streamed twins donate their chunk carry,
  which is only sound when the carry-out pytree is structurally
  identical (shape AND dtype, leaf for leaf) to the carry-in — checked
  by round-tripping the carry through ``eval_shape``;
- **static-arg stability**: every static argument value the grid passes
  (specs, impl strings, chunk lengths) must be hashable and *stably*
  hashable — ``hash(x) == hash(deepcopy(x))`` and ``x == deepcopy(x)``
  — because an identity-hashed static key silently turns the jit cache
  into a compile-per-call (the failure RecompilationSentinel catches at
  runtime; this catches it statically);
- **planner coupling**: for every grid workload, ``plan_dispatch`` must
  be deterministic (two calls, equal plans), its bucket key stable, and
  its chosen rung one the contract table covers — so the gate cannot
  silently drift away from what production actually dispatches.

The whole run self-enforces *zero compiles* by executing under a
``RecompilationSentinel(budget=0)`` over every checked entry point
(pinned independently by tests/unit/test_shapecheck.py). This is the
static complement to the runtime drift canaries: the canaries prove two
engines produce the same BITS, shapecheck proves every engine still
honors the same SHAPES — before anything compiles, on any backend.

Exit codes: 0 clean, 1 contract violations (or a compile sneaking in),
2 usage/internal errors. ``--artifact PATH`` writes the JSON findings
payload for the CI analysis lane.
"""

from __future__ import annotations

import argparse
import copy
import dataclasses
import json
import sys
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.parallel import sharded
from yuma_simulation_tpu.simulation import engine, sweep
from yuma_simulation_tpu.simulation.planner import (
    ShapeBucket,
    bucket_shape,
    plan_dispatch,
)
from yuma_simulation_tpu.utils.profiling import (
    RecompilationBudgetExceeded,
    RecompilationSentinel,
)

#: Workload shapes the grid buckets: the reference 3v x 2m cases (one
#: MXU tile after donor-pack padding), the exact one-tile shape, a
#: cross-tile-boundary shape (padding must engage), and the two bench
#: flagships. (V, M, E, B).
GRID_WORKLOADS = (
    (3, 2, 5, 1),       # reference cases -> padded to (8, 128)
    (8, 128, 1, 1),     # exactly one tile, single epoch
    (9, 129, 5, 3),     # crosses both tile boundaries -> (16, 256)
    (64, 256, 7, 2),    # mid-size sweep shape
    (256, 1024, 3, 1),  # bench flagship class
    (256, 4096, 3, 1),  # metagraph flagship (foundry real-subnet shape)
)

#: Variant specs the contracts run under: the plain EMA baseline, the
#: prev-weights carry (extra carry leaf), and a reset-mode capacity
#: variant — together they cover every distinct carry/output structure.
SPEC_VERSIONS = (
    "Yuma 1 (paper)",
    "Yuma 2 (Adrian-Fish)",
    "Yuma 3.1 (Rhef+reset)",
)

#: Engine rungs the contract table covers; the planner-coupling check
#: fails if plan_dispatch ever resolves a rung outside this set.
COVERED_RUNGS = (
    "fused_varying_mxu",
    "fused_varying",
    "fused_scan_mxu",
    "fused_scan",
    "xla",
)


def _sds(shape, dtype) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _fmt(struct) -> str:
    return f"{tuple(struct.shape)}:{jnp.dtype(struct.dtype).name}"


@dataclasses.dataclass
class CheckResult:
    """One (contract, bucket) verdict for the JSON artifact."""

    contract: str
    bucket: str
    ok: bool
    detail: str = ""

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Contract:
    """One declared entry-point contract.

    ``run`` performs the abstract trace for a bucket and returns the
    problem string ("" = clean). ``statics`` lists the static argument
    values whose hash stability the gate verifies."""

    name: str
    run: Callable[[ShapeBucket], str]
    statics: tuple = ()


def _tree_mismatches(got, want, label: str) -> str:
    """Compare two ShapeDtypeStruct pytrees; '' when identical."""
    got_paths = {
        jax.tree_util.keystr(p): leaf
        for p, leaf in jax.tree_util.tree_flatten_with_path(got)[0]
    }
    want_paths = {
        jax.tree_util.keystr(p): leaf
        for p, leaf in jax.tree_util.tree_flatten_with_path(want)[0]
    }
    problems = []
    for key in sorted(set(got_paths) | set(want_paths)):
        g, w = got_paths.get(key), want_paths.get(key)
        if g is None:
            problems.append(f"{label}{key}: missing (contract declares "
                            f"{_fmt(w)})")
        elif w is None:
            problems.append(f"{label}{key}: undeclared output {_fmt(g)}")
        elif tuple(g.shape) != tuple(w.shape) or jnp.dtype(
            g.dtype
        ) != jnp.dtype(w.dtype):
            problems.append(
                f"{label}{key}: got {_fmt(g)}, contract declares {_fmt(w)}"
            )
    return "; ".join(problems)


def _engine_inputs(b: ShapeBucket):
    """ShapeDtypeStructs for one unbatched engine dispatch at the
    bucket's COMPILED (padded) shape — the axes a cached program sees."""
    E, V, M = max(1, b.epochs), b.padded_V, b.padded_M
    return (
        _sds((E, V, M), jnp.float32),
        _sds((E, V), jnp.float32),
        _sds((), jnp.int32),
        _sds((), jnp.int32),
    )


def _engine_expect(b: ShapeBucket) -> dict:
    """The full-save output contract of every engine rung."""
    E, V, M = max(1, b.epochs), b.padded_V, b.padded_M
    return {
        "dividends": _sds((E, V), jnp.float32),
        "bonds": _sds((E, V, M), jnp.float32),
        "incentives": _sds((E, M), jnp.float32),
        "consensus": _sds((E, M), jnp.float32),
    }


def _numerics_expect(E: int):
    """Per-stream sketch contract: five [E] leaves, fingerprint u32."""
    from yuma_simulation_tpu.simulation.carry import NumericsSketch

    return NumericsSketch(
        finite_frac=_sds((E,), jnp.float32),
        lo=_sds((E,), jnp.float32),
        hi=_sds((E,), jnp.float32),
        absmax=_sds((E,), jnp.float32),
        fingerprint=_sds((E,), jnp.uint32),
    )


def _carry_struct(b: ShapeBucket, spec) -> dict:
    V, M = b.padded_V, b.padded_M
    carry = {
        "bonds": _sds((V, M), jnp.float32),
        "consensus": _sds((M,), jnp.float32),
    }
    if spec.carries_prev_weights:
        carry["w_prev"] = _sds((V, M), jnp.float32)
    return carry


def _run_xla(b: ShapeBucket, spec, cfg) -> str:
    W, S, ri, re_ = _engine_inputs(b)
    got = jax.eval_shape(
        lambda W, S, ri, re_, cfg: engine._simulate_scan(
            W, S, ri, re_, cfg, spec,
            save_bonds=True, save_incentives=True, save_consensus=True,
            consensus_impl="bisect",
        ),
        W, S, ri, re_, cfg,
    )
    return _tree_mismatches(got, _engine_expect(b), "ys")


def _run_fused(
    b: ShapeBucket, spec, cfg, *, mxu: bool, varying: bool = False
) -> str:
    W, S, ri, re_ = _engine_inputs(b)
    got = jax.eval_shape(
        lambda W, S, ri, re_, cfg: engine._simulate_case_fused(
            W, S, ri, re_, cfg, spec,
            save_bonds=True, save_incentives=True, save_consensus=True,
            mxu=mxu, varying=varying,
        ),
        W, S, ri, re_, cfg,
    )
    return _tree_mismatches(got, _engine_expect(b), "ys")


def _run_numerics(b: ShapeBucket, spec, cfg) -> str:
    """The drift-canary capture contract: sketches ride the jitted
    outputs as [E] streams (zero host syncs by construction)."""
    W, S, ri, re_ = _engine_inputs(b)
    E = max(1, b.epochs)
    got = jax.eval_shape(
        lambda W, S, ri, re_, cfg: engine._simulate_scan(
            W, S, ri, re_, cfg, spec,
            save_bonds=False, save_incentives=False, save_consensus=False,
            consensus_impl="bisect", capture_numerics=True,
        ),
        W, S, ri, re_, cfg,
    )
    want = {
        "dividends": _sds((E, b.padded_V), jnp.float32),
        "numerics": {
            "dividends": _numerics_expect(E),
            "consensus": _numerics_expect(E),
        },
    }
    return _tree_mismatches(got, want, "ys")


def _run_streamed(
    b: ShapeBucket, spec, cfg, *, fused: bool, varying: bool = False
) -> str:
    """Donation validity: the donated chunk carry must round-trip to a
    structurally identical carry-out, or donation would be unsound (the
    donated buffer could not back the next chunk's carry)."""
    W, S, ri, re_ = _engine_inputs(b)
    carry_in = _carry_struct(b, spec)
    if fused:
        fn = engine._simulate_case_fused_streamed

        def call(W, S, ri, re_, cfg, c):
            return fn(
                W, S, ri, re_, cfg, spec,
                save_bonds=False, save_incentives=False,
                carry=c, return_carry=True, varying=varying,
            )
    else:
        fn = engine._simulate_scan_streamed

        def call(W, S, ri, re_, cfg, c):
            return fn(
                W, S, ri, re_, cfg, spec,
                save_bonds=False, save_incentives=False,
                consensus_impl="bisect", carry=c, return_carry=True,
            )

    ys, carry_out = jax.eval_shape(call, W, S, ri, re_, cfg, carry_in)
    problems = _tree_mismatches(
        carry_out, carry_in, "carry"
    )  # donated-in == out
    E = max(1, b.epochs)
    problems2 = _tree_mismatches(
        ys, {"dividends": _sds((E, b.padded_V), jnp.float32)}, "ys"
    )
    return "; ".join(p for p in (problems, problems2) if p)


def _run_suffix_resume(b: ShapeBucket, spec, cfg, *, rung: str) -> str:
    """The suffix-resume entry point (0.18.0 — the chain-replay state
    cache's engine seam): the PLAIN engines called with a supplied
    carry, a traced epoch offset, and ``return_carry=True``. The
    carry-out must round-trip structurally identical to the carry-in
    (a ``state_<k>.npz`` from one segment must feed the next segment's
    ``initial_state=`` for any k), and the ys contract must be the
    ordinary per-epoch one — checked per engine rung across every
    planner bucket, still zero compiles."""
    W, S, ri, re_ = _engine_inputs(b)
    carry_in = _carry_struct(b, spec)
    offset = _sds((), jnp.int32)
    if rung == "xla":

        def call(W, S, ri, re_, cfg, c, off):
            return engine._simulate_scan(
                W, S, ri, re_, cfg, spec,
                save_bonds=False, save_incentives=True,
                consensus_impl="bisect",
                carry=c, epoch_offset=off, return_carry=True,
            )
    else:
        from yuma_simulation_tpu.simulation.planner import rung_flags

        def call(W, S, ri, re_, cfg, c, off):
            return engine._simulate_case_fused(
                W, S, ri, re_, cfg, spec,
                save_bonds=False, save_incentives=True,
                carry=c, epoch_offset=off, return_carry=True,
                **rung_flags(rung),
            )

    ys, carry_out = jax.eval_shape(
        call, W, S, ri, re_, cfg, carry_in, offset
    )
    E, V, M = max(1, b.epochs), b.padded_V, b.padded_M
    problems = _tree_mismatches(carry_out, carry_in, "carry")
    problems2 = _tree_mismatches(
        ys,
        {
            "dividends": _sds((E, V), jnp.float32),
            "incentives": _sds((E, M), jnp.float32),
        },
        "ys",
    )
    return "; ".join(p for p in (problems, problems2) if p)


def _run_batched(b: ShapeBucket, spec, cfg) -> str:
    E, V, M = max(1, b.epochs), b.padded_V, b.padded_M
    B = max(1, b.batch)
    got = jax.eval_shape(
        lambda W, S, ri, re_, cfg: sweep._simulate_batch_xla(
            W, S, ri, re_, cfg, spec, False, False, "bisect"
        ),
        _sds((B, E, V, M), jnp.float32),
        _sds((B, E, V), jnp.float32),
        _sds((B,), jnp.int32),
        _sds((B,), jnp.int32),
        cfg,
    )
    want = {"dividends": _sds((B, E, V), jnp.float32)}
    return _tree_mismatches(got, want, "ys")


def _run_mc(b: ShapeBucket, spec, cfg) -> str:
    """The Monte-Carlo helpers: epoch-ordered accumulation keeps [B, V];
    the slab generator materializes [B, CH, V, M] fresh weights."""
    V, M = b.padded_V, b.padded_M
    B, E, CH = max(1, b.batch), max(1, b.epochs), 4
    tot = jax.eval_shape(
        sharded._mc_epoch_sum,
        _sds((B, V), jnp.float32),
        _sds((B, E, V), jnp.float32),
    )
    problems = _tree_mismatches(tot, _sds((B, V), jnp.float32), "totals")
    slab = jax.eval_shape(
        lambda k, lo, bw, p: sharded._montecarlo_weight_slab(
            k, lo, bw, p, chunk_epochs=CH
        ),
        _sds((B, 2), jnp.uint32),
        _sds((), jnp.int32),
        _sds((V, M), jnp.float32),
        _sds((), jnp.float32),
    )
    problems2 = _tree_mismatches(
        slab, _sds((B, CH, V, M), jnp.float32), "slab"
    )
    return "; ".join(p for p in (problems, problems2) if p)


def _run_throughput(b: ShapeBucket, spec, cfg) -> str:
    """simulate_scaled / _batch / _constant: in-carry accumulation
    returns `[.., V]` totals plus the final `[.., V, M]` bond state."""
    V, M = b.padded_V, b.padded_M
    B, E = max(1, b.batch), max(1, b.epochs)
    W, S = _sds((V, M), jnp.float32), _sds((V,), jnp.float32)
    scales = _sds((E,), jnp.float32)
    acc, bonds = jax.eval_shape(
        lambda W, S, sc, cfg: engine.simulate_scaled(
            W, S, sc, cfg, spec, consensus_impl="bisect", epoch_impl="xla"
        ),
        W, S, scales, cfg,
    )
    problems = [
        _tree_mismatches(acc, _sds((V,), jnp.float32), "acc"),
        _tree_mismatches(bonds, _sds((V, M), jnp.float32), "bonds"),
    ]
    accb, bondsb = jax.eval_shape(
        lambda W, S, sc, cfg: engine.simulate_scaled_batch(
            W, S, sc, cfg, spec, consensus_impl="bisect", epoch_impl="xla"
        ),
        _sds((B, V, M), jnp.float32),
        _sds((B, V), jnp.float32),
        scales,
        cfg,
    )
    problems.append(
        _tree_mismatches(accb, _sds((B, V), jnp.float32), "acc_batch")
    )
    problems.append(
        _tree_mismatches(
            bondsb, _sds((B, V, M), jnp.float32), "bonds_batch"
        )
    )
    accc, bondsc = jax.eval_shape(
        lambda W, S, cfg: engine.simulate_constant(
            W, S, E, cfg, spec, consensus_impl="bisect"
        ),
        W, S, cfg,
    )
    problems.append(
        _tree_mismatches(accc, _sds((V,), jnp.float32), "acc_const")
    )
    problems.append(
        _tree_mismatches(
            bondsc, _sds((V, M), jnp.float32), "bonds_const"
        )
    )
    return "; ".join(p for p in problems if p)


#: Every jitted object the gate traces — the RecompilationSentinel's
#: tracked set: eval_shape over ANY of these must add zero cache
#: entries, or the gate itself would be paying compiles.
ENTRY_POINTS = (
    engine._simulate_scan,
    engine._simulate_case_fused,
    engine._simulate_scan_streamed,
    engine._simulate_case_fused_streamed,
    engine.simulate_scaled,
    engine.simulate_scaled_batch,
    engine.simulate_constant,
    sweep._simulate_batch_xla,
    sharded._mc_epoch_sum,
    sharded._montecarlo_weight_slab,
)


def _static_problems(value, label: str) -> str:
    """Hashability AND hash stability of one static-arg value: an
    identity-hashed object is a compile-per-call in disguise."""
    try:
        h = hash(value)
    except TypeError:
        return f"static arg {label} is unhashable ({type(value).__name__})"
    try:
        clone = copy.deepcopy(value)
    except Exception:  # unclonable singletons (None, modules) are stable
        return ""
    if value != clone or h != hash(clone):
        return (
            f"static arg {label} hashes by identity "
            f"({type(value).__name__}): every instance is a fresh jit "
            "cache key — a silent compile per call"
        )
    return ""


def build_grid() -> list[ShapeBucket]:
    """The planner bucket grid, deduped by compile-cache key."""
    seen: dict[str, ShapeBucket] = {}
    for V, M, E, B in GRID_WORKLOADS:
        b = bucket_shape(V, M, epochs=E, batch=B)
        seen.setdefault(b.key, b)
    return list(seen.values())


def _planner_coupling(b: ShapeBucket, cfg) -> str:
    """plan_dispatch determinism + rung coverage for this bucket."""
    shape = (max(1, b.epochs), b.padded_V, b.padded_M)
    spec = variant_for_version(SPEC_VERSIONS[0])
    plan_a = plan_dispatch("shapecheck", shape, spec, cfg, jnp.float32)
    plan_b = plan_dispatch("shapecheck", shape, spec, cfg, jnp.float32)
    problems = []
    if plan_a != plan_b:
        problems.append(
            "plan_dispatch is nondeterministic for this shape "
            f"({plan_a} != {plan_b})"
        )
    if plan_a.engine not in COVERED_RUNGS:
        problems.append(
            f"planner resolved uncovered rung {plan_a.engine!r}: add a "
            "shapecheck contract before shipping a new rung"
        )
    if plan_a.bucket.key != bucket_shape(
        b.padded_V, b.padded_M, epochs=max(1, b.epochs), batch=1
    ).key:
        problems.append(
            f"bucket key unstable: plan says {plan_a.bucket.key!r}"
        )
    return "; ".join(problems)


def run_shapecheck(cfg: Optional[YumaConfig] = None) -> list[CheckResult]:
    """Every contract over every grid bucket; see module docstring."""
    cfg = cfg if cfg is not None else YumaConfig()
    specs = {v: variant_for_version(v) for v in SPEC_VERSIONS}
    results: list[CheckResult] = []

    def record(contract: str, bucket: str, problem: str) -> None:
        results.append(
            CheckResult(contract, bucket, ok=not problem, detail=problem)
        )

    # static-arg stability (bucket-independent, checked once)
    for version, spec in specs.items():
        record(
            "static-args",
            f"spec:{version}",
            _static_problems(spec, f"spec[{version}]"),
        )
    record("static-args", "consensus_impl", _static_problems("bisect", "consensus_impl"))
    record("static-args", "chunk_epochs", _static_problems(4, "chunk_epochs"))

    for b in build_grid():
        record("planner", b.key, _planner_coupling(b, cfg))
        for version, spec in specs.items():
            tag = f"{b.key}/{version}"
            try:
                record("engine-xla", tag, _run_xla(b, spec, cfg))
                record("engine-fused", tag, _run_fused(b, spec, cfg, mxu=False))
                record("engine-mxu", tag, _run_fused(b, spec, cfg, mxu=True))
                record(
                    "engine-varying",
                    tag,
                    _run_fused(b, spec, cfg, mxu=False, varying=True),
                )
                record(
                    "engine-varying-mxu",
                    tag,
                    _run_fused(b, spec, cfg, mxu=True, varying=True),
                )
                record("streamed-xla", tag, _run_streamed(b, spec, cfg, fused=False))
                record("streamed-fused", tag, _run_streamed(b, spec, cfg, fused=True))
                record(
                    "streamed-varying",
                    tag,
                    _run_streamed(b, spec, cfg, fused=True, varying=True),
                )
                for rung in COVERED_RUNGS:
                    record(
                        f"suffix-resume-{rung}",
                        tag,
                        _run_suffix_resume(b, spec, cfg, rung=rung),
                    )
            except Exception as exc:  # abstract trace itself failed
                record(
                    "engine", tag, f"abstract trace raised {type(exc).__name__}: {exc}"
                )
        base = specs[SPEC_VERSIONS[0]]
        try:
            record("numerics-capture", b.key, _run_numerics(b, base, cfg))
            record("batched-xla", b.key, _run_batched(b, base, cfg))
            record("montecarlo", b.key, _run_mc(b, base, cfg))
            record("throughput", b.key, _run_throughput(b, base, cfg))
        except Exception as exc:
            record(
                "aux", b.key, f"abstract trace raised {type(exc).__name__}: {exc}"
            )
    return results


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="shapecheck",
        description=(
            "zero-compile shape-contract gate: jax.eval_shape every "
            "jitted entry point over the planner bucket grid"
        ),
    )
    parser.add_argument(
        "--check", action="store_true",
        help="gate mode (the default behavior; spelled out for CI "
        "readability)",
    )
    parser.add_argument(
        "--format", choices=("human", "json"), default="human"
    )
    parser.add_argument(
        "--artifact", metavar="PATH",
        help="also write the JSON payload to PATH (CI artifact)",
    )
    args = parser.parse_args(argv)

    try:
        with RecompilationSentinel(
            *ENTRY_POINTS, budget=0, label="shapecheck"
        ):
            results = run_shapecheck()
        compile_problem = ""
    except RecompilationBudgetExceeded as exc:
        # The gate's own invariant: abstract tracing must never compile.
        results = []
        compile_problem = str(exc)

    failures = [r for r in results if not r.ok]
    payload = {
        "checks": [r.to_json() for r in results],
        "total": len(results),
        "failures": len(failures),
        "compiles_added": compile_problem or 0,
        "entry_points": [
            getattr(f, "__name__", str(f)) for f in ENTRY_POINTS
        ],
        "buckets": [b.key for b in build_grid()],
    }
    if args.artifact:
        with open(args.artifact, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")
    if args.format == "json":
        print(json.dumps(payload, indent=2))
    else:
        for r in failures:
            print(f"shapecheck: FAIL {r.contract} [{r.bucket}]: {r.detail}")
        if compile_problem:
            print(f"shapecheck: FAIL zero-compile invariant: {compile_problem}")
        compiles = "0 compiles" if not compile_problem else "COMPILED"
        print(
            f"shapecheck: {len(results)} checks over "
            f"{len(build_grid())} buckets, {len(failures)} failure(s), "
            f"{compiles}"
        )
    return 1 if (failures or compile_problem) else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
