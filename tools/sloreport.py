"""sloreport: render and gate a flight bundle's SLO state.

The judgment half of the observability CLI pair (``tools/obsreport.py``
renders what happened; this renders whether it was ACCEPTABLE). Reads
the ``slo.json`` a flight-recorder publish leaves beside the spans —
declarative :class:`yuma_simulation_tpu.telemetry.slo.SLOSpec`
objectives, per-SLO burn state, mergeable latency sketches with their
headline quantiles, and the alert history — and renders one report per
bundle. Fleet stores are detected automatically: every host bundle
under ``hosts/`` reports (a SIGKILLed host that never published is
skipped, not failed — its ledger is its record).

Usage::

    python -m tools.sloreport BUNDLE_DIR            # render the state
    python -m tools.sloreport BUNDLE_DIR --check    # CI gate: exit 2 if
                                                    # any SLO was
                                                    # fast-burning at
                                                    # capture, or the
                                                    # state is malformed
    python -m tools.sloreport BUNDLE_DIR --json     # machine-readable

``--check`` semantics: the bundle is the service's last word — a bundle
captured while an SLO fast-burns its error budget records an outage the
deploy pipeline must not wave through, so the gate exits non-zero;
recovery before capture un-flips the state and the gate passes. A
bundle with no ``slo.json`` passes with a note (old bundles stay
valid — the format is additive) unless ``--require`` demands one.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

_VALID_STATES = ("ok", "slow_burn", "fast_burn")


def load_slo(directory: str | pathlib.Path) -> dict | None:
    """The bundle's ``slo.json``, or None when absent/undecodable."""
    path = pathlib.Path(directory) / "slo.json"
    if not path.exists():
        return None
    try:
        return json.loads(path.read_text())
    except (json.JSONDecodeError, OSError):
        return None


def check_slo(snapshot: dict | None) -> list[str]:
    """Gate problems for one bundle's SLO state (empty = pass):
    structural rot (unknown states, specs/states mismatch) and any SLO
    captured in ``fast_burn`` — the state the serving tier sheds under,
    which a green pipeline must never carry forward silently."""
    if snapshot is None:
        return []
    problems: list[str] = []
    states = snapshot.get("states")
    if not isinstance(states, dict):
        return ["slo.json carries no states mapping"]
    spec_names = {
        s.get("name") for s in snapshot.get("specs", ()) if isinstance(s, dict)
    }
    for name, st in sorted(states.items()):
        state = st.get("state") if isinstance(st, dict) else st
        if state not in _VALID_STATES:
            problems.append(f"SLO {name}: unknown state {state!r}")
            continue
        if state == "fast_burn":
            burn = (
                st.get("fast_burn_rate") if isinstance(st, dict) else None
            )
            problems.append(
                f"SLO {name} was FAST-BURNING at capture"
                + (f" (burn rate {burn})" if burn is not None else "")
            )
        if spec_names and name not in spec_names:
            problems.append(f"SLO {name} has state but no spec")
    return problems


def render_slo(directory: str, snapshot: dict | None) -> str:
    lines = [f"SLO report: {directory}"]
    if snapshot is None:
        lines.append(
            "no slo.json recorded (pre-0.13.0 bundle, or the process "
            "observed no SLO signals)"
        )
        return "\n".join(lines)
    states = snapshot.get("states", {})
    specs = {
        s.get("name"): s
        for s in snapshot.get("specs", ())
        if isinstance(s, dict)
    }
    for name, st in sorted(states.items()):
        spec = specs.get(name, {})
        state = st.get("state", "?") if isinstance(st, dict) else st
        flag = {"ok": " ", "slow_burn": "~", "fast_burn": "!"}.get(state, "?")
        parts = [
            f"  [{flag}] {name}: {state}",
            f"objective={st.get('objective', spec.get('objective', '?'))}",
            f"fast_burn={st.get('fast_burn_rate', '?')}"
            f"/{spec.get('fast_burn_threshold', '?')}",
            f"slow_burn={st.get('slow_burn_rate', '?')}"
            f"/{spec.get('slow_burn_threshold', '?')}",
        ]
        fw = st.get("fast_window") if isinstance(st, dict) else None
        if isinstance(fw, dict):
            parts.append(f"window={fw.get('good', 0)}g/{fw.get('bad', 0)}b")
        if spec.get("description"):
            parts.append(f"({spec['description']})")
        lines.append(" ".join(parts))
    sketches = snapshot.get("sketches", {})
    if sketches:
        lines.append("sketches:")
        for metric, rec in sorted(sketches.items()):
            q = rec.get("quantiles", {})

            def fmt(key: str) -> str:
                v = q.get(key)
                return "?" if v is None else f"{v:.4g}s"

            lines.append(
                f"  {metric}: n={rec.get('count', 0)} "
                f"p50={fmt('0.5')} p90={fmt('0.9')} p99={fmt('0.99')} "
                f"max={rec.get('max')}"
            )
    alerts = snapshot.get("alerts", ())
    if alerts:
        lines.append(f"alerts ({len(alerts)}):")
        for a in alerts[-10:]:
            lines.append(
                f"  {a.get('slo')}: {a.get('from')} -> {a.get('to')} "
                f"(burn {a.get('burn_rate')})"
            )
    return "\n".join(lines)


def _targets(directory: str) -> list[tuple[str, pathlib.Path]]:
    """The bundle directories to report: the fleet store's per-host
    bundles, or the directory itself."""
    from yuma_simulation_tpu.fabric.store import FleetStore, is_fleet_store

    if is_fleet_store(directory):
        store = FleetStore(directory)
        return [
            (f"host {host_id}", store.host_dir(host_id))
            for host_id in store.host_ids()
        ]
    return [("bundle", pathlib.Path(directory))]


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="sloreport", description=__doc__.split("\n\n")[0]
    )
    parser.add_argument("directory", help="flight bundle or fleet store")
    parser.add_argument(
        "--check",
        action="store_true",
        help="exit 2 when any SLO was fast-burning at capture or the "
        "recorded state is malformed",
    )
    parser.add_argument(
        "--require",
        action="store_true",
        help="with --check: a missing slo.json is itself a failure",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit the state as JSON"
    )
    args = parser.parse_args(argv)

    targets = _targets(args.directory)
    snapshots = {label: load_slo(path) for label, path in targets}
    if args.json:
        print(
            json.dumps(
                {label: snap for label, snap in snapshots.items()},
                indent=2,
                sort_keys=True,
            )
        )
    else:
        first = True
        for label, path in targets:
            if not first:
                print()
            first = False
            print(render_slo(f"{label} ({path})", snapshots[label]))
    if args.check:
        problems: list[str] = []
        recorded = 0
        for label, _path in targets:
            snap = snapshots[label]
            if snap is not None:
                recorded += 1
            problems.extend(f"{label}: {p}" for p in check_slo(snap))
        if args.require and recorded == 0:
            problems.append("no slo.json found in any target bundle")
        if problems:
            print("\nsloreport --check FAILED:", file=sys.stderr)
            for p in problems:
                print(f"  - {p}", file=sys.stderr)
            return 2
        print(
            f"\nsloreport --check: {recorded}/{len(targets)} bundle(s) "
            "recorded SLO state; none fast-burning"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
