"""``python -m tools.wirecheck`` entry point."""

import sys

from tools.wirecheck.cli import main

sys.exit(main())
