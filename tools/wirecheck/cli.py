"""wirecheck command line: ``python -m tools.wirecheck --check``.

Two verbs over the same whole-program index:

- ``--check`` (default): run the JX3xx wire-contract gates over the
  analyzed roots, diff the produced schemas against the committed
  ``SCHEMAS.lock.json``, and exit 1 on any finding — this is the CI
  gate. A missing lock is a hard error (exit 2): the lock is part of
  the contract, a clean checkout must carry it.
- ``--update``: regenerate the lock from the current tree. This is the
  sanctioned way to evolve a schema; because the gate is additive-only,
  ``--update`` is routine when a record grows a field and a reviewed
  act when one disappears (the diff shows up in the lock's git diff).

Exit codes mirror jaxlint: 0 clean, 1 findings (with ``--strict`` also
unused JX3xx suppressions), 2 usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path
from typing import Optional, Sequence

from tools.jaxlint.analyzer import analyze_units, iter_python_files
from tools.jaxlint.program import Program, parse_unit

from tools.wirecheck.extract import extract_index
from tools.wirecheck.gates import lock_diff, schemas_of

#: rules delegated to the jaxlint driver (suppressions, --strict sweep)
WIRE_RULES = {"JX301", "JX302", "JX303", "JX304"}

DEFAULT_ROOTS = ("yuma_simulation_tpu", "tools", "tests")
DEFAULT_LOCK = "SCHEMAS.lock.json"


def _load_lock(path: Path) -> Optional[dict]:
    if not path.is_file():
        return None
    payload = json.loads(path.read_text(encoding="utf-8"))
    schemas = payload.get("schemas")
    if not isinstance(schemas, dict):
        raise SystemExit(
            f"wirecheck: malformed lock file {path} (no 'schemas' object)"
        )
    return schemas


def _write_lock(path: Path, schemas: dict) -> None:
    payload = {"version": 1, "schemas": schemas}
    path.write_text(
        json.dumps(payload, indent=2, sort_keys=True) + "\n",
        encoding="utf-8",
    )


def _payload(schemas, findings, lock_problems, unused) -> dict:
    return {
        "schemas": schemas,
        "findings": [
            {
                "path": f.path,
                "line": f.line,
                "code": f.code,
                "message": f.message,
            }
            for f in findings
        ],
        "lock_regressions": [
            {"kind": kind, "key": key, "message": message}
            for kind, key, message in lock_problems
        ],
        "unused_suppressions": [
            {
                "path": p,
                "line": line,
                "codes": sorted(codes) if codes else None,
            }
            for p, line, codes in unused
        ],
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="wirecheck",
        description=(
            "whole-program wire/durable-record contract analyzer "
            "(ledger events, lease annotations, HTTP payloads, "
            "slo/numerics telemetry) with an additive-only schema lock"
        ),
    )
    parser.add_argument(
        "paths", nargs="*", default=list(DEFAULT_ROOTS),
        help="roots to analyze (default: %(default)s — partial roots "
        "weaken the gates, which self-gate on missing evidence)",
    )
    mode = parser.add_mutually_exclusive_group()
    mode.add_argument(
        "--check", action="store_true",
        help="gate the tree against the lock; exit 1 on findings "
        "(default verb)",
    )
    mode.add_argument(
        "--update", action="store_true",
        help="regenerate the schema lock from the current tree",
    )
    parser.add_argument(
        "--lock", metavar="PATH", default=DEFAULT_LOCK,
        help="schema lock file (default: %(default)s)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="print the JSON payload instead of human-readable lines",
    )
    parser.add_argument(
        "--artifact", metavar="PATH",
        help="also write the JSON payload to PATH (CI artifact)",
    )
    parser.add_argument(
        "--strict", action="store_true",
        help="also fail on unused # jaxlint: disable=JX3xx suppressions",
    )
    args = parser.parse_args(argv)

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(
            f"wirecheck: path does not exist: {', '.join(missing)}",
            file=sys.stderr,
        )
        return 2

    units = []
    for file in iter_python_files(args.paths):
        units.append(
            parse_unit(file.read_text(encoding="utf-8"), str(file))
        )
    if not units:
        print(
            "wirecheck: no python files found under "
            f"{', '.join(args.paths)}",
            file=sys.stderr,
        )
        return 2

    # The gate pass rides the jaxlint driver so per-line suppressions
    # (and their --strict staleness sweep) behave identically whether a
    # finding surfaces via `python -m tools.jaxlint` or here. JX304 is
    # NOT delegated: the jaxlint family reads the repo-root lock, while
    # this CLI owns --lock and reports the diff itself below.
    reports = analyze_units(units, select=WIRE_RULES - {"JX304"})
    findings = [f for r in reports for f in r.findings]
    unused = [
        (r.path, line, codes)
        for r in reports
        for line, codes in r.unused_suppressions
    ]

    schemas = schemas_of(extract_index(Program(units)))
    lock_path = Path(args.lock)

    if args.update:
        _write_lock(lock_path, schemas)
        print(
            f"wirecheck: wrote {lock_path} "
            f"({sum(len(v) for v in schemas.values())} record schema(s) "
            f"across {len(schemas)} kind(s))"
        )
        if findings:
            print(
                f"wirecheck: note: {len(findings)} contract finding(s) "
                "remain — --update freezes schemas, it does not waive "
                "JX301-JX303",
                file=sys.stderr,
            )
        return 0

    locked = _load_lock(lock_path)
    if locked is None:
        print(
            f"wirecheck: lock file {lock_path} not found — run "
            "`python -m tools.wirecheck --update` and commit it",
            file=sys.stderr,
        )
        return 2
    lock_problems = lock_diff(schemas, locked)

    payload = _payload(schemas, findings, lock_problems, unused)
    if args.artifact:
        with open(args.artifact, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
            fh.write("\n")

    if args.as_json:
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f.render())
        for kind, key, message in lock_problems:
            print(f"{lock_path}:1:0: JX304 {message}")
        for p, line, codes in unused:
            label = ",".join(sorted(codes)) if codes else "all"
            print(
                f"{p}:{line}:0: note: unused suppression ({label})"
                + ("" if args.strict else " [--strict fails on this]")
            )
        print(
            f"wirecheck: {len(findings)} finding(s), "
            f"{len(lock_problems)} lock regression(s), "
            f"{len(unused)} unused suppression(s) across "
            f"{len(units)} file(s)"
        )

    if findings or lock_problems:
        return 1
    if args.strict and unused:
        return 1
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
