"""wirecheck: whole-program wire/durable-artifact contract analysis.

The serve/fleet/replay tiers communicate through five duck-typed record
surfaces — ledger JSONL, ``event=`` log lines, lease-annotation
sidecars, HTTP request/response bodies, and the flight bundle's
``slo.json``/``numerics.jsonl`` streams — none of which any type
checker sees: a producer writes a dict literal, a consumer ``.get``s a
field name, and nothing but a drill that happens to exercise both sides
notices when the names drift apart. This package rides jaxlint's
program index (:mod:`tools.jaxlint.program`) to make those shapes a
checked contract:

- :mod:`tools.wirecheck.extract` indexes every producer (dict literals
  flowing into ``FailureLedger.append`` / ``log_event`` /
  ``lease.annotate`` / serve response builders / client request
  builders / ``sketch_records`` / ``SLOEngine.snapshot``) and every
  consumer (subscript/``.get`` field reads in the report tools, fleet
  health, router claim scoring, and client response parsing);
- :mod:`tools.wirecheck.gates` unifies them into per-artifact-kind
  field schemas and checks the four wire-contract properties (orphan
  reads, typed-error totality, lease-annotation closure, additive-only
  lock evolution) — the same checks jaxlint surfaces as the JX3xx rule
  family (:mod:`tools.jaxlint.rules.wire`);
- :mod:`tools.wirecheck.cli` is the ``python -m tools.wirecheck``
  driver: ``--check`` gates the tree against the committed
  ``SCHEMAS.lock.json``; ``--update`` is the sanctioned way to evolve
  the lock (additively) when a record kind legitimately grows.

Stdlib ``ast`` only — like jaxlint, it runs without jax installed.
"""

from tools.wirecheck.extract import (  # noqa: F401
    WireIndex,
    extract_index,
)
from tools.wirecheck.gates import (  # noqa: F401
    lock_diff,
    schemas_of,
)
