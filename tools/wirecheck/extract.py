"""Producer/consumer indexing over jaxlint's program model.

Every cross-process record kind gets one schema key:

=============  =====================  ====================================
kind           schema key             record surface
=============  =====================  ====================================
``ledger``     event name             ``FailureLedger.append`` /
                                      ``_append_ledger`` JSONL records
``log``        event name             ``log_event`` key=value lines
``annotation`` ``"ad"``               lease heartbeat sidecars
                                      (``lease.annotate`` payloads)
``response``   ``"body"``             serve HTTP response bodies
``request``    ``"payload"``          serve HTTP request bodies
``slo``        ``"snapshot"``         flight bundle ``slo.json``
``numerics``   ``"record"``           flight bundle ``numerics.jsonl``
=============  =====================  ====================================

Producers are *literal* writes: dict-literal keys at the emission call
site, keyword args of ledger appends, ``dict(ad, alive=...)``
enrichment keywords, ``rec["field"] = ...`` stamp stores. Consumers are
*literal* reads — ``v.get("field")``, ``v["field"]``, ``"field" in v``
— attributed to a kind (and, for ledger records, an event) only when
the variable's provenance is statically clear: the loop/comprehension
variable of an ``r.get("event") == "name"`` filter, a parameter named
``ad``/``payload`` in a serve module, an ``X.body`` attribute read.
Anything dynamic is skipped: under-attribution weakens coverage, never
invents a finding.

Test files (``tests/``) contribute producers (a drill or test that
posts a request documents the wire format as much as a client does)
but never consumers — tests read fields of records they fabricate,
which would alias fixture shapes into the real schemas.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Iterator, Optional

from tools.jaxlint.model import dotted
from tools.jaxlint.program import FileUnit, Program

#: Fields the framework stamps onto every ledger/log record at the
#: emission primitive (``FailureLedger.append`` / ``log_event`` add
#: ``t`` and the active RunContext identity) — produced for every
#: event without appearing at any call site.
LEDGER_AUTO_FIELDS = ("event", "t", "run_id", "span_id", "parent_id")
LOG_AUTO_FIELDS = ("event", "run_id", "span_id", "parent_id")

#: Call leaves that emit an event-keyed record; the event name is the
#: first positional arg except for ``log_event(logger, event, ...)``.
_LEDGER_EMITTERS = {"append", "_append_ledger"}
_LOG_EMITTERS = {"log_event"}

#: Call leaves whose first dict-literal argument is a serve request
#: body (client builders, the drill, the router's forward leg).
_REQUEST_BUILDERS = {"simulate", "sweep", "table", "whatif", "_post"}

#: Client methods that collect ``**kwargs`` into the payload — every
#: keyword at every call site is a produced payload field. ``whatif``
#: nests its positional spec under the ``"whatif"`` key, so positional
#: dicts are NOT top-level fields for these.
_KWARG_BUILDERS = {"simulate", "sweep", "table", "whatif"}

#: Entry points taking the payload dict itself as a positional arg:
#: ``client._post(path, {...})``, the service facade's
#: ``handle(kind, {...})``, and the admission layer's
#: ``admit({...}, request_id=...)`` (whose keywords are function
#: params, never payload fields).
_DICT_BUILDERS = {"_post", "admit", "handle"}

#: Call leaves whose dict-literal args are serve response bodies.
_RESPONSE_SINKS = {"_send_json", "send_json", "resolve"}


@dataclasses.dataclass(frozen=True)
class Site:
    """One producer or consumer occurrence."""

    unit: FileUnit
    line: int
    #: producer-only: a framework stamp (``annotate`` setdefaults,
    #: run_id restamps) rather than a caller-advertised field — stamps
    #: satisfy orphan reads but are exempt from dead-weight checks.
    stamp: bool = False

    @property
    def path(self) -> str:
        return self.unit.path


class WireIndex:
    """producers/consumers: ``(kind, key) -> field -> [Site, ...]``."""

    def __init__(self) -> None:
        self.producers: dict[tuple[str, str], dict[str, list[Site]]] = {}
        self.consumers: dict[tuple[str, str], dict[str, list[Site]]] = {}

    def produce(
        self,
        kind: str,
        key: str,
        field: str,
        unit: FileUnit,
        line: int,
        *,
        stamp: bool = False,
    ) -> None:
        self.producers.setdefault((kind, key), {}).setdefault(
            field, []
        ).append(Site(unit, line, stamp))

    def consume(
        self, kind: str, key: str, field: str, unit: FileUnit, line: int
    ) -> None:
        self.consumers.setdefault((kind, key), {}).setdefault(
            field, []
        ).append(Site(unit, line))

    def produced_fields(self, kind: str, key: str) -> set[str]:
        return set(self.producers.get((kind, key), ()))


# -- small AST helpers ----------------------------------------------------


def _posix(unit: FileUnit) -> str:
    return Path(unit.path).as_posix()


def _is_test_unit(unit: FileUnit) -> bool:
    p = _posix(unit)
    return "tests/" in p or Path(p).name.startswith("test_")


def _is_serve_unit(unit: FileUnit) -> bool:
    return "/serve/" in _posix(unit) or "serve/" in _posix(unit)


def _call_leaf(call: ast.Call) -> str:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return ""


def _literal_names(arg: ast.expr) -> Optional[list[str]]:
    """A literal event name, or a trace-resolvable choice of two."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return [arg.value]
    if isinstance(arg, ast.IfExp):
        a = _literal_names(arg.body)
        b = _literal_names(arg.orelse)
        if a is not None and b is not None:
            return a + b
    return None


def _is_ledger_append(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute):
        return False
    recv = (dotted(call.func.value) or "").lower()
    return "ledger" in recv


def _is_lease_receiver(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute):
        return False
    recv = (dotted(call.func.value) or "").lower()
    return "lease" in recv


def _dict_literal_keys(node: ast.expr) -> list[tuple[str, int]]:
    """``(key, lineno)`` for every literal string key of a dict
    literal (non-literal keys and ``**spread``s are skipped)."""
    if not isinstance(node, ast.Dict):
        return []
    out = []
    for key in node.keys:
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            out.append((key.value, getattr(key, "lineno", node.lineno)))
    return out


def _read_of(node: ast.expr) -> Optional[tuple[ast.expr, str]]:
    """``(base, field)`` when ``node`` is a literal field read:
    ``base.get("f" [, default])`` or ``base["f"]``."""
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "get"
        and node.args
        and isinstance(node.args[0], ast.Constant)
        and isinstance(node.args[0].value, str)
    ):
        return node.func.value, node.args[0].value
    if (
        isinstance(node, ast.Subscript)
        and isinstance(node.ctx, ast.Load)
        and isinstance(node.slice, ast.Constant)
        and isinstance(node.slice.value, str)
    ):
        return node.value, node.slice.value
    return None


def _membership_read(node: ast.expr) -> Optional[tuple[ast.expr, str]]:
    """``(base, field)`` for ``"f" in base`` membership tests."""
    if (
        isinstance(node, ast.Compare)
        and len(node.ops) == 1
        and isinstance(node.ops[0], ast.In)
        and isinstance(node.left, ast.Constant)
        and isinstance(node.left.value, str)
    ):
        return node.comparators[0], node.left.value
    return None


def _reads_on_name(tree: ast.AST, names: set[str]) -> Iterator[tuple[str, int]]:
    """Every literal field read whose base is a bare Name in `names`."""
    for node in ast.walk(tree):
        hit = _read_of(node) or _membership_read(node)
        if hit is None:
            continue
        base, field = hit
        if isinstance(base, ast.Name) and base.id in names:
            yield field, getattr(node, "lineno", 0)


def _functions(tree: ast.Module) -> Iterator[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# -- event-scoped ledger-record dataflow ----------------------------------


def _event_of_conditions(
    conditions: list[ast.expr], var: str
) -> tuple[list[str], list[tuple[str, int]]]:
    """``(events, extra_reads)`` from a filter like
    ``r.get("event") == "unit_ok" and r.get("worker")``: the literal
    event name(s) the filter pins `var`'s records to, plus every other
    field read on `var` inside the same conditions."""
    events: list[str] = []
    leaves: list[ast.expr] = []
    stack = list(conditions)
    while stack:
        cond = stack.pop()
        if isinstance(cond, ast.BoolOp) and isinstance(cond.op, ast.And):
            stack.extend(cond.values)
        else:
            leaves.append(cond)
    for leaf in leaves:
        if (
            isinstance(leaf, ast.Compare)
            and len(leaf.ops) == 1
            and isinstance(leaf.ops[0], (ast.Eq, ast.In))
        ):
            read = _read_of(leaf.left)
            if (
                read is not None
                and read[1] == "event"
                and isinstance(read[0], ast.Name)
                and read[0].id == var
            ):
                comp = leaf.comparators[0]
                if isinstance(leaf.ops[0], ast.Eq):
                    names = _literal_names(comp)
                    if names:
                        events.extend(names)
                elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                    for el in comp.elts:
                        names = _literal_names(el)
                        if names:
                            events.extend(names)
    extra: list[tuple[str, int]] = []
    for cond in conditions:
        for field, line in _reads_on_name(cond, {var}):
            if field != "event":
                extra.append((field, line))
    return events, extra


class _LedgerConsumerScanner:
    """Per-function walk attributing field reads to ledger events.

    Tracks an environment of names statically known to hold records of
    one event: comprehension results filtered on ``.get("event")``,
    loop variables inside ``if r.get("event") == ...`` guards, and
    dicts filled from such variables (the ``last_ok[r["unit"]] = r``
    idiom). Reads on anything else are ignored.
    """

    def __init__(self, index: WireIndex, unit: FileUnit) -> None:
        self.index = index
        self.unit = unit
        self.env: dict[str, str] = {}

    def _emit(self, event: str, field: str, line: int) -> None:
        if field != "event":
            self.index.consume("ledger", event, field, self.unit, line)

    def _collect_var_reads(
        self, tree: ast.AST, var: str, event: str
    ) -> None:
        for field, line in _reads_on_name(tree, {var}):
            self._emit(event, field, line)

    def _scan_comprehension(self, node: ast.AST) -> None:
        if not isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
        ):
            return
        gens = node.generators
        if len(gens) != 1 or not isinstance(gens[0].target, ast.Name):
            return
        var = gens[0].target.id
        events, extra = _event_of_conditions(gens[0].ifs, var)
        if not events and isinstance(gens[0].iter, ast.Name):
            bound = self.env.get(gens[0].iter.id)
            if bound is not None:
                events = [bound]
        if not events:
            return
        elts: list[ast.AST] = []
        if isinstance(node, ast.DictComp):
            elts = [node.key, node.value]
        else:
            elts = [node.elt]
        for event in events:
            for field, line in extra:
                self._emit(event, field, line)
            for elt in elts:
                self._collect_var_reads(elt, var, event)

    def _bind_target(self, target: ast.expr, event: str) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = event

    def _comprehension_event(self, value: ast.expr) -> Optional[str]:
        """The single event a comprehension value is filtered to."""
        if not isinstance(
            value, (ast.ListComp, ast.SetComp, ast.GeneratorExp)
        ):
            return None
        gens = value.generators
        if len(gens) != 1 or not isinstance(gens[0].target, ast.Name):
            return None
        events, _ = _event_of_conditions(gens[0].ifs, gens[0].target.id)
        if len(events) == 1:
            return events[0]
        return None

    def _iter_event(self, iter_node: ast.expr) -> Optional[str]:
        """The event a ``for``-loop iterable is bound to: a bound name,
        or ``bound.values()`` / ``bound.items()``."""
        if isinstance(iter_node, ast.Name):
            return self.env.get(iter_node.id)
        if (
            isinstance(iter_node, ast.Call)
            and isinstance(iter_node.func, ast.Attribute)
            and iter_node.func.attr in ("values", "items")
            and isinstance(iter_node.func.value, ast.Name)
        ):
            return self.env.get(iter_node.func.value.id)
        return None

    def _scan_scoped_block(
        self, body: list[ast.stmt], var: str, event: str
    ) -> None:
        """Reads on `var` inside a block where it holds `event`
        records; ``D[...] = var`` stores bind D to the event too."""
        for stmt in body:
            for field, line in _reads_on_name(stmt, {var}):
                self._emit(event, field, line)
            for node in ast.walk(stmt):
                if (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == var
                ):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Subscript) and isinstance(
                            tgt.value, ast.Name
                        ):
                            self.env[tgt.value.id] = event

    def scan(self, func: ast.FunctionDef) -> None:
        for stmt in ast.walk(func):
            if isinstance(stmt, ast.Assign):
                event = self._comprehension_event(stmt.value)
                if event is not None:
                    for tgt in stmt.targets:
                        self._bind_target(tgt, event)
                elif isinstance(stmt.value, ast.Name):
                    bound = self.env.get(stmt.value.id)
                    if bound is not None:
                        for tgt in stmt.targets:
                            self._bind_target(tgt, bound)
            elif isinstance(stmt, ast.For) and isinstance(
                stmt.target, ast.Name
            ):
                var = stmt.target.id
                event = self._iter_event(stmt.iter)
                if event is not None:
                    self._scan_scoped_block(stmt.body, var, event)
                else:
                    # `for r in records: if r.get("event") == ...:`
                    for inner in ast.walk(stmt):
                        if not isinstance(inner, ast.If):
                            continue
                        events, extra = _event_of_conditions(
                            [inner.test], var
                        )
                        for ev in events:
                            for field, line in extra:
                                self._emit(ev, field, line)
                            self._scan_scoped_block(inner.body, var, ev)
        for node in ast.walk(func):
            self._scan_comprehension(node)


# -- per-kind extraction passes -------------------------------------------


def _extract_event_producers(unit: FileUnit, index: WireIndex) -> None:
    for call in ast.walk(unit.tree):
        if not isinstance(call, ast.Call):
            continue
        leaf = _call_leaf(call)
        if leaf in _LEDGER_EMITTERS:
            if leaf == "append" and not _is_ledger_append(call):
                continue
            kind, name_idx, auto = "ledger", 0, LEDGER_AUTO_FIELDS
        elif leaf in _LOG_EMITTERS:
            kind, name_idx, auto = "log", 1, LOG_AUTO_FIELDS
        else:
            continue
        if len(call.args) <= name_idx:
            continue
        events = _literal_names(call.args[name_idx])
        if not events:
            continue
        fields = [kw.arg for kw in call.keywords if kw.arg is not None]
        for event in events:
            for field in fields:
                index.produce(kind, event, field, unit, call.lineno)
            for field in auto:
                index.produce(
                    kind, event, field, unit, call.lineno, stamp=True
                )


def _returned_dict_keys(func: ast.FunctionDef) -> list[tuple[str, int]]:
    out: list[tuple[str, int]] = []
    for node in ast.walk(func):
        if isinstance(node, ast.Return) and node.value is not None:
            out.extend(_dict_literal_keys(node.value))
    return out


def _extract_annotation(
    program: Program, unit: FileUnit, index: WireIndex
) -> None:
    """Producers: ``lease.annotate(slot, payload)`` payload keys — a
    dict literal in place, or the returned dict literal of the resolved
    payload-builder call (``self.advertisement()``); the annotate
    primitive's own ``setdefault`` stamps; ``dict(ad, alive=...)``
    enrichment of a read-back ad. Consumers: field reads on ``ad``
    variables in serve modules."""
    enclosing_cls: dict[int, Optional[str]] = {}

    def walk_cls(node: ast.AST, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk_cls(child, child.name)
            else:
                enclosing_cls[id(child)] = cls
                walk_cls(child, cls)

    walk_cls(unit.tree, None)

    def cls_of(call: ast.Call) -> Optional[str]:
        node: ast.AST = call
        return enclosing_cls.get(id(node))

    for call in ast.walk(unit.tree):
        if not isinstance(call, ast.Call):
            continue
        if _call_leaf(call) != "annotate" or not _is_lease_receiver(call):
            continue
        if len(call.args) < 2:
            continue
        payload = call.args[1]
        for field, line in _dict_literal_keys(payload):
            index.produce("annotation", "ad", field, unit, line)
        if isinstance(payload, ast.Call):
            builder = program.resolve_call(unit, payload, cls_of(call))
            if builder is not None:
                for field, line in _returned_dict_keys(builder.node):
                    index.produce(
                        "annotation", "ad", field, builder.unit, line
                    )
        # the annotate primitive's own identity stamps
        target = program.resolve_call(unit, call, cls_of(call))
        if target is not None:
            for node in ast.walk(target.node):
                if (
                    isinstance(node, ast.Call)
                    and _call_leaf(node) == "setdefault"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    index.produce(
                        "annotation",
                        "ad",
                        node.args[0].value,
                        target.unit,
                        node.lineno,
                        stamp=True,
                    )

    if not _is_serve_unit(unit):
        return
    for func in _functions(unit.tree):
        ad_names = {"ad"}
        for node in ast.walk(func):
            # names bound from read_annotation() are ads too
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _call_leaf(node.value) == "read_annotation"
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        ad_names.add(tgt.id)
            # loop vars over an `ads` collection
            if (
                isinstance(node, (ast.For, ast.comprehension))
                and isinstance(node.target, ast.Name)
                and isinstance(node.iter, ast.Name)
                and node.iter.id == "ads"
            ):
                ad_names.add(node.target.id)
        for field, line in _reads_on_name(func, ad_names):
            index.consume("annotation", "ad", field, unit, line)
        for node in ast.walk(func):
            # dict(ad, alive=..., slot=...) enrichment produces fields
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "dict"
                and node.args
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in ad_names
            ):
                for kw in node.keywords:
                    if kw.arg is not None:
                        index.produce(
                            "annotation", "ad", kw.arg, unit, node.lineno
                        )


def _dict_splat_calls(node: ast.expr) -> list[str]:
    """Leaf names of ``**call()`` entries in a dict literal — the
    ``{"status": "ok", **self.replay.timeline_info(n)}`` idiom where
    most of the body comes from a backend builder."""
    if not isinstance(node, ast.Dict):
        return []
    out = []
    for key, value in zip(node.keys, node.values):
        if key is None and isinstance(value, ast.Call):
            leaf = _call_leaf(value)
            if leaf:
                out.append(leaf)
    return out


def _extract_response(
    unit: FileUnit,
    index: WireIndex,
    funcs_by_name: dict,
) -> None:
    def produce_builder(leaf: str, seen: set) -> None:
        """Merge the returned dict-literal keys of every program
        function with this bare name (duck-typed backend builders like
        ``timeline_info``/``healthz`` — ``self.X.method`` receivers
        defeat exact call resolution, so name lookup is the contract)."""
        if leaf in seen:
            return
        seen.add(leaf)
        for builder_unit, func in funcs_by_name.get(leaf, ()):
            for field, line in _returned_dict_keys(func):
                index.produce("response", "body", field, builder_unit, line)
            for node in ast.walk(func):
                if isinstance(node, ast.Return) and node.value is not None:
                    for inner in _dict_splat_calls(node.value):
                        produce_builder(inner, seen)

    def produce_body(value: ast.expr) -> None:
        for field, line in _dict_literal_keys(value):
            index.produce("response", "body", field, unit, line)
        for leaf in _dict_splat_calls(value):
            produce_builder(leaf, set())
        if isinstance(value, ast.Call):
            leaf = _call_leaf(value)
            if leaf:
                produce_builder(leaf, set())

    if _is_serve_unit(unit):
        for node in ast.walk(unit.tree):
            if isinstance(node, ast.Call):
                if _call_leaf(node) not in _RESPONSE_SINKS:
                    continue
                for arg in node.args:
                    produce_body(arg)
            elif isinstance(node, ast.Return) and node.value is not None:
                values = [node.value]
                if isinstance(node.value, ast.Tuple):
                    values = list(node.value.elts)
                for value in values:
                    produce_body(value)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                # `body = {...}` assembly (coalescer lane slicing, the
                # service's _execute branches) and `body["k"] = v`
                # enrichment on the same name
                tgt = node.targets[0]
                if (
                    isinstance(tgt, ast.Name)
                    and tgt.id == "body"
                    and isinstance(node.value, ast.Dict)
                ):
                    produce_body(node.value)
                elif (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "body"
                    and isinstance(tgt.slice, ast.Constant)
                    and isinstance(tgt.slice.value, str)
                ):
                    index.produce(
                        "response",
                        "body",
                        tgt.slice.value,
                        unit,
                        node.lineno,
                    )
    if _is_test_unit(unit):
        return
    for node in ast.walk(unit.tree):
        hit = _read_of(node) or _membership_read(node)
        if hit is None:
            continue
        base, field = hit
        if isinstance(base, ast.Attribute) and base.attr == "body":
            index.consume(
                "response", "body", field, unit, getattr(node, "lineno", 0)
            )


def _extract_request(unit: FileUnit, index: WireIndex) -> None:
    for node in ast.walk(unit.tree):
        if not isinstance(node, ast.Call):
            continue
        leaf = _call_leaf(node)
        if leaf in _KWARG_BUILDERS and isinstance(
            node.func, ast.Attribute
        ):
            # client.simulate(case=..., deadline_seconds=...) collects
            # **kwargs into the payload dict — every keyword at every
            # call site is a produced payload field
            for kw in node.keywords:
                if kw.arg is not None:
                    index.produce(
                        "request", "payload", kw.arg, unit, node.lineno
                    )
        if leaf in _DICT_BUILDERS:
            # _post("/path", {...}) / admit({...}, request_id=...) /
            # handle(kind, {...}): the positional dict IS the payload
            for arg in node.args:
                for field, line in _dict_literal_keys(arg):
                    index.produce("request", "payload", field, unit, line)
    # one-hop dataflow: dict literals bound to a name that later feeds
    # a payload entry point — `payload = {...}; svc.handle(k, payload)`
    # and the test corpus's `for payload in ({...}, {...}): handle(...)`
    for func in _functions(unit.tree):
        payload_names: set[str] = set()
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            if _call_leaf(node) not in _DICT_BUILDERS:
                continue
            for arg in node.args:
                # handle(kind, dict(payload)) defensive-copy unwrap
                if (
                    isinstance(arg, ast.Call)
                    and isinstance(arg.func, ast.Name)
                    and arg.func.id == "dict"
                    and arg.args
                ):
                    arg = arg.args[0]
                if isinstance(arg, ast.Name):
                    payload_names.add(arg.id)
        if not payload_names:
            continue
        for node in ast.walk(func):
            values: list[ast.expr] = []
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id in payload_names
            ):
                values = [node.value]
            elif (
                isinstance(node, (ast.For, ast.comprehension))
                and isinstance(node.target, ast.Name)
                and node.target.id in payload_names
                and isinstance(node.iter, (ast.Tuple, ast.List))
            ):
                values = list(node.iter.elts)
            for value in values:
                for field, line in _dict_literal_keys(value):
                    index.produce("request", "payload", field, unit, line)
    if not _is_serve_unit(unit) or _is_test_unit(unit):
        return
    for node in ast.walk(unit.tree):
        # payload.setdefault("tenant", ...) / payload["k"] = ... stamps
        if (
            isinstance(node, ast.Call)
            and _call_leaf(node) == "setdefault"
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "payload"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            index.produce(
                "request",
                "payload",
                node.args[0].value,
                unit,
                node.lineno,
                stamp=True,
            )
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Subscript)
            and isinstance(node.targets[0].value, ast.Name)
            and node.targets[0].value.id == "payload"
            and isinstance(node.targets[0].slice, ast.Constant)
            and isinstance(node.targets[0].slice.value, str)
        ):
            index.produce(
                "request",
                "payload",
                node.targets[0].slice.value,
                unit,
                node.lineno,
                stamp=True,
            )
    for func in _functions(unit.tree):
        for field, line in _reads_on_name(func, {"payload"}):
            index.consume("request", "payload", field, unit, line)


def _extract_slo(unit: FileUnit, index: WireIndex) -> None:
    posix = _posix(unit)
    if "slo" in Path(posix).name and not _is_test_unit(unit):
        for func in _functions(unit.tree):
            if func.name == "snapshot":
                for field, line in _returned_dict_keys(func):
                    index.produce("slo", "snapshot", field, unit, line)
    for func in _functions(unit.tree):
        if func.name == "record_slo":
            for node in ast.walk(func):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)
                    and isinstance(node.targets[0].slice, ast.Constant)
                    and isinstance(node.targets[0].slice.value, str)
                ):
                    index.produce(
                        "slo",
                        "snapshot",
                        node.targets[0].slice.value,
                        unit,
                        node.lineno,
                        stamp=True,
                    )
    if "sloreport" in posix:
        for func in _functions(unit.tree):
            for field, line in _reads_on_name(
                func, {"snapshot", "snap"}
            ):
                index.consume("slo", "snapshot", field, unit, line)


def _extract_numerics(unit: FileUnit, index: WireIndex) -> None:
    posix = _posix(unit)
    for func in _functions(unit.tree):
        if func.name == "sketch_records":
            for node in ast.walk(func):
                for field, line in _dict_literal_keys(node):
                    index.produce("numerics", "record", field, unit, line)
        if func.name in ("record_numerics", "append_numerics"):
            for node in ast.walk(func):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)
                    and isinstance(node.targets[0].slice, ast.Constant)
                    and isinstance(node.targets[0].slice.value, str)
                ):
                    index.produce(
                        "numerics",
                        "record",
                        node.targets[0].slice.value,
                        unit,
                        node.lineno,
                        stamp=True,
                    )
        # `for rec in sketch_records(...): rec["expected"] = ...`
        # (the supervisor's accepted-drift stamp on canary records)
        sketch_bound: set[str] = set()
        for node in ast.walk(func):
            src = None
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                src = node.value
                targets = [
                    t.id for t in node.targets if isinstance(t, ast.Name)
                ]
            elif isinstance(node, (ast.For, ast.comprehension)):
                src = node.iter if isinstance(node.iter, ast.Call) else None
                targets = (
                    [node.target.id]
                    if isinstance(node.target, ast.Name)
                    else []
                )
                if (
                    isinstance(node.iter, ast.Name)
                    and node.iter.id in sketch_bound
                ):
                    sketch_bound.update(targets)
                    continue
            else:
                continue
            if (
                src is not None
                and isinstance(src, ast.Call)
                and _call_leaf(src) == "sketch_records"
            ):
                sketch_bound.update(targets)
        if sketch_bound:
            for node in ast.walk(func):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id in sketch_bound
                    and isinstance(node.targets[0].slice, ast.Constant)
                    and isinstance(node.targets[0].slice.value, str)
                ):
                    index.produce(
                        "numerics",
                        "record",
                        node.targets[0].slice.value,
                        unit,
                        node.lineno,
                        stamp=True,
                    )
    consumer_names = None
    if "driftreport" in posix:
        consumer_names = {"rec", "record", "r", "primary", "canary"}
    elif posix.endswith("telemetry/numerics.py"):
        consumer_names = {"rec", "primary", "canary"}
    if consumer_names:
        for func in _functions(unit.tree):
            for field, line in _reads_on_name(func, consumer_names):
                index.consume("numerics", "record", field, unit, line)


def extract_index(program: Program) -> WireIndex:
    """The whole program's producer/consumer index."""
    index = WireIndex()
    # bare-name function lookup for response-builder resolution
    # (``{**self.replay.timeline_info(n)}`` — dotted receivers defeat
    # exact resolution, the method name is the duck-typed contract)
    funcs_by_name: dict[str, list] = {}
    for info in program.functions.values():
        if _is_test_unit(info.unit):
            continue
        funcs_by_name.setdefault(info.node.name, []).append(
            (info.unit, info.node)
        )
    for unit in program.units:
        if unit.tree is None:
            continue
        _extract_event_producers(unit, index)
        _extract_annotation(program, unit, index)
        _extract_response(unit, index, funcs_by_name)
        _extract_request(unit, index)
        _extract_slo(unit, index)
        _extract_numerics(unit, index)
        if not _is_test_unit(unit):
            for func in _functions(unit.tree):
                _LedgerConsumerScanner(index, unit).scan(func)
    return index
