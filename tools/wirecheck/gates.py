"""The four wire-contract gates over a :class:`~.extract.WireIndex`.

Each gate emits findings through a callback ``add(unit, line, code,
message)`` so the same logic backs both the jaxlint JX3xx rule family
(per-line suppressible, ``--strict``-swept) and the ``wirecheck`` CLI.
Every finding names the other side of the contract — the producer
chain for an orphan read, the reachability chain for an unmapped typed
error — because a wire-contract failure is never local to the line it
anchors on.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Callable, Optional

from tools.jaxlint.model import dotted
from tools.jaxlint.program import FileUnit, FuncInfo, Program

from tools.wirecheck.extract import (
    Site,
    WireIndex,
    _call_leaf,
    _is_serve_unit,
)

AddFn = Callable[[FileUnit, int, str, str], None]

#: kinds whose producer schemas are frozen into SCHEMAS.lock.json.
LOCKED_KINDS = (
    "ledger",
    "log",
    "annotation",
    "response",
    "request",
    "slo",
    "numerics",
)


def schemas_of(index: WireIndex) -> dict:
    """``{kind: {key: sorted field list}}`` of every produced record."""
    out: dict[str, dict[str, list[str]]] = {}
    for (kind, key), fields in index.producers.items():
        out.setdefault(kind, {})[key] = sorted(fields)
    return {k: dict(sorted(v.items())) for k, v in sorted(out.items())}


def _producer_chain(index: WireIndex, kind: str, key: str) -> str:
    """Human-readable producer chain for a finding message."""
    sites: dict[str, Site] = {}
    fields_at: dict[str, list[str]] = {}
    for field, occurrences in index.producers.get((kind, key), {}).items():
        for site in occurrences:
            where = f"{Path(site.path).as_posix()}:{site.line}"
            sites.setdefault(where, site)
            fields_at.setdefault(where, []).append(field)
    parts = []
    for where in sorted(sites)[:3]:
        shown = sorted(set(fields_at[where]))
        listed = ", ".join(shown[:8])
        if len(shown) > 8:
            listed += ", ..."
        parts.append(f"{where} (fields: {listed})")
    more = max(0, len(sites) - 3)
    chain = "; ".join(parts)
    if more:
        chain += f"; and {more} more site(s)"
    return chain


# -- gate 1: no orphan reads (JX301) --------------------------------------


def gate_orphan_reads(index: WireIndex, add: AddFn) -> None:
    """A field consumed anywhere must have at least one producer.

    Judged per schema key, and only for keys that HAVE producers in the
    analyzed program — a partial run (one root) that sees consumers but
    no producers cannot distinguish drift from its own blind spot, so
    it stays silent rather than guessing."""
    for (kind, key), fields in sorted(index.consumers.items()):
        if kind == "annotation":
            continue  # both directions owned by JX303 (lease closure)
        produced = index.produced_fields(kind, key)
        if kind in ("ledger", "log"):
            # one event stream: log_event and ledger appends share the
            # event namespace, and report tools read the merged view
            produced = index.produced_fields(
                "ledger", key
            ) | index.produced_fields("log", key)
        if not produced:
            continue
        chain = _producer_chain(index, kind, key)
        for field, sites in sorted(fields.items()):
            if field in produced:
                continue
            seen: set[tuple[str, int]] = set()
            for site in sites:
                anchor = (site.path, site.line)
                if anchor in seen:
                    continue
                seen.add(anchor)
                add(
                    site.unit,
                    site.line,
                    "JX301",
                    f"orphan read: field '{field}' of {kind} record "
                    f"'{key}' is consumed here but no producer ever "
                    f"writes it — producers of '{key}': {chain}",
                )


# -- gate 2: typed-error totality (JX302) ---------------------------------


class _ClassTable:
    """Leaf-name class hierarchy across the program."""

    def __init__(self, program: Program) -> None:
        self.bases: dict[str, set[str]] = {}
        self.defined_at: dict[str, tuple[FileUnit, int]] = {}
        for unit in program.units:
            if unit.tree is None:
                continue
            for node in ast.walk(unit.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                base_leaves = set()
                for base in node.bases:
                    d = dotted(base)
                    if d:
                        base_leaves.add(d.rsplit(".", 1)[-1])
                self.bases.setdefault(node.name, set()).update(base_leaves)
                self.defined_at.setdefault(node.name, (unit, node.lineno))

    def ancestry(self, name: str) -> set[str]:
        out: set[str] = set()
        work = [name]
        while work:
            cur = work.pop()
            if cur in out:
                continue
            out.add(cur)
            work.extend(self.bases.get(cur, ()))
        return out


def _raise_leaf(node: ast.Raise) -> Optional[str]:
    exc = node.exc
    if isinstance(exc, ast.Call):
        exc = exc.func
    d = dotted(exc) if exc is not None else None
    if d:
        return d.rsplit(".", 1)[-1]
    return None


def _handler_leaves(program: Program) -> set[str]:
    """Every class leaf named by a typed ``except`` clause in a serve
    module, with module-level exception tuples (the
    ``_FORWARD_FAILURES`` idiom) resolved."""
    tuples: dict[str, set[str]] = {}
    for unit in program.units:
        if unit.tree is None or not _is_serve_unit(unit):
            continue
        for node in unit.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, (ast.Tuple, ast.List))
            ):
                leaves = set()
                for el in node.value.elts:
                    d = dotted(el)
                    if d:
                        leaves.add(d.rsplit(".", 1)[-1])
                tuples[node.targets[0].id] = leaves
    handled: set[str] = set()
    for unit in program.units:
        if unit.tree is None or not _is_serve_unit(unit):
            continue
        for node in ast.walk(unit.tree):
            if not isinstance(node, ast.ExceptHandler) or node.type is None:
                continue
            types = (
                list(node.type.elts)
                if isinstance(node.type, (ast.Tuple, ast.List))
                else [node.type]
            )
            for t in types:
                d = dotted(t)
                if d is None:
                    continue
                leaf = d.rsplit(".", 1)[-1]
                if isinstance(t, ast.Name) and t.id in tuples:
                    handled.update(tuples[t.id])
                else:
                    handled.add(leaf)
    return handled


def _classify_decisions(
    program: Program,
) -> Optional[tuple[set[str], set[str]]]:
    """``(isinstance_roots, constructed)`` of ``classify_failure``, or
    None when the program carries no classifier (fixture runs skip the
    retryability half)."""
    for unit in program.units:
        if unit.tree is None:
            continue
        for node in ast.walk(unit.tree):
            if (
                isinstance(node, ast.FunctionDef)
                and node.name == "classify_failure"
            ):
                roots: set[str] = set()
                constructed: set[str] = set()
                for inner in ast.walk(node):
                    if not isinstance(inner, ast.Call):
                        continue
                    leaf = _call_leaf(inner)
                    if leaf == "isinstance" and len(inner.args) == 2:
                        types = (
                            list(inner.args[1].elts)
                            if isinstance(
                                inner.args[1], (ast.Tuple, ast.List)
                            )
                            else [inner.args[1]]
                        )
                        for t in types:
                            d = dotted(t)
                            if d:
                                roots.add(d.rsplit(".", 1)[-1])
                    elif leaf and leaf[0].isupper():
                        constructed.add(leaf)
                return roots, constructed
    return None


def _serve_bridges_classifier(program: Program) -> bool:
    """True when some serve module routes caught exceptions through the
    shared classifier path (``classify_failure`` /
    ``_failure_response``) — the design where one broad handler plus
    the taxonomy IS the HTTP mapping for the whole hierarchy."""
    for unit in program.units:
        if unit.tree is None or not _is_serve_unit(unit):
            continue
        for node in ast.walk(unit.tree):
            if isinstance(node, ast.ExceptHandler):
                for inner in ast.walk(node):
                    if isinstance(inner, ast.Call) and _call_leaf(inner) in (
                        "classify_failure",
                        "_failure_response",
                    ):
                        return True
    return False


def gate_typed_errors(program: Program, add: AddFn) -> None:
    """Every ``ResilienceError`` subclass raised in a serve-reachable
    function must map to an HTTP status (a typed serve ``except``
    naming it or an ancestor, or the shared ``classify_failure`` →
    ``_failure_response`` bridge) and to a retryability class in
    ``classify_failure``."""
    table = _ClassTable(program)
    if "ResilienceError" not in table.bases:
        return
    serve_funcs = [
        f
        for f in program.functions.values()
        if _is_serve_unit(f.unit)
    ]
    if not serve_funcs:
        return
    resilience = {
        name
        for name in table.bases
        if "ResilienceError" in table.ancestry(name)
    }

    # serve-reachable closure with one witness chain per function
    chains: dict[str, list[str]] = {}
    work: list[FuncInfo] = []
    for f in serve_funcs:
        if f.qualname not in chains:
            chains[f.qualname] = [f.qualname]
            work.append(f)
    while work:
        caller = work.pop()
        for node in ast.walk(caller.node):
            if not isinstance(node, ast.Call):
                continue
            callee = program.resolve_call(caller.unit, node, caller.cls)
            if callee is None or callee.qualname in chains:
                continue
            chains[callee.qualname] = chains[caller.qualname] + [
                callee.qualname
            ]
            work.append(callee)

    handled = _handler_leaves(program)
    bridge = _serve_bridges_classifier(program)
    decisions = _classify_decisions(program)
    reported: set[tuple[str, str, int]] = set()

    for qualname, chain in sorted(chains.items()):
        info = program.functions.get(qualname)
        if info is None:
            continue
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Raise):
                continue
            leaf = _raise_leaf(node)
            if leaf is None or leaf not in resilience:
                continue
            ancestry = table.ancestry(leaf)
            http_mapped = bool(ancestry & handled) or bridge
            classified = True
            if decisions is not None:
                roots, constructed = decisions
                classified = bool(ancestry & roots) or leaf in constructed
            key = (leaf, info.unit.path, node.lineno)
            if key in reported:
                continue
            via = " -> ".join(chain)
            if not http_mapped:
                reported.add(key)
                add(
                    info.unit,
                    node.lineno,
                    "JX302",
                    f"typed error '{leaf}' raised here is reachable "
                    f"from the serve tier (via {via}) but no serve "
                    "module maps it to an HTTP status: add a typed "
                    "except handler (or route the path through "
                    "classify_failure/_failure_response)",
                )
            elif not classified:
                reported.add(key)
                add(
                    info.unit,
                    node.lineno,
                    "JX302",
                    f"typed error '{leaf}' raised here is reachable "
                    f"from the serve tier (via {via}) but "
                    "classify_failure never assigns it a retryability "
                    "class: derive it from a classified root "
                    "(EngineFailure/ResilienceError) or teach the "
                    "classifier about it",
                )


# -- gate 3: lease-annotation closure (JX303) -----------------------------


def gate_lease_closure(index: WireIndex, add: AddFn) -> None:
    """Every annotation field the router scores must be advertised by
    the worker heartbeat writer, and every advertised field must be
    read by some placement/autoscaler consumer — a one-sided field is
    either a placement decision reading garbage or dead wire weight."""
    produced = index.producers.get(("annotation", "ad"), {})
    consumed = index.consumers.get(("annotation", "ad"), {})
    if not produced or not consumed:
        return
    producer_chain = _producer_chain(index, "annotation", "ad")
    for field, sites in sorted(consumed.items()):
        if field in produced:
            continue
        seen: set[tuple[str, int]] = set()
        for site in sites:
            anchor = (site.path, site.line)
            if anchor in seen:
                continue
            seen.add(anchor)
            add(
                site.unit,
                site.line,
                "JX303",
                f"claim scoring reads annotation field '{field}' that "
                "no worker heartbeat ever advertises — the score is "
                f"computed from a hole; advertised at: {producer_chain}",
            )
    consumer_sites = "; ".join(
        sorted(
            {
                f"{Path(s.path).as_posix()}:{s.line}"
                for sites in consumed.values()
                for s in sites
            }
        )[:3]
    )
    for field, sites in sorted(produced.items()):
        if field in consumed:
            continue
        if all(site.stamp for site in sites):
            continue  # framework identity stamps, not advertised hints
        seen = set()
        for site in sites:
            anchor = (site.path, site.line)
            if anchor in seen or site.stamp:
                continue
            seen.add(anchor)
            add(
                site.unit,
                site.line,
                "JX303",
                f"annotation field '{field}' is advertised in every "
                "heartbeat but no placement consumer ever reads it — "
                "dead wire weight; consumers read at: "
                f"{consumer_sites}",
            )


# -- gate 4: additive-only lock evolution (JX304) -------------------------


def lock_diff(current: dict, locked: dict) -> list[tuple[str, str, str]]:
    """``(kind, key, message)`` for every locked schema element the
    current tree no longer produces. Additions are fine (additive
    evolution is the contract); removals and renames are findings."""
    problems: list[tuple[str, str, str]] = []
    for kind, keys in sorted(locked.items()):
        current_keys = current.get(kind, {})
        for key, fields in sorted(keys.items()):
            if key not in current_keys:
                problems.append(
                    (
                        kind,
                        key,
                        f"locked {kind} record '{key}' is no longer "
                        "produced anywhere: old readers that consume "
                        "it would silently see nothing — restore the "
                        "producer, or regenerate the lock with "
                        "`python -m tools.wirecheck --update` if the "
                        "removal is deliberate",
                    )
                )
                continue
            missing = sorted(set(fields) - set(current_keys[key]))
            for field in missing:
                problems.append(
                    (
                        kind,
                        key,
                        f"locked field '{field}' of {kind} record "
                        f"'{key}' is no longer produced: removing or "
                        "renaming a locked field breaks old readers — "
                        "restore it, or regenerate the lock with "
                        "`python -m tools.wirecheck --update` if the "
                        "removal is deliberate",
                    )
                )
    return problems


def gate_lock(
    index: WireIndex,
    locked_schemas: dict,
    program: Program,
    add: AddFn,
) -> None:
    """JX304: anchor each lock regression on the record's first
    surviving producer site (or the program's first unit when the
    whole record vanished)."""
    current = schemas_of(index)
    fallback: Optional[FileUnit] = None
    for unit in sorted(program.units, key=lambda u: u.path):
        if unit.tree is not None:
            fallback = unit
            break
    for kind, key, message in lock_diff(current, locked_schemas):
        anchor_unit, anchor_line = fallback, 1
        sites = [
            site
            for fields in index.producers.get((kind, key), {}).values()
            for site in fields
        ]
        if sites:
            best = min(sites, key=lambda s: (s.path, s.line))
            anchor_unit, anchor_line = best.unit, best.line
        if anchor_unit is None:
            continue
        add(anchor_unit, anchor_line, "JX304", message)


def run_gates(
    program: Program,
    index: WireIndex,
    add: AddFn,
    *,
    locked_schemas: Optional[dict] = None,
) -> None:
    """All four gates; the lock gate only when a lock is supplied."""
    gate_orphan_reads(index, add)
    gate_typed_errors(program, add)
    gate_lease_closure(index, add)
    if locked_schemas is not None:
        gate_lock(index, locked_schemas, program, add)
