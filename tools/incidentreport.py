"""incidentreport: render and gate a flight bundle's incident record.

The postmortem half of the observability CLI family: obsreport renders
what happened, sloreport whether it was acceptable, driftreport whether
the numbers drifted — this renders WHY. For each correlated incident it
prints the suspected cause (a typed fault ledger event), the symptom
timeline (detector ``anomaly_detected`` records, SLO burn transitions),
the blast radius, and the resolution state.

Record of truth: the bundle's durable ``incidents.jsonl`` (appended by
the runtime :class:`yuma_simulation_tpu.telemetry.incident.IncidentEngine`
on every state transition, last record per id wins). Bundles without
one — drill bundles, old bundles — fall back to offline correlation
over the ledger, which derives the same incidents from the same typed
events.

Usage::

    python -m tools.incidentreport BUNDLE_DIR                # postmortems
    python -m tools.incidentreport BUNDLE_DIR --check        # CI gate
    python -m tools.incidentreport BUNDLE_DIR --expect-none  # control arm
    python -m tools.incidentreport BUNDLE_DIR --json         # machine-readable

``--check`` semantics (exit 1): every cause-class ledger event must
belong to an incident, and every incident must carry a cause candidate.
The first clause is the tamper bound — deleting an incident from
``incidents.jsonl`` orphans its cause event in the ledger, so a faulted
drill passes ONLY because correlation actually succeeded. Exit 2 means
the incident record itself is malformed (undecodable state, missing
identity). ``--expect-none`` (exit 1 on ANY incident) pins the
unfaulted control arms to zero.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from yuma_simulation_tpu.telemetry.flight import load_bundle
from yuma_simulation_tpu.telemetry.incident import (
    CAUSE_EVENTS,
    correlate,
    latest_incidents,
    unattributed_symptoms,
)

#: The process-loss cause a restarted controller ledgers after finding
#: a stale open run marker ("controller_restarted").
RESTART_EVENT = "controller_restarted"

_VALID_STATES = ("open", "resolved")


def _incident_records(bundle) -> tuple[list, bool]:
    """(current incident records, durable?) — ``incidents.jsonl`` folded
    last-record-per-id when the sink exists, else offline correlation
    over the ledger."""
    if bundle.incidents:
        return latest_incidents(bundle.incidents), True
    return [i.to_json() for i in correlate(bundle.ledger)], False


def check_incidents(bundle, records: list) -> tuple[list, list]:
    """(problems -> exit 1, malformed -> exit 2) for one bundle."""
    problems: list[str] = []
    malformed: list[str] = []
    known = set()
    for rec in records:
        if not isinstance(rec, dict) or not rec.get("incident"):
            malformed.append(f"incident record without identity: {rec!r:.120}")
            continue
        ident = str(rec["incident"])
        known.add(ident)
        if rec.get("state") not in _VALID_STATES:
            malformed.append(
                f"{ident}: undecodable state {rec.get('state')!r}"
            )
        cause = rec.get("cause")
        cause_event = (
            cause.get("event") if isinstance(cause, dict) else None
        )
        if cause_event not in CAUSE_EVENTS:
            problems.append(
                f"{ident}: no cause candidate "
                f"(cause event {cause_event!r} is not a typed fault)"
            )
        elif CAUSE_EVENTS[cause_event] != rec.get("cause_class"):
            problems.append(
                f"{ident}: cause {cause_event} does not support class "
                f"{rec.get('cause_class')!r}"
            )
    # Coverage: every typed fault event in the ledger must belong to an
    # incident in the record of truth. With a durable incidents.jsonl
    # this is the tamper bound; without one, offline correlation covers
    # by construction and the clause is a self-consistency check.
    from yuma_simulation_tpu.telemetry.incident import _subject

    for rec in bundle.ledger:
        if not isinstance(rec, dict):
            continue
        cls = CAUSE_EVENTS.get(rec.get("event", ""))
        if cls is None:
            continue
        subject = _subject(rec)
        ident = f"{cls}:{subject}" if subject else cls
        if ident not in known:
            problems.append(
                f"uncorrelated cause: ledger {rec.get('event')} "
                f"({subject or 'bundle'}) has no incident {ident}"
            )
    return problems, malformed


def render_incidents(
    label: str, bundle, records: list, durable: bool
) -> str:
    lines = [f"incident report: {label}"]
    source = "incidents.jsonl" if durable else "offline correlation"
    open_count = sum(
        1 for r in records
        if isinstance(r, dict) and r.get("state") == "open"
    )
    lines.append(
        f"{len(records)} incident(s), {open_count} open ({source})"
    )
    for rec in records:
        if not isinstance(rec, dict):
            continue
        ident = rec.get("incident", "?")
        state = rec.get("state", "?")
        lines.append(f"  [{'!' if state == 'open' else ' '}] {ident} "
                     f"[{state}]")
        cause = rec.get("cause") or {}
        cause_bits = [f"cause: {cause.get('event', '?')}"]
        for key in ("netuid", "unit", "worker", "reason", "kind",
                    "stalled_seconds", "run"):
            if key in cause:
                cause_bits.append(f"{key}={cause[key]}")
        if cause.get("event") == RESTART_EVENT:
            cause_bits.append("(stale open run marker at startup)")
        lines.append("      " + " ".join(str(b) for b in cause_bits))
        opened = rec.get("opened_t")
        resolved = rec.get("resolved_t")
        when = f"      opened t={opened}"
        if resolved is not None:
            when += (
                f"; resolved t={resolved}"
                f" ({rec.get('resolution') or 'recovered'})"
            )
        lines.append(when)
        blast = rec.get("blast_radius") or {}
        if blast:
            lines.append(
                "      blast radius: "
                + " ".join(
                    f"{dim}={vals}" for dim, vals in sorted(blast.items())
                )
            )
        symptoms = rec.get("symptoms") or []
        if symptoms:
            lines.append(f"      timeline ({len(symptoms)}):")
            for s in symptoms[:10]:
                bits = [f"t={s.get('t')}", str(s.get('kind', '?'))]
                for key in ("event", "series", "slo", "state", "detail",
                            "reason"):
                    if s.get(key):
                        bits.append(str(s[key]))
                lines.append("        " + " ".join(bits))
            if len(symptoms) > 10:
                lines.append(f"        ... {len(symptoms) - 10} more")
    # Symptom events that attached to no incident are operator
    # questions, not failures — surface the count, never gate on it.
    attached = set()
    for rec in records:
        if isinstance(rec, dict):
            for s in rec.get("symptoms") or []:
                if isinstance(s, dict):
                    attached.add((s.get("event"), s.get("t")))
    orphans = [
        r
        for r in unattributed_symptoms(bundle.ledger, [])
        if (r.get("event"), r.get("t")) not in attached
    ]
    if orphans:
        lines.append(f"unattributed symptoms: {len(orphans)}")
    anomalies = sum(
        1 for r in bundle.ledger
        if isinstance(r, dict) and r.get("event") == "anomaly_detected"
    )
    opened_events = sum(
        1 for r in bundle.ledger
        if isinstance(r, dict) and r.get("event") == "incident_opened"
    )
    resolved_events = sum(
        1 for r in bundle.ledger
        if isinstance(r, dict) and r.get("event") == "incident_resolved"
    )
    lines.append(
        f"ledger: {anomalies} anomaly_detected, "
        f"{opened_events} incident_opened, "
        f"{resolved_events} incident_resolved"
    )
    if bundle.metrics:
        last = bundle.metrics[-1]
        gauges = last.get("gauges", {}) if isinstance(last, dict) else {}
        counters = last.get("counters", {}) if isinstance(last, dict) else {}
        if "incidents_open" in gauges or "anomalies_total" in counters:
            lines.append(
                f"metrics: incidents_open={gauges.get('incidents_open', 0)} "
                f"anomalies_total={counters.get('anomalies_total', 0)}"
            )
    return "\n".join(lines)


def _targets(directory: str) -> list[tuple[str, pathlib.Path]]:
    from yuma_simulation_tpu.fabric.store import FleetStore, is_fleet_store

    if is_fleet_store(directory):
        store = FleetStore(directory)
        return [
            (f"host {host_id}", store.host_dir(host_id))
            for host_id in store.host_ids()
        ]
    return [("bundle", pathlib.Path(directory))]


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="incidentreport", description=__doc__.split("\n\n")[0]
    )
    parser.add_argument("directory", help="flight bundle or fleet store")
    parser.add_argument(
        "--check",
        action="store_true",
        help="exit 1 when any typed fault ledger event lacks a "
        "correlated incident or any incident lacks a cause candidate; "
        "exit 2 when the incident record is malformed",
    )
    parser.add_argument(
        "--expect-none",
        action="store_true",
        help="exit 1 when ANY incident exists (unfaulted control arms)",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit incidents as JSON"
    )
    args = parser.parse_args(argv)

    targets = _targets(args.directory)
    loaded = []
    for label, path in targets:
        bundle = load_bundle(path)
        records, durable = _incident_records(bundle)
        loaded.append((label, path, bundle, records, durable))

    if args.json:
        print(
            json.dumps(
                {
                    label: {"durable": durable, "incidents": records}
                    for label, _p, _b, records, durable in loaded
                },
                indent=2,
                sort_keys=True,
                default=str,
            )
        )
    else:
        for i, (label, path, bundle, records, durable) in enumerate(loaded):
            if i:
                print()
            print(render_incidents(f"{label} ({path})", bundle, records,
                                   durable))

    rc = 0
    if args.expect_none:
        for label, _p, _b, records, _d in loaded:
            if records:
                print(
                    f"\nincidentreport --expect-none FAILED: {label} has "
                    f"{len(records)} incident(s)",
                    file=sys.stderr,
                )
                rc = max(rc, 1)
        if rc == 0:
            print("\nincidentreport --expect-none: zero incidents")
    if args.check:
        all_problems: list[str] = []
        all_malformed: list[str] = []
        for label, _p, bundle, records, _durable in loaded:
            problems, malformed = check_incidents(bundle, records)
            all_problems.extend(f"{label}: {p}" for p in problems)
            all_malformed.extend(f"{label}: {m}" for m in malformed)
        if all_malformed:
            print("\nincidentreport --check MALFORMED:", file=sys.stderr)
            for m in all_malformed:
                print(f"  - {m}", file=sys.stderr)
            return 2
        if all_problems:
            print("\nincidentreport --check FAILED:", file=sys.stderr)
            for p in all_problems:
                print(f"  - {p}", file=sys.stderr)
            return 1
        total = sum(len(records) for _l, _p, _b, records, _d in loaded)
        print(
            f"\nincidentreport --check: {total} incident(s) across "
            f"{len(loaded)} bundle(s); every typed fault correlated, "
            "every incident caused"
        )
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
