"""Randomized cross-engine agreement sweep ON REAL TPU HARDWARE.

The golden artifacts (tools/tpu_parity.py) pin both engines against the
reference CSVs on the 14 built-in cases; this tool pins the engines
against EACH OTHER on randomized workloads at sizes the golden cases
never reach — the fused case scan (the `epoch_impl="auto"` TPU default)
vs the XLA `lax.scan` engine, per output, per shape, per version.

    python tools/cross_engine_check.py --out CROSS_ENGINE.json

Measured behavior (DESIGN.md "Precision policy"): with the r4 canonical
fixed-point support test (`ops/consensus.py::support_fixed_stakes` /
`support_rounded`) shared by every engine, consensus agreement is
bitwise BY CONSTRUCTION — round 3's knife-edge `support == kappa` tie
flips (6/90 runs per regime, from order-dependent f32 support sums) are
gone at the source. This sweep re-measures that claim on chip after any
kernel change; `consensus_mismatch_runs` must be 0 in both regimes.
Residual nonzero deviations in bonds/dividends/incentives are DOWNSTREAM
f32 arithmetic-order effects on identical consensus (the capacity-bond
worst is one low-mantissa quantum of its ~2^64-scaled state). The sweep
additionally requires the exact-MXU scan (the r4 `auto` default) to be
BITWISE the VPU scan on every output of every run
(`mxu_vs_vpu_bitwise_mismatch_runs` must be 0).
"""

import argparse
import datetime
import json
import os
import sys

import numpy as np

# Runs as `python tools/cross_engine_check.py` from the repo root;
# PYTHONPATH cannot be used instead — setting it breaks the TPU plugin
# registration in this environment.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from yuma_simulation_tpu.utils import enable_compilation_cache

enable_compilation_cache()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from yuma_simulation_tpu.models.config import YumaConfig, YumaParams  # noqa: E402
from yuma_simulation_tpu.models.variants import variant_for_version  # noqa: E402
from yuma_simulation_tpu.simulation.engine import (  # noqa: E402
    _simulate_case_fused,
    _simulate_scan,
)

SHAPES = [(16, 6, 18), (10, 3, 2), (8, 64, 1024), (6, 128, 2048), (4, 256, 4096)]
VERSIONS = [
    ("Yuma 0 (subtensor)", {}),
    ("Yuma 1 (paper)", {}),
    ("Yuma 1 (paper) - liquid alpha on", dict(liquid_alpha=True)),
    ("Yuma 2 (Adrian-Fish)", {}),
    ("Yuma 3.1 (Rhef+reset)", {}),
    ("Yuma 4 (Rhef+relative bonds)", {}),
]
SEEDS = (0, 1, 2)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--dense",
        action="store_true",
        help="dense uniform weights (no manufactured ties) instead of the "
        "default ~25%%-zeroed sparse regime",
    )
    args = ap.parse_args()
    assert not jax.config.jax_enable_x64, "run in the shipped f32 mode"

    worst = {"consensus": 0.0, "bonds": 0.0, "dividends": 0.0, "incentives": 0.0}
    worst_rel = dict(worst)
    consensus_mismatch_runs = 0
    mxu_bitwise_mismatch_runs = 0
    runs = 0
    for E, V, M in SHAPES:
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            W_np = rng.random((E, V, M)).astype(np.float32)
            if not args.dense:
                # ~25% zeros: manufactures stake-sum ties / zero columns
                W_np[W_np < 0.25] = 0.0
            W = jnp.asarray(W_np)
            S = jnp.asarray(rng.random((E, V)).astype(np.float32) + 0.01)
            ri = jnp.asarray(int(rng.integers(0, M)), jnp.int32)
            re = jnp.asarray(int(rng.integers(1, E)), jnp.int32)
            for version, params in VERSIONS:
                cfg = YumaConfig(yuma_params=YumaParams(**params))
                spec = variant_for_version(version)
                ys_x = _simulate_scan(
                    W, S, ri, re, cfg, spec, save_consensus=True
                )
                ys_f = _simulate_case_fused(
                    W, S, ri, re, cfg, spec, save_consensus=True
                )
                # The exact-MXU scan must be BITWISE the VPU scan on
                # every output (its limb-split support is the same
                # canonical integer sum; everything else shares ops).
                ys_m = _simulate_case_fused(
                    W, S, ri, re, cfg, spec, save_consensus=True, mxu=True
                )
                for k in worst:
                    if not np.array_equal(
                        np.asarray(ys_m[k]), np.asarray(ys_f[k])
                    ):
                        mxu_bitwise_mismatch_runs += 1
                        break
                for k in worst:
                    a = np.asarray(ys_f[k], np.float64)
                    b = np.asarray(ys_x[k], np.float64)
                    d = float(np.abs(a - b).max())
                    worst[k] = max(worst[k], d)
                    # Scale-aware twin: capacity bonds are O(S * 2^64), so
                    # the absolute number alone misreads as huge.
                    scale = max(float(np.abs(b).max()), 1e-30)
                    worst_rel[k] = max(worst_rel[k], d / scale)
                    if k == "consensus" and d != 0.0:
                        consensus_mismatch_runs += 1
                runs += 1

    dev = jax.devices()[0]
    artifact = {
        "artifact": (
            "fused case scan vs XLA engine on randomized "
            + ("dense" if args.dense else "sparse")
            + " workloads (the default-TPU path vs the fallback path, "
            "all outputs)"
        ),
        "regime": "dense" if args.dense else "sparse (~25% zeroed weights)",
        "device": f"{dev.device_kind} ({dev.platform})",
        "shapes_EVM": SHAPES,
        "seeds": list(SEEDS),
        "versions": [v for v, _ in VERSIONS],
        "runs": runs,
        "consensus_mismatch_runs": consensus_mismatch_runs,
        "mxu_vs_vpu_bitwise_mismatch_runs": mxu_bitwise_mismatch_runs,
        "worst_abs_deviation": worst,
        "worst_deviation_rel_to_output_scale": worst_rel,
        "captured": datetime.date.today().isoformat(),
        "notes": (
            "Both engines evaluate the consensus support test on the "
            "canonical fixed-point integers (ops/consensus.py::"
            "support_fixed_stakes, rounded once to dtype by "
            "support_rounded), so consensus agreement is bitwise by "
            "construction — consensus_mismatch_runs must be 0 and "
            "worst consensus deviation 0.0. Round 3's 6/90 knife-edge "
            "support==kappa tie flips came from order-dependent f32 "
            "support sums and are eliminated at the source. Remaining "
            "bonds/dividends/incentives deviations are downstream f32 "
            "arithmetic-order effects on IDENTICAL consensus (the "
            "capacity-bond worst is one low-mantissa quantum of its "
            "~2^64-scaled state; dividend/incentive worsts are ~1e-7, "
            "f32 ulp scale)."
        ),
    }
    # The canonical support test makes consensus agreement bitwise by
    # construction; any mismatch is a regression (an engine stopped using
    # support_fixed_stakes/support_rounded). The status field is stamped
    # BEFORE the artifact is written so a failing run can never leave a
    # clean-looking JSON on disk, and the exit code fails CI loudly.
    failed = []
    if consensus_mismatch_runs:
        failed.append("consensus_mismatch")
    if mxu_bitwise_mismatch_runs:
        failed.append("mxu_bitwise_mismatch")
    artifact["status"] = "ok" if not failed else "FAILED_" + "+".join(failed)
    text = json.dumps(artifact, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text)
    if failed:
        sys.exit(
            f"FAIL: {consensus_mismatch_runs} consensus mismatch runs, "
            f"{mxu_bitwise_mismatch_runs} MXU-vs-VPU bitwise mismatch runs"
        )


if __name__ == "__main__":
    main()
