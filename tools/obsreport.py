"""obsreport: render a sweep's flight-recorder bundle as a human timeline.

The operator's one-stop answer to "what did this sweep actually do":
loads the bundle a supervised sweep leaves in its checkpoint directory
(``ledger.jsonl`` + ``spans.jsonl`` + ``metrics.jsonl`` +
``costs.jsonl`` + ``report.json`` — see
:mod:`yuma_simulation_tpu.telemetry.flight`) and renders the span tree
with every ledger record — demotions, stalls, shrinks, requeues,
quarantines — attributed to its span, cross-checked against the run's
`SweepHealthReport`, plus a perf section (AOT cost report + roofline
verdicts) when the bundle carries cost records and a per-tenant request
timeline when it carries a serving run's `request:*` spans
(`yuma_simulation_tpu.serve`).

Usage::

    python -m tools.obsreport SWEEP_DIR              # timeline, latest run
    python -m tools.obsreport SWEEP_DIR --run RUN_ID # a specific run
    python -m tools.obsreport SWEEP_DIR --check      # CI gate: exit 2 on
                                                     # unresolvable records
                                                     # or report mismatch
    python -m tools.obsreport SWEEP_DIR --json       # machine-readable
    python -m tools.obsreport SWEEP_DIR --follow     # tail a LIVE
                                                     # (segmented) bundle:
                                                     # new spans/records/
                                                     # seals as they land
    python -m tools.obsreport SWEEP_DIR --drill      # run the chaos drill
                                                     # into SWEEP_DIR first
                                                     # (CI smoke; CPU)

``--drill`` provokes the full chaos composition deterministically via
the test-only fault hooks — a stall, a NaN lane, a torn checkpoint
chunk, and (when ``jax.shard_map`` is available) a device loss on the
virtual 8-device CPU mesh — so the CI chaos lane can produce, gate and
upload a real bundle on every push.

Fleet stores (``yuma_simulation_tpu.fabric``) are detected
automatically: the report renders the merged ``FleetHealthReport`` plus
one per-host timeline section, and ``--check`` additionally runs the
fleet gate — every unit has a verified result, every claim on disk
resolves to a ledger record (and, through the per-host bundle check, to
a span), and the published fleet report matches the merged ledgers.
``--fleet-drill`` runs the multiprocess pod-level chaos drill (one host
SIGKILLed, one lease torn, a stall and a NaN lane on a third host, an
unfaulted oracle host) into DIRECTORY first, verifying healthy lanes
land bitwise-identical to the unfaulted run.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys

#: Identity/bookkeeping keys not repeated per rendered ledger record.
_IDENTITY_KEYS = ("event", "t", "run_id", "span_id", "parent_id")


def _fmt_ts(t: float | None) -> str:
    if not t:
        return "--:--:--.---"
    return datetime.datetime.fromtimestamp(t).strftime("%H:%M:%S.%f")[:-3]


def _fmt_fields(rec: dict) -> str:
    parts = []
    for k, v in rec.items():
        if k in _IDENTITY_KEYS:
            continue
        parts.append(f"{k}={json.dumps(v) if isinstance(v, (list, dict)) else v}")
    return " ".join(parts)


def render_run(bundle, run_id: str) -> str:
    """One run's recovery timeline as indented text."""
    from yuma_simulation_tpu.telemetry.flight import build_timeline

    tl = build_timeline(bundle, run_id)
    lines = [f"run {run_id}"]
    if not tl["spans"]:
        lines.append("  (no spans recorded for this run)")

    def emit(span_id: str, depth: int) -> None:
        s = tl["spans"][span_id]
        t0, t1 = s.get("t_start"), s.get("t_end")
        dur = f"{t1 - t0:.3f}s" if t0 and t1 else "?"
        status = "" if s.get("status") == "ok" else f"  {s['status'].upper()}"
        attrs = s.get("attrs") or {}
        attr_txt = "".join(
            f" {k}={json.dumps(v)}"
            for k, v in attrs.items()
            if k not in ("steps", "plan")  # plans render in their own section
        )
        pad = "  " * (depth + 1)
        lines.append(
            f"{pad}{_fmt_ts(t0)}  {s.get('name')} [{span_id}] "
            f"{dur}{attr_txt}{status}"
        )
        for rec in tl["records"].get(span_id, ()):
            lines.append(
                f"{pad}  * {_fmt_ts(rec.get('t'))} "
                f"{rec.get('event')} {_fmt_fields(rec)}".rstrip()
            )
        for child in tl["children"].get(span_id, ()):
            emit(child, depth + 1)

    for root in tl["roots"]:
        emit(root, 0)
    orphans = tl["records"].get("", ())
    if orphans:
        lines.append("  records with no span (pre-telemetry writer?):")
        for rec in orphans:
            lines.append(f"    * {rec.get('event')} {_fmt_fields(rec)}")
    return "\n".join(lines)


def render(bundle, run_id: str | None) -> str:
    from yuma_simulation_tpu.telemetry.flight import ledger_counts

    lines = [f"flight bundle: {bundle.directory}"]
    runs = bundle.run_ids()
    if not runs:
        lines.append("no runs recorded (empty or pre-telemetry directory)")
        return "\n".join(lines)
    lines.append(
        "runs: " + ", ".join(runs) + f"  (ledger: {len(bundle.ledger)} "
        f"records, spans: {len(bundle.spans)})"
    )
    target = run_id if run_id is not None else runs[-1]
    lines.append("")
    lines.append(render_run(bundle, target))
    counts = ledger_counts(bundle.ledger, target)
    lines.append("")
    lines.append(
        "ledger-derived counts: "
        + " ".join(f"{k}={v}" for k, v in counts.items())
    )
    if bundle.report is not None and bundle.report.get("run_id") == target:
        rep = bundle.report.get("report", {})
        lines.append(
            "health report:         "
            + " ".join(f"{k}={rep.get(k)}" for k in counts)
        )
    if bundle.metrics:
        last = bundle.metrics[-1]
        counters = last.get("counters", {})
        gauges = last.get("gauges", {})
        lines.append(
            "metrics (last snapshot): "
            + " ".join(
                f"{k}={_num(v)}" for k, v in {**counters, **gauges}.items()
            )
        )
    plans = render_plans(bundle, target)
    if plans:
        lines.append("")
        lines.extend(plans)
    serve = render_serve(bundle, target)
    if serve:
        lines.append("")
        lines.extend(serve)
    scaleout = render_scaleout(bundle)
    if scaleout:
        lines.append("")
        lines.extend(scaleout)
    perf = render_perf(bundle)
    if perf:
        lines.append("")
        lines.extend(perf)
    numerics = render_numerics(bundle)
    if numerics:
        lines.append("")
        lines.extend(numerics)
    foundry = render_foundry(bundle, target)
    if foundry:
        lines.append("")
        lines.extend(foundry)
    replay = render_replay(bundle)
    if replay:
        lines.append("")
        lines.extend(replay)
    controller = render_controller(bundle)
    if controller:
        lines.append("")
        lines.extend(controller)
    telemetry = render_telemetry(bundle)
    if telemetry:
        lines.append("")
        lines.extend(telemetry)
    dispatch = render_dispatch(bundle)
    if dispatch:
        lines.append("")
        lines.extend(dispatch)
    incidents = render_incidents(bundle)
    if incidents:
        lines.append("")
        lines.extend(incidents)
    return "\n".join(lines)


def render_telemetry(bundle) -> list[str]:
    """The continuous-telemetry section of a ROTATING bundle: one line
    per sealed segment (the ``segment_sealed`` seal records that ride
    ``segments/seg_*/seal.json``), the retention tombstone (the
    cumulative ``segments_compacted`` record in ``compacted.json``),
    and the registered profiler captures (``profile_started`` /
    ``profile_published`` records in ``profiles.jsonl``). Empty for
    monolithic bundles with no profiles."""
    if not (bundle.segments or bundle.profiles or bundle.compacted):
        return []
    lines = ["continuous telemetry (rotating segments & profiles):"]
    seals = [
        s for s in bundle.segments if s.get("event") == "segment_sealed"
    ]
    for seal in seals:
        lines.append(
            f"  sealed {seal.get('segment', '?')} at "
            f"{_fmt_ts(seal.get('t'))}: {_fmt_bytes(seal.get('bytes'))} "
            f"across {len(seal.get('run_ids', ()))} run(s)"
        )
    counters = gauges = {}
    if bundle.metrics:
        counters = bundle.metrics[-1].get("counters", {})
        gauges = bundle.metrics[-1].get("gauges", {})
    if "telemetry_segments_total" in counters:
        lines.append(
            "  rotation counters: sealed="
            f"{_num(counters['telemetry_segments_total'])} "
            f"retained={_fmt_bytes(gauges.get('telemetry_bytes_retained', 0))}"
        )
    c = bundle.compacted
    if c and c.get("event") == "segments_compacted":
        lines.append(
            f"  compacted: {c.get('segments', 0)} segment(s) / "
            f"{_fmt_bytes(c.get('bytes', 0))} reclaimed by retention "
            f"(runs exempted from span checks: "
            f"{len(c.get('run_ids', ()))})"
        )
    for rec in bundle.profiles:
        event = rec.get("event") or "profile_published"
        marker = "[.]" if event == "profile_started" else "[x]"
        lines.append(
            f"  profile {marker} {event} {_fmt_ts(rec.get('t'))} "
            f"mode={rec.get('mode', '?')} "
            f"artifact={rec.get('artifact', '?')}"
        )
    return lines


def render_incidents(bundle) -> list[str]:
    """The incident-intelligence section (0.24.0): current state per
    incident from the bundle's durable ``incidents.jsonl`` (last record
    per id), plus the anomaly_detected / incident_opened /
    incident_resolved ledger tallies. Empty on clean bundles — an
    unfaulted run never creates the sink. Deep postmortems live in
    ``python -m tools.incidentreport``."""
    from yuma_simulation_tpu.telemetry.incident import latest_incidents

    anomalies = sum(
        1
        for r in bundle.ledger
        if r.get("event") == "anomaly_detected"
    )
    opened = sum(
        1 for r in bundle.ledger if r.get("event") == "incident_opened"
    )
    resolved = sum(
        1 for r in bundle.ledger if r.get("event") == "incident_resolved"
    )
    current = latest_incidents(bundle.incidents)
    if not (current or anomalies or opened or resolved):
        return []
    lines = ["incident intelligence:"]
    lines.append(
        f"  ledger: anomalies={anomalies} opened={opened} "
        f"resolved={resolved}"
    )
    last = bundle.metrics[-1] if bundle.metrics else {}
    counters = last.get("counters", {}) if isinstance(last, dict) else {}
    if "anomalies_total" in counters:
        lines.append(
            f"  metrics: anomalies_total={counters['anomalies_total']}"
        )
    for rec in current:
        flag = "!" if rec.get("state") == "open" else " "
        cause = rec.get("cause") or {}
        lines.append(
            f"  [{flag}] {rec.get('incident')} [{rec.get('state')}] "
            f"cause={cause.get('event', '?')} "
            f"symptoms={len(rec.get('symptoms') or ())}"
            + (
                f" resolution={rec.get('resolution')}"
                if rec.get("resolution")
                else ""
            )
        )
    return lines


def render_dispatch(bundle) -> list[str]:
    """The dispatch-timing section: the always-on per-(engine rung x
    shape bucket x backend) latency sketches joined off the bundle's
    metrics lines, plus the roofline-gap attribution table
    (``tools/perfattrib.py``) when a BENCH history sits beside the
    working directory. Empty when no snapshot carried sketches."""
    try:
        from tools.perfattrib import (
            attribute,
            collect_sketches,
            render_rows,
        )
    except ImportError:  # executed as a bare script, not -m tools.*
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from perfattrib import attribute, collect_sketches, render_rows

    sketches = collect_sketches(bundle.metrics)
    if not sketches:
        return []
    from yuma_simulation_tpu.telemetry.slo import LatencySketch

    lines = [
        "dispatch timing ('dispatch_seconds' sketch family, "
        f"{len(sketches)} key(s)):"
    ]
    for key, e in sorted(sketches.items()):
        secs = float(e.get("seconds_total", 0.0))
        epochs = int(e.get("epochs_total", 0))
        rate = f" {epochs / secs:.1f}ep/s" if secs > 0 and epochs else ""
        quantiles = ""
        if isinstance(e.get("sketch"), dict):
            try:
                sk = LatencySketch.from_json(e["sketch"])
                p50, p99 = sk.quantile(0.5), sk.quantile(0.99)
                if p50 is not None and p99 is not None:
                    quantiles = (
                        f" p50={p50 * 1e3:.1f}ms p99={p99 * 1e3:.1f}ms"
                    )
            except Exception:
                pass
        lines.append(
            f"  {key}: {e.get('dispatches', 0)} dispatch(es) "
            f"{secs:.3f}s{rate}{quantiles}"
        )
    history = os.environ.get("YUMA_TPU_BENCH_HISTORY", "BENCH_HISTORY.jsonl")
    if os.path.exists(history):
        import io

        from yuma_simulation_tpu.utils.checkpoint import (
            read_jsonl_tolerant,
        )

        records = read_jsonl_tolerant(history)
        if records:
            out = io.StringIO()
            render_rows(attribute(records[-1], sketches), out=out)
            lines.append("  roofline-gap attribution (perfattrib):")
            lines.extend(
                "  " + line for line in out.getvalue().splitlines()
            )
    return lines


def render_replay(bundle) -> list[str]:
    """The chain-replay section: cache effectiveness and suffix-vs-full
    epoch savings, per tenant, aggregated from the serve ledger's
    ``whatif_served`` records, cross-read against the process counters
    (``state_cache_hits`` / ``state_cache_misses`` /
    ``replay_suffix_epochs_saved``) of the last metrics snapshot."""
    served = [
        r for r in bundle.ledger if r.get("event") == "whatif_served"
    ]
    counters = (
        bundle.metrics[-1].get("counters", {}) if bundle.metrics else {}
    )
    hits = counters.get("state_cache_hits", 0)
    misses = counters.get("state_cache_misses", 0)
    saved = counters.get("replay_suffix_epochs_saved", 0)
    if not served and not (hits or misses):
        return []
    lines = ["chain replay (what-ifs & state cache):"]
    total = (hits or 0) + (misses or 0)
    ratio = f"{hits / total:.0%}" if total else "n/a"
    lines.append(
        f"  cache: hits={_num(hits)} misses={_num(misses)} "
        f"(hit ratio {ratio}), suffix epochs saved={_num(saved)}"
    )
    tenants: dict[str, dict] = {}
    for rec in served:
        t = tenants.setdefault(
            str(rec.get("tenant", "?")),
            {"whatifs": 0, "hits": 0, "suffix": 0, "full": 0},
        )
        t["whatifs"] += 1
        t["hits"] += 1 if rec.get("cache_hit") else 0
        t["suffix"] += int(rec.get("suffix_epochs", 0))
        t["full"] += int(rec.get("full_epochs", 0))
    for tenant, t in sorted(tenants.items()):
        pct = (
            f"{1 - t['suffix'] / t['full']:.0%}" if t["full"] else "n/a"
        )
        lines.append(
            f"  tenant {tenant}: whatifs={t['whatifs']} "
            f"cache_hits={t['hits']} simulated {t['suffix']} of "
            f"{t['full']} epochs ({pct} saved by suffix resume)"
        )
    return lines


def render_controller(bundle) -> list[str]:
    """The continuous-replay controller section: sweep/watermark
    progress and self-healing actions, aggregated from the controller
    bundle's ``window_swept`` / ``watermark_advanced`` /
    ``subnet_ingested`` / ``subnet_stalled`` / ``subnet_quarantined``
    ledger records, cross-read against the freshness gauges and
    counters of the last metrics snapshot (``replay_staleness_seconds``
    / ``subnets_live`` / ``windows_swept_total`` /
    ``snapshots_quarantined_total``)."""
    swept = [r for r in bundle.ledger if r.get("event") == "window_swept"]
    advanced = [
        r for r in bundle.ledger if r.get("event") == "watermark_advanced"
    ]
    ingested = [
        r for r in bundle.ledger if r.get("event") == "subnet_ingested"
    ]
    stalled = [
        r for r in bundle.ledger if r.get("event") == "subnet_stalled"
    ]
    quarantined = [
        r for r in bundle.ledger if r.get("event") == "subnet_quarantined"
    ]
    if not (swept or stalled or quarantined or ingested):
        return []
    last = bundle.metrics[-1] if bundle.metrics else {}
    counters = last.get("counters", {})
    gauges = last.get("gauges", {})
    lines = ["continuous replay (controller):"]
    lines.append(
        f"  windows swept={_num(counters.get('windows_swept_total', len(swept)))} "
        f"watermark advances={len(advanced)} "
        f"ingest events={len(ingested)}"
    )
    lines.append(
        f"  freshness: staleness="
        f"{_num(gauges.get('replay_staleness_seconds', 0))}s "
        f"live subnets={_num(gauges.get('subnets_live', 0))} "
        f"stalled={len(stalled)} quarantined="
        f"{_num(counters.get('snapshots_quarantined_total', len(quarantined)))}"
    )
    per_subnet: dict[int, dict] = {}
    for rec in swept:
        s = per_subnet.setdefault(
            int(rec.get("netuid", -1)),
            {"windows": 0, "epochs": 0, "suffix": 0, "head": 0},
        )
        s["windows"] += 1
        s["suffix"] += int(rec.get("suffix_epochs", 0))
        s["epochs"] = max(s["epochs"], int(rec.get("total_epochs", 0)))
        s["head"] = max(s["head"], int(rec.get("block_to", 0)))
    for netuid, s in sorted(per_subnet.items()):
        pct = (
            f"{1 - s['suffix'] / s['epochs']:.0%}"
            if s["epochs"]
            else "n/a"
        )
        lines.append(
            f"  subnet {netuid}: windows={s['windows']} head block "
            f"{s['head']}, simulated {s['suffix']} of {s['epochs']} "
            f"epochs ({pct} saved by watermark resume)"
        )
    for rec in stalled:
        lines.append(
            f"  stalled: subnet {rec.get('netuid')} head "
            f"{rec.get('head_block')} ({rec.get('stalled_seconds')}s "
            "quiet) -> slow poll tier"
        )
    for rec in quarantined:
        lines.append(
            f"  quarantined: subnet {rec.get('netuid')} block "
            f"{rec.get('block')} ({rec.get('reason')})"
        )
    return lines


def render_foundry(bundle, run_id: str) -> list[str]:
    """The scenario-foundry section: how much of the bundle's workload
    was GENERATED rather than hand-written — the `scenarios_generated`
    counter of the last metrics snapshot (process-lifetime). The
    per-scenario provenance records (`event=scenario_compiled`,
    `event=metagraph_loaded`) ride the LOG stream, not the bundle
    ledger — `grep event=` the process log for them."""
    del run_id  # the counter is process-scoped, not per-run
    generated = 0
    if bundle.metrics:
        generated = (
            bundle.metrics[-1]
            .get("counters", {})
            .get("scenarios_generated", 0)
        )
    if not generated:
        return []
    return [
        "scenario foundry (generated workload):",
        f"  scenarios_generated={_num(generated)} (process total; "
        "per-scenario provenance rides event=scenario_compiled / "
        "event=metagraph_loaded log records)",
    ]


def render_numerics(bundle) -> list[str]:
    """The numerics flight-recorder section: capture counts per role/
    engine and a one-line verdict per canary comparison (the detailed
    ulp/first-divergent-epoch render lives in ``tools/driftreport.py``,
    which also gates ``--check``)."""
    if not bundle.numerics:
        return []
    try:
        from tools.driftreport import diff_bundle
    except ImportError:  # executed as a bare script, not -m tools.*
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from driftreport import diff_bundle

    roles: dict[str, int] = {}
    for rec in bundle.numerics:
        key = f"{rec.get('role', 'primary')}:{rec.get('engine', '?')}"
        roles[key] = roles.get(key, 0) + 1
    lines = [
        "numerics (per-epoch tensor stats + fingerprints):",
        "  records: "
        + " ".join(f"{k}={v}" for k, v in sorted(roles.items())),
    ]
    for v in diff_bundle(bundle.numerics):
        where = (
            f"unit={v['unit']} stream={v['stream']}"
            + (f" ({v['label']})" if v.get("label") else "")
        )
        if v["unmatched"]:
            lines.append(f"  [?] {where}: canary with no primary")
        elif v["divergences"]:
            d = v["divergences"][0]
            lines.append(
                f"  [!] {where}: DRIFT at epoch "
                f"{d['first_divergent_epoch']} (lane {d['lane']}, "
                f"ulp {d['ulp_distance']:+d})"
            )
        else:
            lines.append(
                f"  [ ] {where}: canary bitwise identical "
                f"({v.get('primary_engine')} vs {v['canary_engine']})"
            )
    return lines


def render_plans(bundle, run_id: str) -> list[str]:
    """The dispatch-plan section: one line per recorded `DispatchPlan`
    span attribute (`event=dispatch_planned`, simulation.planner) —
    engine rung, shape bucket, sharding lanes, predicted HBM, slab cap
    and the WHY, so a flight bundle answers "which engine ran, and on
    what grounds" without replaying the sweep."""
    seen: list[tuple[str, dict]] = []
    for s in bundle.spans:
        if s.get("run_id") != run_id:
            continue
        plan = (s.get("attrs") or {}).get("plan")
        if isinstance(plan, dict):
            seen.append((s.get("name", "?"), plan))
    if not seen:
        return []
    lines = ["dispatch plans:"]
    for name, plan in seen:
        parts = [
            f"  {name}:",
            f"engine={plan.get('engine')}",
            f"bucket={plan.get('bucket')}",
        ]
        if plan.get("shards", 1) != 1:
            parts.append(f"shards={plan['shards']}")
        if plan.get("lanes", 1) != 1:
            parts.append(f"lanes={plan['lanes']}")
        if plan.get("hbm_gib") is not None:
            parts.append(f"hbm={plan['hbm_gib']}GiB")
        if plan.get("fits") is not None:
            parts.append(f"fits={plan['fits']}")
        if plan.get("chunk_epochs") is not None:
            parts.append(f"chunk_epochs={plan['chunk_epochs']}")
        if plan.get("why"):
            parts.append(f"({plan['why']})")
        lines.append(" ".join(parts))
    return lines


#: Critical-path phase spans the serving tier synthesizes per request.
_PHASE_NAMES = ("queue", "coalesce", "compile", "execute")


def _phase_index(bundle) -> dict:
    """``(run_id, parent_id) -> [phase span]`` in one pass over the
    bundle, so per-request breakdowns are a dict hit instead of a full
    span rescan per request row."""
    index: dict[tuple, list] = {}
    for s in bundle.spans:
        if s.get("name") in _PHASE_NAMES:
            index.setdefault(
                (s.get("run_id"), s.get("parent_id")), []
            ).append(s)
    return index


def _phase_breakdown(phases: dict, request_span) -> str:
    """The request's critical-path children (queue/coalesce/compile/
    execute) inline, e.g. ``(queue 0.2ms | execute 81.0ms)``."""
    rid, sid = request_span.get("run_id"), request_span.get("span_id")
    parts = []
    for s in phases.get((rid, sid), ()):
        name = s.get("name")
        t0, t1 = s.get("t_start"), s.get("t_end")
        if t0 is None or t1 is None:
            continue
        parts.append((_PHASE_NAMES.index(name), f"{name} {1000 * (t1 - t0):.1f}ms"))
    if not parts:
        return ""
    return " (" + " | ".join(p for _, p in sorted(parts)) + ")"


def render_serve(bundle, run_id: str) -> list[str]:
    """The per-tenant request timeline of a SERVING bundle: one section
    per tenant, one line per ``request:*`` span — arrival time,
    endpoint, outcome, HTTP status, wall duration, critical-path
    breakdown — so a server's flight bundle answers "what did each
    tenant see" without grepping the ledger. Scans EVERY run in the
    bundle: a request joining a caller's distributed trace records its
    span under the CALLER's run_id, not the server run's."""
    requests = []
    for s in bundle.spans:
        if not str(s.get("name", "")).startswith("request:"):
            continue
        requests.append(s)
    if not requests:
        return []
    by_tenant: dict[str, list] = {}
    for s in requests:
        attrs = s.get("attrs") or {}
        by_tenant.setdefault(str(attrs.get("tenant", "?")), []).append(s)
    lines = [f"serve requests ({len(requests)} across {len(by_tenant)} tenant(s)):"]
    phases = _phase_index(bundle)
    for tenant in sorted(by_tenant):
        spans = sorted(
            by_tenant[tenant], key=lambda s: float(s.get("t_start") or 0.0)
        )
        shed = sum(
            1
            for s in spans
            if (s.get("attrs") or {}).get("status") in (429, 503, 504)
        )
        lines.append(
            f"  tenant {tenant}: {len(spans)} request(s)"
            + (f", {shed} shed/failed" if shed else "")
        )
        for s in spans:
            attrs = s.get("attrs") or {}
            t0, t1 = s.get("t_start"), s.get("t_end")
            dur = f"{t1 - t0:.3f}s" if t0 and t1 else "?"
            lines.append(
                (
                    f"    {_fmt_ts(t0)}  {s.get('name')} "
                    f"{attrs.get('endpoint', '?')} "
                    f"-> {attrs.get('status', '?')} "
                    f"{attrs.get('outcome', '')} {dur}"
                ).rstrip()
                + _phase_breakdown(phases, s)
            )
    return lines


def render_scaleout(bundle) -> list[str]:
    """The router-fleet section of a horizontally scaled SERVING
    bundle: worker lifecycle (``worker_spawned`` / ``worker_retired``
    / ``worker_lost``), per-worker placement + affinity tallies from
    the router's ``request_done`` records, ``request_rerouted``
    counts, and the fleet metrics (``serve_workers_live``,
    ``serve_reroutes_total``, ``affinity_hits_total``) from the last
    snapshot carrying them. Empty for single-process bundles."""
    spawned = [r for r in bundle.ledger if r.get("event") == "worker_spawned"]
    retired = [r for r in bundle.ledger if r.get("event") == "worker_retired"]
    lost = [r for r in bundle.ledger if r.get("event") == "worker_lost"]
    rerouted = [
        r for r in bundle.ledger if r.get("event") == "request_rerouted"
    ]
    placed = [
        r
        for r in bundle.ledger
        if r.get("event") == "request_done" and r.get("worker")
    ]
    if not (spawned or retired or lost or rerouted or placed):
        return []
    lines = [
        f"scale-out fleet: {len(spawned)} spawned, {len(retired)} retired, "
        f"{len(lost)} lost, {len(rerouted)} reroute(s)"
    ]
    for r in spawned:
        lines.append(
            f"  spawned {r.get('worker', '?')} slot={r.get('slot', '?')} "
            f"reason={r.get('reason', '?')} "
            f"aot_builds={_num(r.get('aot_builds', '?'))}"
        )
    for r in lost:
        lines.append(
            f"  lost    {r.get('worker', '?')} "
            f"during {r.get('request') or '?'} ({r.get('error', '?')})"
        )
    for r in retired:
        lines.append(
            f"  retired {r.get('worker', '?')} reason={r.get('reason', '?')}"
        )
    by_worker: dict[str, list[int]] = {}
    hits = 0
    for r in placed:
        tally = by_worker.setdefault(str(r.get("worker")), [0, 0])
        tally[0] += 1
        if r.get("affinity"):
            tally[1] += 1
            hits += 1
    if placed:
        lines.append(
            f"  placement: {len(placed)} routed request(s), "
            f"{hits} affinity-placed"
        )
        for worker in sorted(by_worker):
            served, affine = by_worker[worker]
            lines.append(
                f"    {worker}: {served} served, {affine} affinity-placed"
            )
    # A merged fleet bundle concatenates every process's snapshots;
    # the router's fleet counters may not be in the LAST one, so take
    # the last snapshot that carries each name.
    fleet: dict[str, object] = {}
    for snap in bundle.metrics:
        merged = {**snap.get("counters", {}), **snap.get("gauges", {})}
        for name in (
            "serve_workers_live",
            "serve_reroutes_total",
            "affinity_hits_total",
        ):
            if name in merged:
                fleet[name] = merged[name]
    if fleet:
        lines.append(
            "  fleet metrics: "
            + " ".join(f"{k}={_num(v)}" for k, v in fleet.items())
        )
    return lines


def _fmt_bytes(n) -> str:
    if n is None:
        return "?"
    return f"{n / 2**30:.2f}GiB" if n >= 2**30 else f"{n / 2**20:.1f}MiB"


def render_perf(bundle) -> list[str]:
    """The perf section: one line per AOT cost record (costs.jsonl) —
    flops / bytes moved / peak memory / HLO fingerprint plus the
    roofline verdict under the current host's device spec, with the
    last snapshot's measured epochs/s alongside the predicted ceiling."""
    if not bundle.costs:
        return []
    import dataclasses

    from yuma_simulation_tpu.telemetry.cost import (
        CostRecord,
        resolve_device_spec,
        roofline,
    )

    spec = resolve_device_spec()
    # The last snapshot's measured rate belongs to whichever rung the
    # sweep actually ran — the bundle doesn't say which, so it renders
    # once in the header and is NOT attributed to any record's roofline
    # (an attained% against the wrong rung's ceiling would be noise).
    measured = None
    if bundle.metrics:
        g = bundle.metrics[-1].get("gauges", {})
        measured = g.get("epochs_per_sec")
    field_names = {f.name for f in dataclasses.fields(CostRecord)}
    header = f"perf (AOT cost report, device spec: {spec.name}"
    if measured is not None:
        header += f", last measured rate: {measured:.3g}ep/s"
    lines = [header + "):"]
    defaults = {"engine": "?", "backend": None, "V": 0, "M": 0, "epochs": 0}
    for raw in bundle.costs:
        # Tolerant reconstruction: a minimal (or foreign-writer) line
        # that passed check_bundle must render, not crash the report.
        rec = CostRecord(
            **{
                **defaults,
                **{k: v for k, v in raw.items() if k in field_names},
            }
        )
        shape = f"[{rec.epochs}x{rec.V}x{rec.M}]"
        if rec.flops is None and rec.bytes_accessed is None:
            lines.append(
                f"  {rec.engine} {shape}: unavailable"
                + (f" ({rec.reason})" if rec.reason else "")
            )
            continue
        rl = roofline(rec, spec)
        parts = [
            f"  {rec.engine} {shape}:",
            f"flops={rec.flops:.3g}" if rec.flops is not None else "flops=?",
            (
                f"bytes={rec.bytes_accessed:.3g}"
                if rec.bytes_accessed is not None
                else "bytes=?"
            ),
            f"peak={_fmt_bytes(rec.peak_bytes)}"
            + (f"({rec.peak_bytes_source})" if rec.peak_bytes_source else ""),
            f"hlo={rec.hlo_fingerprint}" if rec.hlo_fingerprint else "",
        ]
        if rl.arithmetic_intensity is not None:
            parts.append(f"intensity={rl.arithmetic_intensity:.3g}")
        if rl.bound:
            parts.append(f"bound={rl.bound}")
        if rl.predicted_epochs_per_sec is not None:
            parts.append(f"roofline={rl.predicted_epochs_per_sec:.3g}ep/s")
        lines.append(" ".join(p for p in parts if p))
    return lines


def _num(v):
    return int(v) if isinstance(v, float) and v.is_integer() else v


class _FileCursor:
    """Byte-offset tail over one append-only JSONL sink: each
    :meth:`read_new` returns only the COMPLETE lines appended since the
    last call, reading only the new bytes. A torn tail (a concurrent
    ``append_durable`` mid-write) is buffered until its newline lands.
    A file that SHRANK (atomic republish that dropped or repaired a
    line) triggers a rescan from zero that suppresses, by line CONTENT,
    the records already emitted — a fixed skip count misaligns the
    moment the rewrite changed any line before the old cursor, silently
    dropping or re-emitting a record."""

    def __init__(self, path):
        self.path = path
        self.offset = 0
        self.bytes_read = 0
        self._partial = b""
        #: hashes of every raw line emitted so far — the identity the
        #: shrink-rescan dedupes against (one small int per record the
        #: follow session already processed, like _seen_spans).
        self._emitted: set = set()
        self._rescan = None  #: emitted-hash snapshot while rescanning

    def read_new(self) -> list[dict]:
        try:
            size = self.path.stat().st_size
        except OSError:
            return []
        if size < self.offset:
            self._rescan = set(self._emitted)
            self.offset = 0
            self._partial = b""
        if size <= self.offset:
            return []
        try:
            with open(self.path, "rb") as fh:
                fh.seek(self.offset)
                chunk = fh.read(size - self.offset)
        except OSError:
            return []
        self.offset += len(chunk)
        self.bytes_read += len(chunk)
        pieces = (self._partial + chunk).split(b"\n")
        self._partial = pieces.pop()
        # Survivors of the rewrite all land in this one read (the
        # rescan starts at 0 and reads to current size), so the dedupe
        # set retires here — after it, identical future lines are new
        # records, not replays.
        dedupe, self._rescan = self._rescan, None
        out: list[dict] = []
        for raw in pieces:
            raw = raw.strip()
            if not raw:
                continue
            key = hash(raw)
            if dedupe is not None and key in dedupe:
                continue
            try:
                rec = json.loads(raw)
            except ValueError:
                continue  # torn/garbled line: tolerated, like the loader
            if isinstance(rec, dict):
                self._emitted.add(key)
                out.append(rec)
        return out


class BundleTailer:
    """Incremental reader behind ``--follow``: per-file byte cursors
    over the bundle's append-only sinks (root ledger / profiles /
    incidents plus every rotation segment's spans), so one tick costs
    O(new bytes) — not O(bundle) — however many sealed segments the
    rotating bundle has accumulated. New segment directories get their
    cursor on first sight; seals are reported once. The monolithic
    (non-rotating) spans file is whole-file REPUBLISHED by its writer,
    so it alone is re-read on size change, deduped by span identity —
    the segmented path never touches it."""

    def __init__(self, directory):
        import pathlib as _pathlib

        from yuma_simulation_tpu.telemetry import flight

        self.directory = _pathlib.Path(directory)
        self._flight = flight
        self._cursors: dict = {}
        self._seen_spans: set = set()
        self._seen_seals: set = set()
        self._mono_spans_size = -1
        self.spans = self.ledger = self.profiles = 0
        self.incidents = 0

    def _cursor(self, path) -> _FileCursor:
        cur = self._cursors.get(path)
        if cur is None:
            cur = self._cursors[path] = _FileCursor(path)
        return cur

    @property
    def bytes_read(self) -> int:
        """Total bytes read off disk across every cursor so far — the
        regression surface the O(new bytes) test pins."""
        return sum(c.bytes_read for c in self._cursors.values())

    def _poll_segments(self) -> list[tuple[str, dict]]:
        events: list[tuple[str, dict]] = []
        root = self.directory / self._flight.SEGMENTS_DIR
        if not root.is_dir():
            return events
        for seg in sorted(p for p in root.iterdir() if p.is_dir()):
            seal_path = seg / self._flight.SEAL_NAME
            if seg.name not in self._seen_seals and seal_path.exists():
                try:
                    seal = json.loads(seal_path.read_text())
                except (OSError, ValueError):
                    seal = None
                if isinstance(seal, dict):
                    self._seen_seals.add(seg.name)
                    events.append(("seal", seal))
            for rec in self._cursor(
                seg / self._flight.SPANS_NAME
            ).read_new():
                key = (rec.get("run_id"), rec.get("span_id"))
                if key in self._seen_spans:
                    continue  # closed form re-appends the open span
                self._seen_spans.add(key)
                events.append(("span", rec))
        return events

    def _poll_mono_spans(self) -> list[tuple[str, dict]]:
        path = self.directory / self._flight.SPANS_NAME
        try:
            size = path.stat().st_size
        except OSError:
            return []
        if size == self._mono_spans_size:
            return []
        self._mono_spans_size = size
        from yuma_simulation_tpu.utils.checkpoint import (
            read_jsonl_tolerant,
        )

        events: list[tuple[str, dict]] = []
        for rec in read_jsonl_tolerant(path):
            key = (rec.get("run_id"), rec.get("span_id"))
            if key in self._seen_spans:
                continue
            self._seen_spans.add(key)
            events.append(("span", rec))
        return events

    def poll(self) -> list[tuple[str, dict]]:
        """One tick: every newly landed record as ``(kind, record)`` —
        kind in seal / span / ledger / profile / incident."""
        events = self._poll_segments()
        events.extend(self._poll_mono_spans())
        for kind, name in (
            ("ledger", self._flight.LEDGER_NAME),
            ("profile", self._flight.PROFILES_NAME),
            ("incident", self._flight.INCIDENTS_NAME),
        ):
            for rec in self._cursor(self.directory / name).read_new():
                events.append((kind, rec))
        self.spans += sum(1 for k, _ in events if k == "span")
        self.ledger += sum(1 for k, _ in events if k == "ledger")
        self.profiles += sum(1 for k, _ in events if k == "profile")
        self.incidents += sum(1 for k, _ in events if k == "incident")
        return events


def _follow_line(kind: str, rec: dict) -> str:
    if kind == "seal":
        return (
            f"{_fmt_ts(rec.get('t'))}  segment_sealed "
            f"{rec.get('segment')} {_fmt_bytes(rec.get('bytes'))} "
            f"runs={len(rec.get('run_ids', ()))}"
        )
    if kind == "span":
        return (
            f"{_fmt_ts(rec.get('t_start'))}  span {rec.get('name')} "
            f"[{rec.get('span_id')}] run={rec.get('run_id')}"
        )
    if kind == "profile":
        return (
            f"{_fmt_ts(rec.get('t'))}  "
            f"{rec.get('event', 'profile_published')} "
            f"mode={rec.get('mode', '?')} "
            f"artifact={rec.get('artifact', '?')}"
        )
    if kind == "incident":
        return (
            f"{_fmt_ts(rec.get('t'))}  incident {rec.get('incident')} "
            f"[{rec.get('state')}] cause="
            f"{(rec.get('cause') or {}).get('event', '?')}"
        )
    return (
        f"{_fmt_ts(rec.get('t'))}  {rec.get('event')} "
        f"{_fmt_fields(rec)}".rstrip()
    )


def follow(
    directory: str,
    *,
    interval: float = 2.0,
    max_seconds: float = 0.0,
    out=None,
) -> int:
    """``--follow``: tail a LIVE bundle — print each newly landed span,
    ledger record, incident transition, sealed segment and registered
    profile as one line. Incremental since 0.24.0: a
    :class:`BundleTailer` keeps per-file byte cursors, so each tick
    reads only the new bytes instead of re-loading the whole segmented
    bundle (the torn tail a concurrent writer may leave is buffered
    until complete). Runs until Ctrl-C, or for `max_seconds` when given
    (the CI-friendly bound)."""
    import time as _time

    out = out or sys.stdout
    tailer = BundleTailer(directory)
    deadline = _time.monotonic() + max_seconds if max_seconds > 0 else None
    print(f"following {directory} (interval {interval}s)", file=out)
    try:
        while True:
            for kind, rec in tailer.poll():
                print(_follow_line(kind, rec), file=out)
            out.flush()
            if deadline is not None and _time.monotonic() >= deadline:
                break
            _time.sleep(interval)
    except KeyboardInterrupt:
        pass
    print(
        f"followed: {tailer.spans} span(s), {tailer.ledger} ledger "
        f"record(s), {len(tailer._seen_seals)} sealed segment(s), "
        f"{tailer.profiles} profile(s), {tailer.incidents} incident "
        f"transition(s) ({tailer.bytes_read} bytes read)",
        file=out,
    )
    return 0


def run_drill(directory: str) -> None:
    """The deterministic chaos drill: stall + NaN lane + torn chunk
    (+ device loss when `jax.shard_map` exists), supervised into
    `directory` — produces a complete flight-recorder bundle. CPU-only
    by construction (the virtual 8-device mesh)."""
    import pathlib

    target = pathlib.Path(directory)
    if target.exists() and any(target.iterdir()):
        # A resumed drill satisfies every unit from the prior run's
        # chunks, dispatches nothing, and the armed faults never fire —
        # a green gate that verified nothing. Refuse rather than
        # silently no-op (and never delete a directory we didn't write).
        raise SystemExit(
            f"--drill target {directory!r} already exists and is not "
            "empty; point the drill at a fresh directory (a resumed "
            "drill exercises none of its faults)"
        )

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    from yuma_simulation_tpu.resilience import (
        Deadline,
        DeviceLossFault,
        FaultPlan,
        NaNFault,
        RetryPolicy,
        StallFault,
        SweepSupervisor,
        inject_faults,
    )
    from yuma_simulation_tpu.scenarios import get_cases
    from yuma_simulation_tpu.utils import setup_logging

    setup_logging()
    version = "Yuma 1 (paper)"
    cases = get_cases()[:4]
    policy = RetryPolicy(max_attempts_per_rung=2, backoff_base=0.0, seed=0)
    roomy = Deadline(120.0, grace_seconds=120.0)
    sharded = hasattr(jax, "shard_map")
    mesh = None
    lost = None
    if sharded:
        from yuma_simulation_tpu.parallel import make_mesh

        mesh = make_mesh()
        lost = mesh.devices.flat[1].id
    dispatch_kwargs = {"mesh": mesh} if mesh is not None else {}

    def supervisor(d, deadline):
        return SweepSupervisor(
            directory=d, unit_size=3, deadline=deadline, retry_policy=policy
        )

    # Warm-up passes under the roomy budget, exactly as the chaos tests
    # do: the tight chaos deadline must only ever kill the injected
    # hold, never a machine-speed-dependent cold compile — including
    # the NaN-operand and degraded-mesh jit variants.
    supervisor(None, roomy).run_batch(cases, version, **dispatch_kwargs)
    warm = {"nan": NaNFault(epoch=2, case=1)}
    if sharded:
        warm["device_loss"] = DeviceLossFault(device_id=lost)
    with inject_faults(FaultPlan(**warm)):
        supervisor(None, roomy).run_batch(cases, version, **dispatch_kwargs)

    # Post-shrink attempts get the retry grace; the hold must exceed
    # budget + grace wherever it lands (same arithmetic as the tests).
    plan_kwargs = dict(
        nan=NaNFault(epoch=2, case=1),
        truncate_chunks={1: 10},
    )
    if sharded:
        plan_kwargs["stall"] = StallFault(seconds=12.0, dispatches=1)
        plan_kwargs["device_loss"] = DeviceLossFault(device_id=lost)
        tight = Deadline(1.5, grace_seconds=6.0)
    else:
        plan_kwargs["stall"] = StallFault(seconds=1.0, dispatches=1)
        tight = Deadline(0.15, grace_seconds=60.0)
    with inject_faults(FaultPlan(**plan_kwargs)):
        out = supervisor(directory, tight).run_batch(
            cases, version, **dispatch_kwargs
        )
    report = out["report"]
    print(
        f"drill complete ({'sharded, 4 faults' if sharded else '3 faults'}):"
        f" stalls={report.stalls_killed} requeued={report.units_requeued}"
        f" shrinks={report.mesh_shrinks}"
        f" quarantined={report.lanes_quarantined}"
    )


def render_fleet_units(store, merged: list) -> list[str]:
    """The per-unit roster with the host that EXECUTED each unit
    inline (its accepted ``unit_ok`` record — previously the reader
    had to cross-reference lease tombstones by hand), plus lanes,
    steal generation, engine, and recovery counts."""
    last_ok: dict[int, dict] = {}
    for rec in merged:
        if rec.get("event") == "unit_ok" and "unit" in rec:
            last_ok[rec["unit"]] = rec
    try:
        num_units = store.manifest()["num_units"]
    except Exception:
        num_units = max(last_ok) + 1 if last_ok else 0
    if not num_units:
        return []
    lines = ["units (executing host inline):"]
    for unit in range(num_units):
        rec = last_ok.get(unit)
        if rec is None:
            lines.append(f"  unit {unit}: UNPUBLISHED")
            continue
        lanes = rec.get("lanes") or ["?", "?"]
        extras = []
        if rec.get("generation"):
            extras.append(f"gen={rec['generation']}")
        for key in ("stalls", "demotions", "mesh_shrinks", "canaries", "drifts"):
            if rec.get(key):
                extras.append(f"{key}={rec[key]}")
        if rec.get("quarantined"):
            extras.append(f"quarantined={len(rec['quarantined'])}")
        lines.append(
            f"  unit {unit} lanes=[{lanes[0]},{lanes[1]}) "
            f"host={rec.get('host', '?')} "
            f"engine={rec.get('engine', '?')}"
            + ("  " + " ".join(extras) if extras else "")
        )
    return lines


def render_stitched(store, bundles: dict) -> list[str]:
    """The ONE cross-process timeline: when several host bundles share
    a run (the propagated sweep-level trace), render their span UNION
    as a single tree — driver root down through every host's claims,
    units, attempts and engine rungs."""
    from yuma_simulation_tpu.telemetry.flight import merge_bundles

    hosts_by_run: dict[str, list] = {}
    for host_id, b in bundles.items():
        for rid in {s.get("run_id") for s in b.spans}:
            if rid:
                hosts_by_run.setdefault(rid, []).append(host_id)
    shared = {
        rid: hosts
        for rid, hosts in hosts_by_run.items()
        if len(hosts) >= 2
    }
    if not shared:
        return []
    union = merge_bundles(bundles.values(), directory=store.directory)
    lines = []
    for rid in sorted(shared):
        lines.append(
            f"--- stitched trace {rid} "
            f"(hosts: {', '.join(sorted(shared[rid]))}) ---"
        )
        lines.append(render_run(union, rid))
    return lines


def render_fleet(directory: str) -> str:
    """The fleet-store report: manifest + merged FleetHealthReport +
    the stitched cross-process trace (hosts sharing one propagated
    run render as ONE tree) + the per-unit executing-host roster +
    one per-host timeline section (each host's bundle through the
    existing single-run renderer)."""
    from yuma_simulation_tpu.fabric.health import (
        build_fleet_report,
        load_fleet_report,
        merged_ledger,
    )
    from yuma_simulation_tpu.fabric.store import FleetStore
    from yuma_simulation_tpu.telemetry.flight import load_bundle

    store = FleetStore(directory)
    manifest = store.manifest()
    report = build_fleet_report(store)
    published = load_fleet_report(store)
    lines = [
        f"fleet store: {store.directory}",
        f"fleet: {manifest.get('fleet')}  units: {manifest['num_units']}"
        f"  published: {report.units_published}",
        "fleet health: "
        + " ".join(
            f"{k}={getattr(report, k)}"
            for k in (
                "hosts_lost",
                "units_stolen",
                "units_abandoned",
                "units_duplicate",
                "stalls_killed",
                "engine_demotions",
                "mesh_shrinks",
                "lanes_quarantined",
                "canaries_run",
                "drift_events",
            )
        ),
        f"hosts: seen={list(report.hosts_seen)} "
        f"finished={list(report.hosts_finished)} "
        f"lost={list(report.hosts_lost)}",
    ]
    if manifest.get("trace"):
        lines.append(f"trace: {manifest['trace'].get('traceparent')}")
    if published is None:
        lines.append("fleet_report.json: not finalized (derived above)")
    for deg in report.degradations:
        lines.append(
            f"  host roster {deg.from_devices}->{deg.to_devices} "
            f"(lost {', '.join(deg.lost_device_ids)}: {deg.reason})"
        )
    units = render_fleet_units(store, merged_ledger(store))
    if units:
        lines.append("")
        lines.extend(units)
    bundles = {
        host_id: load_bundle(store.host_dir(host_id))
        for host_id in store.host_ids()
    }
    stitched = render_stitched(store, bundles)
    if stitched:
        lines.append("")
        lines.extend(stitched)
    for host_id in store.host_ids():
        lines.append("")
        lines.append(f"--- host {host_id} ---")
        lines.append(render(bundles[host_id], None))
    return "\n".join(lines)


def check_fleet_store(directory: str) -> list[str]:
    """The fleet ``--check`` gate: the fleet-level consistency check,
    the per-host bundle check for every FINISHED host (a SIGKILLed
    host never ran its bundle-publish finally — its ledger is the
    surviving record; demanding spans of the dead would be a false
    positive), and the STITCHED orphan-span gate — every span flagged
    as continuing a remote parent must resolve in some sibling host
    bundle; a bundle tampered to orphan a span fails here."""
    from yuma_simulation_tpu.fabric.health import (
        build_fleet_report,
        check_fleet,
    )
    from yuma_simulation_tpu.fabric.store import FleetStore
    from yuma_simulation_tpu.telemetry.flight import (
        check_bundle,
        check_stitched,
        load_bundle,
    )

    problems = list(check_fleet(directory))
    store = FleetStore(directory)
    report = build_fleet_report(store)
    bundles = {
        host_id: load_bundle(store.host_dir(host_id))
        for host_id in store.host_ids()
    }
    for host_id in report.hosts_finished:
        bundle = bundles.get(host_id) or load_bundle(
            store.host_dir(host_id)
        )
        problems.extend(f"host {host_id}: {p}" for p in check_bundle(bundle))
    problems.extend(check_stitched(bundles.values()))
    return problems


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="obsreport", description=__doc__.split("\n\n")[0]
    )
    parser.add_argument("directory", help="the supervised sweep directory")
    parser.add_argument(
        "--run", default=None, help="run_id to render (default: latest)"
    )
    parser.add_argument(
        "--check",
        action="store_true",
        help="consistency gate: exit 2 if any ledger record lacks a "
        "resolvable span or the report counts mismatch the ledger",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit the bundle as JSON"
    )
    parser.add_argument(
        "--follow",
        action="store_true",
        help="tail the LIVE bundle: poll-reload and print each newly "
        "landed span / ledger record / sealed segment / profile "
        "(Ctrl-C or --max-seconds to stop)",
    )
    parser.add_argument(
        "--interval", type=float, default=2.0,
        help="--follow poll interval in seconds (default 2)",
    )
    parser.add_argument(
        "--max-seconds", type=float, default=0.0,
        help="--follow duration bound in seconds (0 = until Ctrl-C)",
    )
    parser.add_argument(
        "--drill",
        action="store_true",
        help="run the deterministic chaos drill into DIRECTORY first "
        "(CI smoke; forces the CPU backend)",
    )
    parser.add_argument(
        "--fleet-drill",
        action="store_true",
        help="run the multiprocess pod-level fleet chaos drill into "
        "DIRECTORY first (>=3 simulated hosts: kill, lease tear, "
        "stall+NaN; CI smoke, CPU)",
    )
    args = parser.parse_args(argv)

    if args.drill:
        run_drill(args.directory)
    if args.fleet_drill:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from yuma_simulation_tpu.fabric.simhost import (
            run_drill as run_fleet_drill,
        )

        summary = run_fleet_drill(args.directory)
        report = summary["report"]
        print(
            "fleet drill complete (3 faulted hosts + oracle): "
            f"hosts_lost={list(report.hosts_lost)} "
            f"stolen={report.units_stolen} "
            f"stalls={report.stalls_killed} "
            f"quarantined={report.lanes_quarantined}"
        )
        # The drill's store is the fleet bundle to render/check below.
        args.directory = summary["store"]

    from yuma_simulation_tpu.fabric.store import is_fleet_store
    from yuma_simulation_tpu.telemetry.flight import check_bundle, load_bundle

    if args.follow:
        return follow(
            args.directory,
            interval=args.interval,
            max_seconds=args.max_seconds,
        )

    if is_fleet_store(args.directory):
        if args.json:
            from yuma_simulation_tpu.fabric.health import (
                build_fleet_report,
                merged_ledger,
            )
            from yuma_simulation_tpu.fabric.store import FleetStore

            store = FleetStore(args.directory)
            print(
                json.dumps(
                    {
                        "directory": str(store.directory),
                        "fleet": store.manifest(),
                        "report": build_fleet_report(store).to_json(),
                        "ledger": merged_ledger(store),
                    },
                    indent=2,
                    sort_keys=True,
                )
            )
        else:
            print(render_fleet(args.directory))
        if args.check:
            problems = check_fleet_store(args.directory)
            if problems:
                print("\nobsreport --check FAILED:", file=sys.stderr)
                for p in problems:
                    print(f"  - {p}", file=sys.stderr)
                return 2
            print("\nobsreport --check: fleet store is sound")
        return 0

    bundle = load_bundle(args.directory)
    if args.json:
        print(
            json.dumps(
                {
                    "directory": str(bundle.directory),
                    "runs": bundle.run_ids(),
                    "spans": bundle.spans,
                    "ledger": bundle.ledger,
                    "metrics": bundle.metrics,
                    "costs": bundle.costs,
                    "report": bundle.report,
                },
                indent=2,
                sort_keys=True,
            )
        )
    else:
        print(render(bundle, args.run))
    if args.check:
        problems = check_bundle(bundle)
        if problems:
            print("\nobsreport --check FAILED:", file=sys.stderr)
            for p in problems:
                print(f"  - {p}", file=sys.stderr)
            return 2
        print("\nobsreport --check: bundle is sound")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
