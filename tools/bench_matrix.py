"""Extended benchmark matrix over the BASELINE.json configurations.

`bench.py` prints the single driver-consumed headline line; this tool
covers the full config list (small subnet, correctness matrix, vmap'd
hyperparameter grid, large-subnet stress, batched varying-weights,
sharded Monte-Carlo) and prints one JSON line per config. Run on TPU
(default) or CPU (`jax.config jax_platforms`).

Methodology (r3, VERDICT r2 item 5): every line uses the same
discipline as bench.py — one warm-up run (compile), then the epoch count
is grown until a single run lasts >= MIN_SECONDS (the remote-tunnel
dispatch overhead is ~0.1 s/call; a sub-second window would skew short
configs), then best-of-REPS wall time. Each JSON line records the
methodology fields (`reps`, `times_s`, `epochs_timed`) so run-to-run
variance is visible per entry instead of a footnote. Epoch-loop lines go
through `epoch_impl="auto"` — the parity-safe path users get by default
— not a hand-picked implementation.
"""

import json
import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

# Runs as `python tools/bench_matrix.py` from the repo root; PYTHONPATH
# cannot be used instead — setting it breaks the TPU plugin registration
# in this environment.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from yuma_simulation_tpu.utils import enable_compilation_cache
from yuma_simulation_tpu.utils.timing import (
    DEFAULT_REPS as REPS,
    DEFAULT_TARGET_SECONDS as MIN_SECONDS,
    time_best,
)

enable_compilation_cache()

from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.variants import canonical_versions, variant_for_version
from yuma_simulation_tpu.ops.consensus import default_consensus_impl
from yuma_simulation_tpu.parallel import make_mesh, montecarlo_total_dividends
from yuma_simulation_tpu.scenarios import create_case, get_cases
from yuma_simulation_tpu.simulation.engine import (
    simulate_constant,
    simulate_scaled,
    simulate_scaled_batch,
)
from yuma_simulation_tpu.simulation.sweep import (
    config_grid,
    sweep_hyperparams,
    total_dividends_batch,
)

def _fetch(x):
    return np.asarray(x)  # forces execution on remote TPU runtimes


def _line(name, value, unit, extra=None):
    rec = {"config": name, "value": round(value, 2), "unit": unit}
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)


def _bench(run, n, unit_name, max_n=1 << 20, granularity=1):
    """The shared timing discipline (utils/timing.py): warm (compile),
    grow `n` iteratively until one timed run lasts >= MIN_SECONDS, then
    best-of-REPS. Returns (rate, methodology_dict)."""
    rate, n, times, cv = time_best(
        run, n, max_n=max_n, granularity=granularity
    )
    return rate, {
        "reps": REPS,
        "times_s": times,
        "cv": cv,
        unit_name: n,
        "method": f"best-of-{REPS}, >= {MIN_SECONDS}s per timed run",
    }


def bench_subnet(V, M, epochs, name):
    rng = np.random.default_rng(0)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 2 (Adrian-Fish)")
    # The documented shape-gated default (sorted below the compile-
    # pathology threshold — what Monte-Carlo's "auto" picks, and what r2
    # measured here), stated in the line label so the choice is visible.
    ci = default_consensus_impl(V, M)

    def run(n):
        _fetch(simulate_constant(W, S, n, cfg, spec, consensus_impl=ci)[0])

    rate, meta = _bench(run, epochs, "epochs_timed")
    meta["consensus_impl"] = ci
    _line(f"{name}, consensus={ci}", rate, "epochs/s", meta)


def bench_stress_varying(V=256, M=4096, epochs=16384):
    """The honest full-kernel stress line: weights vary every epoch
    (nothing hoistable), routed through epoch_impl="auto" — the path
    `simulate_scaled` picks for real users (the exact-MXU fused scan on
    TPU, XLA elsewhere)."""
    rng = np.random.default_rng(0)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    scales = jnp.asarray(
        1.0 + 1e-7 * np.arange(1 << 17, dtype=np.float32), jnp.float32
    )
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 2 (Adrian-Fish)")

    def run(n):
        _fetch(simulate_scaled(W, S, scales[:n], cfg, spec, epoch_impl="auto")[0])

    rate, meta = _bench(run, epochs, "epochs_timed", max_n=1 << 17)
    _line(
        f"stress {V}v x {M}m, weights varying every epoch "
        f"(Yuma 2, epoch_impl=auto)",
        rate,
        "epochs/s",
        meta,
    )


def bench_batched_varying(B=4, V=256, M=4096, epochs=4096):
    """Varying-weights work that fills the chip (VERDICT r2 item 3): B
    scenarios advanced together, routed through epoch_impl="auto". Since
    r5 this spec (Yuma 2 / EMA_PREV) rides the batched exact-MXU fused
    scan like the EMA family: the measured-temporary VMEM model admits
    the third resident mat at B=4 x 256x4096, and beyond that the
    kernel re-derives the previous normalized weights from
    W * scales[e-1] (bitwise the same values) instead of keeping the
    mat (r4 verdict item 3; previously auto fell back to the XLA vmap
    at ~26k scenario-epochs/s)."""
    rng = np.random.default_rng(2)
    W = jnp.asarray(rng.random((B, V, M)), jnp.float32)
    S = jnp.asarray(rng.random((B, V)) + 0.01, jnp.float32)
    scales = jnp.asarray(
        1.0 + 1e-7 * np.arange(1 << 16, dtype=np.float32), jnp.float32
    )
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 2 (Adrian-Fish)")

    def run(n):
        _fetch(
            simulate_scaled_batch(
                W, S, scales[:n], cfg, spec, epoch_impl="auto"
            )[0]
        )

    rate, meta = _bench(run, epochs, "epochs_timed", max_n=1 << 16)
    _line(
        f"batched varying-weights: {B} scenarios x {V}v x {M}m "
        f"(epoch_impl=auto; Yuma 2 / EMA_PREV on the batched exact-MXU "
        f"fused scan since r5)",
        B * rate,
        "scenario-epochs/s",
        meta,
    )


def bench_correctness_matrix():
    cases = get_cases()
    versions = canonical_versions()
    total_epochs = sum(c.num_epochs for c in cases) * len(versions)

    def run(n):
        # n is in sweeps of the whole matrix (the shapes are fixed by the
        # cases); epochs_timed reports n * total_epochs below.
        for _ in range(n):
            for version, params in versions:
                cfg = YumaConfig(yuma_params=params)
                total_dividends_batch(cases, version, cfg)

    rate, meta = _bench(run, 1, "matrix_sweeps_timed", max_n=64)
    meta["epochs_per_sweep"] = total_epochs
    _line(
        f"all {len(versions)} versions x {len(cases)} cases (correctness matrix)",
        rate * total_epochs,
        "epochs/s",
        meta,
    )


def bench_hyperparam_grid():
    configs, points = config_grid(
        bond_alpha=[0.025, 0.05, 0.1, 0.2],
        kappa=[0.3, 0.4, 0.5, 0.6],
        bond_penalty=[0.0, 0.5, 0.99, 1.0],
    )
    case = create_case("Case 2")

    def run(n):
        for _ in range(n):
            _fetch(sweep_hyperparams(case, "Yuma 1 (paper)", configs)["dividends"])

    rate, meta = _bench(run, 1, "grid_sweeps_timed", max_n=256)
    meta["grid_points"] = len(points)
    _line(
        f"{len(points)}-point bond_alpha x kappa x beta grid (vmap)",
        rate * len(points) * case.num_epochs,
        "epochs/s",
        meta,
    )


def bench_hyperparam_grid_fused(V=64, M=1024, epochs=2048):
    """The r3-verdict item-5 configuration: a hyperparameter grid through
    the FUSED batched scan as ONE dispatch — per-scenario [B]
    kappa/bond_penalty/bond_alpha vectors ride a VMEM operand
    (`fused_ema_scan` per_scenario_hp), vs the vmap'd XLA engine. The
    16-scenario batch at 64x1024 stays inside the VMEM residency budget
    (the 256x4096 stress shape fits only ~4 resident scenarios) and is
    the latency-bound regime where batching pays (DESIGN.md
    "Utilization")."""
    from yuma_simulation_tpu.simulation.sweep import sweep_scaled_fused

    configs, points = config_grid(
        bond_alpha=[0.05, 0.2],
        kappa=[0.4, 0.5],
        bond_penalty=[0.0, 0.5, 0.99, 1.0],
    )
    B = len(points)
    rng = np.random.default_rng(17)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    scales = jnp.asarray(
        1.0 + 1e-7 * np.arange(1 << 16, dtype=np.float32), jnp.float32
    )

    for impl in ("auto", "xla") if jax.default_backend() == "tpu" else ("xla",):
        def run(n):
            _fetch(
                sweep_scaled_fused(
                    W, S, scales[:n], configs, "Yuma 1 (paper)",
                    epoch_impl=impl,
                )[0]
            )

        rate, meta = _bench(run, epochs, "epochs_timed", max_n=1 << 16)
        meta["grid_points"] = B
        _line(
            f"{B}-point bond_alpha x kappa x beta grid, {V}v x {M}m "
            f"varying weights, ONE dispatch ({impl})",
            rate * B,
            "scenario-epochs/s",
            meta,
        )


def bench_batched_case_scan(B=2, E=256, V=256, M=4096):
    """The batched fused case scan (r4): true per-epoch weights for a
    scenario batch, one Pallas dispatch. At this shape the fused path
    is ~1.5x the XLA vmap; the tiny built-in suite is faster on XLA
    (auto's ~2^19-cell gate, DESIGN.md)."""
    from yuma_simulation_tpu.simulation.sweep import simulate_batch

    rng = np.random.default_rng(23)
    W = jnp.asarray(rng.random((B, E, V, M)), jnp.float32)
    S = jnp.asarray(rng.random((B, E, V)) + 0.01, jnp.float32)
    ri = jnp.full((B,), -1, jnp.int32)
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 1 (paper)")

    impls = ("auto", "xla") if jax.default_backend() == "tpu" else ("xla",)
    for impl in impls:
        def run(n):
            for _ in range(n):
                _fetch(
                    simulate_batch(
                        W, S, ri, ri, cfg, spec, epoch_impl=impl
                    )["dividends"]
                )

        rate, meta = _bench(run, 1, "passes_timed", max_n=64)
        _line(
            f"batched TRUE-weights case scan: {B} scenarios x {E}e x "
            f"{V}v x {M}m ({impl})",
            rate * B * E,
            "scenario-epochs/s",
            meta,
        )


def bench_montecarlo(num_scenarios=256, epochs=100, V=64, M=1024):
    mesh = make_mesh()
    keys = iter(range(1, 1 << 20))

    def run(n):
        # Fresh key per call so no run is a cache hit of the previous
        # one; n scales the scenario count.
        out = montecarlo_total_dividends(
            jax.random.key(next(keys)), n, epochs, V, M,
            "Yuma 1 (paper)", mesh=mesh,
        )
        assert np.isfinite(out).all()

    rate, meta = _bench(
        run,
        num_scenarios,
        "scenarios_timed",
        max_n=1 << 14,
        granularity=mesh.shape["data"],
    )
    meta["devices"] = len(jax.devices())
    _line(
        f"Monte-Carlo x {epochs} epochs, {V}v x {M}m "
        f"(shard_map, warm, impls=auto)",
        rate * epochs,
        "epochs/s",
        meta,
    )


def bench_batched_throughput(B=64, V=64, M=1024, epochs=500):
    """The constant-weights chip-filling regime: a vmap batch of B
    independent scenarios scanned for `epochs` epochs (the Monte-Carlo
    regime, consensus hoisted)."""
    rng = np.random.default_rng(1)
    W = jnp.asarray(rng.random((B, V, M)), jnp.float32)
    S = jnp.asarray(rng.random((B, V)) + 0.01, jnp.float32)
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 1 (paper)")

    from functools import partial

    @partial(jax.jit, static_argnames=("n",))
    def batch(W, S, n):
        return jax.vmap(
            lambda w, s: simulate_constant(
                w, s, n, cfg, spec,
                consensus_impl="sorted", hoist_invariant=True,
            )[0]
        )(W, S)

    def run(n):
        _fetch(batch(W, S, n))

    rate, meta = _bench(run, epochs, "epochs_timed", max_n=1 << 18)
    _line(
        f"batched constant-weights: {B} scenarios x {V}v x {M}m "
        f"(vmap, hoisted, warm)",
        B * rate,
        "scenario-epochs/s",
        meta,
    )


def main():
    bench_subnet(16, 256, 2048, "small subnet 16v x 256m (Yuma 2)")
    bench_subnet(256, 4096, 2048, "stress 256v x 4096m (Yuma 2, constant weights)")
    bench_stress_varying()
    if jax.default_backend() == "tpu":
        bench_batched_varying()
    bench_correctness_matrix()
    bench_hyperparam_grid()
    bench_hyperparam_grid_fused()
    if jax.default_backend() == "tpu":
        bench_batched_case_scan()
    bench_batched_throughput()
    bench_montecarlo()


if __name__ == "__main__":
    main()
