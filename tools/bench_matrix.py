"""Extended benchmark matrix over the BASELINE.json configurations.

`bench.py` prints the single driver-consumed headline line; this tool
covers the full config list (small subnet, correctness matrix, vmap'd
hyperparameter grid, large-subnet stress, sharded Monte-Carlo) and prints
one JSON line per config. Run on TPU (default) or CPU
(`jax.config jax_platforms`).
"""

import json
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

# Runs as `python tools/bench_matrix.py` from the repo root; PYTHONPATH
# cannot be used instead — setting it breaks the TPU plugin registration
# in this environment.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from yuma_simulation_tpu.utils import enable_compilation_cache

enable_compilation_cache()

from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.variants import canonical_versions, variant_for_version
from yuma_simulation_tpu.parallel import make_mesh, montecarlo_total_dividends
from yuma_simulation_tpu.scenarios import get_cases
from yuma_simulation_tpu.simulation.engine import simulate_constant, simulate_scaled
from yuma_simulation_tpu.simulation.sweep import config_grid, sweep_hyperparams, total_dividends_batch
from yuma_simulation_tpu.scenarios import create_case


def _fetch(x):
    return np.asarray(x)  # forces execution on remote TPU runtimes


def _line(name, value, unit, extra=None):
    rec = {"config": name, "value": round(value, 2), "unit": unit}
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)


def bench_subnet(V, M, epochs, name):
    rng = np.random.default_rng(0)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 2 (Adrian-Fish)")
    run = lambda: _fetch(  # noqa: E731
        simulate_constant(W, S, epochs, cfg, spec, consensus_impl="sorted")[0]
    )
    run()
    t0 = time.perf_counter()
    run()
    _line(name, epochs / (time.perf_counter() - t0), "epochs/s")


def bench_stress_varying(V=256, M=4096, epochs=16384):
    """The honest full-kernel stress line: weights vary every epoch
    (nothing hoistable), single-Pallas-program scan, long scan so the
    ~0.1 s/call tunnel dispatch overhead is amortized."""
    rng = np.random.default_rng(0)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    scales = jnp.asarray(
        1.0 + 1e-7 * np.arange(epochs, dtype=np.float32), jnp.float32
    )
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 2 (Adrian-Fish)")
    impl = "fused_scan_mxu" if jax.default_backend() == "tpu" else "xla"
    run = lambda: _fetch(  # noqa: E731
        simulate_scaled(W, S, scales, cfg, spec, epoch_impl=impl)[0]
    )
    run()
    t0 = time.perf_counter()
    run()
    dt = time.perf_counter() - t0
    _line(
        f"stress {V}v x {M}m, weights varying every epoch "
        f"(Yuma 2, {impl})",
        epochs / dt,
        "epochs/s",
        {"wall_s": round(dt, 2)},
    )


def bench_correctness_matrix():
    cases = get_cases()
    versions = canonical_versions()
    t0 = time.perf_counter()
    for version, params in versions:
        cfg = YumaConfig(yuma_params=params)
        total_dividends_batch(cases, version, cfg)
    dt = time.perf_counter() - t0
    total_epochs = sum(c.num_epochs for c in cases) * len(versions)
    _line(
        f"all {len(versions)} versions x {len(cases)} cases (correctness matrix)",
        total_epochs / dt,
        "epochs/s",
        {"wall_s": round(dt, 2)},
    )


def bench_hyperparam_grid():
    configs, points = config_grid(
        bond_alpha=[0.025, 0.05, 0.1, 0.2],
        kappa=[0.3, 0.4, 0.5, 0.6],
        bond_penalty=[0.0, 0.5, 0.99, 1.0],
    )
    case = create_case("Case 2")
    run = lambda: _fetch(  # noqa: E731
        sweep_hyperparams(case, "Yuma 1 (paper)", configs)["dividends"]
    )
    run()
    t0 = time.perf_counter()
    run()
    dt = time.perf_counter() - t0
    _line(
        f"{len(points)}-point bond_alpha x kappa x beta grid (vmap)",
        len(points) * case.num_epochs / dt,
        "epochs/s",
        {"grid_points": len(points), "wall_s": round(dt, 2)},
    )


def bench_montecarlo(num_scenarios=256, epochs=100, V=64, M=1024):
    mesh = make_mesh()

    def run(key):
        out = montecarlo_total_dividends(
            key, num_scenarios, epochs, V, M, "Yuma 1 (paper)", mesh=mesh
        )
        assert np.isfinite(out).all()

    run(jax.random.key(0))  # compile + warm
    t0 = time.perf_counter()
    run(jax.random.key(1))
    dt = time.perf_counter() - t0
    _line(
        f"Monte-Carlo {num_scenarios} scenarios x {epochs} epochs, "
        f"{V}v x {M}m (shard_map, warm)",
        num_scenarios * epochs / dt,
        "epochs/s",
        {"devices": len(jax.devices()), "wall_s": round(dt, 2)},
    )


def bench_batched_throughput(B=64, V=64, M=1024, epochs=500):
    """The number that fills the chip: a vmap batch of B independent
    constant-weight scenarios scanned for `epochs` epochs, scenario-epochs
    per second (the Monte-Carlo regime, consensus hoisted — single-run
    utilization on one small subnet is ~1-3% of peak; batching is how the
    chip earns its keep)."""
    rng = np.random.default_rng(1)
    W = jnp.asarray(rng.random((B, V, M)), jnp.float32)
    S = jnp.asarray(rng.random((B, V)) + 0.01, jnp.float32)
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 1 (paper)")

    @jax.jit
    def batch(W, S):
        return jax.vmap(
            lambda w, s: simulate_constant(
                w, s, epochs, cfg, spec,
                consensus_impl="sorted", hoist_invariant=True,
            )[0]
        )(W, S)

    _fetch(batch(W, S))
    t0 = time.perf_counter()
    _fetch(batch(W, S))
    dt = time.perf_counter() - t0
    _line(
        f"batched throughput: {B} scenarios x {V}v x {M}m x {epochs} epochs "
        f"(vmap, hoisted, warm)",
        B * epochs / dt,
        "scenario-epochs/s",
        {"wall_s": round(dt, 2)},
    )


def main():
    bench_subnet(16, 256, 2048, "small subnet 16v x 256m (Yuma 2)")
    bench_subnet(256, 4096, 2048, "stress 256v x 4096m (Yuma 2)")
    bench_stress_varying()
    bench_correctness_matrix()
    bench_hyperparam_grid()
    bench_batched_throughput()
    bench_montecarlo()


if __name__ == "__main__":
    main()
