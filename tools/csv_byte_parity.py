"""Byte-level parity of the rendered total-dividends CSV artifacts.

The reference's parity artifact is the `%.6f`-rendered
`total_dividends_b{beta}.csv` (reference
scripts/total_dividends_sheet_generator.py:64). The golden-surface tests
pin full-precision values to <1.5e-6, but a deviation of a few 1e-7 can
still flip the 6th rendered decimal on a knife-edge cell — so the
literal byte artifact needs its own gate:

    python tools/csv_byte_parity.py --out CSV_BYTE_PARITY.json

For each beta this renders the framework's CSV exactly as the CLI does
(x64 CPU parity mode, same `to_csv(index=False, float_format="%.6f")`)
and byte-compares it against the reference-rendered golden
(`tests/golden/total_dividends_b{beta}.csv`, generated from the torch
reference by tools/gen_goldens.py). Every differing cell is enumerated
and must satisfy BOTH:

- the rendered strings differ by exactly one unit in the 6th decimal
  (a rounding-boundary flip, not a numerical disagreement), and
- the framework's full-precision value is within the 1.5e-6 golden
  tolerance of the reference's full-precision value
  (`*_full.csv`, `%.17g`).

Any cell outside that class fails the run (exit 1) and the artifact's
status says so. `tests/unit/test_csv_byte_parity.py` runs the same
classification in-suite.
"""

from __future__ import annotations

import argparse
import csv
import datetime
import io
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
GOLDEN_DIR = os.path.join(REPO, "tests", "golden")

BETAS = ("0", "0.5", "0.99", "1.0")
FULL_TOL = 1.5e-6
#: One unit in the 6th rendered decimal, with float slack.
RENDER_UNIT = 1.0000001e-6


def pin_key(cell: dict) -> str:
    """The one spelling of a differing cell's identity in the pinned
    golden (tests/golden/csv_diff_cells.json) — shared by the --pin
    writer and the in-suite comparison so the two cannot drift."""
    return (
        f"{cell['case']}|{cell['column']}|{cell['rendered_mine']}|"
        f"{cell['rendered_reference']}"
    )


def render_csv_text(beta: str) -> tuple[str, "object"]:
    """The framework's rendered CSV for one beta, byte-for-byte as the
    CLI writes it, plus the unrendered DataFrame (full precision)."""
    import pandas as pd  # noqa: F401  (df.to_csv)

    from yuma_simulation_tpu.models.config import SimulationHyperparameters
    from yuma_simulation_tpu.models.variants import canonical_versions
    from yuma_simulation_tpu.reporting.tables import (
        generate_total_dividends_table,
    )
    from yuma_simulation_tpu.scenarios import get_cases

    hp = SimulationHyperparameters(bond_penalty=float(beta))
    df = generate_total_dividends_table(get_cases(), canonical_versions(), hp)
    buf = io.StringIO()
    df.to_csv(buf, index=False, float_format="%.6f")
    return buf.getvalue(), df


def f64_totals(beta: str):
    """The SAME total-dividends surface computed end-to-end in float64
    (every array f64; the XLA engine — the fused kernels are f32-only) —
    the oracle for classifying each rendered-byte flip: if the f64 run's
    %.6f rendering matches the reference's f32 rendering on a differing
    cell, the reference sits with the high-precision value and the
    framework's own f32 rounding produced the flip; if it matches the
    framework instead, the REFERENCE's f32 arithmetic is what crossed
    the rendering boundary — unreachable except by emulating torch's
    exact reduction orders. Returns a {(case, column): float} map shaped
    like the rendered table. Same computation as the shipped artifact:
    `generate_total_dividends_table` itself, parameterized by dtype."""
    import jax.numpy as jnp

    from yuma_simulation_tpu.models.config import SimulationHyperparameters
    from yuma_simulation_tpu.models.variants import canonical_versions
    from yuma_simulation_tpu.reporting.tables import (
        generate_total_dividends_table,
    )
    from yuma_simulation_tpu.scenarios import get_cases

    hp = SimulationHyperparameters(bond_penalty=float(beta))
    df = generate_total_dividends_table(
        get_cases(),
        canonical_versions(),
        hp,
        dtype=jnp.float64,
        epoch_impl="xla",
    )
    return {
        (row["Case"], col): float(row[col])
        for _, row in df.iterrows()
        for col in df.columns
        if col != "Case"
    }


def classify_beta(beta: str, oracle: dict | None = None) -> dict:
    """Byte-compare one beta's rendered CSV against the reference-rendered
    golden; enumerate and classify every differing cell. With `oracle`
    (the :func:`f64_totals` map), each differing cell additionally gets
    `f64_oracle`: which side of the flip the float64 end-to-end run
    lands on — "sides_with_reference" means the framework's own f32
    rounding produced the flip, "sides_with_framework" means the
    reference's f32 arithmetic crossed the rendering boundary (closable
    only by emulating torch's exact reduction orders), "neither" means
    the true value renders differently from both f32 runs."""
    mine_text, df = render_csv_text(beta)
    golden_path = os.path.join(GOLDEN_DIR, f"total_dividends_b{beta}.csv")
    with open(golden_path, newline="") as f:
        golden_text = f.read()
    if mine_text == golden_text:
        return {
            "beta": beta,
            "byte_identical": True,
            "differing_cells": [],
            "cells_total": sum(1 for _ in csv.reader(io.StringIO(mine_text))),
        }

    mine_rows = list(csv.reader(io.StringIO(mine_text)))
    golden_rows = list(csv.reader(io.StringIO(golden_text)))
    full_path = os.path.join(GOLDEN_DIR, f"total_dividends_b{beta}_full.csv")
    with open(full_path, newline="") as f:
        full_rows = list(csv.reader(f))
    assert len(mine_rows) == len(golden_rows) == len(full_rows)
    header = mine_rows[0]
    assert header == golden_rows[0]
    # Row alignment: cells are compared by index, so a reordered case
    # list must fail loudly here, not misattribute diffs across cases.
    for r in range(1, len(mine_rows)):
        assert mine_rows[r][0] == golden_rows[r][0] == full_rows[r][0], (
            f"row {r} case labels misaligned: {mine_rows[r][0]!r} vs "
            f"{golden_rows[r][0]!r} vs {full_rows[r][0]!r}"
        )

    diffs = []
    cells = 0
    for r in range(1, len(mine_rows)):
        for c in range(1, len(header)):
            cells += 1
            a, b = mine_rows[r][c], golden_rows[r][c]
            if a == b:
                continue
            mine_full = float(df.iloc[r - 1, c])
            ref_full = float(full_rows[r][c])
            full_dev = abs(mine_full - ref_full)
            rendered_dev = abs(float(a) - float(b))
            cell = {
                "case": mine_rows[r][0],
                "column": header[c],
                "rendered_mine": a,
                "rendered_reference": b,
                "full_precision_deviation": full_dev,
                "is_sixth_decimal_rounding": bool(
                    rendered_dev <= RENDER_UNIT and full_dev < FULL_TOL
                ),
            }
            if oracle is not None:
                key = (mine_rows[r][0], header[c])
                f64_rendered = "%.6f" % oracle[key]
                cell["f64_oracle"] = (
                    "sides_with_reference"
                    if f64_rendered == b
                    else "sides_with_framework"
                    if f64_rendered == a
                    else "neither"
                )
            diffs.append(cell)
    return {
        "beta": beta,
        "byte_identical": False,
        "cells_total": cells,
        "differing_cells": diffs,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--pin",
        default=None,
        help=(
            "write the exact differing-cell list (beta/case/column/"
            "rendered strings) to this JSON path — the in-suite pinned "
            "golden tests/unit/test_csv_byte_parity.py enforces; any "
            "cell appearing or vanishing later fails the suite"
        ),
    )
    args = ap.parse_args()

    # Parity mode: CPU + x64 (the Yuma-0 f64 quantization divide), the
    # same regime the goldens were generated in. config.update, not env:
    # the env snapshot is stale once sitecustomize has imported jax
    # (tests/conftest.py documents the same trap).
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    per_beta = [classify_beta(beta, oracle=f64_totals(beta)) for beta in BETAS]
    bad = [
        d
        for p in per_beta
        for d in p["differing_cells"]
        if not d["is_sixth_decimal_rounding"]
    ]
    oracle_counts: dict = {}
    for p in per_beta:
        for d in p["differing_cells"]:
            oracle_counts[d["f64_oracle"]] = (
                oracle_counts.get(d["f64_oracle"], 0) + 1
            )
    if args.pin:
        pinned = {
            p["beta"]: sorted(pin_key(d) for d in p["differing_cells"])
            for p in per_beta
        }
        with open(args.pin, "w") as f:
            json.dump(pinned, f, indent=1, sort_keys=True)
            f.write("\n")
    artifact = {
        "artifact": (
            "byte-level diff of the rendered total_dividends_b{beta}.csv "
            "artifacts (framework CLI rendering, x64 CPU parity mode) vs "
            "the reference-rendered goldens"
        ),
        "reference_renderer": (
            "/root/reference/scripts/total_dividends_sheet_generator.py:64 "
            "via tools/gen_goldens.py"
        ),
        "status": "ok" if not bad else "FAILED_cells_outside_rounding_class",
        "betas": list(BETAS),
        "cells_per_beta": per_beta[0]["cells_total"],
        "differing_cells_per_beta": {
            p["beta"]: len(p["differing_cells"]) for p in per_beta
        },
        "out_of_class_cells": len(bad),
        "f64_oracle_counts": oracle_counts,
        "per_beta": per_beta,
        "captured": datetime.date.today().isoformat(),
        "notes": (
            "Rendered CSVs are not byte-identical: a minority of cells "
            "(~10%) sit on a 6th-decimal rounding boundary where the "
            "framework's <1.5e-6 full-precision deviation flips the last "
            "rendered digit by one unit. Every differing cell is "
            "enumerated above and classified; is_sixth_decimal_rounding "
            "must be true for all (one rendered-unit string delta AND "
            "full-precision deviation < 1.5e-6). The f64_oracle field "
            "records which side of each flip an end-to-end float64 run "
            "lands on: cells siding with the framework are the "
            "REFERENCE's own f32 arithmetic crossing the rendering "
            "boundary (closable only by emulating torch's exact "
            "reduction orders, which the canonical consensus support "
            "test deliberately does not chase); cells siding with the "
            "reference are the framework's f32 order, the "
            "correspondingly irreducible mirror class; 'neither' cells "
            "have both f32 runs straddling the boundary around the true "
            "value. The exact cell list is pinned in "
            "tests/golden/csv_diff_cells.json and enforced cell-for-cell "
            "in-suite (drift within the class is impossible without a "
            "golden update)."
        ),
    }
    text = json.dumps(artifact, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(
        json.dumps(
            {
                k: artifact[k]
                for k in (
                    "status",
                    "differing_cells_per_beta",
                    "out_of_class_cells",
                )
            }
        )
    )
    if bad:
        sys.exit(f"FAIL: {len(bad)} differing cells outside the rounding class")


if __name__ == "__main__":
    main()
