"""jaxlint rule registry: one module per rule family.

Each family module exposes ``FAMILY`` (its name), ``RULES`` (code ->
(rule-name, summary)) and ``check(program, add)`` — ``add(unit, node,
code, message)`` records a raw finding; the driver applies per-line
suppressions afterwards. Codes are stable across refactors: JX0xx
trace/hygiene discipline (PR 2), JX1xx concurrency discipline, JX2xx
telemetry contracts (both PR 11), JX3xx wire/durable-record contracts
(the wirecheck family).
"""

from __future__ import annotations

from tools.jaxlint.rules import (
    concurrency,
    contracts,
    hygiene,
    tracing,
    wire,
)

#: Family modules in check order (deterministic output ordering).
FAMILIES = (tracing, hygiene, concurrency, contracts, wire)

#: The aggregate rule registry: code -> (name, summary).
RULES: dict[str, tuple[str, str]] = {}
#: code -> family name ("tracing"/"hygiene"/"concurrency"/"contracts").
RULE_FAMILY: dict[str, str] = {}
for _mod in FAMILIES:
    for _code, _entry in _mod.RULES.items():
        if _code in RULES:  # pragma: no cover — registry integrity
            raise RuntimeError(f"duplicate jaxlint rule code {_code}")
        RULES[_code] = _entry
        RULE_FAMILY[_code] = _mod.FAMILY
