"""Structural-hygiene rule family: numeric literals, API surface, carries.

These are the module-level rules of PR 2, unchanged in semantics:
JX005 (dtype-less numeric literals break the x64 bit-parity harness),
JX007 (the frozen v1 API surface must not import private modules), and
JX008 (engine scan carries must be the registered pytree dataclasses of
simulation/carry.py, never raw tuple/dict literals).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Optional

from tools.jaxlint.model import (
    dotted,
    is_literal_like,
    scope_nodes,
    target_names,
)
from tools.jaxlint.program import FileUnit, Program

FAMILY = "hygiene"

RULES = {
    "JX005": (
        "dtypeless-literal",
        "jnp.asarray/jnp.array of a numeric literal without an explicit "
        "dtype (bit-parity discipline: x64 mode silently promotes)",
    ),
    "JX007": (
        "private-import-in-v1",
        "public v1 API module imports a private (underscore-prefixed) "
        "module or name",
    ),
    "JX008": (
        "raw-scan-carry",
        "lax.scan carry built as a raw tuple/dict literal in engine.py; "
        "engine carries must be registered pytree dataclasses "
        "(simulation/carry.py)",
    ),
}


def _check_jx005(unit: FileUnit, call: ast.Call, add) -> None:
    fname = dotted(call.func) or ""
    if fname.split(".")[-1] not in ("asarray", "array"):
        return
    root = fname.split(".", 1)[0]
    if root not in ("jnp", "jax", "numpy", "np"):
        return
    if not call.args or not is_literal_like(call.args[0]):
        return
    has_dtype = len(call.args) >= 2 or any(
        kw.arg == "dtype" for kw in call.keywords
    )
    if not has_dtype:
        add(
            unit,
            call,
            "JX005",
            f"{fname}({ast.unparse(call.args[0])}) literal without an "
            "explicit dtype: under the x64 parity harness this "
            "silently promotes to f64 and breaks the bit-parity "
            "contract — pass dtype= explicitly",
        )


def _check_jx007(unit: FileUnit, node, add) -> None:
    if isinstance(node, ast.ImportFrom):
        mod = node.module or ""
        comps = [c for c in mod.split(".") if c]
        if any(c.startswith("_") and c != "__future__" for c in comps):
            add(
                unit,
                node,
                "JX007",
                f"v1 public API imports private module '{mod}': the "
                "frozen ApiVer surface must depend only on public "
                "modules",
            )
        for alias in node.names:
            if alias.name.startswith("_") and alias.name != "*":
                add(
                    unit,
                    node,
                    "JX007",
                    f"v1 public API imports private name "
                    f"'{alias.name}' from '{mod}'",
                )
    else:
        for alias in node.names:
            comps = alias.name.split(".")
            if any(c.startswith("_") and c != "__future__" for c in comps):
                add(
                    unit,
                    node,
                    "JX007",
                    f"v1 public API imports private module "
                    f"'{alias.name}'",
                )


def _is_container_literal(e: ast.expr) -> bool:
    if isinstance(e, (ast.Tuple, ast.List, ast.Dict)):
        return True
    if isinstance(e, ast.IfExp):
        return _is_container_literal(e.body) or _is_container_literal(
            e.orelse
        )
    if isinstance(e, ast.BinOp) and isinstance(e.op, ast.Add):
        return _is_container_literal(e.left) or _is_container_literal(
            e.right
        )
    return False


def _check_jx008(unit: FileUnit, add) -> None:
    """lax.scan inits in engine.py must not be raw tuple/dict pytrees."""
    scopes: list[ast.AST] = [unit.tree]
    scopes.extend(
        n
        for n in ast.walk(unit.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    )
    for fn in scopes:
        nodes = scope_nodes(fn)
        # name -> literal-RHS assignments, for resolving `carry0`
        literal_names: set[str] = set()
        for sub in nodes:
            rhs: Optional[ast.expr] = None
            names: list[str] = []
            if isinstance(sub, ast.Assign):
                rhs = sub.value
                names = [n for t in sub.targets for n in target_names(t)]
            elif isinstance(sub, ast.AugAssign) and isinstance(
                sub.target, ast.Name
            ):
                rhs = sub.value
                names = [sub.target.id]
            if rhs is not None and names and _is_container_literal(rhs):
                literal_names.update(names)
        for call in nodes:
            if not isinstance(call, ast.Call):
                continue
            fname = dotted(call.func) or ""
            if fname.split(".")[-1] != "scan":
                continue
            if not (fname.startswith("lax.") or "jax.lax" in fname):
                continue
            init = None
            if len(call.args) >= 2:
                init = call.args[1]
            else:
                for kw in call.keywords:
                    if kw.arg == "init":
                        init = kw.value
            if init is None:
                continue
            bad = _is_container_literal(init) or (
                isinstance(init, ast.Name) and init.id in literal_names
            )
            if bad:
                add(
                    unit,
                    call,
                    "JX008",
                    "lax.scan carry is a raw tuple/dict literal; "
                    "engine carries must be the registered pytree "
                    "dataclasses of simulation/carry.py (stable "
                    "field names, no positional-unpack drift)",
                )


def check(program: Program, add) -> None:
    for unit in program.units:
        if unit.tree is None:
            continue
        posix = Path(unit.path).as_posix()
        is_engine = posix.endswith("simulation/engine.py")
        is_v1 = "/v1/" in posix or posix.startswith("v1/")
        for node in ast.walk(unit.tree):
            if isinstance(node, ast.Call):
                _check_jx005(unit, node, add)
            if is_v1 and isinstance(node, (ast.Import, ast.ImportFrom)):
                _check_jx007(unit, node, add)
        if is_engine:
            _check_jx008(unit, add)
