"""Trace-discipline rule family: what may (not) happen under a jit trace.

Intra-scope rules run on every jit-decorated function (plus its nested
closures, traced as part of the same program) exactly as PR 2's
analyzer did. The interprocedural extension applies the SAME checks to
every function the whole-program layer proved *reachable by call* from
a jit scope (:mod:`tools.jaxlint.program`), with the callee's
per-parameter taint inferred from its call sites — so
``float(x.sum())`` one helper away from the jit boundary is JX002 now,
not invisible.

JX010 is the dedicated wall-clock / host-RNG rule: trace-time values
(`time.time()`, `datetime.now()`, `os.urandom`, `uuid4`, ...) bake into
the compiled artifact and silently replay on every cached execution.
Inside a literal jit body the long-standing JX006 impurity rule already
covers the classic spellings; JX010 adds (a) the extended catalog
(uuid/secrets/urandom/localtime) in literal jit bodies and (b) the
whole catalog in functions only *reachable* from a jit scope, where
JX006 deliberately stays quiet to keep its historical meaning stable.
"""

from __future__ import annotations

import ast

from tools.jaxlint.model import (
    Taint,
    all_params,
    annotation_mentions,
    calls_of,
    collect_taint,
    dotted,
)
from tools.jaxlint.program import FuncInfo, Program, TraceFacts

FAMILY = "tracing"

RULES = {
    "JX001": (
        "jit-static-completeness",
        "str/bool-typed parameter of a jitted function is not listed in "
        "static_argnames (it would be traced, or retrace per call)",
    ),
    "JX002": (
        "tracer-host-cast",
        "host cast (float()/int()/bool()/.item()/.tolist()/np.*) applied "
        "to a value reachable from a jitted function's traced params — "
        "including inside helpers the jit scope calls",
    ),
    "JX003": (
        "tracer-branch",
        "Python if/while branches on a traced value inside a jit-traced "
        "region (trace-time concretization; use lax.cond/jnp.where)",
    ),
    "JX004": (
        "fault-hook-in-trace",
        "fault-injection hook called inside a jit-traced region; hooks "
        "are host-level and self-guard with the is-tracing check — a "
        "traced call site would bake the arming state into the jit cache",
    ),
    "JX006": (
        "impure-in-trace",
        "impure host call (time.*/random.*/np.random.*/datetime.now) "
        "literally inside a jitted body; the value freezes into the trace",
    ),
    "JX009": (
        "device-put-in-trace",
        "jax.device_put inside a scan/jit-traced region: under trace it "
        "is a layout hint at best and a silent no-op at worst — the "
        "transfer the caller meant to overlap with compute never "
        "happens there; stage the buffer from the host-level dispatch "
        "driver (the bug class the double-buffered streaming rewrite "
        "removed)",
    ),
    "JX010": (
        "wallclock-rng-in-trace",
        "wall-clock or host-RNG call (time.*, datetime.*, os.urandom, "
        "uuid.*, secrets.*, random.*, np.random.*) in a function "
        "reachable from a jitted scope: the value is sampled once at "
        "trace time and silently replayed by every cached execution",
    ),
}

#: Host-level fault-injection hooks (resilience/faults.py). Inside a
#: traced body their is-tracing self-guard silently no-ops (or worse:
#: bakes the armed plan into a cached executable) — JX004.
FAULT_HOOKS = {
    "maybe_fail_fused_dispatch",
    "active_nan_fault",
    "mangle_chunk_file",
}

#: JX006's historical impurity catalog (kept stable): fires literally
#: inside jit bodies only.
_TIME_LEAVES = {
    "time", "perf_counter", "monotonic", "process_time",
    "time_ns", "perf_counter_ns",
}
_DATETIME_LEAVES = {"now", "today", "utcnow"}


def _is_jx006_impure(root: str, leaf: str, fname: str) -> bool:
    return (
        (root == "time" and leaf in _TIME_LEAVES)
        or (root == "random" and fname.startswith("random."))
        or fname.startswith(("np.random", "numpy.random"))
        or (root == "datetime" and leaf in _DATETIME_LEAVES)
    )


#: JX010's full wall-clock / host-RNG catalog: the JX006 classics plus
#: the spellings JX006 never covered.
_JX010_EXTRA_LEAVES = {"localtime", "gmtime", "ctime", "strftime"}


def _is_wallclock_rng(root: str, leaf: str, fname: str) -> bool:
    if _is_jx006_impure(root, leaf, fname):
        return True
    if root == "time" and leaf in _JX010_EXTRA_LEAVES:
        return True
    if root == "os" and leaf == "urandom":
        return True
    if root == "uuid" and leaf.startswith("uuid"):
        return True
    if root == "secrets":
        return True
    return False


def _default_for(fn, param: ast.arg):
    a = fn.args
    pos = [*a.posonlyargs, *a.args]
    if param in pos:
        idx = pos.index(param)
        off = len(pos) - len(a.defaults)
        if idx >= off:
            return a.defaults[idx - off]
        return None
    if param in a.kwonlyargs:
        return a.kw_defaults[a.kwonlyargs.index(param)]
    return None


class TraceScopeChecker:
    """Run the trace-discipline checks over ONE scope: either a jit
    body (``chain`` None) or a helper reachable from one (``chain`` is
    the seed call path, appended to every message)."""

    def __init__(self, info: FuncInfo, add, chain=None):
        self.info = info
        self.unit = info.unit
        self._add = add
        self.chain = chain

    def add(self, node: ast.AST, code: str, message: str) -> None:
        if self.chain:
            message = f"{message} [traced via {self.chain}]"
        self._add(self.unit, node, code, message)

    def run(self, traced_general: set, traced_direct: set) -> None:
        taint = Taint(set(traced_general), set(traced_direct))
        # two ordered passes ~= fixpoint for straight-line + one loop
        # level; nested-closure params are tracers by construction only
        # in LITERAL jit bodies (see collect_taint)
        nested = self.chain is None
        collect_taint(self.info.node.body, taint, taint_nested_params=nested)
        collect_taint(self.info.node.body, taint, taint_nested_params=nested)
        self._walk(self.info.node.body, taint)

    def _walk(self, stmts: list[ast.stmt], taint: Taint) -> None:
        for st in stmts:
            if isinstance(st, (ast.If, ast.While)):
                test = st.test
                if taint.tainted(test, direct=True):
                    kw = "if" if isinstance(st, ast.If) else "while"
                    self.add(
                        test,
                        "JX003",
                        f"Python `{kw}` branches on a traced value inside "
                        "a jit-traced region — this concretizes at trace "
                        "time; use jnp.where / lax.cond / lax.while_loop",
                    )
            for call in calls_of(st):
                self._check_call(call, taint)
            # recurse into nested function bodies — closures (scan
            # steps, vmapped lambdas-made-def) trace as part of this
            # same program. FunctionDefs inside nested suites are
            # reached through the suite recursion below.
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk(st.body, taint)
            if isinstance(st, (ast.If, ast.While, ast.For)):
                self._walk(st.body, taint)
                self._walk(st.orelse, taint)
            elif isinstance(st, ast.With):
                self._walk(st.body, taint)
            elif isinstance(st, ast.Try):
                self._walk(st.body, taint)
                for h in st.handlers:
                    self._walk(h.body, taint)
                self._walk(st.orelse, taint)
                self._walk(st.finalbody, taint)

    def _check_call(self, call: ast.Call, taint: Taint) -> None:
        fname = dotted(call.func) or ""
        leaf = fname.split(".")[-1]
        root = fname.split(".", 1)[0]

        # JX002: host casts on traced values
        if isinstance(call.func, ast.Name) and call.func.id in (
            "float",
            "int",
            "bool",
        ):
            if any(taint.tainted(a, direct=False) for a in call.args):
                self.add(
                    call,
                    "JX002",
                    f"{call.func.id}() applied to a traced value inside a "
                    "jit-traced region: concretizes the tracer (or silently "
                    "freezes a weak-typed constant into the trace)",
                )
        elif isinstance(call.func, ast.Attribute) and call.func.attr in (
            "item",
            "tolist",
        ):
            if taint.tainted(call.func.value, direct=False):
                self.add(
                    call,
                    "JX002",
                    f".{call.func.attr}() on a traced value inside a "
                    "jit-traced region: forces a host transfer at trace time",
                )
        elif root in ("np", "numpy") and not fname.startswith(
            ("np.random", "numpy.random")
        ):
            if any(
                taint.tainted(a, direct=False)
                for a in call.args
                if not isinstance(a, ast.Starred)
            ):
                self.add(
                    call,
                    "JX002",
                    f"{fname}() applied to a traced value inside a "
                    "jit-traced region: numpy concretizes tracers to host "
                    "arrays — use the jnp equivalent",
                )

        # JX009: host->device staging belongs to the host-level driver.
        if leaf == "device_put":
            self.add(
                call,
                "JX009",
                f"{fname}() inside a jit-traced region: under trace "
                "device_put is at best a layout constraint and never "
                "the async host->HBM transfer the call site implies — "
                "stage buffers from the host-level dispatch driver "
                "(engine.simulate_streamed's double-buffer is the "
                "pattern)",
            )

        # JX004: fault hooks must stay host-level
        if leaf in FAULT_HOOKS:
            self.add(
                call,
                "JX004",
                f"fault-injection hook '{leaf}' called inside a "
                "jit-traced region: the hook's is-tracing guard makes it "
                "a silent no-op under trace (and an armed plan would "
                "otherwise bake into the jit cache) — call it from the "
                "host-level dispatch wrapper instead",
            )

        # JX006 (literal jit bodies only — historical catalog) and
        # JX010 (extended catalog; the ONLY impurity code in reachable
        # helpers, so one call never double-reports).
        jx006 = _is_jx006_impure(root, leaf, fname)
        jx010 = _is_wallclock_rng(root, leaf, fname)
        if self.chain is None and jx006:
            self.add(
                call,
                "JX006",
                f"impure host call {fname}() inside a jitted body: the "
                "value freezes at trace time and silently re-used across "
                "calls — compute it on the host and pass it in (or use "
                "jax.random with explicit keys)",
            )
        elif jx010 and (self.chain is not None or not jx006):
            self.add(
                call,
                "JX010",
                f"wall-clock/host-RNG call {fname}() executes at trace "
                "time here: the sampled value bakes into the compiled "
                "artifact and replays on every cached execution — "
                "compute it on the host side of the dispatch and pass "
                "it in (or use jax.random with explicit keys)",
            )


def _check_jx001(unit, fn, static: set[str], add) -> None:
    for p in all_params(fn):
        if p.arg in static:
            continue
        str_like = annotation_mentions(p.annotation, {"str"})
        bool_like = annotation_mentions(p.annotation, {"bool"})
        default = _default_for(fn, p)
        str_default = isinstance(default, ast.Constant) and isinstance(
            default.value, str
        )
        if str_like or bool_like or str_default:
            kind = "str" if (str_like or str_default) else "bool"
            add(
                unit,
                p,
                "JX001",
                f"jitted function '{fn.name}' takes {kind}-typed param "
                f"'{p.arg}' that is not in static_argnames: it either "
                "fails to trace or silently keys a recompile per value",
            )


def check(program: Program, add) -> None:
    """Run the tracing family over the whole program."""
    for info in program.functions.values():
        if info.unit.tree is None:
            continue
        if info.is_jit:
            if info.jit_parseable:
                _check_jx001(info.unit, info.node, info.jit_static, add)
            traced = {p.arg for p in all_params(info.node)} - (
                info.jit_static or set()
            )
            TraceScopeChecker(info, add).run(set(traced), set(traced))
    # Nested jit scopes (functions jit-decorated inside another
    # function) are not in the program index; analyze them per unit so
    # the PR 2 behavior — every literal jit body is checked — holds.
    for unit in program.units:
        if unit.tree is None:
            continue
        indexed = {
            info.node
            for info in program.functions.values()
            if info.unit is unit
        }
        from tools.jaxlint.model import jit_decoration

        for node in ast.walk(unit.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node in indexed:
                continue
            jit = jit_decoration(node)
            if jit is None:
                continue
            static, parseable = jit
            stub = FuncInfo(
                qualname=f"{unit.module}.<nested>.{node.name}",
                module=unit.module,
                cls=None,
                node=node,
                unit=unit,
                jit_static=static,
                jit_parseable=parseable,
                self_guarded=False,
            )
            if parseable:
                _check_jx001(unit, node, static, add)
            traced = {p.arg for p in all_params(node)} - static
            TraceScopeChecker(stub, add).run(set(traced), set(traced))
    # Interprocedural: helpers the fixpoint proved reachable from a jit
    # scope, with their inferred per-param taint.
    for qual, facts in sorted(program.reached.items()):
        info = program.functions.get(qual)
        if info is None or info.unit.tree is None:
            continue
        checker = TraceScopeChecker(info, add, chain=facts.chain)
        checker.run(
            set(facts.tainted_general), set(facts.tainted_direct)
        )
