"""JX3xx: wire/durable-artifact contracts (the wirecheck family).

Thin jaxlint adapter over :mod:`tools.wirecheck` — extraction lives in
``tools/wirecheck/extract.py``, the gates in ``tools/wirecheck/gates.py``
— so the same producer/consumer index backs both this rule family (per
line suppressible, swept by ``--strict``) and the standalone
``python -m tools.wirecheck`` CLI that owns the schema lock.

The gates are whole-program by construction and self-gate on evidence:
JX301/JX303 stay silent for record kinds whose producers (or consumers)
are outside the analyzed roots, JX302 requires a ``ResilienceError``
hierarchy plus a serve tier in the program, and JX304 only runs when
the analyzed roots span the repo AND ``SCHEMAS.lock.json`` exists at
the repo root — so single-file fixture runs and partial-root
invocations never produce blind-spot noise.
"""

from __future__ import annotations

import json
from pathlib import Path

from tools.jaxlint.program import Program

FAMILY = "wire"

RULES = {
    "JX301": (
        "orphan-wire-read",
        "a consumer reads a record field that no producer in the "
        "program ever writes — the read is permanently None/KeyError "
        "and the report/score built from it is a hole",
    ),
    "JX302": (
        "unmapped-typed-error",
        "a ResilienceError subclass raised in a serve-reachable "
        "function has no HTTP-status mapping in the serve tier or no "
        "retryability class in classify_failure",
    ),
    "JX303": (
        "lease-annotation-closure",
        "a lease-annotation field is scored by claim ranking but "
        "never advertised by the worker heartbeat (or advertised but "
        "never read: dead wire weight)",
    ),
    "JX304": (
        "locked-schema-regression",
        "a field frozen in SCHEMAS.lock.json is no longer produced — "
        "wire schemas evolve additively; regenerate the lock with "
        "`python -m tools.wirecheck --update` only for deliberate "
        "removals",
    ),
}

#: the committed schema lock at the repo root (tools/jaxlint/rules/ ->
#: repo); tests monkeypatch-free: they exercise JX304 through the
#: wirecheck CLI's --lock instead.
_LOCK_PATH = Path(__file__).resolve().parents[3] / "SCHEMAS.lock.json"

#: JX304 needs the whole repo in view: a partial-root run would read
#: the lock, miss the producers living in the unanalyzed root, and
#: report every schema as regressed.
_REPO_ROOTS = ("yuma_simulation_tpu", "tools", "tests")


def _spans_repo(program: Program) -> bool:
    seen = set()
    for unit in program.units:
        posix = Path(unit.path).as_posix()
        for root in _REPO_ROOTS:
            if f"{root}/" in posix or posix.startswith(f"{root}/"):
                seen.add(root)
    return set(_REPO_ROOTS) <= seen


def _locked_schemas() -> dict | None:
    if not _LOCK_PATH.is_file():
        return None
    try:
        payload = json.loads(_LOCK_PATH.read_text(encoding="utf-8"))
    except (OSError, ValueError):  # pragma: no cover — unreadable lock
        return None
    schemas = payload.get("schemas")
    return schemas if isinstance(schemas, dict) else None


def check(program: Program, add) -> None:
    # Imported here, not at module top: rules/__init__ imports every
    # family eagerly, and wirecheck imports jaxlint.program — the lazy
    # import keeps the package graph acyclic at import time.
    from tools.wirecheck.extract import extract_index
    from tools.wirecheck.gates import run_gates

    index = extract_index(program)

    def anchor(line: int):
        class _A:
            lineno = line
            col_offset = 0

        return _A()

    def emit(unit, line, code, message):
        add(unit, anchor(line), code, message)

    locked = _locked_schemas() if _spans_repo(program) else None
    run_gates(program, index, emit, locked_schemas=locked)
