"""Concurrency-discipline rule family (JX1xx).

The serving tier, fleet fabric, telemetry registries, and resilience
watchdog together hold ~19 lock sites, all following three conventions
this family makes checkable:

- **JX101 guarded-field**: a field a class writes under ``with
  self._lock:`` in one method is part of that lock's protected state —
  reading or writing it bare in another method is a data race (torn
  reads of multi-step updates, lost increments). Guards are discovered
  structurally: any ``self.X`` assigned a ``threading.Lock`` /
  ``RLock`` / ``Condition``. ``__init__``/``__del__`` run before
  publication / at teardown and are exempt, as are methods whose name
  ends in ``_locked`` (the caller-holds-the-lock helper convention).
- **JX102 atomic-publish**: durable artifacts (flight bundles, fleet
  stores, ledgers, span/metric/numerics sinks) survive crashes only
  because every publish routes through
  ``utils.checkpoint.publish_atomic`` (temp + fsync + rename + dir
  fsync) or its append-side twin. A direct write-mode ``open()`` /
  ``write_text`` / ``write_bytes`` whose path names one of those
  artifacts is a torn-file bug waiting for a SIGKILL.
- **JX103 contextvar-across-thread**: ``contextvars`` do NOT flow into
  a bare ``threading.Thread`` — a target that reads the telemetry
  context (``log_event``/``current_span``/``ContextVar.get``) sees the
  defaults unless the spawner copies its context the way
  ``resilience/watchdog.py`` does (``ctx = contextvars.copy_context()``
  then ``target=lambda: ctx.run(worker)``) or the target activates its
  own run context.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Optional

from tools.jaxlint.model import dotted
from tools.jaxlint.program import FileUnit, Program

FAMILY = "concurrency"

RULES = {
    "JX101": (
        "guarded-field-bare-access",
        "field written under `with self.<lock>:` in one method is "
        "read/written without the lock in another method of the same "
        "class (torn reads / lost updates under the serve+fleet thread "
        "mix)",
    ),
    "JX102": (
        "non-atomic-durable-publish",
        "direct write-mode open()/write_text/write_bytes to a durable "
        "artifact path (bundle/store/ledger/span/metric/numerics/"
        "checkpoint); route through utils.checkpoint.publish_atomic or "
        "append_durable so a crash mid-write cannot tear the artifact",
    ),
    "JX103": (
        "contextvar-across-thread",
        "threading.Thread target reads contextvars (telemetry "
        "run/span identity) but the spawner passes a bare target; copy "
        "the caller's context (contextvars.copy_context().run — "
        "resilience/watchdog.py is the pattern) or activate a fresh "
        "run context inside the target",
    ),
}

#: Substrings of a path expression that mark it a durable artifact the
#: crash-safety contract covers (utils/checkpoint.py module docstring).
DURABLE_TOKENS = (
    "bundle", "store", "ledger", "spans", "numerics", "metrics",
    "slo", "manifest", "checkpoint", "lease", "report",
)

#: Write modes that truncate or create — the torn-artifact hazard.
#: ("r+"/"a" appends are covered too: a torn JSONL tail is exactly the
#: crash class the atomic/append-durable contract exists for.)
_WRITE_MODES = ("w", "wb", "w+", "wb+", "x", "xb", "a", "ab", "a+", "ab+")

#: Functions that READ the ambient contextvars context (telemetry
#: identity): calling one from a bare Thread target silently sees the
#: defaults instead of the spawner's run/span.
CONTEXT_READERS = {
    "log_event",
    "current_fields",
    "current_span",
    "current_run",
    "span",
}

#: Calls that ESTABLISH a context inside the target (so inheriting the
#: spawner's context is not relied upon): RunContext.activate(), a
#: ContextVar.set, or running under an explicitly copied context.
_CONTEXT_ESTABLISHERS = {"activate", "set", "run", "copy_context"}


# --------------------------------------------------------------------------
# JX101 guarded fields


def _guard_attrs(cls: ast.ClassDef) -> set[str]:
    """Attribute names assigned a threading.Lock/RLock/Condition
    anywhere in the class body."""
    guards: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or not isinstance(
            node.value, ast.Call
        ):
            continue
        fname = dotted(node.value.func) or ""
        leaf = fname.split(".")[-1]
        if leaf not in ("Lock", "RLock", "Condition"):
            continue
        for t in node.targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                guards.add(t.attr)
    return guards


def _is_guard_with(item: ast.withitem, guards: set[str]) -> bool:
    e = item.context_expr
    return (
        isinstance(e, ast.Attribute)
        and isinstance(e.value, ast.Name)
        and e.value.id == "self"
        and e.attr in guards
    )


def _self_field_accesses(
    method, guards: set[str]
) -> list[tuple[str, bool, bool, ast.Attribute]]:
    """(field, is_store, under_lock, node) for every ``self.X`` field
    access in ``method``. Method calls (``self.m()``) are skipped —
    only state, not behavior, is lock-protected. Nested functions are
    walked in the enclosing lock state (closures run where called; the
    common case here is a locked helper defined inline)."""
    out: list[tuple[str, bool, bool, ast.Attribute]] = []

    def walk(node: ast.AST, locked: bool) -> None:
        if isinstance(node, ast.With):
            inner = locked or any(
                _is_guard_with(i, guards) for i in node.items
            )
            for i in node.items:
                walk(i.context_expr, locked)
            for st in node.body:
                walk(st, inner)
            return
        if isinstance(node, ast.Call):
            # skip the callee attribute itself (self.m() is a method
            # access, not guarded state), but walk its args
            if isinstance(node.func, ast.Attribute):
                walk(node.func.value, locked)
            else:
                walk(node.func, locked)
            for a in node.args:
                walk(a, locked)
            for k in node.keywords:
                walk(k.value, locked)
            return
        if isinstance(node, ast.Attribute):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr not in guards
            ):
                is_store = isinstance(
                    node.ctx, (ast.Store, ast.Del)
                )
                out.append((node.attr, is_store, locked, node))
            walk(node.value, locked)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, locked)

    for st in method.body:
        walk(st, False)
    return out


def _check_jx101(unit: FileUnit, cls: ast.ClassDef, add) -> None:
    guards = _guard_attrs(cls)
    if not guards:
        return
    methods = [
        n
        for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    accesses: dict[str, list] = {}
    for m in methods:
        for field, is_store, locked, node in _self_field_accesses(
            m, guards
        ):
            accesses.setdefault(field, []).append(
                (m.name, is_store, locked, node)
            )
    for field, uses in sorted(accesses.items()):
        locked_writers = {
            m
            for m, is_store, locked, _ in uses
            if is_store and locked and m not in ("__init__", "__del__")
        }
        if not locked_writers:
            continue
        for m, is_store, locked, node in uses:
            if locked or m in ("__init__", "__del__"):
                continue
            if m.endswith("_locked"):
                continue  # caller-holds-the-lock helper convention
            verb = "written" if is_store else "read"
            add(
                unit,
                node,
                "JX101",
                f"'{cls.name}.{field}' is written under the lock in "
                f"{sorted(locked_writers)} but {verb} bare in "
                f"'{m}': lock-protected state must be accessed under "
                "the same lock in every method (or from a *_locked "
                "helper the caller locks around)",
            )


# --------------------------------------------------------------------------
# JX102 atomic publish


def _mentions_durable(expr: ast.expr) -> bool:
    try:
        text = ast.unparse(expr).lower()
    except Exception:  # pragma: no cover — unparse is total on 3.9+
        return False
    return any(tok in text for tok in DURABLE_TOKENS)


def _check_jx102(unit: FileUnit, add) -> None:
    posix = Path(unit.path).as_posix()
    if "yuma_simulation_tpu/" not in posix:
        return  # tools/tests write scratch files by design
    if posix.endswith("utils/checkpoint.py"):
        return  # the atomic primitive itself
    for node in ast.walk(unit.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted(node.func) or ""
        leaf = fname.split(".")[-1]
        if isinstance(node.func, ast.Name) and node.func.id == "open":
            mode = "r"
            if len(node.args) >= 2 and isinstance(
                node.args[1], ast.Constant
            ):
                mode = str(node.args[1].value)
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = str(kw.value.value)
            if mode not in _WRITE_MODES:
                continue
            if node.args and _mentions_durable(node.args[0]):
                add(
                    unit,
                    node,
                    "JX102",
                    f"open(..., {mode!r}) on a durable artifact path: a "
                    "crash between truncate and close leaves a torn "
                    "file the bundle readers must then survive — "
                    "publish through utils.checkpoint.publish_atomic "
                    "(whole-file) or append_durable (JSONL append)",
                )
        elif leaf in ("write_text", "write_bytes") and isinstance(
            node.func, ast.Attribute
        ):
            if _mentions_durable(node.func.value):
                add(
                    unit,
                    node,
                    "JX102",
                    f".{leaf}() on a durable artifact path writes "
                    "in place: a crash mid-write tears the artifact — "
                    "publish through utils.checkpoint.publish_atomic",
                )


# --------------------------------------------------------------------------
# JX103 contextvars across threads


def _contextvar_names(unit: FileUnit) -> set[str]:
    """Module-level names bound to contextvars.ContextVar(...)."""
    names: set[str] = set()
    for node in unit.tree.body:
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            fname = dotted(node.value.func) or ""
            if fname.split(".")[-1] == "ContextVar":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _reads_context(fn, cvars: set[str]) -> Optional[str]:
    """The first context-reading call inside ``fn``, or None."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted(node.func) or ""
        leaf = fname.split(".")[-1]
        if leaf in CONTEXT_READERS:
            return fname or leaf
        if leaf == "get" and isinstance(node.func, ast.Attribute):
            recv = dotted(node.func.value) or ""
            if recv in cvars:
                return f"{recv}.get"
    return None


def _establishes_context(fn) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            fname = dotted(node.func) or ""
            leaf = fname.split(".")[-1]
            if leaf in _CONTEXT_ESTABLISHERS:
                return True
    return False


def _local_functions(unit: FileUnit) -> dict:
    """Every function (any nesting) and method in the unit by bare name
    — Thread targets are resolved by name within the file."""
    out: dict = {}
    for node in ast.walk(unit.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def _check_jx103(unit: FileUnit, add) -> None:
    cvars = _contextvar_names(unit)
    locals_ = _local_functions(unit)
    for node in ast.walk(unit.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted(node.func) or ""
        if fname.split(".")[-1] != "Thread":
            continue
        target_expr = None
        for kw in node.keywords:
            if kw.arg == "target":
                target_expr = kw.value
        if target_expr is None:
            continue
        # resolve to a function defined in this file
        target_fn = None
        if isinstance(target_expr, ast.Name):
            target_fn = locals_.get(target_expr.id)
        elif isinstance(target_expr, ast.Attribute):
            d = dotted(target_expr) or ""
            if d.endswith(".run"):
                continue  # Thread(target=ctx.run, args=(worker,)) form
            if d.startswith(("self.", "cls.")):
                target_fn = locals_.get(target_expr.attr)
        elif isinstance(target_expr, ast.Lambda):
            # `lambda: ctx.run(worker)` — the watchdog pattern — is the
            # fix itself; any other lambda resolves to its called names.
            body = target_expr.body
            if isinstance(body, ast.Call):
                inner = dotted(body.func) or ""
                if inner.endswith(".run"):
                    continue
                if isinstance(body.func, ast.Name):
                    target_fn = locals_.get(body.func.id)
        if target_fn is None:
            continue
        reader = _reads_context(target_fn, cvars)
        if reader is None:
            continue
        if _establishes_context(target_fn):
            continue
        add(
            unit,
            node,
            "JX103",
            f"Thread target '{target_fn.name}' reads the ambient "
            f"contextvars context ({reader}) but is spawned bare: "
            "contextvars do not flow into a new thread, so telemetry "
            "records lose their run/span identity — copy the spawner's "
            "context (ctx = contextvars.copy_context(); "
            "target=lambda: ctx.run(worker)) as resilience/watchdog.py "
            "does, or activate a fresh run context inside the target",
        )


def check(program: Program, add) -> None:
    for unit in program.units:
        if unit.tree is None:
            continue
        for node in ast.walk(unit.tree):
            if isinstance(node, ast.ClassDef):
                _check_jx101(unit, node, add)
        _check_jx102(unit, add)
        _check_jx103(unit, add)
