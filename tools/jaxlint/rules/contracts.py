"""Telemetry-contract rule family (JX2xx).

The observability pipeline is only as trustworthy as its names: a
typo'd ``log_event`` name silently drops a recovery record out of every
``grep event=`` and every report tool; a metric name nobody declared
drifts away from the dashboards; an event nobody consumes is dead
weight that LOOKS monitored. PR 11 makes the names a checked contract:

- ``yuma_simulation_tpu/telemetry/registry.py`` *declares* every
  structured event name (``log_event`` + ledger appends) and every
  metric name, each with its expected consumers among the report tools
  (``obsreport``/``sloreport``/``driftreport``) or an explicit
  operator-only justification;
- **JX201** flags an emitted event name the registry does not declare
  (typos become lint failures at the emission site) — and non-literal
  event names, which defeat the registry entirely;
- **JX202** does the same for metric names at their
  ``counter()``/``gauge()``/``histogram()`` creation sites;
- **JX203** audits the registry itself: a declared consumer tool whose
  source never mentions the event name (the "looks monitored" lie), an
  operator-only event with no recorded justification, and — in
  whole-program runs over the package — a declared event no code ever
  emits.

The registry is parsed statically (stdlib ``ast``), never imported, so
jaxlint keeps running without jax installed. When the analyzed path set
does not include the registry (single-fixture runs), the real package
registry next to this tool is used.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Optional

from tools.jaxlint.model import dotted
from tools.jaxlint.program import FileUnit, Program

FAMILY = "contracts"

RULES = {
    "JX201": (
        "undeclared-event",
        "log_event / ledger event name is not declared in "
        "telemetry/registry.py (or is not a string literal, which "
        "defeats the registry): typo'd telemetry silently vanishes "
        "from every report tool",
    ),
    "JX202": (
        "undeclared-metric",
        "counter/gauge/histogram name is not declared in "
        "telemetry/registry.py: undeclared series drift away from "
        "dashboards and the obsreport reconciliation",
    ),
    "JX203": (
        "registry-drift",
        "registry entry out of sync with reality: a declared consumer "
        "tool never references the event, an operator-only event "
        "carries no justification, or (whole-package runs) no code "
        "emits a declared event",
    ),
}

REGISTRY_RELPATH = "yuma_simulation_tpu/telemetry/registry.py"
CONSUMER_TOOLS = ("obsreport", "sloreport", "driftreport", "incidentreport")

#: Call leaves that emit a structured event; the event name is the
#: FIRST positional arg unless listed in _SECOND_ARG_EMITTERS.
_EVENT_EMITTERS = {"log_event", "append", "_append_ledger"}
_SECOND_ARG_EMITTERS = {"log_event"}  # log_event(logger, event, ...)
_METRIC_LEAVES = {"counter", "gauge", "histogram"}


class RegistryView:
    """The statically-parsed registry: names, consumers, reasons, and
    the source lines declarations sit on (JX203 anchors there)."""

    def __init__(self) -> None:
        self.events: dict[str, dict] = {}
        self.metrics: dict[str, dict] = {}
        self.path: Optional[str] = None
        self.unit: Optional[FileUnit] = None

    @property
    def loaded(self) -> bool:
        return bool(self.events or self.metrics)


def _parse_spec_call(value: ast.expr) -> dict:
    """EventSpec(...)/MetricSpec(...) keywords, literally parseable."""
    out: dict = {"line": getattr(value, "lineno", 0)}
    if not isinstance(value, ast.Call):
        return out
    for i, arg in enumerate(value.args):
        if i == 0 and isinstance(arg, ast.Constant):
            out["summary"] = arg.value
    for kw in value.keywords:
        if kw.arg is None:
            continue
        v = kw.value
        if isinstance(v, ast.Constant):
            out[kw.arg] = v.value
        elif isinstance(v, (ast.Tuple, ast.List)):
            out[kw.arg] = tuple(
                el.value
                for el in v.elts
                if isinstance(el, ast.Constant)
            )
    return out


def _parse_registry_tree(tree: ast.Module, view: RegistryView) -> None:
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        names = [
            t.id for t in node.targets if isinstance(t, ast.Name)
        ]
        if not names or not isinstance(node.value, ast.Dict):
            continue
        target = None
        if "EVENTS" in names:
            target = view.events
        elif "METRICS" in names:
            target = view.metrics
        if target is None:
            continue
        for key, value in zip(node.value.keys, node.value.values):
            if isinstance(key, ast.Constant) and isinstance(
                key.value, str
            ):
                spec = _parse_spec_call(value)
                spec.setdefault("line", getattr(key, "lineno", 0))
                target[key.value] = spec


def load_registry(program: Program) -> RegistryView:
    view = RegistryView()
    for unit in program.units:
        if unit.tree is None:
            continue
        if Path(unit.path).as_posix().endswith(REGISTRY_RELPATH):
            view.path = unit.path
            view.unit = unit
            _parse_registry_tree(unit.tree, view)
            return view
    # Fall back to the real registry next to this tool (fixture runs).
    root = Path(__file__).resolve().parents[3]
    candidate = root / REGISTRY_RELPATH
    if candidate.exists():
        try:
            tree = ast.parse(candidate.read_text(encoding="utf-8"))
        except SyntaxError:
            return view
        view.path = str(candidate)
        _parse_registry_tree(tree, view)
    return view


def _call_leaf(call: ast.Call) -> str:
    """The called name's leaf, robust to call-valued receivers
    (``get_registry().counter`` has no dotted spelling)."""
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return ""


def _event_name_arg(call: ast.Call, leaf: str) -> Optional[ast.expr]:
    idx = 1 if leaf in _SECOND_ARG_EMITTERS else 0
    if len(call.args) > idx:
        return call.args[idx]
    for kw in call.keywords:
        if kw.arg == "event":
            return kw.value
    return None


def _literal_names(arg: ast.expr) -> Optional[list[str]]:
    """The literal event name(s) of an emission argument: a plain
    string, or a trace-resolvable choice between strings
    (``"slo_alert" if bad else "slo_recovered"``)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return [arg.value]
    if isinstance(arg, ast.IfExp):
        a = _literal_names(arg.body)
        b = _literal_names(arg.orelse)
        if a is not None and b is not None:
            return a + b
    return None


def _is_ledger_append(call: ast.Call) -> bool:
    """`x.append(...)` only counts as an event emission when the
    receiver is ledger-shaped — list.append must stay invisible."""
    if not isinstance(call.func, ast.Attribute):
        return False
    recv = (dotted(call.func.value) or "").lower()
    return "ledger" in recv


def _emitted_events(
    unit: FileUnit,
) -> list[tuple[ast.Call, Optional[list[str]]]]:
    """(call, literal-names-or-None) for every event emission site."""
    out: list[tuple[ast.Call, Optional[list[str]]]] = []
    for node in ast.walk(unit.tree):
        if not isinstance(node, ast.Call):
            continue
        leaf = _call_leaf(node)
        if leaf not in _EVENT_EMITTERS:
            continue
        if leaf == "append" and not _is_ledger_append(node):
            continue
        arg = _event_name_arg(node, leaf)
        if arg is None:
            continue
        names = _literal_names(arg)
        if names is not None:
            out.append((node, names))
        elif isinstance(arg, (ast.Name, ast.Attribute)):
            # a forwarded `event` parameter (the serve ledger shim) is
            # checked at ITS literal call sites, not here
            continue
        else:
            out.append((node, None))
    return out


def _in_package(unit: FileUnit) -> bool:
    return "yuma_simulation_tpu/" in Path(unit.path).as_posix()


def check(program: Program, add) -> None:
    # Whole-package runs MUST carry their own registry unit: analyzing
    # the package without one is the pre-PR-11 state where no telemetry
    # name was a checked contract at all. The real-registry fallback
    # inside load_registry exists for FIXTURE runs only (single files,
    # no package program), so gate on the unit census first.
    package_units = [
        u for u in program.units if u.tree is not None and _in_package(u)
    ]
    has_registry_unit = any(
        Path(u.path).as_posix().endswith(REGISTRY_RELPATH)
        for u in package_units
    )
    if len(package_units) > 1 and not has_registry_unit:
        anchor_unit = min(package_units, key=lambda u: u.path)
        add(
            anchor_unit,
            anchor_unit.tree,
            "JX203",
            "package analyzed without a telemetry registry: "
            f"{REGISTRY_RELPATH} must declare every event/metric "
            "name (the contract JX201/JX202 check emissions "
            "against)",
        )
        return
    registry = load_registry(program)
    if not registry.loaded:
        return  # fixture run, nothing to check against

    emitted_names: set[str] = set()
    for unit in program.units:
        if unit.tree is None:
            continue
        if registry.path is not None and unit.path == registry.path:
            continue
        if not _in_package(unit):
            continue  # tools/tests fixtures emit freely
        for call, names in _emitted_events(unit):
            if names is None:
                add(
                    unit,
                    call,
                    "JX201",
                    "event name is not a string literal: the registry "
                    "cross-check (and every `grep event=`) cannot see "
                    "dynamic names — emit a declared literal",
                )
                continue
            for name in names:
                if name not in registry.events:
                    add(
                        unit,
                        call,
                        "JX201",
                        f"event '{name}' is not declared in "
                        f"telemetry/registry.py: declare it (with its "
                        "consumers) or fix the typo",
                    )
                else:
                    emitted_names.add(name)
        for node in ast.walk(unit.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = _call_leaf(node)
            if leaf not in _METRIC_LEAVES:
                continue
            if not isinstance(node.func, ast.Attribute):
                continue  # bare gauge()/counter() builders elsewhere
            if not node.args:
                continue
            arg = node.args[0]
            if not (
                isinstance(arg, ast.Constant) and isinstance(arg.value, str)
            ):
                add(
                    unit,
                    node,
                    "JX202",
                    f"{leaf}() metric name is not a string literal: "
                    "the registry cross-check cannot see dynamic names",
                )
                continue
            if arg.value not in registry.metrics:
                add(
                    unit,
                    node,
                    "JX202",
                    f"metric '{arg.value}' is not declared in "
                    "telemetry/registry.py: declare it or fix the typo",
                )

    # -- JX203: audit the registry itself --------------------------------
    reg_unit = registry.unit
    if reg_unit is None:
        return  # fixture run against the fallback registry: emission
        # sites were checked above; the registry audit runs when the
        # registry file itself is in the analyzed set (package runs).
    root = Path(registry.path).resolve().parents[2]
    source_cache: dict[str, Optional[str]] = {}

    def consumer_source(consumer: str) -> tuple[Optional[str], str]:
        """(source-or-None, display-path) for a declared consumer: a
        report tool (tools/<name>.py) or a dotted package module."""
        if consumer in source_cache:
            return source_cache[consumer], _display(consumer)
        if consumer in CONSUMER_TOOLS:
            candidate = root / "tools" / f"{consumer}.py"
        else:
            candidate = (
                root
                / "yuma_simulation_tpu"
                / Path(*consumer.split("."))
            ).with_suffix(".py")
        src = (
            candidate.read_text(encoding="utf-8")
            if candidate.exists()
            else None
        )
        source_cache[consumer] = src
        return src, _display(consumer)

    def _display(consumer: str) -> str:
        if consumer in CONSUMER_TOOLS:
            return f"tools/{consumer}.py"
        return "yuma_simulation_tpu/" + "/".join(consumer.split(".")) + ".py"

    def anchor(line: int):
        class _A:
            lineno = line
            col_offset = 0

        return _A()

    def check_consumers(
        name: str, kind: str, spec: dict, *, require_reason: bool
    ) -> None:
        consumers = tuple(spec.get("consumers") or ())
        reason = spec.get("operator_reason") or ""
        line = int(spec.get("line", 0))
        if require_reason and not consumers and not reason:
            add(
                reg_unit,
                anchor(line),
                "JX203",
                f"{kind} '{name}' declares no consumer and no "
                "operator_reason: every telemetry name is either "
                "consumed by a tool/module or justified as "
                "operator-grep-only",
            )
        for consumer in consumers:
            src, display = consumer_source(consumer)
            if src is None:
                add(
                    reg_unit,
                    anchor(line),
                    "JX203",
                    f"{kind} '{name}' declares consumer '{consumer}' "
                    f"but {display} does not exist (expected one of "
                    f"{CONSUMER_TOOLS} or a dotted package module)",
                )
            elif f'"{name}"' not in src and f"'{name}'" not in src:
                add(
                    reg_unit,
                    anchor(line),
                    "JX203",
                    f"{kind} '{name}' declares consumer '{consumer}' "
                    f"but {display} never references the name: the "
                    f"{kind} LOOKS monitored and is not — wire the "
                    "consumer or re-declare it operator-only with a "
                    "reason",
                )

    package_run = sum(1 for u in program.units if _in_package(u)) > 1
    for name, spec in sorted(registry.events.items()):
        check_consumers(name, "event", spec, require_reason=True)
        if package_run and name not in emitted_names:
            add(
                reg_unit,
                anchor(int(spec.get("line", 0))),
                "JX203",
                f"event '{name}' is declared but no analyzed package "
                "code emits it: delete the entry or restore the "
                "emitter (dead registry entries hide real coverage "
                "gaps)",
            )
    # Metrics are consumed generically by construction — every
    # registered series lands in metrics.jsonl snapshots and the
    # Prometheus exposition — so only EXPLICIT consumer claims are
    # verified (an event, by contrast, vanishes into greps unless
    # someone reads it back by name).
    for name, spec in sorted(registry.metrics.items()):
        check_consumers(name, "metric", spec, require_reason=False)
