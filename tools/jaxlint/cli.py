"""jaxlint command line: ``python -m tools.jaxlint yuma_simulation_tpu/``.

Exit codes: 0 clean, 1 findings (with ``--strict`` also unused
suppressions), 2 usage errors. Output formats: ``human`` (one
``path:line:col: CODE message`` per finding) and ``json`` (a single
object with findings, suppression stats, and the rule registry — stable
for CI consumption). ``--artifact PATH`` additionally writes the JSON
payload to a file whatever the display format — the CI analysis lane
uploads it so a red lane ships its own findings list.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from tools.jaxlint.analyzer import RULE_FAMILY, RULES, analyze_paths


def _rule_set(spec: Optional[str], base: set[str]) -> set[str]:
    if not spec:
        return base
    requested = {c.strip() for c in spec.split(",") if c.strip()}
    unknown = requested - set(RULES)
    if unknown:
        raise SystemExit(
            f"jaxlint: unknown rule code(s): {', '.join(sorted(unknown))}"
        )
    return requested


def _json_payload(reports, findings, suppressed, unused) -> dict:
    return {
        "findings": [
            {
                "path": f.path,
                "line": f.line,
                "col": f.col,
                "code": f.code,
                "rule": RULES.get(f.code, ("parse-error",))[0],
                "family": RULE_FAMILY.get(f.code, "driver"),
                "message": f.message,
            }
            for f in findings
        ],
        "files_analyzed": len(reports),
        "suppressed": suppressed,
        "unused_suppressions": [
            {
                "path": p,
                "line": line,
                "codes": sorted(codes) if codes else None,
            }
            for p, line, codes in unused
        ],
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="jaxlint",
        description=(
            "whole-program TPU-discipline analyzer for "
            "yuma_simulation_tpu (tracer leaks through helper calls, "
            "recompilation triggers, lock/publish/contextvar "
            "discipline, telemetry-name contracts)"
        ),
    )
    parser.add_argument(
        "paths", nargs="*", default=["yuma_simulation_tpu"],
        help="files or directories to analyze (default: yuma_simulation_tpu)",
    )
    parser.add_argument(
        "--format", choices=("human", "json"), default="human",
        help="output format (default: human)",
    )
    parser.add_argument(
        "--select", metavar="CODES",
        help="comma-separated rule codes to run (default: all)",
    )
    parser.add_argument(
        "--ignore", metavar="CODES",
        help="comma-separated rule codes to skip",
    )
    parser.add_argument(
        "--strict", action="store_true",
        help="also fail on unused suppression comments (keeps "
        "`# jaxlint: disable` lines from rotting)",
    )
    parser.add_argument(
        "--artifact", metavar="PATH",
        help="also write the JSON findings payload to PATH (CI artifact)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule registry"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for code, (name, summary) in sorted(RULES.items()):
            family = RULE_FAMILY.get(code, "driver")
            print(f"{code} [{name}] ({family})\n    {summary}")
        return 0

    select = _rule_set(args.select, set(RULES))
    select -= _rule_set(args.ignore, set())
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(
            f"jaxlint: path does not exist: {', '.join(missing)}",
            file=sys.stderr,
        )
        return 2
    reports = analyze_paths(args.paths, select)
    if not reports:
        print("jaxlint: no python files found under "
              f"{', '.join(args.paths)}", file=sys.stderr)
        return 2

    # Parse failures ride the findings list as JX999 entries, so they
    # share the findings exit path below.
    findings = [f for r in reports for f in r.findings]
    suppressed = sum(r.suppressed for r in reports)
    unused = [
        (r.path, line, codes)
        for r in reports
        for line, codes in r.unused_suppressions
    ]
    payload = _json_payload(reports, findings, suppressed, unused)
    if args.artifact:
        with open(args.artifact, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")

    if args.format == "json":
        print(json.dumps(payload, indent=2))
    else:
        for f in findings:
            print(f.render())
        for p, line, codes in unused:
            label = ",".join(sorted(codes)) if codes else "all"
            print(
                f"{p}:{line}:0: note: unused suppression ({label})"
                + (" [--strict fails on this]" if not args.strict else "")
            )
        summary = (
            f"jaxlint: {len(findings)} finding(s) in {len(reports)} "
            f"file(s), {suppressed} suppressed, {len(unused)} unused "
            "suppression(s)"
        )
        print(summary)

    if findings:
        return 1
    if args.strict and unused:
        return 1
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
