"""jaxlint core: AST analysis of the project's JAX/TPU discipline.

This is a *project-specific* analyzer (stdlib ``ast`` only, no third-party
deps): the rules encode conventions that keep this codebase's ~20
``jax.jit`` entry points cheap to compile, parity-exact, and safe to run
on a remote TPU — conventions no generic linter checks. Each rule has a
stable code so violations can be suppressed per line with

    some_call()  # jaxlint: disable=JX003

(comma-separate several codes; a bare ``# jaxlint: disable`` suppresses
every rule on that line). Suppressions that never fire are reported so
they cannot rot silently (``--strict`` fails on them).

Taint model
-----------
Rules JX002/JX003/JX004/JX006 analyze "jit scopes": functions decorated
``@jax.jit`` / ``@partial(jax.jit, ...)`` plus every function *defined
inside* one (closures traced as part of the same program). Parameters
not listed in ``static_argnames`` are traced values; taint flows through
assignments, attribute/subscript access, and arithmetic. Two refinements
keep the model honest for this codebase:

- attribute reads that are static even on tracers (``.shape``, ``.dtype``,
  ``.ndim``, ...) and the config pytree's registered *static* fields
  (``liquid_alpha``, ``consensus_precision``, the quantile overrides —
  models/config.py) do not propagate taint;
- ``x is None`` / ``x is not None`` tests are pytree-structure checks,
  resolved at trace time, and never taint a branch.

For the *control-flow* rule (JX003) a function-call boundary stops taint
unless the callee is rooted at ``jnp``/``jax``/``lax`` (those return
tracers; anything else is a host predicate — e.g. the engine-eligibility
gates — whose result is a Python bool computed from static structure).
The *host-cast* rule (JX002) keeps taint flowing through every call, so
``float(jnp.sum(x))`` is still flagged.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Iterable, Optional

#: Stable rule registry: code -> (name, summary). The summaries are what
#: ``--list-rules`` and the JSON output carry.
RULES: dict[str, tuple[str, str]] = {
    "JX001": (
        "jit-static-completeness",
        "str/bool-typed parameter of a jitted function is not listed in "
        "static_argnames (it would be traced, or retrace per call)",
    ),
    "JX002": (
        "tracer-host-cast",
        "host cast (float()/int()/bool()/.item()/.tolist()/np.*) applied "
        "to a value reachable from a jitted function's traced params",
    ),
    "JX003": (
        "tracer-branch",
        "Python if/while branches on a traced value inside a jitted body "
        "(trace-time concretization; use lax.cond/jnp.where)",
    ),
    "JX004": (
        "fault-hook-in-trace",
        "fault-injection hook called inside a jit-traced body; hooks are "
        "host-level and self-guard with the is-tracing check — a traced "
        "call site would bake the arming state into the jit cache",
    ),
    "JX005": (
        "dtypeless-literal",
        "jnp.asarray/jnp.array of a numeric literal without an explicit "
        "dtype (bit-parity discipline: x64 mode silently promotes)",
    ),
    "JX006": (
        "impure-in-trace",
        "impure host call (time.*/random.*/np.random.*/datetime.now) "
        "inside a jitted body; the value freezes into the trace",
    ),
    "JX007": (
        "private-import-in-v1",
        "public v1 API module imports a private (underscore-prefixed) "
        "module or name",
    ),
    "JX008": (
        "raw-scan-carry",
        "lax.scan carry built as a raw tuple/dict literal in engine.py; "
        "engine carries must be registered pytree dataclasses "
        "(simulation/carry.py)",
    ),
    "JX009": (
        "device-put-in-trace",
        "jax.device_put inside a scan/jit-traced region: under trace it "
        "is a layout hint at best and a silent no-op at worst — the "
        "transfer the caller meant to overlap with compute never "
        "happens there; stage the buffer from the host-level dispatch "
        "driver (the bug class the double-buffered streaming rewrite "
        "removed)",
    ),
}

#: Parse failures are reported under this pseudo-code (not suppressible).
PARSE_ERROR_CODE = "JX999"

#: Attribute reads that yield host/static values even on traced arrays.
TRACE_STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "itemsize", "aval", "sharding",
    # Registered *static* (aux-data) fields of the config pytrees —
    # models/config.py marks exactly these with metadata=dict(static=True).
    "liquid_alpha", "consensus_precision",
    "override_consensus_high", "override_consensus_low",
}

#: Host-level fault-injection hooks (resilience/faults.py). Inside a
#: traced body their is-tracing self-guard silently no-ops (or worse:
#: bakes the armed plan into a cached executable) — JX004.
FAULT_HOOKS = {
    "maybe_fail_fused_dispatch",
    "active_nan_fault",
    "mangle_chunk_file",
}

#: Call roots that return traced values (taint passes through for the
#: control-flow rule); everything else is treated as a host predicate.
TRACER_CALL_ROOTS = {"jnp", "jax", "lax", "float", "int", "bool"}

_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable(?:=(?P<codes>[A-Z0-9,\s]+?))?\s*(?:#|$)"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a concrete source location."""

    path: str
    line: int
    col: int
    code: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"


@dataclasses.dataclass
class FileReport:
    """Per-file analysis result (post-suppression)."""

    path: str
    findings: list[Finding]
    suppressed: int
    #: suppression comments that matched no finding: (line, codes-or-None)
    unused_suppressions: list[tuple[int, Optional[frozenset[str]]]]


# --------------------------------------------------------------------------
# small AST helpers


def dotted(node: ast.expr) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _const_str_set(node: ast.expr) -> Optional[set[str]]:
    """static_argnames value -> set of names, when literally parseable."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out: set[str] = set()
        for el in node.elts:
            if not (isinstance(el, ast.Constant) and isinstance(el.value, str)):
                return None
            out.add(el.value)
        return out
    return None


def _is_literal_like(node: ast.expr) -> bool:
    """Numeric-literal-ish first args of asarray: ``-1``, ``2.0``,
    ``float("nan")``, ``1 / 3``, ``[0, 1]``."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float, complex, bool))
    if isinstance(node, ast.UnaryOp):
        return _is_literal_like(node.operand)
    if isinstance(node, ast.BinOp):
        return _is_literal_like(node.left) and _is_literal_like(node.right)
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_literal_like(el) for el in node.elts)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id in ("float", "int", "bool") and not node.keywords:
            return all(isinstance(a, ast.Constant) for a in node.args)
    return False


def _annotation_mentions(ann: Optional[ast.expr], names: set[str]) -> bool:
    """Whether an annotation expression contains one of ``names`` as a
    bare Name (handles ``bool``, ``bool | None``, ``Optional[str]``)."""
    if ann is None:
        return False
    return any(
        isinstance(n, ast.Name) and n.id in names for n in ast.walk(ann)
    )


def jit_decoration(
    fn: ast.FunctionDef | ast.AsyncFunctionDef,
) -> Optional[tuple[set[str], bool]]:
    """``(static_argnames, parseable)`` when ``fn`` is jit-wrapped, else
    None. ``parseable`` is False when a static_argnames expression was
    present but not a literal (analysis then skips JX001 for safety)."""
    for dec in fn.decorator_list:
        target: Optional[ast.expr] = None
        call: Optional[ast.Call] = None
        if isinstance(dec, ast.Call):
            fname = dotted(dec.func) or ""
            if fname == "jit" or fname.endswith(".jit"):
                target, call = dec.func, dec  # @jax.jit(static_argnames=...)
            elif fname == "partial" or fname.endswith(".partial"):
                if dec.args:
                    inner = dotted(dec.args[0]) or ""
                    if inner == "jit" or inner.endswith(".jit"):
                        target, call = dec.args[0], dec
        else:
            fname = dotted(dec) or ""
            if fname == "jit" or fname.endswith(".jit"):
                target = dec
        if target is None:
            continue
        static: set[str] = set()
        parseable = True
        if call is not None:
            for kw in call.keywords:
                if kw.arg == "static_argnames":
                    got = _const_str_set(kw.value)
                    if got is None:
                        parseable = False
                    else:
                        static |= got
                elif kw.arg == "static_argnums":
                    # positions -> names, when literally parseable
                    params = _all_params(fn)
                    nums: list[int] = []
                    ok = True
                    vals = (
                        kw.value.elts
                        if isinstance(kw.value, (ast.Tuple, ast.List))
                        else [kw.value]
                    )
                    for el in vals:
                        if isinstance(el, ast.Constant) and isinstance(
                            el.value, int
                        ):
                            nums.append(el.value)
                        else:
                            ok = False
                    if ok:
                        for i in nums:
                            if 0 <= i < len(params):
                                static.add(params[i].arg)
                    else:
                        parseable = False
        return static, parseable
    return None


def _all_params(fn) -> list[ast.arg]:
    a = fn.args
    return [*a.posonlyargs, *a.args, *a.kwonlyargs]


# --------------------------------------------------------------------------
# taint engine


class _Taint:
    """Two-level taint over local names of one jit scope.

    ``general`` propagates through every expression form (JX002's view:
    any value *reachable from* a traced param). ``direct`` additionally
    stops at host-call boundaries (JX003's view: values that are
    syntactically tracers, not results of host predicates)."""

    def __init__(self, general: set[str], direct: set[str]):
        self.general = general
        self.direct = direct

    # -- expression evaluation ------------------------------------------

    def tainted(self, e: ast.expr, *, direct: bool) -> bool:
        names = self.direct if direct else self.general
        return self._eval(e, names, direct)

    def _eval(self, e: ast.expr, names: set[str], direct: bool) -> bool:
        if isinstance(e, ast.Name):
            return e.id in names
        if isinstance(e, ast.Constant) or isinstance(e, ast.Lambda):
            return False
        if isinstance(e, ast.Attribute):
            if e.attr in TRACE_STATIC_ATTRS:
                return False
            return self._eval(e.value, names, direct)
        if isinstance(e, ast.Compare):
            # `x is None` / `x is not None`: pytree-structure checks,
            # static at trace time regardless of x.
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                return False
            return self._eval(e.left, names, direct) or any(
                self._eval(c, names, direct) for c in e.comparators
            )
        if isinstance(e, ast.Call):
            root = (dotted(e.func) or "").split(".", 1)[0]
            if direct and root not in TRACER_CALL_ROOTS:
                # A method call on a traced object (x.sum(), W.mean())
                # returns a tracer; a free-function call is a host
                # predicate boundary (engine eligibility gates etc.).
                if isinstance(e.func, ast.Attribute):
                    return self._eval(e.func.value, names, direct)
                return False  # host-predicate boundary
            args_tainted = any(
                self._eval(a, names, direct)
                for a in e.args
                if not isinstance(a, ast.Starred)
            ) or any(
                self._eval(k.value, names, direct) for k in e.keywords
            ) or any(
                self._eval(a.value, names, direct)
                for a in e.args
                if isinstance(a, ast.Starred)
            )
            return args_tainted or self._eval(e.func, names, direct)
        children = [
            c for c in ast.iter_child_nodes(e) if isinstance(c, ast.expr)
        ]
        return any(self._eval(c, names, direct) for c in children)

    # -- statement-order propagation ------------------------------------

    def absorb_assignment(self, targets: Iterable[ast.expr], value: ast.expr):
        gen = self._eval(value, self.general, False)
        dire = self._eval(value, self.direct, True)
        if not (gen or dire):
            return
        for t in targets:
            for name in _target_names(t):
                if gen:
                    self.general.add(name)
                if dire:
                    self.direct.add(name)


def _target_names(t: ast.expr) -> list[str]:
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        return [n for el in t.elts for n in _target_names(el)]
    if isinstance(t, ast.Starred):
        return _target_names(t.value)
    return []  # attribute/subscript stores don't bind new names


def _collect_taint(stmts: list[ast.stmt], taint: _Taint) -> None:
    """One ordered pass folding assignments (and nested-function params)
    into the taint sets. Callers run it twice for a cheap fixpoint."""
    for st in stmts:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for p in _all_params(st):
                taint.general.add(p.arg)
                taint.direct.add(p.arg)
            _collect_taint(st.body, taint)
        elif isinstance(st, ast.Assign):
            taint.absorb_assignment(st.targets, st.value)
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            taint.absorb_assignment([st.target], st.value)
        elif isinstance(st, ast.AugAssign):
            taint.absorb_assignment([st.target], st.value)
        elif isinstance(st, ast.NamedExpr):  # pragma: no cover (stmt ctx)
            taint.absorb_assignment([st.target], st.value)
        elif isinstance(st, ast.For):
            taint.absorb_assignment([st.target], st.iter)
            _collect_taint(st.body, taint)
            _collect_taint(st.orelse, taint)
        elif isinstance(st, (ast.While, ast.If)):
            _collect_taint(st.body, taint)
            _collect_taint(st.orelse, taint)
        elif isinstance(st, ast.With):
            for item in st.items:
                if item.optional_vars is not None:
                    taint.absorb_assignment(
                        [item.optional_vars], item.context_expr
                    )
            _collect_taint(st.body, taint)
        elif isinstance(st, ast.Try):
            _collect_taint(st.body, taint)
            for h in st.handlers:
                _collect_taint(h.body, taint)
            _collect_taint(st.orelse, taint)
            _collect_taint(st.finalbody, taint)
        # walrus targets inside plain expressions
        for sub in ast.walk(st):
            if isinstance(sub, ast.NamedExpr):
                taint.absorb_assignment([sub.target], sub.value)


# --------------------------------------------------------------------------
# per-file analysis


class FileAnalyzer:
    def __init__(self, path: str, tree: ast.Module, select: set[str]):
        self.path = path
        self.tree = tree
        self.select = select
        self.findings: list[Finding] = []
        posix = Path(path).as_posix()
        self.is_engine = posix.endswith("simulation/engine.py")
        self.is_v1 = "/v1/" in posix or posix.startswith("v1/")

    def add(self, node: ast.AST, code: str, message: str) -> None:
        if code in self.select:
            self.findings.append(
                Finding(
                    self.path,
                    getattr(node, "lineno", 0),
                    getattr(node, "col_offset", 0),
                    code,
                    message,
                )
            )

    def run(self) -> list[Finding]:
        self._module_rules()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                jit = jit_decoration(node)
                if jit is not None:
                    static, parseable = jit
                    if parseable:
                        self._check_jx001(node, static)
                    self._check_jit_body(node, static)
        return self.findings

    # -- module-level rules ---------------------------------------------

    def _module_rules(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                self._check_jx005(node)
            if self.is_v1 and isinstance(node, (ast.Import, ast.ImportFrom)):
                self._check_jx007(node)
        if self.is_engine:
            self._check_jx008()

    def _check_jx005(self, call: ast.Call) -> None:
        fname = dotted(call.func) or ""
        if fname.split(".")[-1] not in ("asarray", "array"):
            return
        root = fname.split(".", 1)[0]
        if root not in ("jnp", "jax", "numpy", "np"):
            return
        if not call.args or not _is_literal_like(call.args[0]):
            return
        has_dtype = len(call.args) >= 2 or any(
            kw.arg == "dtype" for kw in call.keywords
        )
        if not has_dtype:
            self.add(
                call,
                "JX005",
                f"{fname}({ast.unparse(call.args[0])}) literal without an "
                "explicit dtype: under the x64 parity harness this "
                "silently promotes to f64 and breaks the bit-parity "
                "contract — pass dtype= explicitly",
            )

    def _check_jx007(self, node: ast.Import | ast.ImportFrom) -> None:
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            comps = [c for c in mod.split(".") if c]
            if any(
                c.startswith("_") and c != "__future__" for c in comps
            ):
                self.add(
                    node,
                    "JX007",
                    f"v1 public API imports private module '{mod}': the "
                    "frozen ApiVer surface must depend only on public "
                    "modules",
                )
            for alias in node.names:
                if alias.name.startswith("_") and alias.name != "*":
                    self.add(
                        node,
                        "JX007",
                        f"v1 public API imports private name "
                        f"'{alias.name}' from '{mod}'",
                    )
        else:
            for alias in node.names:
                comps = alias.name.split(".")
                if any(
                    c.startswith("_") and c != "__future__" for c in comps
                ):
                    self.add(
                        node,
                        "JX007",
                        f"v1 public API imports private module "
                        f"'{alias.name}'",
                    )

    @staticmethod
    def _scope_nodes(scope) -> list[ast.AST]:
        """Nodes belonging to ``scope``'s own body, stopping at nested
        function definitions (each is analyzed as its own scope — this
        keeps scan reports single and literal-name resolution local)."""
        body = scope.body if hasattr(scope, "body") else []
        out: list[ast.AST] = []
        stack = list(body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            out.append(node)
            stack.extend(ast.iter_child_nodes(node))
        return out

    def _check_jx008(self) -> None:
        """lax.scan inits in engine.py must not be raw tuple/dict pytrees."""
        scopes: list[ast.AST] = [self.tree]
        scopes.extend(
            n
            for n in ast.walk(self.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        )
        for fn in scopes:
            nodes = self._scope_nodes(fn)
            # name -> literal-RHS assignments, for resolving `carry0`
            literal_names: set[str] = set()
            for sub in nodes:
                rhs: Optional[ast.expr] = None
                names: list[str] = []
                if isinstance(sub, ast.Assign):
                    rhs = sub.value
                    names = [n for t in sub.targets for n in _target_names(t)]
                elif isinstance(sub, ast.AugAssign) and isinstance(
                    sub.target, ast.Name
                ):
                    rhs = sub.value
                    names = [sub.target.id]
                if rhs is not None and names and self._is_container_literal(rhs):
                    literal_names.update(names)
            for call in nodes:
                if not isinstance(call, ast.Call):
                    continue
                fname = dotted(call.func) or ""
                if fname.split(".")[-1] != "scan":
                    continue
                if not (fname.startswith("lax.") or "jax.lax" in fname):
                    continue
                init = None
                if len(call.args) >= 2:
                    init = call.args[1]
                else:
                    for kw in call.keywords:
                        if kw.arg == "init":
                            init = kw.value
                if init is None:
                    continue
                bad = self._is_container_literal(init) or (
                    isinstance(init, ast.Name) and init.id in literal_names
                )
                if bad:
                    self.add(
                        call,
                        "JX008",
                        "lax.scan carry is a raw tuple/dict literal; "
                        "engine carries must be the registered pytree "
                        "dataclasses of simulation/carry.py (stable "
                        "field names, no positional-unpack drift)",
                    )

    @staticmethod
    def _is_container_literal(e: ast.expr) -> bool:
        if isinstance(e, (ast.Tuple, ast.List, ast.Dict)):
            return True
        if isinstance(e, ast.IfExp):
            return FileAnalyzer._is_container_literal(
                e.body
            ) or FileAnalyzer._is_container_literal(e.orelse)
        if isinstance(e, ast.BinOp) and isinstance(e.op, ast.Add):
            return FileAnalyzer._is_container_literal(
                e.left
            ) or FileAnalyzer._is_container_literal(e.right)
        return False

    # -- jit-scope rules -------------------------------------------------

    def _check_jx001(self, fn, static: set[str]) -> None:
        for p in _all_params(fn):
            if p.arg in static:
                continue
            str_like = _annotation_mentions(p.annotation, {"str"})
            bool_like = _annotation_mentions(p.annotation, {"bool"})
            default = self._default_for(fn, p)
            str_default = isinstance(default, ast.Constant) and isinstance(
                default.value, str
            )
            if str_like or bool_like or str_default:
                kind = "str" if (str_like or str_default) else "bool"
                self.add(
                    p,
                    "JX001",
                    f"jitted function '{fn.name}' takes {kind}-typed param "
                    f"'{p.arg}' that is not in static_argnames: it either "
                    "fails to trace or silently keys a recompile per value",
                )

    @staticmethod
    def _default_for(fn, param: ast.arg) -> Optional[ast.expr]:
        a = fn.args
        pos = [*a.posonlyargs, *a.args]
        if param in pos:
            idx = pos.index(param)
            off = len(pos) - len(a.defaults)
            if idx >= off:
                return a.defaults[idx - off]
            return None
        if param in a.kwonlyargs:
            return a.kw_defaults[a.kwonlyargs.index(param)]
        return None

    def _check_jit_body(self, fn, static: set[str]) -> None:
        params = {p.arg for p in _all_params(fn)}
        traced = params - static
        taint = _Taint(set(traced), set(traced))
        # two ordered passes ~= fixpoint for straight-line + one loop level
        _collect_taint(fn.body, taint)
        _collect_taint(fn.body, taint)
        self._walk_jit(fn.body, taint)

    def _walk_jit(self, stmts: list[ast.stmt], taint: _Taint) -> None:
        for st in stmts:
            if isinstance(st, (ast.If, ast.While)):
                test = st.test
                if taint.tainted(test, direct=True):
                    kw = "if" if isinstance(st, ast.If) else "while"
                    self.add(
                        test,
                        "JX003",
                        f"Python `{kw}` branches on a traced value inside "
                        "a jitted body — this concretizes at trace time; "
                        "use jnp.where / lax.cond / lax.while_loop",
                    )
            for call in self._calls_of(st):
                self._check_call_in_trace(call, taint)
            # recurse into nested suites (incl. nested function bodies —
            # they trace as part of this program)
            for child in ast.iter_child_nodes(st):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._walk_jit(child.body, taint)
            if isinstance(st, (ast.If, ast.While, ast.For)):
                self._walk_jit(st.body, taint)
                self._walk_jit(st.orelse, taint)
            elif isinstance(st, ast.With):
                self._walk_jit(st.body, taint)
            elif isinstance(st, ast.Try):
                self._walk_jit(st.body, taint)
                for h in st.handlers:
                    self._walk_jit(h.body, taint)
                self._walk_jit(st.orelse, taint)
                self._walk_jit(st.finalbody, taint)

    @staticmethod
    def _calls_of(st: ast.stmt) -> list[ast.Call]:
        """Call nodes belonging to this statement, not descending into
        nested function bodies (walked separately) or nested suites."""
        exprs: list[ast.expr] = []
        for field_, value in ast.iter_fields(st):
            if field_ in ("body", "orelse", "finalbody", "handlers"):
                continue
            if isinstance(value, ast.expr):
                exprs.append(value)
            elif isinstance(value, list):
                exprs.extend(v for v in value if isinstance(v, ast.expr))
        calls: list[ast.Call] = []
        for e in exprs:
            for sub in ast.walk(e):
                if isinstance(sub, ast.Call):
                    calls.append(sub)
                elif isinstance(sub, ast.Lambda):
                    for inner in ast.walk(sub.body):
                        if isinstance(inner, ast.Call):
                            calls.append(inner)
        # dedupe while keeping order (lambda bodies walked twice above)
        seen: set[int] = set()
        out = []
        for c in calls:
            if id(c) not in seen:
                seen.add(id(c))
                out.append(c)
        return out

    def _check_call_in_trace(self, call: ast.Call, taint: _Taint) -> None:
        fname = dotted(call.func) or ""
        leaf = fname.split(".")[-1]
        root = fname.split(".", 1)[0]

        # JX002: host casts on traced values
        if isinstance(call.func, ast.Name) and call.func.id in (
            "float",
            "int",
            "bool",
        ):
            if any(taint.tainted(a, direct=False) for a in call.args):
                self.add(
                    call,
                    "JX002",
                    f"{call.func.id}() applied to a traced value inside a "
                    "jitted body: concretizes the tracer (or silently "
                    "freezes a weak-typed constant into the trace)",
                )
        elif isinstance(call.func, ast.Attribute) and call.func.attr in (
            "item",
            "tolist",
        ):
            if taint.tainted(call.func.value, direct=False):
                self.add(
                    call,
                    "JX002",
                    f".{call.func.attr}() on a traced value inside a "
                    "jitted body: forces a host transfer at trace time",
                )
        elif root in ("np", "numpy") and not fname.startswith(
            ("np.random", "numpy.random")
        ):
            if any(
                taint.tainted(a, direct=False)
                for a in call.args
                if not isinstance(a, ast.Starred)
            ):
                self.add(
                    call,
                    "JX002",
                    f"{fname}() applied to a traced value inside a jitted "
                    "body: numpy concretizes tracers to host arrays — use "
                    "the jnp equivalent",
                )

        # JX009: host->device staging belongs to the host-level driver.
        # Any device_put spelling (jax.device_put, a bare alias import)
        # inside a jit scope is flagged: traced, it cannot start the
        # async transfer the call site exists for.
        if leaf == "device_put":
            self.add(
                call,
                "JX009",
                f"{fname}() inside a jit-traced region: under trace "
                "device_put is at best a layout constraint and never "
                "the async host->HBM transfer the call site implies — "
                "stage buffers from the host-level dispatch driver "
                "(engine.simulate_streamed's double-buffer is the "
                "pattern)",
            )

        # JX004: fault hooks must stay host-level
        if leaf in FAULT_HOOKS:
            self.add(
                call,
                "JX004",
                f"fault-injection hook '{leaf}' called inside a jitted "
                "body: the hook's is-tracing guard makes it a silent no-op "
                "under trace (and an armed plan would otherwise bake into "
                "the jit cache) — call it from the host-level dispatch "
                "wrapper instead",
            )

        # JX006: impure host calls
        impure = (
            (root == "time" and leaf in (
                "time", "perf_counter", "monotonic", "process_time",
                "time_ns", "perf_counter_ns",
            ))
            or (root == "random" and fname.startswith("random."))
            or fname.startswith(("np.random", "numpy.random"))
            or (root == "datetime" and leaf in ("now", "today", "utcnow"))
        )
        if impure:
            self.add(
                call,
                "JX006",
                f"impure host call {fname}() inside a jitted body: the "
                "value freezes at trace time and silently re-used across "
                "calls — compute it on the host and pass it in (or use "
                "jax.random with explicit keys)",
            )


# --------------------------------------------------------------------------
# suppression handling + entry points


def _parse_suppressions(
    source: str,
) -> dict[int, Optional[frozenset[str]]]:
    """line -> codes (None = all rules) for ``# jaxlint: disable=...``."""
    out: dict[int, Optional[frozenset[str]]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        codes = m.group("codes")
        if codes is None:
            out[i] = None
        else:
            out[i] = frozenset(
                c.strip() for c in codes.split(",") if c.strip()
            )
    return out


def analyze_source(
    source: str,
    path: str = "<string>",
    select: Optional[set[str]] = None,
) -> FileReport:
    """Analyze one file's source text. ``select`` limits the rule set."""
    select = select if select is not None else set(RULES)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return FileReport(
            path,
            [
                Finding(
                    path,
                    exc.lineno or 0,
                    exc.offset or 0,
                    PARSE_ERROR_CODE,
                    f"could not parse file: {exc.msg}",
                )
            ],
            0,
            [],
        )
    findings = FileAnalyzer(path, tree, select).run()
    suppressions = _parse_suppressions(source)
    kept: list[Finding] = []
    used_lines: set[int] = set()
    suppressed = 0
    for f in findings:
        codes = suppressions.get(f.line, ...)
        if codes is ... or (codes is not None and f.code not in codes):
            kept.append(f)
        else:
            suppressed += 1
            used_lines.add(f.line)
    # A suppression is only provably unused when every rule it names
    # actually ran: under --select/--ignore a suppression for a
    # de-selected rule may be load-bearing in the full run, so it is
    # neither used nor unused here.
    def _judgeable(codes: Optional[frozenset[str]]) -> bool:
        if codes is None:
            return select >= set(RULES)
        return codes <= select

    unused = [
        (line, codes)
        for line, codes in sorted(suppressions.items())
        if line not in used_lines and _judgeable(codes)
    ]
    kept.sort(key=lambda f: (f.line, f.col, f.code))
    return FileReport(path, kept, suppressed, unused)


def iter_python_files(paths: Iterable[str]) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        pp = Path(p)
        if pp.is_dir():
            out.extend(sorted(pp.rglob("*.py")))
        elif pp.suffix == ".py":
            out.append(pp)
    return out


def analyze_paths(
    paths: Iterable[str], select: Optional[set[str]] = None
) -> list[FileReport]:
    reports = []
    for file in iter_python_files(paths):
        source = file.read_text(encoding="utf-8")
        reports.append(analyze_source(source, str(file), select))
    return reports
