"""jaxlint driver: whole-program analysis of the JAX/TPU discipline.

This is a *project-specific* analyzer (stdlib ``ast`` only, no
third-party deps): the rules encode conventions that keep this
codebase's ~20 ``jax.jit`` entry points cheap to compile, parity-exact,
and safe to run threaded next to the serve/fleet tiers — conventions no
generic linter checks. PR 11 grew the per-function pass of PR 2 into a
whole-program suite:

- :mod:`tools.jaxlint.model` — findings, suppressions, the taint engine;
- :mod:`tools.jaxlint.program` — module/function index, import
  resolution, call graph, and the traced-reachability fixpoint;
- :mod:`tools.jaxlint.rules` — one module per rule family: ``tracing``
  (JX001-JX010), ``hygiene`` (JX005/7/8), ``concurrency`` (JX1xx),
  ``contracts`` (JX2xx).

Violations are suppressed per line with ``# jaxlint: disable=JXnnn``
(see :mod:`tools.jaxlint.model`); unused suppressions fail ``--strict``.

This module keeps the stable public API every caller of PR 2 used:
:data:`RULES`, :func:`analyze_source`, :func:`analyze_paths`,
:class:`Finding`, :class:`FileReport`.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable, Optional

from tools.jaxlint.model import (  # noqa: F401  (public API re-exports)
    PARSE_ERROR_CODE,
    FileReport,
    Finding,
    apply_suppressions,
)
from tools.jaxlint.program import FileUnit, Program, parse_unit
from tools.jaxlint.rules import FAMILIES, RULE_FAMILY, RULES  # noqa: F401


def analyze_units(
    units: list[FileUnit], select: Optional[set[str]] = None
) -> list[FileReport]:
    """Run every selected rule family over ``units`` as ONE program
    (interprocedural facts flow across all of them), then fold each
    file's suppression comments into its report."""
    select = select if select is not None else set(RULES)
    program = Program(units)

    def add(unit: FileUnit, node, code: str, message: str) -> None:
        if code in select:
            unit.add(node, code, message)

    for family in FAMILIES:
        if any(RULE_FAMILY[c] == family.FAMILY for c in select):
            family.check(program, add)
    return [
        apply_suppressions(
            unit.path, unit.source, unit.findings, select, set(RULES)
        )
        for unit in units
    ]


def analyze_source(
    source: str,
    path: str = "<string>",
    select: Optional[set[str]] = None,
) -> FileReport:
    """Analyze one file's source text. ``select`` limits the rule set.

    Single-file programs still get the interprocedural pass (helper
    calls resolve within the file); cross-module facts need
    :func:`analyze_paths`.
    """
    return analyze_units([parse_unit(source, path)], select)[0]


def iter_python_files(paths: Iterable[str]) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        pp = Path(p)
        if pp.is_dir():
            out.extend(sorted(pp.rglob("*.py")))
        elif pp.suffix == ".py":
            out.append(pp)
    return out


def analyze_paths(
    paths: Iterable[str], select: Optional[set[str]] = None
) -> list[FileReport]:
    units = []
    for file in iter_python_files(paths):
        source = file.read_text(encoding="utf-8")
        units.append(parse_unit(source, str(file)))
    return analyze_units(units, select)
