"""jaxlint data model: findings, suppressions, AST helpers, taint engine.

Shared by every rule family (:mod:`tools.jaxlint.rules`) and the
whole-program layer (:mod:`tools.jaxlint.program`). Nothing here imports
jax or the package under analysis — stdlib ``ast`` only, so the linter
runs before (and without) an install.

Suppression contract
--------------------
Each rule has a stable code so violations can be suppressed per line with

    some_call()  # jaxlint: disable=JXnnn

(comma-separate several codes; a bare ``# jaxlint: disable`` suppresses
every rule on that line). Suppressions that never fire are reported so
they cannot rot silently (``--strict`` fails on them).

Taint model
-----------
Rules JX002/JX003/JX004/JX006/JX009/JX010 analyze "trace scopes":
functions decorated ``@jax.jit`` / ``@partial(jax.jit, ...)``, every
function *defined inside* one (closures traced as part of the same
program), and — since the interprocedural pass — every function
*reachable by call* from one (:mod:`tools.jaxlint.program`). Parameters
not listed in ``static_argnames`` are traced values; taint flows through
assignments, attribute/subscript access, and arithmetic. Two refinements
keep the model honest for this codebase:

- attribute reads that are static even on tracers (``.shape``, ``.dtype``,
  ``.ndim``, ...) and the config pytree's registered *static* fields
  (``liquid_alpha``, ``consensus_precision``, the quantile overrides —
  models/config.py) do not propagate taint;
- ``x is None`` / ``x is not None`` tests are pytree-structure checks,
  resolved at trace time, and never taint a branch.

For the *control-flow* rule (JX003) a function-call boundary stops taint
unless the callee is rooted at ``jnp``/``jax``/``lax`` (those return
tracers; anything else is a host predicate — e.g. the engine-eligibility
gates — whose result is a Python bool computed from static structure).
The *host-cast* rule (JX002) keeps taint flowing through every call, so
``float(jnp.sum(x))`` is still flagged.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Iterable, Optional

#: Parse failures are reported under this pseudo-code (not suppressible).
PARSE_ERROR_CODE = "JX999"

#: Attribute reads that yield host/static values even on traced arrays.
TRACE_STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "itemsize", "aval", "sharding",
    # Registered *static* (aux-data) fields of the config pytrees —
    # models/config.py marks exactly these with metadata=dict(static=True).
    "liquid_alpha", "consensus_precision",
    "override_consensus_high", "override_consensus_low",
}

#: Call roots that return traced values (taint passes through for the
#: control-flow rule); everything else is treated as a host predicate.
TRACER_CALL_ROOTS = {"jnp", "jax", "lax", "float", "int", "bool"}

_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable(?:=(?P<codes>[A-Z0-9,\s]+?))?\s*(?:#|$)"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a concrete source location."""

    path: str
    line: int
    col: int
    code: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"


@dataclasses.dataclass
class FileReport:
    """Per-file analysis result (post-suppression)."""

    path: str
    findings: list[Finding]
    suppressed: int
    #: suppression comments that matched no finding: (line, codes-or-None)
    unused_suppressions: list[tuple[int, Optional[frozenset[str]]]]


# --------------------------------------------------------------------------
# small AST helpers


def dotted(node: ast.expr) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str_set(node: ast.expr) -> Optional[set[str]]:
    """static_argnames value -> set of names, when literally parseable."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out: set[str] = set()
        for el in node.elts:
            if not (isinstance(el, ast.Constant) and isinstance(el.value, str)):
                return None
            out.add(el.value)
        return out
    return None


def is_literal_like(node: ast.expr) -> bool:
    """Numeric-literal-ish first args of asarray: ``-1``, ``2.0``,
    ``float("nan")``, ``1 / 3``, ``[0, 1]``."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float, complex, bool))
    if isinstance(node, ast.UnaryOp):
        return is_literal_like(node.operand)
    if isinstance(node, ast.BinOp):
        return is_literal_like(node.left) and is_literal_like(node.right)
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(is_literal_like(el) for el in node.elts)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id in ("float", "int", "bool") and not node.keywords:
            return all(isinstance(a, ast.Constant) for a in node.args)
    return False


def annotation_mentions(ann: Optional[ast.expr], names: set[str]) -> bool:
    """Whether an annotation expression contains one of ``names`` as a
    bare Name (handles ``bool``, ``bool | None``, ``Optional[str]``)."""
    if ann is None:
        return False
    return any(
        isinstance(n, ast.Name) and n.id in names for n in ast.walk(ann)
    )


def all_params(fn) -> list[ast.arg]:
    a = fn.args
    return [*a.posonlyargs, *a.args, *a.kwonlyargs]


def jit_decoration(
    fn: ast.FunctionDef | ast.AsyncFunctionDef,
) -> Optional[tuple[set[str], bool]]:
    """``(static_argnames, parseable)`` when ``fn`` is jit-wrapped, else
    None. ``parseable`` is False when a static_argnames expression was
    present but not a literal (analysis then skips JX001 for safety)."""
    for dec in fn.decorator_list:
        target: Optional[ast.expr] = None
        call: Optional[ast.Call] = None
        if isinstance(dec, ast.Call):
            fname = dotted(dec.func) or ""
            if fname == "jit" or fname.endswith(".jit"):
                target, call = dec.func, dec  # @jax.jit(static_argnames=...)
            elif fname == "partial" or fname.endswith(".partial"):
                if dec.args:
                    inner = dotted(dec.args[0]) or ""
                    if inner == "jit" or inner.endswith(".jit"):
                        target, call = dec.args[0], dec
        else:
            fname = dotted(dec) or ""
            if fname == "jit" or fname.endswith(".jit"):
                target = dec
        if target is None:
            continue
        static: set[str] = set()
        parseable = True
        if call is not None:
            for kw in call.keywords:
                if kw.arg == "static_argnames":
                    got = const_str_set(kw.value)
                    if got is None:
                        parseable = False
                    else:
                        static |= got
                elif kw.arg == "static_argnums":
                    # positions -> names, when literally parseable
                    params = all_params(fn)
                    nums: list[int] = []
                    ok = True
                    vals = (
                        kw.value.elts
                        if isinstance(kw.value, (ast.Tuple, ast.List))
                        else [kw.value]
                    )
                    for el in vals:
                        if isinstance(el, ast.Constant) and isinstance(
                            el.value, int
                        ):
                            nums.append(el.value)
                        else:
                            ok = False
                    if ok:
                        for i in nums:
                            if 0 <= i < len(params):
                                static.add(params[i].arg)
                    else:
                        parseable = False
        return static, parseable
    return None


#: Names whose truthiness identifies a "am I under trace right now?"
#: self-guard (telemetry.runctx._tracing_now and friends). A function
#: that opens with `if <guard>(): return` is host-only by construction:
#: the interprocedural pass treats it as a trace boundary.
TRACING_GUARD_NAMES = re.compile(r"(_tracing_now|is_tracing|tracing_now)$")


def has_tracing_self_guard(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    """True when `fn` begins (docstring aside) with an early return
    gated on an is-tracing predicate — the `DispatchPlan.record`
    pattern that makes a host helper safe to *call* from a traced
    scope because its body no-ops under trace."""
    body = list(fn.body)
    while body and isinstance(body[0], ast.Expr) and isinstance(
        body[0].value, ast.Constant
    ):
        body = body[1:]  # skip docstring
    for st in body[:3]:  # the guard must come before any real work
        if not isinstance(st, ast.If):
            continue
        test = st.test
        if isinstance(test, ast.Call):
            name = dotted(test.func) or ""
            if TRACING_GUARD_NAMES.search(name):
                if all(
                    isinstance(s, (ast.Return, ast.Pass)) for s in st.body
                ):
                    return True
    return False


# --------------------------------------------------------------------------
# taint engine


class Taint:
    """Two-level taint over local names of one trace scope.

    ``general`` propagates through every expression form (JX002's view:
    any value *reachable from* a traced param). ``direct`` additionally
    stops at host-call boundaries (JX003's view: values that are
    syntactically tracers, not results of host predicates)."""

    def __init__(self, general: set[str], direct: set[str]):
        self.general = general
        self.direct = direct

    # -- expression evaluation ------------------------------------------

    def tainted(self, e: ast.expr, *, direct: bool) -> bool:
        names = self.direct if direct else self.general
        return self._eval(e, names, direct)

    def _eval(self, e: ast.expr, names: set[str], direct: bool) -> bool:
        if isinstance(e, ast.Name):
            return e.id in names
        if isinstance(e, ast.Constant) or isinstance(e, ast.Lambda):
            return False
        if isinstance(e, ast.Attribute):
            if e.attr in TRACE_STATIC_ATTRS:
                return False
            return self._eval(e.value, names, direct)
        if isinstance(e, ast.Compare):
            # `x is None` / `x is not None`: pytree-structure checks,
            # static at trace time regardless of x.
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                return False
            return self._eval(e.left, names, direct) or any(
                self._eval(c, names, direct) for c in e.comparators
            )
        if isinstance(e, ast.Call):
            root = (dotted(e.func) or "").split(".", 1)[0]
            if direct and root not in TRACER_CALL_ROOTS:
                # A method call on a traced object (x.sum(), W.mean())
                # returns a tracer; a free-function call is a host
                # predicate boundary (engine eligibility gates etc.).
                if isinstance(e.func, ast.Attribute):
                    return self._eval(e.func.value, names, direct)
                return False  # host-predicate boundary
            args_tainted = any(
                self._eval(a, names, direct)
                for a in e.args
                if not isinstance(a, ast.Starred)
            ) or any(
                self._eval(k.value, names, direct) for k in e.keywords
            ) or any(
                self._eval(a.value, names, direct)
                for a in e.args
                if isinstance(a, ast.Starred)
            )
            return args_tainted or self._eval(e.func, names, direct)
        children = [
            c for c in ast.iter_child_nodes(e) if isinstance(c, ast.expr)
        ]
        return any(self._eval(c, names, direct) for c in children)

    # -- statement-order propagation ------------------------------------

    def absorb_assignment(self, targets: Iterable[ast.expr], value: ast.expr):
        gen = self._eval(value, self.general, False)
        dire = self._eval(value, self.direct, True)
        if not (gen or dire):
            return
        for t in targets:
            for name in target_names(t):
                if gen:
                    self.general.add(name)
                if dire:
                    self.direct.add(name)


def target_names(t: ast.expr) -> list[str]:
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        return [n for el in t.elts for n in target_names(el)]
    if isinstance(t, ast.Starred):
        return target_names(t.value)
    return []  # attribute/subscript stores don't bind new names


def collect_taint(
    stmts: list[ast.stmt], taint: Taint, *, taint_nested_params: bool = True
) -> None:
    """One ordered pass folding assignments (and nested-function params)
    into the taint sets. Callers run it twice for a cheap fixpoint.

    ``taint_nested_params`` blanket-taints the params of nested function
    definitions — right for LITERAL jit bodies, where closures are scan
    steps / vmapped bodies whose params are tracers by construction.
    Interprocedurally *reached* helpers pass False: their own taint is
    inferred per parameter at each call site, and their closures are
    host dispatch plumbing (rung strings, fault records) that the
    blanket rule would falsely taint; closure-captured traced locals
    still taint normally through the shared name set."""
    for st in stmts:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if taint_nested_params:
                for p in all_params(st):
                    taint.general.add(p.arg)
                    taint.direct.add(p.arg)
            collect_taint(
                st.body, taint, taint_nested_params=taint_nested_params
            )
        elif isinstance(st, ast.Assign):
            taint.absorb_assignment(st.targets, st.value)
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            taint.absorb_assignment([st.target], st.value)
        elif isinstance(st, ast.AugAssign):
            taint.absorb_assignment([st.target], st.value)
        elif isinstance(st, ast.NamedExpr):  # pragma: no cover (stmt ctx)
            taint.absorb_assignment([st.target], st.value)
        elif isinstance(st, ast.For):
            taint.absorb_assignment([st.target], st.iter)
            collect_taint(st.body, taint, taint_nested_params=taint_nested_params)
            collect_taint(st.orelse, taint, taint_nested_params=taint_nested_params)
        elif isinstance(st, (ast.While, ast.If)):
            collect_taint(st.body, taint, taint_nested_params=taint_nested_params)
            collect_taint(st.orelse, taint, taint_nested_params=taint_nested_params)
        elif isinstance(st, ast.With):
            for item in st.items:
                if item.optional_vars is not None:
                    taint.absorb_assignment(
                        [item.optional_vars], item.context_expr
                    )
            collect_taint(st.body, taint, taint_nested_params=taint_nested_params)
        elif isinstance(st, ast.Try):
            collect_taint(st.body, taint, taint_nested_params=taint_nested_params)
            for h in st.handlers:
                collect_taint(h.body, taint, taint_nested_params=taint_nested_params)
            collect_taint(st.orelse, taint, taint_nested_params=taint_nested_params)
            collect_taint(st.finalbody, taint, taint_nested_params=taint_nested_params)
        # walrus targets inside plain expressions
        for sub in ast.walk(st):
            if isinstance(sub, ast.NamedExpr):
                taint.absorb_assignment([sub.target], sub.value)


def scope_nodes(scope) -> list[ast.AST]:
    """Nodes belonging to ``scope``'s own body, stopping at nested
    function definitions (each is analyzed as its own scope — this
    keeps scan reports single and literal-name resolution local)."""
    body = scope.body if hasattr(scope, "body") else []
    out: list[ast.AST] = []
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def calls_of(st: ast.stmt) -> list[ast.Call]:
    """Call nodes belonging to this statement, not descending into
    nested function bodies (walked separately) or nested suites."""
    exprs: list[ast.expr] = []
    for field_, value in ast.iter_fields(st):
        if field_ in ("body", "orelse", "finalbody", "handlers"):
            continue
        if isinstance(value, ast.expr):
            exprs.append(value)
        elif isinstance(value, list):
            exprs.extend(v for v in value if isinstance(v, ast.expr))
    calls: list[ast.Call] = []
    for e in exprs:
        for sub in ast.walk(e):
            if isinstance(sub, ast.Call):
                calls.append(sub)
            elif isinstance(sub, ast.Lambda):
                for inner in ast.walk(sub.body):
                    if isinstance(inner, ast.Call):
                        calls.append(inner)
    # dedupe while keeping order (lambda bodies walked twice above)
    seen: set[int] = set()
    out = []
    for c in calls:
        if id(c) not in seen:
            seen.add(id(c))
            out.append(c)
    return out


# --------------------------------------------------------------------------
# suppression handling


def parse_suppressions(
    source: str,
) -> dict[int, Optional[frozenset[str]]]:
    """line -> codes (None = all rules) for ``# jaxlint: disable=...``."""
    out: dict[int, Optional[frozenset[str]]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        codes = m.group("codes")
        if codes is None:
            out[i] = None
        else:
            out[i] = frozenset(
                c.strip() for c in codes.split(",") if c.strip()
            )
    return out


def apply_suppressions(
    path: str,
    source: str,
    findings: list[Finding],
    select: set[str],
    all_rules: set[str],
) -> FileReport:
    """Filter raw findings through the file's suppression comments."""
    suppressions = parse_suppressions(source)
    kept: list[Finding] = []
    used_lines: set[int] = set()
    suppressed = 0
    for f in findings:
        codes = suppressions.get(f.line, ...)
        if codes is ... or (codes is not None and f.code not in codes):
            kept.append(f)
        else:
            suppressed += 1
            used_lines.add(f.line)

    # A suppression is only provably unused when every rule it names
    # actually ran: under --select/--ignore a suppression for a
    # de-selected rule may be load-bearing in the full run, so it is
    # neither used nor unused here.
    def _judgeable(codes: Optional[frozenset[str]]) -> bool:
        if codes is None:
            return select >= all_rules
        return codes <= select

    unused = [
        (line, codes)
        for line, codes in sorted(suppressions.items())
        if line not in used_lines and _judgeable(codes)
    ]
    kept.sort(key=lambda f: (f.line, f.col, f.code))
    return FileReport(path, kept, suppressed, unused)
