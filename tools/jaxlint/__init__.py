"""jaxlint: AST-based TPU-discipline analyzer for yuma_simulation_tpu.

Eight project-specific rules (JX001-JX008) over stdlib ``ast`` — no new
dependencies. See :mod:`tools.jaxlint.analyzer` for the rule registry and
the taint model, :mod:`tools.jaxlint.cli` for the CLI
(``python -m tools.jaxlint yuma_simulation_tpu/ --strict``).
"""

from tools.jaxlint.analyzer import (  # noqa: F401
    RULES,
    FileReport,
    Finding,
    analyze_paths,
    analyze_source,
)
from tools.jaxlint.cli import main  # noqa: F401
