"""jaxlint: whole-program TPU-discipline analyzer for yuma_simulation_tpu.

Four rule families over stdlib ``ast`` — no new dependencies:
``tracing`` (JX001-JX010: jit-scope discipline, now interprocedural —
violations in helpers *reachable from* a jitted scope are found through
the call graph), ``hygiene`` (JX005/JX007/JX008), ``concurrency``
(JX101-JX103: guarded fields, atomic publishes, contextvars across
threads), ``contracts`` (JX201-JX203: telemetry event/metric names
checked against ``yuma_simulation_tpu/telemetry/registry.py``).

See :mod:`tools.jaxlint.rules` for the registry,
:mod:`tools.jaxlint.program` for the whole-program model, and
:mod:`tools.jaxlint.cli` for the CLI
(``python -m tools.jaxlint yuma_simulation_tpu tools tests --strict``).
"""

from tools.jaxlint.analyzer import (  # noqa: F401
    RULE_FAMILY,
    RULES,
    FileReport,
    Finding,
    analyze_paths,
    analyze_source,
)
from tools.jaxlint.cli import main  # noqa: F401
