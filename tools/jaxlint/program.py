"""jaxlint whole-program layer: module index, call graph, traced reach.

The per-function analyzer (PR 2) could only see a violation *literally
inside* a jit-decorated body: a tracer escaping through `float()` in a
helper one call away, or `time.time()` in a function a jitted scope
calls, was invisible. This module builds the facts the interprocedural
rules need:

- a **module index**: every analyzed file mapped to a dotted module
  name (derived from its path anchor — ``yuma_simulation_tpu``,
  ``tools``, ``tests``, ``scripts`` — or the bare stem for loose files);
- a **function index**: module-level functions and class methods by
  qualified name, with their jit decoration parsed;
- per-file **import resolution** (absolute and package-relative), so
  ``from ..telemetry.cost import estimate`` resolves to the indexed
  function;
- a **traced-reachability fixpoint**: seeded at every jit scope, a
  worklist propagates (a) reachability — the callee's body executes at
  trace time — and (b) *per-parameter taint* — which callee params
  receive values reachable from the caller's traced params — through
  every resolvable call. Callees that are themselves jit scopes are
  boundaries (jit-of-jit is analyzed at its own seed), and so are
  helpers opening with an is-tracing early return
  (:func:`tools.jaxlint.model.has_tracing_self_guard` — the
  ``DispatchPlan.record`` pattern).

Resolution is deliberately conservative: bare names in the same module,
imported symbols, ``module.attr`` chains through imports, and
``self.method`` / ``cls.method`` within a class. A call that does not
resolve is a host boundary exactly as before — the pass adds detection,
never speculation.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Optional

from tools.jaxlint.model import (
    PARSE_ERROR_CODE,
    Finding,
    Taint,
    all_params,
    collect_taint,
    dotted,
    has_tracing_self_guard,
    jit_decoration,
)

#: Path components that anchor a dotted module name. Order matters only
#: for documentation; the LAST anchor occurrence in the path wins so a
#: checkout under e.g. /home/tools/repo still maps tests/ correctly.
MODULE_ANCHORS = ("yuma_simulation_tpu", "yuma_simulation", "tools", "tests", "scripts")


def module_name_for(path: str) -> str:
    """Dotted module name for a file path, anchored at the repo's
    top-level packages; loose files map to their stem (fixtures)."""
    parts = Path(path).parts
    anchor = None
    for i, part in enumerate(parts):
        if part in MODULE_ANCHORS:
            anchor = i
    if anchor is None:
        return Path(path).stem
    mods = list(parts[anchor:])
    mods[-1] = Path(mods[-1]).stem
    if mods[-1] == "__init__":
        mods = mods[:-1]
    return ".".join(mods)


@dataclasses.dataclass
class FuncInfo:
    """One indexed function or method."""

    qualname: str  # module.func or module.Class.method
    module: str
    cls: Optional[str]
    node: ast.FunctionDef | ast.AsyncFunctionDef
    unit: "FileUnit"
    jit_static: Optional[set[str]]  # None when not jit-decorated
    jit_parseable: bool
    self_guarded: bool

    @property
    def is_jit(self) -> bool:
        return self.jit_static is not None


@dataclasses.dataclass
class FileUnit:
    """One parsed source file plus its accumulated raw findings."""

    path: str
    source: str
    tree: Optional[ast.Module]
    module: str
    findings: list[Finding] = dataclasses.field(default_factory=list)
    #: local name -> ("module", dotted) | ("symbol", dotted)
    imports: dict[str, tuple[str, str]] = dataclasses.field(
        default_factory=dict
    )

    def add(self, node: ast.AST, code: str, message: str) -> None:
        self.findings.append(
            Finding(
                self.path,
                getattr(node, "lineno", 0),
                getattr(node, "col_offset", 0),
                code,
                message,
            )
        )


def parse_unit(source: str, path: str) -> FileUnit:
    module = module_name_for(path)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        unit = FileUnit(path, source, None, module)
        unit.findings.append(
            Finding(
                path,
                exc.lineno or 0,
                exc.offset or 0,
                PARSE_ERROR_CODE,
                f"could not parse file: {exc.msg}",
            )
        )
        return unit
    return FileUnit(path, source, tree, module)


def _resolve_relative(module: str, level: int, target: Optional[str]) -> str:
    """``from ..x import f`` inside ``pkg.sub.mod`` -> ``pkg.x``."""
    parts = module.split(".")
    # level 1 = current package (strip the module leaf), 2 = parent, ...
    base = parts[: max(0, len(parts) - level)]
    if target:
        base = base + target.split(".")
    return ".".join(base)


def _index_imports(unit: FileUnit) -> None:
    assert unit.tree is not None
    for node in ast.walk(unit.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".", 1)[0]
                target = alias.name if alias.asname else alias.name.split(".", 1)[0]
                unit.imports[local] = ("module", target)
                if alias.asname is None and "." in alias.name:
                    # `import a.b.c` binds `a`, but the full dotted
                    # spelling `a.b.c.f` must also resolve.
                    unit.imports.setdefault(alias.name, ("module", alias.name))
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if node.level:
                mod = _resolve_relative(unit.module, node.level, node.module)
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                unit.imports[local] = ("symbol", f"{mod}.{alias.name}")


@dataclasses.dataclass
class TraceFacts:
    """What the fixpoint learned about one function."""

    #: human-readable call chain from a jit seed ("mod.f -> mod.helper")
    chain: str
    #: params holding values reachable from the caller's traced params
    tainted_general: set[str]
    #: params that are syntactically tracers at every taint step
    tainted_direct: set[str]


class Program:
    """The whole-program view: every unit, every function, and the
    traced-reachability facts the interprocedural rules consume."""

    def __init__(self, units: list[FileUnit]):
        self.units = units
        self.functions: dict[str, FuncInfo] = {}
        #: facts for NON-jit functions reachable from a jit scope
        self.reached: dict[str, TraceFacts] = {}
        self._build_index()
        self._fixpoint()

    # -- indexing --------------------------------------------------------

    def _build_index(self) -> None:
        for unit in self.units:
            if unit.tree is None:
                continue
            _index_imports(unit)
            for node in unit.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._index_fn(unit, node, cls=None)
                elif isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            self._index_fn(unit, sub, cls=node.name)

    def _index_fn(self, unit: FileUnit, node, cls: Optional[str]) -> None:
        qual = (
            f"{unit.module}.{cls}.{node.name}"
            if cls
            else f"{unit.module}.{node.name}"
        )
        jit = jit_decoration(node)
        self.functions[qual] = FuncInfo(
            qualname=qual,
            module=unit.module,
            cls=cls,
            node=node,
            unit=unit,
            jit_static=None if jit is None else jit[0],
            jit_parseable=jit[1] if jit is not None else True,
            self_guarded=has_tracing_self_guard(node),
        )

    # -- call resolution -------------------------------------------------

    def resolve_call(
        self, unit: FileUnit, call: ast.Call, cls: Optional[str]
    ) -> Optional[FuncInfo]:
        """The indexed callee of ``call``, or None (host boundary)."""
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
            hit = self.functions.get(f"{unit.module}.{name}")
            if hit is not None and hit.cls is None:
                return hit
            imp = unit.imports.get(name)
            if imp is not None and imp[0] == "symbol":
                return self.functions.get(imp[1])
            return None
        d = dotted(func)
        if d is None:
            return None
        root, _, rest = d.partition(".")
        if root in ("self", "cls") and cls is not None and rest and "." not in rest:
            return self.functions.get(f"{unit.module}.{cls}.{rest}")
        imp = unit.imports.get(root)
        if imp is not None and rest:
            kind, target = imp
            if kind == "module":
                return self.functions.get(f"{target}.{rest}")
            if kind == "symbol":
                # `from pkg import mod` then `mod.f(...)`
                return self.functions.get(f"{target}.{rest}")
        # full dotted spelling of an `import a.b.c`
        prefix, _, leaf = d.rpartition(".")
        if prefix in {
            t for k, (kind, t) in unit.imports.items() if kind == "module"
        }:
            return self.functions.get(f"{prefix}.{leaf}")
        return None

    # -- traced-reachability fixpoint ------------------------------------

    def _fixpoint(self) -> None:
        # Seeds: every jit scope, with its own traced params.
        work: list[str] = [
            q for q, f in self.functions.items() if f.is_jit
        ]
        seen_state: dict[str, tuple[int, int]] = {}
        guard = 0
        while work and guard < 10_000:
            guard += 1
            qual = work.pop()
            info = self.functions.get(qual)
            if info is None or info.unit.tree is None:
                continue
            if info.is_jit:
                traced = {
                    p.arg for p in all_params(info.node)
                } - (info.jit_static or set())
                facts = TraceFacts(qual, set(traced), set(traced))
            else:
                facts = self.reached.get(qual)
                if facts is None:
                    continue
            state = (
                len(facts.tainted_general),
                len(facts.tainted_direct),
            )
            if seen_state.get(qual) == state:
                continue
            seen_state[qual] = state
            self._propagate_from(info, facts, work)

    def _propagate_from(
        self, info: FuncInfo, facts: TraceFacts, work: list[str]
    ) -> None:
        taint = Taint(
            set(facts.tainted_general), set(facts.tainted_direct)
        )
        collect_taint(
            info.node.body, taint, taint_nested_params=info.is_jit
        )
        collect_taint(
            info.node.body, taint, taint_nested_params=info.is_jit
        )
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            callee = self.resolve_call(info.unit, node, info.cls)
            if callee is None or callee.is_jit or callee.self_guarded:
                continue
            if callee.qualname == info.qualname:
                continue  # direct recursion adds nothing new
            params = [p.arg for p in all_params(callee.node)]
            if callee.cls is not None and params and params[0] in (
                "self",
                "cls",
            ):
                params = params[1:]
            gen: set[str] = set()
            dire: set[str] = set()
            for i, arg in enumerate(node.args):
                if isinstance(arg, ast.Starred) or i >= len(params):
                    break
                if taint.tainted(arg, direct=False):
                    gen.add(params[i])
                if taint.tainted(arg, direct=True):
                    dire.add(params[i])
            for kw in node.keywords:
                if kw.arg is None or kw.arg not in params:
                    continue
                if taint.tainted(kw.value, direct=False):
                    gen.add(kw.arg)
                if taint.tainted(kw.value, direct=True):
                    dire.add(kw.arg)
            prev = self.reached.get(callee.qualname)
            if prev is None:
                self.reached[callee.qualname] = TraceFacts(
                    f"{facts.chain} -> {callee.qualname}", gen, dire
                )
                work.append(callee.qualname)
            else:
                before = (
                    len(prev.tainted_general),
                    len(prev.tainted_direct),
                )
                prev.tainted_general |= gen
                prev.tainted_direct |= dire
                if (
                    len(prev.tainted_general),
                    len(prev.tainted_direct),
                ) != before:
                    work.append(callee.qualname)
