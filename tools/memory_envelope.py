"""Single-chip memory envelope: how big a subnet fits, and what it costs.

Probes `simulate_constant` (the long-horizon throughput path) at growing
`[V, M]` shapes on the current backend, recording wall time and the
device's peak HBM usage, until allocation fails. With `--sharded`, runs
the miner-sharded equivalent over a `(1, N)` mesh instead — on the CPU
test mesh this demonstrates the >1-chip path without TPU pod hardware.

Prints one JSON line per probed shape; the final summary line marks the
largest shape that fit. Results are recorded in DESIGN.md ("Memory
envelope").

Run from the repo root: `python tools/memory_envelope.py [--sharded]`
(PYTHONPATH cannot be used — setting it breaks TPU plugin registration
in this environment).
"""

import argparse
import json
import os
import re
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from yuma_simulation_tpu.utils import enable_compilation_cache  # noqa: E402

# Cold compiles grow steeply with [V, M] on the remote-tunnel runtime
# (~1 min at 256x4096, >>10 min at the top of the ladder); the persistent
# cache makes reruns and post-failure retries sub-second.
enable_compilation_cache()


def peak_hbm_gib():
    """Peak device memory in GiB from the RUNTIME's allocator stats, or
    None when the backend doesn't report them (CPU) — None serializes as
    valid JSON null, NaN would not."""
    stats = jax.local_devices()[0].memory_stats() or {}
    peak = stats.get("peak_bytes_in_use")
    return round(peak / 2**30, 2) if peak else None


def aot_peak_hbm_gib(run_aot) -> tuple:
    """(peak GiB, source) from the COMPILED program's own memory
    analysis (`telemetry.cost.capture_compiled`), lowered from
    ShapeDtypeStructs — the backend-independent answer the allocator
    stats can't give on CPU. Returns (None, reason) only when the
    runtime truly reports no memory analysis."""
    from yuma_simulation_tpu.telemetry.cost import capture_compiled

    try:
        lowered = run_aot()
    except Exception as e:
        return None, f"lowering failed: {str(e).splitlines()[0][:120]}"
    rec = capture_compiled(lowered, engine="probe", V=0, M=0, epochs=0)
    if rec.peak_bytes is None:
        return None, rec.reason or "no memory analysis"
    return round(rec.peak_bytes / 2**30, 2), (
        f"aot_{rec.peak_bytes_source or 'memory_analysis'}"
    )


def probe(V: int, M: int, epochs: int, mesh=None) -> dict:
    from yuma_simulation_tpu.models.config import YumaConfig
    from yuma_simulation_tpu.models.variants import variant_for_version
    from yuma_simulation_tpu.simulation.engine import simulate_constant

    rng = np.random.default_rng(0)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 2 (Adrian-Fish)")
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        W = jax.device_put(
            W, NamedSharding(mesh, PartitionSpec(None, mesh.axis_names[-1]))
        )

    def run():
        # bisect, not sorted: the sorted path's XLA program hits
        # pathological remote-compile times at >= 512x8192 (DESIGN.md
        # "Memory envelope"); bisect compiles in seconds at every rung.
        total, _ = simulate_constant(
            W, S, epochs, cfg, spec, consensus_impl="bisect", mesh=mesh
        )
        return np.asarray(total)

    run()  # compile + warm
    t0 = time.perf_counter()
    out = run()
    dt = time.perf_counter() - t0
    assert np.isfinite(out).all()
    # The runtime's allocator peak when it reports one (TPU/GPU); else
    # the compiled program's own memory analysis (args+outputs+temps,
    # or its explicit peak where the runtime exposes it) — so the CPU
    # envelope carries a real number, with null reserved for runtimes
    # that truly report neither.
    peak, source = peak_hbm_gib(), "runtime"
    if peak is None:

        def run_aot():
            Wspec = jax.ShapeDtypeStruct(W.shape, W.dtype)
            Sspec = jax.ShapeDtypeStruct(S.shape, S.dtype)
            return jax.jit(
                lambda w, s: simulate_constant(
                    w, s, epochs, cfg, spec, consensus_impl="bisect",
                    mesh=mesh,
                )[0]
            ).lower(Wspec, Sspec)

        peak, source = aot_peak_hbm_gib(run_aot)
    return {
        "V": V,
        "M": M,
        "epochs": epochs,
        "epochs_per_s": round(epochs / dt, 1),
        "peak_hbm_gib": peak,
        "peak_hbm_source": source,
        "state_mib_per_vm_buffer": round(V * M * 4 / 2**20, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sharded", action="store_true")
    ap.add_argument("--epochs", type=int, default=1000)
    args = ap.parse_args()

    mesh = None
    if args.sharded:
        from yuma_simulation_tpu.parallel import make_mesh

        n = len(jax.devices())
        mesh = make_mesh(data=1, model=n)

    # Doubling ladder of [V, M]; stop at first allocation failure.
    # (8192x131072 — 4 GiB/buffer — is known to fail at remote compile.)
    shapes = [
        (1024, 16384),
        (2048, 32768),
        (4096, 32768),
        (4096, 65536),
        (8192, 65536),
        (8192, 131072),
    ]
    epochs = args.epochs
    if jax.default_backend() == "cpu":
        # CPU-mesh probes demonstrate the sharded path, not throughput:
        # a handful of epochs on two rungs of the ladder is enough.
        epochs = min(epochs, 8)
        shapes = shapes[:2]
    largest = None
    for V, M in shapes:
        try:
            rec = probe(V, M, epochs, mesh)
        except Exception as e:  # XLA OOM surfaces as RuntimeError
            # First line only, ANSI escapes stripped: keep the committed
            # artifact stable and readable across regenerations.
            stripped = re.sub(r"\x1b\[[0-9;]*m", "", str(e))
            msg = (stripped.splitlines() or ["<no message>"])[0][:200]
            print(
                json.dumps({"V": V, "M": M, "fits": False, "error": msg}),
                flush=True,
            )
            break
        rec.update(fits=True, sharded=bool(mesh), backend=jax.default_backend())
        largest = rec
        print(json.dumps(rec), flush=True)
    print(json.dumps({"largest_fitting": largest}), flush=True)


if __name__ == "__main__":
    main()
