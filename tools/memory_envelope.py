"""Single-chip memory envelope: how big a subnet fits, and what it costs.

Probes `simulate_constant` (the long-horizon throughput path) at growing
`[V, M]` shapes on the current backend, recording wall time and the
device's peak HBM usage, until allocation fails. With `--sharded`, runs
the miner-sharded equivalent over a `(1, N)` mesh instead — on the CPU
test mesh this demonstrates the >1-chip path without TPU pod hardware.

Prints one JSON line per probed shape; the final summary line marks the
largest shape that fit. Results are recorded in DESIGN.md ("Memory
envelope").

Run from the repo root: `python tools/memory_envelope.py [--sharded]`
(PYTHONPATH cannot be used — setting it breaks TPU plugin registration
in this environment).
"""

import argparse
import json
import os
import re
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from yuma_simulation_tpu.utils import enable_compilation_cache  # noqa: E402

# Cold compiles grow steeply with [V, M] on the remote-tunnel runtime
# (~1 min at 256x4096, >>10 min at the top of the ladder); the persistent
# cache makes reruns and post-failure retries sub-second.
enable_compilation_cache()


def peak_hbm_gib():
    """Peak device memory in GiB, or None when the backend doesn't report
    it (CPU) — None serializes as valid JSON null, NaN would not."""
    stats = jax.local_devices()[0].memory_stats() or {}
    peak = stats.get("peak_bytes_in_use")
    return round(peak / 2**30, 2) if peak else None


def probe(V: int, M: int, epochs: int, mesh=None) -> dict:
    from yuma_simulation_tpu.models.config import YumaConfig
    from yuma_simulation_tpu.models.variants import variant_for_version
    from yuma_simulation_tpu.simulation.engine import simulate_constant

    rng = np.random.default_rng(0)
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 2 (Adrian-Fish)")
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        W = jax.device_put(
            W, NamedSharding(mesh, PartitionSpec(None, mesh.axis_names[-1]))
        )

    def run():
        # bisect, not sorted: the sorted path's XLA program hits
        # pathological remote-compile times at >= 512x8192 (DESIGN.md
        # "Memory envelope"); bisect compiles in seconds at every rung.
        total, _ = simulate_constant(
            W, S, epochs, cfg, spec, consensus_impl="bisect", mesh=mesh
        )
        return np.asarray(total)

    run()  # compile + warm
    t0 = time.perf_counter()
    out = run()
    dt = time.perf_counter() - t0
    assert np.isfinite(out).all()
    return {
        "V": V,
        "M": M,
        "epochs": epochs,
        "epochs_per_s": round(epochs / dt, 1),
        "peak_hbm_gib": peak_hbm_gib(),
        "state_mib_per_vm_buffer": round(V * M * 4 / 2**20, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sharded", action="store_true")
    ap.add_argument("--epochs", type=int, default=1000)
    args = ap.parse_args()

    mesh = None
    if args.sharded:
        from yuma_simulation_tpu.parallel import make_mesh

        n = len(jax.devices())
        mesh = make_mesh(data=1, model=n)

    # Doubling ladder of [V, M]; stop at first allocation failure.
    # (8192x131072 — 4 GiB/buffer — is known to fail at remote compile.)
    shapes = [
        (1024, 16384),
        (2048, 32768),
        (4096, 32768),
        (4096, 65536),
        (8192, 65536),
        (8192, 131072),
    ]
    epochs = args.epochs
    if jax.default_backend() == "cpu":
        # CPU-mesh probes demonstrate the sharded path, not throughput:
        # a handful of epochs on two rungs of the ladder is enough.
        epochs = min(epochs, 8)
        shapes = shapes[:2]
    largest = None
    for V, M in shapes:
        try:
            rec = probe(V, M, epochs, mesh)
        except Exception as e:  # XLA OOM surfaces as RuntimeError
            # First line only, ANSI escapes stripped: keep the committed
            # artifact stable and readable across regenerations.
            stripped = re.sub(r"\x1b\[[0-9;]*m", "", str(e))
            msg = (stripped.splitlines() or ["<no message>"])[0][:200]
            print(
                json.dumps({"V": V, "M": M, "fits": False, "error": msg}),
                flush=True,
            )
            break
        rec.update(fits=True, sharded=bool(mesh), backend=jax.default_backend())
        largest = rec
        print(json.dumps(rec), flush=True)
    print(json.dumps({"largest_fitting": largest}), flush=True)


if __name__ == "__main__":
    main()
