"""Test harness config: virtual 8-device CPU mesh + x64 for parity mode.

The multi-chip story is tested without TPU hardware by forcing 8 host
platform devices (SURVEY.md §4: this replaces the reference's absent fake
backend layer). x64 is enabled so the Yuma-0 variant's float64 quantization
divide (reference yumas.py:81) is honored; all framework arrays stay
explicitly float32.
"""

import os
import sys

# Forced assignment: the environment's sitecustomize pre-sets
# JAX_PLATFORMS to the real accelerator plugin, so setdefault would lose.
# (The config.update below is what actually takes effect — sitecustomize
# has already imported jax by the time this file runs, so the env snapshot
# is stale; backends themselves initialize lazily, so the update is safe.)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402,F401

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")

# ---------------------------------------------------------------------------
# Capability probes: known environment gaps vs real regressions.
#
# The project targets the toolchain pinned in pyproject.toml (jax >= 0.7);
# containers with an older baked-in jax hit a fixed, well-understood set
# of failures that are NOT code regressions. Each probe below names the
# missing capability explicitly, and `pytest_collection_modifyitems`
# turns exactly the known-affected tests into skips with that reason —
# so a tier-1 run distinguishes "this environment can't run it" from
# "the code broke it". On a full toolchain every probe passes and
# nothing is skipped.

#: jax.shard_map with the post-rename API (check_vma=...) appeared in
#: jax 0.6/0.7; older jax only has jax.experimental.shard_map with
#: check_rep, which the parallel layer deliberately does not use
#: (pyproject pins jax>=0.7 for exactly this).
HAS_JAX_SHARD_MAP = hasattr(jax, "shard_map")

_JAX_VERSION = tuple(int(p) for p in jax.__version__.split(".")[:2])

#: The golden CSV diff pins, the Pallas interpret-mode reset parity and
#: the f32-subprocess goldens were minted on the jax>=0.7 toolchain;
#: older jax/XLA CPU builds differ by a few final-ulp roundings (one
#: 6th-decimal CSV cell) and an interpret-mode divergence in the fused
#: reset path — environment numerics, not regressions.
JAX_AT_PINNED_TOOLCHAIN = _JAX_VERSION >= (0, 7)

#: (test file basename, test function name) -> (probe, reason). A test
#: listed here is skipped when its probe is False; parametrized variants
#: all share the probe.
_CAPABILITY_SKIPS = {
    # --- jax.shard_map absent ---
    **{
        ("test_multichip.py", name): (
            HAS_JAX_SHARD_MAP,
            f"jax {jax.__version__} has no jax.shard_map "
            "(pyproject pins jax>=0.7)",
        )
        for name in (
            "test_sharded_batch_matches_vmap",
            "test_sharded_batch_pads_uneven",
            "test_montecarlo_sharded",
            "test_montecarlo_batch_pads_and_trims",
            "test_montecarlo_per_epoch_weights_matches_engine_oracle",
            "test_montecarlo_impl_knobs",
        )
    },
    # The elastic drills that re-dispatch on the SHRUNK mesh need a real
    # shard_map; the rest of test_elastic_mesh.py (surviving_mesh logic,
    # pre-dispatch fault aborts, the single-device last rung) runs
    # everywhere.
    **{
        ("test_elastic_mesh.py", name): (
            HAS_JAX_SHARD_MAP,
            f"jax {jax.__version__} has no jax.shard_map "
            "(pyproject pins jax>=0.7)",
        )
        for name in (
            "test_elastic_degradation_on_device_loss",
            "test_chaos_drill_all_four_faults_sharded",
        )
    },
    # The telemetry flight-recorder drill that adds device loss needs
    # the same elastic sharded dispatch; the rest of test_telemetry.py
    # runs everywhere.
    ("test_telemetry.py", "test_chaos_drill_four_faults_sharded_bundle"): (
        HAS_JAX_SHARD_MAP,
        f"jax {jax.__version__} has no jax.shard_map "
        "(pyproject pins jax>=0.7)",
    ),
    # The serving tier's mid-request device-loss drill dispatches the
    # request through the elastic SHARDED path; the rest of
    # test_serve.py (admission, quotas, coalescing, breaker, NaN
    # partials) runs everywhere.
    (
        "test_serve.py",
        "test_device_loss_mid_request_returns_structured_degraded",
    ): (
        HAS_JAX_SHARD_MAP,
        f"jax {jax.__version__} has no jax.shard_map "
        "(pyproject pins jax>=0.7)",
    ),
    # --- CSV byte-parity pins minted on the jax>=0.7 toolchain ---
    ("test_csv_byte_parity.py", "test_rendered_csv_cells_pinned_exactly"): (
        JAX_AT_PINNED_TOOLCHAIN,
        f"golden CSV diff pins were minted on jax>=0.7; jax "
        f"{jax.__version__} CPU numerics differ by final-ulp roundings",
    ),
    # --- fused case-scan reset parity in interpret mode ---
    ("test_fused_case_scan.py", "test_fused_case_scan_reset_fires_like_xla"): (
        JAX_AT_PINNED_TOOLCHAIN,
        f"Pallas interpret-mode reset parity requires the jax>=0.7 "
        f"toolchain (jax {jax.__version__} diverges beyond the pinned "
        "tolerance)",
    ),
    # --- f32 subprocess golden ---
    (
        "test_fused_epoch.py",
        "test_fused_scan_ema_rust_matches_in_f32_subprocess",
    ): (
        JAX_AT_PINNED_TOOLCHAIN,
        f"f32-mode subprocess golden was pinned on jax>=0.7; jax "
        f"{jax.__version__} CPU numerics drift beyond its tolerance",
    ),
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        key = (
            os.path.basename(str(item.fspath)),
            getattr(item, "originalname", item.name),
        )
        probe = _CAPABILITY_SKIPS.get(key)
        if probe is not None and not probe[0]:
            item.add_marker(
                pytest.mark.skip(reason=f"environment gap: {probe[1]}")
            )
