"""Test harness config: virtual 8-device CPU mesh + x64 for parity mode.

The multi-chip story is tested without TPU hardware by forcing 8 host
platform devices (SURVEY.md §4: this replaces the reference's absent fake
backend layer). x64 is enabled so the Yuma-0 variant's float64 quantization
divide (reference yumas.py:81) is honored; all framework arrays stay
explicitly float32.
"""

import os
import sys

# Forced assignment: the environment's sitecustomize pre-sets
# JAX_PLATFORMS to the real accelerator plugin, so setdefault would lose.
# (The config.update below is what actually takes effect — sitecustomize
# has already imported jax by the time this file runs, so the env snapshot
# is stale; backends themselves initialize lazily, so the update is safe.)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402,F401

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")
