"""The showcase example must stay green (VERDICT r1: untested additions rot).

Runs `examples/quickstart.py` end to end in a subprocess on the CPU
backend and checks the artifacts it promises to write.
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


@pytest.mark.slow
def test_quickstart_runs_green(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in [_REPO, env.get("PYTHONPATH", "")] if p
    )
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(_REPO, "examples", "quickstart.py"),
            "--out-dir",
            str(tmp_path),
        ],
        cwd=_REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=1200,
    )
    assert out.returncode == 0, out.stderr[-4000:]
    assert (tmp_path / "chart_table.html").exists()
    assert (tmp_path / "total_dividends_b0.99.csv").exists()
    assert (tmp_path / "mc").is_dir()
