"""Chain-replay service battery (ISSUE 14): the snapshot-timeline
archive contract (monotone blocks, content addressing, idempotent
re-publish, no history rewrites), the state cache's LRU bound and
corruption degradation, what-if spec JSON round-trips and validation,
the cached-vs-uncached bitwise pin, the serve tier's what-if/replay
endpoints (suffix-sized admission included), and the report tooling
(obsreport's replay section, perfgate's whatif gate)."""

import dataclasses
import json

import numpy as np
import pytest

from yuma_simulation_tpu.foundry.metagraph import synthetic_snapshot
from yuma_simulation_tpu.replay import (
    ArchiveError,
    ReplayService,
    SnapshotArchive,
    StateCache,
    WhatIfError,
    WhatIfSpec,
    run_whatif,
    synthetic_timeline,
)

NETUID = 9
VERSION = "Yuma 2 (Adrian-Fish)"


def _archive(tmp_path, snapshots=3, seed=1):
    arch = SnapshotArchive(tmp_path / "archive")
    synthetic_timeline(
        arch,
        NETUID,
        snapshots=snapshots,
        seed=seed,
        num_validators=3,
        num_miners=4,
    )
    return arch


# ---------------------------------------------------------------- archive


class TestArchive:
    def test_timeline_round_trip_and_content_addressing(self, tmp_path):
        arch = _archive(tmp_path)
        entries = arch.timeline(NETUID)
        assert [e.block for e in entries] == [1000, 1100, 1200]
        snap = arch.load(NETUID, 1100)
        assert snap.block == 1100 and snap.num_miners == 4
        # deterministic generator: same seed -> same content address
        again = SnapshotArchive(tmp_path / "again")
        e2 = synthetic_timeline(
            again, NETUID, snapshots=3, seed=1,
            num_validators=3, num_miners=4,
        )
        assert [e.key for e in entries] == [e.key for e in e2]

    def test_append_is_idempotent_but_never_rewrites(self, tmp_path):
        arch = _archive(tmp_path)
        snap = synthetic_snapshot(
            1, num_validators=3, num_miners=4, netuid=NETUID, block=1000
        )
        assert arch.append(snap).block == 1000  # idempotent no-op
        assert len(arch.timeline(NETUID)) == 3
        rewritten = synthetic_snapshot(
            99, num_validators=3, num_miners=4, netuid=NETUID, block=1000
        )
        with pytest.raises(ArchiveError, match="does not rewrite"):
            arch.append(rewritten)

    def test_non_monotone_and_shape_drift_rejected(self, tmp_path):
        arch = _archive(tmp_path)
        stale = synthetic_snapshot(
            5, num_validators=3, num_miners=4, netuid=NETUID, block=1150
        )
        with pytest.raises(ArchiveError, match="append-only"):
            arch.append(stale)
        reshaped = synthetic_snapshot(
            5, num_validators=4, num_miners=4, netuid=NETUID, block=1300
        )
        with pytest.raises(ArchiveError, match="drifts"):
            arch.append(reshaped)

    def test_corrupt_blob_detected(self, tmp_path):
        arch = _archive(tmp_path)
        entry = arch.timeline(NETUID)[0]
        blob = arch._blob_path(NETUID, entry.key)
        blob.write_bytes(b"torn" + blob.read_bytes()[4:])
        with pytest.raises(ArchiveError, match="content address"):
            arch.load(NETUID, entry.block)

    def test_unknown_subnet_and_window_scenario(self, tmp_path):
        arch = _archive(tmp_path)
        with pytest.raises(ArchiveError, match="no timeline"):
            arch.timeline(4242)
        scenario = arch.window_scenario(
            NETUID, window=2, epochs_per_snapshot=3
        )
        assert scenario.weights.shape == (6, 3, 4)
        # snapshot i's rows hold for its 3 epochs, then switch
        assert np.array_equal(scenario.weights[0], scenario.weights[2])
        assert not np.array_equal(scenario.weights[2], scenario.weights[3])
        fp_full = arch.timeline_fingerprint(NETUID)
        fp_win = arch.timeline_fingerprint(NETUID, window=2)
        assert fp_full != fp_win


# ------------------------------------------------------------- state cache


class TestStateCache:
    def test_lru_eviction_bound(self, tmp_path):
        from tests.unit.test_suffix_resume import _scenario

        cache = StateCache(tmp_path / "cache", max_baselines=2)
        keys = []
        for i in range(3):
            meta = cache.build_baseline(
                _scenario(seed=i),
                "Yuma 1 (paper)",
                scenario_fingerprint=f"lru-{i}",
                stride=4,
                engine="xla",
            )
            keys.append(meta.key)
        assert len(cache.keys()) == 2
        assert keys[0] not in cache.keys()  # oldest evicted whole
        assert keys[1] in cache.keys() and keys[2] in cache.keys()

    def test_identical_build_is_idempotent(self, tmp_path):
        from tests.unit.test_suffix_resume import _scenario

        cache = StateCache(tmp_path / "cache")
        a = cache.build_baseline(
            _scenario(seed=1), "Yuma 1 (paper)",
            scenario_fingerprint="idem", stride=4, engine="xla",
        )
        b = cache.build_baseline(
            _scenario(seed=1), "Yuma 1 (paper)",
            scenario_fingerprint="idem", stride=4, engine="xla",
        )
        assert a.key == b.key and len(cache.keys()) == 1

    def test_resume_epoch_picks_nearest_checkpoint(self, tmp_path):
        from tests.unit.test_suffix_resume import _scenario

        cache = StateCache(tmp_path / "cache")
        meta = cache.build_baseline(
            _scenario(seed=2), "Yuma 1 (paper)",
            scenario_fingerprint="near", stride=3, engine="xla",
        )
        assert meta.checkpoints == (3, 6, 9)
        assert cache.resume_epoch(meta.key, 2) == 0
        assert cache.resume_epoch(meta.key, 3) == 3
        assert cache.resume_epoch(meta.key, 8) == 6
        assert cache.resume_epoch(meta.key, 9) == 9

    def test_corrupt_state_degrades_to_full_run(self, tmp_path):
        from tests.unit.test_suffix_resume import _scenario

        scenario = _scenario(seed=4)
        cache = StateCache(tmp_path / "cache")
        meta = cache.build_baseline(
            scenario, VERSION,
            scenario_fingerprint="corrupt", stride=4, engine="xla",
        )
        spec = WhatIfSpec(
            netuid=NETUID, version=VERSION, from_epoch=9,
            stake_scale=((0, 2.0),),
        )
        clean = run_whatif(
            cache, meta, scenario, None, spec, use_cache=True
        )
        assert clean.cache_hit and clean.resume_epoch == 8
        cache._state_path(meta.key, 8).write_bytes(b"rot")
        degraded = run_whatif(
            cache, meta, scenario, None, spec, use_cache=True
        )
        assert not degraded.cache_hit and degraded.epochs_simulated == 10
        np.testing.assert_array_equal(
            degraded.dividends, clean.dividends
        )


# ----------------------------------------------------------------- whatif


class TestWhatIfSpec:
    def test_json_round_trip_and_key_stability(self):
        spec = WhatIfSpec(
            netuid=3,
            version=VERSION,
            from_epoch=5,
            hparams=(("bond_alpha", 0.2),),
            weight_rows=((1, (0.25, 0.75, 0.0, 0.0)),),
            stake_scale=((0, 1.5),),
        )
        again = WhatIfSpec.from_json(json.loads(json.dumps(spec.to_json())))
        assert again == spec
        assert again.spec_key() == spec.spec_key()
        other = dataclasses.replace(spec, from_epoch=6)
        assert other.spec_key() != spec.spec_key()

    @pytest.mark.parametrize(
        "payload,match",
        [
            ({"netuid": 1, "version": VERSION}, "must perturb"),
            (
                {"netuid": 1, "version": VERSION, "from_epoch": -1,
                 "stake_scale": [[0, 2.0]]},
                "from_epoch",
            ),
            (
                {"netuid": 1, "version": VERSION,
                 "hparams": [["liquid_alpha", 1.0]]},
                "not what-if-settable",
            ),
            (
                {"netuid": 1, "version": VERSION,
                 "stake_scale": [[0, -2.0]]},
                "finite number",
            ),
            (
                {"netuid": 1, "version": VERSION, "bogus": 1,
                 "stake_scale": [[0, 2.0]]},
                "unknown what-if fields",
            ),
            # a non-numeric pair value must be the TYPED spec error
            # (admission turns WhatIfError into a 400; a bare
            # ValueError would escape as a 503)
            (
                {"netuid": 1, "version": VERSION,
                 "stake_scale": [[1, "x"]]},
                "stake_scale entry",
            ),
            (
                {"netuid": 1, "version": VERSION,
                 "weight_rows": [[0, 7]]},
                "weight_rows entry",
            ),
        ],
    )
    def test_invalid_specs_are_typed(self, payload, match):
        with pytest.raises(WhatIfError, match=match):
            WhatIfSpec.from_json(payload)

    def test_out_of_range_indices_rejected_at_apply(self, tmp_path):
        from tests.unit.test_suffix_resume import _scenario

        scenario = _scenario(seed=5)
        cache = StateCache(tmp_path / "cache")
        meta = cache.build_baseline(
            scenario, VERSION,
            scenario_fingerprint="oob", stride=4, engine="xla",
        )
        for spec, match in [
            (
                WhatIfSpec(netuid=1, version=VERSION,
                           stake_scale=((99, 2.0),)),
                "out of range",
            ),
            (
                WhatIfSpec(netuid=1, version=VERSION, from_epoch=10,
                           stake_scale=((0, 2.0),)),
                "beyond",
            ),
            (
                WhatIfSpec(netuid=1, version=VERSION,
                           weight_rows=((0, (1.0, 0.0)),)),
                "miners",
            ),
        ]:
            with pytest.raises(WhatIfError, match=match):
                run_whatif(cache, meta, scenario, None, spec)


@pytest.mark.parametrize("rung", ("xla", "fused_scan", "fused_scan_mxu"))
def test_whatif_cached_equals_uncached_every_rung(tmp_path, rung):
    """The acceptance pin: a what-if's cached suffix resume is bitwise
    the uncached end-to-end run of the same perturbed world — per
    engine rung (the fused pair in interpret mode off-TPU), with both
    an array perturbation and a piecewise hparam delta in play."""
    from tests.unit.test_suffix_resume import _scenario

    from yuma_simulation_tpu.models.config import YumaConfig

    scenario = _scenario(seed=6)
    cache = StateCache(tmp_path / "cache")
    meta = cache.build_baseline(
        scenario,
        VERSION,
        scenario_fingerprint=f"rung-{rung}",
        stride=4,
        engine=rung,
    )
    spec = WhatIfSpec(
        netuid=1,
        version=VERSION,
        from_epoch=9,
        stake_scale=((1, 2.0),),
        hparams=(("bond_alpha", 0.15),),
    )
    cached = run_whatif(
        cache, meta, scenario, YumaConfig(), spec, use_cache=True
    )
    uncached = run_whatif(
        cache, meta, scenario, YumaConfig(), spec, use_cache=False
    )
    assert cached.cache_hit and cached.resume_epoch == 8
    assert cached.epochs_simulated == 2 and uncached.epochs_simulated == 10
    np.testing.assert_array_equal(cached.dividends, uncached.dividends)
    np.testing.assert_array_equal(cached.incentives, uncached.incentives)


class TestReplayService:
    def test_miss_then_hit_bitwise_and_counters(self, tmp_path):
        from yuma_simulation_tpu.telemetry.metrics import get_registry

        _archive(tmp_path)
        svc = ReplayService(
            tmp_path / "archive", tmp_path / "cache",
            epochs_per_snapshot=4, stride=4,
        )
        spec = WhatIfSpec(
            netuid=NETUID, version=VERSION, from_epoch=9,
            weight_rows=((0, (1.0, 0.0, 0.0, 0.0)),),
        )
        reg = get_registry()
        hits0 = reg.counter("state_cache_hits").value
        misses0 = reg.counter("state_cache_misses").value
        saved0 = reg.counter("replay_suffix_epochs_saved").value
        first = svc.whatif(spec)
        assert not first.cache_hit and first.epochs_simulated == 12
        second = svc.whatif(spec)
        assert second.cache_hit and second.resume_epoch == 8
        assert second.epochs_simulated == 4 and second.epochs_saved == 8
        np.testing.assert_array_equal(first.dividends, second.dividends)
        np.testing.assert_array_equal(
            first.dividend_delta, second.dividend_delta
        )
        # the perturbation is causal: zero delta before from_epoch
        assert np.abs(second.dividend_delta[:9]).max() == 0
        assert np.abs(second.dividend_delta[9:]).max() > 0
        assert reg.counter("state_cache_hits").value == hits0 + 1
        assert reg.counter("state_cache_misses").value == misses0 + 1
        assert (
            reg.counter("replay_suffix_epochs_saved").value == saved0 + 8
        )

    def test_describe_prices_suffix_sized(self, tmp_path):
        _archive(tmp_path)
        svc = ReplayService(
            tmp_path / "archive", tmp_path / "cache",
            epochs_per_snapshot=4, stride=4,
        )
        spec = WhatIfSpec(
            netuid=NETUID, version=VERSION, from_epoch=9,
            stake_scale=((1, 2.0),),
        )
        before = svc.describe(spec)
        assert before["cached"] is False and before["suffix_epochs"] == 12
        svc.whatif(spec)
        after = svc.describe(spec)
        assert after["cached"] is True
        assert after["resume_epoch"] == 8 and after["suffix_epochs"] == 4


# ------------------------------------------------------------- serve tier


@pytest.fixture
def replay_server(tmp_path):
    from yuma_simulation_tpu.serve.server import (
        SimulationServer,
        wait_until_ready,
    )
    from yuma_simulation_tpu.serve.service import ServeConfig

    _archive(tmp_path)
    server = SimulationServer(
        ServeConfig(
            bundle_dir=str(tmp_path / "serve"),
            replay_archive_dir=str(tmp_path / "archive"),
            replay_cache_dir=str(tmp_path / "cache"),
            replay_epochs_per_snapshot=4,
            replay_stride=4,
        )
    ).start()
    assert wait_until_ready(server.url)
    try:
        yield server, tmp_path
    finally:
        server.close()


class TestServeWhatIf:
    def test_endpoints_end_to_end(self, replay_server):
        from yuma_simulation_tpu.serve.server import SimulationClient

        server, tmp_path = replay_server
        client = SimulationClient(server.url, tenant="t-replay")
        idx = client.replay()
        assert idx.status == 200
        assert [s["netuid"] for s in idx.body["subnets"]] == [NETUID]
        tl = client.replay(NETUID)
        assert tl.status == 200 and tl.body["epochs"] == 12
        assert client.replay(777).status == 404

        spec = {
            "netuid": NETUID,
            "version": VERSION,
            "from_epoch": 9,
            "stake_scale": [[1, 2.0]],
        }
        first = client.whatif(spec)
        assert first.status == 200 and first.body["cache_hit"] is False
        second = client.whatif(spec)
        assert second.status == 200 and second.body["cache_hit"] is True
        assert second.body["epochs_simulated"] == 4
        assert second.body["epochs_saved"] == 8
        assert (
            second.body["total_dividend_delta"]
            == first.body["total_dividend_delta"]
        )
        assert second.request_id is not None

    def test_admission_rejections_are_typed(self, replay_server):
        from yuma_simulation_tpu.serve.server import SimulationClient

        server, _ = replay_server
        client = SimulationClient(server.url)
        r = client.whatif(
            {"netuid": 404, "version": VERSION, "stake_scale": [[0, 2.0]]}
        )
        assert r.status == 400 and r.body["reason"] == "unknown_subnet"
        r = client.whatif({"netuid": NETUID, "version": VERSION})
        assert r.status == 400 and "perturb" in r.body["message"]
        r = client.whatif(
            {"netuid": NETUID, "version": "Yuma nonesuch",
             "stake_scale": [[0, 2.0]]}
        )
        assert r.status == 400

    def test_bundle_ledger_and_obsreport_section(self, replay_server):
        from yuma_simulation_tpu.serve.server import SimulationClient

        server, tmp_path = replay_server
        client = SimulationClient(server.url, tenant="render-me")
        spec = {
            "netuid": NETUID,
            "version": VERSION,
            "from_epoch": 9,
            "stake_scale": [[0, 3.0]],
        }
        assert client.whatif(spec).status == 200
        assert client.whatif(spec).status == 200
        server.close()
        from yuma_simulation_tpu.telemetry.flight import (
            check_bundle,
            load_bundle,
        )

        bundle = load_bundle(tmp_path / "serve")
        assert check_bundle(bundle) == []
        served = [
            r for r in bundle.ledger if r.get("event") == "whatif_served"
        ]
        assert len(served) == 2
        assert served[1]["cache_hit"] is True
        assert served[1]["suffix_epochs"] == 4
        assert served[1]["full_epochs"] == 12
        from tools.obsreport import render_replay

        lines = "\n".join(render_replay(bundle))
        assert "tenant render-me" in lines and "suffix resume" in lines

    def test_unconfigured_replay_rejects(self):
        from yuma_simulation_tpu.serve.service import (
            ServeConfig,
            SimulationService,
        )

        svc = SimulationService(ServeConfig(start_dispatcher=False))
        try:
            status, body, _ = svc.handle(
                "whatif",
                {
                    "whatif": {
                        "netuid": 1,
                        "version": VERSION,
                        "stake_scale": [[0, 2.0]],
                    }
                },
            )
            assert status == 400
            assert body["reason"] == "replay_unconfigured"
            assert svc.replay_get("/v1/replay")[0] == 404
        finally:
            svc.close()


# ----------------------------------------------------------- perfgate gate


class TestPerfgateWhatIf:
    def _record(self, **whatif):
        return {
            "whatif": {
                "full_seconds": 0.5,
                "suffix_seconds": 0.1,
                "speedup": 5.0,
                "epoch_ratio": 5.0,
                **whatif,
            }
        }

    def test_structural_requires_whatif_fields(self):
        from tools.perfgate import check_structure

        problems = check_structure({"whatif": {}, "value": 1.0})
        assert any("whatif.speedup" in p for p in problems)
        problems = check_structure(
            {"whatif": {"error": "boom"}, "value": 1.0}
        )
        assert any("boom" in p for p in problems)

    def test_speedup_floor_derives_from_epoch_ratio(self):
        from tools.perfgate import check_whatif

        assert check_whatif(self._record()) == []
        failures = check_whatif(self._record(speedup=1.2))
        assert failures and "epoch ratio" in failures[0]
        # a barely-saving resume (ratio ~1) is vacuously fine at >= 1x
        assert check_whatif(
            self._record(speedup=1.01, epoch_ratio=1.05)
        ) == []
