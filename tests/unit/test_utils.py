"""Utils: checkpointed sweeps, timers, logging setup."""

import logging
import time

import numpy as np
import pytest

from yuma_simulation_tpu.utils import CheckpointedSweep, setup_logging, timed


def test_checkpointed_sweep_resumes(tmp_path):
    calls = []

    def fn(i):
        calls.append(i)
        return np.full((2, 3), i, np.float32)

    sweep = CheckpointedSweep(tmp_path, num_chunks=4, tag="t")
    out = sweep.run(fn)
    assert out.shape == (8, 3)
    assert calls == [0, 1, 2, 3]

    # Delete one chunk; resume recomputes only that chunk.
    (tmp_path / "chunk_00002.npz").unlink()
    calls.clear()
    sweep2 = CheckpointedSweep(tmp_path, num_chunks=4, tag="t")
    out2 = sweep2.run(fn)
    assert calls == [2]
    np.testing.assert_array_equal(out, out2)


def test_checkpointed_sweep_rejects_mismatched_manifest(tmp_path):
    CheckpointedSweep(tmp_path, num_chunks=4, tag="a")
    with pytest.raises(ValueError, match="different"):
        CheckpointedSweep(tmp_path, num_chunks=8, tag="a")


class _CaptureHandler(logging.Handler):
    """Grab formatted record messages exactly as log_event emits them."""

    def __init__(self):
        super().__init__()
        self.lines: list[str] = []

    def emit(self, record):
        self.lines.append(record.getMessage())


def _roundtrip(event, fields):
    from yuma_simulation_tpu.utils.logging import log_event, parse_event_line

    logger = logging.getLogger("yuma_simulation_tpu.test_parse_event")
    logger.propagate = False
    handler = _CaptureHandler()
    logger.addHandler(handler)
    try:
        log_event(logger, event, **fields)
    finally:
        logger.removeHandler(handler)
    (line,) = handler.lines
    return parse_event_line(line)


def test_parse_event_line_roundtrip_quoting():
    """ISSUE 3 satellite: parse_event_line is the exact inverse of
    log_event's quoting — spaces, equals signs, quotes, backslashes."""
    fields = {
        "plain": "ok",
        "spaced": "a b c",
        "equals": "k=v",
        "quoted": 'she said "hi"',
        "backslash": "a\\b\\\\c",
        "number": 7,
        "mixed": 'x="1 2" \\ end',
    }
    parsed = _roundtrip("drill", fields)
    assert parsed is not None
    assert parsed.pop("event") == "drill"
    assert parsed == {k: str(v) for k, v in fields.items()}


def test_parse_event_line_property_roundtrip():
    """Randomized round-trip over the quoting alphabet (seeded — a
    failure reproduces exactly): every generated field survives
    log_event -> parse_event_line verbatim."""
    import random
    import string

    alphabet = string.ascii_letters + string.digits + ' ="\\=_-.:,'
    rng = random.Random(1234)
    for trial in range(50):
        fields = {}
        for k in range(rng.randint(1, 6)):
            value = "".join(
                rng.choice(alphabet) for _ in range(rng.randint(1, 24))
            )
            if value == "":
                continue
            fields[f"f{k}"] = value
        parsed = _roundtrip("prop", fields)
        assert parsed is not None, (trial, fields)
        assert parsed.pop("event") == "prop"
        expected = {k: v for k, v in fields.items() if v != ""}
        assert parsed == expected, (trial, fields)


def test_parse_event_line_skips_formatter_prefix_and_non_events():
    from yuma_simulation_tpu.utils.logging import parse_event_line

    parsed = parse_event_line(
        "12:00:01 WARNING yuma_simulation_tpu.resilience.retry: "
        'event=engine_demoted from_engine=fused_scan to_engine=xla'
    )
    assert parsed == {
        "event": "engine_demoted",
        "from_engine": "fused_scan",
        "to_engine": "xla",
    }
    assert parse_event_line("no structured record here") is None
    assert parse_event_line("") is None


def test_publish_atomic_is_crash_safe_shape(tmp_path):
    """The shared primitive the ledger and checkpoint sidecars reuse:
    publish leaves no temp residue and replaces content atomically."""
    from yuma_simulation_tpu.utils import publish_atomic

    target = tmp_path / "x.json"
    publish_atomic(target, b"one")
    assert target.read_bytes() == b"one"
    publish_atomic(target, b"two")
    assert target.read_bytes() == b"two"
    assert list(tmp_path.iterdir()) == [target]


def test_timed_rate():
    with timed("x", epochs=100) as t:
        pass
    assert t.seconds >= 0
    assert t.epochs_per_sec is None or t.epochs_per_sec > 0


def test_setup_logging_idempotent():
    setup_logging()
    root = logging.getLogger("yuma_simulation_tpu")
    n = len(root.handlers)
    setup_logging()
    assert len(root.handlers) == n


def test_checkpointed_sweep_survives_stale_tmp(tmp_path):
    # A crash between write and rename leaves a partial file behind; it
    # must be ignored and its chunk recomputed.
    sweep = CheckpointedSweep(tmp_path, num_chunks=2)
    (tmp_path / "partial_00001.tmp").write_bytes(b"garbage")
    out = sweep.run(lambda i: np.full((1, 2), i, np.float32))
    assert out.shape == (2, 2)
    assert sweep.completed_chunks() == [0, 1]


def test_profile_trace(tmp_path):
    import numpy as np
    import jax.numpy as jnp

    from yuma_simulation_tpu.utils import profile_trace

    with profile_trace(None):  # no-op path
        pass
    with profile_trace(str(tmp_path / "trace")):
        np.asarray(jnp.arange(8).sum())
    assert any((tmp_path / "trace").rglob("*"))


def test_checkpoint_config_fingerprint_mismatch(tmp_path):
    CheckpointedSweep(tmp_path, num_chunks=2, config={"seed": 1, "V": 16})
    # same config (different key order) resumes fine
    CheckpointedSweep(tmp_path, num_chunks=2, config={"V": 16, "seed": 1})
    with pytest.raises(ValueError, match="different"):
        CheckpointedSweep(tmp_path, num_chunks=2, config={"seed": 2, "V": 16})


def test_checkpoint_config_must_be_serializable(tmp_path):
    with pytest.raises(TypeError, match="JSON-serializable"):
        CheckpointedSweep(tmp_path, num_chunks=1, config={"fn": object()})


def test_checkpoint_legacy_manifest_resumes(tmp_path):
    """A manifest written before `config_fingerprint` existed must stay
    resumable (key-by-key comparison) and be upgraded in place."""
    import json

    (tmp_path / "manifest.json").write_text(
        json.dumps({"num_chunks": 2, "tag": "t"})
    )
    CheckpointedSweep(tmp_path, num_chunks=2, tag="t", config={"a": 1})
    upgraded = json.loads((tmp_path / "manifest.json").read_text())
    assert "config_fingerprint" in upgraded
    # The shared keys are still enforced.
    with pytest.raises(ValueError, match="different"):
        CheckpointedSweep(tmp_path, num_chunks=3, tag="t", config={"a": 1})


def test_checkpoint_legacy_manifest_merge_and_warning(tmp_path, caplog):
    """ADVICE r2: the backfill must only add keys ABSENT from the old
    manifest (keys written by a newer version survive), and stamping an
    unverifiable fingerprint over pre-existing chunks warns."""
    import json

    (tmp_path / "manifest.json").write_text(
        json.dumps({"num_chunks": 2, "tag": "t", "from_future": 42})
    )
    with open(tmp_path / "chunk_00000.npz", "wb") as f:
        np.savez(f, result=np.ones((2, 3)))
    with caplog.at_level(logging.WARNING, "yuma_simulation_tpu.utils.checkpoint"):
        sweep = CheckpointedSweep(tmp_path, num_chunks=2, tag="t", config={"a": 1})
    assert any("not verified" in r.getMessage() for r in caplog.records)
    merged = json.loads((tmp_path / "manifest.json").read_text())
    assert merged["from_future"] == 42  # newer-version key survived
    assert "config_fingerprint" in merged
    calls = []
    out = sweep.run(lambda i: (calls.append(i), np.full((2, 3), i))[1])
    assert calls == [1]  # chunk 0 was resumed, not recomputed
    assert out.shape == (4, 3)


def test_time_best_counts_and_granularity():
    """The shared bench-timing helper: grows the work count past the
    target window on a multiple of `granularity`, and reports the grown
    count it actually timed."""
    from yuma_simulation_tpu.utils.timing import time_best

    executed = []

    def run(n):
        executed.append(n)
        time.sleep(n * 1e-4)  # 10k "epochs" ~= 1 s
        return n

    rate, n_timed, times, cv = time_best(
        run, 7, max_n=100_000, granularity=7, target_seconds=0.05, reps=2
    )
    assert n_timed % 7 == 0 and n_timed > 7  # grew, on the granularity grid
    assert len(times) == 2 and rate > 0
    assert cv >= 0.0  # dispersion across the two repeats
    assert all(n % 7 == 0 for n in executed)
    # A run already past the window is not grown.
    rate2, n2, _, _ = time_best(
        run, 1_000, max_n=100_000, target_seconds=0.05, reps=2
    )
    assert n2 == 1_000


def test_time_best_terminates_and_rounds_edge_cases():
    from yuma_simulation_tpu.utils.timing import time_best

    executed = []

    def instant(n):  # never reaches the window: growth must still stop
        executed.append(n)
        return n

    # max_n=20 is NOT a multiple of granularity=6: the floored cap (18)
    # must terminate the loop, not re-time 18 forever.
    _, n_timed, _, cv = time_best(
        instant, 6, max_n=20, granularity=6, target_seconds=10.0, reps=1
    )
    assert n_timed == 18
    assert cv == 0.0  # single rep: no dispersion to report
    # The caller-supplied initial n is rounded onto the grid too.
    executed.clear()
    time_best(instant, 7, max_n=18, granularity=6, target_seconds=10.0, reps=1)
    assert all(n % 6 == 0 for n in executed)


def test_enable_compilation_cache(tmp_path, monkeypatch):
    import jax

    from yuma_simulation_tpu.utils import enable_compilation_cache

    before = jax.config.jax_compilation_cache_dir
    try:
        used = enable_compilation_cache(str(tmp_path / "cache"))
        assert used == str(tmp_path / "cache")
        assert jax.config.jax_compilation_cache_dir == used
        assert (tmp_path / "cache").is_dir()
        # env-var override path
        monkeypatch.setenv("YUMA_TPU_JAX_CACHE", str(tmp_path / "env_cache"))
        assert enable_compilation_cache() == str(tmp_path / "env_cache")
    finally:
        jax.config.update("jax_compilation_cache_dir", before)
