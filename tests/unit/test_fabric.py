"""Fleet fabric: lease protocol, shared store, work-stealing scheduler —
ISSUE 7 acceptance battery (in-process half).

The multiprocess pod-level chaos drill (host SIGKILL + lease tear +
stall + NaN across >=3 simulated hosts) lives in
tests/unit/test_fleet_drill.py (slow+chaos — the CI chaos lane runs
it); everything deterministic and seconds-scale is here: claim
exclusivity under randomized interleavings, torn-lease tolerance,
expiry-driven stealing with per-unit attempt history, at-most-once
publish, and the fleet end-to-end bitwise contract."""

import errno
import json
import pathlib
import random
import threading
import time

import numpy as np
import pytest

from yuma_simulation_tpu.fabric import (
    FleetConfig,
    FleetStore,
    LeaseStore,
    build_fleet_report,
    check_fleet,
    merged_ledger,
    partition_lanes,
    publish_fleet_report,
    run_fleet_batch,
)
from yuma_simulation_tpu.resilience import (
    FaultPlan,
    LeaseTearFault,
    NaNFault,
    inject_faults,
)
from yuma_simulation_tpu.resilience.errors import LeaseExpired
from yuma_simulation_tpu.scenarios import get_cases
from yuma_simulation_tpu.utils.checkpoint import publish_atomic

VERSION = "Yuma 1 (paper)"


# ------------------------------------------------------------- the lease


def test_claim_is_exclusive_and_released(tmp_path):
    a = LeaseStore(tmp_path, "hostA", ttl_seconds=30.0)
    b = LeaseStore(tmp_path, "hostB", ttl_seconds=30.0)
    claim = a.try_claim(0)
    assert claim is not None and claim.generation == 0
    assert b.try_claim(0) is None  # live claim protects the unit
    a.renew(0)  # heartbeat is a no-op-ish refresh while owned
    a.release(0)
    assert not a.lease_path(0).exists()
    second = b.try_claim(0)
    assert second is not None and second.generation == 0  # no steal


def test_expired_lease_is_stolen_with_generation_and_typed_abandon(tmp_path):
    dead = LeaseStore(tmp_path, "dead-host", ttl_seconds=0.1)
    assert dead.try_claim(0) is not None
    time.sleep(0.25)
    thief = LeaseStore(tmp_path, "thief", ttl_seconds=0.1)
    stolen = thief.try_claim(0)
    assert stolen is not None
    assert stolen.generation == 1
    assert stolen.stolen_from == "dead-host"
    # the original holder discovers the theft as the TYPED failure
    with pytest.raises(LeaseExpired) as exc:
        dead.renew(0)
    assert exc.value.unit == 0 and exc.value.holder == "thief"
    assert not dead.still_owner(0)
    # the steal left its durable tombstone (= the attempt history)
    assert thief.generation(0) == 1


def test_torn_lease_is_tolerated_and_stealable(tmp_path):
    holder = LeaseStore(tmp_path, "holder", ttl_seconds=60.0)
    assert holder.try_claim(0) is not None
    # shared-store corruption: truncate the live claim record
    path = holder.lease_path(0)
    path.write_bytes(path.read_bytes()[:7])
    scanner = LeaseStore(tmp_path, "scanner", ttl_seconds=60.0)
    info = scanner.read(0)
    assert info is not None and info.torn
    # torn trumps mtime: stealable NOW, whatever the heartbeat says
    assert scanner.is_stealable(info)
    stolen = scanner.try_claim(0)
    assert stolen is not None and stolen.generation == 1
    # the torn record carried no parseable holder
    assert stolen.stolen_from == ""
    with pytest.raises(LeaseExpired):
        holder.renew(0)


def test_claim_race_exactly_one_winner_randomized_interleavings(tmp_path):
    """ISSUE 7 property: two hosts racing to claim the same unit never
    both win (and therefore never both publish — publish is gated on
    holding the claim). Randomized sleeps at every protocol pause point
    across many trials explore the interleaving space; the link-based
    claim must yield exactly one winner in every schedule."""
    trials = 20
    for trial in range(trials):
        d = tmp_path / f"trial{trial}"
        winners = []
        lock = threading.Lock()
        barrier = threading.Barrier(2)

        def host(name: str, seed: str) -> None:
            rng = random.Random(seed)
            ls = LeaseStore(d, name, ttl_seconds=30.0)
            ls._pause = lambda stage: time.sleep(rng.random() * 0.005)
            barrier.wait()
            if ls.try_claim(0) is not None:
                with lock:
                    winners.append(name)

        threads = [
            threading.Thread(target=host, args=(n, f"{trial}:{n}"))
            for n in ("hostA", "hostB")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(winners) == 1, (trial, winners)


def test_steal_race_exactly_one_winner_randomized_interleavings(tmp_path):
    """The steal path's exclusivity: two stealers racing for the same
    EXPIRED lease — the tombstone rename arbitrates; exactly one may
    claim, and the loser backs off without damaging the fresh claim."""
    for trial in range(12):
        d = tmp_path / f"trial{trial}"
        # TTL chosen so the dead claim (aged 0.5s) is long expired while
        # a freshly-stolen claim stays live across the whole race (ms).
        dead = LeaseStore(d, "dead-host", ttl_seconds=0.3)
        assert dead.try_claim(0) is not None
        time.sleep(0.5)
        winners = []
        lock = threading.Lock()
        barrier = threading.Barrier(2)

        def thief(name: str, seed: str) -> None:
            rng = random.Random(seed)
            # same TTL as the fleet (expiry is a fleet-wide constant)
            ls = LeaseStore(d, name, ttl_seconds=0.3)
            ls._pause = lambda stage: time.sleep(rng.random() * 0.005)
            barrier.wait()
            claim = ls.try_claim(0)
            if claim is not None:
                with lock:
                    winners.append((name, claim.generation))

        threads = [
            threading.Thread(target=thief, args=(n, f"s{trial}:{n}"))
            for n in ("thiefA", "thiefB")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(winners) == 1, (trial, winners)
        assert winners[0][1] == 1  # generation counts the one steal
        # the fresh claim survived the losing stealer intact
        survivor = LeaseStore(d, "observer", ttl_seconds=60.0)
        info = survivor.read(0)
        assert info is not None and not info.torn
        assert info.host == winners[0][0]


@pytest.mark.faultinject
def test_lease_tear_fault_tears_own_live_lease(tmp_path):
    ls = LeaseStore(tmp_path, "hostA", ttl_seconds=60.0)
    assert ls.try_claim(0) is not None
    with inject_faults(FaultPlan(lease_tear=LeaseTearFault(after_renewals=2))):
        ls.renew(0)  # renewal 1: not yet
        assert not ls.read(0).torn
        ls.renew(0)  # renewal 2: tear fires, once
        assert ls.read(0).torn
        ls.renew(0)  # inode unchanged: the holder still renews
        assert ls.read(0).torn


@pytest.mark.faultinject
def test_host_crash_fault_sigkills_after_n_claims(tmp_path):
    """The crash hook must take the PROCESS down with SIGKILL (no
    teardown), so it runs in a scratch subprocess."""
    import signal
    import subprocess
    import sys

    repo = pathlib.Path(__file__).resolve().parents[2]
    code = (
        "from yuma_simulation_tpu.resilience.faults import ("
        "FaultPlan, HostCrashFault, inject_faults, maybe_crash_host)\n"
        "with inject_faults(FaultPlan(host_crash=HostCrashFault(after_claims=2))):\n"
        "    maybe_crash_host(0)\n"
        "    print('survived-first-claim', flush=True)\n"
        "    maybe_crash_host(1)\n"
        "    print('NEVER-REACHED', flush=True)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        cwd=repo,
        timeout=120,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]
    assert "survived-first-claim" in proc.stdout
    assert "NEVER-REACHED" not in proc.stdout


# ------------------------------------------------------- publish_atomic


def test_publish_atomic_exdev_falls_back_to_copy_rename(tmp_path, monkeypatch):
    """Shared-store case: the temp file lands on a different filesystem
    than the target — `rename` raises EXDEV and the publish must fall
    back to copy + same-filesystem rename, still atomic at the target."""
    calls = {"exdev": 0}
    orig = pathlib.Path.replace

    def fake_replace(self, target):
        if calls["exdev"] == 0 and ".xdev." not in self.name:
            calls["exdev"] += 1
            raise OSError(errno.EXDEV, "Invalid cross-device link")
        return orig(self, target)

    monkeypatch.setattr(pathlib.Path, "replace", fake_replace)
    publish_atomic(tmp_path / "x.json", b'{"a": 1}')
    assert calls["exdev"] == 1
    assert (tmp_path / "x.json").read_bytes() == b'{"a": 1}'
    assert not list(tmp_path.glob("*.tmp"))  # no stragglers either way


def test_publish_atomic_tmp_dir_staging(tmp_path):
    staging = tmp_path / "staging"
    staging.mkdir()
    target = tmp_path / "store" / "rec.json"
    target.parent.mkdir()
    publish_atomic(target, b'{"b": 2}', tmp_dir=staging)
    assert target.read_bytes() == b'{"b": 2}'
    assert not list(staging.iterdir())


def test_publish_atomic_unexpected_oserror_propagates(tmp_path, monkeypatch):
    def always_fail(self, target):
        raise OSError(errno.EACCES, "Permission denied")

    monkeypatch.setattr(pathlib.Path, "replace", always_fail)
    with pytest.raises(OSError) as exc:
        publish_atomic(tmp_path / "x.json", b"{}")
    assert exc.value.errno == errno.EACCES


# ------------------------------------------------------------- the store


def test_store_at_most_once_publish_and_corruption_requeue(tmp_path):
    store = FleetStore(tmp_path)
    store.ensure_manifest(
        num_units=2, unit_lanes=[(0, 1), (1, 2)], tag="t", config={"v": 1}
    )
    first = np.arange(6.0).reshape(1, 2, 3)
    assert store.publish_result(0, {"dividends": first})
    # at-most-once: a verified result is never overwritten
    assert not store.publish_result(0, {"dividends": np.zeros((1, 2, 3))})
    np.testing.assert_array_equal(store.load_result(0)["dividends"], first)
    # corruption requeues: a torn result drops back to pending and the
    # republish is accepted
    path = store.result_path(0)
    path.write_bytes(path.read_bytes()[:20])
    assert not store.verify_result(0)
    assert 0 in store.pending_units()
    assert store.publish_result(0, {"dividends": first})
    assert store.verify_result(0)


def test_store_manifest_rejects_a_different_sweep(tmp_path):
    store = FleetStore(tmp_path)
    store.ensure_manifest(
        num_units=1, unit_lanes=[(0, 4)], tag="a", config={"v": 1}
    )
    again = FleetStore(tmp_path)
    again.ensure_manifest(
        num_units=1, unit_lanes=[(0, 4)], tag="a", config={"v": 1}
    )
    with pytest.raises(ValueError, match="different"):
        again.ensure_manifest(
            num_units=1, unit_lanes=[(0, 4)], tag="a", config={"v": 2}
        )


def test_partition_lanes_matches_supervisor_rule():
    assert partition_lanes(7, 3) == [(0, 3), (3, 6), (6, 7)]
    with pytest.raises(ValueError, match="empty"):
        partition_lanes(0, 3)
    with pytest.raises(ValueError, match="unit_size"):
        partition_lanes(3, 0)


# --------------------------------------------------------- the scheduler


def test_fleet_batch_single_host_matches_supervised_run(tmp_path):
    from yuma_simulation_tpu.resilience import SweepSupervisor

    cases = get_cases()[:4]
    clean = SweepSupervisor(directory=None, unit_size=2).run_batch(
        cases, VERSION
    )
    out = run_fleet_batch(
        cases,
        VERSION,
        FleetConfig(
            directory=tmp_path, unit_size=2, lease_ttl_seconds=30.0
        ),
    )
    report = out["report"]
    assert report.units_published == report.num_units == 2
    assert report.clean
    np.testing.assert_array_equal(out["dividends"], clean["dividends"])
    assert check_fleet(tmp_path) == []


def test_fleet_two_hosts_share_the_grid_no_double_publish(tmp_path):
    """Two in-process hosts (threads) work-steal one store: every unit
    publishes exactly once, the merged result is bitwise the clean
    single-host run, and the merged ledgers reconcile."""
    from yuma_simulation_tpu.resilience import SweepSupervisor

    cases = get_cases()[:4]
    clean = SweepSupervisor(directory=None, unit_size=1).run_batch(
        cases, VERSION
    )
    errors = []

    def host(host_id: str) -> None:
        try:
            run_fleet_batch(
                cases,
                VERSION,
                FleetConfig(
                    directory=tmp_path,
                    host_id=host_id,
                    unit_size=1,
                    lease_ttl_seconds=30.0,
                    poll_seconds=0.05,
                    max_wait_seconds=240.0,
                ),
                finalize=False,
            )
        except BaseException as exc:  # noqa: BLE001 — surfaced below
            errors.append((host_id, exc))

    threads = [
        threading.Thread(target=host, args=(f"host{i}",)) for i in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    store = FleetStore(tmp_path)
    report = publish_fleet_report(store)
    assert report.units_published == 4
    assert report.hosts_lost == ()
    ok_units = sorted(
        r["unit"]
        for r in merged_ledger(store)
        if r.get("event") == "unit_ok"
    )
    assert ok_units == [0, 1, 2, 3]  # exactly one accepted publish each
    np.testing.assert_array_equal(
        store.collect("dividends"), np.asarray(clean["dividends"])
    )
    assert check_fleet(tmp_path) == []


def test_lease_expiry_steal_requeues_with_attempt_history(tmp_path):
    """A host dies holding a claim (simulated: claims and never
    heartbeats); a surviving host steals after expiry, re-executes, and
    the per-unit attempt history survives in the ledger + tombstones —
    the PR 3 requeue-history semantics one level up."""
    cases = get_cases()[:4]
    lanes = partition_lanes(len(cases), 2)
    store = FleetStore(tmp_path)
    store.ensure_manifest(
        num_units=len(lanes),
        unit_lanes=lanes,
        tag=f"fleet_batch:{VERSION}",
        config={
            "driver": "run_fleet_batch",
            "version": VERSION,
            "num_scenarios": len(cases),
            "unit_size": 2,
            "dtype": "float32",
        },
    )
    # the doomed host claims unit 0 and is never heard from again
    dead = LeaseStore(store.leases_dir, "doomed-host", ttl_seconds=0.2)
    assert dead.try_claim(0) is not None
    time.sleep(0.4)

    out = run_fleet_batch(
        cases,
        VERSION,
        FleetConfig(
            directory=tmp_path,
            host_id="survivor",
            unit_size=2,
            lease_ttl_seconds=0.2,
            poll_seconds=0.05,
        ),
    )
    report = out["report"]
    assert report.units_published == 2
    assert report.units_stolen == 1
    records = merged_ledger(FleetStore(tmp_path))
    stolen = [r for r in records if r.get("event") == "unit_stolen"]
    assert len(stolen) == 1
    assert stolen[0]["unit"] == 0
    assert stolen[0]["prior_host"] == "doomed-host"
    assert stolen[0]["generation"] == 1
    # the winning execution's records carry the steal generation
    ok0 = [
        r
        for r in records
        if r.get("event") == "unit_ok" and r.get("unit") == 0
    ]
    assert len(ok0) == 1 and ok0[0]["generation"] == 1
    # and the durable tombstone backs the count (check_fleet verifies)
    assert LeaseStore(store.leases_dir, "observer").generation(0) == 1
    assert check_fleet(tmp_path) == []


@pytest.mark.faultinject
def test_fleet_nan_lane_quarantines_globally_healthy_lanes_bitwise(tmp_path):
    """A NaN lane inside one fleet unit: globalized quarantine
    provenance in the fleet ledger, healthy lanes bitwise vs clean."""
    from yuma_simulation_tpu.resilience import SweepSupervisor

    cases = get_cases()[:4]
    clean = SweepSupervisor(directory=None, unit_size=2).run_batch(
        cases, VERSION
    )
    with inject_faults(FaultPlan(nan=NaNFault(epoch=2, case=1))):
        out = run_fleet_batch(
            cases,
            VERSION,
            FleetConfig(
                directory=tmp_path, unit_size=2, lease_ttl_seconds=30.0
            ),
        )
    report = out["report"]
    # unit 0 = lanes [0,2) and unit 1 = lanes [2,4): local lane 1 of
    # each unit poisons global lanes 1 and 3
    assert report.lanes_quarantined == 2
    assert out["quarantine"].quarantined_cases == (1, 3)
    for lane in (0, 2):
        np.testing.assert_array_equal(
            out["dividends"][lane], np.asarray(clean["dividends"])[lane]
        )
    for lane in (1, 3):
        np.testing.assert_array_equal(
            out["dividends"][lane][:2],
            np.asarray(clean["dividends"])[lane][:2],
        )
        assert (out["dividends"][lane][2:] == 0).all()
    assert np.isfinite(out["dividends"]).all()
    assert check_fleet(tmp_path) == []


def test_fleet_resume_is_pure_collection(tmp_path):
    """A second fleet run over a completed store claims nothing,
    publishes nothing, and returns the identical result."""
    cases = get_cases()[:4]
    cfg = FleetConfig(
        directory=tmp_path, unit_size=2, lease_ttl_seconds=30.0
    )
    first = run_fleet_batch(cases, VERSION, cfg)
    second = run_fleet_batch(
        cases,
        VERSION,
        FleetConfig(
            directory=tmp_path,
            host_id="late-joiner",
            unit_size=2,
            lease_ttl_seconds=30.0,
        ),
    )
    np.testing.assert_array_equal(first["dividends"], second["dividends"])
    assert second["host"].units_published == 0
    ok = [
        r
        for r in merged_ledger(FleetStore(tmp_path))
        if r.get("event") == "unit_ok"
    ]
    assert len(ok) == 2  # only the first run executed


def test_check_fleet_flags_missing_result_and_tampered_report(tmp_path):
    cases = get_cases()[:4]
    run_fleet_batch(
        cases,
        VERSION,
        FleetConfig(directory=tmp_path, unit_size=2, lease_ttl_seconds=30.0),
    )
    assert check_fleet(tmp_path) == []
    store = FleetStore(tmp_path)
    # tamper the published report: counts must be caught
    report_path = tmp_path / "fleet_report.json"
    data = json.loads(report_path.read_text())
    data["units_stolen"] = 7
    report_path.write_text(json.dumps(data))
    problems = check_fleet(tmp_path)
    assert any("units_stolen" in p for p in problems)
    # remove a result: the unit must be reported lost
    publish_fleet_report(store)  # heal the report first
    store.result_path(1).unlink()
    problems = check_fleet(tmp_path)
    assert any("unit 1" in p and "verified" in p for p in problems)


def test_fleet_report_derivation_is_pure(tmp_path):
    cases = get_cases()[:2]
    run_fleet_batch(
        cases,
        VERSION,
        FleetConfig(directory=tmp_path, unit_size=2, lease_ttl_seconds=30.0),
    )
    a = build_fleet_report(tmp_path)
    b = build_fleet_report(tmp_path)
    assert a == b


# ------------------------------------------------------------ v1 surface


def test_run_simulation_fleet_knob_matches_plain(tmp_path):
    from yuma_simulation_tpu.scenarios import create_case
    from yuma_simulation_tpu.simulation.engine import run_simulation

    case = create_case("Case 2")
    plain = run_simulation(case, VERSION)
    fleet = run_simulation(case, VERSION, fleet=tmp_path)
    assert set(plain[0]) == set(fleet[0])
    for validator in plain[0]:
        np.testing.assert_array_equal(plain[0][validator], fleet[0][validator])
    np.testing.assert_array_equal(np.asarray(plain[1]), np.asarray(fleet[1]))
    np.testing.assert_array_equal(np.asarray(plain[2]), np.asarray(fleet[2]))
    # a second invocation against the same store is pure collection
    again = run_simulation(case, VERSION, fleet=tmp_path)
    for validator in plain[0]:
        np.testing.assert_array_equal(fleet[0][validator], again[0][validator])
    ok = [
        r
        for r in merged_ledger(FleetStore(tmp_path))
        if r.get("event") == "unit_ok"
    ]
    assert len(ok) == 1  # executed exactly once across both calls


def test_dividends_cli_fleet_store_builds_each_sheet_once(tmp_path):
    """The `yuma-dividends --fleet-store` path: the beta sheet builds as
    one lease-claimed unit; a second invocation against the same store
    is pure collection (no rebuild) and writes identical bytes."""
    import pandas as pd

    from yuma_simulation_tpu.cli.total_dividends_sheet_generator import main

    out1, out2 = tmp_path / "o1", tmp_path / "o2"
    store = tmp_path / "store"
    main(
        ["--bond-penalty", "1.0", "--out-dir", str(out1),
         "--fleet-store", str(store)]
    )
    csv_bytes = (out1 / "total_dividends_b1.0.csv").read_bytes()
    df = pd.read_csv(out1 / "total_dividends_b1.0.csv")
    assert len(df) == 14 and not df.isnull().values.any()
    main(
        ["--bond-penalty", "1.0", "--out-dir", str(out2),
         "--fleet-store", str(store)]
    )
    assert (out2 / "total_dividends_b1.0.csv").read_bytes() == csv_bytes
    ok = [
        r
        for r in merged_ledger(FleetStore(store))
        if r.get("event") == "unit_ok"
    ]
    assert len(ok) == 1  # the sheet built exactly once across both runs
    assert check_fleet(store) == []


# --------------------------------------------------------- mesh plumbing


def test_surviving_members_is_the_shared_shrink_filter():
    from yuma_simulation_tpu.parallel import surviving_members

    # fleet rosters: plain host-id strings
    assert surviving_members(["h0", "h1", "h2"], ["h1"]) == ["h0", "h2"]

    # device-like members: identity via .id
    class Dev:
        def __init__(self, i):
            self.id = i

    devs = [Dev(0), Dev(1), Dev(2)]
    assert [d.id for d in surviving_members(devs, [1])] == [0, 2]


# --------------------------------------------------------- fleet grid


def test_run_fleet_grid_matches_supervised_grid_bitwise(tmp_path):
    """ISSUE 8 satellite (ROADMAP item 4 residual): the fleet grid
    driver expands a hyperparameter grid into lease-claimed fleet units
    and lands BITWISE what the single-host supervised grid produces."""
    from yuma_simulation_tpu.fabric import FleetConfig, run_fleet_grid
    from yuma_simulation_tpu.resilience import SweepSupervisor
    from yuma_simulation_tpu.simulation.sweep import config_grid

    case = get_cases()[0]
    axes = {"bond_penalty": [0.0, 0.5, 1.0], "kappa": [0.4, 0.5]}
    out = run_fleet_grid(
        case,
        VERSION,
        FleetConfig(directory=tmp_path, unit_size=4),
        axes=axes,
    )
    configs, points = config_grid(
        **{k: list(v) for k, v in sorted(axes.items())}
    )
    ref = SweepSupervisor(directory=None, unit_size=4).run_grid(
        case, VERSION, configs
    )
    assert out["points"] == points
    np.testing.assert_array_equal(
        np.asarray(out["dividends"]), np.asarray(ref["dividends"])
    )
    # 6 grid points / unit_size 4 -> 2 units, all published by this host.
    assert out["host"].units_published == 2
    assert out["report"].units_published == 2


def test_run_fleet_grid_second_invocation_is_pure_collection(tmp_path):
    """A second host joining after the grid completed publishes nothing
    and collects the full surface — the fleet batch driver's resume
    contract, inherited by the grid driver."""
    from yuma_simulation_tpu.fabric import FleetConfig, run_fleet_grid

    case = get_cases()[0]
    axes = {"bond_penalty": [0.0, 1.0]}
    first = run_fleet_grid(
        case, VERSION, FleetConfig(directory=tmp_path, unit_size=1), axes=axes
    )
    second = run_fleet_grid(
        case,
        VERSION,
        FleetConfig(directory=tmp_path, unit_size=1, host_id="late-joiner"),
        axes=axes,
    )
    assert second["host"].units_published == 0
    np.testing.assert_array_equal(
        np.asarray(first["dividends"]), np.asarray(second["dividends"])
    )


def test_run_fleet_grid_requires_axes_or_configs(tmp_path):
    from yuma_simulation_tpu.fabric import run_fleet_grid

    with pytest.raises(ValueError, match="axes"):
        run_fleet_grid(get_cases()[0], VERSION, tmp_path)
