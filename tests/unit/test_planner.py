"""DispatchPlan (ISSUE 6 tentpole): one decision surface for engine
rung, consensus, ladder, shape bucket and memory plan — plan-driven
dispatch must match the legacy per-caller resolution exactly, plans
must be deterministic pure values, donor packing and the chunked
Monte-Carlo must be bitwise-invariant to how the planner slices them,
and the streamed slab cap must not change results.
"""

import dataclasses
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yuma_simulation_tpu.models.config import YumaConfig, YumaParams
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.simulation.planner import (
    ENGINE_LADDER,
    LANE_TILE,
    SUBLANE_TILE,
    bucket_shape,
    ladder_from,
    plan_dispatch,
    resolve_montecarlo_engine,
    resolve_scaled_engine,
)

from tests.conftest import HAS_JAX_SHARD_MAP

VERSION = "Yuma 1 (paper)"
CFG = YumaConfig()


# ---------------------------------------------------------------------------
# plan determinism + shape


def test_plan_is_deterministic_and_frozen():
    args = ("t", (40, 3, 2), VERSION, CFG, jnp.float32)
    a = plan_dispatch(*args)
    b = plan_dispatch(*args)
    assert a == b
    assert json.dumps(a.to_json(), sort_keys=True) == json.dumps(
        b.to_json(), sort_keys=True
    )
    with pytest.raises(dataclasses.FrozenInstanceError):
        a.engine = "xla"  # type: ignore[misc]


def test_plan_determinism_property():
    """Property sweep: equal inputs -> equal plans across a matrix of
    shapes, versions and knobs (the planner is a pure host function)."""
    for shape in [(1, 3, 2), (40, 6, 18), (5, 256, 300), (4, 10, 8, 16)]:
        for version in (VERSION, "Yuma 2 (Adrian-Fish)"):
            for save_bonds in (False, True):
                kwargs = dict(save_bonds=save_bonds, streaming=True)
                a = plan_dispatch(
                    "p", shape, version, CFG, jnp.float32, **kwargs
                )
                b = plan_dispatch(
                    "p", shape, version, CFG, jnp.float32, **kwargs
                )
                assert a == b, (shape, version, save_bonds)


def test_plan_bad_shape_rejected():
    with pytest.raises(ValueError, match="E, V, M"):
        plan_dispatch("t", (3, 2), VERSION, CFG, jnp.float32)


# ---------------------------------------------------------------------------
# engine resolution (the legacy `_resolve_case_engine` contract)


def test_auto_resolves_to_xla_off_tpu():
    plan = plan_dispatch("t", (10, 6, 18), VERSION, CFG, jnp.float32)
    if jax.default_backend() == "tpu":
        assert plan.engine in ("fused_scan_mxu", "fused_scan")
    else:
        assert plan.engine == "xla"
        assert plan.consensus_impl in ("sorted", "bisect")
    assert plan.ladder == ladder_from(plan.engine)


def test_explicit_fused_preconditions_raise():
    from yuma_simulation_tpu.parallel.mesh import make_mesh

    with pytest.raises(ValueError, match="bisection"):
        plan_dispatch(
            "t", (10, 6, 18), VERSION, CFG, jnp.float32,
            epoch_impl="fused_scan", consensus_impl="sorted",
        )
    with pytest.raises(ValueError, match="single-core"):
        plan_dispatch(
            "t", (10, 6, 18), VERSION, CFG, jnp.float32,
            epoch_impl="fused_scan", mesh=make_mesh(),
        )
    with pytest.raises(ValueError, match="quarantine"):
        plan_dispatch(
            "t", (2, 10, 6, 18), VERSION, CFG, jnp.float32,
            epoch_impl="fused_scan", quarantine=True,
        )
    with pytest.raises(ValueError, match="miner"):
        plan_dispatch(
            "t", (2, 10, 6, 18), VERSION, CFG, jnp.float32,
            epoch_impl="fused_scan", has_miner_mask=True,
        )
    with pytest.raises(ValueError, match="unknown epoch_impl"):
        plan_dispatch(
            "t", (10, 6, 18), VERSION, CFG, jnp.float32,
            epoch_impl="warp",
        )
    with pytest.raises(ValueError, match="unknown consensus_impl"):
        plan_dispatch(
            "t", (10, 6, 18), VERSION, CFG, jnp.float32,
            consensus_impl="median",
        )


def test_auto_forced_to_xla_by_guards():
    for kwargs in (
        dict(quarantine=True),
        dict(has_miner_mask=True),
        dict(consensus_impl="sorted"),
    ):
        plan = plan_dispatch(
            "t", (2, 10, 6, 18), VERSION, CFG, jnp.float32, **kwargs
        )
        assert plan.engine == "xla", kwargs
        assert any("auto->xla" in r for r in plan.reasons)


def test_fallback_consensus_matches_direct_xla_resolution():
    """A demotion off a fused rung must use exactly the consensus a
    direct XLA request would have resolved to."""
    direct = plan_dispatch(
        "t", (10, 6, 18), VERSION, CFG, jnp.float32, epoch_impl="xla",
        consensus_impl="auto",
    )
    fused = plan_dispatch(
        "t", (10, 6, 18), VERSION, CFG, jnp.float32,
        epoch_impl="fused_scan", consensus_impl="auto",
    )
    assert fused.fallback_consensus == direct.consensus_impl


def test_ladder_ownership_shared_with_resilience():
    """retry.py re-exports the planner's ladder — one owner for rung
    ordering AND eligibility."""
    from yuma_simulation_tpu.resilience import retry

    assert retry.ENGINE_LADDER is ENGINE_LADDER
    assert retry.ladder_from is ladder_from
    assert ladder_from("fused_varying_mxu") == ENGINE_LADDER
    assert ladder_from("fused_scan_mxu") == (
        "fused_scan_mxu", "fused_scan", "xla"
    )
    assert ladder_from("hoisted") == ("hoisted",)


def test_throughput_resolutions():
    spec = variant_for_version(VERSION)
    got = resolve_scaled_engine(
        (6, 18), spec.bonds_mode, CFG, jnp.float32, 10
    )
    if jax.default_backend() == "tpu":
        assert got in ("fused_scan_mxu", "fused_scan")
    else:
        assert got == "xla"
    assert resolve_montecarlo_engine("auto", varying=True) == "xla"
    assert resolve_montecarlo_engine("auto", varying=False) == "hoisted"
    with pytest.raises(ValueError, match="hoistable"):
        resolve_montecarlo_engine("hoisted", varying=True)
    with pytest.raises(ValueError, match="unknown epoch_impl"):
        resolve_montecarlo_engine("sorted", varying=False)


# ---------------------------------------------------------------------------
# shape bucket / donor packing


def test_bucket_policy_tile_aligns():
    b = bucket_shape(3, 2, epochs=40, batch=14)
    assert (b.padded_V, b.padded_M) == (SUBLANE_TILE, LANE_TILE)
    assert b.key == "b14e40v8m128"
    # already-aligned shapes are their own bucket
    b2 = bucket_shape(256, 4096)
    assert (b2.padded_V, b2.padded_M) == (256, 4096)
    # suites in the same bucket share a compiled-shape key
    assert bucket_shape(5, 7, epochs=40).key == bucket_shape(
        3, 2, epochs=40
    ).key


def test_pack_scenarios_fills_the_tile():
    from yuma_simulation_tpu.scenarios import create_case
    from yuma_simulation_tpu.simulation.sweep import pack_scenarios

    cases = [create_case("Case 1"), create_case("Case 2")]  # 40e x 3v x 2m
    W, S, ri, re, mask = pack_scenarios(cases)
    assert W.shape == (2, 40, SUBLANE_TILE, LANE_TILE)
    assert S.shape == (2, 40, SUBLANE_TILE)
    np.testing.assert_array_equal(np.asarray(mask[0][:3]), [1.0, 1.0, 0.0])
    assert float(np.asarray(mask).sum()) == 2 * 2  # 2 real miners per case


def test_donor_packed_lanes_bitwise_match_per_case_dispatch():
    """ISSUE 6 acceptance: donor-packed vs per-case dispatch, bitwise.
    Each scenario dispatched ALONE through the same bucket must produce
    bit-for-bit the lane the packed batch produced — packing a suite
    together changes nothing but the batch axis."""
    from yuma_simulation_tpu.models.variants import variant_for_version
    from yuma_simulation_tpu.scenarios import create_case
    from yuma_simulation_tpu.scenarios.synthetic import (
        random_subnet_scenario,
    )
    from yuma_simulation_tpu.simulation.sweep import (
        pack_scenarios,
        simulate_batch,
    )

    suite = [
        create_case("Case 1"),
        random_subnet_scenario(
            1, num_validators=5, num_miners=7, num_epochs=40
        ),
        create_case("Case 4"),  # reset case
    ]
    spec = variant_for_version(VERSION)
    W, S, ri, re, mask = pack_scenarios(suite)
    packed = simulate_batch(
        W, S, ri, re, CFG, spec, miner_mask=mask, epoch_impl="xla"
    )
    for i in range(len(suite)):
        solo = simulate_batch(
            W[i : i + 1],
            S[i : i + 1],
            ri[i : i + 1],
            re[i : i + 1],
            CFG,
            spec,
            miner_mask=mask[i : i + 1],
            epoch_impl="xla",
        )
        np.testing.assert_array_equal(
            np.asarray(packed["dividends"][i]),
            np.asarray(solo["dividends"][0]),
            err_msg=f"lane {i}",
        )


def test_donor_packed_totals_match_unpacked_simulate():
    """Packing is inert per lane: totals through the packed batch agree
    with each scenario simulated raw (same tolerance discipline as
    test_padding — tile padding rides the identical mask mechanism)."""
    from yuma_simulation_tpu.scenarios import create_case
    from yuma_simulation_tpu.scenarios.synthetic import (
        random_subnet_scenario,
    )
    from yuma_simulation_tpu.simulation.engine import simulate
    from yuma_simulation_tpu.simulation.sweep import total_dividends_batch

    suite = [
        create_case("Case 1"),
        create_case("Case 2"),
        # heterogeneous member forces the packed (masked) route
        random_subnet_scenario(
            7, num_validators=5, num_miners=7, num_epochs=40
        ),
    ]
    totals = total_dividends_batch(suite, VERSION)
    assert totals.shape[1] == SUBLANE_TILE  # the packed bucket's V
    for i, s in enumerate(suite):
        solo = simulate(
            s, VERSION, save_bonds=False, save_incentives=False
        ).dividends.sum(axis=0)
        v = len(s.validators)
        np.testing.assert_allclose(
            totals[i, :v], solo, rtol=2e-5, atol=2e-6, err_msg=f"lane {i}"
        )
        assert float(np.abs(totals[i, v:]).max()) == 0.0


# ---------------------------------------------------------------------------
# memory plan / streamed slab cap

SMALL_SPEC = json.dumps(
    {"name": "tiny-dev", "memory_bytes": 300 * 1024 * 1024}
)


def test_memory_plan_monolithic_fit_has_no_chunking(monkeypatch):
    from yuma_simulation_tpu.telemetry.cost import DEVICE_SPEC_ENV

    monkeypatch.setenv(DEVICE_SPEC_ENV, SMALL_SPEC)
    plan = plan_dispatch("t", (10, 6, 18), VERSION, CFG, jnp.float32)
    assert plan.memory.fits is True
    assert plan.memory.chunk_epochs is None


def test_memory_plan_streaming_caps_slabs_instead_of_raising(monkeypatch):
    """A stack that cannot fit monolithically still PLANS under
    streaming=True: no HBMPreflightError, a finite slab cap sized for
    two resident buffers."""
    from yuma_simulation_tpu.telemetry.cost import (
        DEVICE_SPEC_ENV,
        HBMPreflightError,
    )

    monkeypatch.setenv(DEVICE_SPEC_ENV, SMALL_SPEC)
    shape = (100_000, 256, 1024)  # ~100 GB stack on a 300 MiB "device"
    with pytest.raises(HBMPreflightError):
        plan_dispatch("t", shape, VERSION, CFG, jnp.float32)
    plan = plan_dispatch(
        "t", shape, VERSION, CFG, jnp.float32, streaming=True
    )
    assert plan.memory.fits is False
    cap = plan.memory.chunk_epochs
    assert cap is not None and 1 <= cap < 100_000
    # Two slabs of the cap + the working set actually fit the budget.
    from yuma_simulation_tpu.telemetry.cost import estimate_hbm_bytes

    two_slabs = (
        2 * estimate_hbm_bytes(256, 1024, resident_epochs=cap).total_bytes
    )
    assert two_slabs <= 300 * 1024 * 1024


def test_streaming_still_rejects_unfittable_working_set(monkeypatch):
    """Streaming fixes epoch-stack overflow, not working-set overflow:
    when the fixed [V, M] state alone exceeds the budget, no slab
    length helps — the plan must reject with the typed error, and
    YUMA_TPU_PREFLIGHT=0 must disable BOTH the reject and the slab
    re-slicing."""
    from yuma_simulation_tpu.telemetry.cost import (
        DEVICE_SPEC_ENV,
        HBMPreflightError,
        PREFLIGHT_ENV,
    )

    monkeypatch.setenv(
        DEVICE_SPEC_ENV, json.dumps({"name": "dot", "memory_bytes": 512})
    )
    with pytest.raises(HBMPreflightError):
        plan_dispatch(
            "t", (100, 64, 128), VERSION, CFG, jnp.float32, streaming=True
        )
    monkeypatch.setenv(PREFLIGHT_ENV, "0")
    plan = plan_dispatch(
        "t", (100, 64, 128), VERSION, CFG, jnp.float32, streaming=True
    )
    assert plan.memory.chunk_epochs is None  # kill switch: no re-slicing


def test_streamed_respects_plan_slab_cap_bitwise(monkeypatch):
    """ISSUE 6 satellite 1 + acceptance: the streamed driver re-slices
    incoming chunks to the plan's cap (visible as extra per-slab
    dispatches) and the result stays BITWISE the monolithic scan."""
    from tests.unit.test_fused_case_scan import _workload
    from yuma_simulation_tpu.simulation.engine import (
        _simulate_scan,
        simulate_streamed,
    )
    from yuma_simulation_tpu.telemetry.cost import DEVICE_SPEC_ENV

    W, S = _workload(seed=5, E=12)
    spec = variant_for_version(VERSION)
    mono = _simulate_scan(
        W, S, jnp.asarray(2, jnp.int32), jnp.asarray(4, jnp.int32), CFG,
        spec,
    )
    # A spec so tight the plan caps slabs at a couple of epochs: the
    # single 12-epoch chunk below MUST be re-sliced to the cap.
    monkeypatch.setenv(
        DEVICE_SPEC_ENV,
        json.dumps({"name": "nano", "memory_bytes": 7_000}),
    )
    plan = plan_dispatch(
        "t", (12,) + W.shape[1:], VERSION, CFG, jnp.float32,
        streaming=True,
    )
    assert plan.memory.chunk_epochs is not None
    assert 1 <= plan.memory.chunk_epochs < 12
    got = simulate_streamed(
        [(W, S)],
        VERSION,
        CFG,
        reset_bonds_index=2,
        reset_bonds_epoch=4,
        save_bonds=True,
        save_incentives=True,
        epoch_impl="xla",
    )
    np.testing.assert_array_equal(got.dividends, np.asarray(mono["dividends"]))
    np.testing.assert_array_equal(got.bonds, np.asarray(mono["bonds"]))


# ---------------------------------------------------------------------------
# chunked per-epoch Monte-Carlo (the planned batched engine ride)


def test_montecarlo_batched_chunk_invariant_bitwise():
    from yuma_simulation_tpu.parallel.sharded import (
        montecarlo_per_epoch_batched,
    )

    key = jax.random.PRNGKey(5)
    args = (key, 5, 12, 4, 16, VERSION)
    whole = montecarlo_per_epoch_batched(*args, consensus_impl="bisect")
    for cap in (1, 5, 12):
        chunked = montecarlo_per_epoch_batched(
            *args, consensus_impl="bisect", chunk_epochs=cap
        )
        np.testing.assert_array_equal(whole, chunked, err_msg=f"cap={cap}")
    assert whole.shape == (5, 4)
    assert np.isfinite(whole).all()


@pytest.mark.skipif(
    not HAS_JAX_SHARD_MAP, reason="jax.shard_map not in this jax build"
)
def test_montecarlo_batched_bitwise_matches_shard_map_path():
    """The batched XLA rung is the SAME step function as the shard_map
    Monte-Carlo body (keys `split(split(key, 1)[0], B)`), so on one
    device the two are bitwise-identical."""
    from yuma_simulation_tpu.parallel import make_mesh
    from yuma_simulation_tpu.parallel.sharded import (
        montecarlo_per_epoch_batched,
        montecarlo_total_dividends,
    )

    key = jax.random.PRNGKey(5)
    mono = montecarlo_total_dividends(
        key, 5, 12, 4, 16, VERSION, mesh=make_mesh(),
        weights_mode="per_epoch", consensus_impl="bisect",
    )
    batched = montecarlo_per_epoch_batched(
        key, 5, 12, 4, 16, VERSION, consensus_impl="bisect"
    )
    np.testing.assert_array_equal(mono, batched)


def test_montecarlo_batched_fused_interpret_parity():
    """The fused rung (interpret mode off-TPU) agrees with the XLA
    oracle to reduction-order rounding and is itself chunk-invariant
    (the epoch sum accumulates strictly in epoch order)."""
    from yuma_simulation_tpu.parallel.sharded import (
        montecarlo_per_epoch_batched,
    )

    key = jax.random.PRNGKey(3)
    args = (key, 2, 6, 4, 8, VERSION)
    fused = montecarlo_per_epoch_batched(
        *args, epoch_impl="fused_scan", consensus_impl="bisect"
    )
    fused_chunked = montecarlo_per_epoch_batched(
        *args, epoch_impl="fused_scan", consensus_impl="bisect",
        chunk_epochs=2,
    )
    np.testing.assert_array_equal(fused, fused_chunked)
    xla = montecarlo_per_epoch_batched(
        *args, epoch_impl="xla", consensus_impl="bisect"
    )
    np.testing.assert_allclose(fused, xla, rtol=2e-5, atol=2e-6)


# ---------------------------------------------------------------------------
# recording


def test_plan_record_stamps_span_and_event(caplog):
    import logging

    from yuma_simulation_tpu.telemetry.runctx import RunContext, span
    from yuma_simulation_tpu.utils.logging import parse_event_line

    plan = plan_dispatch("rec-test", (10, 6, 18), VERSION, CFG, jnp.float32)
    with caplog.at_level(
        logging.DEBUG, "yuma_simulation_tpu.simulation.planner"
    ):
        with RunContext("run-plan-test") as run:
            with span("dispatch") as s:
                plan.record()
            assert s.attrs["plan"]["engine"] == plan.engine
            assert s.attrs["plan"]["bucket"] == plan.bucket.key
    events = [
        parse_event_line(r.getMessage()) for r in caplog.records
    ]
    events = [e for e in events if e and e["event"] == "dispatch_planned"]
    assert len(events) == 1
    assert events[0]["label"] == "rec-test"
    assert events[0]["engine"] == plan.engine
    # the record carries the run/span identity for the flight bundle
    assert events[0]["run_id"] == run.run_id


def test_liquid_alpha_and_versions_plan_consistently():
    """The plan agrees with what the engines actually accept: every
    named version plans and simulates on the planned engine."""
    from yuma_simulation_tpu.scenarios import create_case
    from yuma_simulation_tpu.simulation.engine import simulate

    case = create_case("Case 2")
    cfg = YumaConfig(yuma_params=YumaParams(liquid_alpha=True))
    for version in (VERSION, "Yuma 2 (Adrian-Fish)", "Yuma 3 (Rhef)"):
        plan = plan_dispatch(
            "t", np.shape(case.weights), version, cfg, jnp.float32
        )
        out = simulate(
            case, version, cfg, save_bonds=False, save_incentives=False,
            epoch_impl=plan.engine,
        )
        assert np.isfinite(out.dividends).all()
