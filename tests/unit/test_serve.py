"""Serving tier: admission, backpressure, coalescing, degradation.

ISSUE 8 acceptance surface. The hostile-traffic contract under test:
every request gets a TYPED response (result / partial-with-quarantine /
429 + Retry-After / structured rejection — zero bare 500s), coalesced
lanes are bitwise the solo dispatch, the queue/shed/breaker metrics are
live on /metrics and in the flight bundle, and a mid-request device
loss degrades into a structured response instead of a 500 (the sharded
half gated on HAS_JAX_SHARD_MAP exactly like the elastic drills)."""

import json
import threading
import time

import numpy as np
import pytest

from yuma_simulation_tpu.resilience import (
    AdmissionRejected,
    DeviceLossFault,
    FaultPlan,
    OverloadFault,
    QueueOverflow,
    classify_failure,
    inject_faults,
)
from yuma_simulation_tpu.scenarios import create_case
from yuma_simulation_tpu.scenarios.synthetic import random_subnet_scenario
from yuma_simulation_tpu.serve import (
    CircuitBreaker,
    ServeConfig,
    SimulationClient,
    SimulationServer,
    SimulationService,
    TokenBucket,
    wait_until_ready,
)

VERSION = "Yuma 1 (paper)"


def _service(**knobs) -> SimulationService:
    knobs.setdefault("coalesce_window_seconds", 0.0)
    return SimulationService(ServeConfig(**knobs))


def _scenario_payload(scenario, **extra) -> dict:
    return {
        "weights": np.asarray(scenario.weights).tolist(),
        "stakes": np.asarray(scenario.stakes).tolist(),
        **extra,
    }


# ---------------------------------------------------------------------------
# quotas / breaker units (pure host logic, injectable clocks)


def test_token_bucket_refills_on_the_clock():
    t = [0.0]
    bucket = TokenBucket(rate=2.0, burst=2, clock=lambda: t[0])
    assert bucket.try_acquire() == 0.0
    assert bucket.try_acquire() == 0.0
    wait = bucket.try_acquire()
    assert wait == pytest.approx(0.5)
    t[0] += 0.5  # one token refilled
    assert bucket.try_acquire() == 0.0
    assert bucket.try_acquire() > 0.0


def test_token_bucket_zero_rate_never_refills():
    bucket = TokenBucket(rate=0.0, burst=1, clock=lambda: 0.0)
    assert bucket.try_acquire() == 0.0
    assert bucket.try_acquire() >= 60.0


def test_breaker_trips_half_opens_and_closes():
    t = [0.0]
    b = CircuitBreaker(threshold=2, cooldown_seconds=10.0, clock=lambda: t[0])
    ladder = ("fused_scan", "xla")
    assert b.filter_ladder(ladder) == ladder
    b.record_failure("fused_scan")
    assert b.filter_ladder(ladder) == ladder  # below threshold
    b.record_failure("fused_scan")  # trips open
    assert b.filter_ladder(ladder) == ("xla",)
    assert b.snapshot()["fused_scan"]["state"] == "open"
    t[0] = 10.0  # cooldown elapsed -> exactly one half-open probe
    assert b.filter_ladder(ladder) == ladder
    assert b.snapshot()["fused_scan"]["state"] == "half_open"
    assert b.filter_ladder(ladder) == ("xla",)  # second caller: still open
    b.record_failure("fused_scan")  # probe failed -> re-open, new cooldown
    assert b.filter_ladder(ladder) == ("xla",)
    t[0] = 20.0
    assert b.filter_ladder(ladder) == ladder  # probe again
    b.record_success("fused_scan")  # probe succeeded -> closed
    assert b.snapshot()["fused_scan"]["state"] == "closed"
    assert b.filter_ladder(ladder) == ladder


def test_breaker_abort_probe_releases_the_latch():
    """A half-open probe dying on a NON-engine failure must not leave
    `probing` latched (which would keep the rung dead forever): abort
    clears the latch, the rung stays open, and the next caller is
    admitted as a fresh probe."""
    t = [0.0]
    b = CircuitBreaker(threshold=1, cooldown_seconds=5.0, clock=lambda: t[0])
    ladder = ("fused_scan", "xla")
    b.record_failure("fused_scan")  # trips
    t[0] = 5.0
    assert b.filter_ladder(ladder) == ladder  # half-open probe admitted
    b.abort_probe("fused_scan")  # probe died on a caller error
    assert b.snapshot()["fused_scan"]["state"] == "open"
    assert b.filter_ladder(ladder) == ladder  # fresh probe, not dead
    b.abort_probe("xla")  # no-op on a non-probing rung
    b.record_success("fused_scan")
    assert b.snapshot()["fused_scan"]["state"] == "closed"


def test_plan_demoted_reanchors_below_only():
    """The breaker's re-anchoring primitive: `DispatchPlan.demoted`
    walks DOWN the plan's own ladder (never upgrades), switches to the
    pre-resolved XLA fallback consensus, and records why."""
    import jax.numpy as jnp

    from yuma_simulation_tpu.models.config import YumaConfig
    from yuma_simulation_tpu.simulation.planner import plan_dispatch

    plan = plan_dispatch(
        "breaker-test",
        (40, 3, 2),
        VERSION,
        YumaConfig(),
        jnp.float32,
        epoch_impl="fused_scan",
        quarantine=False,
    )
    assert plan.demoted("fused_scan") is plan  # same rung: no-op
    lower = plan.demoted("xla")
    assert lower.engine == "xla"
    assert lower.ladder == ("xla",)
    assert lower.consensus_impl == plan.fallback_consensus
    assert any("circuit breaker" in r for r in lower.reasons)
    with pytest.raises(ValueError, match="walks DOWN|only walks DOWN"):
        lower.demoted("fused_scan")


def test_tenant_quota_table_is_bounded():
    """A hostile client minting a fresh tenant per request cannot grow
    the bucket table without bound; negotiated-override tenants are
    pinned through the flood."""
    from yuma_simulation_tpu.serve import TenantQuotas

    q = TenantQuotas(
        rate=1.0,
        burst=1,
        overrides={"vip": (5.0, 5)},
        clock=lambda: 0.0,
        max_tenants=8,
    )
    q.bucket("vip")
    for i in range(100):
        q.bucket(f"hostile-{i}")
    assert len(q._buckets) <= 8
    assert "vip" in q._buckets  # the override tenant survived eviction


def test_breaker_never_opens_the_last_rung():
    b = CircuitBreaker(threshold=1, cooldown_seconds=1e9, clock=lambda: 0.0)
    b.record_failure("xla")
    b.record_failure("xla")
    assert b.filter_ladder(("xla",)) == ("xla",)


# ---------------------------------------------------------------------------
# admission


def test_admission_rejects_malformed_payloads():
    svc = _service(start_dispatcher=False)
    try:
        for payload in (
            [],  # not an object
            {"weights": [[1.0]]},  # wrong rank, no stakes
            {"case": "No Such Case"},
            {"case": "Case 1", "version": "Yuma 99"},
            {"case": "Case 1", "engine": "warp_drive"},
            {"case": "Case 1", "deadline_seconds": -5},
            {"case": "Case 1", "config": {"liquid_alpha": 1.0}},
            {"case": "Case 1", "engine": "fused_scan", "quarantine": True},
            {
                "weights": np.zeros((2, 3, 4)).tolist(),
                "stakes": np.zeros((2, 2)).tolist(),  # mismatched V
            },
        ):
            status, body, _ = svc.handle("simulate", payload)
            assert status == 400, (payload, body)
            assert body["error"] == "AdmissionRejected"
            assert body["status"] == "rejected"
    finally:
        svc.close()


def test_admission_accepts_reset_bonds_knobs():
    """The explicit-array surface's ``reset_bonds_index`` /
    ``reset_bonds_epoch`` knobs thread into the built Scenario (and
    non-integers are rejected) — this is also the wirecheck producer
    evidence that the fields admission reads ARE part of the wire
    contract, not dead parser surface."""
    from yuma_simulation_tpu.resilience.errors import AdmissionRejected
    from yuma_simulation_tpu.serve.admission import admit

    kw = dict(
        request_id="r1", kind="simulate", default_deadline_seconds=30.0
    )
    payload = {
        "weights": np.zeros((2, 2, 3)).tolist(),
        "stakes": np.ones((2, 2)).tolist(),
        "reset_bonds_index": 1,
        "reset_bonds_epoch": 1,
    }
    ticket = admit(payload, **kw)
    assert ticket.scenario.reset_bonds_index == 1
    assert ticket.scenario.reset_bonds_epoch == 1
    with pytest.raises(AdmissionRejected):
        admit(dict(payload, reset_bonds_index="one"), **kw)


def test_admission_clamps_priority_to_negotiated_ceiling():
    """The payload ``priority`` field is untrusted: with a
    ``tenant_priority`` ceiling table installed, a tenant rides at most
    its negotiated entry (absent tenants at 0) — a client cannot opt
    out of SLO-driven shedding by claiming priority in the body. No
    table (default) keeps the payload-trusting behavior."""
    from yuma_simulation_tpu.serve.admission import admit

    kw = dict(
        request_id="r1", kind="simulate", default_deadline_seconds=30.0
    )
    assert admit({"case": "Case 1", "priority": 7}, **kw).priority == 7
    assert (
        admit(
            {"case": "Case 1", "priority": 7}, tenant_priority={}, **kw
        ).priority
        == 0
    )
    assert (
        admit(
            {"case": "Case 1", "tenant": "vip", "priority": 7},
            tenant_priority={"vip": 2},
            **kw,
        ).priority
        == 2
    )
    assert (
        admit(
            {"case": "Case 1", "tenant": "vip", "priority": 1},
            tenant_priority={"vip": 2},
            **kw,
        ).priority
        == 1
    )


def test_admission_preflight_rejects_with_suggestion(monkeypatch):
    """The analytic HBM preflight prices the request BEFORE any compile:
    under a nano device spec the shape is rejected with the planner's
    stream/shard suggestion in the structured 400."""
    monkeypatch.setenv(
        "YUMA_TPU_DEVICE_SPEC",
        json.dumps({"name": "nano-serve", "memory_bytes": 16384}),
    )
    svc = _service(start_dispatcher=False)
    try:
        scenario = random_subnet_scenario(
            0, num_validators=8, num_miners=16, num_epochs=40
        )
        status, body, _ = svc.handle(
            "simulate", _scenario_payload(scenario, tenant="big")
        )
        assert status == 400
        assert body["reason"] == "preflight_rejected"
        assert "suggestion" in body
    finally:
        svc.close()


def test_admission_caps_sweep_grid_cardinality():
    """A hostile `axes` payload whose cartesian product explodes is
    rejected at admission — the grid is materialized host-side at
    dispatch, so unbounded points would be a host-memory DoS the array
    ceilings cannot catch."""
    svc = _service(start_dispatcher=False)
    try:
        status, body, _ = svc.handle(
            "sweep",
            {
                "tenant": "hostile",
                "case": "Case 1",
                "axes": {
                    "kappa": list(np.linspace(0.1, 0.9, 100)),
                    "bond_alpha": list(np.linspace(0.1, 0.9, 100)),
                    "bond_penalty": list(np.linspace(0.0, 1.0, 100)),
                },
            },
        )
        assert status == 400
        assert body["error"] == "AdmissionRejected"
        assert "points" in body["message"]
    finally:
        svc.close()


def test_classify_failure_never_reclassifies_serve_errors():
    """PR 3/PR 7 marker discipline: the typed serve errors are decisions,
    not messages — phrasings that LOOK like stall/host-loss/resource
    markers must not re-classify them into retryable engine failures."""
    for exc in (
        AdmissionRejected(
            "heartbeat timeout: connection reset by peer "
            "(a hostile payload could phrase anything)"
        ),
        AdmissionRejected("RESOURCE_EXHAUSTED out of memory"),
        QueueOverflow("deadline exceeded: collective operation timed out"),
        QueueOverflow("coordinator unreachable; worker task died"),
    ):
        assert classify_failure(exc) is None, exc
    # The typed payload survives for the HTTP layer.
    exc = QueueOverflow("shed", retry_after=2.5, queue_depth=7)
    assert exc.retry_after == 2.5 and exc.queue_depth == 7 and exc.retryable
    rej = AdmissionRejected("no", reason="preflight_rejected", suggestion="s")
    assert rej.reason == "preflight_rejected" and rej.suggestion == "s"


# ---------------------------------------------------------------------------
# backpressure


def test_tenant_quota_sheds_with_retry_after():
    svc = _service(
        tenant_overrides={"greedy": (0.0, 2)}, start_dispatcher=False
    )
    try:
        results = []
        threads = [
            threading.Thread(
                target=lambda: results.append(
                    svc.handle("simulate", {"tenant": "greedy", "case": "Case 1"})
                )
            )
            for _ in range(2)
        ]
        # The first two requests hold the tenant's whole burst; they sit
        # queued (no dispatcher) while the third arrives.
        for th in threads:
            th.start()
        for _ in range(100):
            if len(svc.queue) == 2:
                break
            time.sleep(0.05)
        status, body, headers = svc.handle(
            "simulate", {"tenant": "greedy", "case": "Case 1"}
        )
        assert status == 429
        assert body["error"] == "QueueOverflow"
        assert body["retry_after"] > 0
        assert "Retry-After" in headers
        # Another tenant's bucket is untouched: queued fine.
        svc.start_dispatcher()
        status2, body2, _ = svc.handle(
            "simulate", {"tenant": "polite", "case": "Case 1"}
        )
        assert status2 == 200 and body2["status"] == "ok"
        for th in threads:
            th.join(timeout=120)
        assert [s for s, _b, _h in results] == [200, 200]
    finally:
        svc.close()


def test_queue_bound_sheds_with_retry_after():
    svc = _service(queue_limit=2, start_dispatcher=False)
    try:
        results = []
        threads = [
            threading.Thread(
                target=lambda i=i: results.append(
                    svc.handle("simulate", {"tenant": f"t{i}", "case": "Case 1"})
                )
            )
            for i in range(2)
        ]
        for th in threads:
            th.start()
        for _ in range(100):
            if len(svc.queue) == 2:
                break
            time.sleep(0.05)
        status, body, headers = svc.handle(
            "simulate", {"tenant": "t9", "case": "Case 1"}
        )
        assert status == 429 and body["error"] == "QueueOverflow"
        assert headers.get("Retry-After")
        assert svc.registry.counter("serve_requests_shed").value >= 1
        svc.start_dispatcher()
        for th in threads:
            th.join(timeout=120)
        assert [s for s, _b, _h in results] == [200, 200]
    finally:
        svc.close()


@pytest.mark.faultinject
def test_overload_burst_sheds_and_server_recovers():
    """The OverloadFault drill: a synthetic admission-layer burst fills
    the bounded queue, the real request sheds 429 with Retry-After, the
    shed counter moves — and once the burst drains, the same request
    succeeds. The server never answers anything untyped."""
    svc = _service(queue_limit=4, start_dispatcher=False)
    try:
        shed_before = svc.registry.counter("serve_requests_shed").value
        with inject_faults(FaultPlan(overload=OverloadFault(requests=12))):
            status, body, headers = svc.handle(
                "simulate", {"tenant": "victim", "case": "Case 1"}
            )
        assert status == 429 and body["error"] == "QueueOverflow"
        assert headers.get("Retry-After")
        # 12-burst into a 4-slot queue: >= 8 synthetic sheds + the victim.
        assert (
            svc.registry.counter("serve_requests_shed").value
            >= shed_before + 9
        )
        svc.start_dispatcher()  # drain the synthetic burst
        status2, body2, _ = svc.handle(
            "simulate", {"tenant": "victim", "case": "Case 1"}
        )
        assert status2 == 200 and body2["status"] == "ok"
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# coalescing: bitwise vs solo, under concurrency


def _soak_payloads():
    """Two shape buckets: the built-in [40,3,2] cases and a 40x10x7
    synthetic family (padded_V 16 vs 8 — distinct buckets by the
    planner's tile policy)."""
    payloads = [
        {"tenant": "a", "case": "Case 1"},
        {"tenant": "b", "case": "Case 2"},
        {"tenant": "c", "case": "Case 4"},  # reset-bonds case
    ]
    for seed in (1, 2, 3):
        payloads.append(
            _scenario_payload(
                random_subnet_scenario(
                    seed, num_validators=10, num_miners=7, num_epochs=40
                ),
                tenant=f"s{seed}",
            )
        )
    return payloads


def test_concurrent_soak_coalesced_bitwise_vs_solo():
    """N threads x mixed shapes through one server: every response is a
    typed 200, same-bucket requests coalesce into shared dispatches,
    and every coalesced result is BITWISE the solo dispatch of the same
    request (the donor-packing contract, end to end)."""
    payloads = _soak_payloads()

    # Solo oracle: same service pipeline, coalescing off, sequential.
    solo_svc = _service()
    try:
        solo = [
            solo_svc.handle("simulate", dict(p)) for p in payloads
        ]
    finally:
        solo_svc.close()
    assert all(s == 200 for s, _b, _h in solo)

    # Soak: queue everything BEFORE the dispatcher starts, so grouping
    # is deterministic (first pop sweeps all bucket-mates).
    svc = _service(
        coalesce_window_seconds=0.05, max_batch=8, start_dispatcher=False
    )
    try:
        results: dict[int, tuple] = {}
        threads = [
            threading.Thread(
                target=lambda i=i: results.__setitem__(
                    i, svc.handle("simulate", dict(payloads[i]))
                )
            )
            for i in range(len(payloads))
        ]
        for th in threads:
            th.start()
        for _ in range(200):
            if len(svc.queue) == len(payloads):
                break
            time.sleep(0.05)
        assert len(svc.queue) == len(payloads)
        svc.start_dispatcher()
        for th in threads:
            th.join(timeout=300)
        assert sorted(results) == list(range(len(payloads)))

        coalesced_counts = []
        for i, payload in enumerate(payloads):
            status, body, _ = results[i]
            assert status == 200, body
            assert body["status"] == "ok"
            coalesced_counts.append(body["coalesced"])
            _s, solo_body, _h = solo[i]
            # Bitwise: the exact float lists of the solo dispatch.
            assert body["dividends"] == solo_body["dividends"], (
                f"request {i} coalesced result diverged from solo"
            )
            assert body["total_dividends"] == solo_body["total_dividends"]
        # Both buckets actually coalesced (3 members each).
        assert max(coalesced_counts) >= 2
        assert (
            svc.registry.counter("serve_coalesced_lanes").value >= 4
        )
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# graceful degradation


@pytest.mark.faultinject
def test_breaker_trips_fleet_wide_after_typed_failures():
    """Repeated typed failures on an explicitly requested fused rung:
    each request individually demotes to xla (typed, 200), and after
    `breaker_threshold` of them the rung trips — subsequent requests
    start at xla with ZERO demotions (no latency paid to the dead rung)
    until the cooldown's half-open probe."""
    svc = _service(
        breaker_threshold=2, breaker_cooldown_seconds=3600.0
    )
    payload = {
        "tenant": "fused-power-user",
        "case": "Case 1",
        "engine": "fused_scan",
        "quarantine": False,
    }
    try:
        with inject_faults(FaultPlan(fused_oom_dispatches=1000)):
            for i in range(2):
                status, body, _ = svc.handle("simulate", dict(payload))
                assert status == 200, body
                assert body["report"]["engine_demotions"] >= 1, (i, body)
                assert body["report"]["engines_used"] == ["xla"]
            # Tripped: the fused rung is skipped fleet-wide, so the
            # fault (which only fires on fused dispatches) never fires
            # and no demotion latency is paid.
            status, body, _ = svc.handle("simulate", dict(payload))
            assert status == 200
            assert body["report"]["engine_demotions"] == 0
            assert body["report"]["engines_used"] == ["xla"]
        assert svc.breaker.snapshot()["fused_scan"]["state"] == "open"
        assert svc.registry.counter("serve_breaker_trips").value >= 1
    finally:
        svc.close()


@pytest.mark.chaos
def test_nan_lane_returns_partial_not_500():
    """A request whose simulation goes non-finite comes back as a
    structured PARTIAL response carrying the quarantine provenance —
    never a 500 — and a healthy request coalesced into the same
    dispatch stays bitwise clean."""
    from yuma_simulation_tpu.resilience import NaNFault

    solo_svc = _service()
    try:
        _s, clean_body, _h = solo_svc.handle(
            "simulate", {"tenant": "clean", "case": "Case 2"}
        )
    finally:
        solo_svc.close()

    svc = _service(max_batch=4, start_dispatcher=False)
    try:
        results: dict[int, tuple] = {}
        payloads = [
            {"tenant": "poisoned", "case": "Case 1"},
            {"tenant": "clean", "case": "Case 2"},
        ]
        threads = [
            threading.Thread(
                target=lambda i=i: results.__setitem__(
                    i, svc.handle("simulate", dict(payloads[i]))
                )
            )
            for i in range(2)
        ]
        for th in threads:
            th.start()
        for _ in range(100):
            if len(svc.queue) == 2:
                break
            time.sleep(0.05)
        with inject_faults(FaultPlan(nan=NaNFault(epoch=2, case=0))):
            svc.start_dispatcher()
            for th in threads:
                th.join(timeout=300)
        status0, body0, _ = results[0]
        status1, body1, _ = results[1]
        assert status0 == 200 and body0["status"] == "partial"
        assert body0["quarantine"][0]["epoch"] == 2
        assert body0["degraded"] is True
        # The healthy tenant in the SAME coalesced dispatch: clean and
        # bitwise identical to its unfaulted solo run.
        assert status1 == 200 and body1["status"] == "ok"
        assert body1["coalesced"] == 2
        assert body1["dividends"] == clean_body["dividends"]
    finally:
        svc.close()


@pytest.mark.chaos
def test_device_loss_mid_request_returns_structured_degraded():
    """Mid-request device loss: the elastic mesh shrinks under the
    supervisor, the response is a structured 200 with the degradation
    visible (mesh_shrinks, degraded=true), and the server keeps serving.
    Gated on HAS_JAX_SHARD_MAP exactly like the elastic drills."""
    from yuma_simulation_tpu.parallel import make_mesh

    mesh = make_mesh()
    lost = mesh.devices.flat[1].id
    svc = _service(mesh=mesh, default_deadline_seconds=240.0)
    payload = {"tenant": "sharded", "case": "Case 1"}
    try:
        status, body, _ = svc.handle("simulate", dict(payload))  # warm
        assert status == 200, body
        with inject_faults(
            FaultPlan(device_loss=DeviceLossFault(device_id=lost))
        ):
            status, body, _ = svc.handle("simulate", dict(payload))
        assert status == 200, body
        assert body["status"] == "ok"
        assert body["degraded"] is True
        assert body["report"]["mesh_shrinks"] >= 1
        # The server survived: next request is clean.
        status, body, _ = svc.handle("simulate", dict(payload))
        assert status == 200 and body["degraded"] is False
    finally:
        svc.close()


def test_deadline_exhausted_while_queued_is_typed():
    svc = _service(start_dispatcher=False, default_deadline_seconds=0.2)
    try:
        result = {}
        th = threading.Thread(
            target=lambda: result.setdefault(
                "r", svc.handle("simulate", {"tenant": "late", "case": "Case 1"})
            )
        )
        th.start()
        for _ in range(100):
            if len(svc.queue) == 1:
                break
            time.sleep(0.02)
        time.sleep(0.3)  # let the deadline lapse while queued
        svc.start_dispatcher()
        th.join(timeout=60)
        status, body, _ = result["r"]
        assert status == 504
        assert body["error"] == "DeadlineExhausted" and body["retryable"]
    finally:
        svc.close()


def test_shutdown_is_graceful_and_typed():
    svc = _service()
    status, body, _ = svc.handle("simulate", {"tenant": "x", "case": "Case 1"})
    assert status == 200
    svc.close()
    svc.close()  # idempotent
    status, body, _ = svc.handle("simulate", {"tenant": "x", "case": "Case 1"})
    assert status == 503 and body["status"] == "shutting_down"


# ---------------------------------------------------------------------------
# sweep / table endpoints


def test_sweep_endpoint_matches_direct_grid():
    from yuma_simulation_tpu.resilience.supervisor import SweepSupervisor
    from yuma_simulation_tpu.simulation.sweep import config_grid

    svc = _service()
    try:
        status, body, _ = svc.handle(
            "sweep",
            {
                "tenant": "grid",
                "case": "Case 1",
                "axes": {"bond_penalty": [0.0, 0.5, 1.0]},
            },
        )
        assert status == 200 and body["status"] == "ok", body
        assert [p["bond_penalty"] for p in body["points"]] == [0.0, 0.5, 1.0]
        configs, _points = config_grid(bond_penalty=[0.0, 0.5, 1.0])
        ref = SweepSupervisor(directory=None, unit_size=8).run_grid(
            create_case("Case 1"), VERSION, configs
        )
        np.testing.assert_array_equal(
            np.asarray(body["total_dividends"]),
            np.asarray(ref["dividends"]).sum(axis=1),
        )
    finally:
        svc.close()


def test_table_endpoint_returns_csv():
    svc = _service()
    try:
        status, body, _ = svc.handle(
            "table", {"tenant": "csv", "versions": [VERSION]}
        )
        assert status == 200 and body["status"] == "ok"
        assert body["csv"].startswith("Case,")
        assert "Case 1" in body["csv"]
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# HTTP layer + flight bundle + obsreport


def test_http_server_end_to_end(tmp_path):
    bundle_dir = tmp_path / "serve-bundle"
    server = SimulationServer(
        ServeConfig(
            coalesce_window_seconds=0.0, bundle_dir=str(bundle_dir)
        )
    ).start()
    try:
        assert wait_until_ready(server.url)
        client = SimulationClient(server.url, tenant="alice")
        r = client.simulate(case="Case 1")
        assert r.status == 200 and r.ok, r.body
        bad = client.simulate(weights=[[1.0]])
        assert bad.status == 400 and bad.body["error"] == "AdmissionRejected"
        health = client.healthz()
        assert health.status == 200 and health.body["status"] == "ok"
        assert health.body["requests_total"] >= 2
        metrics = client.metrics()
        for series in (
            "serve_queue_depth",
            "serve_requests_shed",
            "serve_breaker_open",
            "serve_requests_total",
            "serve_request_seconds",
        ):
            assert series in metrics, series
        missing = client._request("POST", "/v1/nope", {})
        assert missing.status == 404
    finally:
        server.close()

    # The flight bundle is sound (obsreport --check's gate) and renders
    # the per-tenant request timeline.
    from tools.obsreport import render, render_serve
    from yuma_simulation_tpu.telemetry.flight import check_bundle, load_bundle

    bundle = load_bundle(bundle_dir)
    assert check_bundle(bundle) == []
    run_id = bundle.latest_run_id()
    serve_lines = "\n".join(render_serve(bundle, run_id))
    assert "tenant alice" in serve_lines
    assert "request:" in serve_lines
    full = render(bundle, run_id)
    assert "serve requests" in full
    # The acceptance metrics land in the bundle snapshot too.
    last = bundle.metrics[-1]
    assert "serve_queue_depth" in last["gauges"]
    assert "serve_requests_shed" in last["counters"]
    assert "serve_breaker_trips" in last["counters"]


def test_http_rejects_undecodable_body():
    import urllib.error
    import urllib.request

    server = SimulationServer(ServeConfig(start_dispatcher=True)).start()
    try:
        assert wait_until_ready(server.url)
        req = urllib.request.Request(
            server.url + "/v1/simulate",
            data=b"{not json",
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                status = resp.status
                body = json.loads(resp.read().decode())
        except urllib.error.HTTPError as err:
            status = err.code
            body = json.loads(err.read().decode())
        assert status == 400 and body["error"] == "InvalidJSON"
    finally:
        server.close()


def test_debug_ops_endpoints_end_to_end(tmp_path):
    """ISSUE 19 live ops plane over HTTP: /debug/vars and /debug/spans
    answer during traffic, POST /debug/profile is single-flight (the
    concurrent second request gets a typed 409 naming the active
    window), the auto-stop deadline publishes the trace artifact into
    the bundle, and a rejected request gets a typed 400."""
    import pathlib

    from yuma_simulation_tpu.telemetry.flight import load_bundle

    bundle_dir = tmp_path / "ops-bundle"
    server = SimulationServer(
        ServeConfig(
            coalesce_window_seconds=0.0,
            bundle_dir=str(bundle_dir),
            flight_rotation=True,
        )
    ).start()
    try:
        assert wait_until_ready(server.url)
        client = SimulationClient(server.url, tenant="ops")
        assert client.simulate(case="Case 1").ok

        v = client.debug_vars()
        assert v.status == 200
        assert v.body["profile"]["active"] is False
        assert "segments" in v.body
        assert v.body["metrics"]["counters"]["serve_requests_total"] >= 1

        s = client.debug_spans()
        assert s.status == 200 and s.body["run_id"]

        started = client.debug_profile(seconds=0.5)
        assert started.status == 200, started.body
        assert started.body["profile"]["mode"] == "trace"
        busy = client.debug_profile(seconds=0.5)
        assert busy.status == 409 and busy.body["error"] == "ProfileBusy"
        assert (
            busy.body["active"]["serial"]
            == started.body["profile"]["serial"]
        )

        # the deadline auto-stop publishes without an operator stop
        # (generous deadline: jax's stop_trace writes the capture to
        # disk, which crawls when the suite shards run concurrently)
        deadline = time.time() + 90.0
        profiles = bundle_dir / "profiles.jsonl"
        records: list = []
        while time.time() < deadline:
            if profiles.exists():
                records = [
                    json.loads(line)
                    for line in profiles.read_text().splitlines()
                ]
                if any(
                    r["event"] == "profile_published" for r in records
                ):
                    break
            time.sleep(0.05)
        assert records, "profile never published before the deadline"
        assert records[-1]["event"] == "profile_published"
        assert pathlib.Path(records[-1]["artifact"]).exists()

        bad = client.debug_profile(seconds=-1.0)
        assert bad.status == 400 and bad.body["error"] == "InvalidRequest"
    finally:
        server.close()

    # the published capture is registered in the (segmented) bundle
    bundle = load_bundle(bundle_dir)
    assert bundle.profiles
    assert bundle.profiles[-1]["event"] == "profile_published"
