"""Monte-Carlo bridge (ISSUE 12 tentpole pillar 4): seeded parameter
sampling, generated-suite carriers (batch / supervised / fleet), and
the run_fleet_grid bitwise round-trip over a sampled config population
on a DSL-compiled scenario."""

import numpy as np
import pytest

from yuma_simulation_tpu.foundry import (
    Choice,
    IntRange,
    LogUniform,
    OneHot,
    ScenarioSpec,
    Stakes,
    Uniform,
    at_epochs,
    builtin_case_specs,
    compile_spec,
    derived_seed,
    montecarlo_config_batch,
    montecarlo_suite,
    run_montecarlo,
    sample_params,
    sequence,
)

VERSION = "Yuma 1 (paper)"


def _drifting_spec(seed: int = 0, shift_epoch: int = 5,
                   stake: float = 0.6) -> ScenarioSpec:
    """A tiny DSL builder parameterized the way a Monte-Carlo study
    samples it: shift epoch and anchor stake vary per draw."""
    rest = (1.0 - stake) / 2.0
    return ScenarioSpec(
        name=f"mc drift (seed={seed})",
        validators=("anchor", "a", "b"),
        base_validator="anchor",
        num_miners=2,
        num_epochs=10,
        stakes=sequence(Stakes((stake, rest, rest))),
        weights=sequence(
            at_epochs(OneHot((0, 0, 0)), 0, int(shift_epoch)),
            at_epochs(OneHot((1, 1, 1)), int(shift_epoch)),
        ),
    )


# ------------------------------------------------------------- sampling


def test_sample_params_is_deterministic_and_typed():
    dists = {
        "stake": Uniform(0.4, 0.7),
        "shift_epoch": IntRange(2, 7),
        "family": Choice(("copier", "cartel")),
        "sigma": LogUniform(0.01, 0.1),
        "constant": 3,
    }
    a = sample_params(dists, 5, seed=42)
    b = sample_params(dists, 5, seed=42)
    assert a == b
    assert all(0.4 <= p["stake"] <= 0.7 for p in a)
    assert all(2 <= p["shift_epoch"] <= 7 for p in a)
    assert all(p["family"] in ("copier", "cartel") for p in a)
    assert all(0.01 <= p["sigma"] <= 0.1 for p in a)
    assert all(p["constant"] == 3 for p in a)
    assert sample_params(dists, 5, seed=43) != a


def test_sample_params_prefix_is_stable():
    dists = {"x": Uniform(0.0, 1.0)}
    long = sample_params(dists, 8, seed=7)
    short = sample_params(dists, 3, seed=7)
    assert long[:3] == short


def test_derived_seed_is_stable_and_spread():
    assert derived_seed(1, 0) == derived_seed(1, 0)
    seeds = {derived_seed(1, i) for i in range(64)}
    assert len(seeds) == 64


def test_montecarlo_suite_compiles_spec_draws():
    scenarios, points = montecarlo_suite(
        _drifting_spec,
        {"shift_epoch": IntRange(2, 7), "stake": Uniform(0.4, 0.7)},
        4,
        seed=0,
    )
    assert len(scenarios) == len(points) == 4
    shapes = {s.weights.shape for s in scenarios}
    assert shapes == {(10, 3, 2)}
    # draws actually vary
    assert len({s.weights.tobytes() for s in scenarios}) > 1


def test_montecarlo_suite_accepts_adversarial_builders():
    from yuma_simulation_tpu.foundry import weight_copier_scenario

    scenarios, _ = montecarlo_suite(
        lambda seed, lag: weight_copier_scenario(int(seed), lag=int(lag)),
        {"lag": IntRange(1, 2)},
        3,
        seed=5,
    )
    assert len(scenarios) == 3


# ------------------------------------------------------------- carriers


def test_generated_suite_batch_vs_supervised_is_bitwise():
    """The same generated population lands bit-for-bit identical
    dividends on the plain batched engine and the full supervised
    tier."""
    scenarios, _ = montecarlo_suite(
        _drifting_spec,
        {"shift_epoch": IntRange(2, 7), "stake": Uniform(0.4, 0.7)},
        5,
        seed=1,
    )
    plain = run_montecarlo(scenarios, VERSION, route="batch")
    supervised = run_montecarlo(scenarios, VERSION, route="supervised")
    np.testing.assert_array_equal(
        plain["dividends"], np.asarray(supervised["dividends"])
    )


def test_generated_suite_fleet_vs_supervised_is_bitwise(tmp_path):
    """The fleet carrier (lease-claimed units over a shared store)
    reproduces the supervised dividends bitwise for a generated
    population."""
    from yuma_simulation_tpu.fabric import FleetConfig

    scenarios, _ = montecarlo_suite(
        _drifting_spec,
        {"shift_epoch": IntRange(2, 7), "stake": Uniform(0.4, 0.7)},
        4,
        seed=2,
    )
    fleet = run_montecarlo(
        scenarios,
        VERSION,
        route="fleet",
        fleet=FleetConfig(directory=tmp_path, unit_size=2),
    )
    supervised = run_montecarlo(scenarios, VERSION, route="supervised")
    np.testing.assert_array_equal(
        np.asarray(fleet["dividends"]),
        np.asarray(supervised["dividends"]),
    )


def test_unknown_route_is_rejected():
    scenario = compile_spec(_drifting_spec())
    with pytest.raises(ValueError, match="unknown route"):
        run_montecarlo([scenario], VERSION, route="teleport")
    with pytest.raises(ValueError, match="mesh"):
        run_montecarlo([scenario], VERSION, route="sharded")
    with pytest.raises(ValueError, match="fleet"):
        run_montecarlo([scenario], VERSION, route="fleet")


# -------------------------------------------- config-space MC -> fleet


def test_montecarlo_config_batch_is_seeded_and_batched():
    import jax

    configs, points = montecarlo_config_batch(
        {"kappa": Uniform(0.4, 0.6), "bond_alpha": LogUniform(0.02, 0.3)},
        6,
        seed=3,
    )
    assert len(points) == 6
    leaves = [leaf for leaf in jax.tree.leaves(configs)]
    assert all(leaf.shape[0] == 6 for leaf in leaves)
    again, points2 = montecarlo_config_batch(
        {"kappa": Uniform(0.4, 0.6), "bond_alpha": LogUniform(0.02, 0.3)},
        6,
        seed=3,
    )
    assert points == points2


def test_montecarlo_config_batch_rejects_static_fields():
    with pytest.raises(ValueError, match="static"):
        montecarlo_config_batch({"liquid_alpha": Choice((True, False))},
                                2, seed=0)


def test_config_montecarlo_round_trips_fleet_grid_bitwise(tmp_path):
    """The acceptance pin: a Monte-Carlo sample over hyperparameters of
    a DSL-compiled scenario round-trips through `run_fleet_grid`
    BITWISE against the single-host supervised grid."""
    from yuma_simulation_tpu.fabric import FleetConfig, run_fleet_grid
    from yuma_simulation_tpu.resilience import SweepSupervisor

    scenario = compile_spec(builtin_case_specs()["Case 1"])
    configs, points = montecarlo_config_batch(
        {"kappa": Uniform(0.35, 0.65), "bond_penalty": Uniform(0.0, 1.0)},
        5,
        seed=4,
    )
    fleet_out = run_fleet_grid(
        scenario,
        VERSION,
        FleetConfig(directory=tmp_path, unit_size=2),
        configs=configs,
        points=points,
    )
    ref = SweepSupervisor(directory=None, unit_size=2).run_grid(
        scenario, VERSION, configs
    )
    assert fleet_out["points"] == points
    np.testing.assert_array_equal(
        np.asarray(fleet_out["dividends"]), np.asarray(ref["dividends"])
    )


# ------------------------------------------------------------- drill CLI


def test_drill_suite_is_deterministic():
    from yuma_simulation_tpu.foundry.__main__ import build_drill_suite

    a = build_drill_suite(0, 8)
    b = build_drill_suite(0, 8)
    assert len(a) == 8
    for sa, sb in zip(a, b):
        np.testing.assert_array_equal(sa.weights, sb.weights)
        np.testing.assert_array_equal(sa.stakes, sb.stakes)
