"""Golden parity: the full total-dividends surface vs the CPU reference.

The parity artifact (SURVEY.md §3.2) is the 14 cases x 9 versions x
3 validators x 4 bond_penalty CSV set written by the reference's
`total_dividends_sheet_generator`. `tests/golden/*_full.csv` pins those
values at full float precision (generated from the reference in this
container); every value must match to ~1e-6 — the 6-decimal CSV surface.
"""

import csv
import os

import pytest

from tests.conftest import GOLDEN_DIR
from yuma_simulation_tpu.models.config import SimulationHyperparameters
from yuma_simulation_tpu.models.variants import canonical_versions
from yuma_simulation_tpu.reporting.tables import generate_total_dividends_table
from yuma_simulation_tpu.scenarios import cases

TOL = 1.5e-6


def load_golden(beta):
    path = os.path.join(GOLDEN_DIR, f"total_dividends_b{beta}_full.csv")
    with open(path) as f:
        return list(csv.DictReader(f))


@pytest.mark.parametrize("beta", [0, 0.5, 0.99, 1.0])
def test_total_dividends_parity(beta):
    golden = load_golden(beta)
    hp = SimulationHyperparameters(bond_penalty=float(beta))
    df = generate_total_dividends_table(cases, canonical_versions(), hp)

    assert list(df["Case"]) == [row["Case"] for row in golden]
    worst = (0.0, None)
    for i, row in enumerate(golden):
        for col, val in row.items():
            if col == "Case":
                continue
            got = float(df[col][i])
            diff = abs(got - float(val))
            if diff > worst[0]:
                worst = (diff, (row["Case"], col, float(val), got))
    assert worst[0] < TOL, f"beta={beta}: worst mismatch {worst}"
