"""Chunked epoch streaming (r4 verdict item 1): `simulate_streamed` /
`simulate(max_resident_epochs=...)` thread the `(bonds, consensus[,
w_prev])` carry between per-chunk dispatches, so true-per-epoch-weights
runs whose `[E, V, M]` stack exceeds HBM still produce BITWISE the
monolithic scan's results. Pinned here on both engines (XLA scan and the
fused Pallas kernel in interpret mode) across every named version,
including resets that fire inside a later chunk and the EMA_PREV
previous-weights carry (reference semantics: simulation_utils.py:44-88,
yumas.py:299-300).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from yuma_simulation_tpu.models.config import YumaConfig, YumaParams
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.scenarios import get_cases
from yuma_simulation_tpu.simulation.engine import (
    _simulate_case_fused,
    _simulate_scan,
    simulate,
    simulate_streamed,
)

from tests.unit.test_fused_case_scan import ALL_VERSIONS, _workload


def _chunks(W, S, sizes):
    lo = 0
    for n in sizes:
        yield W[lo : lo + n], S[lo : lo + n]
        lo += n


@pytest.mark.parametrize(
    "version,params", ALL_VERSIONS, ids=[v for v, _ in ALL_VERSIONS]
)
def test_streamed_xla_bitwise_matches_monolithic(version, params):
    # Reset at epoch 4 lands inside the second chunk — the global epoch
    # offset, not the chunk-local index, must drive the reset rule.
    W, S = _workload()
    cfg = YumaConfig(yuma_params=YumaParams(**params))
    spec = variant_for_version(version)
    ri = jnp.asarray(2, jnp.int32)
    re = jnp.asarray(4, jnp.int32)
    mono = _simulate_scan(W, S, ri, re, cfg, spec, save_consensus=True)
    got = simulate_streamed(
        _chunks(W, S, [3, 4, 3]),
        version,
        cfg,
        reset_bonds_index=2,
        reset_bonds_epoch=4,
        save_bonds=True,
        save_incentives=True,
        save_consensus=True,
        epoch_impl="xla",
    )
    np.testing.assert_array_equal(got.dividends.shape, (10, 6))
    for name, g in [
        ("dividends", got.dividends),
        ("bonds", got.bonds),
        ("incentives", got.incentives),
        ("consensus", got.consensus),
    ]:
        key = name
        np.testing.assert_array_equal(
            g, np.asarray(mono[key]), err_msg=f"{version}: {name}"
        )


@pytest.mark.parametrize(
    "version,params",
    [
        ("Yuma 1 (paper)", {}),
        ("Yuma 2 (Adrian-Fish)", {}),  # EMA_PREV: w_prev rides the carry
        ("Yuma 3.1 (Rhef+reset)", {}),
        (
            "Yuma 1 (paper) - liquid alpha on",
            dict(liquid_alpha=True),
        ),
    ],
    ids=["yuma1", "yuma2-prev-weights", "yuma31-reset", "yuma1-liquid"],
)
def test_streamed_fused_bitwise_matches_monolithic(version, params):
    W, S = _workload(seed=3)
    cfg = YumaConfig(yuma_params=YumaParams(**params))
    spec = variant_for_version(version)
    ri = jnp.asarray(1, jnp.int32)
    re = jnp.asarray(5, jnp.int32)
    mono = _simulate_case_fused(W, S, ri, re, cfg, spec, save_consensus=True)
    got = simulate_streamed(
        _chunks(W, S, [4, 2, 4]),
        version,
        cfg,
        reset_bonds_index=1,
        reset_bonds_epoch=5,
        save_bonds=True,
        save_incentives=True,
        save_consensus=True,
        epoch_impl="fused_scan",
    )
    for name, g in [
        ("dividends", got.dividends),
        ("bonds", got.bonds),
        ("incentives", got.incentives),
        ("consensus", got.consensus),
    ]:
        np.testing.assert_array_equal(
            g, np.asarray(mono[name]), err_msg=f"{version}: {name}"
        )


def test_streamed_carry_roundtrip_fused_vs_xla_chunk_sizes():
    # Chunk-size choice must not change results (same engine, any split).
    W, S = _workload(seed=7)
    cfg = YumaConfig()
    a = simulate_streamed(
        _chunks(W, S, [10]), "Yuma 2 (Adrian-Fish)", cfg, epoch_impl="xla"
    )
    b = simulate_streamed(
        _chunks(W, S, [1] * 10), "Yuma 2 (Adrian-Fish)", cfg, epoch_impl="xla"
    )
    np.testing.assert_array_equal(a.dividends, b.dividends)


def test_simulate_max_resident_epochs_matches_monolithic():
    case = get_cases()[3]  # a reset case
    for version in ("Yuma 1 (paper)", "Yuma 2 (Adrian-Fish)"):
        mono = simulate(case, version)
        got = simulate(
            case,
            version,
            max_resident_epochs=7,
            save_bonds=True,
            save_incentives=True,
        )
        np.testing.assert_array_equal(got.dividends, mono.dividends)
        np.testing.assert_array_equal(got.bonds, mono.bonds)
        np.testing.assert_array_equal(got.incentives, mono.incentives)


def test_streamed_defaults_skip_heavy_outputs():
    W, S = _workload()
    got = simulate_streamed(_chunks(W, S, [5, 5]), "Yuma 1 (paper)")
    assert got.bonds is None and got.incentives is None
    assert got.dividends.shape == (10, 6)


def test_streamed_no_chunks_raises():
    with pytest.raises(ValueError, match="no chunks"):
        simulate_streamed(iter(()), "Yuma 1 (paper)")


@pytest.mark.parametrize(
    "version",
    ["Yuma 1 (paper)", "Yuma 2 (Adrian-Fish)", "Yuma 3 (Rhef)"],
)
def test_simulate_generated_bitwise_matches_monolithic(version):
    # One-dispatch on-device streaming (a statically unrolled chunk
    # chain — see _simulate_generated_run's compile note) must agree
    # bitwise with the monolithic scan of the same concatenated stack.
    from yuma_simulation_tpu.simulation.engine import simulate_generated

    W, S = _workload(seed=11, E=12)
    CH = 4

    def gen_fn(i):
        import jax.lax as _lax

        z = jnp.zeros((), jnp.int32)
        return (
            _lax.dynamic_slice(W, (i * CH, z, z), (CH,) + W.shape[1:]),
            _lax.dynamic_slice(S, (i * CH, z), (CH, S.shape[1])),
        )

    cfg = YumaConfig()
    spec = variant_for_version(version)
    mono = _simulate_scan(
        W,
        S,
        jnp.asarray(-1, jnp.int32),
        jnp.asarray(-1, jnp.int32),
        cfg,
        spec,
        save_bonds=False,
        save_incentives=False,
    )
    D, B = simulate_generated(gen_fn, 3, version, cfg, epoch_impl="xla")
    np.testing.assert_array_equal(D, np.asarray(mono["dividends"]))
    assert B.shape == W.shape[1:]


def test_save_auto_threshold(monkeypatch):
    # r4 verdict item 5: the save_bonds=True default must not silently
    # materialize a beyond-threshold [E, V, M] bond history.
    import yuma_simulation_tpu.simulation.engine as eng

    case = get_cases()[0]
    monkeypatch.setattr(eng, "SAVE_AUTO_LIMIT_BYTES", 64)
    res = simulate(case, "Yuma 1 (paper)")
    assert res.bonds is None and res.incentives is None
    assert res.dividends.shape[0] == len(case.weights)
    # Explicit True always wins over the auto threshold.
    res = simulate(case, "Yuma 1 (paper)", save_bonds=True)
    assert res.bonds is not None
    with pytest.raises(ValueError, match="save_bonds"):
        simulate(case, "Yuma 1 (paper)", save_bonds="always")
    # run_simulation's reference-driver contract is unconditional.
    from yuma_simulation_tpu.simulation.engine import run_simulation

    div, bonds, inc = run_simulation(case, "Yuma 1 (paper)")
    assert len(bonds) == len(case.weights) and len(inc) == len(case.weights)
