"""f32-mode (TPU default) golden parity, pinned.

The main parity suite runs under x64 (tests/conftest.py) so Yuma-0's
float64 quantization divide matches the reference exactly. But no TPU
user runs x64 — the shipped default is pure f32, where that divide
degrades to f32 (models/epoch.py rust64 branch). This test runs the full
14 cases x 9 versions x 4 beta golden surface in a SUBPROCESS with x64
disabled and pins the measured envelope: worst deviation from the
reference CSVs is ~6e-7 (all versions, measured in this container),
asserted here at 1.5e-6 — the same bound as the x64 parity suite, i.e.
the mode users actually run matches the reference CSV surface at its own
6-decimal precision.
"""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

_SCRIPT = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
assert not jax.config.jax_enable_x64, "subprocess must run in f32 mode"

import csv, json
from yuma_simulation_tpu.models.config import SimulationHyperparameters
from yuma_simulation_tpu.models.variants import canonical_versions
from yuma_simulation_tpu.reporting.tables import generate_total_dividends_table
from yuma_simulation_tpu.scenarios import cases

worst = {}
for beta in (0, 0.5, 0.99, 1.0):
    path = os.path.join("tests", "golden", f"total_dividends_b{beta}_full.csv")
    with open(path) as f:
        golden = list(csv.DictReader(f))
    hp = SimulationHyperparameters(bond_penalty=float(beta))
    df = generate_total_dividends_table(cases, canonical_versions(), hp)
    assert list(df["Case"]) == [row["Case"] for row in golden]
    for i, row in enumerate(golden):
        for col, val in row.items():
            if col == "Case":
                continue
            version = col.split(" - ", 1)[1]
            diff = abs(float(df[col][i]) - float(val))
            worst[version] = max(worst.get(version, 0.0), diff)
print("F32RESULT " + json.dumps(worst))
"""

TOL = 1.5e-6


@pytest.mark.slow
def test_f32_mode_golden_surface():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in [_REPO, env.get("PYTHONPATH", "")] if p
    )
    # The parent test process forces x64 via jax.config, not env — the
    # child starts clean. Make sure no stray flag re-enables it.
    env.pop("JAX_ENABLE_X64", None)
    out = subprocess.run(
        [sys.executable, "-c", _SCRIPT],
        cwd=_REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=1200,
    )
    assert out.returncode == 0, out.stderr[-4000:]
    line = next(
        ln for ln in out.stdout.splitlines() if ln.startswith("F32RESULT ")
    )
    worst = json.loads(line[len("F32RESULT "):])
    assert len(worst) == 9, worst
    offenders = {v: d for v, d in worst.items() if d >= TOL}
    assert not offenders, f"f32-mode drift beyond {TOL}: {offenders}"
