"""The hoisted constant-weights fast path matches the in-scan form.

Identical update ops on identical values; agreement is exact at most
scan lengths and within one f32 ULP otherwise (XLA fuses very short
scans differently, which perturbs the *baseline*, not the hoist).
Parametrized over all nine canonical versions so the liquid-alpha
rate derivation is exercised on every bonds family."""

import numpy as np
import pytest

import jax.numpy as jnp

from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.variants import canonical_versions, variant_for_version
from yuma_simulation_tpu.simulation.engine import simulate_constant

_VERSIONS = canonical_versions()


@pytest.mark.parametrize(
    "version_params", _VERSIONS, ids=[v for v, _ in _VERSIONS]
)
@pytest.mark.parametrize("n", [1, 2, 17])
def test_hoisted_matches_scan(version_params, n):
    version, params = version_params
    rng = np.random.default_rng(4)
    W = jnp.asarray(rng.random((8, 16)), jnp.float32)
    S = jnp.asarray(rng.random(8) + 0.01, jnp.float32)
    config = YumaConfig(yuma_params=params)
    spec = variant_for_version(version)
    total_a, bonds_a = simulate_constant(W, S, n, config, spec)
    total_b, bonds_b = simulate_constant(
        W, S, n, config, spec, hoist_invariant=True
    )
    np.testing.assert_allclose(
        np.asarray(total_a), np.asarray(total_b), rtol=1e-6, atol=1e-8
    )
    np.testing.assert_allclose(
        np.asarray(bonds_a), np.asarray(bonds_b), rtol=1e-6,
        atol=1e-6 * max(1.0, float(np.abs(np.asarray(bonds_a)).max())),
    )


def test_hoisted_rejects_zero_epochs():
    rng = np.random.default_rng(4)
    W = jnp.asarray(rng.random((4, 8)), jnp.float32)
    S = jnp.asarray(rng.random(4) + 0.01, jnp.float32)
    spec = variant_for_version("Yuma 1 (paper)")
    with pytest.raises(ValueError, match="num_epochs"):
        simulate_constant(W, S, 0, YumaConfig(), spec, hoist_invariant=True)
