"""Elastic mesh degradation: shrink-and-continue on device loss.

`surviving_mesh` and the distributed-init wrap are pure host logic and
run everywhere; the end-to-end elastic drills dispatch through
`jax.shard_map` and are gated by the conftest capability probe
(HAS_JAX_SHARD_MAP) exactly like the multichip suite."""

import logging

import numpy as np
import pytest

from yuma_simulation_tpu.parallel import make_mesh, surviving_mesh
from yuma_simulation_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from yuma_simulation_tpu.resilience import (
    Deadline,
    DeviceLossError,
    DeviceLossFault,
    FaultPlan,
    NaNFault,
    RetryPolicy,
    StallFault,
    SweepSupervisor,
    classify_failure,
    inject_faults,
)
from yuma_simulation_tpu.scenarios import get_cases
from yuma_simulation_tpu.utils.logging import parse_event_line

VERSION = "Yuma 1 (paper)"
POLICY = RetryPolicy(max_attempts_per_rung=2, backoff_base=0.0, seed=0)


# --------------------------------------------------------- surviving_mesh


def test_surviving_mesh_drops_named_devices():
    mesh = make_mesh(data=8, model=1)
    lost = mesh.devices.flat[3].id
    smaller = surviving_mesh(mesh, [lost])
    assert smaller is not None
    assert smaller.shape[DATA_AXIS] == 7
    assert lost not in {d.id for d in smaller.devices.flat}


def test_surviving_mesh_preserves_model_axis_when_divisible():
    mesh = make_mesh(data=4, model=2)
    # drop two devices -> 6 survivors, still divisible by model=2
    ids = [d.id for d in mesh.devices.flat]
    smaller = surviving_mesh(mesh, ids[:2])
    assert smaller is not None
    assert smaller.shape[MODEL_AXIS] == 2
    assert smaller.shape[DATA_AXIS] == 3


def test_surviving_mesh_collapses_model_axis_when_not_divisible():
    mesh = make_mesh(data=4, model=2)
    ids = [d.id for d in mesh.devices.flat]
    smaller = surviving_mesh(mesh, ids[:1])  # 7 survivors, 7 % 2 != 0
    assert smaller is not None
    assert smaller.shape[MODEL_AXIS] == 1
    assert smaller.shape[DATA_AXIS] == 7


def test_surviving_mesh_returns_none_at_last_rung():
    mesh = make_mesh(data=2, model=1, devices=list(make_mesh().devices.flat)[:2])
    ids = [d.id for d in mesh.devices.flat]
    assert surviving_mesh(mesh, ids) is None          # nothing survives
    assert surviving_mesh(mesh, ids[:1]) is None      # one survivor


def test_device_loss_error_is_retryable_and_carries_ids():
    err = DeviceLossError("chip fell over", device_ids=(3, 5))
    assert classify_failure(err) is err
    assert err.device_ids == (3, 5)


# ------------------------------------------------- distributed-init wrap


def test_distributed_init_failure_is_typed_and_logged(monkeypatch, caplog):
    """ISSUE 3 satellite: an explicit-coordinator join failure surfaces
    as the typed DistributedInitError with one
    event=distributed_init_failed record — not a raw backend error."""
    import jax

    from yuma_simulation_tpu.parallel.mesh import initialize_distributed
    from yuma_simulation_tpu.resilience import DistributedInitError

    monkeypatch.setattr(
        jax.distributed, "is_initialized", lambda: False, raising=False
    )

    def never_joins(**kwargs):
        raise RuntimeError("barrier timed out waiting for 1 tasks")

    monkeypatch.setattr(jax.distributed, "initialize", never_joins)
    with caplog.at_level(
        logging.WARNING, logger="yuma_simulation_tpu.parallel.mesh"
    ):
        with pytest.raises(DistributedInitError, match="refusing to degrade"):
            initialize_distributed(
                "127.0.0.1:1", 2, 0, initialization_timeout=1
            )
    parsed = [
        p
        for line in caplog.text.splitlines()
        if (p := parse_event_line(line)) is not None
    ]
    assert any(p["event"] == "distributed_init_failed" for p in parsed)
    record = next(p for p in parsed if p["event"] == "distributed_init_failed")
    assert record["coordinator"] == "127.0.0.1:1"
    # the compat contract the multi-process smoke greps for still holds
    assert issubclass(DistributedInitError, RuntimeError)


# --------------------------------------------- elastic dispatch drills


@pytest.mark.chaos
def test_elastic_degradation_on_device_loss(caplog):
    """ISSUE 3 tentpole: an injected DeviceLossFault shrinks the mesh
    over the survivors, re-pads/re-shards, resumes, and the degraded
    run's lanes are bitwise the full-mesh run — with one
    event=mesh_degraded record for the shrink."""
    from yuma_simulation_tpu.parallel import simulate_batch_sharded

    cases = get_cases()[:3]
    mesh = make_mesh()
    clean = simulate_batch_sharded(cases, VERSION, mesh=mesh, elastic=True)
    assert clean["mesh_degradations"] == ()
    lost = mesh.devices.flat[2].id
    with caplog.at_level(
        logging.WARNING, logger="yuma_simulation_tpu.parallel.sharded"
    ):
        with inject_faults(
            FaultPlan(device_loss=DeviceLossFault(device_id=lost))
        ):
            got = simulate_batch_sharded(
                cases, VERSION, mesh=mesh, elastic=True
            )
    walk = got["mesh_degradations"]
    assert len(walk) == 1
    assert walk[0].from_devices == 8 and walk[0].to_devices == 7
    assert walk[0].lost_device_ids == (lost,)
    np.testing.assert_array_equal(got["dividends"], clean["dividends"])
    records = [
        p
        for line in caplog.text.splitlines()
        if (p := parse_event_line(line)) is not None
        and p["event"] == "mesh_degraded"
    ]
    assert len(records) == 1
    assert records[0]["from_devices"] == "8" and records[0]["to_devices"] == "7"


@pytest.mark.chaos
def test_device_loss_without_elastic_aborts_typed():
    from yuma_simulation_tpu.parallel import simulate_batch_sharded

    cases = get_cases()[:2]
    mesh = make_mesh()
    lost = mesh.devices.flat[0].id
    with inject_faults(FaultPlan(device_loss=DeviceLossFault(device_id=lost))):
        with pytest.raises(DeviceLossError):
            simulate_batch_sharded(cases, VERSION, mesh=mesh, elastic=False)


@pytest.mark.chaos
def test_unattributed_device_loss_falls_to_single_device(monkeypatch):
    """A DeviceLossError naming no device cannot pick a shard to drop:
    the last rung is single-device XLA (no `shard_map`), still bitwise
    the plain vmap batch. Runs on every toolchain — the sharded dispatch
    is stubbed to fail, so only host logic and the XLA rung execute."""
    from yuma_simulation_tpu.models.config import YumaConfig
    from yuma_simulation_tpu.models.variants import variant_for_version
    from yuma_simulation_tpu.parallel import sharded as sharded_mod
    from yuma_simulation_tpu.parallel.sharded import simulate_batch_sharded
    from yuma_simulation_tpu.simulation.sweep import (
        simulate_batch,
        stack_scenarios,
    )

    cases = get_cases()[:2]
    mesh = make_mesh()
    W, S, ri, re = stack_scenarios(cases)
    ref = simulate_batch(
        W, S, ri, re, YumaConfig(), variant_for_version(VERSION),
        epoch_impl="xla",
    )

    calls = {"n": 0}

    def flaky_scan(*args, **kwargs):
        calls["n"] += 1
        raise DeviceLossError("which chip? unknown")

    monkeypatch.setattr(sharded_mod, "_sharded_batch_scan", flaky_scan)
    got = simulate_batch_sharded(cases, VERSION, mesh=mesh, elastic=True)
    assert calls["n"] == 1
    walk = got["mesh_degradations"]
    assert len(walk) == 1 and walk[0].to_devices == 1
    assert walk[0].lost_device_ids == ()
    np.testing.assert_array_equal(
        got["dividends"], np.asarray(ref["dividends"])
    )


# ------------------------------------- the full four-fault chaos drill


@pytest.mark.chaos
def test_chaos_drill_all_four_faults_sharded(tmp_path):
    """ISSUE 3 acceptance, full composition: ONE supervised sharded
    sweep survives a stall, a device loss, a NaN lane, AND a torn
    checkpoint chunk; healthy lanes are bit-identical to the unfaulted
    supervised run and the ledger + health report account for every
    recovery action."""
    from yuma_simulation_tpu.resilience.supervisor import FailureLedger

    cases = get_cases()[:4]
    mesh = make_mesh()
    lost = mesh.devices.flat[1].id

    def supervisor(directory, deadline=None):
        return SweepSupervisor(
            directory=directory,
            unit_size=3,
            deadline=deadline or Deadline(120.0, grace_seconds=120.0),
            retry_policy=POLICY,
        )

    clean = supervisor(tmp_path / "clean").run_batch(
        cases, VERSION, mesh=mesh
    )
    assert clean["report"].clean
    # Warm the degraded-mesh + NaN-operand jit variants under a roomy
    # budget (device loss and NaN armed, no stall), so the chaos pass's
    # tight budget can only ever kill the injected hold — cold-compile
    # time is machine-dependent and must not race the deadline.
    with inject_faults(
        FaultPlan(
            device_loss=DeviceLossFault(device_id=lost),
            nan=NaNFault(epoch=2, case=1),
        )
    ):
        supervisor(None).run_batch(cases, VERSION, mesh=mesh)

    # Post-shrink attempts get the retry grace, so the hold must exceed
    # budget + grace (1.5 + 6.0) to be killed wherever it lands.
    plan = FaultPlan(
        stall=StallFault(seconds=12.0, dispatches=1),  # hangs 1 dispatch
        device_loss=DeviceLossFault(device_id=lost),   # drops 1 device
        nan=NaNFault(epoch=2, case=1),                 # poisons lane 1
        truncate_chunks={1: 10},                       # tears chunk 1
    )
    with inject_faults(plan):
        out = supervisor(
            tmp_path / "chaos", deadline=Deadline(1.5, grace_seconds=6.0)
        ).run_batch(cases, VERSION, mesh=mesh)

    report = out["report"]
    assert report.units_completed == report.units_total == 2
    assert report.stalls_killed == 1
    assert report.mesh_shrinks >= 1
    assert report.units_requeued == 1
    assert report.lanes_quarantined == 1

    # healthy lanes bitwise; the NaN lane masked from its epoch on
    for lane in (0, 2, 3):
        np.testing.assert_array_equal(
            out["dividends"][lane], clean["dividends"][lane]
        )
    np.testing.assert_array_equal(
        out["dividends"][1][:2], clean["dividends"][1][:2]
    )
    assert (out["dividends"][1][2:] == 0).all()
    assert out["quarantine"].quarantined_cases == (1,)

    # the ledger accounts for every action
    led = FailureLedger(tmp_path / "chaos" / "ledger.jsonl")
    oks = led.entries("unit_ok")
    assert [e["unit"] for e in oks] == [0, 1, 1]
    assert sum(e["stalls"] for e in oks) >= 1
    assert sum(e["mesh_shrinks"] for e in oks) >= 1
    assert led.entries("unit_requeued")
    assert sorted(
        case for e in oks for case, _epoch, _tensor in e["quarantined"]
    ) == [1]
