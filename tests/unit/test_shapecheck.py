"""shapecheck: live-contract gate + zero-compile pin + detection proofs.

The live gate mirrors test_jaxlint.py's: `python -m tools.shapecheck
--check` must exit 0 over every planner bucket. The RecompilationSentinel
test is the acceptance pin that the whole run adds ZERO jit-cache
entries — abstract shape tracing must never pay an XLA compile. The
detection tests prove the gate actually rejects: a drifted output
contract, a donation-invalid carry, and an identity-hashed static arg.
"""

import dataclasses
import json

import jax.numpy as jnp

from tools import shapecheck
from yuma_simulation_tpu.utils.profiling import RecompilationSentinel


def test_live_contracts_clean():
    results = shapecheck.run_shapecheck()
    bad = [r for r in results if not r.ok]
    assert not bad, "\n".join(
        f"{r.contract} [{r.bucket}]: {r.detail}" for r in bad
    )
    # the grid genuinely exercises multiple buckets and contracts
    assert len(shapecheck.build_grid()) >= 4
    assert len(results) > 50


def test_zero_compiles_pinned():
    """The acceptance pin: the whole shapecheck run — every rung, every
    bucket, every spec — under a zero-budget sentinel."""
    with RecompilationSentinel(
        *shapecheck.ENTRY_POINTS, budget=0, label="shapecheck-pin"
    ):
        shapecheck.run_shapecheck()


def test_contract_drift_detected(monkeypatch):
    """A refactor that changes an output's shape must turn the gate
    red: drift the declared dividends contract and watch every engine
    check fail."""
    real = shapecheck._engine_expect

    def drifted(b):
        want = real(b)
        want["dividends"] = shapecheck._sds(
            (max(1, b.epochs), b.padded_V, 2), jnp.float32
        )
        return want

    monkeypatch.setattr(shapecheck, "_engine_expect", drifted)
    results = shapecheck.run_shapecheck()
    bad = [r for r in results if not r.ok and r.contract == "engine-xla"]
    assert bad and "dividends" in bad[0].detail


def test_missing_output_stream_detected():
    """_tree_mismatches reports both directions: a dropped stream and
    an undeclared one."""
    got = {"dividends": shapecheck._sds((5, 8), jnp.float32)}
    want = {
        "dividends": shapecheck._sds((5, 8), jnp.float32),
        "bonds": shapecheck._sds((5, 8, 128), jnp.float32),
    }
    msg = shapecheck._tree_mismatches(got, want, "ys")
    assert "missing" in msg and "bonds" in msg
    msg2 = shapecheck._tree_mismatches(want, got, "ys")
    assert "undeclared" in msg2


def test_dtype_drift_detected():
    got = {"fingerprint": shapecheck._sds((5,), jnp.int32)}
    want = {"fingerprint": shapecheck._sds((5,), jnp.uint32)}
    msg = shapecheck._tree_mismatches(got, want, "ys")
    assert "int32" in msg and "uint32" in msg


def test_donation_invalid_carry_detected(monkeypatch):
    """Donation soundness: feed a carry whose bonds dtype cannot
    round-trip and require the streamed contract to go red (either as
    a struct mismatch or a trace-time rejection)."""
    real = shapecheck._carry_struct

    def torn(b, spec):
        c = real(b, spec)
        c["bonds"] = shapecheck._sds(c["bonds"].shape, jnp.float16)
        return c

    monkeypatch.setattr(shapecheck, "_carry_struct", torn)
    results = shapecheck.run_shapecheck()
    bad = [
        r
        for r in results
        if not r.ok and r.contract in ("streamed-xla", "streamed-fused", "engine")
    ]
    assert bad, "f16 carry round-tripped cleanly — donation check is dead"


def test_static_arg_stability():
    """Hash-stable statics pass; identity-hashed and unhashable ones
    are named failures (the compile-per-call class the
    RecompilationSentinel otherwise only catches at runtime)."""

    @dataclasses.dataclass(frozen=True)
    class GoodSpec:
        name: str = "ok"

    assert shapecheck._static_problems(GoodSpec(), "spec") == ""
    assert shapecheck._static_problems("bisect", "impl") == ""

    class IdentityHashed:
        pass

    msg = shapecheck._static_problems(IdentityHashed(), "spec")
    assert "identity" in msg
    msg2 = shapecheck._static_problems([1, 2], "spec")
    assert "unhashable" in msg2


def test_planner_rung_coverage_guard(monkeypatch):
    """A new planner rung without a shapecheck contract turns the
    planner-coupling check red instead of silently going unchecked."""
    monkeypatch.setattr(shapecheck, "COVERED_RUNGS", ("nothing",))
    results = shapecheck.run_shapecheck()
    bad = [r for r in results if not r.ok and r.contract == "planner"]
    assert bad and "uncovered rung" in bad[0].detail


def test_cli_artifact_and_exit_code(tmp_path, capsys):
    artifact = tmp_path / "shapecheck.json"
    rc = shapecheck.main(["--check", "--artifact", str(artifact)])
    out = capsys.readouterr().out
    assert rc == 0, out
    payload = json.loads(artifact.read_text())
    assert payload["failures"] == 0
    assert payload["compiles_added"] == 0
    assert payload["total"] == len(payload["checks"])
    assert "_simulate_scan" in payload["entry_points"]


def test_grid_covers_tile_padding():
    """The grid must include at least one bucket whose padding actually
    engaged (padded != raw), or the donor-pack path is untested."""
    assert any(
        b.padded_V != b.V or b.padded_M != b.M
        for b in shapecheck.build_grid()
    )


def test_expected_shapes_follow_bucket():
    b = shapecheck.bucket_shape(9, 129, epochs=5, batch=2)
    want = shapecheck._engine_expect(b)
    assert tuple(want["dividends"].shape) == (5, 16)
    assert tuple(want["bonds"].shape) == (5, 16, 256)
    assert tuple(want["consensus"].shape) == (5, 256)
