"""Numerics flight recorder: per-epoch tensor-stat telemetry, the
cross-engine drift canary, and the driftreport gate — ISSUE 10
acceptance battery.

Covers the capture half (fingerprint algebra, sketch invariance across
monolithic / streamed-all-chunkings / sharded execution), the
comparison half (supervisor + serve canaries, the typed `engine_drift`
ledger event, the drift SLO), the gate (`tools/driftreport --check`
exit codes on clean vs drifted bundles), the bundle-stream contract
(numerics.jsonl survives a failed/resumed sweep), the one-switch
disable (`YUMA_NUMERICS=0`), and the zero-warm-repeat-compile pin."""

import json
import pathlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tests.conftest import HAS_JAX_SHARD_MAP
from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.resilience import (
    DriftFault,
    FaultPlan,
    RetryPolicy,
    SweepSupervisor,
    inject_faults,
)
from yuma_simulation_tpu.scenarios import get_cases
from yuma_simulation_tpu.simulation.engine import simulate, simulate_streamed

VERSION = "Yuma 1 (paper)"
POLICY = RetryPolicy(max_attempts_per_rung=2, backoff_base=0.0, seed=0)

SKETCH_FIELDS = ("finite_frac", "lo", "hi", "absmax", "fingerprint")


def _supervisor(directory=None, **kw):
    kw.setdefault("unit_size", 2)
    kw.setdefault("deadline", None)
    kw.setdefault("retry_policy", POLICY)
    return SweepSupervisor(directory=directory, **kw)


def _assert_sketches_equal(a: dict, b: dict, streams=None) -> None:
    keys = streams if streams is not None else (set(a) & set(b))
    assert keys, "no overlapping numerics streams to compare"
    for stream in keys:
        for field in SKETCH_FIELDS:
            np.testing.assert_array_equal(
                np.asarray(getattr(a[stream], field)),
                np.asarray(getattr(b[stream], field)),
                err_msg=f"{stream}.{field} not bitwise identical",
            )


# ------------------------------------------------------- fingerprint ops


def test_fingerprint_is_order_independent_and_ulp_sensitive():
    """The wrapping-u32 bit sum is partition-invariant by construction
    (integer addition commutes exactly), and a single-ulp flip moves
    the fingerprint by EXACTLY 1 — the property driftreport's
    ulp-distance render rests on."""
    from yuma_simulation_tpu.ops.fingerprint import (
        fingerprint_u32,
        flip_ulp,
        ulp_delta,
    )

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.random((16, 32)), jnp.float32)
    full = int(fingerprint_u32(x))
    # Any re-partitioning of the reduction produces the same u32 sum.
    by_rows = int(jnp.sum(fingerprint_u32(x, axes=(1,)), dtype=jnp.uint32))
    shuffled = int(fingerprint_u32(x.ravel()[::-1]))
    assert full == by_rows == shuffled
    # One-ulp flip of one element: delta exactly +1.
    flipped = x.at[3, 5].set(flip_ulp(x[3, 5]))
    assert ulp_delta(full, int(fingerprint_u32(flipped))) == 1
    # ulp_delta is signed and minimal-magnitude mod 2^32.
    assert ulp_delta(5, 3) == -2
    assert ulp_delta(0, (1 << 32) - 1) == -1


def test_epoch_sketch_stats_handle_nonfinite():
    """finite_frac carries the failure signal while the masked min/max/
    absmax stay informative (a NaN-poisoned epoch must not read as
    min=nan, absmax=nan)."""
    from yuma_simulation_tpu.telemetry.numerics import epoch_sketch

    x = jnp.asarray([1.0, -2.0, np.nan, np.inf], jnp.float32)
    sk = epoch_sketch(x)
    assert float(sk.finite_frac) == pytest.approx(0.5)
    assert float(sk.lo) == -2.0
    assert float(sk.hi) == 1.0
    assert float(sk.absmax) == 2.0


def test_first_divergence_and_diff_records():
    from yuma_simulation_tpu.telemetry.numerics import (
        diff_records,
        first_divergence,
    )

    assert first_divergence([1, 2, 3], [1, 2, 3]) is None
    assert first_divergence([1, 2, 3], [1, 5, 3]) == (1, 3)
    # Length mismatch diverges at the shorter length.
    assert first_divergence([1, 2], [1, 2, 3]) == (2, 0)
    primary = {"fingerprint": [[1, 2], [3, 4]], "lanes": [0, 2]}
    canary = {"fingerprint": [[1, 2], [3, 5]], "lanes": [0, 2]}
    out = diff_records(primary, canary)
    assert out == [
        {"lane": 1, "first_divergent_epoch": 1, "ulp_distance": 1}
    ]


# ------------------------------------------------ sketch invariance


def test_sketches_bitwise_invariant_monolithic_streamed_sharded():
    """The ISSUE 10 invariance property: per-epoch stats + fingerprints
    are bitwise identical across monolithic, chunk-streamed (several
    chunkings, aligned and ragged) and miner-sharded execution of the
    same case — every sketch reduction is exact and order-independent,
    so the merge is concatenation and the psum is the unsharded sum."""
    case = get_cases()[0]
    cfg = YumaConfig()
    mono = simulate(case, VERSION, cfg)
    assert mono.numerics is not None
    assert set(mono.numerics) == {"dividends", "consensus"}

    W = np.asarray(case.weights, np.float32)
    S = np.asarray(case.stakes, np.float32)
    E = W.shape[0]

    def gen(chunk):
        for lo in range(0, E, chunk):
            yield (W[lo : lo + chunk], S[lo : lo + chunk])

    for chunk in (E, 8, 7, 3):  # monolithic-as-one-chunk, even, ragged
        streamed = simulate_streamed(gen(chunk), VERSION, cfg)
        assert streamed.numerics is not None
        _assert_sketches_equal(mono.numerics, streamed.numerics)

    if HAS_JAX_SHARD_MAP:
        from yuma_simulation_tpu.parallel import make_mesh

        sharded = simulate(case, VERSION, cfg, mesh=make_mesh())
        assert sharded.numerics is not None
        _assert_sketches_equal(mono.numerics, sharded.numerics)


@pytest.mark.skipif(
    not HAS_JAX_SHARD_MAP, reason="needs jax.shard_map (jax>=0.7)"
)
def test_batch_sketches_bitwise_invariant_under_scenario_sharding():
    """simulate_batch_sharded's gathered numerics pytree is bitwise the
    unsharded vmap's — the shard-invariant merge the sharded layer
    advertises."""
    from yuma_simulation_tpu.models.variants import variant_for_version
    from yuma_simulation_tpu.parallel import make_mesh
    from yuma_simulation_tpu.parallel.sharded import simulate_batch_sharded
    from yuma_simulation_tpu.simulation.sweep import (
        simulate_batch,
        stack_scenarios,
    )

    cases = get_cases()[:4]
    cfg = YumaConfig()
    W, S, ri, re = stack_scenarios(cases)
    solo = simulate_batch(
        W, S, ri, re, cfg, variant_for_version(VERSION)
    )
    sharded = simulate_batch_sharded(
        cases, VERSION, cfg, mesh=make_mesh()
    )
    assert "numerics" in sharded
    _assert_sketches_equal(solo["numerics"], sharded["numerics"])


def test_numerics_env_switch_disables_capture(monkeypatch):
    """The one config/env switch: YUMA_NUMERICS=0 turns the whole
    stream off — engines return no sketches, supervisors write no
    records."""
    monkeypatch.setenv("YUMA_NUMERICS", "0")
    from yuma_simulation_tpu.telemetry.numerics import numerics_enabled

    assert not numerics_enabled()
    res = simulate(get_cases()[0], VERSION, YumaConfig())
    assert res.numerics is None
    out = _supervisor(canary_fraction=1.0).run_batch(
        get_cases()[:2], VERSION
    )
    assert out["numerics_records"] == []


# --------------------------------------------- supervisor canary + gate


def test_supervisor_canary_clean_and_bundle_stream(tmp_path):
    """A canaried supervised sweep: every selected unit re-executes on
    the demoted rung, compares bitwise clean, ledgers one unit_canary
    per canary, publishes primary+canary numerics records, and passes
    both check_bundle and driftreport --check."""
    from tools.driftreport import main as driftreport_main
    from yuma_simulation_tpu.telemetry.flight import (
        check_bundle,
        load_bundle,
    )

    cases = get_cases()[:4]
    out = _supervisor(tmp_path, canary_fraction=1.0).run_batch(
        cases, VERSION
    )
    rep = out["report"]
    assert rep.canaries_run == 2 and rep.drift_events == 0 and rep.clean
    bundle = load_bundle(tmp_path)
    assert check_bundle(bundle) == []
    roles = {(r["unit"], r["role"], r["stream"]) for r in bundle.numerics}
    assert {(0, "primary", "dividends"), (0, "canary", "dividends")} <= roles
    canaries = [
        r for r in bundle.ledger if r.get("event") == "unit_canary"
    ]
    assert len(canaries) == 2
    assert all(r["drift_streams"] == 0 for r in canaries)
    assert driftreport_main([str(tmp_path), "--check", "--require"]) == 0


def test_supervisor_canary_fraction_strides_deterministically():
    cases = get_cases()[:4]  # 2 units at unit_size=2
    out = _supervisor(canary_fraction=0.5).run_batch(cases, VERSION)
    assert out["report"].canaries_run == 1  # unit 0 only
    out = _supervisor(canary_fraction=0.0).run_batch(cases, VERSION)
    assert out["report"].canaries_run == 0


@pytest.mark.faultinject
def test_drift_drill_end_to_end(tmp_path):
    """THE acceptance drill: an injected single-ulp DriftFault in one
    lane produces a typed engine_drift ledger event localizing the
    exact (lane, first divergent epoch, ulp distance), a degraded
    report, a fast-burning engine_drift SLO, and driftreport --check
    exit != 0 — while healthy streams stay bitwise clean."""
    from tools.driftreport import main as driftreport_main
    from yuma_simulation_tpu.telemetry.flight import (
        check_bundle,
        load_bundle,
    )
    from yuma_simulation_tpu.telemetry.slo import (
        get_slo_engine,
        set_slo_engine,
    )

    previous = set_slo_engine(None)  # fresh engine for the drill
    try:
        cases = get_cases()[:4]
        with inject_faults(FaultPlan(drift=DriftFault(epoch=5, case=1))):
            out = _supervisor(tmp_path, canary_fraction=1.0).run_batch(
                cases, VERSION
            )
        rep = out["report"]
        assert rep.canaries_run == 2
        assert rep.drift_events == 2  # one per unit's dividends stream
        assert not rep.clean
        bundle = load_bundle(tmp_path)
        assert check_bundle(bundle) == []  # drift is consistent, not rot
        drifts = [
            r for r in bundle.ledger if r.get("event") == "engine_drift"
        ]
        assert len(drifts) == 2
        # Unit 1 (lanes [2, 4)) local lane 1 -> GLOBAL lane 3; the flip
        # at epoch 5 is localized with ulp distance exactly 1.
        assert drifts[1]["stream"] == "dividends"
        assert drifts[1]["lanes"] == [[3, 5, 1]]
        # The drift SLO fast-burns on the bad canary events.
        assert get_slo_engine().state("engine_drift") == "fast_burn"
        # The gate fails the bundle.
        assert driftreport_main([str(tmp_path), "--check"]) == 1
    finally:
        set_slo_engine(previous)


@pytest.mark.faultinject
def test_drift_fault_inert_outside_canary_scope(tmp_path):
    """The DriftFault fires ONLY inside canary re-executions: with no
    canaries armed, an armed plan perturbs nothing (primaries trace the
    exact production program) and the sweep stays bitwise clean."""
    cases = get_cases()[:2]
    clean = _supervisor().run_batch(cases, VERSION)
    with inject_faults(FaultPlan(drift=DriftFault(epoch=5))):
        armed = _supervisor().run_batch(cases, VERSION)
    np.testing.assert_array_equal(clean["dividends"], armed["dividends"])
    assert armed["report"].clean


def test_numerics_stream_survives_failed_and_resumed_sweep(tmp_path):
    """The bundle-stream contract: a resumed sweep keeps the prior
    run's numerics records for units it never re-executed, and a
    requeued (torn-chunk) unit's re-capture REPLACES its records
    instead of duplicating them — exactly like costs.jsonl."""
    from yuma_simulation_tpu.telemetry.flight import load_bundle

    cases = get_cases()[:4]
    _supervisor(tmp_path, canary_fraction=1.0).run_batch(cases, VERSION)
    first = {
        (r["unit"], r["role"], r["stream"]): r["fingerprint"]
        for r in load_bundle(tmp_path).numerics
    }
    assert len(first) == 8  # 2 units x 2 roles x 2 streams

    # Tear unit 1's chunk: the resume requeues EXACTLY that unit.
    (tmp_path / "chunk_00001.npz").write_bytes(b"torn")
    _supervisor(tmp_path, canary_fraction=1.0).run_batch(cases, VERSION)
    bundle = load_bundle(tmp_path)
    second = {
        (r["unit"], r["role"], r["stream"]): r["fingerprint"]
        for r in bundle.numerics
    }
    # No duplicates, nothing lost, and the re-executed capture is
    # bitwise the original (units are pure).
    assert second == first
    requeues = [
        r for r in bundle.ledger if r.get("event") == "unit_requeued"
    ]
    assert {r["unit"] for r in requeues} == {1}


def test_append_numerics_is_append_only_and_merge_heals(tmp_path):
    """The long-lived-server flush path: `append_numerics` appends
    without rewriting the file (O(batch) on a handler thread), and the
    next full `record_numerics` merge dedupes appended duplicates by
    identity — the `append_spans` contract on the numerics stream."""
    from yuma_simulation_tpu.telemetry.flight import (
        FlightRecorder,
        load_bundle,
    )

    rec = {
        "unit": 0, "lanes": [0, 1], "stream": "dividends",
        "engine": "xla", "role": "primary", "label": "t", "epochs": 1,
        "fingerprint": [[7]],
    }
    recorder = FlightRecorder(tmp_path)
    recorder.append_numerics([rec])
    recorder.append_numerics([rec])  # duplicate identity, appended
    assert len(load_bundle(tmp_path).numerics) == 2
    recorder.record_numerics([], run_id="run-x")  # the close-time merge
    assert len(load_bundle(tmp_path).numerics) == 1


# ------------------------------------------------------- serve canary


@pytest.mark.faultinject
def test_serve_canary_drift_degrades_healthz_and_trips_breaker(tmp_path):
    """The serving half of the drill: a DriftFault during the
    background canary tick yields a typed engine_drift ledger event,
    /healthz degraded (the engine_drift SLO fast-burns), a tripped
    primary-rung breaker, and driftreport --check exit != 0 on the
    serve bundle — while an unfaulted tick stays drift-clean."""
    from tools.driftreport import main as driftreport_main
    from yuma_simulation_tpu.serve.service import (
        ServeConfig,
        SimulationService,
    )
    from yuma_simulation_tpu.telemetry.slo import set_slo_engine

    previous = set_slo_engine(None)
    service = SimulationService(
        ServeConfig(
            bundle_dir=str(tmp_path),
            warmup_shapes=((6, 3, 2),),
            breaker_threshold=1,
            start_dispatcher=False,
        )
    )
    try:
        state = service.run_canary_once()
        assert state == {"ticks": 1, "drift": 0, "last_bucket": "6x3x2"}
        assert service.healthz()["status"] == "ok"

        with inject_faults(FaultPlan(drift=DriftFault(epoch=2))):
            state = service.run_canary_once()
        assert state["drift"] >= 1
        h = service.healthz()
        assert h["status"] == "degraded"
        assert "engine_drift" in h["slo"]["fast_burn"]
        assert h["breaker"]["xla"]["state"] == "open"
        assert h["canary"]["drift"] >= 1
        drifts = service.ledger.entries("engine_drift")
        assert drifts and drifts[0]["bucket"] == "6x3x2"
        assert drifts[0]["lanes"][0][1] == 2  # first divergent epoch
    finally:
        service.close()
        set_slo_engine(previous)
    assert driftreport_main([str(tmp_path), "--check", "--require"]) == 1


def test_serve_request_populates_numerics_and_canary_bucket(tmp_path):
    """A real request both stashes its supervised dispatch's numerics
    records into the bundle and registers its shape as a canary
    bucket; the clean bundle passes driftreport."""
    from tools.driftreport import main as driftreport_main
    from yuma_simulation_tpu.serve.service import (
        ServeConfig,
        SimulationService,
    )

    service = SimulationService(ServeConfig(bundle_dir=str(tmp_path)))
    try:
        status, body, _headers = service.handle(
            "simulate", {"case": "Case 1", "tenant": "t"}
        )
        assert status == 200 and body["status"] == "ok"
        snap = service._canary_snapshot()
        assert snap["buckets"] >= 1
        assert service.run_canary_once()["drift"] == 0
    finally:
        service.close()
    assert (tmp_path / "numerics.jsonl").exists()
    assert driftreport_main([str(tmp_path), "--check", "--require"]) == 0


# ------------------------------------------------------ fleet + report


def test_fleet_canary_counts_and_unit_engines(tmp_path):
    """FleetHealthReport surfaces per-unit executed engine rungs and
    the canary/drift counts derived from the merged ledgers; the store
    passes check_fleet and driftreport."""
    from tools.driftreport import main as driftreport_main
    from yuma_simulation_tpu.fabric.health import check_fleet
    from yuma_simulation_tpu.fabric.scheduler import (
        FleetConfig,
        run_fleet_batch,
    )

    out = run_fleet_batch(
        get_cases()[:4],
        VERSION,
        FleetConfig(
            directory=tmp_path, unit_size=2, canary_fraction=1.0
        ),
    )
    rep = out["report"]
    assert rep.canaries_run == 2 and rep.drift_events == 0
    assert rep.unit_engines == ((0, "xla"), (1, "xla"))
    assert rep.clean
    assert check_fleet(tmp_path) == []
    assert (
        driftreport_main([str(tmp_path), "--check", "--require"]) == 0
    )


def test_fleet_canary_fraction_strides_at_fleet_scope(tmp_path):
    """The stride selection happens at FLEET scope: a fraction of 0.5
    over 4 fleet units canaries exactly 2 of them — not all 4, which is
    what per-unit local supervisors (each seeing only local idx 0)
    would do on their own."""
    from yuma_simulation_tpu.fabric.scheduler import (
        FleetConfig,
        _fleet_canary_fraction,
        run_fleet_batch,
    )

    assert [_fleet_canary_fraction(0.5, i) for i in range(4)] == [
        1.0, 0.0, 1.0, 0.0,
    ]
    assert [_fleet_canary_fraction(0.0, i) for i in range(4)] == [0.0] * 4
    out = run_fleet_batch(
        get_cases()[:4],
        VERSION,
        FleetConfig(
            directory=tmp_path, unit_size=1, canary_fraction=0.5
        ),
    )
    assert out["report"].canaries_run == 2


def test_fleet_report_cross_check_catches_canary_tampering(tmp_path):
    """The canary counts are CROSS-CHECKED: a published fleet report
    whose canaries_run disagrees with the merged ledgers fails
    check_fleet (the counts are auditable, not decorative)."""
    from yuma_simulation_tpu.fabric.health import check_fleet
    from yuma_simulation_tpu.fabric.scheduler import (
        FleetConfig,
        run_fleet_batch,
    )
    from yuma_simulation_tpu.fabric.store import FLEET_REPORT_NAME

    run_fleet_batch(
        get_cases()[:2],
        VERSION,
        FleetConfig(directory=tmp_path, unit_size=2, canary_fraction=1.0),
    )
    report_path = pathlib.Path(tmp_path) / FLEET_REPORT_NAME
    rec = json.loads(report_path.read_text())
    rec["canaries_run"] = 99
    report_path.write_text(json.dumps(rec))
    problems = check_fleet(tmp_path)
    assert any("canaries_run" in p for p in problems)


# ----------------------------------------------------- gate + SLO units


def test_driftreport_expected_class_renders_but_passes(tmp_path):
    """A canary record stamped `expected` (the codified u16-fallback
    pairing class, ADVICE r5) renders as drift but does NOT fail the
    gate — codified-accepted, not silently dropped."""
    from tools.driftreport import main as driftreport_main

    records = [
        {
            "unit": 0, "lanes": [0, 1], "stream": "dividends",
            "engine": "fused_scan", "role": "primary", "label": "t",
            "epochs": 3, "fingerprint": [[1, 2, 3]],
            "finite_frac": [[1, 1, 1]], "min": [[0, 0, 0]],
            "max": [[1, 1, 1]], "absmax": [[1, 1, 1]],
        },
        {
            "unit": 0, "lanes": [0, 1], "stream": "dividends",
            "engine": "xla", "role": "canary", "label": "t",
            "epochs": 3, "fingerprint": [[1, 2, 4]],
            "finite_frac": [[1, 1, 1]], "min": [[0, 0, 0]],
            "max": [[1, 1, 1]], "absmax": [[1, 1, 1]],
            "expected": "u16-quantize fallback pairing",
        },
    ]
    (tmp_path / "numerics.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in records)
    )
    assert driftreport_main([str(tmp_path), "--check"]) == 0
    # Strip the expected stamp: the same divergence now fails.
    del records[1]["expected"]
    (tmp_path / "numerics.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in records)
    )
    assert driftreport_main([str(tmp_path), "--check"]) == 1


def test_driftreport_malformed_records_exit_2(tmp_path):
    from tools.driftreport import main as driftreport_main

    (tmp_path / "numerics.jsonl").write_text(
        json.dumps({"unit": 0, "role": "primary"}) + "\n"
    )
    assert driftreport_main([str(tmp_path), "--check"]) == 2


def test_driftreport_require_flags_missing_stream(tmp_path):
    from tools.driftreport import main as driftreport_main

    assert driftreport_main([str(tmp_path), "--check"]) == 0
    assert driftreport_main([str(tmp_path), "--check", "--require"]) == 1


def test_engine_drift_slo_fast_burns_on_single_event():
    """The drift SLOSpec is min_events=1 by design: ONE confirmed drift
    is an incident (the stream carries only deliberate canary
    comparisons), and recovery un-flips it when the window passes."""
    from yuma_simulation_tpu.telemetry.slo import (
        DEFAULT_SLO_SPECS,
        SLOEngine,
    )

    spec = next(s for s in DEFAULT_SLO_SPECS if s.name == "engine_drift")
    assert spec.degrade and spec.min_events == 1
    clock = [1000.0]
    engine = SLOEngine(DEFAULT_SLO_SPECS, clock=lambda: clock[0])
    engine.event("engine_drift_ok", True)
    assert engine.state("engine_drift") == "ok"
    engine.event("engine_drift_ok", False)
    assert engine.state("engine_drift") == "fast_burn"
    assert "engine_drift" in engine.degraded()
    clock[0] += spec.slow_window_seconds + 10
    assert engine.state("engine_drift") == "ok"


def test_planner_records_expected_drift_reason_for_explicit_fused():
    """An EXPLICIT fused opt-in beyond the int32 dyadic bound plans
    with the documented accepted-drift caveat recorded; auto refuses
    the pairing outright (the eligibility gate)."""
    from yuma_simulation_tpu.simulation.planner import (
        EXPECTED_DRIFT_U16_FALLBACK,
        plan_dispatch,
    )

    plan = plan_dispatch(
        "t", (4, 4, 16384), VERSION, YumaConfig(), jnp.float32,
        epoch_impl="fused_scan", check_memory=False,
    )
    assert EXPECTED_DRIFT_U16_FALLBACK in plan.reasons
    auto = plan_dispatch(
        "t", (4, 4, 16384), VERSION, YumaConfig(), jnp.float32,
        epoch_impl="auto", check_memory=False,
    )
    assert auto.engine == "xla"


# ------------------------------------------------------- compile budget


def test_canaried_sweep_warm_repeat_is_compile_free():
    """The capture is part of the one traced program and the canary
    re-uses the demoted rung's existing cache entry: a warm canaried
    sweep adds ZERO jit-cache entries (the existing pins in
    test_recompilation.py stay untouched; this pins the NEW path)."""
    from yuma_simulation_tpu.simulation.engine import _simulate_scan
    from yuma_simulation_tpu.simulation.sweep import _simulate_batch_xla
    from yuma_simulation_tpu.utils.profiling import RecompilationSentinel

    cases = get_cases()[:4]
    sup = _supervisor(canary_fraction=1.0)
    sup.run_batch(cases, VERSION)  # warm-up (cold compiles allowed)
    with RecompilationSentinel(
        _simulate_batch_xla,
        _simulate_scan,
        budget=0,
        label="canaried sweep warm repeat",
    ) as sentinel:
        out = sup.run_batch(cases, VERSION)
    assert sentinel.new_entries == 0
    assert out["report"].canaries_run == 2
