"""Heterogeneous-suite padding: batched == individual, padding inert.

SURVEY.md §7 hard part (e): padded miner columns must contribute zero
weight everywhere (and not perturb the u16 consensus grid of real
miners); padded validators zero stake; padded epochs zero dividends.
"""

import numpy as np
import pytest

from yuma_simulation_tpu.scenarios import create_case
from yuma_simulation_tpu.scenarios.synthetic import random_subnet_scenario
from yuma_simulation_tpu.simulation.engine import simulate
from yuma_simulation_tpu.simulation.sweep import pad_scenarios, total_dividends_batch


@pytest.fixture(scope="module")
def hetero_suite():
    return [
        create_case("Case 1"),  # 40e x 3v x 2m
        random_subnet_scenario(1, num_validators=5, num_miners=7, num_epochs=30),
        random_subnet_scenario(2, num_validators=4, num_miners=3, num_epochs=40),
    ]


def test_pad_scenarios_shapes(hetero_suite):
    W, S, ri, re, mask = pad_scenarios(hetero_suite)
    assert W.shape == (3, 40, 5, 7)
    assert S.shape == (3, 40, 5)
    assert mask.shape == (3, 7)
    np.testing.assert_array_equal(np.asarray(mask[0]), [1, 1, 0, 0, 0, 0, 0])
    # padded epochs of the 30-epoch scenario carry zero stake
    assert float(np.abs(np.asarray(S[1, 30:])).max()) == 0.0


@pytest.mark.parametrize(
    "version",
    [
        "Yuma 0 (subtensor)",
        "Yuma 1 (paper)",
        "Yuma 1 (paper) - liquid alpha on",
        "Yuma 2 (Adrian-Fish)",
        "Yuma 3 (Rhef)",
        "Yuma 4 (Rhef+relative bonds)",
        "Yuma 4 (Rhef+relative bonds) - liquid alpha on",
    ],
)
def test_padded_batch_matches_individual(hetero_suite, version):
    # The liquid variants exercise the masked quantile path: padded zero
    # columns must not shift the 0.25/0.75 consensus quantiles.
    config = None
    if "liquid" in version:
        from yuma_simulation_tpu.models.config import YumaConfig, YumaParams

        config = YumaConfig(yuma_params=YumaParams(liquid_alpha=True))
    batched = total_dividends_batch(hetero_suite, version, config)
    for i, s in enumerate(hetero_suite):
        solo = simulate(
            s, version, config, save_bonds=False, save_incentives=False
        ).dividends.sum(axis=0)
        v = len(s.validators)
        np.testing.assert_allclose(
            batched[i, :v], solo, rtol=2e-5, atol=2e-6,
            err_msg=f"{version} scenario {i}",
        )
        if batched.shape[1] > v:
            assert float(np.abs(batched[i, v:]).max()) == 0.0
