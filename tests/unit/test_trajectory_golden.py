"""Per-epoch trajectory parity vs the reference driver.

Totals can mask compensating errors in the carry logic (bond EMA,
W_prev threading, reset injection); these goldens pin the full `[E, V]`
dividend time-series and the final bond state for the carry-heavy cases
(Case 5: reset metadata; Case 9: time-varying stakes; Case 11: reset with
non-default stakes) across all 9 versions at beta=0.99.
"""

import os

import numpy as np
import pytest

from tests.conftest import GOLDEN_DIR
from yuma_simulation_tpu.models.config import SimulationHyperparameters, YumaConfig
from yuma_simulation_tpu.models.variants import canonical_versions
from yuma_simulation_tpu.scenarios import create_case
from yuma_simulation_tpu.simulation.engine import simulate

_GOLDENS = np.load(os.path.join(GOLDEN_DIR, "trajectory_goldens.npz"))
_VERSIONS = canonical_versions()


@pytest.mark.parametrize("epoch_impl", ["xla", "fused_scan"])
@pytest.mark.parametrize("short", ["Case 5", "Case 9", "Case 11"])
@pytest.mark.parametrize("version_params", _VERSIONS, ids=[v for v, _ in _VERSIONS])
def test_dividend_trajectory_parity(short, version_params, epoch_impl):
    version, params = version_params
    case = create_case(short)
    cfg = YumaConfig(
        simulation=SimulationHyperparameters(bond_penalty=0.99),
        yuma_params=params,
    )
    res = simulate(
        case, version, cfg, save_incentives=False, epoch_impl=epoch_impl
    )

    golden_div = _GOLDENS[f"{short}/{version}/dividends"]
    np.testing.assert_allclose(
        res.dividends, golden_div, rtol=5e-5, atol=2e-6,
        err_msg=f"{short} x {version} dividends trajectory",
    )
    golden_bonds = _GOLDENS[f"{short}/{version}/final_bonds"]
    np.testing.assert_allclose(
        res.bonds[-1],
        golden_bonds,
        rtol=5e-4,
        atol=1e-5 * max(1.0, float(np.abs(golden_bonds).max())),
        err_msg=f"{short} x {version} final bonds",
    )
