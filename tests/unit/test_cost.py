"""The AOT cost-model layer (ISSUE 5 tentpole): cost extraction on CPU
(partial fields tolerated), roofline math, HBM preflight rejection of the
known-overflow shape BEFORE compilation, perfgate verdicts on synthetic
histories, and the costs.jsonl flight-recorder flow."""

import json
import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yuma_simulation_tpu.telemetry.cost import (
    DEVICE_SPEC_ENV,
    ENGINE_RUNGS,
    PREFLIGHT_ENV,
    CostRecord,
    DeviceSpec,
    HBMPreflightError,
    _normalize_cost_analysis,
    capture_engine_cost,
    capture_engine_costs,
    estimate_hbm_bytes,
    preflight_hbm,
    resolve_device_spec,
    roofline,
)

SMALL_SPEC_ENV = json.dumps(
    {"name": "test-16g", "peak_flops": 1.97e14,
     "hbm_bandwidth": 8.19e11, "memory_bytes": 16 * 2**30}
)


# ---------------------------------------------------------------------------
# Cost extraction on CPU


def test_capture_xla_engine_cost_on_cpu():
    """The XLA rung captures real flops/bytes/peak on CPU; the analysis
    is normalized across jax versions (list- or dict-shaped)."""
    rec = capture_engine_cost("xla", 16, 32, 8)
    assert rec.engine == "xla" and rec.backend == "cpu"
    assert rec.flops and rec.flops > 0
    assert rec.bytes_accessed and rec.bytes_accessed > 0
    assert rec.peak_bytes and rec.peak_bytes > 0
    assert rec.peak_bytes_source in ("memory_analysis", "derived")
    assert rec.argument_bytes and rec.output_bytes is not None
    # [8, 16, 32] f32 weights + [8, 16] stakes + 2 int32 scalars.
    assert rec.argument_bytes >= 8 * 16 * 32 * 4
    assert rec.hlo_fingerprint and len(rec.hlo_fingerprint) == 16
    assert rec.reason is None


def test_fused_rungs_yield_explicit_null_with_reason_on_cpu():
    """Acceptance: every rung in the cost report carries flops/bytes/
    peak-memory fields — as numbers, or explicit null WITH a reason
    (the fused Pallas rungs off-TPU)."""
    costs = capture_engine_costs(16, 32, 8)
    assert set(costs) == set(ENGINE_RUNGS)
    for engine in ("fused_scan", "fused_scan_mxu"):
        rec = costs[engine]
        assert rec.flops is None and rec.bytes_accessed is None
        assert rec.peak_bytes is None
        assert rec.reason and "TPU" in rec.reason
    as_json = costs["xla"].to_json()
    for field in ("flops", "bytes_accessed", "peak_bytes",
                  "hlo_fingerprint", "reason"):
        assert field in as_json


def test_hlo_fingerprint_tracks_the_program():
    """Same shape -> same fingerprint (deterministic lowering); a
    different shape is a different program."""
    a = capture_engine_cost("xla", 16, 32, 8)
    b = capture_engine_cost("xla", 16, 32, 8)
    c = capture_engine_cost("xla", 16, 64, 8)
    assert a.hlo_fingerprint == b.hlo_fingerprint
    assert a.hlo_fingerprint != c.hlo_fingerprint


def test_cost_analysis_scan_amortization_pinned():
    """XLA's cost_analysis counts a scan body ONCE regardless of trip
    count — the documented reason rooflines are ceilings, not
    forecasts. If a jax upgrade starts scaling flops with E, this pin
    flags it so the roofline docs (and perfgate baselines) follow."""
    e8 = capture_engine_cost("xla", 16, 32, 8)
    e32 = capture_engine_cost("xla", 16, 32, 32)
    assert e8.flops == e32.flops  # amortized body
    assert e32.argument_bytes > e8.argument_bytes  # the [E,V,M] stack grows


def test_normalize_cost_analysis_shapes():
    assert _normalize_cost_analysis(None) == {}
    flat = _normalize_cost_analysis({"flops": 2.0, "bytes accessed": 3.0})
    assert flat == {"flops": 2.0, "bytes accessed": 3.0}
    summed = _normalize_cost_analysis(
        [{"flops": 2.0}, {"flops": 1.0, "transcendentals": 4.0}]
    )
    assert summed["flops"] == 3.0 and summed["transcendentals"] == 4.0


def test_unknown_engine_rejected():
    with pytest.raises(ValueError, match="unknown engine"):
        capture_engine_cost("warp_drive", 16, 32, 8)


# ---------------------------------------------------------------------------
# Roofline math


def _rec(flops, bytes_accessed, epochs=100):
    return CostRecord(
        engine="xla", backend="tpu", V=16, M=32, epochs=epochs,
        flops=flops, bytes_accessed=bytes_accessed,
    )


def test_roofline_memory_bound():
    spec = DeviceSpec("t", peak_flops=1e12, hbm_bandwidth=1e9)
    # intensity 0.1 << ridge 1000 -> memory bound; t = 1e9/1e9 = 1 s.
    rl = roofline(_rec(1e8, 1e9), spec, measured_epochs_per_sec=50.0)
    assert rl.bound == "memory"
    assert rl.arithmetic_intensity == pytest.approx(0.1)
    assert rl.ridge_intensity == pytest.approx(1000.0)
    assert rl.predicted_seconds == pytest.approx(1.0)
    assert rl.predicted_epochs_per_sec == pytest.approx(100.0)
    assert rl.attained_fraction == pytest.approx(0.5)


def test_roofline_compute_bound():
    spec = DeviceSpec("t", peak_flops=1e12, hbm_bandwidth=1e12)
    # intensity 100 >= ridge 1 -> compute bound; t = 1e14/1e12 = 100 s.
    rl = roofline(_rec(1e14, 1e12), spec)
    assert rl.bound == "compute"
    assert rl.predicted_seconds == pytest.approx(100.0)
    assert rl.attained_fraction is None


def test_roofline_degrades_on_unknown_spec_and_null_record():
    rl = roofline(_rec(1e8, 1e9), DeviceSpec("mystery"))
    assert rl.bound is None and rl.predicted_epochs_per_sec is None
    assert rl.arithmetic_intensity == pytest.approx(0.1)
    null = CostRecord(engine="fused_scan", backend="cpu", V=16, M=32,
                      epochs=8, reason="unavailable")
    rl2 = roofline(null, DeviceSpec("t", 1e12, 1e9))
    assert rl2.bound is None and rl2.predicted_seconds is None


def test_resolve_device_spec_env_override(monkeypatch):
    monkeypatch.setenv(DEVICE_SPEC_ENV, SMALL_SPEC_ENV)
    spec = resolve_device_spec()
    assert spec.name == "test-16g"
    assert spec.memory_bytes == 16 * 2**30
    monkeypatch.setenv(DEVICE_SPEC_ENV, "not json {")
    assert resolve_device_spec().name != "test-16g"  # ignored, falls back
    # explicit override beats env
    monkeypatch.setenv(DEVICE_SPEC_ENV, SMALL_SPEC_ENV)
    assert resolve_device_spec(DeviceSpec("explicit")).name == "explicit"


# ---------------------------------------------------------------------------
# Footprint + preflight


def test_estimate_hbm_bytes_arithmetic():
    base = estimate_hbm_bytes(8192, 131072, resident_epochs=0)
    # 6 working-set [V, M] f32 buffers at 4 GiB each = 24 GiB.
    assert base.total_bytes == 6 * 8192 * 131072 * 4
    sharded = estimate_hbm_bytes(8192, 131072, resident_epochs=0,
                                 miner_shards=4)
    assert sharded.total_bytes == base.total_bytes // 4
    stacked = estimate_hbm_bytes(64, 128, resident_epochs=10,
                                 save_bonds=True)
    assert stacked.breakdown["weights_stack"] == 10 * 64 * 128 * 4
    assert stacked.breakdown["bonds_out"] == 10 * 64 * 128 * 4
    lanes = estimate_hbm_bytes(64, 128, resident_epochs=10, batch_lanes=3)
    assert lanes.total_bytes == 3 * estimate_hbm_bytes(
        64, 128, resident_epochs=10
    ).total_bytes


def test_preflight_rejects_known_overflow_shape(caplog):
    """Acceptance: 8192x131072 (the shape the memory envelope brackets
    as failing at compile) rejects with a typed event BEFORE any
    compile."""
    from yuma_simulation_tpu.utils.logging import parse_event_line

    spec = DeviceSpec("test-16g", memory_bytes=16 * 2**30)
    est = estimate_hbm_bytes(8192, 131072, resident_epochs=0)
    with caplog.at_level(logging.WARNING,
                         "yuma_simulation_tpu.telemetry.cost"):
        with pytest.raises(HBMPreflightError) as err:
            preflight_hbm("envelope", est, spec=spec)
    verdict = err.value.verdict
    assert verdict.fits is False
    assert verdict.predicted_bytes == est.total_bytes
    assert "shard the miner axis" in (verdict.suggestion or "")
    events = [parse_event_line(r.getMessage()) for r in caplog.records]
    events = [e for e in events if e and e["event"] == "preflight_rejected"]
    assert len(events) == 1
    assert events[0]["V"] == "8192" and events[0]["M"] == "131072"
    assert events[0]["device"] == "test-16g"


def test_preflight_passes_fitting_and_unknown_capacity():
    spec = DeviceSpec("test-16g", memory_bytes=16 * 2**30)
    ok = preflight_hbm(
        "envelope", estimate_hbm_bytes(1024, 16384, resident_epochs=0),
        spec=spec,
    )
    assert ok.fits is True
    unknown = preflight_hbm(
        "envelope", estimate_hbm_bytes(8192, 131072, resident_epochs=0),
        spec=DeviceSpec("cpu"),
    )
    assert unknown.fits is None  # open pass, no event, no raise


def test_preflight_env_disable(monkeypatch):
    monkeypatch.setenv(PREFLIGHT_ENV, "0")
    spec = DeviceSpec("test-16g", memory_bytes=16 * 2**30)
    v = preflight_hbm(
        "envelope", estimate_hbm_bytes(8192, 131072, resident_epochs=0),
        spec=spec,
    )
    assert v.fits is None


def test_preflight_suggests_streaming_when_epoch_stack_dominates():
    spec = DeviceSpec("test-16g", memory_bytes=16 * 2**30)
    # 256x4096: working set 24 MiB; 65536 resident epochs = 256 GiB.
    est = estimate_hbm_bytes(256, 4096, resident_epochs=65536)
    v = preflight_hbm("simulate", est, spec=spec, raise_on_reject=False)
    assert v.fits is False
    assert "max_resident_epochs" in v.suggestion


def test_simulate_constant_preflight_fires_before_any_allocation(monkeypatch):
    """The engine advisor integration: the known-overflow shape is
    rejected on ShapeDtypeStructs — no 4 GiB buffer is ever built, no
    trace starts (a trace would TypeError on the abstract W first)."""
    from yuma_simulation_tpu.models.config import YumaConfig
    from yuma_simulation_tpu.models.variants import variant_for_version
    from yuma_simulation_tpu.simulation.engine import simulate_constant

    monkeypatch.setenv(DEVICE_SPEC_ENV, SMALL_SPEC_ENV)
    W = jax.ShapeDtypeStruct((8192, 131072), jnp.float32)
    S = jax.ShapeDtypeStruct((8192,), jnp.float32)
    with pytest.raises(HBMPreflightError, match="simulate_constant"):
        simulate_constant(
            W, S, 10, YumaConfig(), variant_for_version("Yuma 1 (paper)")
        )


def test_simulate_preflight_rejects_under_tiny_spec(monkeypatch):
    from yuma_simulation_tpu.scenarios import create_case
    from yuma_simulation_tpu.simulation.engine import simulate

    monkeypatch.setenv(
        DEVICE_SPEC_ENV,
        json.dumps({"name": "tiny", "memory_bytes": 512}),
    )
    case = create_case("Case 1")
    with pytest.raises(HBMPreflightError, match="predicted peak HBM"):
        simulate(case, "Yuma 1 (paper)")
    # The same dispatch passes when the preflight is disabled.
    monkeypatch.setenv(PREFLIGHT_ENV, "0")
    out = simulate(case, "Yuma 1 (paper)")
    assert np.isfinite(out.dividends).all()


def test_sharded_batch_preflight_rejects_under_tiny_spec(monkeypatch):
    from yuma_simulation_tpu.parallel import make_mesh
    from yuma_simulation_tpu.parallel.sharded import simulate_batch_sharded
    from yuma_simulation_tpu.scenarios import create_case

    monkeypatch.setenv(
        DEVICE_SPEC_ENV,
        json.dumps({"name": "tiny", "memory_bytes": 512}),
    )
    cases = [create_case("Case 1"), create_case("Case 2")]
    with pytest.raises(HBMPreflightError, match="sharded_batch"):
        simulate_batch_sharded(cases, "Yuma 1 (paper)", mesh=make_mesh())


def test_preflight_error_is_not_ladder_retryable():
    """classify_failure must treat a preflight rejection as a caller
    error (None), never as a retryable engine failure: no amount of
    rung demotion changes the arithmetic."""
    from yuma_simulation_tpu.resilience.errors import classify_failure

    assert classify_failure(HBMPreflightError("no fit")) is None


# ---------------------------------------------------------------------------
# perfgate verdicts on synthetic histories


def _history_record(value, cv=0.02, smoke=False, backend="cpu", t=0.0,
                    secondary=None, **overrides):
    costs = {
        engine: {
            "engine": engine, "backend": backend, "V": 256, "M": 4096,
            "epochs": 512,
            "flops": 1e8 if engine == "xla" else None,
            "bytes_accessed": 2e8 if engine == "xla" else None,
            "peak_bytes": 2**30 if engine == "xla" else None,
            "reason": None if engine == "xla" else "TPU-only rung",
        }
        for engine in ENGINE_RUNGS
    }
    # The 0.10.0 schema (grown 0.19.0): the per-epoch-weights lines —
    # XLA and fused-varying — are first-class tracked metrics, and
    # every record declares its attained-fraction floors.
    tracked = {
        "true_weights_xla": value / 10,
        "true_weights_fused": value / 10,
        "streamed_true_weights": value / 8,
        "montecarlo_per_epoch_weights": value / 9,
        "montecarlo_per_epoch_fused": value / 9,
    }
    tracked.update(secondary or {})
    record = {
        "t": t, "backend": backend, "smoke": smoke, "jax": "x",
        "metric": "epochs/sec", "value": value, "unit": "epochs/s",
        "secondary": tracked,
        "cv": {"primary": cv}, "costs": costs, "rooflines": {},
        "attained_floor": {"xla": 0.002},
        # The 0.14.0 schema: the numerics-capture overhead is a
        # first-class gated metric (structural + ceiling gates).
        "numerics": {
            "workload": "true_weights_xla",
            "epochs_per_sec_off": value / 10,
            "epochs_per_sec_on": value / 10 * 0.99,
            "overhead_frac": 0.01,
        },
        # The 0.17.0 schema: fresh-subprocess cold-start seconds (cold
        # vs executable-cache-warm) are first-class gated metrics.
        "cold_start": {
            "shape": "64x32x64",
            "first_dispatch_seconds_cold": 6.0,
            "first_dispatch_seconds_warm": 3.5,
            "warm_aot": {"hits": 1, "misses": 0, "builds": 0},
        },
        # The 0.23.0 schema: the dispatch-sketch observation overhead
        # is a first-class gated metric (structural + ceiling gates).
        "dispatch_sketch": {
            "workload": "simulate() 64v x 256m, E=64",
            "epochs_per_sec_off": value / 10,
            "epochs_per_sec_on": value / 10 * 0.99,
            "overhead_frac": 0.01,
        },
        # The 0.18.0 schema: the what-if suffix-resume speedup is a
        # first-class gated metric (structural + ratio-floor gates).
        "whatif": {
            "shape": "40x128x1024",
            "resume_epoch": 32,
            "epochs": 40,
            "epoch_ratio": 5.0,
            "full_seconds": 0.15,
            "suffix_seconds": 0.045,
            "speedup": 3.3,
        },
    }
    record.update(overrides)
    return record


def _write_history(tmp_path, records):
    path = tmp_path / "hist.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in records))
    return str(path)


def test_perfgate_detects_regression(tmp_path, capsys):
    from tools.perfgate import compare, main

    records = [_history_record(100.0, t=i) for i in range(5)]
    records.append(_history_record(70.0, t=5))
    result = compare(records)
    assert result["verdicts"]["primary"]["status"] == "regression"
    path = _write_history(tmp_path, records)
    assert main(["--history", path, "--check"]) == 1
    assert main(["--history", path]) == 0  # report-only never gates
    capsys.readouterr()


def test_perfgate_improvement_and_flat(tmp_path):
    from tools.perfgate import compare

    records = [_history_record(100.0, t=i) for i in range(4)]
    assert (
        compare(records + [_history_record(140.0, t=9)])["verdicts"][
            "primary"]["status"]
        == "improvement"
    )
    assert (
        compare(records + [_history_record(97.0, t=9)])["verdicts"][
            "primary"]["status"]
        == "flat"
    )


def test_perfgate_noisy_but_flat_widens_tolerance(tmp_path):
    """A 25% drop under cv=0.15 (noise_mult 3 -> 45% tolerance) must NOT
    false-fail; the same drop on a tight metric must."""
    from tools.perfgate import compare

    noisy = [_history_record(100.0, cv=0.15, t=i) for i in range(5)]
    verdict = compare(noisy + [_history_record(75.0, cv=0.15, t=9)])[
        "verdicts"]["primary"]
    assert verdict["status"] == "flat"
    assert verdict["tolerance"] == pytest.approx(0.45)
    tight = [_history_record(100.0, cv=0.01, t=i) for i in range(5)]
    assert (
        compare(tight + [_history_record(75.0, cv=0.01, t=9)])["verdicts"][
            "primary"]["status"]
        == "regression"
    )


def test_perfgate_baselines_never_mix_backends_or_smoke(tmp_path):
    from tools.perfgate import compare

    history = [_history_record(100.0, backend="tpu", t=i) for i in range(5)]
    history += [_history_record(100.0, smoke=True, t=i) for i in range(5)]
    # A fresh real CPU capture has NO comparable baseline despite 10
    # prior records.
    verdict = compare(history + [_history_record(10.0, t=99)])["verdicts"][
        "primary"]
    assert verdict["status"] == "no_baseline"


def test_perfgate_structural_gate(tmp_path):
    from tools.perfgate import check_structure, main

    sound = _history_record(100.0)
    assert check_structure(sound) == []
    # A null analysis field with no reason is schema rot.
    broken = _history_record(100.0)
    broken["costs"]["xla"]["flops"] = None
    problems = check_structure(broken)
    assert any("null with no reason" in p for p in problems)
    # A missing rung is schema rot.
    short = _history_record(100.0)
    del short["costs"]["fused_scan"]
    assert any("fused_scan" in p for p in check_structure(short))
    # An EMPTY cost report is schema rot too (--skip-costs captures must
    # not green the CI gate), and a non-dict rung entry must be reported
    # rather than crash the gate.
    empty_costs = _history_record(100.0)
    empty_costs["costs"] = {}
    assert len(check_structure(empty_costs)) == len(ENGINE_RUNGS)
    mangled = _history_record(100.0)
    mangled["costs"]["xla"] = 1
    assert any("not an object" in p for p in check_structure(mangled))
    path = _write_history(tmp_path, [broken])
    assert main(["--history", path, "--check", "--structural"]) == 2
    path2 = _write_history(tmp_path, [sound])
    assert main(["--history", path2, "--check", "--structural"]) == 0
    # Empty history is a structural failure, not a pass.
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert main(["--history", str(empty), "--check"]) == 2


def test_perfgate_tracked_secondary_is_structural(tmp_path):
    """ISSUE 6 satellite: the three per-epoch-weights lines are
    first-class gated metrics — a record that drops one (or ships a
    non-numeric value) is schema rot, exactly like a missing cost
    rung."""
    from tools.perfgate import TRACKED_SECONDARY, check_structure, main

    for name in TRACKED_SECONDARY:
        record = _history_record(100.0)
        del record["secondary"][name]
        assert any(name in p for p in check_structure(record)), name
        record = _history_record(100.0)
        record["secondary"][name] = "fast"
        assert any(name in p for p in check_structure(record)), name
    missing_floor = _history_record(100.0)
    del missing_floor["attained_floor"]
    assert any("attained_floor" in p for p in check_structure(missing_floor))
    path = _write_history(tmp_path, [_history_record(100.0)])
    assert main(["--history", path, "--check", "--structural"]) == 0


def test_perfgate_attained_fraction_gate(tmp_path, capsys):
    """ISSUE 6 tentpole (c): a rung whose measured rate drops below its
    declared fraction of the roofline prediction fails --check — in
    structural mode too — while null fractions (every CPU build) pass
    vacuously and CLI floors override the record's declaration."""
    from tools.perfgate import check_attained, main

    def with_attained(frac, floor=0.25):
        record = _history_record(100.0)
        record["rooflines"] = {
            "xla": {"engine": "xla", "attained_fraction": frac},
            "fused_scan": {"engine": "fused_scan",
                           "attained_fraction": None},
        }
        record["attained_floor"] = {"xla": floor}
        return record

    assert check_attained(with_attained(0.5)) == []
    failures = check_attained(with_attained(0.1))
    assert len(failures) == 1 and "xla" in failures[0]
    # Null fractions never fail; un-floored rungs never fail.
    assert check_attained(with_attained(None)) == []
    # CLI override beats the record's declaration.
    assert check_attained(with_attained(0.5), {"xla": 0.9})
    path = _write_history(tmp_path, [with_attained(0.1)])
    assert main(["--history", path, "--check", "--structural"]) == 1
    assert main(["--history", path, "--check"]) == 1
    # Report-only never gates; a passing floor exits 0.
    assert main(["--history", path]) == 0
    ok = _write_history(tmp_path, [with_attained(0.5)])
    assert main(["--history", ok, "--check", "--structural"]) == 0
    # The override can fail a record its own declaration passes.
    assert main(
        ["--history", ok, "--check", "--attained-floor", "xla=0.9"]
    ) == 1
    capsys.readouterr()


def test_perfgate_attained_fraction_rides_baseline_diff():
    """The distance-to-ceiling is also a baselined metric: a drop in
    attained fraction regresses even when no floor is declared."""
    from tools.perfgate import compare

    def rec(frac, t):
        record = _history_record(100.0, t=t)
        record["rooflines"] = {
            "xla": {"engine": "xla", "attained_fraction": frac}
        }
        return record

    history = [rec(0.5, t=i) for i in range(5)] + [rec(0.2, t=9)]
    verdict = compare(history)["verdicts"]["attained:xla"]
    assert verdict["status"] == "regression"


def test_perfgate_cold_start_is_structural(tmp_path):
    """ISSUE 13 satellite: the cold-start pair is schema — a record
    that drops it, ships a non-numeric value, or carries the child's
    error object is rot, exactly like a missing cost rung."""
    from tools.perfgate import COLD_START_FIELDS, check_structure, main

    sound = _history_record(100.0)
    assert check_structure(sound) == []
    for field in COLD_START_FIELDS:
        record = _history_record(100.0)
        del record["cold_start"][field]
        assert any(field in p for p in check_structure(record)), field
    missing = _history_record(100.0)
    del missing["cold_start"]
    assert any("cold_start" in p for p in check_structure(missing))
    # A failed measurement ({} or an error object) is rot, with the
    # child's error surfaced in the problem line.
    skipped = _history_record(100.0, cold_start={})
    assert any("cold_start" in p for p in check_structure(skipped))
    errored = _history_record(
        100.0, cold_start={"shape": "64x32x64", "error": "child died"}
    )
    problems = check_structure(errored)
    assert any("child died" in p for p in problems)
    path = _write_history(tmp_path, [errored])
    assert main(["--history", path, "--check", "--structural"]) == 2


def test_perfgate_cold_start_ceiling_gate(tmp_path, capsys):
    """--cold-start-ceiling: the CACHE-WARM first dispatch is gated
    against a declared wall-seconds budget — active in structural mode
    (the pair is an in-record measurement), vacuous without the flag."""
    from tools.perfgate import check_cold_start, main

    record = _history_record(100.0)
    assert check_cold_start(record) == []  # no ceiling declared
    assert check_cold_start(record, ceiling=10.0) == []
    failures = check_cold_start(record, ceiling=1.0)
    assert len(failures) == 1 and "3.5" in failures[0]
    path = _write_history(tmp_path, [record])
    assert main(
        ["--history", path, "--check", "--structural",
         "--cold-start-ceiling", "10.0"]
    ) == 0
    assert main(
        ["--history", path, "--check", "--structural",
         "--cold-start-ceiling", "1.0"]
    ) == 1
    capsys.readouterr()


def test_perfgate_report_artifact(tmp_path):
    from tools.perfgate import main

    path = _write_history(
        tmp_path,
        [_history_record(100.0, t=i) for i in range(3)]
        + [_history_record(101.0, t=9)],
    )
    report = tmp_path / "perfgate_report.json"
    assert main(["--history", path, "--check", "--report",
                 str(report)]) == 0
    payload = json.loads(report.read_text())
    assert payload["verdicts"]["primary"]["status"] == "flat"
    assert payload["structural_problems"] == []


# ---------------------------------------------------------------------------
# costs.jsonl flight flow + obsreport perf section


def test_flight_record_costs_merge_and_check(tmp_path):
    from yuma_simulation_tpu.telemetry.flight import (
        FlightRecorder,
        check_bundle,
        load_bundle,
    )

    recorder = FlightRecorder(tmp_path)
    costs = capture_engine_costs(16, 32, 8)
    recorder.record_costs(costs, run_id="run-a")
    recorder.record_costs(costs, run_id="run-a")  # re-capture: no dupes
    bundle = load_bundle(tmp_path)
    assert len(bundle.costs) == len(ENGINE_RUNGS)
    assert {r["engine"] for r in bundle.costs} == set(ENGINE_RUNGS)
    assert all(r["run_id"] == "run-a" for r in bundle.costs)
    assert check_bundle(bundle) == []
    # A second run at another shape accumulates.
    recorder.record_costs(
        [capture_engine_cost("xla", 16, 64, 8)], run_id="run-b"
    )
    assert len(load_bundle(tmp_path).costs) == len(ENGINE_RUNGS) + 1


def test_check_bundle_flags_null_cost_without_reason(tmp_path):
    from yuma_simulation_tpu.telemetry.flight import (
        FlightRecorder,
        check_bundle,
        load_bundle,
    )

    recorder = FlightRecorder(tmp_path)
    bad = CostRecord(engine="xla", backend="cpu", V=1, M=1, epochs=1)
    recorder.record_costs([bad])
    problems = check_bundle(load_bundle(tmp_path))
    assert any("null flops with no reason" in p for p in problems)


def test_obsreport_renders_perf_section(tmp_path):
    from tools.obsreport import render_perf
    from yuma_simulation_tpu.telemetry.flight import (
        FlightRecorder,
        load_bundle,
    )

    FlightRecorder(tmp_path).record_costs(capture_engine_costs(16, 32, 8))
    lines = render_perf(load_bundle(tmp_path))
    text = "\n".join(lines)
    assert "AOT cost report" in text
    assert "xla [8x16x32]:" in text and "flops=" in text
    assert "unavailable" in text  # the fused rungs on CPU, reason shown


def test_obsreport_perf_tolerates_minimal_cost_lines(tmp_path):
    """A check_bundle-valid but minimal costs.jsonl line (foreign
    writer) must render, not crash the report."""
    from tools.obsreport import render_perf
    from yuma_simulation_tpu.telemetry.flight import (
        COSTS_NAME,
        check_bundle,
        load_bundle,
    )

    (tmp_path / COSTS_NAME).write_text(
        json.dumps({"engine": "xla", "flops": 1e9, "bytes_accessed": 2e9})
        + "\n"
    )
    bundle = load_bundle(tmp_path)
    assert check_bundle(bundle) == []
    text = "\n".join(render_perf(bundle))
    assert "xla" in text and "flops=1e+09" in text


# ---------------------------------------------------------------------------
# compile_seconds histogram (RecompilationSentinel satellite)


def test_sentinel_records_compile_seconds_histogram():
    from yuma_simulation_tpu.telemetry.metrics import get_registry
    from yuma_simulation_tpu.utils.profiling import RecompilationSentinel

    registry = get_registry()
    before = registry.histogram("compile_seconds").snapshot()["count"]

    @jax.jit
    def fresh(x):
        return x * jnp.asarray(2.0, jnp.float32)

    with RecompilationSentinel(fresh, budget=1, label="cold"):
        np.asarray(fresh(jnp.ones((4,), jnp.float32)))
    after = registry.histogram("compile_seconds").snapshot()
    assert after["count"] == before + 1
    assert after["sum"] > 0
    # A compile-free region must NOT observe (no phantom compile time).
    with RecompilationSentinel(fresh, budget=0, label="warm"):
        np.asarray(fresh(jnp.ones((4,), jnp.float32)))
    assert registry.histogram("compile_seconds").snapshot()["count"] == (
        before + 1
    )


def test_record_epoch_rate_cv_gauge_and_event(caplog):
    from yuma_simulation_tpu.telemetry.metrics import (
        MetricsRegistry,
        record_epoch_rate,
    )
    from yuma_simulation_tpu.utils.logging import parse_event_line

    registry = MetricsRegistry()
    with caplog.at_level(logging.INFO,
                         "yuma_simulation_tpu.telemetry.metrics"):
        record_epoch_rate(
            "bench", epochs_per_sec=123.0, cv=0.07, registry=registry
        )
    assert registry.gauge("epochs_per_sec_cv").value == pytest.approx(0.07)
    events = [parse_event_line(r.getMessage()) for r in caplog.records]
    events = [e for e in events if e and e["event"] == "epoch_rate"]
    assert events and events[0]["cv"] == "0.0700"
