"""Resilience layer: error taxonomy, engine-degradation ladder, and
numerical quarantine — every recovery path provoked deterministically on
CPU via the fault-injection hooks (ISSUE 1 acceptance criteria: a forced
fused-engine OOM retries and completes on the XLA engine with identical
results to a clean XLA run; an injected NaN at epoch k quarantines only
that case while the rest of the batch matches a clean run bitwise)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yuma_simulation_tpu.models.config import YumaConfig, YumaParams
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.resilience import (
    ENGINE_LADDER,
    EngineCompileError,
    EngineFailure,
    EngineLadderExhausted,
    EngineResourceExhausted,
    FaultPlan,
    NaNFault,
    RetryPolicy,
    build_quarantine_report,
    classify_failure,
    inject_faults,
    ladder_from,
)
from yuma_simulation_tpu.resilience.retry import run_ladder
from yuma_simulation_tpu.scenarios import create_case, get_cases
from yuma_simulation_tpu.simulation.engine import simulate, simulate_streamed
from yuma_simulation_tpu.simulation.sweep import (
    config_grid,
    simulate_batch,
    stack_scenarios,
    sweep_hyperparams,
)

VERSION = "Yuma 1 (paper)"
POLICY = RetryPolicy(max_attempts_per_rung=1, backoff_base=0.0)


# ---------------------------------------------------------------- taxonomy


def test_classify_failure_maps_messages_to_types():
    assert isinstance(
        classify_failure(RuntimeError("RESOURCE_EXHAUSTED: out of memory")),
        EngineResourceExhausted,
    )
    assert isinstance(
        classify_failure(RuntimeError("ran out of memory while allocating")),
        EngineResourceExhausted,
    )
    assert isinstance(
        classify_failure(RuntimeError("INTERNAL: Mosaic failed to compile")),
        EngineCompileError,
    )
    # already-typed failures pass through unchanged
    err = EngineResourceExhausted("x")
    assert classify_failure(err) is err
    # caller errors are NOT engine failures: never demoted on
    assert classify_failure(ValueError("RESOURCE_EXHAUSTED-ish")) is None
    assert classify_failure(RuntimeError("some unrelated crash")) is None


@pytest.mark.parametrize(
    "message",
    [
        # the status name XLA stamps on an expired operation deadline
        "DEADLINE_EXCEEDED: operation timed out after 600s",
        "deadline exceeded while compiling module jit__simulate_scan",
        # collective / channel timeout phrasings from the TPU runtime:
        # a wedged all-reduce surfaces on the HEALTHY peers as these
        "collective operation timed out: all-reduce id=7",
        "Collective timed out waiting for peers",
        "channel timed out after 120s",
        "INTERNAL: channel is in an error state",
        "timed out waiting for launch group",
        "barrier timed out: 3 of 4 tasks arrived",
        "heartbeat timeout: coordinator unreachable",
    ],
)
def test_classify_failure_stall_patterns(message):
    """ISSUE 3 satellite: every DEADLINE_EXCEEDED / collective-timeout
    phrasing classifies as a retryable EngineStall — each pattern pinned
    individually so a marker regression names the exact phrasing lost."""
    from yuma_simulation_tpu.resilience import EngineStall

    typed = classify_failure(RuntimeError(message))
    assert isinstance(typed, EngineStall), message
    assert isinstance(typed, EngineFailure)  # retryable by the ladder


def test_classify_failure_stall_beats_compile_marker():
    """A hung compile ('deadline exceeded while compiling') must
    classify as a (transient, retryable-in-place) stall, not as a
    deterministic compile abort."""
    from yuma_simulation_tpu.resilience import EngineStall

    typed = classify_failure(
        RuntimeError("deadline exceeded during XLA compilation of module")
    )
    assert isinstance(typed, EngineStall)


def test_classify_failure_stall_caller_errors_still_win():
    # the taxonomy's caller-error contract is unchanged by the stall tier
    assert classify_failure(ValueError("DEADLINE_EXCEEDED-ish")) is None


@pytest.mark.parametrize(
    "message",
    [
        # coordinator-channel loss: the healthy peers' view of a dead host
        "heartbeat timeout: coordinator unreachable",
        "coordination service unavailable",
        "lost connection to coordinator at 10.0.0.2:8476",
        "coordinator disconnected before barrier",
        # TCP-level phrasings a dead peer's kernel sends back
        "connection reset by peer",
        "UNAVAILABLE: connection refused",
        "peer closed connection during transfer",
        "host unreachable: worker-7",
        "worker task died during all-reduce",
    ],
)
def test_classify_failure_host_loss_patterns(message):
    """ISSUE 7 satellite: every coordinator-loss / heartbeat-timeout /
    connection-reset phrasing classifies as the typed retryable
    HostLossError — each pattern pinned individually so a marker
    regression names the exact phrasing lost. HostLossError subclasses
    EngineStall, so every pre-fleet stall-handling path (watchdog,
    ladder, supervisor accounting) treats a host loss exactly as
    before, while fleet callers can match the narrower type and steal
    the dead host's leases."""
    from yuma_simulation_tpu.resilience import EngineStall, HostLossError

    typed = classify_failure(RuntimeError(message))
    assert isinstance(typed, HostLossError), message
    assert isinstance(typed, EngineStall)  # stall semantics preserved
    assert isinstance(typed, EngineFailure)  # retryable by the ladder


def test_classify_failure_host_loss_caller_errors_still_win():
    assert classify_failure(ValueError("connection reset by peer")) is None


def test_classify_failure_host_loss_excludes_local_oserrors():
    """A local EPIPE/ECONNRESET from the caller's own plumbing shares
    the peer-death phrasings but is NOT a host loss — retrying a unit
    cannot fix the caller's environment. Runtime-reported peer death
    arrives as RuntimeError, which still classifies (above)."""
    assert classify_failure(OSError(32, "Broken pipe")) is None
    assert (
        classify_failure(ConnectionResetError(104, "Connection reset by peer"))
        is None
    )


def test_lease_expired_is_not_an_engine_failure():
    """A lost lease means the unit belongs to ANOTHER host — retrying
    the engine here is wrong, so LeaseExpired must never classify as
    retryable."""
    from yuma_simulation_tpu.resilience import LeaseExpired

    assert classify_failure(LeaseExpired("stolen", unit=3)) is None


def test_ladder_from_rungs():
    assert ladder_from("fused_varying_mxu") == ENGINE_LADDER
    assert ladder_from("fused_varying") == (
        "fused_varying", "fused_scan_mxu", "fused_scan", "xla"
    )
    assert ladder_from("fused_scan_mxu") == (
        "fused_scan_mxu", "fused_scan", "xla"
    )
    assert ladder_from("fused_scan") == ("fused_scan", "xla")
    assert ladder_from("xla") == ("xla",)
    # unknown engines retry in place, never demote across semantics
    assert ladder_from("hoisted") == ("hoisted",)


def test_retry_policy_validation():
    with pytest.raises(ValueError):
        RetryPolicy(max_attempts_per_rung=0)
    with pytest.raises(ValueError):
        RetryPolicy(jitter=1.5)
    with pytest.raises(ValueError):
        RetryPolicy(backoff_factor=0.5)


def test_run_ladder_exhaustion_carries_records():
    def always_oom(rung):
        raise EngineResourceExhausted(f"no memory on {rung}")

    with pytest.raises(EngineLadderExhausted) as exc:
        run_ladder(always_oom, "fused_scan", POLICY)
    records = exc.value.records
    assert [r.from_engine for r in records] == ["fused_scan"]
    assert records[0].to_engine == "xla"


def test_run_ladder_propagates_caller_errors():
    calls = []

    def bad_request(rung):
        calls.append(rung)
        raise ValueError("caller mistake")

    with pytest.raises(ValueError, match="caller mistake"):
        run_ladder(bad_request, "fused_scan", POLICY)
    assert calls == ["fused_scan"]  # no retry, no demotion


# ----------------------------------------------------- ladder: fused OOM


@pytest.mark.faultinject
def test_forced_fused_oom_aborts_without_policy():
    case = create_case("Case 2")
    with inject_faults(FaultPlan(fused_oom_dispatches=1)):
        with pytest.raises(EngineResourceExhausted):
            simulate(
                case, VERSION, epoch_impl="fused_scan",
                save_bonds=False, save_incentives=False,
            )


@pytest.mark.faultinject
def test_fused_oom_demotes_to_xla_bitwise():
    """Acceptance rung 1: a forced fused-engine OOM retries and completes
    on the XLA engine with results identical to a clean XLA run."""
    case = create_case("Case 2")
    ref = simulate(
        case, VERSION, epoch_impl="xla",
        save_bonds=False, save_incentives=False,
    )
    with inject_faults(FaultPlan(fused_oom_dispatches=1)):
        got = simulate(
            case, VERSION, epoch_impl="fused_scan", retry_policy=POLICY,
            save_bonds=False, save_incentives=False,
        )
    np.testing.assert_array_equal(got.dividends, ref.dividends)
    assert got.demotions is not None and len(got.demotions) == 1
    rec = got.demotions[0]
    assert rec.from_engine == "fused_scan" and rec.to_engine == "xla"
    assert rec.error_type == "EngineResourceExhausted"


@pytest.mark.faultinject
def test_fused_oom_retries_same_rung_then_succeeds():
    """A transient failure clears within the rung's retry budget: no
    demotion, and the fused engine's own (interpret-mode) result."""
    case = create_case("Case 2")
    clean = simulate(
        case, VERSION, epoch_impl="fused_scan",
        save_bonds=False, save_incentives=False,
    )
    with inject_faults(FaultPlan(fused_oom_dispatches=1)):
        got = simulate(
            case, VERSION, epoch_impl="fused_scan",
            retry_policy=RetryPolicy(max_attempts_per_rung=2, backoff_base=0.0),
            save_bonds=False, save_incentives=False,
        )
    assert got.demotions is None
    np.testing.assert_array_equal(got.dividends, clean.dividends)


@pytest.mark.faultinject
def test_batch_fused_oom_demotes_to_xla_bitwise():
    cases = get_cases()[:3]
    spec = variant_for_version(VERSION)
    cfg = YumaConfig()
    W, S, ri, re = stack_scenarios(cases)
    ref = simulate_batch(W, S, ri, re, cfg, spec, epoch_impl="xla")
    with inject_faults(FaultPlan(fused_oom_dispatches=1)):
        got = simulate_batch(
            W, S, ri, re, cfg, spec,
            epoch_impl="fused_scan", retry_policy=POLICY,
        )
    np.testing.assert_array_equal(
        np.asarray(got["dividends"]), np.asarray(ref["dividends"])
    )


# ------------------------------------------------------ ladder: streamed


def _chunks(case, split):
    W = np.asarray(case.weights)
    S = np.asarray(case.stakes)
    out, lo = [], 0
    for n in split:
        out.append((W[lo:lo + n], S[lo:lo + n]))
        lo += n
    return out


@pytest.mark.faultinject
def test_streamed_fused_oom_demotes_and_restarts_bitwise():
    """The whole stream restarts on the demoted rung (engines are never
    mixed mid-stream), and matches the clean XLA streamed run bitwise."""
    case = create_case("Case 2")
    chunks = _chunks(case, [20, 20])
    ref = simulate_streamed(list(chunks), VERSION, epoch_impl="xla")
    with inject_faults(FaultPlan(fused_oom_dispatches=1)):
        got = simulate_streamed(
            list(chunks), VERSION, epoch_impl="fused_scan",
            retry_policy=POLICY,
        )
    np.testing.assert_array_equal(got.dividends, ref.dividends)
    assert got.demotions[0].from_engine == "fused_scan"


@pytest.mark.faultinject
def test_streamed_generator_first_chunk_failure_replays():
    """A one-shot generator CAN be replayed when the failure hits the
    first dispatch: the chunk in hand is re-fed ahead of the untouched
    remainder."""
    case = create_case("Case 2")
    chunks = _chunks(case, [20, 20])
    ref = simulate_streamed(list(chunks), VERSION, epoch_impl="xla")
    with inject_faults(FaultPlan(fused_oom_dispatches=1)):
        got = simulate_streamed(
            (c for c in chunks), VERSION, epoch_impl="fused_scan",
            retry_policy=POLICY,
        )
    np.testing.assert_array_equal(got.dividends, ref.dividends)


@pytest.mark.faultinject
def test_streamed_generator_midstream_failure_is_explained():
    """Past the first chunk a one-shot generator cannot be replayed; the
    error says to pass a re-iterable sequence instead of demoting onto a
    half-consumed stream."""
    case = create_case("Case 2")
    chunks = _chunks(case, [10, 10, 10, 10])
    with inject_faults(FaultPlan(fused_oom_dispatches=1, fused_oom_skip=2)):
        with pytest.raises(ValueError, match="re-iterable"):
            simulate_streamed(
                (c for c in chunks), VERSION, epoch_impl="fused_scan",
                retry_policy=POLICY,
            )


@pytest.mark.faultinject
def test_max_resident_epochs_midstream_failure_restarts():
    """simulate(max_resident_epochs=...) owns the full arrays, so its
    chunk stream is re-iterable and a failure past chunk 0 still demotes
    and restarts instead of aborting."""
    case = create_case("Case 2")
    ref = simulate(
        case, VERSION, epoch_impl="xla",
        save_bonds=False, save_incentives=False,
    )
    with inject_faults(FaultPlan(fused_oom_dispatches=1, fused_oom_skip=1)):
        got = simulate(
            case, VERSION, epoch_impl="fused_scan",
            max_resident_epochs=10, retry_policy=POLICY,
            save_bonds=False, save_incentives=False,
        )
    np.testing.assert_array_equal(got.dividends, ref.dividends)
    assert got.demotions[0].to_engine == "xla"


@pytest.mark.faultinject
def test_streamed_midstream_failure_restarts_reiterable():
    """The same mid-stream failure IS recoverable from a re-iterable
    sequence: full restart on the demoted rung, bitwise clean result."""
    case = create_case("Case 2")
    chunks = _chunks(case, [10, 10, 10, 10])
    ref = simulate_streamed(list(chunks), VERSION, epoch_impl="xla")
    with inject_faults(FaultPlan(fused_oom_dispatches=1, fused_oom_skip=2)):
        got = simulate_streamed(
            list(chunks), VERSION, epoch_impl="fused_scan",
            retry_policy=POLICY,
        )
    np.testing.assert_array_equal(got.dividends, ref.dividends)


def test_streamed_rejects_non_bool_save_flags():
    case = create_case("Case 2")
    chunks = _chunks(case, [20, 20])
    for kw in ("save_bonds", "save_incentives", "save_consensus"):
        with pytest.raises(ValueError, match="True or False"):
            simulate_streamed(list(chunks), VERSION, **{kw: "auto"})


# -------------------------------------------------------------- quarantine


@pytest.mark.faultinject
def test_nan_at_epoch_k_quarantines_only_that_case():
    """Acceptance rung 2: an injected NaN at epoch k quarantines only
    that case (masked from epoch k on, with (case, epoch, tensor)
    provenance) while the rest of the batch matches a clean run
    bitwise."""
    cases = get_cases()[:3]
    spec = variant_for_version(VERSION)
    cfg = YumaConfig()
    W, S, ri, re = stack_scenarios(cases)
    clean = simulate_batch(W, S, ri, re, cfg, spec)
    k = 2
    with inject_faults(FaultPlan(nan=NaNFault(epoch=k, case=1))):
        got = simulate_batch(W, S, ri, re, cfg, spec, quarantine=True)
    report = build_quarantine_report(got["quarantine"])
    assert report.quarantined_cases == (1,)
    assert report.entries[0].epoch == k
    assert report.entries[0].tensor == "dividends"
    assert list(report.healthy_mask()) == [True, False, True]
    d = np.asarray(got["dividends"])
    dc = np.asarray(clean["dividends"])
    # healthy lanes: bitwise the clean (unguarded!) run
    np.testing.assert_array_equal(d[0], dc[0])
    np.testing.assert_array_equal(d[2], dc[2])
    # quarantined lane: valid partial results before k, zero-masked after
    np.testing.assert_array_equal(d[1, :k], dc[1, :k])
    assert (d[1, k:] == 0).all()
    assert np.isfinite(d).all()


@pytest.mark.faultinject
def test_nan_without_quarantine_contaminates():
    """The contrast the quarantine exists for: unguarded, the injected
    NaN reaches the output stream."""
    cases = get_cases()[:3]
    spec = variant_for_version(VERSION)
    W, S, ri, re = stack_scenarios(cases)
    with inject_faults(FaultPlan(nan=NaNFault(epoch=2, case=1))):
        got = simulate_batch(W, S, ri, re, YumaConfig(), spec)
    assert not np.isfinite(np.asarray(got["dividends"])[1]).all()


def test_quarantine_guard_is_value_neutral_for_healthy_batches():
    cases = get_cases()[:3]
    spec = variant_for_version(VERSION)
    W, S, ri, re = stack_scenarios(cases)
    plain = simulate_batch(W, S, ri, re, YumaConfig(), spec, save_bonds=True)
    guarded = simulate_batch(
        W, S, ri, re, YumaConfig(), spec, save_bonds=True, quarantine=True
    )
    np.testing.assert_array_equal(
        np.asarray(plain["dividends"]), np.asarray(guarded["dividends"])
    )
    np.testing.assert_array_equal(
        np.asarray(plain["bonds"]), np.asarray(guarded["bonds"])
    )
    report = build_quarantine_report(guarded["quarantine"])
    assert not report and report.quarantined_cases == ()


def test_quarantine_rejects_fused_engine():
    cases = get_cases()[:2]
    spec = variant_for_version(VERSION)
    W, S, ri, re = stack_scenarios(cases)
    with pytest.raises(ValueError, match="quarantine"):
        simulate_batch(
            W, S, ri, re, YumaConfig(), spec,
            epoch_impl="fused_scan", quarantine=True,
        )


def test_config_grid_nan_lane_quarantined():
    """A genuinely propagating NaN (a non-finite hyperparameter in a
    config_grid lane — the kernel is NaN-sanitizing on its array inputs,
    so hyperparameters are where real sweeps blow up): quarantined with
    provenance, other grid points bitwise the clean sweep."""
    case = create_case("Case 2")
    configs, _ = config_grid(bond_alpha=[0.1, float("nan"), 0.3])
    ys = sweep_hyperparams(case, VERSION, configs, quarantine=True)
    report = build_quarantine_report(ys["quarantine"])
    assert report.quarantined_cases == (1,)
    # the EMA recurrence first applies the rate at (global) epoch 1
    assert report.entries[0].epoch == 1
    clean_cfgs, _ = config_grid(bond_alpha=[0.1, 0.2, 0.3])
    clean = sweep_hyperparams(case, VERSION, clean_cfgs)
    np.testing.assert_array_equal(
        np.asarray(ys["dividends"])[0], np.asarray(clean["dividends"])[0]
    )
    np.testing.assert_array_equal(
        np.asarray(ys["dividends"])[2], np.asarray(clean["dividends"])[2]
    )
    assert np.isfinite(np.asarray(ys["dividends"])).all()


@pytest.mark.faultinject
def test_simulate_single_scenario_nan_fault_unguarded():
    """simulate() threads the poison operand too (case=None targets the
    sole scenario): the NaN lands exactly at the chosen epoch's
    dividends row and nowhere else (the injection is output-level, so
    the carry stays clean)."""
    case = create_case("Case 2")
    with inject_faults(FaultPlan(nan=NaNFault(epoch=3))):
        got = simulate(
            case, VERSION, epoch_impl="xla",
            save_bonds=False, save_incentives=False,
        )
    finite_rows = np.isfinite(got.dividends).all(axis=1)
    assert not finite_rows[3]
    assert finite_rows[np.arange(len(finite_rows)) != 3].all()


# ------------------------------------------------------------- satellites


def test_miner_sharding_rejects_degraded_miner_counts():
    """ADVICE r5 medium: a multi-miner-shard mesh over an M where
    miner_sum degrades to a plain reduce must be rejected, not silently
    stripped of the bitwise sharded==unsharded contract."""
    from yuma_simulation_tpu.parallel.mesh import make_mesh
    from yuma_simulation_tpu.scenarios.synthetic import (
        random_subnet_scenario,
    )

    mesh = make_mesh(data=4, model=2)
    for bad_m in (20, 8):  # 20 % 8 != 0; 8 < 2*SUM_BLOCKS
        scen = random_subnet_scenario(
            7, num_validators=4, num_miners=bad_m, num_epochs=4
        )
        with pytest.raises(ValueError, match="miner"):
            simulate(scen, VERSION, mesh=mesh)
    # a single miner shard imposes no M constraint
    flat = make_mesh(data=8, model=1)
    scen = random_subnet_scenario(
        7, num_validators=4, num_miners=20, num_epochs=4
    )
    res = simulate(scen, VERSION, mesh=flat)
    assert np.isfinite(res.dividends).all()


def test_fused_eligibility_gated_on_int32_dyadic_bound(monkeypatch):
    """ADVICE r5 low: beyond the int32 dyadic-quantization bound
    (M * 2^grid_bits >= 2^31, i.e. M >= 16384 at the default precision)
    the fused and XLA quantize fallbacks may differ by one ulp, so auto
    must never pair them: eligibility is off there even where VMEM
    admission would still pass."""
    from yuma_simulation_tpu.models.epoch import BondsMode
    from yuma_simulation_tpu.ops import pallas_epoch

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    cfg = YumaConfig()
    ok = (4, 4, 8192)
    too_wide = (4, 4, 16384)
    assert pallas_epoch.fused_case_scan_eligible(
        ok, BondsMode.EMA, cfg, jnp.float32, False
    )
    assert not pallas_epoch.fused_case_scan_eligible(
        too_wide, BondsMode.EMA, cfg, jnp.float32, False
    )
    assert pallas_epoch.fused_scan_eligible(
        ok[1:], BondsMode.EMA, cfg, jnp.float32
    )
    assert not pallas_epoch.fused_scan_eligible(
        too_wide[1:], BondsMode.EMA, cfg, jnp.float32
    )


def test_log_event_format(caplog):
    import logging

    from yuma_simulation_tpu.utils.logging import log_event

    logger = logging.getLogger("yuma_simulation_tpu.test_log_event")
    with caplog.at_level(logging.WARNING, logger=logger.name):
        log_event(logger, "engine_demoted", from_engine="a", to_engine="b")
    assert "event=engine_demoted from_engine=a to_engine=b" in caplog.text


def test_inject_faults_rejects_nesting():
    with inject_faults(FaultPlan()):
        with pytest.raises(RuntimeError, match="armed"):
            with inject_faults(FaultPlan()):
                pass


def test_simulate_batch_rejects_unknown_epoch_impl():
    cases = get_cases()[:2]
    spec = variant_for_version(VERSION)
    W, S, ri, re = stack_scenarios(cases)
    with pytest.raises(ValueError, match="unknown epoch_impl"):
        simulate_batch(W, S, ri, re, YumaConfig(), spec, epoch_impl="fast")


@pytest.mark.parametrize(
    "message",
    [
        # every stall-marker phrasing...
        "DEADLINE_EXCEEDED: operation timed out after 600s",
        "collective operation timed out: all-reduce id=7",
        "barrier timed out: 3 of 4 tasks arrived",
        # ...every host-loss phrasing...
        "heartbeat timeout: coordinator unreachable",
        "connection reset by peer",
        "worker task died",
        # ...and every resource/compile phrasing
        "RESOURCE_EXHAUSTED: out of memory while allocating",
        "INTERNAL: Mosaic failed to compile",
    ],
)
def test_classify_failure_serve_errors_immune_to_markers(message):
    """ISSUE 8 satellite: the serving tier's typed errors are decisions,
    not messages. An AdmissionRejected or QueueOverflow whose text
    happens to contain a stall/host-loss/resource/compile marker must
    NEVER re-classify into a retryable engine failure — the ladder
    retrying a rejected or shed request would re-run exactly the work
    admission/backpressure refused. Pinned per pattern, like the PR 3
    stall and PR 7 host-loss batteries."""
    from yuma_simulation_tpu.resilience import AdmissionRejected, QueueOverflow

    assert classify_failure(AdmissionRejected(message)) is None, message
    assert classify_failure(QueueOverflow(message)) is None, message


def test_serve_error_payloads_survive_typing():
    """The typed fields the HTTP layer serializes (reason/suggestion,
    retry_after/queue_depth) ride the exception objects."""
    from yuma_simulation_tpu.resilience import AdmissionRejected, QueueOverflow

    rej = AdmissionRejected(
        "predicted 12.0 GiB exceeds capacity",
        reason="preflight_rejected",
        suggestion="stream with max_resident_epochs<=512",
    )
    assert rej.reason == "preflight_rejected"
    assert "max_resident_epochs" in rej.suggestion
    ovf = QueueOverflow("queue at bound", retry_after=3.25, queue_depth=64)
    assert ovf.retry_after == 3.25
    assert ovf.queue_depth == 64
    assert ovf.retryable is True  # by the CLIENT, never the ladder
