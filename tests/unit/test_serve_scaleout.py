"""Horizontal serve scale-out: claim scoring, signed tenant identity,
client retry budget, and the SLO-burn autoscaler.

PR 16 acceptance surface, the PURE half: every placement decision the
router makes is a tuple comparison over advertisements
(:func:`~yuma_simulation_tpu.serve.router.claim_score`), so the
affinity contract — suffix savings beat warm buckets beat idleness,
dead workers never win, ties never flap — is unit-testable with
dictionaries. The multi-process half (SIGKILL mid-request, lease
expiry, bundle merge) lives in the ``--scaleout-drill`` chaos lane.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from yuma_simulation_tpu.fabric.lease import LeaseStore
from yuma_simulation_tpu.resilience import ClientRetriesExhausted
from yuma_simulation_tpu.serve import (
    ApiKeyring,
    Autoscaler,
    SimulationClient,
    WorkerPool,
    claim_score,
    mint_api_key,
    rank_claims,
)
from yuma_simulation_tpu.serve.router import (
    canonical_key,
    stable_host_hash,
    suffix_epochs_saved,
)


def _ad(worker_id, **over):
    ad = {
        "worker_id": worker_id,
        "alive": True,
        "retired": False,
        "inflight": 0,
        "held_prefixes": [],
        "warm_buckets": [],
        "url": f"http://127.0.0.1:0/{worker_id}",
    }
    ad.update(over)
    return ad


BASELINE = ["netuid-1", "Yuma 2 (Adrian-Fish)", ["hp", 0.5], "fp-abc"]


def _held(key=None, checkpoints=(4, 8)):
    return {"key": BASELINE if key is None else key, "checkpoints": list(checkpoints)}


# ---------------------------------------------------------------------------
# claim scoring (pure)


def test_dead_worker_never_wins():
    assert claim_score(_ad("w0", alive=False)) is None
    assert claim_score(_ad("w1", retired=True)) is None
    ranked = rank_claims(
        [_ad("w0", alive=False), _ad("w1"), _ad("w2", retired=True)]
    )
    assert [a["worker_id"] for a in ranked] == ["w1"]


def test_suffix_savings_beat_warm_bucket():
    holder = _ad("holder", held_prefixes=[_held()], inflight=5)
    warm = _ad("warm", warm_buckets=["12x3x4"], inflight=0)
    ranked = rank_claims(
        [warm, holder],
        baseline_key=BASELINE,
        perturb_epoch=10,
        bucket="12x3x4",
    )
    # Skipping 8 baseline epochs outweighs a warm trace AND a busier
    # queue: recompute costs more than a compile here by contract.
    assert ranked[0]["worker_id"] == "holder"


def test_warm_bucket_beats_idleness():
    warm = _ad("warm", warm_buckets=["12x3x4"], inflight=3)
    idle = _ad("idle", inflight=0)
    ranked = rank_claims([idle, warm], bucket="12x3x4")
    assert ranked[0]["worker_id"] == "warm"


def test_least_loaded_wins_among_equals():
    busy = _ad("busy", inflight=4)
    calm = _ad("calm", inflight=1)
    assert rank_claims([busy, calm])[0]["worker_id"] == "calm"


def test_equal_workers_tiebreak_is_stable():
    ads = [_ad("w0"), _ad("w1"), _ad("w2")]
    winner = rank_claims(ads)[0]["worker_id"]
    for _ in range(5):
        assert rank_claims(list(reversed(ads)))[0]["worker_id"] == winner
    expected = max(ads, key=lambda a: stable_host_hash(a["worker_id"]))
    assert winner == expected["worker_id"]


def test_checkpoints_beyond_perturb_epoch_do_not_count():
    ad = _ad("w0", held_prefixes=[_held(checkpoints=[4, 8, 16])])
    assert suffix_epochs_saved(ad, BASELINE, 10) == 8
    assert suffix_epochs_saved(ad, BASELINE, 3) == 0
    # No epoch bound: the deepest checkpoint counts.
    assert suffix_epochs_saved(ad, BASELINE, None) == 16


def test_wrong_baseline_key_saves_nothing():
    ad = _ad("w0", held_prefixes=[_held(key=["other", "key"])])
    assert suffix_epochs_saved(ad, BASELINE, 10) == 0
    assert suffix_epochs_saved(ad, None, 10) == 0


def test_canonical_key_survives_the_json_boundary():
    # Heartbeat ads cross JSON: tuples become lists, nested ones too.
    native = ("netuid-1", ("hp", 0.5), "fp")
    wired = json.loads(json.dumps(native))
    assert isinstance(wired, list)
    assert canonical_key(native) == canonical_key(wired)
    assert canonical_key(native) != canonical_key(("netuid-2", ("hp", 0.5), "fp"))


def test_score_tuple_shape():
    ad = _ad("w0", held_prefixes=[_held()], warm_buckets=["2x3x4"], inflight=2)
    saved, warm, neg_inflight, tiebreak = claim_score(
        ad, baseline_key=BASELINE, perturb_epoch=10, bucket="2x3x4"
    )
    assert (saved, warm, neg_inflight) == (8, 1, -2)
    assert tiebreak == stable_host_hash("w0")


# ---------------------------------------------------------------------------
# pool discovery (lease dir is the source of truth)


def test_pool_scan_verdicts(tmp_path):
    pool = WorkerPool(tmp_path, max_slots=4, ttl_seconds=60.0)
    assert pool.scan() == []
    worker = LeaseStore(
        tmp_path / "leases", "w0-abc123", ttl_seconds=60.0
    )
    assert worker.try_claim(0) is not None
    worker.annotate(0, _ad("w0-abc123"))
    [ad] = pool.scan()
    assert ad["alive"] and ad["slot"] == 0
    # An ad whose lease is held by SOMEONE ELSE is not alive: the ad is
    # stale leftovers from a previous tenant of the slot.
    worker.annotate(0, _ad("w0-imposter"))
    [ad] = pool.scan()
    assert not ad["alive"]
    worker.annotate(0, _ad("w0-abc123", retired=True))
    assert pool.live() == []


def test_pool_stale_lease_is_dead(tmp_path):
    pool = WorkerPool(tmp_path, max_slots=2, ttl_seconds=0.1)
    worker = LeaseStore(tmp_path / "leases", "w1-dead", ttl_seconds=0.1)
    worker.try_claim(1)
    worker.annotate(1, _ad("w1-dead"))
    assert pool.live()
    time.sleep(0.3)  # past TTL with no heartbeat: SIGKILL semantics
    assert pool.live() == []


def test_mark_lost_reports_first_time_only(tmp_path):
    pool = WorkerPool(tmp_path, max_slots=2, ttl_seconds=60.0)
    worker = LeaseStore(tmp_path / "leases", "w0-x", ttl_seconds=60.0)
    worker.try_claim(0)
    worker.annotate(0, _ad("w0-x"))
    assert pool.live()
    assert pool.mark_lost("w0-x") is True
    assert pool.mark_lost("w0-x") is False  # ledger worker_lost ONCE
    assert pool.live() == []  # routing stops before the lease expires


# ---------------------------------------------------------------------------
# signed tenant identity


def test_api_key_round_trip():
    ring = ApiKeyring({"acme": "s3cret", "umbrella": "hushhush"})
    assert ring.resolve(mint_api_key("acme", "s3cret")) == "acme"
    assert ring.resolve(mint_api_key("umbrella", "hushhush")) == "umbrella"


def test_api_key_rejections_are_uniform():
    ring = ApiKeyring({"acme": "s3cret"})
    assert ring.resolve(None) is None
    assert ring.resolve("") is None
    assert ring.resolve("no-dot-here") is None
    assert ring.resolve("acme.deadbeef") is None  # forged signature
    assert ring.resolve(mint_api_key("acme", "wrong")) is None
    assert ring.resolve(mint_api_key("ghost", "s3cret")) is None


def test_api_keyring_refuses_empty_or_garbled():
    with pytest.raises(ValueError):
        ApiKeyring({})
    with pytest.raises(ValueError):
        ApiKeyring({"acme": ""})
    with pytest.raises(ValueError):
        ApiKeyring({"": "secret"})


def test_api_keyring_loads_a_keyfile(tmp_path):
    path = tmp_path / "keys.json"
    path.write_text(json.dumps({"acme": "s3cret"}))
    ring = ApiKeyring.load(path)
    assert len(ring) == 1
    assert ring.resolve(mint_api_key("acme", "s3cret")) == "acme"


# ---------------------------------------------------------------------------
# client retry budget


class _ScriptedHandler(BaseHTTPRequestHandler):
    script: list  # [(status, headers, body), ...] consumed in order
    seen: list

    def do_POST(self):  # noqa: N802 — stdlib handler contract
        length = int(self.headers.get("Content-Length", 0))
        self.rfile.read(length)
        # urllib title-cases header names on the wire: normalize.
        self.seen.append({k.lower(): v for k, v in self.headers.items()})
        status, headers, body = (
            self.script.pop(0) if self.script else (200, {}, {"status": "ok"})
        )
        raw = json.dumps(body).encode()
        self.send_response(status)
        for k, v in headers.items():
            self.send_header(k, v)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def log_message(self, *args):  # quiet
        pass


def _scripted_server(script):
    handler = type(
        "Scripted", (_ScriptedHandler,), {"script": list(script), "seen": []}
    )
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, handler


def test_client_retries_transient_statuses_with_one_trace():
    server, handler = _scripted_server(
        [
            (503, {"Retry-After": "0.01"}, {"status": "unavailable"}),
            (429, {"Retry-After": "0.01"}, {"status": "shed"}),
            (200, {}, {"status": "ok"}),
        ]
    )
    try:
        client = SimulationClient(
            f"http://127.0.0.1:{server.server_address[1]}",
            retries=3,
            backoff_base=0.01,
        )
        resp = client._request("POST", "/v1/simulate", {"tenant": "t"})
        assert resp.status == 200 and resp.body["status"] == "ok"
        assert len(handler.seen) == 3
        # All attempts stitch into ONE caller trace.
        traceparents = {h.get("traceparent") for h in handler.seen}
        assert len(traceparents) == 1 and None not in traceparents
    finally:
        server.shutdown()


def test_client_returns_last_transient_body_when_budget_spent():
    server, _ = _scripted_server(
        [(429, {"Retry-After": "0.01"}, {"status": "shed"})] * 3
    )
    try:
        client = SimulationClient(
            f"http://127.0.0.1:{server.server_address[1]}",
            retries=2,
            backoff_base=0.01,
        )
        resp = client._request("POST", "/v1/simulate", {"tenant": "t"})
        # The typed 429 body is the contract: returned, never raised.
        assert resp.status == 429 and resp.body["status"] == "shed"
    finally:
        server.shutdown()


def test_client_raises_typed_exhaustion_on_dead_endpoint():
    # Bind-then-close: the port is real but nobody listens.
    probe = ThreadingHTTPServer(("127.0.0.1", 0), _ScriptedHandler)
    port = probe.server_address[1]
    probe.server_close()
    client = SimulationClient(
        f"http://127.0.0.1:{port}", retries=2, backoff_base=0.01
    )
    with pytest.raises(ClientRetriesExhausted) as err:
        client._request("POST", "/v1/simulate", {"tenant": "t"})
    assert err.value.attempts == 3
    assert err.value.last_error is not None


def test_client_rejects_negative_retries():
    with pytest.raises(ValueError):
        SimulationClient("http://127.0.0.1:1", retries=-1)


# ---------------------------------------------------------------------------
# autoscaler (fake pool, fake burn, fake clock)


class _FakeBurn:
    def __init__(self):
        self.burning = ()

    def degraded(self):
        return self.burning


class _FakeRouter:
    """pool.live() / spawn_worker / retire_worker — the whole contract
    the autoscaler needs, with deterministic worker ages."""

    def __init__(self, *ads):
        self.ads = list(ads)
        self.spawns = []
        self.retires = []
        self.pool = self

    def live(self):
        return list(self.ads)

    def spawn_worker(self, *, reason="startup"):
        ad = _ad(f"w{len(self.ads)}-auto", started_t=100.0 + len(self.ads))
        self.ads.append(ad)
        self.spawns.append(reason)
        return ad

    def retire_worker(self, worker_id, *, reason="idle"):
        self.retires.append((worker_id, reason))
        self.ads = [a for a in self.ads if a["worker_id"] != worker_id]
        return True


def _scaler(router, burn, t, **over):
    knobs = dict(
        min_workers=1,
        max_workers=3,
        idle_retire_seconds=10.0,
        cooldown_seconds=5.0,
        clock=lambda: t[0],
    )
    knobs.update(over)
    return Autoscaler(router, burn, **knobs)


def test_autoscaler_spawns_on_fast_burn_with_cooldown():
    router = _FakeRouter(_ad("w0", started_t=1.0))
    burn, t = _FakeBurn(), [0.0]
    scaler = _scaler(router, burn, t)
    burn.burning = ("serve_request_seconds",)
    assert scaler.tick() == "spawn"
    assert router.spawns == ["slo_fast_burn:serve_request_seconds"]
    # Still burning, but inside the cooldown: hold, don't stampede.
    assert scaler.tick() is None
    t[0] = 6.0
    assert scaler.tick() == "spawn"
    # At max_workers: burn or not, never exceed the ceiling.
    t[0] = 12.0
    assert scaler.tick() is None
    assert len(router.ads) == 3


def test_autoscaler_retires_idle_youngest_first():
    router = _FakeRouter(
        _ad("w-old", started_t=1.0), _ad("w-young", started_t=50.0)
    )
    burn, t = _FakeBurn(), [0.0]
    scaler = _scaler(router, burn, t, idle_retire_seconds=10.0)
    assert scaler.tick() is None  # records idle-since, retires nothing
    t[0] = 11.0
    assert scaler.tick() == "retire"
    assert router.retires == [("w-young", "idle")]
    # min_workers floor: the long-lived worker stays forever.
    t[0] = 1000.0
    assert scaler.tick() is None
    assert [a["worker_id"] for a in router.ads] == ["w-old"]


def test_autoscaler_inflight_and_burn_reset_the_idle_clock():
    router = _FakeRouter(
        _ad("w-old", started_t=1.0, inflight=1),  # never idle
        _ad("w-busy", started_t=50.0),
    )
    burn, t = _FakeBurn(), [0.0]
    scaler = _scaler(router, burn, t, max_workers=2)
    scaler.tick()
    router.ads[1]["inflight"] = 2  # work arrived: not idle anymore
    t[0] = 11.0
    assert scaler.tick() is None
    router.ads[1]["inflight"] = 0
    t[0] = 12.0
    scaler.tick()  # idle clock restarts HERE
    t[0] = 21.0
    assert scaler.tick() is None  # only 9s idle: under the threshold
    t[0] = 23.0
    assert scaler.tick() == "retire"
    assert router.retires == [("w-busy", "idle")]


def test_autoscaler_refuses_inverted_bounds():
    with pytest.raises(ValueError):
        Autoscaler(_FakeRouter(), _FakeBurn(), min_workers=3, max_workers=2)
