"""SweepSupervisor: idempotent units, the crash-safe FailureLedger, and
the SweepHealthReport — ISSUE 3 acceptance battery.

The combined chaos drill here runs the UNSHARDED composition (stall +
NaN lane + torn checkpoint chunk in one sweep); the sharded composition
adding device loss lives in tests/unit/test_elastic_mesh.py (it needs
`jax.shard_map`, which the conftest capability probe gates)."""

import json

import numpy as np
import pytest

from yuma_simulation_tpu.resilience import (
    Deadline,
    FaultPlan,
    NaNFault,
    RetryPolicy,
    StallFault,
    SweepSupervisor,
    inject_faults,
)
from yuma_simulation_tpu.resilience.supervisor import FailureLedger
from yuma_simulation_tpu.scenarios import create_case, get_cases
from yuma_simulation_tpu.simulation.sweep import config_grid
from yuma_simulation_tpu.utils.logging import parse_event_line

VERSION = "Yuma 1 (paper)"
#: Deterministic, backoff-free policy: 2 supervised attempts everywhere.
POLICY = RetryPolicy(max_attempts_per_rung=2, backoff_base=0.0, seed=0)
#: Roomy budget for healthy dispatches; the stall drills shrink it.
ROOMY = Deadline(budget_seconds=120.0, grace_seconds=120.0)


def _supervisor(**kw):
    kw.setdefault("unit_size", 2)
    kw.setdefault("deadline", ROOMY)
    kw.setdefault("retry_policy", POLICY)
    return SweepSupervisor(**kw)


# --------------------------------------------------------- FailureLedger


def test_ledger_appends_atomically_and_reloads(tmp_path):
    path = tmp_path / "ledger.jsonl"
    led = FailureLedger(path)
    led.append("unit_ok", unit=0, attempts=1)
    led.append("unit_stalled", unit=1, attempt=1)
    # every line on disk is complete JSON at all times
    lines = path.read_text().splitlines()
    assert [json.loads(ln)["event"] for ln in lines] == [
        "unit_ok", "unit_stalled",
    ]
    # a fresh handle sees the full history (resume case)
    led2 = FailureLedger(path)
    assert len(led2) == 2
    assert led2.entries("unit_ok")[0]["unit"] == 0


def test_ledger_tolerates_torn_tail(tmp_path):
    path = tmp_path / "ledger.jsonl"
    path.write_text('{"event": "unit_ok", "unit": 0}\n{"event": "unit_')
    led = FailureLedger(path)
    assert len(led) == 1  # torn line dropped, valid prefix kept
    led.append("unit_ok", unit=1)
    assert [e["unit"] for e in led.entries("unit_ok")] == [0, 1]


def test_ledger_survives_midfile_corruption(tmp_path):
    """A corrupt MIDDLE line (non-atomic external writer, bit rot) must
    not discard the valid records after it — the next append republishes
    the history, so a dropped tail would be erased permanently."""
    path = tmp_path / "ledger.jsonl"
    path.write_text(
        '{"event": "unit_ok", "unit": 0}\n'
        "@@corrupt@@\n"
        '{"event": "unit_ok", "unit": 1}\n'
    )
    led = FailureLedger(path)
    assert [e["unit"] for e in led.entries("unit_ok")] == [0, 1]
    led.append("unit_ok", unit=2)
    led2 = FailureLedger(path)
    assert [e["unit"] for e in led2.entries("unit_ok")] == [0, 1, 2]


def test_ledger_in_memory_mode():
    led = FailureLedger(None)
    led.append("unit_ok", unit=0)
    assert led.path is None and len(led) == 1


# ------------------------------------------------------- partition/args


def test_partition_covers_range_exactly():
    sup = _supervisor(unit_size=3)
    assert sup._partition(7) == [(0, 3), (3, 6), (6, 7)]
    assert sup._partition(3) == [(0, 3)]
    with pytest.raises(ValueError, match="empty"):
        sup._partition(0)


def test_supervisor_validation():
    with pytest.raises(ValueError, match="unit_size"):
        SweepSupervisor(unit_size=0)
    with pytest.raises(ValueError, match="quarantine"):
        SweepSupervisor(engine="fused_scan", quarantine=True)


# ------------------------------------------------------------ happy path


def test_clean_supervised_batch_matches_unsupervised():
    from yuma_simulation_tpu.models.config import YumaConfig
    from yuma_simulation_tpu.models.variants import variant_for_version
    from yuma_simulation_tpu.simulation.sweep import (
        simulate_batch,
        stack_scenarios,
    )

    cases = get_cases()[:4]
    W, S, ri, re = stack_scenarios(cases)
    ref = simulate_batch(
        W, S, ri, re, YumaConfig(), variant_for_version(VERSION)
    )
    out = _supervisor().run_batch(cases, VERSION)
    report = out["report"]
    assert report.clean and report.units_total == 2
    assert report.engines_used == ("xla",)
    assert not out["quarantine"]
    # supervision partitions the batch but must not perturb a value
    np.testing.assert_array_equal(
        out["dividends"], np.asarray(ref["dividends"])
    )


def test_supervised_grid_quarantines_bad_lane():
    configs, _ = config_grid(bond_alpha=[0.05, 0.1, float("nan"), 0.3, 0.4])
    out = _supervisor().run_grid(create_case("Case 2"), VERSION, configs)
    report = out["report"]
    assert report.units_total == 3 and report.lanes_quarantined == 1
    # lane index is GLOBAL (grid point 2 sits in unit 1 at local 0)
    assert out["quarantine"].quarantined_cases == (2,)
    clean_cfgs, _ = config_grid(bond_alpha=[0.05, 0.1, 0.2, 0.3, 0.4])
    clean = _supervisor().run_grid(create_case("Case 2"), VERSION, clean_cfgs)
    for lane in (0, 1, 3, 4):
        np.testing.assert_array_equal(
            out["dividends"][lane], clean["dividends"][lane]
        )
    assert np.isfinite(out["dividends"]).all()


# ------------------------------------------------------------- recovery


@pytest.mark.chaos
def test_stall_is_killed_counted_and_absorbed():
    cases = get_cases()[:4]
    clean = _supervisor().run_batch(cases, VERSION)
    sup = _supervisor(deadline=Deadline(0.15, grace_seconds=60.0))
    with inject_faults(FaultPlan(stall=StallFault(seconds=1.0, dispatches=1))):
        out = sup.run_batch(cases, VERSION)
    report = out["report"]
    assert report.stalls_killed == 1
    assert report.units_completed == report.units_total == 2
    np.testing.assert_array_equal(out["dividends"], clean["dividends"])


@pytest.mark.chaos
def test_fused_oom_demotion_is_accounted():
    cases = get_cases()[:3]
    sup = _supervisor(
        unit_size=3,
        quarantine=False,
        engine="fused_scan",
        retry_policy=RetryPolicy(max_attempts_per_rung=1, backoff_base=0.0),
    )
    with inject_faults(FaultPlan(fused_oom_dispatches=1)):
        out = sup.run_batch(cases, VERSION)
    report = out["report"]
    assert report.engine_demotions == 1
    assert report.engines_used == ("xla",)


@pytest.mark.chaos
def test_persistent_stall_raises_after_ledgered_attempts(tmp_path):
    """A unit that stalls on EVERY supervised attempt (no grace saves
    it) exhausts the unit retry budget and raises the typed failure,
    with the whole walk in the durable ledger — a wedged sweep dies
    loudly and auditable, never silently."""
    cases = get_cases()[:2]
    sup = _supervisor(
        directory=tmp_path,
        deadline=Deadline(0.1),  # no grace: retries get the same budget
        retry_policy=RetryPolicy(max_attempts_per_rung=2, backoff_base=0.0),
    )
    with inject_faults(FaultPlan(stall=StallFault(seconds=0.6, dispatches=99))):
        with pytest.raises(Exception) as exc:
            sup.run_batch(cases, VERSION)
    name = type(exc.value).__name__
    assert name in ("EngineStall", "EngineLadderExhausted"), name
    led = FailureLedger(tmp_path / "ledger.jsonl")
    assert led.entries("unit_failed"), "the final failure must be ledgered"
    assert led.entries("unit_stalled"), "each stall kill must be ledgered"


@pytest.mark.chaos
def test_resume_preserves_quarantine_provenance(tmp_path):
    """A resumed sweep's chunks still carry the prior run's zero-masked
    lanes; the resumed run's QuarantineReport must name them (from the
    ledger) — otherwise the caller treats masked zeros as genuine."""
    cases = get_cases()[:4]
    with inject_faults(FaultPlan(nan=NaNFault(epoch=2, case=1))):
        first = _supervisor(directory=tmp_path, unit_size=3).run_batch(
            cases, VERSION
        )
    assert first["quarantine"].quarantined_cases == (1,)
    second = _supervisor(directory=tmp_path, unit_size=3).run_batch(
        cases, VERSION
    )
    assert second["report"].units_resumed == 2
    assert second["quarantine"].quarantined_cases == (1,)
    entry = second["quarantine"].entries[0]
    assert entry.epoch == 2 and entry.tensor == "dividends"
    assert second["report"].lanes_quarantined == 1
    assert not second["report"].clean  # the OUTPUT carries masked lanes
    np.testing.assert_array_equal(first["dividends"], second["dividends"])


def test_durable_sweep_resumes_from_chunks(tmp_path):
    cases = get_cases()[:4]
    first = _supervisor(directory=tmp_path).run_batch(cases, VERSION)
    second = _supervisor(directory=tmp_path).run_batch(cases, VERSION)
    assert second["report"].units_resumed == 2
    assert second["report"].engines_used == ("resumed",)
    np.testing.assert_array_equal(first["dividends"], second["dividends"])
    # the ledger accumulated both runs' history
    led = FailureLedger(tmp_path / "ledger.jsonl")
    assert len(led.entries("unit_ok")) == 2  # only the first run executed


# ------------------------------------------------- the combined drill


@pytest.mark.chaos
def test_chaos_drill_stall_nan_torn_chunk(tmp_path, caplog):
    """ISSUE 3 acceptance (unsharded composition): ONE supervised sweep
    survives an injected stall, a NaN lane, and a torn checkpoint chunk;
    healthy lanes are bit-identical to the unfaulted run, and the
    FailureLedger + SweepHealthReport account for every recovery action.

    unit_size=3 over 4 scenarios puts lanes [0,3) in unit 0 and lane 3
    alone in unit 1, so NaNFault(case=1) poisons exactly one global lane
    (unit 1's single-lane batch has no index 1)."""
    import logging

    cases = get_cases()[:4]

    # The clean pass gets the roomy budget (its cold compiles must not
    # stall) and doubles as a warm-up, so the chaos pass's tight budget
    # only ever kills the injected 1.0s hold, never a compile.
    clean = _supervisor(directory=tmp_path / "clean", unit_size=3).run_batch(
        cases, VERSION
    )
    assert clean["report"].clean
    # The armed NaN fault threads a poison-epoch operand into the jit
    # signature (a DIFFERENT cache entry from the clean run); warm that
    # variant too, or its cold compile would race the tight budget and
    # add machine-speed-dependent stall kills to the deterministic one.
    with inject_faults(FaultPlan(nan=NaNFault(epoch=2, case=1))):
        _supervisor(unit_size=3).run_batch(cases, VERSION)

    def sup(directory):
        return _supervisor(
            directory=directory,
            unit_size=3,
            deadline=Deadline(0.15, grace_seconds=60.0),
        )

    plan = FaultPlan(
        stall=StallFault(seconds=1.0, dispatches=1),  # kills 1 dispatch
        nan=NaNFault(epoch=2, case=1),                # poisons lane 1
        truncate_chunks={1: 10},                      # tears chunk 1
    )
    with caplog.at_level(logging.WARNING):
        with inject_faults(plan):
            out = sup(tmp_path / "chaos").run_batch(cases, VERSION)

    report = out["report"]
    # -- the sweep ran to completion and every action is accounted for
    assert report.units_completed == report.units_total == 2
    assert report.stalls_killed == 1
    assert report.units_requeued == 1  # the torn chunk's unit
    assert report.lanes_quarantined == 1
    assert not report.clean

    # -- healthy lanes: bit-identical to the unfaulted run
    for lane in (0, 2, 3):
        np.testing.assert_array_equal(
            out["dividends"][lane], clean["dividends"][lane]
        )
    # -- the poisoned lane: valid prefix, zero-masked from the fault on
    np.testing.assert_array_equal(
        out["dividends"][1][:2], clean["dividends"][1][:2]
    )
    assert (out["dividends"][1][2:] == 0).all()
    assert np.isfinite(out["dividends"]).all()
    assert out["quarantine"].quarantined_cases == (1,)
    assert out["quarantine"].entries[0].epoch == 2

    # -- the ledger tells the same story, structurally
    led = FailureLedger(tmp_path / "chaos" / "ledger.jsonl")
    oks = led.entries("unit_ok")
    assert [e["unit"] for e in oks] == [0, 1, 1]  # unit 1 requeued
    assert sum(e["stalls"] for e in oks) == report.stalls_killed
    assert [e["unit"] for e in led.entries("unit_requeued")] == [1]
    quarantined = sorted(
        case for e in oks for case, _epoch, _tensor in e["quarantined"]
    )
    assert quarantined == [1]

    # -- and the event stream parses record-for-record (no regexing)
    events = [
        parsed
        for line in caplog.text.splitlines()
        if (parsed := parse_event_line(line)) is not None
    ]
    kinds = [e["event"] for e in events]
    assert "engine_stalled" in kinds
    assert "checkpoint_chunk_requeued" in kinds
    assert any(
        e["event"] == "fault_injected" and e["kind"] == "truncate_chunk"
        for e in events
    )


@pytest.mark.chaos
def test_chaos_drill_is_rerunnable_after_crash(tmp_path):
    """Resume-after-chaos: a second supervisor over the same directory
    loads every healed chunk and recomputes nothing."""
    cases = get_cases()[:4]
    d = tmp_path / "sweep"
    plan = FaultPlan(truncate_chunks={0: 8})
    with inject_faults(plan):
        first = _supervisor(directory=d).run_batch(cases, VERSION)
    assert first["report"].units_requeued == 1
    second = _supervisor(directory=d).run_batch(cases, VERSION)
    assert second["report"].units_resumed == 2
    np.testing.assert_array_equal(first["dividends"], second["dividends"])


# --------------------------------------------------------- error policy


def test_caller_errors_are_never_retried(tmp_path):
    sup = _supervisor(directory=tmp_path)
    with pytest.raises(ValueError):
        sup.run_batch([], VERSION)
    # an empty sweep is rejected before any unit runs
    assert not (tmp_path / "ledger.jsonl").exists()


def test_unclassified_failure_is_ledgered_and_raised(tmp_path, monkeypatch):
    cases = get_cases()[:2]
    sup = _supervisor(directory=tmp_path, unit_size=2)

    import yuma_simulation_tpu.resilience.supervisor as supervisor_mod

    def explode(*a, **k):
        raise ArithmeticError("not an engine failure")

    monkeypatch.setattr(supervisor_mod, "_batch_on_rung", explode)
    with pytest.raises(ArithmeticError):
        sup.run_batch(cases, VERSION)
    led = FailureLedger(tmp_path / "ledger.jsonl")
    failed = led.entries("unit_failed")
    assert len(failed) == 1 and failed[0]["error"] == "ArithmeticError"
    # no retry for caller errors: exactly one attempt was booked
    assert not led.entries("unit_retry")
