"""Metagraph snapshot ingestion (ISSUE 12 tentpole pillar 2): schema
round-trips, validation, deterministic synthesis at the real-subnet
flagship shape, and the V=256 x M=4096 run through EVERY Yuma variant
via plan_dispatch on CPU."""

import json

import numpy as np
import pytest

from yuma_simulation_tpu.foundry import (
    MetagraphSnapshot,
    SnapshotError,
    load_metagraph_snapshot,
    save_metagraph_snapshot,
    scenario_from_snapshot,
    synthetic_snapshot,
)

#: Small-but-real ingestion shape for the fast tests; the flagship
#: (256 x 4096) runs once in the variant-matrix test below.
SMALL = dict(num_validators=12, num_miners=64, nnz_per_row=8)


def test_synthetic_snapshot_is_deterministic():
    a = synthetic_snapshot(11, **SMALL)
    b = synthetic_snapshot(11, **SMALL)
    np.testing.assert_array_equal(a.weights, b.weights)
    np.testing.assert_array_equal(a.stakes, b.stakes)
    c = synthetic_snapshot(12, **SMALL)
    assert (a.weights != c.weights).any()


def test_synthetic_snapshot_defaults_to_flagship_shape():
    snap = synthetic_snapshot(0, num_validators=4, num_miners=16,
                              nnz_per_row=4)
    assert snap.weights.shape == (4, 16)
    import inspect

    sig = inspect.signature(synthetic_snapshot)
    assert sig.parameters["num_validators"].default == 256
    assert sig.parameters["num_miners"].default == 4096


def test_npz_sparse_round_trip_is_bitwise(tmp_path):
    snap = synthetic_snapshot(3, netuid=21, block=42, **SMALL)
    path = save_metagraph_snapshot(snap, tmp_path / "snap.npz")
    back = load_metagraph_snapshot(path)
    np.testing.assert_array_equal(back.weights, snap.weights)
    np.testing.assert_array_equal(back.stakes, snap.stakes)
    assert (back.netuid, back.block) == (21, 42)


def test_npz_dense_round_trip_is_bitwise(tmp_path):
    snap = synthetic_snapshot(4, **SMALL)
    path = save_metagraph_snapshot(
        snap, tmp_path / "snap.npz", sparse=False
    )
    back = load_metagraph_snapshot(path)
    np.testing.assert_array_equal(back.weights, snap.weights)


def test_json_round_trip_is_bitwise(tmp_path):
    snap = synthetic_snapshot(5, netuid=1, block=7, num_validators=6,
                              num_miners=12, nnz_per_row=3)
    path = save_metagraph_snapshot(snap, tmp_path / "snap.json")
    back = load_metagraph_snapshot(path)
    np.testing.assert_array_equal(back.weights, snap.weights)
    np.testing.assert_array_equal(back.stakes, snap.stakes)


# ------------------------------------------------------- schema rejection


def test_rejects_unknown_extension(tmp_path):
    p = tmp_path / "snap.csv"
    p.write_text("nope")
    with pytest.raises(SnapshotError, match="extension"):
        load_metagraph_snapshot(p)


def test_rejects_wrong_format_tag(tmp_path):
    p = tmp_path / "snap.json"
    p.write_text(json.dumps({"format": "something-else"}))
    with pytest.raises(SnapshotError, match="format"):
        load_metagraph_snapshot(p)


def test_rejects_missing_keys(tmp_path):
    p = tmp_path / "snap.json"
    p.write_text(json.dumps({"format": "yuma-metagraph-v1", "netuid": 0}))
    with pytest.raises(SnapshotError, match="missing key"):
        load_metagraph_snapshot(p)


def test_rejects_negative_weights(tmp_path):
    p = tmp_path / "snap.json"
    p.write_text(
        json.dumps(
            {
                "format": "yuma-metagraph-v1",
                "netuid": 0,
                "block": 0,
                "stakes": [1.0, 2.0],
                "weights": [[0.5, -0.5], [1.0, 0.0]],
            }
        )
    )
    with pytest.raises(SnapshotError, match="non-negative"):
        load_metagraph_snapshot(p)


def test_rejects_nan_stakes(tmp_path):
    # The constructor only checks shape consistency; content validation
    # runs on every load/save — exercise the save path.
    snap = MetagraphSnapshot(
        netuid=0,
        block=0,
        stakes=np.asarray([np.nan, 1.0], np.float32),
        weights=np.eye(2, dtype=np.float32),
    )
    with pytest.raises(SnapshotError, match="finite"):
        save_metagraph_snapshot(snap, tmp_path / "bad.npz")


def test_rejects_inconsistent_shapes():
    with pytest.raises(SnapshotError, match="inconsistent"):
        MetagraphSnapshot(
            netuid=0,
            block=0,
            stakes=np.ones(3, np.float32),
            weights=np.ones((2, 4), np.float32),
        )


def test_rejects_csr_out_of_range_indices(tmp_path):
    # A negative index would silently wrap onto the last miner column;
    # an oversized one would escape as a raw IndexError — both must be
    # the typed schema error.
    for bad_index in (-1, 99):
        np.savez(
            tmp_path / f"bad{bad_index}.npz",
            stakes=np.ones(2, np.float32),
            weights_indptr=np.asarray([0, 1, 2], np.int64),
            weights_indices=np.asarray([0, bad_index], np.int64),
            weights_values=np.asarray([1.0, 1.0], np.float32),
            num_miners=4,
        )
        with pytest.raises(SnapshotError, match="out of range"):
            load_metagraph_snapshot(tmp_path / f"bad{bad_index}.npz")


def test_rejects_csr_indptr_mismatch(tmp_path):
    np.savez(
        tmp_path / "bad.npz",
        stakes=np.ones(3, np.float32),
        weights_indptr=np.asarray([0, 1], np.int64),  # V+1 should be 4
        weights_indices=np.asarray([0], np.int64),
        weights_values=np.asarray([1.0], np.float32),
    )
    with pytest.raises(SnapshotError, match="indptr"):
        load_metagraph_snapshot(tmp_path / "bad.npz")


# --------------------------------------------------------- scenario build


def test_scenario_from_snapshot_is_normalized_and_validated():
    snap = synthetic_snapshot(6, **SMALL)
    sc = scenario_from_snapshot(snap, num_epochs=5)
    assert sc.weights.shape == (5, 12, 64)
    row_sums = sc.weights.sum(axis=2)
    nz = row_sums[row_sums != 0.0]
    np.testing.assert_allclose(nz, 1.0, rtol=1e-5)
    np.testing.assert_allclose(sc.stakes.sum(axis=1), 1.0, rtol=1e-5)


def test_flagship_snapshot_runs_every_variant_through_plan_dispatch():
    """The acceptance pin: a V=256 x M=4096 snapshot (the BENCH
    flagship bucket) runs through EVERY Yuma variant on CPU via
    `plan_dispatch`, small epoch count, finite dividends throughout."""
    import jax.numpy as jnp

    from yuma_simulation_tpu.models.config import YumaConfig
    from yuma_simulation_tpu.models.variants import (
        YUMA_VERSIONS,
        variant_for_version,
    )
    from yuma_simulation_tpu.simulation.engine import simulate
    from yuma_simulation_tpu.simulation.planner import plan_dispatch

    snap = synthetic_snapshot(7)  # defaults: V=256, M=4096
    sc = scenario_from_snapshot(snap, num_epochs=2)
    assert (sc.num_validators, sc.num_miners) == (256, 4096)
    for version in YUMA_VERSIONS:
        plan = plan_dispatch(
            "foundry_metagraph",
            sc.weights.shape,
            variant_for_version(version),
            YumaConfig(),
            jnp.float32,
        )
        assert plan.engine in ("xla", "fused_scan", "fused_scan_mxu")
        result = simulate(
            sc, version, save_bonds=False, save_incentives=False
        )
        div = np.asarray(result.dividends)
        assert div.shape == (2, 256)
        assert np.isfinite(div).all(), version
        assert (div >= 0).all(), version
