"""Adversarial-family property suite (ISSUE 12 tentpole pillar 3):
dividend-outcome assertions over seeded randomized generator
parameters — hypothesis-style quantification, deterministic by
construction (every case reproduces from its printed seed)."""

import numpy as np
import pytest

from yuma_simulation_tpu.foundry import (
    CARTEL_INCENTIVE_FLOOR_PER_EPOCH,
    LIQUID_ALPHA_VERSIONS,
    cartel_miner_incentive,
    cartel_scenario,
    copier_dividend_gap,
    liquid_config,
    stake_churn_scenario,
    takeover_scenario,
    total_dividends,
    weight_copier_scenario,
)

#: The randomized-parameter sweep: each seed derives stakes, the honest
#: schedule's shift epochs, and the shift targets inside the generator.
SEEDS = (0, 1, 2)


def test_liquid_alpha_version_set_is_the_noncapacity_set():
    """The property quantifies over exactly the versions whose bond
    recurrence reads `liquid_alpha` (everything but the Yuma 3.x
    capacity family — models/epoch.py)."""
    assert set(LIQUID_ALPHA_VERSIONS) == {
        "Yuma 0 (subtensor)",
        "Yuma 1 (paper)",
        "Yuma 1 (paper) - liquid alpha on",
        "Yuma 2 (Adrian-Fish)",
        "Yuma 4 (Rhef+relative bonds)",
        "Yuma 4 (Rhef+relative bonds) - liquid alpha on",
    }


@pytest.mark.parametrize("version", LIQUID_ALPHA_VERSIONS)
@pytest.mark.parametrize("seed", SEEDS)
def test_lag1_copier_earns_strictly_less_under_liquid_alpha(seed, version):
    """The acceptance property: a lag-1 weight copier with stake EQUAL
    to the validator it copies earns strictly less total dividends
    under liquid alpha, across every Yuma variant that supports it."""
    adversary = weight_copier_scenario(seed, lag=1)
    gap = copier_dividend_gap(adversary, version, liquid_config())
    assert gap > 0.0, (
        f"copier property violated: seed={seed} version={version!r} "
        f"gap={gap}"
    )


@pytest.mark.parametrize("seed", SEEDS)
def test_deeper_lag_does_not_rescue_the_copier(seed):
    """Lag-3 copiers lose too (the property is monotone in information
    staleness, spot-checked on the paper variant)."""
    adversary = weight_copier_scenario(seed, lag=3)
    gap = copier_dividend_gap(adversary, "Yuma 1 (paper)", liquid_config())
    assert gap > 0.0


@pytest.mark.parametrize("seed", SEEDS)
def test_subminority_cartel_gain_is_bounded_at_the_grid_floor(seed):
    """A cartel below the consensus majority cannot move incentive to
    its miner beyond the u16 quantization floor; a majority cartel
    captures the whole pool (~1.0/epoch) — five orders of magnitude
    apart, asserted on both sides."""
    sub = cartel_scenario(seed, cartel_stake_fraction=0.3)
    over = cartel_scenario(seed, cartel_stake_fraction=0.7)
    for version in ("Yuma 1 (paper)", "Yuma 3 (Rhef)",
                    "Yuma 4 (Rhef+relative bonds)"):
        bound = (
            sub.scenario.num_epochs * CARTEL_INCENTIVE_FLOOR_PER_EPOCH
        )
        gained = cartel_miner_incentive(sub, version)
        captured = cartel_miner_incentive(over, version)
        assert 0.0 <= gained <= bound, (seed, version, gained)
        assert captured > 0.5 * over.scenario.num_epochs, (
            seed, version, captured,
        )


@pytest.mark.parametrize("seed", SEEDS)
def test_takeover_raises_attacker_share_only_after_the_epoch(seed):
    from yuma_simulation_tpu.simulation.engine import simulate

    adversary = takeover_scenario(seed)
    k = adversary.roles["takeover_epoch"]
    attacker = adversary.roles["attacker"]
    result = simulate(adversary.scenario, "Yuma 1 (paper)")
    div = np.asarray(result.dividends)
    pre = float(div[:k, attacker].mean())
    post = float(div[k + 2 :, attacker].mean())
    assert post > pre, (seed, pre, post)


@pytest.mark.parametrize("seed", SEEDS)
def test_churn_shock_keeps_dividends_finite_and_conserved(seed):
    """A join/leave stake shock never breaks the output contract: all
    dividends finite and non-negative, the leaver earns nothing after
    the shock, and a validator with stake keeps the per-epoch pool
    normalized."""
    from yuma_simulation_tpu.simulation.engine import simulate

    adversary = stake_churn_scenario(seed)
    shock = adversary.roles["shock_epoch"]
    leaver = adversary.roles["leaver"]
    for version in ("Yuma 1 (paper)", "Yuma 2 (Adrian-Fish)"):
        div = np.asarray(simulate(adversary.scenario, version).dividends)
        assert np.isfinite(div).all()
        assert (div >= 0).all()
        assert div[shock + 1 :, leaver].sum() == 0.0


@pytest.mark.parametrize("seed", SEEDS)
def test_property_helpers_are_deterministic(seed):
    adversary = weight_copier_scenario(seed)
    a = total_dividends(adversary.scenario, "Yuma 1 (paper)",
                        liquid_config())
    b = total_dividends(adversary.scenario, "Yuma 1 (paper)",
                        liquid_config())
    np.testing.assert_array_equal(a, b)
