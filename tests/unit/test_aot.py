"""AOT executable cache (ISSUE 13): bitwise parity, cache keying,
corruption/staleness taxonomy, concurrent publish, and the sentinel's
cache-hit-vs-true-compile distinction.

The acceptance bars pinned here:

- an AOT-dispatched result is BITWISE equal to the JIT path for every
  engine rung the planner resolves on this backend, across the planner
  bucket grid (off-TPU the grid resolves to the XLA rung; the fused
  rungs ride the same seam and are covered by the TPU parity tooling);
- a cache-warm "second process" (fresh memo + fresh cache handle over
  the same directory) performs ZERO builds — loads only — and a
  budget-0 RecompilationSentinel region accepts it;
- a corrupted/truncated artifact is a typed miss that requeues to JIT
  (never a crash, never a wrong result), and a jaxlib-version bump is a
  typed STALE miss;
- concurrent writers racing the same artifact through publish_atomic
  leave exactly one whole, loadable winner.
"""

import json
import logging
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.scenarios import create_case
from yuma_simulation_tpu.simulation import aot
from yuma_simulation_tpu.simulation.engine import _simulate_scan, simulate
from yuma_simulation_tpu.simulation.planner import plan_dispatch
from yuma_simulation_tpu.simulation.sweep import (
    _simulate_batch_xla,
    simulate_batch,
    stack_scenarios,
)
from yuma_simulation_tpu.utils.profiling import (
    RecompilationBudgetExceeded,
    RecompilationSentinel,
)

VERSION = "Yuma 1 (paper)"


@pytest.fixture
def cache_dir(tmp_path, monkeypatch):
    """A fresh cache root with the process-global state isolated: the
    env var cleared, and the active cache + memo dropped afterward so
    the rest of the suite keeps the legacy always-JIT path."""
    monkeypatch.delenv(aot.EXECUTABLE_CACHE_ENV, raising=False)
    aot.deactivate_executable_cache()
    yield tmp_path / "cache"
    aot.deactivate_executable_cache()


def _tree_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


def _monolithic_args(E, V, M, seed=0):
    rng = np.random.default_rng(seed)
    W = jnp.asarray(rng.random((E, V, M)), jnp.float32)
    S = jnp.asarray(rng.random((E, V)) + 0.01, jnp.float32)
    ri = jnp.asarray(-1, jnp.int32)
    re = jnp.asarray(-1, jnp.int32)
    return W, S, ri, re


# ---------------------------------------------------------------------------
# bitwise parity: AOT dispatch == JIT dispatch


#: The planner bucket grid's small workloads ((V, M, E, B) — the
#: tools/shapecheck.py spelling): the reference case shape, the exact
#: one-tile shape, and a cross-tile-boundary batched shape. The large
#: bench flagships are deliberately excluded — this is a per-push
#: bitwise pin, not a compile-time benchmark.
PARITY_WORKLOADS = (
    (3, 2, 5, 1),
    (8, 128, 1, 1),
    (9, 129, 5, 3),
)


@pytest.mark.parametrize("V,M,E,B", PARITY_WORKLOADS)
def test_aot_dispatch_bitwise_equals_jit_on_planner_grid(
    V, M, E, B, cache_dir
):
    """For each planner-grid bucket: plan the dispatch, resolve the
    plan's rung through attach_executable, and pin the executable's
    output bitwise against the plain jitted engine at identical
    statics. Off-TPU the planner resolves every bucket to the XLA rung;
    the same seam carries the fused rungs on chip."""
    cfg = YumaConfig()
    spec = variant_for_version(VERSION)
    shape = (B, E, V, M) if B > 1 else (E, V, M)
    plan = plan_dispatch(
        "aot_parity", shape, spec, cfg, jnp.float32, check_memory=False
    )
    cache = aot.configure_executable_cache(cache_dir)
    planned = plan.attach_executable(VERSION, cache=cache)
    assert planned.executable is not None
    assert planned.executable.source == "built"
    from yuma_simulation_tpu.telemetry.numerics import numerics_enabled

    capture = numerics_enabled()
    if B > 1:
        rng = np.random.default_rng(1)
        W = jnp.asarray(rng.random((B, E, V, M)), jnp.float32)
        S = jnp.asarray(rng.random((B, E, V)) + 0.01, jnp.float32)
        ri = jnp.full((B,), -1, jnp.int32)
        re = jnp.full((B,), -1, jnp.int32)
        direct = _simulate_batch_xla(
            W, S, ri, re, cfg, spec,
            save_bonds=False, save_incentives=False,
            consensus_impl=plan.consensus_impl,
            capture_numerics=capture, miner_mask=None,
        )
        via_aot = planned.executable.call(W, S, ri, re, cfg, miner_mask=None)
    else:
        W, S, ri, re = _monolithic_args(E, V, M, seed=1)
        direct = _simulate_scan(
            W, S, ri, re, cfg, spec=spec,
            save_bonds=False, save_incentives=False, save_consensus=False,
            consensus_impl=plan.consensus_impl, capture_numerics=capture,
        )
        via_aot = planned.executable.call(W, S, ri, re, cfg)
    assert jax.tree.structure(direct) == jax.tree.structure(via_aot)
    assert _tree_equal(direct, via_aot)


def test_engine_results_identical_with_and_without_cache(cache_dir):
    """The end-to-end pin: simulate() and simulate_batch() produce
    bitwise-identical results with the cache off, cold, and warm."""
    case = create_case("Case 2")
    baseline = simulate(case, VERSION)
    cases = [create_case("Case 1"), create_case("Case 2")]
    W, S, ri, re = stack_scenarios(cases)
    cfg = YumaConfig()
    spec = variant_for_version(VERSION)
    batch_baseline = simulate_batch(W, S, ri, re, cfg, spec)

    cache = aot.configure_executable_cache(cache_dir)
    cold = simulate(case, VERSION)
    batch_cold = simulate_batch(W, S, ri, re, cfg, spec)
    assert cache.stats.builds >= 2 and cache.stats.hits == 0
    warm = simulate(case, VERSION)
    batch_warm = simulate_batch(W, S, ri, re, cfg, spec)
    for got in (cold, warm):
        assert np.array_equal(baseline.dividends, got.dividends)
        assert np.array_equal(baseline.bonds, got.bonds)
        assert np.array_equal(baseline.incentives, got.incentives)
    assert _tree_equal(batch_baseline, batch_cold)
    assert _tree_equal(batch_baseline, batch_warm)


# ---------------------------------------------------------------------------
# the cache-warm second process: loads, zero builds, sentinel-clean


def test_second_process_loads_with_zero_builds(cache_dir):
    case = create_case("Case 3")
    aot.configure_executable_cache(cache_dir)
    first = simulate(case, VERSION)
    # "Second process": fresh memo + fresh cache handle, same directory.
    aot.deactivate_executable_cache()
    cache2 = aot.configure_executable_cache(cache_dir)
    with RecompilationSentinel(
        _simulate_scan, budget=0, label="cache-warm second process"
    ) as sentinel:
        second = simulate(case, VERSION)
    assert cache2.stats.hits == 1
    assert cache2.stats.builds == 0 and cache2.stats.misses == 0
    assert sentinel.new_entries == 0
    assert sentinel.aot_hits == 1 and sentinel.aot_builds == 0
    assert np.array_equal(first.dividends, second.dividends)


def test_sentinel_counts_aot_build_as_true_compile(cache_dir):
    """An AOT MISS that exports a program is a real compile: a budget-0
    region must fail on it exactly as it fails on a tracked re-trace —
    otherwise the executable cache would let cold compiles slip past
    every zero-warm-compile pin."""
    aot.configure_executable_cache(cache_dir)
    case = create_case("Case 1")
    with pytest.raises(RecompilationBudgetExceeded, match="aot builds"):
        with RecompilationSentinel(
            _simulate_scan, budget=0, label="cold aot region"
        ):
            simulate(case, VERSION)


def test_cache_off_dispatch_seam_is_inert(cache_dir):
    """Without an active cache the seam returns None and the legacy
    path runs untouched — the default for the whole existing test
    surface."""
    assert aot.active_cache() is None
    W, S, ri, re = _monolithic_args(4, 3, 2)
    spec = variant_for_version(VERSION)
    kwargs = dict(spec=spec, save_bonds=False, save_incentives=False)
    out = aot.dispatch_via_cache(
        _simulate_scan,
        (W, S, ri, re, YumaConfig()),
        kwargs,
        static_names=tuple(kwargs),
        label="inert",
    )
    assert out is None


# ---------------------------------------------------------------------------
# cache keying: corruption, staleness, concurrency


def _entry_paths(cache):
    blobs = sorted(cache.artifact_dir.glob("*/*.bin"))
    metas = sorted(cache.artifact_dir.glob("*/*.json"))
    return blobs, metas


def test_corrupted_artifact_is_typed_miss_and_requeues_to_jit(
    cache_dir, caplog
):
    case = create_case("Case 2")
    aot.configure_executable_cache(cache_dir)
    expected = simulate(case, VERSION)
    blobs, _ = _entry_paths(aot.active_cache())
    assert blobs
    # Truncate every artifact: the digest check must reject the torn
    # bytes BEFORE deserialization ever sees them.
    for blob in blobs:
        blob.write_bytes(blob.read_bytes()[: max(1, blob.stat().st_size // 3)])
    aot.deactivate_executable_cache()
    cache2 = aot.configure_executable_cache(cache_dir)
    with caplog.at_level(
        logging.INFO, logger="yuma_simulation_tpu.simulation.aot"
    ):
        result = simulate(case, VERSION)
    assert np.array_equal(expected.dividends, result.dividends)
    assert cache2.stats.hits == 0
    assert cache2.stats.misses == 1 and cache2.stats.builds == 1
    assert any(
        "executable_cache_miss" in r.getMessage()
        and "corrupt" in r.getMessage()
        for r in caplog.records
    )
    # The rebuild republished a whole artifact: a third process loads.
    aot.deactivate_executable_cache()
    cache3 = aot.configure_executable_cache(cache_dir)
    simulate(case, VERSION)
    assert cache3.stats.hits == 1 and cache3.stats.builds == 0


def test_missing_metadata_is_typed_miss(cache_dir):
    case = create_case("Case 2")
    aot.configure_executable_cache(cache_dir)
    simulate(case, VERSION)
    _, metas = _entry_paths(aot.active_cache())
    for meta in metas:
        meta.unlink()
    aot.deactivate_executable_cache()
    cache2 = aot.configure_executable_cache(cache_dir)
    simulate(case, VERSION)
    assert cache2.stats.misses == 1 and cache2.stats.builds == 1


def test_jaxlib_version_bump_is_typed_stale_miss(
    cache_dir, monkeypatch, caplog
):
    case = create_case("Case 3")
    aot.configure_executable_cache(cache_dir)
    expected = simulate(case, VERSION)
    # Simulate the next deploy: same artifacts, bumped jaxlib.
    real_env = aot.environment_descriptor()
    monkeypatch.setattr(
        aot,
        "environment_descriptor",
        lambda: {**real_env, "jaxlib": real_env["jaxlib"] + ".post99"},
    )
    aot.deactivate_executable_cache()
    cache2 = aot.configure_executable_cache(cache_dir)
    assert cache2.env_key != _entry_key_of(real_env)
    with caplog.at_level(
        logging.INFO, logger="yuma_simulation_tpu.simulation.aot"
    ):
        result = simulate(case, VERSION)
    assert np.array_equal(expected.dividends, result.dividends)
    assert cache2.stats.stale == 1 and cache2.stats.hits == 0
    assert cache2.stats.builds == 1
    assert any(
        "executable_cache_stale" in r.getMessage() for r in caplog.records
    )
    # Both environments' artifacts now coexist under one fingerprint.
    blobs, _ = _entry_paths(cache2)
    fingerprints = {b.parent.name for b in blobs}
    assert len(fingerprints) == 1 and len(blobs) == 2


def _entry_key_of(env: dict) -> str:
    return aot._environment_key(env)


def test_concurrent_writers_race_safely_through_publish_atomic(cache_dir):
    """N threads exporting and publishing the SAME program concurrently:
    every publish lands whole (publish_atomic's writer-unique temp +
    atomic rename), the final artifact loads, and its digest verifies."""
    from jax import export as jax_export

    aot.register_export_serialization()
    cache = aot.ExecutableCache(cache_dir)
    cache.artifact_dir.mkdir(parents=True, exist_ok=True)
    spec = variant_for_version(VERSION)
    W, S, ri, re = _monolithic_args(4, 3, 2)
    kwargs = dict(spec=spec, save_bonds=False, save_incentives=False)
    from yuma_simulation_tpu.telemetry.cost import hlo_fingerprint

    lowered = _simulate_scan.lower(W, S, ri, re, YumaConfig(), **kwargs)
    fingerprint = hlo_fingerprint(lowered, digits=None)
    exported = jax_export.export(_simulate_scan)(
        W, S, ri, re, YumaConfig(), **kwargs
    )
    errors: list = []

    def publish():
        try:
            assert cache.store(fingerprint, exported, label="race")
        except Exception as e:  # pragma: no cover - the failure surface
            errors.append(e)

    threads = [threading.Thread(target=publish) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    loaded = cache.load(fingerprint, label="race")
    assert loaded is not None
    assert cache.stats.hits == 1
    out = jax.jit(loaded.call)(W, S, ri, re, YumaConfig())
    direct = _simulate_scan(W, S, ri, re, YumaConfig(), **kwargs)
    assert _tree_equal(direct, out)
    # No stray temp files survived the race.
    assert not list(cache.artifact_dir.glob("*/.*tmp"))


# ---------------------------------------------------------------------------
# plan surface + stats artifact


def test_attach_executable_mirrors_attach_cost_contract(cache_dir):
    cfg = YumaConfig()
    case = create_case("Case 1")
    plan = plan_dispatch(
        "seam", np.shape(case.weights), VERSION, cfg, jnp.float32
    )
    cache = aot.configure_executable_cache(cache_dir)
    attached = plan.attach_executable(VERSION, cache=cache)
    assert attached.executable is not None
    # The handle is metadata, not identity: plans still compare equal,
    # JSON stays serializable with a describable stub.
    assert attached == plan
    payload = json.dumps(attached.to_json())
    assert "fingerprint" in payload
    # A second attach resolves from the in-process memo (same handle
    # class, zero additional builds).
    builds_before = cache.stats.builds
    again = plan.attach_executable(VERSION, cache=cache)
    assert again.executable is not None
    assert cache.stats.builds == builds_before
    # Re-anchoring drops the stale handle: a demoted plan must not
    # carry the old rung's program.
    if len(plan.ladder) > 1:
        assert attached.demoted(plan.ladder[-1]).executable is None


def test_process_stats_survive_cache_swap(cache_dir):
    """Sentinel accounting: replacing the active cache mid-region must
    not reset the process totals (a FleetHost/serve construction inside
    a budget-0 pin would otherwise hide real builds behind a fresh
    zeroed AotStats)."""
    c1 = aot.configure_executable_cache(cache_dir / "a")
    c1.stats.builds = 3
    base = aot.process_stats().builds
    c2 = aot.configure_executable_cache(cache_dir / "b")
    c2.stats.builds = 2
    assert aot.process_stats().builds == base + 2


def test_bad_env_cache_path_degrades_to_no_cache(cache_dir, monkeypatch):
    """A typo'd/unwritable YUMA_TPU_EXECUTABLE_CACHE must disable the
    cache with one warning, never crash a dispatch — and must not retry
    the failing configuration on every call."""
    blocker = cache_dir.parent / "blocker"
    blocker.write_text("not a directory")
    monkeypatch.setenv(aot.EXECUTABLE_CACHE_ENV, str(blocker / "sub"))
    monkeypatch.setattr(aot, "_ENV_FAILED", None)
    assert aot.active_cache() is None
    assert aot._ENV_FAILED == str(blocker / "sub")
    assert aot.active_cache() is None  # remembered — no retry storm
    # The seam stays inert, and a real dispatch still works.
    result = simulate(create_case("Case 1"), VERSION)
    assert np.isfinite(result.dividends).all()


def test_write_stats_artifact_shape(cache_dir):
    cache = aot.configure_executable_cache(cache_dir)
    simulate(create_case("Case 1"), VERSION)
    payload = cache.write_stats()
    on_disk = json.loads((cache_dir / aot.STATS_FILENAME).read_text())
    assert on_disk == payload
    assert on_disk["builds"] >= 1 and on_disk["entries_on_disk"] >= 1
    assert on_disk["environment"]["jax"]


def test_preload_shapes_resolves_buckets(cache_dir):
    aot.configure_executable_cache(cache_dir)
    assert aot.preload_shapes([(6, 3, 2)], yuma_version=VERSION) == 1
    # A second process preloading the same bucket loads, not builds.
    aot.deactivate_executable_cache()
    cache2 = aot.configure_executable_cache(cache_dir)
    assert aot.preload_shapes([(6, 3, 2)], yuma_version=VERSION) == 1
    assert cache2.stats.hits == 1 and cache2.stats.builds == 0


def test_fleet_host_preload_before_first_claim(cache_dir, tmp_path):
    """FleetHost.preload_executables: unit-shaped programs resolve
    against the shared cache before any lease is claimed (here: the
    mechanism; the lease-ordering is by construction — preload runs in
    FleetHost construction order, run_units claims after)."""
    from yuma_simulation_tpu.fabric.scheduler import FleetConfig, FleetHost

    fleet = FleetConfig(
        directory=tmp_path / "store",
        host_id="host-a",
        executable_cache_dir=str(cache_dir),
    )
    host = FleetHost(fleet)
    assert aot.active_cache() is not None
    assert host.preload_executables([(5, 3, 2)], VERSION, batch=2) == 1
    assert aot.active_cache().stats.builds == 1
    # The published artifact is the batched unit program: a second host
    # on the same store loads it.
    aot.deactivate_executable_cache()
    host_b = FleetHost(
        FleetConfig(
            directory=tmp_path / "store",
            host_id="host-b",
            executable_cache_dir=str(cache_dir),
        )
    )
    assert host_b.preload_executables([(5, 3, 2)], VERSION, batch=2) == 1
    assert aot.active_cache().stats.hits == 1
    assert aot.active_cache().stats.builds == 0


def test_serve_warm_start_loads_from_cache(cache_dir):
    """ServeConfig.executable_cache_dir: worker 1 warms up by building
    + publishing; worker 2 (fresh memo, same directory) warms up from
    loads alone — the serve-tier cold-start acceptance."""
    from yuma_simulation_tpu.serve import ServeConfig, SimulationService

    shape = (8, 3, 2)
    svc = SimulationService(
        ServeConfig(
            warmup_shapes=(shape,),
            executable_cache_dir=str(cache_dir),
            start_dispatcher=False,
        )
    )
    svc.close()
    assert aot.active_cache().stats.builds >= 1
    aot.deactivate_executable_cache()
    svc2 = SimulationService(
        ServeConfig(
            warmup_shapes=(shape,),
            executable_cache_dir=str(cache_dir),
            start_dispatcher=False,
        )
    )
    svc2.close()
    stats = aot.active_cache().stats
    assert stats.hits >= 1 and stats.builds == 0
