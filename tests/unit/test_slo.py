"""SLO engine: ISSUE 9 acceptance battery (judgment half).

The contract under test: sketch merge is associative/commutative and
quantiles hold the declared relative-error bound under randomized
splits across "hosts"; burn-rate arithmetic pins against hand-computed
windows on a fake clock; the degradation drill — a synthetic
latency/error burst flips the named SLO to fast-burn, `/healthz`
reflects it, admission sheds (typed `SloShed`, counted on the shed
metrics) BEFORE `QueueOverflow`, `sloreport --check` exits non-zero on
the captured bundle, and recovery un-flips it."""

import json

import numpy as np
import pytest

from yuma_simulation_tpu.resilience import (
    QueueOverflow,
    SloShed,
    classify_failure,
)
from yuma_simulation_tpu.telemetry.slo import (
    DEFAULT_SLO_SPECS,
    LatencySketch,
    SLOEngine,
    SLOSpec,
    get_slo_engine,
    observe_duration,
    peek_slo_engine,
    set_slo_engine,
)

VERSION = "Yuma 1 (paper)"


class FakeClock:
    def __init__(self, t: float = 1_000.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ------------------------------------------------------------- sketches


def test_sketch_quantiles_hold_relative_error_bound():
    rng = np.random.default_rng(7)
    alpha = 0.01
    values = np.concatenate(
        [
            rng.lognormal(mean=-2.0, sigma=2.0, size=4000),
            rng.uniform(0.0001, 100.0, size=1000),
        ]
    )
    sketch = LatencySketch(alpha)
    for v in values:
        sketch.observe(float(v))
    ordered = np.sort(values)
    for q in (0.01, 0.25, 0.5, 0.9, 0.99, 0.999):
        rank = min(len(ordered) - 1, max(0, int(np.ceil(q * len(ordered))) - 1))
        true = float(ordered[rank])
        est = sketch.quantile(q)
        assert abs(est - true) / true <= 2 * alpha + 1e-12, (q, est, true)


def test_sketch_merge_is_associative_and_commutative():
    rng = np.random.default_rng(11)
    values = rng.lognormal(sigma=3.0, size=3000).tolist()
    for trial in range(5):
        # Randomized split across "hosts", merged in two random orders.
        k = int(rng.integers(2, 7))
        assignment = rng.integers(0, k, size=len(values))
        hosts = [LatencySketch() for _ in range(k)]
        for host, v in zip(assignment, values):
            hosts[host].observe(v)
        order_a = list(rng.permutation(k))
        order_b = list(rng.permutation(k))
        merged_a = LatencySketch()
        for i in order_a:
            merged_a.merge(hosts[i])
        # Associativity: fold pairwise sub-merges instead of a chain.
        half = LatencySketch()
        for i in order_b[: k // 2]:
            half.merge(hosts[i])
        rest = LatencySketch()
        for i in order_b[k // 2 :]:
            rest.merge(hosts[i])
        merged_b = LatencySketch().merge(half).merge(rest)
        ja, jb = merged_a.to_json(), merged_b.to_json()
        assert ja["counts"] == jb["counts"]
        assert ja["count"] == jb["count"] == len(values)
        assert ja["min"] == jb["min"] and ja["max"] == jb["max"]
        assert ja["sum"] == pytest.approx(jb["sum"])
        for q in (0.5, 0.99):
            assert merged_a.quantile(q) == merged_b.quantile(q)


def test_sketch_merged_quantiles_match_single_sketch_exactly():
    rng = np.random.default_rng(3)
    values = rng.lognormal(sigma=2.0, size=2000).tolist()
    single = LatencySketch()
    parts = [LatencySketch() for _ in range(4)]
    for i, v in enumerate(values):
        single.observe(v)
        parts[i % 4].observe(v)
    merged = LatencySketch()
    for p in parts:
        merged.merge(p)
    assert merged.to_json()["counts"] == single.to_json()["counts"]
    for q in (0.1, 0.5, 0.9, 0.99):
        assert merged.quantile(q) == single.quantile(q)


def test_sketch_json_round_trip_and_edge_values():
    sketch = LatencySketch()
    for v in (0.0, -1.0, 1e-9, 5.0):
        sketch.observe(v)
    rec = sketch.to_json()
    back = LatencySketch.from_json(json.loads(json.dumps(rec)))
    assert back.to_json() == rec
    assert back.count == 4
    # Non-positive values occupy the zero bucket; low quantiles read 0.
    assert back.quantile(0.25) == 0.0
    assert LatencySketch().quantile(0.5) is None
    with pytest.raises(ValueError):
        sketch.quantile(1.5)


def test_sketch_merge_rejects_mismatched_accuracy():
    with pytest.raises(ValueError):
        LatencySketch(0.01).merge(LatencySketch(0.05))
    with pytest.raises(ValueError):
        LatencySketch(relative_accuracy=0.0)


# ------------------------------------------------------------ burn rates


def _latency_spec(**overrides) -> SLOSpec:
    base = dict(
        name="lat",
        objective=0.9,
        sketch="m",
        threshold_seconds=1.0,
        fast_window_seconds=60.0,
        fast_burn_threshold=5.0,
        slow_window_seconds=600.0,
        slow_burn_threshold=2.0,
        min_events=1,
    )
    base.update(overrides)
    return SLOSpec(**base)


def test_burn_rate_arithmetic_pinned_hand_computed():
    clock = FakeClock(10_000.0)
    eng = SLOEngine([_latency_spec()], clock=clock)
    # 10 good + 10 bad in the fast window: bad fraction 0.5, error
    # budget 0.1 -> burn 5.0 exactly.
    for _ in range(10):
        eng.observe("m", 0.5)
    for _ in range(10):
        eng.observe("m", 2.0)
    status = eng.evaluate()["lat"]
    assert status["fast_burn_rate"] == pytest.approx(5.0)
    assert status["fast_window"] == {"good": 10, "bad": 10}
    assert status["state"] == "fast_burn"
    # Aging: 120s later the fast window is empty (burn 0) but the slow
    # window still holds all 20 -> burn 5 >= slow threshold 2.
    clock.advance(120.0)
    status = eng.evaluate()["lat"]
    assert status["fast_burn_rate"] == 0.0
    assert status["slow_burn_rate"] == pytest.approx(5.0)
    assert status["state"] == "slow_burn"
    # 700s total: everything aged out of both windows -> ok.
    clock.advance(580.0)
    status = eng.evaluate()["lat"]
    assert status["slow_burn_rate"] == 0.0
    assert status["state"] == "ok"
    # The alert history tells the whole walk, recovery included.
    assert [a["to"] for a in eng.alerts()][-3:] == [
        "fast_burn",
        "slow_burn",
        "ok",
    ]


def test_burn_rate_boundary_exact_threshold_fires():
    clock = FakeClock()
    eng = SLOEngine(
        [_latency_spec(fast_burn_threshold=2.0)], clock=clock
    )
    # 4/5 good: bad fraction 0.2 / budget 0.1 = burn 2.0 == threshold.
    for v in (0.5, 0.5, 0.5, 0.5, 9.0):
        eng.observe("m", v)
    assert eng.evaluate()["lat"]["state"] == "fast_burn"


def test_min_events_suppresses_sparse_windows():
    clock = FakeClock()
    eng = SLOEngine([_latency_spec(min_events=10)], clock=clock)
    for _ in range(9):
        eng.observe("m", 9.0)  # 9 bad events, all below min_events
    status = eng.evaluate()["lat"]
    assert status["state"] == "ok"
    assert status["fast_burn_rate"] == 0.0
    eng.observe("m", 9.0)  # the 10th arms the window
    assert eng.evaluate()["lat"]["state"] == "fast_burn"


def test_event_based_slo_and_degrade_flag():
    clock = FakeClock()
    eng = SLOEngine(
        [
            SLOSpec(
                "errors",
                objective=0.9,
                event="ok_stream",
                fast_window_seconds=60.0,
                fast_burn_threshold=2.0,
                degrade=True,
            ),
            SLOSpec(
                "sheds",
                objective=0.9,
                event="admitted",
                fast_window_seconds=60.0,
                fast_burn_threshold=2.0,
                degrade=False,
            ),
        ],
        clock=clock,
    )
    for _ in range(5):
        eng.event("ok_stream", False)
        eng.event("admitted", False)
    assert set(eng.fast_burning()) == {"errors", "sheds"}
    # Only degrade=True SLOs drive admission shedding.
    assert eng.degraded() == ("errors",)


def test_spec_validation():
    with pytest.raises(ValueError):
        SLOSpec("x", objective=1.0, event="e")
    with pytest.raises(ValueError):
        SLOSpec("x", objective=0.9)  # neither sketch nor event
    with pytest.raises(ValueError):
        SLOSpec("x", objective=0.9, sketch="m")  # sketch w/o threshold
    with pytest.raises(ValueError):
        SLOSpec("x", objective=0.9, event="e", min_events=0)
    with pytest.raises(ValueError):
        SLOEngine([_latency_spec(), _latency_spec()])  # duplicate names


def test_transitions_feed_metrics_and_hook():
    from yuma_simulation_tpu.telemetry.metrics import MetricsRegistry

    reg = MetricsRegistry()
    seen = []
    clock = FakeClock()
    eng = SLOEngine(
        [_latency_spec()],
        clock=clock,
        registry=reg,
        on_transition=seen.append,
    )
    for _ in range(10):
        eng.observe("m", 5.0)
    assert reg.snapshot()["gauges"]["slo_fast_burn_active"] == 1
    assert reg.snapshot()["counters"]["slo_alerts_total"] >= 1
    clock.advance(2_000.0)
    eng.evaluate()
    assert reg.snapshot()["gauges"]["slo_fast_burn_active"] == 0
    assert [r["to"] for r in seen][-1] == "ok"
    assert all({"slo", "from", "to", "burn_rate"} <= set(r) for r in seen)


def test_process_engine_fed_by_supervisor_and_defaults_are_calm():
    from yuma_simulation_tpu.resilience import SweepSupervisor
    from yuma_simulation_tpu.scenarios import get_cases

    previous = set_slo_engine(None)
    try:
        assert peek_slo_engine() is None
        SweepSupervisor(directory=None, unit_size=2).run_batch(
            get_cases()[:4], VERSION
        )
        eng = peek_slo_engine()
        assert eng is not None, "supervisor must create the process engine"
        assert eng.sketch("unit_seconds").count >= 2
        # CPU-scale units never trip the deliberately generous defaults.
        assert eng.fast_burning() == ()
        assert {s.name for s in DEFAULT_SLO_SPECS} == {
            "serve_latency",
            "serve_errors",
            "serve_shed",
            "unit_duration",
            "cold_start",
            "engine_drift",  # 0.14.0: the numerics-canary objective
            "replay_freshness",  # 0.22.0: the replay-controller SLO
        }
        observe_duration("unit_seconds", 0.01)  # the no-plumbing helper
        assert get_slo_engine() is eng
    finally:
        set_slo_engine(previous)


# -------------------------------------------------------- classification


def test_slo_shed_is_typed_and_immune_to_markers():
    exc = SloShed(
        "SLO fast burn (serve_latency): shedding priority<1 work "
        "deadline exceeded heartbeat timeout",  # hostile phrasing
        retry_after=5.0,
        slos=("serve_latency",),
    )
    assert isinstance(exc, QueueOverflow)
    assert exc.retryable and exc.retry_after == 5.0
    assert exc.slos == ("serve_latency",)
    # Typed non-engine failures never reclassify on message markers.
    assert classify_failure(exc) is None


# ------------------------------------------------------ the serve drill


def _drill_specs() -> tuple:
    return (
        SLOSpec(
            "serve_latency",
            objective=0.9,
            sketch="serve_request_seconds",
            threshold_seconds=0.0,  # synthetic: EVERY request is "slow"
            fast_window_seconds=60.0,
            fast_burn_threshold=5.0,
            slow_window_seconds=600.0,
            slow_burn_threshold=3.0,
            min_events=3,
        ),
    )


def test_service_close_releases_process_slo_hooks():
    """A service with operator specs installs itself as the process
    engine and claims the transition hook; close() must release BOTH,
    so a successor service in the same process gets the hook and the
    supervisor/sentinel `observe_duration` feeds fall back to whatever
    engine preceded the closed service."""
    from yuma_simulation_tpu.serve import ServeConfig, SimulationService

    previous = set_slo_engine(None)
    try:
        svc = SimulationService(
            ServeConfig(
                coalesce_window_seconds=0.0,
                slo_specs=_drill_specs(),
                start_dispatcher=False,
            )
        )
        assert peek_slo_engine() is svc.slo
        assert svc.slo.on_transition is not None
        svc.close()
        assert peek_slo_engine() is None, "process engine not restored"
        assert svc.slo.on_transition is None, "transition hook leaked"
        # A successor can now claim the hook on a shared engine.
        svc2 = SimulationService(
            ServeConfig(coalesce_window_seconds=0.0, start_dispatcher=False)
        )
        try:
            assert svc2.slo.on_transition is not None
        finally:
            svc2.close()
    finally:
        set_slo_engine(previous)


def test_slo_degradation_drill_shed_before_overflow(tmp_path):
    """The acceptance drill: burst -> fast burn -> /healthz degraded ->
    low-priority requests shed typed SloShed BEFORE QueueOverflow ->
    priority traffic still rides -> sloreport --check fails on the
    captured bundle -> recovery un-flips everything."""
    from tools.sloreport import check_slo, load_slo, main as slo_main
    from yuma_simulation_tpu.serve import ServeConfig, SimulationService
    from yuma_simulation_tpu.telemetry.metrics import get_registry

    clock = FakeClock()
    engine = SLOEngine(_drill_specs(), clock=clock)
    bundle_dir = tmp_path / "slo-bundle"
    svc = SimulationService(
        ServeConfig(
            coalesce_window_seconds=0.0,
            bundle_dir=str(bundle_dir),
            queue_limit=64,
            tenant_rate=10_000.0,
            tenant_burst=1_000,
        ),
        slo_engine=engine,
    )
    try:
        # The burst: every request scores "bad" against the synthetic
        # threshold; at min_events=3 the third observation arms the
        # window with burn = (1.0 bad fraction) / 0.1 = 10 >= 5.
        for _ in range(3):
            status, body, _h = svc.handle(
                "simulate", {"tenant": "burst", "case": "Case 1"}
            )
            assert status == 200, body
        health = svc.healthz()
        assert health["status"] == "degraded"
        assert health["ready"] is False
        assert health["slo"]["fast_burn"] == ["serve_latency"]
        assert health["slo"]["degraded"] == ["serve_latency"]

        # Low-priority work sheds typed — BEFORE any queue pressure.
        shed = get_registry().snapshot()["counters"]["serve_requests_shed"]
        status, body, headers = svc.handle(
            "simulate", {"tenant": "victim", "case": "Case 1"}
        )
        assert status == 429, body
        assert body["error"] == "SloShed"
        assert body["slo"] == ["serve_latency"]
        assert "Retry-After" in headers
        assert len(svc.queue) == 0  # shed pre-queue, not queued-then-dropped
        assert (
            get_registry().snapshot()["counters"]["serve_requests_shed"]
            == shed + 1
        )

        # Priority traffic still rides through the same pipeline.
        status, body, _h = svc.handle(
            "simulate",
            {"tenant": "vip", "case": "Case 1", "priority": 2},
        )
        assert status == 200, body
    finally:
        svc.close()

    # The captured bundle records the ACTIVE fast burn: the gate fails.
    snap = load_slo(bundle_dir)
    assert snap is not None
    problems = check_slo(snap)
    assert problems and "FAST-BURNING" in problems[0]
    assert slo_main([str(bundle_dir), "--check"]) == 2
    # Typed ledger events landed, resolvable in the bundle.
    from yuma_simulation_tpu.telemetry.flight import (
        check_bundle,
        load_bundle,
    )

    bundle = load_bundle(bundle_dir)
    assert check_bundle(bundle) == []
    events = [r.get("event") for r in bundle.ledger]
    assert "slo_alert" in events
    shed_recs = [r for r in bundle.ledger if r.get("event") == "request_shed"]
    assert any(r.get("slos") == ["serve_latency"] for r in shed_recs)

    # Recovery: the window drains on the fake clock and un-flips.
    clock.advance(3_600.0)
    assert engine.evaluate()["serve_latency"]["state"] == "ok"
    assert engine.degraded() == ()
    assert [a["to"] for a in engine.alerts()][-1] == "ok"


def test_sloreport_passes_on_healthy_bundle(tmp_path, capsys):
    from tools.sloreport import main as slo_main
    from yuma_simulation_tpu.serve import ServeConfig, SimulationService

    clock = FakeClock()
    engine = SLOEngine(
        (
            SLOSpec(
                "serve_latency",
                objective=0.9,
                sketch="serve_request_seconds",
                threshold_seconds=300.0,  # generous: everything good
                fast_window_seconds=60.0,
                min_events=1,
            ),
        ),
        clock=clock,
    )
    bundle_dir = tmp_path / "healthy-bundle"
    svc = SimulationService(
        ServeConfig(
            coalesce_window_seconds=0.0, bundle_dir=str(bundle_dir)
        ),
        slo_engine=engine,
    )
    try:
        status, _b, _h = svc.handle(
            "simulate", {"tenant": "calm", "case": "Case 1"}
        )
        assert status == 200
    finally:
        svc.close()
    assert slo_main([str(bundle_dir), "--check", "--require"]) == 0
    out = capsys.readouterr().out
    assert "serve_latency" in out and "none fast-burning" in out
    # --require fails when nothing recorded anything.
    empty = tmp_path / "empty"
    empty.mkdir()
    assert slo_main([str(empty), "--check", "--require"]) == 2
    assert slo_main([str(empty), "--check"]) == 0


def test_flight_recorder_publishes_slo_json_for_process_engine(tmp_path):
    from yuma_simulation_tpu.resilience import SweepSupervisor
    from yuma_simulation_tpu.scenarios import get_cases
    from yuma_simulation_tpu.telemetry.flight import load_bundle

    previous = set_slo_engine(None)
    try:
        out = SweepSupervisor(
            directory=str(tmp_path / "sweep"), unit_size=2
        ).run_batch(get_cases()[:4], VERSION)
        assert out["report"].units_total == 2
        bundle = load_bundle(tmp_path / "sweep")
        assert bundle.slo is not None
        states = bundle.slo["states"]
        assert states["unit_duration"]["state"] == "ok"
        assert bundle.slo["sketches"]["unit_seconds"]["count"] >= 2
    finally:
        set_slo_engine(previous)
