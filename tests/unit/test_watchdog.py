"""Deadline watchdog: hang detection for dispatches that never raise.

The retry ladder (PR 1) only sees failures that surface as exceptions;
these tests pin the supervision tier for the ones that don't — a
dispatch that simply never returns. Every drill is deterministic via the
host-side `StallFault` hook (the worker thread sleeps through the
deadline, exactly the observable behavior of a hung native compile) and
CPU-safe (ISSUE 3: stall drills are `chaos`, not `slow`)."""

import threading
import time

import numpy as np
import pytest

from yuma_simulation_tpu.resilience import (
    Deadline,
    EngineStall,
    FaultPlan,
    RetryPolicy,
    StallFault,
    classify_failure,
    inject_faults,
    run_with_deadline,
)
from yuma_simulation_tpu.scenarios import create_case
from yuma_simulation_tpu.simulation.engine import simulate

VERSION = "Yuma 1 (paper)"
POLICY = RetryPolicy(max_attempts_per_rung=1, backoff_base=0.0)


# ------------------------------------------------------------- Deadline


def test_deadline_validation():
    with pytest.raises(ValueError, match="budget_seconds"):
        Deadline(budget_seconds=0.0)
    with pytest.raises(ValueError, match="grace_seconds"):
        Deadline(budget_seconds=1.0, grace_seconds=-1.0)


def test_deadline_retry_grace():
    d = Deadline(budget_seconds=2.0, grace_seconds=3.0)
    assert d.budget_for_attempt(0) == 2.0
    assert d.budget_for_attempt(1) == 5.0
    assert d.budget_for_attempt(5) == 5.0


# ----------------------------------------------------- run_with_deadline


def test_none_deadline_runs_inline():
    """deadline=None is supervision OFF: same thread, no worker."""
    tid = []
    assert run_with_deadline(lambda: tid.append(threading.get_ident()) or 7,
                             None) == 7
    assert tid == [threading.get_ident()]


def test_result_and_exception_pass_through():
    assert run_with_deadline(lambda: 41 + 1, Deadline(5.0)) == 42

    def boom():
        raise KeyError("inner failure")

    with pytest.raises(KeyError, match="inner failure"):
        run_with_deadline(boom, Deadline(5.0))


def test_worker_exception_keeps_traceback():
    def deep():
        raise RuntimeError("from the worker")

    try:
        run_with_deadline(deep, Deadline(5.0))
    except RuntimeError as e:
        frames = []
        tb = e.__traceback__
        while tb is not None:
            frames.append(tb.tb_frame.f_code.co_name)
            tb = tb.tb_next
        assert "deep" in frames
    else:  # pragma: no cover
        pytest.fail("exception swallowed")


@pytest.mark.chaos
def test_missed_heartbeat_raises_engine_stall(caplog):
    """A worker that outsleeps the budget is abandoned; the caller gets
    a typed EngineStall and one event=engine_stalled record."""
    import logging

    release = threading.Event()
    with caplog.at_level(
        logging.WARNING, logger="yuma_simulation_tpu.resilience.watchdog"
    ):
        with pytest.raises(EngineStall) as exc:
            run_with_deadline(
                lambda: release.wait(5.0), Deadline(0.05), label="drill"
            )
    assert exc.value.budget_seconds == pytest.approx(0.05)
    assert "event=engine_stalled" in caplog.text
    assert "label=drill" in caplog.text
    release.set()  # un-wedge the abandoned worker promptly


@pytest.mark.chaos
def test_late_result_is_dropped_not_half_published():
    """A worker finishing AFTER its deadline fired must not publish —
    the stall already won; the late value lands on the floor."""
    done = threading.Event()

    def slow():
        time.sleep(0.2)
        done.set()
        return "late"

    with pytest.raises(EngineStall):
        run_with_deadline(slow, Deadline(0.05), label="late")
    assert done.wait(5.0)  # the abandoned worker did finish...
    # ...and nothing exploded: a fresh supervised dispatch still works.
    assert run_with_deadline(lambda: "fresh", Deadline(5.0)) == "fresh"


def test_engine_stall_is_retryable():
    stall = EngineStall("x", budget_seconds=1.0)
    assert classify_failure(stall) is stall


# ------------------------------------------------- stall fault drills


@pytest.mark.chaos
def test_stall_fault_holds_supervised_dispatch():
    """The StallFault hook sleeps on the WORKER, so the caller's
    deadline sees a genuine missed heartbeat."""
    with inject_faults(FaultPlan(stall=StallFault(seconds=0.6))):
        with pytest.raises(EngineStall):
            run_with_deadline(lambda: 1, Deadline(0.05), label="drill")


@pytest.mark.chaos
def test_stalled_engine_demotes_down_ladder():
    """ISSUE 3 tentpole: a stall on a fused rung feeds the existing
    demotion ladder — killed by the watchdog, classified retryable,
    demoted to XLA, and the completed run matches the clean XLA run
    bitwise (the stalled attempt never published anything)."""
    case = create_case("Case 2")
    ref = simulate(
        case, VERSION, epoch_impl="xla",
        save_bonds=False, save_incentives=False,
    )
    with inject_faults(FaultPlan(stall=StallFault(seconds=1.0))):
        got = simulate(
            case, VERSION, epoch_impl="fused_scan",
            retry_policy=POLICY,
            deadline=Deadline(0.1, grace_seconds=30.0),
            save_bonds=False, save_incentives=False,
        )
    assert got.demotions is not None and len(got.demotions) == 1
    rec = got.demotions[0]
    assert rec.from_engine == "fused_scan" and rec.to_engine == "xla"
    assert rec.error_type == "EngineStall"
    np.testing.assert_array_equal(got.dividends, ref.dividends)


@pytest.mark.chaos
def test_stall_without_retry_policy_aborts_typed():
    """deadline alone (no ladder): the stall surfaces as the typed
    EngineStall instead of a silent hang."""
    case = create_case("Case 2")
    with inject_faults(FaultPlan(stall=StallFault(seconds=0.6))):
        with pytest.raises(EngineStall):
            simulate(
                case, VERSION, epoch_impl="xla",
                deadline=Deadline(0.05),
                save_bonds=False, save_incentives=False,
            )


@pytest.mark.chaos
def test_transient_stall_retries_in_place():
    """One stalled attempt, then the retry (with grace) completes on the
    SAME rung: no demotion — a transient hang must not cost a rung."""
    case = create_case("Case 2")
    ref = simulate(
        case, VERSION, epoch_impl="xla",
        save_bonds=False, save_incentives=False,
    )
    with inject_faults(FaultPlan(stall=StallFault(seconds=1.0, dispatches=1))):
        got = simulate(
            case, VERSION, epoch_impl="xla",
            retry_policy=RetryPolicy(max_attempts_per_rung=2, backoff_base=0.0),
            deadline=Deadline(0.1, grace_seconds=30.0),
            save_bonds=False, save_incentives=False,
        )
    assert got.demotions is None
    np.testing.assert_array_equal(got.dividends, ref.dividends)
