"""Suffix-resume correctness (ISSUE 14): `simulate(initial_state=
cache[k])` over epochs [k, E) must be BITWISE the tail of the
monolithic run — dividends, incentives, AND the per-epoch
NumericsSketch fingerprints — on every engine rung (XLA scan, fused
Pallas VPU, fused Pallas MXU — the fused rungs in interpret mode off-
TPU, exactly like the streaming pins) and under chunked streaming.
Randomized checkpoint epochs k make this a property, not a spot check:
the carry hand-off must be exact at EVERY epoch boundary, because the
chain-replay state cache (replay/statecache.py) checkpoints at
arbitrary strides and the what-if API resumes at whichever checkpoint
precedes the perturbation.
"""

import dataclasses

import numpy as np
import pytest

from yuma_simulation_tpu.scenarios.base import Scenario
from yuma_simulation_tpu.simulation.engine import (
    simulate,
    validate_initial_state,
)

E, V, M = 10, 3, 4

#: Every engine rung of the planner ladder; the fused pair runs in
#: interpret mode on CPU (correct but slow — shapes here are tiny).
ALL_RUNGS = ("xla", "fused_scan", "fused_scan_mxu")

#: Carry-structure coverage: plain EMA, the EMA_PREV w_prev carry leg,
#: and a reset-mode variant (the reset fires at a GLOBAL epoch, so a
#: resumed suffix must honor the offset, not its local index).
VERSIONS = ("Yuma 1 (paper)", "Yuma 2 (Adrian-Fish)", "Yuma 3.1 (Rhef+reset)")


def _scenario(seed: int = 0, reset: bool = False) -> Scenario:
    rng = np.random.default_rng(seed)
    W = rng.random((E, V, M)).astype(np.float32)
    W /= W.sum(axis=2, keepdims=True)
    S = (rng.random((E, V)) + 0.1).astype(np.float32)
    validators = [f"v{i}" for i in range(V)]
    return Scenario(
        name=f"suffix_resume_{seed}",
        validators=validators,
        base_validator=validators[0],
        weights=W,
        stakes=S,
        num_epochs=E,
        reset_bonds_index=1 if reset else None,
        reset_bonds_epoch=6 if reset else None,
    )


def _suffix(scenario: Scenario, k: int) -> Scenario:
    return dataclasses.replace(
        scenario,
        weights=scenario.weights[k:],
        stakes=scenario.stakes[k:],
        num_epochs=E - k,
    )


def _assert_tail_bitwise(full, suffix, k: int, label: str) -> None:
    np.testing.assert_array_equal(
        suffix.dividends, full.dividends[k:], err_msg=f"{label}: dividends"
    )
    np.testing.assert_array_equal(
        suffix.incentives,
        full.incentives[k:],
        err_msg=f"{label}: incentives",
    )
    if full.numerics is not None and suffix.numerics is not None:
        for stream, sketch in full.numerics.items():
            np.testing.assert_array_equal(
                suffix.numerics[stream].fingerprint,
                sketch.fingerprint[k:],
                err_msg=f"{label}: {stream} fingerprints",
            )


@pytest.mark.parametrize("rung", ALL_RUNGS)
@pytest.mark.parametrize("version", VERSIONS)
def test_suffix_resume_bitwise_every_rung(rung, version):
    """Property: for randomized k, prefix-run state at k feeds a suffix
    run that is bitwise the monolithic tail — per rung, per carry
    structure, reset rules included."""
    scenario = _scenario(seed=7, reset="reset" in version)
    full = simulate(
        scenario, version, save_incentives=True, epoch_impl=rung
    )
    rng = np.random.default_rng(hash((rung, version)) % (2**32))
    for k in sorted(rng.choice(np.arange(1, E), size=3, replace=False)):
        k = int(k)
        prefix = simulate(
            dataclasses.replace(
                scenario,
                weights=scenario.weights[:k],
                stakes=scenario.stakes[:k],
                num_epochs=k,
            ),
            version,
            save_incentives=True,
            epoch_impl=rung,
            return_state=True,
        )
        # The prefix itself must be the monolithic head.
        np.testing.assert_array_equal(
            prefix.dividends, full.dividends[:k], err_msg=f"prefix k={k}"
        )
        suffix = simulate(
            _suffix(scenario, k),
            version,
            save_incentives=True,
            epoch_impl=rung,
            initial_state=prefix.final_state,
            epoch_offset=k,
        )
        _assert_tail_bitwise(full, suffix, k, f"{rung}/{version} k={k}")


@pytest.mark.parametrize("version", ("Yuma 2 (Adrian-Fish)",))
def test_suffix_resume_bitwise_under_streaming(version):
    """The streamed path accepts the same initial_state/epoch_offset
    and stays bitwise — resumed chunked runs are how a beyond-HBM
    what-if would dispatch."""
    scenario = _scenario(seed=11)
    full = simulate(scenario, version, save_incentives=True, epoch_impl="xla")
    for k in (3, 7):
        prefix = simulate(
            dataclasses.replace(
                scenario,
                weights=scenario.weights[:k],
                stakes=scenario.stakes[:k],
                num_epochs=k,
            ),
            version,
            save_incentives=True,
            epoch_impl="xla",
            return_state=True,
        )
        suffix = simulate(
            _suffix(scenario, k),
            version,
            save_incentives=True,
            epoch_impl="xla",
            initial_state=prefix.final_state,
            epoch_offset=k,
            max_resident_epochs=2,  # forces the chunked streaming driver
        )
        _assert_tail_bitwise(full, suffix, k, f"streamed k={k}")


@pytest.mark.parametrize("rung", ("xla", "fused_scan"))
def test_statecache_checkpoints_resume_bitwise(tmp_path, rung):
    """The satellite's exact claim: `simulate(initial_state=cache[k])`
    over [k, E) is bitwise the monolithic tail for EVERY checkpoint the
    state cache stored — through the real build/load path (serialize ->
    publish_atomic -> deserialize), randomized stride."""
    from yuma_simulation_tpu.replay.statecache import StateCache

    version = "Yuma 2 (Adrian-Fish)"
    scenario = _scenario(seed=23)
    full = simulate(
        scenario, version, save_incentives=True, epoch_impl=rung
    )
    rng = np.random.default_rng(23)
    stride = int(rng.integers(2, 5))
    cache = StateCache(tmp_path / f"cache-{rung}")
    meta = cache.build_baseline(
        scenario,
        version,
        scenario_fingerprint=f"prop-{rung}",
        stride=stride,
        engine=rung,
    )
    assert meta.checkpoints, "stride < E must checkpoint at least once"
    baseline = cache.load_baseline(meta.key)
    np.testing.assert_array_equal(baseline["dividends"], full.dividends)
    np.testing.assert_array_equal(baseline["incentives"], full.incentives)
    for k in meta.checkpoints:
        state = cache.load_state(meta.key, k)
        suffix = simulate(
            _suffix(scenario, k),
            version,
            save_incentives=True,
            epoch_impl=rung,
            initial_state=state,
            epoch_offset=k,
        )
        _assert_tail_bitwise(
            full, suffix, k, f"cache[{k}] stride={stride} {rung}"
        )


def test_return_state_roundtrips_and_validates():
    """The carry contract: final_state round-trips as initial_state,
    and shape/key mistakes are typed ValueErrors, not XLA crashes."""
    scenario = _scenario(seed=3)
    version = "Yuma 2 (Adrian-Fish)"
    res = simulate(scenario, version, return_state=True)
    state = res.final_state
    assert set(state) == {"bonds", "consensus", "w_prev"}
    assert state["bonds"].shape == (V, M)
    from yuma_simulation_tpu.models.variants import variant_for_version

    spec = variant_for_version(version)
    validate_initial_state(state, spec, V, M)
    with pytest.raises(ValueError, match="lacks 'w_prev'"):
        validate_initial_state(
            {"bonds": state["bonds"], "consensus": state["consensus"]},
            spec,
            V,
            M,
        )
    with pytest.raises(ValueError, match="shape"):
        validate_initial_state(
            {**state, "bonds": state["bonds"][:-1]}, spec, V, M
        )
    with pytest.raises(ValueError, match="unknown keys"):
        validate_initial_state({**state, "extra": state["bonds"]}, spec, V, M)
    with pytest.raises(ValueError, match="epoch_offset"):
        simulate(scenario, version, epoch_offset=-1)
    # A variant that does NOT carry w_prev rejects a carry that has it.
    spec1 = variant_for_version("Yuma 1 (paper)")
    with pytest.raises(ValueError, match="unknown keys"):
        validate_initial_state(state, spec1, V, M)
