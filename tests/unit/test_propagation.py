"""Cross-process trace propagation: ISSUE 9 acceptance battery (trace
half).

The contract under test: a `TraceContext` survives every carrier
(header, env, fleet manifest) byte-exactly; a continued run's spans
root under the caller's span with collision-free prefixed ids; the
single-bundle check exempts remote roots while the STITCHED check
fails on orphans (the tamper gate); the serving tier echoes
`X-Request-Id` on every response, joins inbound traceparents, and
returns the critical-path `Server-Timing`; and a fleet host continues
the sweep-level trace it finds in the manifest."""

import json

import pytest

from yuma_simulation_tpu.telemetry.flight import (
    FlightRecorder,
    check_bundle,
    check_stitched,
    load_bundle,
    merge_bundles,
)
from yuma_simulation_tpu.telemetry.propagation import (
    BAGGAGE_ENV,
    TRACEPARENT_ENV,
    TraceContext,
    child_run,
    continue_trace,
    current_trace_context,
    span_prefix_for,
)
from yuma_simulation_tpu.telemetry.runctx import (
    RunContext,
    current_run,
    span,
)

VERSION = "Yuma 1 (paper)"


# ------------------------------------------------------------ wire forms


def test_traceparent_round_trip_with_dashes_and_baggage():
    ctx = TraceContext(
        "run-ab12cd34ef56", "s0007", (("request", "r1"), ("tenant", "t-1"))
    )
    back = TraceContext.from_traceparent(
        ctx.to_traceparent(), ctx.to_baggage()
    )
    assert back == ctx
    # Operator-chosen run ids with extra dashes survive the framing.
    odd = TraceContext("my-nightly-sweep-2026", "ab12cd34.s0003")
    assert TraceContext.from_traceparent(odd.to_traceparent()) == odd


def test_traceparent_empty_span_round_trips_as_root():
    ctx = TraceContext("run-x")
    header = ctx.to_traceparent()
    assert header == "00-run-x-root-01"
    assert TraceContext.from_traceparent(header) == ctx


@pytest.mark.parametrize(
    "header",
    [None, "", "garbage", "00-x-01", "01-run-a-s0001-01", "00--s1-01", 7],
)
def test_malformed_traceparent_parses_to_none(header):
    assert TraceContext.from_traceparent(header) is None


def test_env_round_trip_and_scrubbed_env():
    ctx = TraceContext("run-e", "s0002", (("k", "v"),))
    env = ctx.to_env()
    assert TraceContext.from_env(env) == ctx
    assert TraceContext.from_env({TRACEPARENT_ENV: "", BAGGAGE_ENV: ""}) is None
    assert TraceContext.from_env({}) is None


def test_manifest_round_trip():
    ctx = TraceContext("run-m", "s0009", (("fleet", "drill"),))
    manifest = {"num_units": 3, "trace": ctx.to_manifest()}
    assert TraceContext.from_manifest(manifest) == ctx
    assert TraceContext.from_manifest({"num_units": 3}) is None


def test_current_trace_context_captures_run_and_span():
    assert current_trace_context() is None
    with RunContext() as run:
        ctx = current_trace_context()
        assert ctx.run_id == run.run_id and ctx.span_id == ""
        with span("outer") as s:
            ctx = current_trace_context(tenant="t9")
            assert ctx.span_id == s.span_id
            assert dict(ctx.baggage) == {"tenant": "t9"}


# ----------------------------------------------------- continued runs


def test_child_run_roots_under_remote_parent_with_prefixed_ids():
    ctx = TraceContext("run-parent", "s0004")
    child = child_run(ctx, prefix="aabbccdd")
    with child:
        with span("hosted") as outer:
            with span("nested") as inner:
                pass
    recs = {r["span_id"]: r for r in child.span_records()}
    root = recs[outer.span_id]
    assert root["run_id"] == "run-parent"
    assert root["parent_id"] == "s0004"
    assert root["remote_parent"] is True
    assert root["span_id"].startswith("aabbccdd.")
    nested = recs[inner.span_id]
    assert nested["parent_id"] == outer.span_id
    assert "remote_parent" not in nested  # local parent, no flag


def test_span_prefix_rejects_dashes():
    with pytest.raises(ValueError):
        RunContext(span_prefix="a-b")
    assert "-" not in span_prefix_for("host-with-dashes-1234")


def test_continue_trace_joins_active_run_first():
    with RunContext() as outer:
        with continue_trace(TraceContext("run-other", "s1")) as run:
            assert run is outer  # in-process callers keep their nesting
    with continue_trace(None) as run:
        assert run.run_id.startswith("run-")
    ctx = TraceContext("run-cont", "s0001")
    with continue_trace(ctx, prefix="ee00ff11") as run:
        assert run.run_id == "run-cont"
        assert current_run() is run


def test_record_span_synthesizes_closed_children():
    with RunContext() as run:
        with span("request") as s:
            pass
    phase = run.record_span(
        "queue", 100.0, 100.5, parent_id=s.span_id, depth=3
    )
    recs = {r["span_id"]: r for r in run.span_records()}
    rec = recs[phase.span_id]
    assert rec["parent_id"] == s.span_id
    assert rec["t_start"] == 100.0 and rec["t_end"] == 100.5
    assert rec["attrs"] == {"depth": 3}


# ------------------------------------------------- bundle checks / stitch


def _bundle_pair(tmp_path):
    """A driver bundle + a continued child bundle in sibling dirs."""
    driver = RunContext(run_id="run-stitch")
    with driver:
        with span("drive") as s:
            ctx = TraceContext(driver.run_id, s.span_id)
    child = child_run(ctx, prefix="11223344")
    with child:
        with span("hosted"):
            pass
    FlightRecorder(tmp_path / "driver").record(driver)
    FlightRecorder(tmp_path / "child").record(child)
    return load_bundle(tmp_path / "driver"), load_bundle(tmp_path / "child")


def test_remote_root_is_exempt_locally_but_stitches_globally(tmp_path):
    driver_b, child_b = _bundle_pair(tmp_path)
    # Single-bundle check: the remote-parent root must NOT be an error.
    assert check_bundle(child_b) == []
    # Stitched: the pair resolves; the child alone is an orphan.
    assert check_stitched([driver_b, child_b]) == []
    problems = check_stitched([child_b])
    assert problems and "orphan" in problems[0]


def test_stitched_check_fails_on_tampered_bundle(tmp_path):
    driver_b, child_b = _bundle_pair(tmp_path)
    # Tamper: drop the driver's span record the child chains to.
    spans_path = tmp_path / "driver" / "spans.jsonl"
    kept = [
        line
        for line in spans_path.read_text().splitlines()
        if json.loads(line).get("name") != "drive"
    ]
    spans_path.write_text("".join(k + "\n" for k in kept))
    tampered = load_bundle(tmp_path / "driver")
    problems = check_stitched([tampered, child_b])
    assert problems and "orphan" in problems[0]


def test_merge_bundles_unions_and_orders(tmp_path):
    driver_b, child_b = _bundle_pair(tmp_path)
    union = merge_bundles([driver_b, child_b])
    ids = {s["span_id"] for s in union.spans}
    assert any(i.startswith("11223344.") for i in ids)
    assert "s0001" in ids
    starts = [s.get("t_start") or 0.0 for s in union.spans]
    assert starts == sorted(starts)


# ------------------------------------------------------- serve carriers


def test_serve_echoes_request_id_on_every_response(tmp_path):
    from yuma_simulation_tpu.serve import (
        ServeConfig,
        SimulationClient,
        SimulationServer,
        wait_until_ready,
    )

    server = SimulationServer(
        ServeConfig(coalesce_window_seconds=0.0)
    ).start()
    try:
        assert wait_until_ready(server.url)
        client = SimulationClient(server.url, tenant="prop")
        ok = client.simulate(case="Case 1")
        assert ok.status == 200 and ok.request_id
        assert ok.traceparent is not None
        timing = ok.server_timing
        for phase in ("queue", "coalesce", "compile", "execute", "total"):
            assert phase in timing, (phase, timing)
        rejected = client.simulate(weights=[[1.0]])
        assert rejected.status == 400 and rejected.request_id
        missing = client._request("POST", "/v1/nowhere", {})
        assert missing.status == 404 and missing.request_id
        health = client.healthz()
        assert health.request_id
        # ids are distinct per call — the retry-correlation property.
        ids = {ok.request_id, rejected.request_id, missing.request_id}
        assert len(ids) == 3
    finally:
        server.close()


def test_serve_joins_inbound_traceparent(tmp_path):
    from yuma_simulation_tpu.serve import (
        ServeConfig,
        SimulationClient,
        SimulationServer,
        wait_until_ready,
    )

    bundle_dir = tmp_path / "serve-bundle"
    server = SimulationServer(
        ServeConfig(coalesce_window_seconds=0.0, bundle_dir=str(bundle_dir))
    ).start()
    try:
        assert wait_until_ready(server.url)
        client = SimulationClient(server.url, tenant="traced")
        with RunContext() as run:
            with span("caller") as s:
                r = client.simulate(case="Case 1")
        assert r.ok and r.request_id
    finally:
        server.close()

    bundle = load_bundle(bundle_dir)
    assert check_bundle(bundle) == []
    # The request span landed in the CALLER's run, parented under the
    # caller's span, flagged remote.
    req = [
        x
        for x in bundle.spans
        if x.get("name") == f"request:{r.request_id}"
    ]
    assert req, [x.get("name") for x in bundle.spans]
    req = req[0]
    assert req["run_id"] == run.run_id
    assert req["parent_id"] == s.span_id
    assert req.get("remote_parent") is True
    # Critical-path children hang off the request span.
    kids = {
        x["name"]
        for x in bundle.spans
        if x.get("parent_id") == req["span_id"]
    }
    assert {"queue", "execute"} <= kids, kids
    # And the caller's own bundle stitches with the server's.
    caller_dir = tmp_path / "caller-bundle"
    FlightRecorder(caller_dir).record(run)
    assert (
        check_stitched([load_bundle(caller_dir), bundle]) == []
    )


def test_obsreport_renders_critical_path(tmp_path):
    from tools.obsreport import render_serve
    from yuma_simulation_tpu.serve import ServeConfig, SimulationService

    bundle_dir = tmp_path / "svc-bundle"
    svc = SimulationService(
        ServeConfig(
            coalesce_window_seconds=0.0, bundle_dir=str(bundle_dir)
        )
    )
    try:
        status, body, headers = svc.handle(
            "simulate", {"tenant": "cp", "case": "Case 1"}
        )
        assert status == 200
        assert "Server-Timing" in headers and "X-Request-Id" in headers
    finally:
        svc.close()
    bundle = load_bundle(bundle_dir)
    lines = "\n".join(render_serve(bundle, bundle.latest_run_id()))
    assert "tenant cp" in lines
    assert "queue" in lines and "execute" in lines


# ------------------------------------------------------- fleet carriers


def test_fleet_host_continues_manifest_trace(tmp_path):
    from yuma_simulation_tpu.fabric.scheduler import (
        FleetConfig,
        run_fleet_batch,
    )
    from yuma_simulation_tpu.fabric.store import FleetStore
    from yuma_simulation_tpu.scenarios import get_cases

    cases = get_cases()[:4]
    store_dir = tmp_path / "store"
    with RunContext() as run:
        with span("driver") as s:
            out = run_fleet_batch(
                cases,
                VERSION,
                FleetConfig(directory=store_dir, unit_size=2, host_id="h-A"),
            )
    assert out["report"].units_published == 2
    store = FleetStore(store_dir)
    manifest = store.manifest()
    ctx = TraceContext.from_manifest(manifest)
    assert ctx is not None
    assert ctx.run_id == run.run_id and ctx.span_id == s.span_id
    # The in-process host joined the driver run directly: every span of
    # its bundle belongs to the driver's run and resolves locally.
    host_bundle = load_bundle(store.host_dir("h-A"))
    assert check_bundle(host_bundle) == []
    assert {x["run_id"] for x in host_bundle.spans} == {run.run_id}
    # Lease claims carried the trace while held; the manifest trace is
    # the durable record (leases are released on publish).
    assert manifest["trace"]["traceparent"].startswith("00-" + run.run_id)


def test_late_joiner_inherits_manifest_trace_as_child_run(tmp_path):
    """A host arriving with NO ambient trace continues the manifest's:
    its spans land in the driver's run under a fresh prefix, rooted at
    the driver's span — the orphan-run regression this PR exists to
    kill."""
    from yuma_simulation_tpu.fabric.scheduler import (
        FleetConfig,
        run_fleet_batch,
    )
    from yuma_simulation_tpu.fabric.store import FleetStore
    from yuma_simulation_tpu.scenarios import get_cases

    cases = get_cases()[:4]
    store_dir = tmp_path / "store"
    driver = RunContext()
    with driver:
        with span("driver") as s:
            run_fleet_batch(
                cases,
                VERSION,
                FleetConfig(directory=store_dir, unit_size=2, host_id="h-A"),
            )
    # Second invocation, no active run: resumes the finished sweep
    # (pure collection) and must STILL continue the manifest trace.
    run_fleet_batch(
        cases,
        VERSION,
        FleetConfig(directory=store_dir, unit_size=2, host_id="h-B"),
    )
    store = FleetStore(store_dir)
    b_bundle = load_bundle(store.host_dir("h-B"))
    assert {x["run_id"] for x in b_bundle.spans} == {driver.run_id}
    roots = [x for x in b_bundle.spans if x.get("remote_parent")]
    assert roots and all(x["parent_id"] == s.span_id for x in roots)
    prefixes = {x["span_id"].split(".")[0] for x in b_bundle.spans}
    assert all("." in x["span_id"] for x in b_bundle.spans)
    # Prefixed ids cannot collide with the driver-joined host's.
    a_ids = {x["span_id"] for x in load_bundle(store.host_dir("h-A")).spans}
    b_ids = {x["span_id"] for x in b_bundle.spans}
    assert not (a_ids & b_ids)
    # The stitched union of driver + both hosts resolves completely.
    driver_dir = tmp_path / "driver-bundle"
    FlightRecorder(driver_dir).record(driver)
    bundles = [
        load_bundle(driver_dir),
        load_bundle(store.host_dir("h-A")),
        b_bundle,
    ]
    assert check_stitched(bundles) == []
    assert len(prefixes) == 1


def test_lease_claim_records_trace(tmp_path):
    from yuma_simulation_tpu.fabric.lease import LeaseStore

    leases = LeaseStore(tmp_path, "host-lease-test")
    with RunContext() as run:
        with span("claiming"):
            claim = leases.try_claim(3)
            assert claim is not None
            rec = json.loads(leases.lease_path(3).read_text())
    assert rec["host"] == "host-lease-test"
    assert rec["trace"].startswith("00-" + run.run_id)
    parsed = TraceContext.from_traceparent(rec["trace"])
    assert parsed.run_id == run.run_id


def test_manifest_trace_excluded_from_identity_check(tmp_path):
    from yuma_simulation_tpu.fabric.store import FleetStore

    store = FleetStore(tmp_path / "s")
    meta = dict(
        num_units=2,
        unit_lanes=[(0, 1), (1, 2)],
        tag="t",
        config={"v": 1},
    )
    store.ensure_manifest(
        **meta, trace=TraceContext("run-first", "s1").to_manifest()
    )
    # A host arriving with a DIFFERENT ambient trace still joins; the
    # first writer's trace stands.
    found = store.ensure_manifest(
        **meta, trace=TraceContext("run-second", "s9").to_manifest()
    )
    assert found["trace"]["traceparent"].startswith("00-run-first")
    # Genuine sweep-identity mismatches still refuse.
    with pytest.raises(ValueError):
        store.ensure_manifest(
            num_units=2,
            unit_lanes=[(0, 1), (1, 2)],
            tag="t",
            config={"v": 2},
        )
