"""The drop-in `yuma_simulation` compatibility package.

Code written against the reference's import paths must run unchanged:
this test is written exactly as a reference user would write it
(cf. reference scripts/charts_table_generator.py:1-9 and
tests/unit/api/api_test.py:1-26).
"""

import numpy as np
import pytest
from bs4 import BeautifulSoup


def test_reference_style_imports_and_run():
    from yuma_simulation._internal.cases import cases
    from yuma_simulation._internal.simulation_utils import run_simulation
    from yuma_simulation._internal.yumas import (
        YumaConfig,
        YumaParams,
        YumaSimulationNames,
        SimulationHyperparameters,
    )

    assert len(cases) == 14
    names = YumaSimulationNames()
    config = YumaConfig(
        simulation=SimulationHyperparameters(bond_penalty=0.5),
        yuma_params=YumaParams(),
    )
    dividends, bonds, incentives = run_simulation(
        case=cases[0], yuma_version=names.YUMA2, yuma_config=config
    )
    assert set(dividends) == set(cases[0].validators)
    assert len(bonds) == cases[0].num_epochs


def test_reference_style_chart_table():
    from yuma_simulation._internal.cases import cases
    from yuma_simulation._internal.yumas import (
        SimulationHyperparameters,
        YumaParams,
    )
    from yuma_simulation.v1.api import generate_chart_table

    html = generate_chart_table(
        cases[:1],
        [("Yuma 1 (paper)", YumaParams())],
        SimulationHyperparameters(bond_penalty=0.99),
        draggable_table=True,
    )
    soup = BeautifulSoup(html.data, "html.parser")
    imgs = soup.find_all("img")
    assert len(imgs) >= 1
    assert all(i["src"].startswith("data:image/png;base64,") for i in imgs)


def test_reference_style_kernel_call():
    from yuma_simulation._internal.yumas import Yuma, YumaConfig

    W = np.array([[0.7, 0.3], [0.2, 0.8], [0.4, 0.6]], np.float32)
    S = np.array([0.8, 0.1, 0.1], np.float32)
    res = Yuma(W, S, None, YumaConfig())
    assert "validator_ema_bond" in res and "server_incentive" in res
    np.testing.assert_allclose(float(res["server_incentive"].sum()), 1.0, atol=1e-5)


def test_reference_style_plotters():
    from yuma_simulation._internal.charts_utils import (
        _calculate_total_dividends,
        _plot_dividends,
    )

    totals, pct = _calculate_total_dividends(
        ["A", "B"], {"A": [1.0, 2.0], "B": [2.0, 2.0]}, "A", 2
    )
    assert totals == {"A": 3.0, "B": 4.0}
    img = _plot_dividends(
        num_epochs=2,
        validators=["A", "B"],
        dividends_per_validator={"A": [1.0, 2.0], "B": [2.0, 2.0]},
        case="smoke",
        base_validator="A",
        to_base64=True,
    )
    assert img.startswith('<img src="data:image/png;base64,')


def test_shim_kernels_accept_torch_tensors():
    """Reference notebooks pass torch tensors; the shim must take them
    as-is (jnp.asarray consumes torch CPU tensors via the array
    protocol)."""
    torch = pytest.importorskip("torch")

    from yuma_simulation._internal.yumas import Yuma, YumaConfig

    g = torch.Generator().manual_seed(0)
    W = torch.rand(4, 8, generator=g)
    S = torch.tensor([0.4, 0.3, 0.2, 0.1])
    out = Yuma(W, S, None, YumaConfig())
    D = np.asarray(out["validator_reward_normalized"])
    assert D.shape == (4,)
    np.testing.assert_allclose(D.sum(), 1.0, atol=2e-5)
    # Same values as the numpy-input path.
    ref = Yuma(W.numpy(), S.numpy(), None, YumaConfig())
    np.testing.assert_array_equal(
        D, np.asarray(ref["validator_reward_normalized"])
    )
