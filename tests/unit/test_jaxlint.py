"""jaxlint: per-rule fixture corpus + the live-codebase-clean gate.

Each rule gets one known-violating and one known-clean snippet (the
clean twin exercises the refinement that keeps the rule quiet on the
real codebase: static_argnames exemptions, `is None` tests, host-call
boundaries, dtype'd literals, ...). The final test runs the real CLI
over the installed package with --strict and requires exit 0 — the
acceptance gate that keeps the tree violation-free.
"""

import os

import pytest

from tools.jaxlint import RULES, analyze_source, main

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def codes_of(src: str, path: str = "fixture.py") -> list[str]:
    return [f.code for f in analyze_source(src, path).findings]


# --------------------------------------------------------------------------
# fixture corpus: (rule, violating snippet, clean twin, path)

CORPUS = {
    "JX001": (
        # str param traced -> recompile per value
        """
import jax
from functools import partial

@partial(jax.jit, static_argnames=("mode",))
def f(x, mode: str = "a", impl: str = "xla"):
    return x
""",
        # everything str/bool-typed is static; unannotated bool default
        # (the traced-first_epoch idiom) is deliberately exempt
        """
import jax
from functools import partial

@partial(jax.jit, static_argnames=("mode", "impl", "save"))
def f(x, mode: str = "a", impl: str = "xla", save: bool = True,
      first_epoch=False):
    return x
""",
    ),
    "JX002": (
        """
import jax

@jax.jit
def f(x):
    y = x + 1
    return float(y.sum())
""",
        # casts of host constants are fine, as is np on untraced shapes
        """
import jax
import numpy as np

@jax.jit
def f(x):
    scale = float(2**17)
    n = np.prod(x.shape)
    return x * scale + n
""",
    ),
    "JX003": (
        """
import jax

@jax.jit
def f(x):
    if x.sum() > 0:
        return x
    while x[0] > 0:
        x = x - 1
    return -x
""",
        # static-arg branches, `is None` structure checks, .shape gates
        # and host-predicate calls are all legitimate trace-time branches
        """
import jax
from functools import partial

@partial(jax.jit, static_argnames=("mode",))
def f(x, carry=None, mode: str = "a"):
    if carry is not None:
        x = x + carry
    if mode == "a":
        x = -x
    E, V = x.shape
    if V > 4:
        x = x * 2
    if eligibility_gate(x.shape, x):
        x = x + 1
    return x
""",
    ),
    "JX004": (
        """
import jax
from yuma_simulation_tpu.resilience.faults import maybe_fail_fused_dispatch

@jax.jit
def f(x):
    maybe_fail_fused_dispatch()
    return x
""",
        # host-level dispatch wrapper (not jitted) is where hooks belong
        """
from yuma_simulation_tpu.resilience import faults

def dispatch(x):
    faults.maybe_fail_fused_dispatch()
    return _jitted_engine(x)
""",
    ),
    "JX005": (
        """
import jax.numpy as jnp

def poison():
    return jnp.asarray(float("nan"))
""",
        """
import jax.numpy as jnp

def poison(dtype):
    return jnp.asarray(float("nan"), dtype=dtype)

def sentinel():
    return jnp.asarray(-1, jnp.int32)
""",
    ),
    "JX006": (
        """
import jax
import time
import random

@jax.jit
def f(x):
    return x * time.time() + random.random()
""",
        # host-side timing around a jitted call is the supported pattern,
        # as is jax.random with explicit keys inside
        """
import jax
import time

@jax.jit
def f(x, key):
    return x + jax.random.normal(key, x.shape)

def bench(x, key):
    t0 = time.perf_counter()
    f(x, key)
    return time.perf_counter() - t0
""",
    ),
    "JX007": (
        """
from yuma_simulation._internal.cases import build
from yuma_simulation_tpu.simulation.engine import _simulate_scan
""",
        # public names (aliased privately) from public modules are fine
        """
from yuma_simulation_tpu.simulation.engine import run_simulation
from yuma_simulation_tpu.simulation.sweep import (
    pad_scenarios as _pad_scenarios,
)
""",
    ),
    "JX008": (
        """
from jax import lax

def run(xs, step):
    carry0 = (1, {"bonds": 0})
    out, _ = lax.scan(step, carry0, xs)
    final, _ = lax.scan(step, (0, 0), xs)
    return out, final
""",
        """
from jax import lax
from yuma_simulation_tpu.simulation.carry import TotalsCarry

def run(xs, step, z):
    carry0 = TotalsCarry(bonds=z, w_prev=z, consensus=z, acc=z)
    out, _ = lax.scan(step, carry0, xs)
    return out
""",
    ),
    "JX009": (
        # device_put inside a jit scope — incl. a scan body nested in
        # one — is never the async host->HBM transfer the caller meant.
        """
import jax
from functools import partial
from jax import lax

@partial(jax.jit, static_argnames=("sharding",))
def run(W, xs, sharding):
    W = jax.device_put(W, sharding)
    def step(carry, x):
        return carry + jax.device_put(x), None
    out, _ = lax.scan(step, W.sum(), xs)
    return out
""",
        # host-level staging (the double-buffered streaming driver's
        # pattern) is exactly what the rule steers toward
        """
import jax

def stage(chunk, dispatch):
    staged = jax.device_put(chunk)
    return dispatch(staged)
""",
    ),
}

#: rules whose scope is path-filtered
_RULE_PATHS = {
    "JX007": "yuma_simulation_tpu/v1/api.py",
    "JX008": "yuma_simulation_tpu/simulation/engine.py",
}


@pytest.mark.parametrize("rule", sorted(CORPUS))
def test_rule_fires_on_violating_fixture(rule):
    bad, _ = CORPUS[rule]
    path = _RULE_PATHS.get(rule, "fixture.py")
    assert rule in codes_of(bad, path), f"{rule} did not fire on its fixture"


@pytest.mark.parametrize("rule", sorted(CORPUS))
def test_rule_quiet_on_clean_fixture(rule):
    _, clean = CORPUS[rule]
    path = _RULE_PATHS.get(rule, "fixture.py")
    got = codes_of(clean, path)
    assert rule not in got, f"{rule} false-positived on its clean twin: {got}"


def test_path_scoped_rules_silent_off_scope():
    """JX007/JX008 are scoped to v1 modules / engine.py; the same source
    elsewhere is intentionally not their business."""
    assert "JX007" not in codes_of(CORPUS["JX007"][0], "scripts/tool.py")
    assert "JX008" not in codes_of(CORPUS["JX008"][0], "pkg/other.py")


def test_suppression_comment_and_unused_tracking():
    src = (
        "import jax.numpy as jnp\n"
        "def g():\n"
        "    return jnp.asarray(1.5)  # jaxlint: disable=JX005\n"
        "x = 1  # jaxlint: disable=JX001\n"
    )
    rep = analyze_source(src, "s.py")
    assert rep.findings == []
    assert rep.suppressed == 1
    assert rep.unused_suppressions == [(4, frozenset({"JX001"}))]
    # a bare disable suppresses every rule on the line
    rep2 = analyze_source(
        "import jax.numpy as jnp\n"
        "def g():\n"
        "    return jnp.asarray(1.5)  # jaxlint: disable\n",
        "s.py",
    )
    assert rep2.findings == [] and rep2.suppressed == 1


def test_wrong_code_suppression_does_not_silence():
    src = (
        "import jax.numpy as jnp\n"
        "def g():\n"
        "    return jnp.asarray(1.5)  # jaxlint: disable=JX001\n"
    )
    rep = analyze_source(src, "s.py")
    assert [f.code for f in rep.findings] == ["JX005"]


def test_parse_error_reported_not_crashed():
    rep = analyze_source("def broken(:\n", "bad.py")
    assert [f.code for f in rep.findings] == ["JX999"]


def test_rule_registry_covers_corpus():
    assert set(CORPUS) == set(RULES)


def test_live_codebase_is_clean_strict(capsys):
    """The acceptance gate: `python -m tools.jaxlint yuma_simulation_tpu/
    --strict` exits 0 on the repo (no violations, no rotting
    suppressions)."""
    pkg = os.path.join(REPO, "yuma_simulation_tpu")
    rc = main([pkg, "--strict"])
    out = capsys.readouterr().out
    assert rc == 0, f"jaxlint --strict found violations:\n{out}"


def test_cli_json_output_and_exit_code(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax.numpy as jnp\n"
        "def g():\n"
        "    return jnp.asarray(2.5)\n"
    )
    rc = main([str(bad), "--format", "json"])
    assert rc == 1
    import json

    payload = json.loads(capsys.readouterr().out)
    assert payload["files_analyzed"] == 1
    (finding,) = payload["findings"]
    assert finding["code"] == "JX005" and finding["line"] == 3
    assert finding["rule"] == "dtypeless-literal"


def test_cli_select_and_strict_unused(tmp_path, capsys):
    f = tmp_path / "m.py"
    f.write_text("x = 1  # jaxlint: disable=JX005\n")
    assert main([str(f)]) == 0  # unused suppression is a note by default
    assert main([str(f), "--strict"]) == 1  # ...and fails under --strict
    capsys.readouterr()
    # --select limits the rule set; unknown codes are a usage error
    assert main([str(f), "--select", "JX001"]) == 0
    with pytest.raises(SystemExit):
        main([str(f), "--select", "JX42"])
