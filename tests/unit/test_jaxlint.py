"""jaxlint: per-rule fixture corpus + the live-codebase-clean gate.

Each rule gets one known-violating and one known-clean snippet (the
clean twin exercises the refinement that keeps the rule quiet on the
real codebase: static_argnames exemptions, `is None` tests, host-call
boundaries, dtype'd literals, ...). The final test runs the real CLI
over the installed package with --strict and requires exit 0 — the
acceptance gate that keeps the tree violation-free.
"""

import os

import pytest

from tools.jaxlint import RULES, analyze_source, main

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def codes_of(src: str, path: str = "fixture.py") -> list[str]:
    return [f.code for f in analyze_source(src, path).findings]


# --------------------------------------------------------------------------
# fixture corpus: (rule, violating snippet, clean twin, path)

CORPUS = {
    "JX001": (
        # str param traced -> recompile per value
        """
import jax
from functools import partial

@partial(jax.jit, static_argnames=("mode",))
def f(x, mode: str = "a", impl: str = "xla"):
    return x
""",
        # everything str/bool-typed is static; unannotated bool default
        # (the traced-first_epoch idiom) is deliberately exempt
        """
import jax
from functools import partial

@partial(jax.jit, static_argnames=("mode", "impl", "save"))
def f(x, mode: str = "a", impl: str = "xla", save: bool = True,
      first_epoch=False):
    return x
""",
    ),
    "JX002": (
        """
import jax

@jax.jit
def f(x):
    y = x + 1
    return float(y.sum())
""",
        # casts of host constants are fine, as is np on untraced shapes
        """
import jax
import numpy as np

@jax.jit
def f(x):
    scale = float(2**17)
    n = np.prod(x.shape)
    return x * scale + n
""",
    ),
    "JX003": (
        """
import jax

@jax.jit
def f(x):
    if x.sum() > 0:
        return x
    while x[0] > 0:
        x = x - 1
    return -x
""",
        # static-arg branches, `is None` structure checks, .shape gates
        # and host-predicate calls are all legitimate trace-time branches
        """
import jax
from functools import partial

@partial(jax.jit, static_argnames=("mode",))
def f(x, carry=None, mode: str = "a"):
    if carry is not None:
        x = x + carry
    if mode == "a":
        x = -x
    E, V = x.shape
    if V > 4:
        x = x * 2
    if eligibility_gate(x.shape, x):
        x = x + 1
    return x
""",
    ),
    "JX004": (
        """
import jax
from yuma_simulation_tpu.resilience.faults import maybe_fail_fused_dispatch

@jax.jit
def f(x):
    maybe_fail_fused_dispatch()
    return x
""",
        # host-level dispatch wrapper (not jitted) is where hooks belong
        """
from yuma_simulation_tpu.resilience import faults

def dispatch(x):
    faults.maybe_fail_fused_dispatch()
    return _jitted_engine(x)
""",
    ),
    "JX005": (
        """
import jax.numpy as jnp

def poison():
    return jnp.asarray(float("nan"))
""",
        """
import jax.numpy as jnp

def poison(dtype):
    return jnp.asarray(float("nan"), dtype=dtype)

def sentinel():
    return jnp.asarray(-1, jnp.int32)
""",
    ),
    "JX006": (
        """
import jax
import time
import random

@jax.jit
def f(x):
    return x * time.time() + random.random()
""",
        # host-side timing around a jitted call is the supported pattern,
        # as is jax.random with explicit keys inside
        """
import jax
import time

@jax.jit
def f(x, key):
    return x + jax.random.normal(key, x.shape)

def bench(x, key):
    t0 = time.perf_counter()
    f(x, key)
    return time.perf_counter() - t0
""",
    ),
    "JX007": (
        """
from yuma_simulation._internal.cases import build
from yuma_simulation_tpu.simulation.engine import _simulate_scan
""",
        # public names (aliased privately) from public modules are fine
        """
from yuma_simulation_tpu.simulation.engine import run_simulation
from yuma_simulation_tpu.simulation.sweep import (
    pad_scenarios as _pad_scenarios,
)
""",
    ),
    "JX008": (
        """
from jax import lax

def run(xs, step):
    carry0 = (1, {"bonds": 0})
    out, _ = lax.scan(step, carry0, xs)
    final, _ = lax.scan(step, (0, 0), xs)
    return out, final
""",
        """
from jax import lax
from yuma_simulation_tpu.simulation.carry import TotalsCarry

def run(xs, step, z):
    carry0 = TotalsCarry(bonds=z, w_prev=z, consensus=z, acc=z)
    out, _ = lax.scan(step, carry0, xs)
    return out
""",
    ),
    "JX009": (
        # device_put inside a jit scope — incl. a scan body nested in
        # one — is never the async host->HBM transfer the caller meant.
        """
import jax
from functools import partial
from jax import lax

@partial(jax.jit, static_argnames=("sharding",))
def run(W, xs, sharding):
    W = jax.device_put(W, sharding)
    def step(carry, x):
        return carry + jax.device_put(x), None
    out, _ = lax.scan(step, W.sum(), xs)
    return out
""",
        # host-level staging (the double-buffered streaming driver's
        # pattern) is exactly what the rule steers toward
        """
import jax

def stage(chunk, dispatch):
    staged = jax.device_put(chunk)
    return dispatch(staged)
""",
    ),
    "JX010": (
        # wall-clock in a helper REACHABLE from a jit scope: invisible
        # to the per-function pass, found through the call graph
        """
import jax
import time
import uuid

def stamp(x):
    return x * time.time(), uuid.uuid4()

@jax.jit
def f(x):
    y, tag = stamp(x + 1)
    return y
""",
        # host-side timing around the dispatch, and an is-tracing
        # self-guarded recorder, are the supported patterns
        """
import jax
import time

def _tracing_now():
    return False

def record(x):
    if _tracing_now():
        return
    print(time.time())

@jax.jit
def f(x):
    record(x)
    return x + 1

def bench(x):
    t0 = time.perf_counter()
    f(x)
    return time.perf_counter() - t0
""",
    ),
    "JX101": (
        # field written under the lock in one method, read bare in
        # another: a torn read under the serve+fleet thread mix
        """
import threading

class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._items = []

    def add(self, item):
        with self._lock:
            self._items.append(item)
            self._count += 1

    def snapshot(self):
        return list(self._items), self._count
""",
        # every access locked, __init__ exempt, *_locked helper
        # convention honored
        """
import threading

class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._items = []

    def add(self, item):
        with self._lock:
            self._items.append(item)
            self._count += 1

    def snapshot(self):
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self):
        return list(self._items), self._count
""",
    ),
    "JX102": (
        # direct write-mode open on a durable artifact path: a crash
        # mid-write tears the bundle
        """
import json

def publish(bundle_dir, payload):
    with open(bundle_dir / "ledger.jsonl", "w") as fh:
        json.dump(payload, fh)
""",
        # the atomic/append primitives are the sanctioned route; reads
        # and scratch files stay invisible
        """
import json
from yuma_simulation_tpu.utils.checkpoint import append_durable, publish_atomic

def publish(bundle_dir, payload):
    publish_atomic(bundle_dir / "ledger.jsonl", json.dumps(payload))
    append_durable(bundle_dir / "spans.jsonl", b"{}")

def load(bundle_dir):
    with open(bundle_dir / "ledger.jsonl") as fh:
        return fh.read()

def scratch(tmp):
    with open(tmp / "notes.txt", "w") as fh:
        fh.write("x")
""",
    ),
    "JX103": (
        # bare Thread target reading the ambient telemetry context:
        # contextvars do not flow into a new thread
        """
import contextvars
import threading

RUN = contextvars.ContextVar("RUN", default=None)

def worker():
    return RUN.get()

def spawn():
    t = threading.Thread(target=worker)
    t.start()
    return t
""",
        # the watchdog pattern: copy the spawner's context explicitly
        """
import contextvars
import threading

RUN = contextvars.ContextVar("RUN", default=None)

def worker():
    return RUN.get()

def spawn():
    ctx = contextvars.copy_context()
    t = threading.Thread(target=lambda: ctx.run(worker))
    t.start()
    return t
""",
    ),
    "JX201": (
        # typo'd event name: not declared in telemetry/registry.py
        """
import logging

logger = logging.getLogger(__name__)

def emit(log_event):
    log_event(logger, "engine_retyr", attempt=1)
""",
        # declared names (and trace-resolvable literal choices) pass
        """
import logging

logger = logging.getLogger(__name__)

def emit(log_event, ok):
    log_event(logger, "engine_retry", attempt=1)
    log_event(logger, "slo_alert" if not ok else "slo_recovered")
""",
    ),
    "JX202": (
        # metric series nobody declared: drifts away from dashboards
        """
def count(registry):
    registry.counter("engine_retires").inc()
""",
        """
def count(registry):
    registry.counter("engine_retries").inc()
    registry.gauge("serve_queue_depth").set(0)
""",
    ),
    "JX203": (
        # registry entry with no consumer and no justification: the
        # name LOOKS monitored and is not
        """
EVENTS = {
    "mystery_event": EventSpec("what even reads this"),
}
""",
        """
EVENTS = {
    "mystery_event": EventSpec(
        "incident forensics",
        operator_reason="greppable breadcrumb between attempt spans",
    ),
}
""",
    ),
    "JX301": (
        # report reads a field no producer of the event ever writes —
        # the column is permanently empty
        """
class Host:
    def ok(self, unit):
        self.ledger.append("unit_ok", unit=unit, stalls=2)


def report(records):
    oks = [r for r in records if r.get("event") == "unit_ok"]
    return [r.get("stall_count") for r in oks]
""",
        """
class Host:
    def ok(self, unit):
        self.ledger.append("unit_ok", unit=unit, stalls=2)


def report(records):
    oks = [r for r in records if r.get("event") == "unit_ok"]
    return [r.get("stalls") for r in oks]
""",
    ),
    "JX302": (
        # typed error raised on a serve-reachable path with no HTTP
        # mapping anywhere in the serve tier
        """
class ResilienceError(Exception):
    pass


class QuotaBlown(ResilienceError):
    pass


def classify_failure(exc):
    if isinstance(exc, ResilienceError):
        return None
    return None


def check(payload):
    if not payload:
        raise QuotaBlown("over budget")


def handle_request(payload):
    check(payload)
    return 200, {"status": "ok"}
""",
        # a typed except on the serve path IS the HTTP mapping
        """
class ResilienceError(Exception):
    pass


class QuotaBlown(ResilienceError):
    pass


def classify_failure(exc):
    if isinstance(exc, ResilienceError):
        return None
    return None


def check(payload):
    if not payload:
        raise QuotaBlown("over budget")


def handle_request(payload):
    try:
        check(payload)
    except QuotaBlown as exc:
        return 429, {"status": "rejected", "error": str(exc)}
    return 200, {"status": "ok"}
""",
    ),
    "JX303": (
        # claim scoring reads an annotation field the heartbeat never
        # advertises; the advertised 'magic' is dead weight both ways
        """
class Pool:
    def heartbeat(self, slot):
        self.leases.annotate(
            slot, {"worker_id": "w0", "inflight": 0, "magic": 1}
        )


def claim_score(ad):
    return (ad.get("inflight"), ad.get("crystal"))
""",
        """
class Pool:
    def heartbeat(self, slot):
        self.leases.annotate(
            slot, {"worker_id": "w0", "inflight": 0}
        )


def claim_score(ad):
    return (ad.get("inflight"), ad.get("worker_id"))
""",
    ),
}

#: rules whose scope is path-filtered
_RULE_PATHS = {
    "JX007": "yuma_simulation_tpu/v1/api.py",
    "JX008": "yuma_simulation_tpu/simulation/engine.py",
    # JX102/JX201/JX202 only police package code (tools/tests write
    # scratch files and fixture events by design)
    "JX101": "yuma_simulation_tpu/serve/store.py",
    "JX102": "yuma_simulation_tpu/telemetry/sink.py",
    "JX103": "yuma_simulation_tpu/resilience/spawn.py",
    "JX201": "yuma_simulation_tpu/fabric/emit.py",
    "JX202": "yuma_simulation_tpu/fabric/count.py",
    "JX203": "yuma_simulation_tpu/telemetry/registry.py",
    # JX301 consumers are skipped in tests/; tools/ keeps the fixture
    # out of the JX2xx package census. JX302/JX303 need a serve-path
    # unit (serve reachability / claim-scoring scope).
    "JX301": "tools/obsfix.py",
    "JX302": "yuma_simulation_tpu/serve/handler.py",
    "JX303": "yuma_simulation_tpu/serve/minirouter.py",
}


@pytest.mark.parametrize("rule", sorted(CORPUS))
def test_rule_fires_on_violating_fixture(rule):
    bad, _ = CORPUS[rule]
    path = _RULE_PATHS.get(rule, "fixture.py")
    assert rule in codes_of(bad, path), f"{rule} did not fire on its fixture"


@pytest.mark.parametrize("rule", sorted(CORPUS))
def test_rule_quiet_on_clean_fixture(rule):
    _, clean = CORPUS[rule]
    path = _RULE_PATHS.get(rule, "fixture.py")
    got = codes_of(clean, path)
    assert rule not in got, f"{rule} false-positived on its clean twin: {got}"


def test_path_scoped_rules_silent_off_scope():
    """JX007/JX008 are scoped to v1 modules / engine.py; the same source
    elsewhere is intentionally not their business."""
    assert "JX007" not in codes_of(CORPUS["JX007"][0], "scripts/tool.py")
    assert "JX008" not in codes_of(CORPUS["JX008"][0], "pkg/other.py")


def test_suppression_comment_and_unused_tracking():
    src = (
        "import jax.numpy as jnp\n"
        "def g():\n"
        "    return jnp.asarray(1.5)  # jaxlint: disable=JX005\n"
        "x = 1  # jaxlint: disable=JX001\n"
    )
    rep = analyze_source(src, "s.py")
    assert rep.findings == []
    assert rep.suppressed == 1
    assert rep.unused_suppressions == [(4, frozenset({"JX001"}))]
    # a bare disable suppresses every rule on the line
    rep2 = analyze_source(
        "import jax.numpy as jnp\n"
        "def g():\n"
        "    return jnp.asarray(1.5)  # jaxlint: disable\n",
        "s.py",
    )
    assert rep2.findings == [] and rep2.suppressed == 1


def test_wrong_code_suppression_does_not_silence():
    src = (
        "import jax.numpy as jnp\n"
        "def g():\n"
        "    return jnp.asarray(1.5)  # jaxlint: disable=JX001\n"
    )
    rep = analyze_source(src, "s.py")
    assert [f.code for f in rep.findings] == ["JX005"]


def test_parse_error_reported_not_crashed():
    rep = analyze_source("def broken(:\n", "bad.py")
    assert [f.code for f in rep.findings] == ["JX999"]


def test_rule_registry_covers_corpus():
    # JX304 (locked-schema regression) is inherently two-input — a
    # tree plus a lock file — so its violating/clean pair lives in
    # tests/unit/test_wirecheck.py as CLI round-trips instead.
    assert set(RULES) - set(CORPUS) == {"JX304"}
    assert set(CORPUS) <= set(RULES)


def test_live_codebase_is_clean_strict(capsys):
    """The acceptance gate: `python -m tools.jaxlint yuma_simulation_tpu
    tools tests --strict` exits 0 on the repo — all three roots, no
    violations, no rotting suppressions."""
    roots = [
        os.path.join(REPO, "yuma_simulation_tpu"),
        os.path.join(REPO, "tools"),
        os.path.join(REPO, "tests"),
    ]
    rc = main([*roots, "--strict"])
    out = capsys.readouterr().out
    assert rc == 0, f"jaxlint --strict found violations:\n{out}"


# --------------------------------------------------------------------------
# whole-program layer: interprocedural reach, cross-module facts


def test_interprocedural_host_cast_through_helper():
    """float(tracer) one call away from the jit boundary — invisible to
    the PR 2 per-function pass, found through the call graph, with the
    seed chain in the message."""
    src = """
import jax

def summarize(v):
    return float(v.sum())

@jax.jit
def f(x):
    return summarize(x * 2)
"""
    rep = analyze_source(src, "fixture.py")
    jx002 = [f for f in rep.findings if f.code == "JX002"]
    assert jx002, rep.findings
    assert "traced via" in jx002[0].message


def test_directly_nested_closure_is_checked():
    """A closure defined straight inside the jit body (the lax.scan
    step idiom) is part of the traced program at EVERY nesting depth —
    the even-depth-only walk was a real blind spot."""
    src = """
import jax

@jax.jit
def f(x):
    def g(v):
        return float(v.sum())
    def outer(v):
        def inner(w):
            return float(w.sum())
        return inner(v)
    return g(x) + outer(x)
"""
    rep = analyze_source(src, "fixture.py")
    jx002 = [f for f in rep.findings if f.code == "JX002"]
    assert len(jx002) == 2, rep.findings


def test_reached_helper_closure_params_not_blanket_tainted():
    """In a helper only REACHABLE from a jit scope, closure params are
    host dispatch plumbing (rung strings, fault records) — branching
    on them is not JX003; closure-captured traced values still are."""
    src = """
import jax

def dispatch(W):
    def by_rung(rung):
        if rung == "fused":
            return W * 2
        if W.sum() > 0:
            return W
        return -W
    return by_rung("fused")

@jax.jit
def f(x):
    return dispatch(x)
"""
    rep = analyze_source(src, "fixture.py")
    jx003 = [f for f in rep.findings if f.code == "JX003"]
    # exactly one: the W.sum() branch (captured traced value), not the
    # rung-string branch
    assert len(jx003) == 1 and jx003[0].line == 8, rep.findings


def test_interprocedural_taint_is_per_parameter():
    """Only params that actually RECEIVE traced values taint the
    callee: a helper called with host constants stays clean."""
    src = """
import jax

def cast(v):
    return float(v)

@jax.jit
def f(x):
    n = cast(3.5)
    return x * n
"""
    rep = analyze_source(src, "fixture.py")
    assert [f.code for f in rep.findings] == [], rep.findings


def test_interprocedural_cross_module():
    """Facts flow across FILES: the helper lives in another module of
    the same analyzed program."""
    from tools.jaxlint.analyzer import analyze_units
    from tools.jaxlint.program import parse_unit

    helper = """
import time

def stamp(x):
    return x * time.time()
"""
    entry = """
import jax
from yuma_simulation_tpu.work.helper import stamp

@jax.jit
def f(x):
    return stamp(x)
"""
    units = [
        parse_unit(helper, "yuma_simulation_tpu/work/helper.py"),
        parse_unit(entry, "yuma_simulation_tpu/work/entry.py"),
    ]
    reports = analyze_units(units)
    codes = [f.code for r in reports for f in r.findings]
    assert "JX010" in codes, codes


def test_jit_boundary_stops_interprocedural_reach():
    """A jit-decorated callee is its own seed, not a continuation of
    the caller's trace scope (jit-of-jit)."""
    src = """
import jax
import time

@jax.jit
def inner(x):
    return x + 1

@jax.jit
def outer(x):
    return inner(x)

def unreachable(x):
    return time.time() * x
"""
    rep = analyze_source(src, "fixture.py")
    assert [f.code for f in rep.findings] == [], rep.findings


def test_package_run_without_registry_is_jx203():
    """Analyzing the package as a program with NO registry module is
    itself a contracts violation — the pre-PR-11 state."""
    from tools.jaxlint.analyzer import analyze_units
    from tools.jaxlint.program import parse_unit

    units = [
        parse_unit("x = 1\n", "yuma_simulation_tpu/a.py"),
        parse_unit("y = 2\n", "yuma_simulation_tpu/b.py"),
    ]
    reports = analyze_units(units)
    codes = [f.code for r in reports for f in r.findings]
    assert codes == ["JX203"], codes


# --------------------------------------------------------------------------
# telemetry registry (the JX2xx contract's declaration side)


def test_registry_validates_and_covers_names():
    from yuma_simulation_tpu.telemetry import registry

    assert registry.validate_registry() == []
    assert "engine_retry" in registry.declared_events()
    assert "engine_retries" in registry.declared_metrics()
    # kinds are pinned so a counter cannot silently become a gauge
    assert registry.METRICS["serve_queue_depth"].kind == "gauge"
    assert registry.METRICS["serve_request_seconds"].kind == "histogram"


def test_cli_json_output_and_exit_code(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax.numpy as jnp\n"
        "def g():\n"
        "    return jnp.asarray(2.5)\n"
    )
    rc = main([str(bad), "--format", "json"])
    assert rc == 1
    import json

    payload = json.loads(capsys.readouterr().out)
    assert payload["files_analyzed"] == 1
    (finding,) = payload["findings"]
    assert finding["code"] == "JX005" and finding["line"] == 3
    assert finding["rule"] == "dtypeless-literal"


def test_cli_select_and_strict_unused(tmp_path, capsys):
    f = tmp_path / "m.py"
    f.write_text("x = 1  # jaxlint: disable=JX005\n")
    assert main([str(f)]) == 0  # unused suppression is a note by default
    assert main([str(f), "--strict"]) == 1  # ...and fails under --strict
    capsys.readouterr()
    # --select limits the rule set; unknown codes are a usage error
    assert main([str(f), "--select", "JX001"]) == 0
    with pytest.raises(SystemExit):
        main([str(f), "--select", "JX42"])
