"""Crash-safe checkpoint recovery (ISSUE 1 acceptance rung 3): a
truncated/corrupt chunk is detected via the checksum manifest,
re-executed, and the resumed sweep output is bitwise identical to an
uninterrupted run; an interrupted run resumes from the manifest."""

import json

import numpy as np
import pytest

from yuma_simulation_tpu.resilience import (
    CheckpointCorruptionError,
    FaultPlan,
    inject_faults,
)
from yuma_simulation_tpu.utils import CheckpointedSweep


def _fn(i):
    # Deterministic, index-dependent payload so bitwise comparison is
    # meaningful across runs.
    rng = np.random.default_rng(1000 + i)
    return rng.random((3, 4)).astype(np.float32)


def _counting(calls):
    def fn(i):
        calls.append(i)
        return _fn(i)

    return fn


@pytest.fixture()
def reference(tmp_path_factory):
    d = tmp_path_factory.mktemp("ckpt_ref")
    return CheckpointedSweep(d, num_chunks=4, tag="r").run(_fn)


def test_checksums_recorded_per_chunk(tmp_path, reference):
    CheckpointedSweep(tmp_path, num_chunks=4, tag="r").run(_fn)
    sums = json.loads((tmp_path / "checksums.json").read_text())
    assert sorted(sums) == ["00000", "00001", "00002", "00003"]
    sweep = CheckpointedSweep(tmp_path, num_chunks=4, tag="r")
    assert sweep.corrupt_chunks() == []
    assert all(sweep.verify_chunk(i) for i in range(4))


@pytest.mark.faultinject
def test_truncated_chunk_detected_and_requeued(tmp_path, reference):
    """Acceptance rung 3: truncation between runs is caught by the
    checksum, only that chunk re-executes, and the resumed output equals
    the uninterrupted run bitwise."""
    CheckpointedSweep(tmp_path, num_chunks=4, tag="r").run(_fn)
    p = tmp_path / "chunk_00001.npz"
    p.write_bytes(p.read_bytes()[:10])
    sweep = CheckpointedSweep(tmp_path, num_chunks=4, tag="r")
    assert sweep.corrupt_chunks() == [1]
    calls = []
    out = sweep.run(_counting(calls))
    assert calls == [1]
    np.testing.assert_array_equal(out, reference)


@pytest.mark.faultinject
def test_bitflipped_chunk_detected_and_requeued(tmp_path, reference):
    """A single flipped byte — an npz that may still DECODE fine — is
    caught by the sha256, not just by load failures."""
    CheckpointedSweep(tmp_path, num_chunks=4, tag="r").run(_fn)
    p = tmp_path / "chunk_00002.npz"
    data = bytearray(p.read_bytes())
    data[-1] ^= 0xFF
    p.write_bytes(bytes(data))
    calls = []
    out = CheckpointedSweep(tmp_path, num_chunks=4, tag="r").run(
        _counting(calls)
    )
    assert calls == [2]
    np.testing.assert_array_equal(out, reference)


@pytest.mark.faultinject
def test_fault_injected_corruption_heals_within_run(tmp_path, reference):
    """The fault hook truncates chunk 1 right after publish; the final
    verification pass catches it and re-executes before returning."""
    calls = []
    with inject_faults(FaultPlan(truncate_chunks={1: 8})):
        out = CheckpointedSweep(tmp_path, num_chunks=4, tag="r").run(
            _counting(calls)
        )
    assert calls == [0, 1, 2, 3, 1]  # chunk 1 ran twice
    np.testing.assert_array_equal(out, reference)


@pytest.mark.faultinject
def test_resumed_chunk_rotting_midrun_is_requeued_at_load(tmp_path, reference):
    """A chunk that passed the resume pre-pass but rots WHILE the rest
    of the sweep computes must requeue at final load (decode check),
    not crash with a raw zipfile error."""

    def interrupt_at_2(i):
        if i == 2:
            raise RuntimeError("interrupted")
        return _fn(i)

    with pytest.raises(RuntimeError):
        CheckpointedSweep(tmp_path, num_chunks=4, tag="r").run(interrupt_at_2)

    calls = []

    def rot_0_while_computing_2(i):
        calls.append(i)
        if i == 2:  # chunk 0 was pre-pass-verified; now it rots
            p = tmp_path / "chunk_00000.npz"
            p.write_bytes(p.read_bytes()[:10])
        return _fn(i)

    out = CheckpointedSweep(tmp_path, num_chunks=4, tag="r").run(
        rot_0_while_computing_2
    )
    assert calls == [2, 3, 0]
    np.testing.assert_array_equal(out, reference)


def test_interrupted_run_resumes_from_manifest(tmp_path, reference):
    """A crash mid-sweep leaves the completed chunks; resume re-executes
    only the missing ones and the result is bitwise the uninterrupted
    run."""

    def interrupt_at_2(i):
        if i == 2:
            raise KeyboardInterrupt
        return _fn(i)

    with pytest.raises(KeyboardInterrupt):
        CheckpointedSweep(tmp_path, num_chunks=4, tag="r").run(interrupt_at_2)
    assert CheckpointedSweep(tmp_path, num_chunks=4, tag="r").completed_chunks() == [0, 1]
    calls = []
    out = CheckpointedSweep(tmp_path, num_chunks=4, tag="r").run(
        _counting(calls)
    )
    assert calls == [2, 3]
    np.testing.assert_array_equal(out, reference)


def test_legacy_chunks_without_checksums_resume(tmp_path, reference):
    """Chunks published before the checksum sidecar existed are verified
    by decode probe: intact ones are NOT recomputed, torn ones are."""
    CheckpointedSweep(tmp_path, num_chunks=4, tag="r").run(_fn)
    (tmp_path / "checksums.json").unlink()
    calls = []
    out = CheckpointedSweep(tmp_path, num_chunks=4, tag="r").run(
        _counting(calls)
    )
    assert calls == []
    np.testing.assert_array_equal(out, reference)
    # now tear one legacy chunk: the probe catches it
    (tmp_path / "checksums.json").unlink()
    p = tmp_path / "chunk_00003.npz"
    p.write_bytes(p.read_bytes()[:10])
    calls = []
    out = CheckpointedSweep(tmp_path, num_chunks=4, tag="r").run(
        _counting(calls)
    )
    assert calls == [3]
    np.testing.assert_array_equal(out, reference)


def test_unreliable_storage_raises_typed_error(tmp_path, monkeypatch):
    """If a chunk fails verification immediately after re-execution the
    storage itself is bad: a typed CheckpointCorruptionError, not
    silently poisoned output."""
    sweep = CheckpointedSweep(tmp_path, num_chunks=2, tag="r")
    monkeypatch.setattr(
        CheckpointedSweep, "verify_chunk", lambda self, i: False
    )
    with pytest.raises(CheckpointCorruptionError):
        sweep.run(_fn)


def test_atomic_manifest_and_sidecar_writes(tmp_path):
    """No publish step may leave a half-written file under a valid name:
    temp names are invisible to the chunk glob and json sidecars."""
    sweep = CheckpointedSweep(tmp_path, num_chunks=2, tag="r")
    sweep.run(_fn)
    leftovers = [
        p.name
        for p in tmp_path.iterdir()
        if p.suffix == ".tmp"
    ]
    assert leftovers == []
    assert sweep.completed_chunks() == [0, 1]
