"""Batched sweeps: hyperparameter grids and scenario batches via vmap."""

import numpy as np
import pytest

from yuma_simulation_tpu.models.config import (
    SimulationHyperparameters,
    YumaConfig,
    YumaParams,
)
from yuma_simulation_tpu.scenarios import create_case, get_cases
from yuma_simulation_tpu.simulation.engine import simulate
from yuma_simulation_tpu.simulation.sweep import (
    config_grid,
    stack_scenarios,
    sweep_hyperparams,
    total_dividends_batch,
)


def test_config_grid_order_and_shape():
    configs, points = config_grid(kappa=[0.3, 0.5], bond_alpha=[0.1, 0.2, 0.3])
    assert len(points) == 6
    assert points[0] == {"kappa": 0.3, "bond_alpha": 0.1}
    assert points[-1] == {"kappa": 0.5, "bond_alpha": 0.3}
    assert configs.simulation.kappa.shape == (6,)
    assert configs.yuma_params.bond_alpha.shape == (6,)


def test_config_grid_rejects_static_fields():
    with pytest.raises(ValueError, match="static"):
        config_grid(liquid_alpha=[True, False])


def test_sweep_matches_individual_runs():
    case = create_case("Case 2")
    version = "Yuma 1 (paper)"
    configs, points = config_grid(bond_penalty=[0.0, 0.5, 1.0])
    ys = sweep_hyperparams(case, version, configs)
    swept = np.asarray(ys["dividends"]).sum(axis=1)  # [grid, V]

    for i, point in enumerate(points):
        cfg = YumaConfig(
            simulation=SimulationHyperparameters(bond_penalty=point["bond_penalty"]),
            yuma_params=YumaParams(),
        )
        res = simulate(case, version, cfg, save_bonds=False, save_incentives=False)
        np.testing.assert_allclose(
            swept[i], res.dividends.sum(axis=0), rtol=1e-5, atol=1e-6
        )


def test_stack_scenarios_rejects_heterogeneous():
    a = create_case("Case 1")
    b = create_case("Case 1", num_epochs=20)
    with pytest.raises(ValueError, match="shape"):
        stack_scenarios([a, b])


def test_total_dividends_batch_matches_single():
    cases = get_cases()[:3]
    version = "Yuma 4 (Rhef+relative bonds)"
    batched = total_dividends_batch(cases, version)
    for i, case in enumerate(cases):
        res = simulate(case, version, save_bonds=False, save_incentives=False)
        np.testing.assert_allclose(
            batched[i], res.dividends.sum(axis=0), rtol=1e-5, atol=1e-6
        )
