"""Batched sweeps: hyperparameter grids and scenario batches via vmap."""

import numpy as np
import pytest

from yuma_simulation_tpu.models.config import (
    SimulationHyperparameters,
    YumaConfig,
    YumaParams,
)
from yuma_simulation_tpu.scenarios import create_case, get_cases
from yuma_simulation_tpu.simulation.engine import simulate
from yuma_simulation_tpu.simulation.sweep import (
    config_grid,
    stack_scenarios,
    sweep_hyperparams,
    total_dividends_batch,
)


def test_config_grid_order_and_shape():
    configs, points = config_grid(kappa=[0.3, 0.5], bond_alpha=[0.1, 0.2, 0.3])
    assert len(points) == 6
    assert points[0] == {"kappa": 0.3, "bond_alpha": 0.1}
    assert points[-1] == {"kappa": 0.5, "bond_alpha": 0.3}
    assert configs.simulation.kappa.shape == (6,)
    assert configs.yuma_params.bond_alpha.shape == (6,)


def test_config_grid_rejects_static_fields():
    with pytest.raises(ValueError, match="static"):
        config_grid(liquid_alpha=[True, False])


def test_sweep_matches_individual_runs():
    case = create_case("Case 2")
    version = "Yuma 1 (paper)"
    configs, points = config_grid(bond_penalty=[0.0, 0.5, 1.0])
    ys = sweep_hyperparams(case, version, configs)
    swept = np.asarray(ys["dividends"]).sum(axis=1)  # [grid, V]

    for i, point in enumerate(points):
        cfg = YumaConfig(
            simulation=SimulationHyperparameters(bond_penalty=point["bond_penalty"]),
            yuma_params=YumaParams(),
        )
        res = simulate(case, version, cfg, save_bonds=False, save_incentives=False)
        np.testing.assert_allclose(
            swept[i], res.dividends.sum(axis=0), rtol=1e-5, atol=1e-6
        )


def test_stack_scenarios_rejects_heterogeneous():
    a = create_case("Case 1")
    b = create_case("Case 1", num_epochs=20)
    with pytest.raises(ValueError, match="shape"):
        stack_scenarios([a, b])


def test_total_dividends_batch_matches_single():
    cases = get_cases()[:3]
    version = "Yuma 4 (Rhef+relative bonds)"
    batched = total_dividends_batch(cases, version)
    for i, case in enumerate(cases):
        res = simulate(case, version, save_bonds=False, save_incentives=False)
        np.testing.assert_allclose(
            batched[i], res.dividends.sum(axis=0), rtol=1e-5, atol=1e-6
        )


def test_sweep_scaled_fused_matches_xla_sweep():
    """The one-dispatch fused hyperparameter sweep (r3 verdict item 5:
    per-scenario [B] kappa/bond_penalty/bond_alpha through the batched
    scan kernel's VMEM hp operand) against the vmap'd XLA engine, on a
    grid whose points provably differ from each other (non-vacuity)."""
    import jax.numpy as jnp

    from yuma_simulation_tpu.simulation.sweep import (
        config_grid,
        sweep_scaled_fused,
    )

    rng = np.random.default_rng(3)
    V, M, E = 16, 64, 8
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    scales = jnp.asarray(1.0 + 1e-4 * rng.random(E), jnp.float32)
    configs, points = config_grid(
        kappa=[0.4, 0.5, 0.65], bond_penalty=[0.0, 0.99], bond_alpha=[0.05, 0.3]
    )
    assert len(points) == 12
    t_xla, b_xla = sweep_scaled_fused(
        W, S, scales, configs, "Yuma 1 (paper)", epoch_impl="xla"
    )
    t_f, b_f = sweep_scaled_fused(
        W, S, scales, configs, "Yuma 1 (paper)", epoch_impl="fused_scan"
    )
    assert t_xla.shape == (12, V)
    # the grid points genuinely differ from each other
    assert float(np.abs(np.asarray(b_xla[0]) - np.asarray(b_xla[-1])).max()) > 1e-3
    np.testing.assert_allclose(np.asarray(t_f), np.asarray(t_xla), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(b_f), np.asarray(b_xla), atol=2e-6)


def test_sweep_scaled_fused_liquid_alpha_bounds_grid():
    """Liquid-alpha bound sweeps ([B] alpha_low/high) flow through the
    in-kernel logit fit; relative-bond model so the rate matters with
    epoch-constant weights (the EMA fixed-point argument, DESIGN.md)."""
    import jax.numpy as jnp

    from yuma_simulation_tpu.models.config import YumaParams
    from yuma_simulation_tpu.simulation.sweep import (
        config_grid,
        sweep_scaled_fused,
    )

    rng = np.random.default_rng(4)
    V, M, E = 16, 64, 8
    W = jnp.asarray(rng.random((V, M)), jnp.float32)
    S = jnp.asarray(rng.random(V) + 0.01, jnp.float32)
    scales = jnp.asarray(1.0 + 1e-4 * rng.random(E), jnp.float32)
    configs, points = config_grid(
        base_params=YumaParams(liquid_alpha=True),
        alpha_low=[0.5, 0.7],
        alpha_high=[0.9, 0.99],
        bond_alpha=[0.05, 0.2],
    )
    version = "Yuma 4 (Rhef+relative bonds) - liquid alpha on"
    t_xla, b_xla = sweep_scaled_fused(
        W, S, scales, configs, version, epoch_impl="xla"
    )
    t_f, b_f = sweep_scaled_fused(
        W, S, scales, configs, version, epoch_impl="fused_scan"
    )
    assert float(np.abs(np.asarray(b_xla[0]) - np.asarray(b_xla[-1])).max()) > 1e-3
    np.testing.assert_allclose(np.asarray(t_f), np.asarray(t_xla), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(b_f), np.asarray(b_xla), atol=2e-6)


def test_simulate_batch_fused_suite_matches_xla():
    """The BATCHED fused case scan: the whole 14-case suite (real
    per-epoch weights, per-scenario reset metadata riding the VMEM
    operand) advances one epoch per grid step and must match the vmap'd
    XLA engine — including the versions whose reset rules actually fire
    — and the MXU variant must be bitwise the VPU variant."""
    from yuma_simulation_tpu.models.variants import variant_for_version
    from yuma_simulation_tpu.scenarios import get_cases
    from yuma_simulation_tpu.simulation.sweep import (
        simulate_batch,
        stack_scenarios,
    )

    cases = get_cases()
    W, S, ri, re = stack_scenarios(cases)
    assert int(np.asarray(ri).max()) >= 0  # suite carries real resets
    for version in (
        "Yuma 1 (paper)",
        "Yuma 3.1 (Rhef+reset)",
        "Yuma 3.2 (Rhef+conditional)",
        "Yuma 4 (Rhef+relative bonds) - liquid alpha on",
    ):
        params = (
            dict(liquid_alpha=True, bond_alpha=0.025, alpha_high=0.99,
                 alpha_low=0.9)
            if "liquid" in version
            else {}
        )
        cfg = YumaConfig(yuma_params=YumaParams(**params))
        spec = variant_for_version(version)
        ys_x = simulate_batch(W, S, ri, re, cfg, spec, save_bonds=True)
        ys_f = simulate_batch(
            W, S, ri, re, cfg, spec, save_bonds=True,
            epoch_impl="fused_scan",
        )
        ys_m = simulate_batch(
            W, S, ri, re, cfg, spec, save_bonds=True,
            epoch_impl="fused_scan_mxu",
        )
        # The numerics sidecar (0.14.0) is a sketch pytree, not a
        # result stream: compare it bitwise where the engines' streams
        # overlap (mxu vs fused shares the fused kernel's capture;
        # fused-vs-xla tolerance lives in the value comparison below).
        num_x = ys_x.pop("numerics", None)
        num_f = ys_f.pop("numerics", None)
        num_m = ys_m.pop("numerics", None)
        if num_f is not None and num_m is not None:
            import jax

            jax.tree.map(
                lambda a, b: np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b),
                    err_msg=f"{version}: numerics (mxu bitwise)",
                ),
                num_m,
                num_f,
            )
        del num_x
        for k in ys_x:
            np.testing.assert_allclose(
                np.asarray(ys_f[k]), np.asarray(ys_x[k]),
                atol=2e-6, rtol=1e-5, err_msg=f"{version}: {k}",
            )
            np.testing.assert_array_equal(
                np.asarray(ys_m[k]), np.asarray(ys_f[k]),
                err_msg=f"{version}: {k} (mxu bitwise)",
            )


def test_simulate_batch_case_x_beta_product_one_dispatch():
    """A (case x beta) product suite with batched config leaves: the
    reference's beta sweep over the whole suite as ONE batched
    computation per engine — fused (per-scenario hp vectors in the
    kernel) vs the XLA vmap-over-config oracle."""
    import jax
    import jax.numpy as jnp

    from yuma_simulation_tpu.models.variants import variant_for_version
    from yuma_simulation_tpu.scenarios import get_cases
    from yuma_simulation_tpu.simulation.sweep import (
        simulate_batch,
        stack_scenarios,
    )

    # Cases 11-13 are the beta-sensitive rows of the golden surface
    # (clipping actually occurs there; most cases never clip, so their
    # Yuma-1 dividends are identical across all betas).
    cases = get_cases()[10:14]
    betas = [0.0, 0.99]
    W, S, ri, re = stack_scenarios(cases)
    B = len(cases) * len(betas)
    Wp = jnp.tile(W, (len(betas), 1, 1, 1))
    Sp = jnp.tile(S, (len(betas), 1, 1))
    rip = jnp.tile(ri, (len(betas),))
    rep = jnp.tile(re, (len(betas),))
    # batched config: bond_penalty varies per scenario, everything else
    # broadcast to [B]
    base = YumaConfig()
    cfgs = jax.tree.map(
        lambda leaf: jnp.broadcast_to(jnp.float32(leaf), (B,)), base
    )
    beta_vec = jnp.asarray(np.repeat(np.float32(betas), len(cases)))
    from dataclasses import replace as dc_replace

    cfgs = YumaConfig(
        simulation=dc_replace(cfgs.simulation, bond_penalty=beta_vec),
        yuma_params=cfgs.yuma_params,
    )
    spec = variant_for_version("Yuma 1 (paper)")
    ys_x = simulate_batch(Wp, Sp, rip, rep, cfgs, spec, save_bonds=True)
    ys_f = simulate_batch(
        Wp, Sp, rip, rep, cfgs, spec, save_bonds=True,
        epoch_impl="fused_scan",
    )
    # beta must actually matter across the product (non-vacuity)
    assert not np.allclose(
        np.asarray(ys_x["dividends"][0]),
        np.asarray(ys_x["dividends"][len(cases)]),
    )
    ys_x.pop("numerics", None)  # observability sidecar, not a stream
    ys_f.pop("numerics", None)
    for k in ys_x:
        np.testing.assert_allclose(
            np.asarray(ys_f[k]), np.asarray(ys_x[k]),
            atol=2e-6, rtol=1e-5, err_msg=k,
        )
