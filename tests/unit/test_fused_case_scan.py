"""The streamed fused case scan: the reference's REAL workload (true
per-epoch weights/stakes, reset injection) on the flagship Pallas kernel.

Round-2 verdict item 1: `fused_ema_scan` only covered scalar-scaled
synthetic weights, so every real scenario fell back to the XLA scan.
`fused_case_scan` streams `W[E, V, M]` / `S[E, V]` blocks per grid step;
these tests pin it against the XLA engine (`_simulate_scan`) on every
bond model, liquid alpha, and both reset rules — and against the golden
reference CSV surface itself. Interpret mode off-TPU; the same program
compiles via Mosaic on chip (pinned there by tools/tpu_parity.py
artifacts).
"""

import csv
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tests.conftest import GOLDEN_DIR
from yuma_simulation_tpu.models.config import (
    SimulationHyperparameters,
    YumaConfig,
    YumaParams,
)
from yuma_simulation_tpu.models.epoch import BondsMode
from yuma_simulation_tpu.models.variants import canonical_versions, variant_for_version
from yuma_simulation_tpu.simulation.engine import (
    _simulate_case_fused,
    _simulate_scan,
    simulate,
    simulate_scaled_batch,
)

TOL = 1.5e-6  # the reference CSV surface's own 6-decimal precision


def _workload(seed=0, E=10, V=6, M=18):
    rng = np.random.default_rng(seed)
    W = jnp.asarray(rng.random((E, V, M)), jnp.float32)
    S = jnp.asarray(rng.random((E, V)) + 0.01, jnp.float32)
    return W, S


ALL_VERSIONS = [
    ("Yuma 0 (subtensor)", {}),
    ("Yuma 1 (paper)", {}),
    ("Yuma 1 (paper) - liquid alpha on", dict(liquid_alpha=True)),
    ("Yuma 2 (Adrian-Fish)", {}),
    ("Yuma 3 (Rhef)", {}),
    ("Yuma 3.1 (Rhef+reset)", {}),
    ("Yuma 3.2 (Rhef+conditional)", {}),
    ("Yuma 4 (Rhef+relative bonds)", {}),
    (
        "Yuma 4 (Rhef+relative bonds) - liquid alpha on",
        dict(liquid_alpha=True, bond_alpha=0.025, alpha_high=0.99, alpha_low=0.9),
    ),
]


@pytest.mark.parametrize(
    "version,params", ALL_VERSIONS, ids=[v for v, _ in ALL_VERSIONS]
)
def test_fused_case_scan_matches_xla_scan(version, params):
    W, S = _workload()
    ri = jnp.asarray(2, jnp.int32)
    re = jnp.asarray(4, jnp.int32)
    cfg = YumaConfig(yuma_params=YumaParams(**params))
    spec = variant_for_version(version)
    ys_x = _simulate_scan(W, S, ri, re, cfg, spec, save_consensus=True)
    ys_f = _simulate_case_fused(W, S, ri, re, cfg, spec, save_consensus=True)
    assert ys_x.keys() == ys_f.keys()
    for k in ys_x:
        np.testing.assert_allclose(
            np.asarray(ys_f[k]),
            np.asarray(ys_x[k]),
            atol=2e-6,
            rtol=1e-5,
            err_msg=f"{version}: {k}",
        )


@pytest.mark.parametrize(
    "version",
    ["Yuma 3.1 (Rhef+reset)", "Yuma 3.2 (Rhef+conditional)",
     "Yuma 4 (Rhef+relative bonds)"],
)
def test_fused_case_scan_reset_fires_like_xla(version):
    # A schedule where miner 3 builds bonds (epochs 0-2), then loses all
    # weight (epochs 3+): its consensus is exactly zero before the reset
    # epoch so the CONDITIONAL rule actually fires, while its bond column
    # is still nonzero (EMA/decay tail) so the reset visibly changes
    # state — not just the no-op metadata path.
    W, S = _workload(seed=3)
    W = W.at[3:, :, 3].set(0.0)
    ri = jnp.asarray(3, jnp.int32)
    re = jnp.asarray(5, jnp.int32)
    cfg = YumaConfig()
    spec = variant_for_version(version)
    ys_x = _simulate_scan(W, S, ri, re, cfg, spec)
    ys_f = _simulate_case_fused(W, S, ri, re, cfg, spec)
    for k in ys_x:
        np.testing.assert_allclose(
            np.asarray(ys_f[k]), np.asarray(ys_x[k]), atol=2e-6, rtol=1e-5
        )
    # and the reset genuinely zeroed the column at the reset epoch:
    # bonds[e=5, :, 3] comes from a fresh purchase, not the pre-reset EMA.
    ys_noreset = _simulate_case_fused(
        W, S, jnp.asarray(-1, jnp.int32), jnp.asarray(-1, jnp.int32), cfg, spec
    )
    assert not np.allclose(
        np.asarray(ys_f["bonds"][5]), np.asarray(ys_noreset["bonds"][5])
    )


def _golden_surface_worst(beta, versions):
    """Worst |fused - golden CSV| over all 14 cases for the versions."""
    from yuma_simulation_tpu.scenarios import cases

    with open(
        os.path.join(GOLDEN_DIR, f"total_dividends_b{beta}_full.csv")
    ) as f:
        golden = list(csv.DictReader(f))
    hp = SimulationHyperparameters(bond_penalty=float(beta))
    worst = 0.0
    for version, params in versions:
        cfg = YumaConfig(simulation=hp, yuma_params=params)
        for i, case in enumerate(cases):
            r = simulate(
                case,
                version,
                cfg,
                save_bonds=False,
                save_incentives=False,
                epoch_impl="fused_scan",
            )
            tot = np.asarray(r.dividends, np.float64).sum(axis=0)
            for j, std in enumerate(
                ["Validator A", "Validator B", "Validator C"]
            ):
                worst = max(
                    worst, abs(tot[j] - float(golden[i][f"{std} - {version}"]))
                )
    return worst


def _x64_safe_versions():
    # Since the double-single f64-quantize emulation (r4), every version
    # — Yuma 0 under x64 included — runs fused; kept as a named hook for
    # the golden-surface tests' history.
    return list(canonical_versions())


def test_fused_case_scan_golden_surface_beta1():
    """The parity artifact itself through the fused path (VERDICT r2
    item 1 'done' criterion): every case x version at beta=1.0 matches
    the reference CSV at its own 6-decimal precision."""
    worst = _golden_surface_worst(1.0, _x64_safe_versions())
    assert worst < TOL, f"fused-path golden drift {worst}"


@pytest.mark.slow
@pytest.mark.parametrize("beta", [0, 0.5, 0.99])
def test_fused_case_scan_golden_surface_other_betas(beta):
    worst = _golden_surface_worst(beta, _x64_safe_versions())
    assert worst < TOL, f"fused-path golden drift {worst} at beta={beta}"


def test_fused_case_scan_yuma0_golden_in_f32_subprocess():
    """Yuma 0's fused case scan in plain f32 mode (the x64 harness above
    runs the double-single emulation instead); pin it against both the
    XLA engine and the golden
    CSV rows in a subprocess with x64 off."""
    import subprocess
    import sys

    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    script = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
assert not jax.config.jax_enable_x64
import csv
import numpy as np
import jax.numpy as jnp
from yuma_simulation_tpu.models.config import YumaConfig
from yuma_simulation_tpu.models.variants import variant_for_version
from yuma_simulation_tpu.simulation.engine import (
    _simulate_case_fused, _simulate_scan, simulate,
)
from yuma_simulation_tpu.scenarios import cases

spec = variant_for_version("Yuma 0 (subtensor)")
rng = np.random.default_rng(5)
W = jnp.asarray(rng.random((10, 6, 18)), jnp.float32)
S = jnp.asarray(rng.random((10, 6)) + 0.01, jnp.float32)
ri = jnp.asarray(-1, jnp.int32)
cfg = YumaConfig()
ys_x = _simulate_scan(W, S, ri, ri, cfg, spec)
ys_f = _simulate_case_fused(W, S, ri, ri, cfg, spec)
for k in ys_x:
    np.testing.assert_allclose(
        np.asarray(ys_f[k]), np.asarray(ys_x[k]), atol=2e-6, rtol=1e-5
    )

with open("tests/golden/total_dividends_b1.0_full.csv") as f:
    golden = list(csv.DictReader(f))
worst = 0.0
for i, case in enumerate(cases):
    r = simulate(case, "Yuma 0 (subtensor)", cfg, save_bonds=False,
                 save_incentives=False, epoch_impl="fused_scan")
    tot = np.asarray(r.dividends, np.float64).sum(axis=0)
    for j, std in enumerate(["Validator A", "Validator B", "Validator C"]):
        worst = max(
            worst,
            abs(tot[j] - float(golden[i][f"{std} - Yuma 0 (subtensor)"])),
        )
assert worst < 1.5e-6, worst
print("YUMA0_CASE_SCAN_OK")
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in [repo, env.get("PYTHONPATH", "")] if p
    )
    env.pop("JAX_ENABLE_X64", None)
    out = subprocess.run(
        [sys.executable, "-c", script],
        cwd=repo,
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert out.returncode == 0, out.stderr[-4000:]
    assert "YUMA0_CASE_SCAN_OK" in out.stdout


def test_simulate_epoch_impl_routing():
    from yuma_simulation_tpu.scenarios import cases

    case = cases[0]
    cfg = YumaConfig()
    # auto off-TPU resolves to the XLA path and matches it exactly.
    r_auto = simulate(case, "Yuma 1 (paper)", cfg)
    r_xla = simulate(case, "Yuma 1 (paper)", cfg, epoch_impl="xla")
    if jax.default_backend() != "tpu":
        np.testing.assert_array_equal(r_auto.dividends, r_xla.dividends)
        np.testing.assert_array_equal(r_auto.bonds, r_xla.bonds)
    # forcing the fused path (interpret off-TPU) matches to rounding.
    r_fused = simulate(case, "Yuma 1 (paper)", cfg, epoch_impl="fused_scan")
    np.testing.assert_allclose(
        r_fused.dividends, r_xla.dividends, atol=2e-6, rtol=1e-5
    )
    # The MXU scan is BITWISE the VPU scan (r4: exact limb-split
    # support; the contract `auto` relies on — on-chip twin pinned by
    # CROSS_ENGINE*.json's mxu_vs_vpu_bitwise_mismatch_runs=0 and
    # MXU_PARITY.json at the shared 1.5e-6 golden bound).
    r_mxu = simulate(case, "Yuma 1 (paper)", cfg, epoch_impl="fused_scan_mxu")
    np.testing.assert_array_equal(r_mxu.dividends, r_fused.dividends)
    np.testing.assert_array_equal(r_mxu.bonds, r_fused.bonds)
    with pytest.raises(ValueError, match="epoch_impl"):
        simulate(case, "Yuma 1 (paper)", cfg, epoch_impl="nope")


@pytest.mark.parametrize("V", [24, 510, 1024])
def test_mxu_scan_bitwise_equals_vpu_scan(V):
    """The r4 exact-MXU contract at both limb regimes (15-bit limbs for
    V <= 512, 10-bit for V <= 2^14): every output of the MXU case scan
    must be bit-identical to the VPU case scan. Interpret mode computes
    the dot in plain f32, which is exact on the limb-split operands for
    the same reason the bf16 MXU is — this pins the split/recombination
    logic; the hardware cast is pinned on chip by the artifacts."""
    rng = np.random.default_rng(V)
    E, M = 4, 64
    W = jnp.asarray(rng.random((E, V, M)), jnp.float32)
    S = jnp.asarray(rng.random((E, V)) + 0.01, jnp.float32)
    ri = jnp.asarray(-1, jnp.int32)
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 1 (paper)")
    ys_v = _simulate_case_fused(W, S, ri, ri, cfg, spec, save_consensus=True)
    ys_m = _simulate_case_fused(
        W, S, ri, ri, cfg, spec, save_consensus=True, mxu=True
    )
    for k in ys_v:
        np.testing.assert_array_equal(
            np.asarray(ys_m[k]), np.asarray(ys_v[k]), err_msg=f"V={V}: {k}"
        )


def test_stake_limb_split_recombines_exactly():
    """_stake_limb_split / _support_limbs_mxu vs an integer oracle at
    both limb regimes, including the 2^30 == stake-1.0 top-limb bit."""
    from yuma_simulation_tpu.ops.pallas_epoch import (
        _stake_limb_split,
        _support_limbs_mxu,
    )

    for V in (8, 512, 4096):
        rng = np.random.default_rng(V)
        # Canonical normalized stakes: column sum ~= 2^30 (the helpers'
        # precondition — support_fixed_stakes of S with sum(S) == 1).
        raw = rng.random(V) + 1e-3
        S_int = np.round(raw / raw.sum() * 2**30).astype(np.int64)[:, None]
        if V == 8:
            # the stake-1.0 edge: one validator holds everything
            S_int = np.zeros((V, 1), np.int64)
            S_int[0, 0] = 2**30
        rows, bits = _stake_limb_split(
            jnp.asarray(S_int, jnp.int32), V, jnp.float32
        )
        # limbs recombine to the stakes exactly
        n = rows.shape[0] // 2
        rec = np.zeros(V, np.int64)
        rows_np = np.asarray(rows, np.float64)
        for j in range(n):
            rec = (rec << bits) + (
                rows_np[2 * j] + rows_np[2 * j + 1]
            ).astype(np.int64)
        np.testing.assert_array_equal(rec, S_int[:, 0])
        # masked support equals the integer oracle
        mask = (rng.random((V, 64)) > 0.5).astype(np.float32)
        got = np.asarray(
            _support_limbs_mxu(rows, bits, jnp.asarray(mask))
        )[0]
        oracle = mask.T.astype(np.int64) @ S_int[:, 0]
        np.testing.assert_array_equal(got.astype(np.int64), oracle)


@pytest.mark.parametrize(
    "overrides",
    [
        dict(override_consensus_high=0.03),
        dict(override_consensus_low=0.001),
        dict(override_consensus_high=0.03, override_consensus_low=0.001),
        # equal overrides collapse the spread -> the reference's
        # 0.99-quantile degenerate fallback must fire in-kernel too
        dict(override_consensus_high=0.02, override_consensus_low=0.02),
    ],
    ids=["high", "low", "both", "degenerate"],
)
def test_fused_liquid_overrides_match_xla(overrides):
    """Consensus-quantile overrides run IN-KERNEL on the fused paths
    (static compile-time constants replacing the joint quantile
    selection, reference yumas.py:124-133) and must match the XLA
    engine, including the degenerate equal-override fallback.

    Random data, not a built-in case: the 14-case suite's 2-miner
    consensus is exactly {0, 1}, which saturates the liquid-alpha
    sigmoid clamp for ANY quantile fit — overrides provably change
    nothing there, so a case-based comparison would pass vacuously.
    The override magnitudes are chosen near the random C scale
    (~1/64 per miner) and each run asserts the override actually
    moved the bonds before asserting the engines agree on them."""
    from yuma_simulation_tpu.simulation.engine import (
        _simulate_case_fused,
        _simulate_scan,
    )

    rng = np.random.default_rng(7)
    E, V, M = 8, 16, 64
    W = jnp.asarray(rng.random((E, V, M)).astype(np.float32))
    S = jnp.asarray(rng.random((E, V)).astype(np.float32) + 0.01)
    ri = jnp.asarray(-1, jnp.int32)
    re = jnp.asarray(-1, jnp.int32)
    cfg = YumaConfig(yuma_params=YumaParams(liquid_alpha=True, **overrides))
    base = YumaConfig(yuma_params=YumaParams(liquid_alpha=True))
    for version in (
        "Yuma 1 (paper) - liquid alpha on",
        "Yuma 4 (Rhef+relative bonds) - liquid alpha on",
    ):
        spec = variant_for_version(version)
        ys_base = _simulate_scan(W, S, ri, re, base, spec, save_bonds=True)
        ys_xla = _simulate_scan(W, S, ri, re, cfg, spec, save_bonds=True)
        ys_fused = _simulate_case_fused(
            W, S, ri, re, cfg, spec, save_bonds=True
        )
        effect = float(
            np.abs(
                np.asarray(ys_xla["bonds"]) - np.asarray(ys_base["bonds"])
            ).max()
        )
        assert effect > 1e-3, (
            f"override {overrides} had no effect on {version}; the "
            "agreement assertion below would be vacuous"
        )
        np.testing.assert_allclose(
            ys_fused["bonds"], ys_xla["bonds"], atol=2e-6, rtol=2e-5,
            err_msg=f"{version} {overrides}",
        )
        np.testing.assert_allclose(
            ys_fused["dividends"], ys_xla["dividends"], atol=2e-6, rtol=2e-5,
            err_msg=f"{version} {overrides}",
        )


def test_simulate_scaled_batch_rejects_unknown_impl():
    W = jnp.ones((2, 4, 8), jnp.float32)
    S = jnp.ones((2, 4), jnp.float32)
    ones = jnp.ones(3, jnp.float32)
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 1 (paper)")
    # A typo'd impl must not silently benchmark the XLA path under the
    # wrong label ("fused_scan_mxu" itself is valid since r4 — the batch
    # rides the dot's batch dimensions).
    with pytest.raises(ValueError, match="epoch_impl"):
        simulate_scaled_batch(W, S, ones, cfg, spec, epoch_impl="nope")


def test_simulate_scaled_rejects_unknown_impl():
    # A typo'd impl must not silently benchmark the XLA path under the
    # wrong label.
    from yuma_simulation_tpu.simulation.engine import simulate_scaled

    cfg = YumaConfig()
    spec = variant_for_version("Yuma 1 (paper)")
    with pytest.raises(ValueError, match="unknown epoch_impl"):
        simulate_scaled(
            jnp.ones((4, 8), jnp.float32),
            jnp.ones((4,), jnp.float32),
            jnp.ones(2, jnp.float32),
            cfg,
            spec,
            epoch_impl="fused_scan_vpu",
        )


def test_simulate_fused_rejects_sorted_consensus():
    from yuma_simulation_tpu.scenarios import cases

    with pytest.raises(ValueError, match="bisect"):
        simulate(
            cases[0], "Yuma 1 (paper)", YumaConfig(),
            consensus_impl="sorted", epoch_impl="fused_scan",
        )


def test_simulate_fused_rejects_mesh():
    from yuma_simulation_tpu.parallel.mesh import make_mesh
    from yuma_simulation_tpu.scenarios import cases

    mesh = make_mesh()
    with pytest.raises(ValueError, match="single-core"):
        simulate(
            cases[0], "Yuma 1 (paper)", YumaConfig(),
            mesh=mesh, epoch_impl="fused_scan",
        )


def test_fused_case_scan_eligible_gating():
    from yuma_simulation_tpu.ops.pallas_epoch import fused_case_scan_eligible

    cfg = YumaConfig()
    on_tpu = jax.default_backend() == "tpu"
    shape = (40, 256, 4096)
    assert fused_case_scan_eligible(shape, BondsMode.EMA, cfg) == on_tpu
    assert fused_case_scan_eligible(shape, BondsMode.CAPACITY, cfg) == on_tpu
    # f64 arrays are never eligible (the Pallas kernels are f32-only)
    assert not fused_case_scan_eligible(shape, BondsMode.EMA, cfg, jnp.float64)
    # over the VMEM budget is never eligible
    assert not fused_case_scan_eligible((40, 8192, 65536), BondsMode.EMA, cfg)
    # liquid-alpha quantile overrides are supported in-kernel (r4) and
    # no longer gate eligibility
    liquid_override = YumaConfig(
        yuma_params=YumaParams(
            liquid_alpha=True, override_consensus_high=0.5
        )
    )
    assert (
        fused_case_scan_eligible(shape, BondsMode.EMA, liquid_override)
        == on_tpu
    )
    assert (
        fused_case_scan_eligible(shape, BondsMode.CAPACITY, liquid_override)
        == on_tpu  # CAPACITY ignores the liquid fit entirely
    )


@pytest.mark.parametrize(
    "mode",
    [BondsMode.EMA, BondsMode.EMA_PREV, BondsMode.CAPACITY, BondsMode.RELATIVE],
    ids=lambda m: m.name,
)
@pytest.mark.parametrize("liquid", [False, True], ids=["plain", "liquid"])
def test_fused_ema_scan_batched_matches_per_scenario(mode, liquid):
    """The scenario-batch axis of fused_ema_scan (VERDICT r2 item 3):
    each batch element reproduces its own single-scenario scan."""
    from yuma_simulation_tpu.ops.pallas_epoch import fused_ema_scan

    rng = np.random.default_rng(7)
    B, V, M, E = 3, 8, 16, 7
    W = jnp.asarray(rng.random((B, V, M)), jnp.float32)
    S = jnp.asarray(rng.random((B, V)) + 0.01, jnp.float32)
    S = S / S.sum(axis=1, keepdims=True)
    scales = jnp.asarray(1.0 + 1e-4 * rng.random(E), jnp.float32)
    Bf, Df = fused_ema_scan(
        W, S, scales, mode=mode, liquid_alpha=liquid, interpret=True
    )
    assert Bf.shape == (B, V, M) and Df.shape == (B, V)
    for i in range(B):
        Bi, Di = fused_ema_scan(
            W[i], S[i], scales, mode=mode, liquid_alpha=liquid, interpret=True
        )
        np.testing.assert_allclose(np.asarray(Bf[i]), np.asarray(Bi), atol=1e-7)
        np.testing.assert_allclose(np.asarray(Df[i]), np.asarray(Di), atol=1e-7)


def test_fused_ema_scan_batched_mxu_accepted():
    # r4: the batched MXU scan is supported (leading dims ride the dot's
    # batch dimensions) and bitwise the batched VPU scan — pinned by
    # tests/unit/test_fused_epoch.py::test_batched_mxu_scan_bitwise_equals_vpu_scan.
    from yuma_simulation_tpu.ops.pallas_epoch import fused_ema_scan

    W = jnp.ones((2, 4, 8), jnp.float32)
    S = jnp.ones((2, 4), jnp.float32) / 4
    b_m, d_m = fused_ema_scan(
        W, S, jnp.ones(3, jnp.float32), mxu=True, interpret=True
    )
    b_v, d_v = fused_ema_scan(
        W, S, jnp.ones(3, jnp.float32), mxu=False, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(b_m), np.asarray(b_v))
    np.testing.assert_array_equal(np.asarray(d_m), np.asarray(d_v))


def test_simulate_scaled_batch_fused_matches_xla():
    rng = np.random.default_rng(11)
    B, V, M, E = 3, 8, 16, 9
    W = jnp.asarray(rng.random((B, V, M)), jnp.float32)
    S = jnp.asarray(rng.random((B, V)) + 0.01, jnp.float32)
    scales = jnp.asarray(1.0 + 1e-4 * rng.random(E), jnp.float32)
    cfg = YumaConfig()
    spec = variant_for_version("Yuma 1 (paper)")
    tx, bx = simulate_scaled_batch(W, S, scales, cfg, spec, epoch_impl="xla")
    tf, bf = simulate_scaled_batch(
        W, S, scales, cfg, spec, epoch_impl="fused_scan"
    )
    np.testing.assert_allclose(np.asarray(tf), np.asarray(tx), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(bf), np.asarray(bx), atol=2e-6)
    # auto must run everywhere (off-TPU it is the XLA path).
    ta, _ = simulate_scaled_batch(W, S, scales, cfg, spec, epoch_impl="auto")
    np.testing.assert_allclose(np.asarray(ta), np.asarray(tx), rtol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize(
    "seed,E,V,M,version,liquid",
    [
        (20, 8, 6, 20, "Yuma 0 (subtensor)", False),  # EMA_RUST (f32 only)
        (21, 13, 3, 2, "Yuma 1 (paper)", False),  # reference case shape
        (22, 7, 9, 33, "Yuma 2 (Adrian-Fish)", False),  # non-aligned dims
        (23, 5, 17, 130, "Yuma 3 (Rhef)", False),  # M just past one lane tile
        (24, 11, 8, 128, "Yuma 4 (Rhef+relative bonds)", True),  # aligned + liquid
        (25, 9, 2, 5, "Yuma 1 (paper) - liquid alpha on", True),  # tiny + liquid
        (26, 6, 12, 64, "Yuma 3.2 (Rhef+conditional)", False),  # conditional reset
    ],
)
def test_fused_case_scan_fuzz_vs_xla(seed, E, V, M, version, liquid):
    """Shape/seed fuzz of the DEFAULT TPU path (`epoch_impl="auto"` ->
    fused_case_scan) against the XLA engine: sparse weights (zero rows
    and zero columns included), duplicate values, reset metadata — the
    structures the golden cases don't randomize over.

    Consensus tolerance: since r5 the XLA engine's row normalization
    uses the partition-invariant miner_sum spelling at M % 8 == 0 while
    the fused kernel keeps its plain in-kernel reduce (DESIGN.md
    "Bitwise miner-axis sharding", residual class) — a knife-edge W_n
    ulp can shift one bisection outcome by exactly one u16 grid step.
    Observed exactly once across this battery (seed 26, M=64, 1/384
    cells). Differing consensus cells must BE that class: one grid
    step, on a handful of cells; anything larger or more widespread
    fails."""
    rng = np.random.default_rng(seed)
    W = rng.random((E, V, M)).astype(np.float32)
    W[W < 0.3] = 0.0  # sparse, with whole-zero rows/columns likely
    W[:, :, min(1, M - 1)] = 0.0  # a guaranteed all-zero miner column
    S = (rng.random((E, V)) + 0.001).astype(np.float32)
    Wj, Sj = jnp.asarray(W), jnp.asarray(S)
    ri = jnp.asarray(int(rng.integers(0, M)), jnp.int32)
    re = jnp.asarray(int(rng.integers(1, E)), jnp.int32)
    params = {}
    if liquid:
        params = dict(liquid_alpha=True)
    cfg = YumaConfig(yuma_params=YumaParams(**params))
    spec = variant_for_version(version)
    ys_x = _simulate_scan(Wj, Sj, ri, re, cfg, spec, save_consensus=True)
    ys_f = _simulate_case_fused(Wj, Sj, ri, re, cfg, spec, save_consensus=True)
    assert ys_x.keys() == ys_f.keys()
    grid = 1.0 / 65535.0
    # Knife-edge class bounds: a flipped consensus cell moves exactly
    # one grid step; its knock-on through the rank contraction bounds
    # the incentive shift at ~2 grid steps (same rationale as the old
    # r4 sharded tolerances).
    edge_bounds = {"consensus": grid, "incentives": 2 * grid}
    for k in ys_x:
        a, b = np.asarray(ys_f[k]), np.asarray(ys_x[k])
        if k in edge_bounds and M % 8 == 0 and M >= 16:
            diff = np.abs(a - b)
            flipped = diff > 3e-6
            assert flipped.mean() <= 0.01, (
                f"{version} seed={seed}: {flipped.sum()}/{flipped.size} "
                f"{k} cells differ — more than the knife-edge class"
            )
            assert diff.max() <= edge_bounds[k] * 1.0000001, (
                f"{version} seed={seed}: {k} deviation "
                f"{diff.max()} exceeds the knife-edge bound"
            )
            continue
        np.testing.assert_allclose(
            a,
            b,
            atol=3e-6,
            rtol=2e-5,
            err_msg=f"{version} seed={seed} shape=({E},{V},{M}): {k}",
        )


def test_simulate_consensus_auto_defers_to_engine():
    """consensus_impl="auto": off-TPU the XLA branch resolves the
    shape-gated default (sorted at small shapes — bitwise twin of
    bisect); on TPU it must NOT block the fused path. Both directions
    produce the default-path values exactly."""
    from yuma_simulation_tpu.scenarios import cases
    from yuma_simulation_tpu.simulation.engine import simulate_constant

    cfg = YumaConfig()
    r_def = simulate(cases[0], "Yuma 1 (paper)", cfg)
    r_auto = simulate(cases[0], "Yuma 1 (paper)", cfg, consensus_impl="auto")
    np.testing.assert_array_equal(r_auto.dividends, r_def.dividends)
    np.testing.assert_array_equal(r_auto.bonds, r_def.bonds)
    # Forcing the fused path with auto consensus is allowed (the kernel
    # bisects); forcing it with sorted still raises.
    r_fused = simulate(
        cases[0], "Yuma 1 (paper)", cfg,
        consensus_impl="auto", epoch_impl="fused_scan",
    )
    np.testing.assert_allclose(
        r_fused.dividends, r_def.dividends, atol=2e-6, rtol=1e-5
    )
    with pytest.raises(ValueError, match="bisect"):
        simulate(
            cases[0], "Yuma 1 (paper)", cfg,
            consensus_impl="sorted", epoch_impl="fused_scan",
        )
    # simulate_constant resolves the static "auto" at trace time; the
    # values are bitwise those of the forced twin implementations.
    W = jnp.asarray(np.random.default_rng(3).random((6, 12)), jnp.float32)
    S = jnp.ones((6,), jnp.float32)
    spec = variant_for_version("Yuma 1 (paper)")
    t_auto, _ = simulate_constant(W, S, 5, cfg, spec, consensus_impl="auto")
    t_sorted, _ = simulate_constant(W, S, 5, cfg, spec, consensus_impl="sorted")
    np.testing.assert_array_equal(np.asarray(t_auto), np.asarray(t_sorted))


def test_consensus_impl_validated_everywhere():
    """Typos must raise on every entry point, not silently run a
    dispatch fallback (one shared contract: resolve_consensus_impl)."""
    from yuma_simulation_tpu.scenarios import cases
    from yuma_simulation_tpu.simulation.engine import (
        simulate_constant,
        simulate_scaled,
    )

    cfg = YumaConfig()
    spec = variant_for_version("Yuma 1 (paper)")
    W = jnp.ones((4, 8), jnp.float32)
    S = jnp.ones((4,), jnp.float32)
    ones = jnp.ones(2, jnp.float32)
    with pytest.raises(ValueError, match="unknown consensus_impl"):
        simulate(cases[0], "Yuma 1 (paper)", cfg, consensus_impl="atuo")
    with pytest.raises(ValueError, match="unknown consensus_impl"):
        simulate_constant(W, S, 2, cfg, spec, consensus_impl="atuo")
    with pytest.raises(ValueError, match="unknown consensus_impl"):
        simulate_scaled(W, S, ones, cfg, spec, consensus_impl="atuo")
    with pytest.raises(ValueError, match="unknown consensus_impl"):
        simulate_scaled_batch(
            W[None], S[None], ones, cfg, spec, consensus_impl="atuo"
        )
    # "auto" runs on all four (values pinned by the sibling test).
    simulate(cases[0], "Yuma 1 (paper)", cfg, consensus_impl="auto")
    simulate_constant(W, S, 2, cfg, spec, consensus_impl="auto")
    simulate_scaled(W, S, ones, cfg, spec, consensus_impl="auto")
    simulate_scaled_batch(W[None], S[None], ones, cfg, spec, consensus_impl="auto")
