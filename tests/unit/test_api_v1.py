"""Public v1 API + scripts: end-to-end chart/CSV generation.

Mirrors the reference's only real test (reference api_test.py:8-26 — the
HTML smoke test) and extends it: incentives-row rule, simulation reuse
across chart types, script CLIs writing the reference-named artifacts.
"""

import pandas as pd
import pytest
from bs4 import BeautifulSoup

from yuma_simulation_tpu.models.config import SimulationHyperparameters, YumaParams
from yuma_simulation_tpu.scenarios import create_case, get_cases
from yuma_simulation_tpu.v1.api import generate_chart_table, run_simulation


@pytest.fixture(scope="module")
def two_version_list():
    return [
        ("Yuma 1 (paper)", YumaParams()),
        ("Yuma 3 (Rhef)", YumaParams()),
    ]


def test_simulate_suite_matches_per_case(two_version_list):
    """The batched chart-suite simulation (one dispatch per version)
    un-pads back to exactly what per-case `run_simulation` produces —
    including a heterogeneous suite where padding is NOT a no-op."""
    import numpy as np

    from yuma_simulation_tpu.models.config import YumaConfig
    from yuma_simulation_tpu.scenarios.synthetic import random_subnet_scenario
    from yuma_simulation_tpu.v1.api import _simulate_suite

    suite = [
        create_case("Case 1"),  # 40e x 3v x 2m
        random_subnet_scenario(7, num_validators=5, num_miners=4, num_epochs=30),
    ]
    hp = SimulationHyperparameters(bond_penalty=0.99)
    out = _simulate_suite(suite, two_version_list, hp)
    assert set(out) == {
        (i, v) for i in range(len(suite)) for v, _ in two_version_list
    }
    for (i, version), (config, (div, bonds, inc)) in out.items():
        case = suite[i]
        E, V, M = case.weights.shape
        ref_div, ref_bonds, ref_inc = run_simulation(
            case, version, YumaConfig(simulation=hp, yuma_params=config.yuma_params)
        )
        assert list(div) == list(ref_div)
        for val in div:
            np.testing.assert_allclose(
                div[val], ref_div[val], rtol=2e-5, atol=2e-6,
                err_msg=f"{version} case {i} {val}",
            )
        assert len(bonds) == E == len(ref_bonds) and bonds[0].shape == (V, M)
        assert len(inc) == E == len(ref_inc) and inc[0].shape == (M,)
        np.testing.assert_allclose(
            np.asarray(bonds), np.asarray(ref_bonds), rtol=2e-5, atol=2e-6
        )
        np.testing.assert_allclose(
            np.asarray(inc), np.asarray(ref_inc), rtol=2e-5, atol=2e-6
        )


def test_generate_chart_table_with_charts(two_version_list):
    cases = get_cases()[:2]
    html = generate_chart_table(
        cases, two_version_list, SimulationHyperparameters(bond_penalty=0.99)
    )
    soup = BeautifulSoup(html.data, "html.parser")
    imgs = soup.find_all("img")
    # 2 cases x 4 chart types x 2 versions
    assert len(imgs) == 16
    assert all(i["src"].startswith("data:image/png;base64,") for i in imgs)


def test_incentives_row_for_cases_10_and_11(two_version_list):
    # The reference adds the incentives chart for positional indices 9/10
    # of the full suite — Cases 10 and 11 (reference v1/api.py:42-45). We
    # carry that on the scenario itself so it survives subsets.
    cases = get_cases()
    assert [c.plot_incentives for c in cases].count(True) == 2
    assert cases[9].plot_incentives and cases[10].plot_incentives

    html = generate_chart_table(
        [cases[9]], two_version_list[:1], SimulationHyperparameters()
    )
    soup = BeautifulSoup(html.data, "html.parser")
    # Case 10 keeps its incentives row even as a 1-element subset.
    assert len(soup.find_all("img")) == 5

    html = generate_chart_table(
        cases[:10], two_version_list[:1], SimulationHyperparameters()
    )
    soup = BeautifulSoup(html.data, "html.parser")
    # 9 plain cases x 4 rows + Case 10's 5 rows = 41 images
    assert len(soup.find_all("img")) == 41


def test_run_simulation_shapes():
    case = create_case("Case 3")
    dividends, bonds, incentives = run_simulation(case, "Yuma 2 (Adrian-Fish)")
    assert set(dividends) == set(case.validators)
    assert all(len(v) == case.num_epochs for v in dividends.values())
    assert len(bonds) == case.num_epochs
    assert bonds[0].shape == (3, 2)
    assert len(incentives) == case.num_epochs
    assert incentives[0].shape == (2,)


def test_total_dividends_script(tmp_path, monkeypatch):
    from scripts.total_dividends_sheet_generator import main

    main(["--bond-penalty", "1.0", "--out-dir", str(tmp_path)])
    out = tmp_path / "total_dividends_b1.0.csv"
    assert out.exists()
    df = pd.read_csv(out)
    assert len(df) == 14
    assert not df.isnull().values.any()
    # 1 case col + 9 versions x 3 validators
    assert len(df.columns) == 1 + 27


def test_charts_script(tmp_path):
    from scripts.charts_table_generator import main

    main(
        [
            "--bond-penalty",
            "0.5",
            "--cases",
            "Case 1",
            "--out-dir",
            str(tmp_path),
        ]
    )
    out = tmp_path / "simulation_results_b0.5.html"
    assert out.exists()
    soup = BeautifulSoup(out.read_text(), "html.parser")
    imgs = soup.find_all("img")
    assert len(imgs) == 9 * 4  # 9 canonical versions x 4 chart types
    assert all(i["src"].startswith("data:image/png;base64,") for i in imgs)


@pytest.mark.slow
def test_full_suite_chart_regression():
    """The reference's own e2e surface (reference api_test.py:8-26) at
    full width: all 14 cases x all 9 canonical versions. 14x4 chart rows
    + 2 incentives rows (Cases 10/11) = 58 rows x 9 versions = 522
    images, with case-parity row shading alternating per case block."""
    from yuma_simulation_tpu.models.variants import canonical_versions

    cases = get_cases()
    assert len(cases) == 14
    versions = canonical_versions()
    assert len(versions) == 9

    html = generate_chart_table(
        cases,
        versions,
        SimulationHyperparameters(bond_penalty=0.99),
        draggable_table=True,
    )
    soup = BeautifulSoup(html.data, "html.parser")
    imgs = soup.find_all("img")
    assert len(imgs) == (14 * 4 + 2) * 9 == 522
    assert all(i["src"].startswith("data:image/png;base64,") for i in imgs)

    # Row shading: one parity class per row, constant within each case
    # block and alternating between consecutive cases (10 and 11 carry 5
    # rows, the rest 4).
    rows = soup.find_all("tr")
    classes = [r.get("class", [""])[0] for r in rows if r.find("img")]
    expected_rows = [4] * 9 + [5, 5] + [4] * 3
    assert len(classes) == sum(expected_rows) == 58
    pos = 0
    for case_idx, n_rows in enumerate(expected_rows):
        block = classes[pos : pos + n_rows]
        parity = "even" if case_idx % 2 == 0 else "odd"
        assert set(block) == {f"yuma-case-{parity}"}, (case_idx, block)
        pos += n_rows
