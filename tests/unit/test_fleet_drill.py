"""The pod-level fleet chaos drill — ISSUE 7 acceptance capstone.

Multiprocess simulated hosts (the pattern of
test_distributed_multiprocess.py): one host SIGKILLed mid-sweep, one
live lease torn, a stall and a NaN lane injected on a third host, plus
an unfaulted oracle host in an identical subprocess environment. The
sweep must complete with healthy lanes bitwise-identical to the
unfaulted run, exactly one accepted publish per unit, and a
FleetHealthReport that reconciles with the merged ledgers
(`obsreport --check` exit 0) — the PR 3 single-host drill guarantee,
extended to the fleet.

slow+chaos: the CI chaos lane (`pytest -m "faultinject or chaos"`)
runs it; the fast tier-1 lane (`-m "not slow"`) skips the multi-minute
subprocess battery.
"""

import pytest


@pytest.mark.slow
@pytest.mark.chaos
def test_pod_level_fleet_chaos_drill(tmp_path, capsys):
    from yuma_simulation_tpu.fabric.simhost import run_drill

    # run_drill itself raises on ANY violated acceptance property:
    # host exit codes, completion, at-most-once publish, bitwise healthy
    # lanes, quarantine masking, ledger<->report reconciliation, and
    # per-finished-host bundle soundness.
    summary = run_drill(tmp_path / "drill", timeout=420.0)
    report = summary["report"]

    # Re-assert the headline acceptance criteria explicitly so a
    # regression names the exact guarantee lost.
    assert report.units_published == report.num_units
    assert "crash-host" in report.hosts_lost
    assert report.units_stolen >= 1
    assert report.stalls_killed >= 1
    assert report.lanes_quarantined >= 1
    assert not report.clean
    # the roster shrink mirrors MeshDegradation one level up
    assert any(
        "crash-host" in d.lost_device_ids for d in report.degradations
    )

    # obsreport --check over the drill store must exit 0 (the CI gate).
    from tools.obsreport import main as obsreport_main

    assert obsreport_main([summary["store"], "--check"]) == 0
    out = capsys.readouterr().out
    assert "fleet store is sound" in out
    # ONE stitched trace (ISSUE 9): the report renders a single
    # cross-process timeline and the per-unit rows name the executing
    # host inline.
    assert "stitched trace" in out
    assert "units (executing host inline):" in out

    # The per-host sloreport gate passes (no host captured an active
    # fast burn; the SIGKILLed host is skipped, not failed).
    from tools.sloreport import main as sloreport_main

    assert sloreport_main([summary["store"], "--check", "--require"]) == 0
    capsys.readouterr()

    # Tamper gate: orphan a host span by deleting the driver's bundle
    # spans — the stitched check must turn obsreport --check red.
    import pathlib

    from yuma_simulation_tpu.fabric.simhost import DRIVER_HOST_ID

    driver_spans = (
        pathlib.Path(summary["store"])
        / "hosts"
        / DRIVER_HOST_ID
        / "spans.jsonl"
    )
    assert driver_spans.exists()
    driver_spans.write_text("")
    assert obsreport_main([summary["store"], "--check"]) == 2
    err = capsys.readouterr().err
    assert "orphan" in err
