"""Continuous telemetry plane (ISSUE 19): rotating flight segments,
the live ops plane, on-demand profiling, and roofline-gap attribution.

The contract under test: under a RotationPolicy the span/metrics/
numerics streams append O(batch) into crash-safe size/age-bounded
segments (SIGKILL mid-append loses at most a torn tail; the tolerant
readers and the restarted writer both recover), retention NEVER
reclaims a segment an open run touched, `load_bundle` reads segmented
and monolithic layouts identically, the /debug/profile latch is
single-flight with a hard auto-stop deadline, and tools/perfattrib
either attributes every engine rung to its roofline or types the
reason it cannot."""

import io
import json
import os
import signal
import subprocess
import sys
import time
import pathlib

import numpy as np
import pytest

from yuma_simulation_tpu.telemetry import (
    MetricsRegistry,
    RunContext,
    check_bundle,
    load_bundle,
    span,
)
from yuma_simulation_tpu.telemetry.flight import (
    COMPACTED_NAME,
    FlightRecorder,
    RotationPolicy,
    SEAL_NAME,
    SEGMENT_PREFIX,
    SEGMENTS_DIR,
)
from yuma_simulation_tpu.telemetry.ops import (
    OpsPlane,
    ProfileBusyError,
    ProfileSession,
)
from yuma_simulation_tpu.telemetry.slo import (
    DispatchStats,
    LatencySketch,
    get_dispatch_stats,
    observe_dispatch,
    set_dispatch_observation,
)

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]

#: Age trigger disabled: every test below drives rotation by size.
SMALL = RotationPolicy(
    max_segment_bytes=512, max_segment_age_seconds=0.0
)


def _numerics_batch(run_id: str, n: int = 4) -> list:
    # well-formed numerics records (check_bundle validates them), with
    # `unit` — part of numerics_identity — distinct per record so the
    # loader's newest-wins dedupe keeps them all
    return [
        {"run_id": run_id, "unit": f"{run_id}:{i}", "stream": "dividends",
         "engine": "xla", "role": "primary", "epochs": 2,
         "fingerprint": [[i, i + 1]], "absmax": 1.0 + i}
        for i in range(n)
    ]


def _sealed_segments(directory) -> list:
    root = pathlib.Path(directory) / SEGMENTS_DIR
    if not root.is_dir():
        return []
    return sorted(
        p
        for p in root.iterdir()
        if p.name.startswith(SEGMENT_PREFIX) and (p / SEAL_NAME).exists()
    )


# ------------------------------------------------------------- rotation


def test_rotation_seals_on_size_and_bundle_reads_across_segments(tmp_path):
    rec = FlightRecorder(tmp_path, rotation=SMALL)
    for i in range(20):
        rec.append_numerics(_numerics_batch(f"run-{i}"))
    sealed = _sealed_segments(tmp_path)
    assert len(sealed) >= 2, "512-byte bound never tripped"
    for seg in sealed:
        seal = json.loads((seg / SEAL_NAME).read_text())
        assert seal["event"] == "segment_sealed"
        assert seal["segment"] == seg.name
        assert seal["bytes"] > 0
        assert isinstance(seal["run_ids"], list) and seal["run_ids"]
    # the loader stitches every segment back into one stream
    bundle = load_bundle(tmp_path)
    assert len(bundle.numerics) == 20 * 4
    assert {n["run_id"] for n in bundle.numerics} == {
        f"run-{i}" for i in range(20)
    }
    assert [s["segment"] for s in bundle.segments if s.get("event") ==
            "segment_sealed"] == [s.name for s in sealed]


def test_segmented_and_monolithic_bundles_read_identically(tmp_path):
    mono_dir, seg_dir = tmp_path / "mono", tmp_path / "seg"
    for directory, rotation in ((mono_dir, None), (seg_dir, SMALL)):
        reg = MetricsRegistry()
        reg.counter("requests_total").inc(3)
        rec = FlightRecorder(directory, rotation=rotation)
        with RunContext("run-io") as run:
            with span("outer"):
                with span("inner"):
                    pass
            rec.record(run, registry=reg)
        rec.append_numerics(_numerics_batch("run-io"))

    mono, seg = load_bundle(mono_dir), load_bundle(seg_dir)
    assert check_bundle(mono) == []
    assert check_bundle(seg) == []

    def canon(records, keys):
        return sorted(
            tuple(r.get(k) for k in keys) for r in records
        )

    span_keys = ("run_id", "span_id", "name", "status")
    assert canon(mono.spans, span_keys) == canon(seg.spans, span_keys)
    num_keys = ("run_id", "epoch", "absmax")
    assert canon(mono.numerics, num_keys) == canon(seg.numerics, num_keys)
    assert [m["counters"] for m in mono.metrics] == [
        m["counters"] for m in seg.metrics
    ]


def test_rotation_default_off_keeps_monolithic_layout(tmp_path, monkeypatch):
    monkeypatch.delenv("YUMA_TPU_FLIGHT_ROTATE", raising=False)
    rec = FlightRecorder(tmp_path)
    assert rec.rotation is None
    rec.append_numerics(_numerics_batch("run-legacy"))
    assert (tmp_path / "numerics.jsonl").exists()
    assert not (tmp_path / SEGMENTS_DIR).exists()


def test_rotation_env_opt_in(tmp_path, monkeypatch):
    monkeypatch.setenv("YUMA_TPU_FLIGHT_ROTATE", "1")
    assert FlightRecorder(tmp_path).rotation == RotationPolicy()
    monkeypatch.setenv("YUMA_TPU_FLIGHT_ROTATE", "off")
    assert FlightRecorder(tmp_path).rotation is None


# ------------------------------------------------- crash-safety (SIGKILL)

_KILL_CHILD = r"""
import sys
from yuma_simulation_tpu.telemetry.flight import FlightRecorder, RotationPolicy

rec = FlightRecorder(
    sys.argv[1],
    rotation=RotationPolicy(max_segment_bytes=512,
                            max_segment_age_seconds=0.0),
)
print("ready", flush=True)
i = 0
while True:
    rec.append_numerics(
        [{"run_id": f"child-{i}", "epoch": e} for e in range(4)]
    )
    i += 1
"""


def test_sigkill_mid_rotation_recovers(tmp_path):
    """SIGKILL a writer mid-append: the tolerant readers shrug off the
    torn tail, and a fresh recorder continues the predecessor's live
    segment instead of stranding it."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", _KILL_CHILD, str(tmp_path)],
        stdout=subprocess.PIPE,
        cwd=REPO_ROOT,
        env=env,
    )
    try:
        assert proc.stdout.readline().strip() == b"ready"
        deadline = time.time() + 30.0
        while not _sealed_segments(tmp_path) and time.time() < deadline:
            time.sleep(0.05)
    finally:
        proc.kill()
        proc.wait(timeout=30)
    sealed_before = _sealed_segments(tmp_path)
    assert sealed_before, "child never sealed a segment before the kill"

    # an explicitly torn tail on the live segment must not break readers
    rec = FlightRecorder(tmp_path, rotation=SMALL)
    live = rec.live_segment()
    with open(live / "numerics.jsonl", "ab") as fh:
        fh.write(b'{"run_id": "torn')
    bundle = load_bundle(tmp_path)
    assert any(n["run_id"].startswith("child-") for n in bundle.numerics)

    # the restarted writer continues exactly where the victim stopped
    before = len(bundle.numerics)
    rec.append_numerics(_numerics_batch("survivor"))
    rec.seal_live_segment()
    bundle = load_bundle(tmp_path)
    assert len(bundle.numerics) == before + 4
    assert set(s.name for s in sealed_before) < {
        s.name for s in _sealed_segments(tmp_path)
    }


# ------------------------------------------------------------- retention


def test_retention_never_deletes_open_run_segment(tmp_path):
    policy = RotationPolicy(
        max_segment_bytes=256,
        max_segment_age_seconds=0.0,
        max_retained_bytes=1,  # reclaim everything reclaimable
        min_retained_segments=0,
    )
    rec = FlightRecorder(tmp_path, rotation=policy)
    rec.mark_run_open("pinned")
    rec.append_numerics(_numerics_batch("pinned"))
    rec.seal_live_segment()
    pinned_seg = _sealed_segments(tmp_path)[-1].name
    for i in range(4):
        rec.append_numerics(_numerics_batch(f"bulk-{i}"))
        rec.seal_live_segment()

    names = {s.name for s in _sealed_segments(tmp_path)}
    assert pinned_seg in names, "retention reclaimed an open run's segment"
    tombstone = json.loads((tmp_path / COMPACTED_NAME).read_text())
    assert tombstone["event"] == "segments_compacted"
    assert tombstone["segments"] >= 1
    assert tombstone["bytes"] > 0
    assert "pinned" not in tombstone["run_ids"]
    # the pinned run's records are still readable
    assert any(
        n["run_id"] == "pinned" for n in load_bundle(tmp_path).numerics
    )

    # closing the run releases the pin: the next pass reclaims it
    rec.mark_run_closed("pinned")
    rec.append_numerics(_numerics_batch("after-close"))
    rec.seal_live_segment()
    assert pinned_seg not in {s.name for s in _sealed_segments(tmp_path)}
    tombstone = json.loads((tmp_path / COMPACTED_NAME).read_text())
    assert "pinned" in tombstone["run_ids"]


# ------------------------------------------------------- O(batch) flush


def test_flush_cost_stays_o_batch_under_rotation(tmp_path):
    """Soak-length proof that a long-lived server's periodic flush does
    not degrade as history accumulates: under rotation each flush
    touches ONLY the live segment, so (a) the bytes any flush rewrites
    stay bounded by the rotation policy however many flushes came
    before, and (b) late flushes are not slower than early ones."""
    rec = FlightRecorder(
        tmp_path,
        rotation=RotationPolicy(
            max_segment_bytes=4096, max_segment_age_seconds=0.0
        ),
    )
    rounds, batch = 300, 4
    durations = []
    for i in range(rounds):
        t0 = time.perf_counter()
        rec.append_numerics(_numerics_batch(f"soak-{i}", batch))
        durations.append(time.perf_counter() - t0)
        live_bytes = rec._segment_bytes(rec.live_segment())
        assert live_bytes < 4096 + 2048, (
            f"flush {i}: live segment grew past the rotation bound "
            f"({live_bytes} bytes) — flush cost is no longer O(batch)"
        )
    assert len(_sealed_segments(tmp_path)) >= 2
    early = sorted(durations[:50])[25]
    late = sorted(durations[-50:])[25]
    # generous: the medians must stay the same order of magnitude (a
    # whole-file merge republish would be ~60x by the last round)
    assert late < max(early, 1e-4) * 10, (
        f"flush latency grew {late / early:.1f}x over {rounds} rounds"
    )
    assert len(load_bundle(tmp_path).numerics) == rounds * batch


# ----------------------------------------------------- dispatch sketches


def test_dispatch_stats_snapshot_shape_and_merge():
    stats = DispatchStats()
    for seconds in (0.01, 0.02, 0.04):
        stats.observe(
            engine="xla", bucket="b256", backend="cpu",
            seconds=seconds, epochs=64,
        )
    snap = stats.snapshot()
    key = DispatchStats.key_for("xla", "b256", "cpu")
    assert set(snap) == {key}
    entry = snap[key]
    assert entry["dispatches"] == 3
    assert entry["epochs_total"] == 192
    assert entry["seconds_total"] == pytest.approx(0.07, abs=1e-6)
    sketch = LatencySketch.from_json(entry["sketch"])
    assert 0.01 <= sketch.quantile(0.5) <= 0.04


def test_dispatch_stats_bounded_cardinality_overflow():
    stats = DispatchStats(max_keys=2)
    for i in range(5):
        stats.observe(
            engine=f"e{i}", bucket="b", backend="cpu", seconds=0.01
        )
    snap = stats.snapshot()
    assert len(snap) <= 3  # 2 real keys + the overflow absorber
    assert sum(e["dispatches"] for e in snap.values()) == 5


def test_set_dispatch_observation_suppresses_the_seam():
    stats = get_dispatch_stats()
    stats.reset()
    prev = set_dispatch_observation(False)
    try:
        observe_dispatch(
            engine="xla", bucket="off", backend="cpu", seconds=0.5
        )
        assert stats.snapshot() == {}
    finally:
        set_dispatch_observation(prev)
    observe_dispatch(engine="xla", bucket="on", backend="cpu", seconds=0.5)
    assert DispatchStats.key_for("xla", "on", "cpu") in stats.snapshot()


def test_simulate_feeds_dispatch_sketch_and_bundle_metrics(tmp_path):
    from yuma_simulation_tpu.scenarios import create_case
    from yuma_simulation_tpu.simulation.engine import simulate

    stats = get_dispatch_stats()
    stats.reset()
    case = create_case("Case 1")
    simulate(case, "Yuma 1 (paper)")
    snap = stats.snapshot()
    assert snap, "the dispatch seam observed nothing"
    entry = next(iter(snap.values()))
    assert entry["epochs_total"] >= case.num_epochs
    assert entry["seconds_total"] > 0

    # the sketches ride flight-bundle metrics lines as meta
    reg = MetricsRegistry()
    FlightRecorder(tmp_path).snapshot_metrics(reg, run_id="run-sk")
    line = load_bundle(tmp_path).metrics[-1]
    assert set(line["dispatch_sketches"]) == set(snap)


# --------------------------------------------- profiling (single-flight)


def test_profile_session_single_flight_and_deadline(tmp_path):
    sess = ProfileSession(tmp_path)
    started = sess.start(0.3, mode="trace")
    assert started["mode"] == "trace"
    with pytest.raises(ProfileBusyError) as err:
        sess.start(0.3, mode="trace")
    assert err.value.status["serial"] == started["serial"]

    # the deadline timer releases the latch without an operator stop
    # (poll on the publish count: the latch clears before the publish)
    deadline = time.time() + 10.0
    while sess.status()["profiles_published"] < 1 and time.time() < deadline:
        time.sleep(0.05)
    assert sess.status()["profiles_published"] == 1, (
        "auto-stop deadline never fired"
    )
    assert not sess.status()["active"]
    records = [
        json.loads(line)
        for line in (tmp_path / "profiles.jsonl").read_text().splitlines()
    ]
    assert records[-1]["event"] == "profile_published"
    assert records[-1]["artifact"] == started["artifact"]
    # jax writes the trace artifact at stop_trace — it exists now
    assert pathlib.Path(started["artifact"]).exists()
    # a new window is admissible once the latch is free
    sess.start(0.2, mode="trace")
    assert sess.stop() is not None
    assert sess.stop() is None  # idempotent


def test_profile_session_rejects_bad_requests(tmp_path):
    sess = ProfileSession(tmp_path)
    with pytest.raises(ValueError):
        sess.start(0.0)
    with pytest.raises(ValueError):
        sess.start(1.0, mode="flamegraph")
    with pytest.raises(ValueError):
        ProfileSession(None).start(1.0)


def test_ops_plane_debug_vars_and_spans(tmp_path):
    ops = OpsPlane(tmp_path)
    FlightRecorder(tmp_path, rotation=SMALL).append_numerics(
        _numerics_batch("ops-run")
    )
    with RunContext("ops-run") as run:
        ops.run = run
        with span("live-work"):
            vars_out = ops.debug_vars()
            spans_out = ops.debug_spans()
    assert vars_out["profile"]["active"] is False
    assert "segments" in vars_out
    assert any(
        s["name"] == "live-work" for s in spans_out["spans"].values()
    )
    ops.close()


# ------------------------------------------------------------ perfattrib


def _sketch_entry(engine, *, dispatches=8, epochs=512, seconds=2.0):
    sk = LatencySketch()
    for _ in range(dispatches):
        sk.observe(seconds / dispatches)
    return {
        "engine": engine,
        "bucket": "b",
        "backend": "cpu",
        "dispatches": dispatches,
        "epochs_total": epochs,
        "seconds_total": seconds,
        "sketch": sk.to_json(),
    }


def _history_record():
    return {
        "costs": {
            "xla": {"flops": 1e9, "bytes_accessed": 1e8, "reason": None},
            "fused_varying_mxu": {
                "flops": None,
                "reason": "Pallas rung unavailable on cpu",
            },
        },
        "rooflines": {
            "xla": {
                "predicted_epochs_per_sec": 400.0,
                "bound": "memory",
                "device": "cpu",
            },
        },
    }


def test_perfattrib_resolves_measured_rungs_and_types_the_rest():
    from tools.perfattrib import attribute, check_rows

    sketches = {"xla|b|cpu": _sketch_entry("xla")}
    rows = {r["engine"]: r for r in attribute(_history_record(), sketches)}

    xla = rows["xla"]
    assert xla["measured_source"] == "dispatch_sketches"
    assert xla["measured_epochs_per_sec"] == pytest.approx(256.0)
    assert xla["attained_fraction"] == pytest.approx(256.0 / 400.0)
    assert xla["limiter"]
    assert rows["fused_varying_mxu"]["reason_kind"] == "rung_unavailable"
    # rungs with neither cost nor sketch carry the no-cost reason
    assert rows["fused_scan"]["reason_kind"] == "no_cost_record"
    assert check_rows(list(rows.values())) == []


def test_perfattrib_check_flags_untyped_gaps():
    from tools.perfattrib import attribute, check_rows

    # attribute() always types its reasons; the gate exists to catch a
    # row that lost one (hand-edited history, a future refactor bug)
    rows = attribute(_history_record(), {})
    assert check_rows(rows) == []
    broken = next(r for r in rows if r["engine"] == "fused_varying_mxu")
    broken.pop("reason")
    problems = check_rows(rows)
    assert problems and "fused_varying_mxu" in problems[0]

    # a measured rung with no roofline gets the typed no-roofline reason
    record2 = _history_record()
    record2["rooflines"] = {}
    rows2 = {
        r["engine"]: r
        for r in attribute(record2, {"xla|b|cpu": _sketch_entry("xla")})
    }
    assert rows2["xla"]["reason_kind"] == "no_device_roofline"


def test_perfattrib_collect_sketches_keeps_cumulative_maximum():
    from tools.perfattrib import collect_sketches

    lines = [
        {"dispatch_sketches": {"k": _sketch_entry("xla", dispatches=3)}},
        {"dispatch_sketches": {"k": _sketch_entry("xla", dispatches=9)}},
        {"dispatch_sketches": {"k": _sketch_entry("xla", dispatches=6)}},
    ]
    assert collect_sketches(lines)["k"]["dispatches"] == 9


def test_perfattrib_check_passes_on_committed_history():
    """The ISSUE 19 acceptance gate, run exactly as CI does."""
    from tools.perfattrib import main

    history = REPO_ROOT / "BENCH_HISTORY.jsonl"
    assert main(["--history", str(history), "--check"]) == 0


# --------------------------------------------------------------- follow


def test_obsreport_follow_tails_a_live_segmented_bundle(tmp_path):
    from tools.obsreport import follow

    rec = FlightRecorder(tmp_path, rotation=SMALL)
    for i in range(10):
        rec.append_numerics(_numerics_batch(f"f-{i}"))
    rec.seal_live_segment()
    FlightRecorder(tmp_path).record_profile(
        {"event": "profile_published", "mode": "trace",
         "artifact": "profiles/trace_001", "seconds": 1.0, "serial": 1}
    )
    out = io.StringIO()
    follow(tmp_path, interval=0.05, max_seconds=0.3, out=out)
    text = out.getvalue()
    assert "seg_000000" in text
    assert "profile" in text
