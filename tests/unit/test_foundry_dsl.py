"""Scenario-foundry DSL: bitwise builtin re-expression, combinator
semantics, serialization round-trips, determinism (ISSUE 12 tentpole
pillar 1)."""

import json

import numpy as np
import pytest

from yuma_simulation_tpu.foundry import (
    Clause,
    CopyWithLag,
    NoisyConsensusFollower,
    OneHot,
    Rows,
    ScenarioSpec,
    SpecError,
    StakeDrift,
    Stakes,
    at_epochs,
    builtin_case_specs,
    compile_spec,
    overlay,
    sequence,
    spec_from_json,
    spec_key,
    spec_to_dict,
    spec_to_json,
)
from yuma_simulation_tpu.scenarios.base import create_case

# --------------------------------------------------- builtin bitwise pin


@pytest.mark.parametrize("case_name", sorted(builtin_case_specs()))
def test_builtin_case_compiles_bitwise_equal(case_name):
    """The acceptance pin: a built-in case re-expressed in the DSL
    compiles to the EXACT hand-built arrays — same bits, same metadata
    — so DSL output is interchangeable with the golden-pinned suite."""
    spec = builtin_case_specs()[case_name]
    dsl = compile_spec(spec)
    ref = create_case(case_name)
    np.testing.assert_array_equal(dsl.weights, ref.weights)
    np.testing.assert_array_equal(dsl.stakes, ref.stakes)
    assert dsl.weights.dtype == ref.weights.dtype == np.float32
    assert dsl.name == ref.name
    assert dsl.validators == ref.validators
    assert dsl.base_validator == ref.base_validator
    assert dsl.num_epochs == ref.num_epochs
    assert dsl.reset_bonds_index == ref.reset_bonds_index
    assert dsl.reset_bonds_epoch == ref.reset_bonds_epoch


def test_at_least_four_builtin_cases_are_reexpressed():
    assert len(builtin_case_specs()) >= 4


# ------------------------------------------------------------ combinators


def _tiny_spec(**kw):
    defaults = dict(
        name="tiny",
        validators=("a", "b"),
        base_validator="a",
        num_miners=2,
        num_epochs=6,
        stakes=sequence(Stakes((0.6, 0.4))),
    )
    defaults.update(kw)
    return ScenarioSpec(**defaults)


def test_later_clause_wins_on_overlap():
    spec = _tiny_spec(
        weights=sequence(
            OneHot((0, 0)),
            at_epochs(OneHot((1, 1)), 2, 4),
        )
    )
    W = compile_spec(spec).weights
    assert (W[:2, :, 0] == 1).all() and (W[2:4, :, 1] == 1).all()
    assert (W[4:, :, 0] == 1).all()


def test_overlay_concatenates_programs():
    base = sequence(OneHot((0, 0)))
    extra = at_epochs(OneHot((1, 1)), 3)
    spec = _tiny_spec(weights=overlay(base, extra))
    W = compile_spec(spec).weights
    assert (W[:3, :, 0] == 1).all() and (W[3:, :, 1] == 1).all()


def test_copy_with_lag_reproduces_lagged_rows():
    spec = _tiny_spec(
        weights=sequence(
            at_epochs(OneHot((0, 0)), 0, 3),
            at_epochs(OneHot((1, 1)), 3),
            CopyWithLag(dst=1, src=0, lag=2),
        )
    )
    W = compile_spec(spec).weights
    for e in range(6):
        np.testing.assert_array_equal(W[e, 1], W[max(e - 2, 0), 0])


def test_stake_drift_hits_both_endpoints():
    spec = _tiny_spec(
        stakes=sequence(StakeDrift((1.0, 0.0), (0.0, 1.0))),
        weights=sequence(OneHot((0, 0))),
    )
    S = compile_spec(spec).stakes
    np.testing.assert_array_equal(S[0], [1.0, 0.0])
    np.testing.assert_array_equal(S[-1], [0.0, 1.0])


def test_noisy_consensus_follower_is_deterministic_and_normalized():
    spec = _tiny_spec(
        validators=("a", "b", "c"),
        stakes=sequence(Stakes((0.5, 0.3, 0.2))),
        weights=sequence(
            Rows(((0.3, 0.7), (0.6, 0.4), (0.0, 0.0))),
            NoisyConsensusFollower(validator=2, sigma=0.1, seed=9),
        ),
    )
    a, b = compile_spec(spec), compile_spec(spec)
    np.testing.assert_array_equal(a.weights, b.weights)
    rows = a.weights[:, 2, :].sum(axis=1)
    np.testing.assert_allclose(rows, 1.0, rtol=1e-5)


# ------------------------------------------------------------ validation


def test_spec_rejects_unknown_base_validator():
    with pytest.raises(SpecError, match="base_validator"):
        _tiny_spec(base_validator="nobody")


def test_one_hot_rejects_out_of_range_miner():
    spec = _tiny_spec(weights=sequence(OneHot((0, 5))))
    with pytest.raises(SpecError, match="miner"):
        compile_spec(spec)


def test_index_carrying_primitives_are_bounds_checked():
    """Negative indices must not numpy-wrap and oversized ones must not
    escape as raw IndexError — every index-carrying primitive raises
    the typed SpecError (the spec format is a public wire surface)."""
    from yuma_simulation_tpu.foundry import BondReset, Takeover

    for bad in (-1, 7):
        with pytest.raises(SpecError, match="out of range"):
            compile_spec(
                _tiny_spec(
                    weights=sequence(
                        OneHot((0, 0)), CopyWithLag(dst=bad, src=0)
                    )
                )
            )
        with pytest.raises(SpecError, match="out of range"):
            compile_spec(
                _tiny_spec(
                    weights=sequence(
                        OneHot((0, 0)),
                        NoisyConsensusFollower(validator=bad),
                    )
                )
            )
        with pytest.raises(SpecError, match="out of range"):
            compile_spec(
                _tiny_spec(
                    weights=sequence(OneHot((0, 0))),
                    events=(Takeover(validator=bad, epoch=2),),
                )
            )
        with pytest.raises(SpecError, match="out of range"):
            compile_spec(
                _tiny_spec(
                    weights=sequence(OneHot((0, 0))),
                    events=(BondReset(index=0, epoch=bad),),
                )
            )


def test_takeover_preserves_per_epoch_totals():
    from yuma_simulation_tpu.foundry import Takeover

    spec = _tiny_spec(
        weights=sequence(OneHot((0, 0))),
        events=(Takeover(validator=1, epoch=2, stake_fraction=0.75),),
    )
    S = compile_spec(spec).stakes
    np.testing.assert_allclose(S.sum(axis=1), 1.0, rtol=1e-6)
    np.testing.assert_allclose(S[2:, 1], 0.75, rtol=1e-6)
    # degenerate: the taker already holds everything -> no-op, total kept
    spec2 = _tiny_spec(
        stakes=sequence(Stakes((0.0, 1.0))),
        weights=sequence(OneHot((0, 0))),
        events=(Takeover(validator=1, epoch=2, stake_fraction=0.6),),
    )
    S2 = compile_spec(spec2).stakes
    np.testing.assert_allclose(S2.sum(axis=1), 1.0, rtol=1e-6)


def test_compile_rejects_multiple_bond_resets():
    from yuma_simulation_tpu.foundry import BondReset

    spec = _tiny_spec(
        weights=sequence(OneHot((0, 0))),
        events=(BondReset(index=0, epoch=2), BondReset(index=1, epoch=4)),
    )
    with pytest.raises(SpecError, match="more than one BondReset"):
        compile_spec(spec)


def test_copier_builder_rejects_too_few_epochs():
    from yuma_simulation_tpu.foundry import weight_copier_scenario

    with pytest.raises(SpecError, match="too short"):
        weight_copier_scenario(0, num_epochs=9, num_segments=4)


def test_compile_rejects_unnormalized_rows():
    from yuma_simulation_tpu.scenarios.base import ScenarioValidationError

    spec = _tiny_spec(weights=sequence(Rows(((0.5, 0.1), (0.2, 0.2)))))
    with pytest.raises(ScenarioValidationError, match="sums to"):
        compile_spec(spec)


# --------------------------------------------------------- serialization


@pytest.mark.parametrize("case_name", sorted(builtin_case_specs()))
def test_spec_json_round_trip_compiles_bitwise(case_name):
    spec = builtin_case_specs()[case_name]
    restored = spec_from_json(spec_to_json(spec))
    assert restored == spec
    np.testing.assert_array_equal(
        compile_spec(restored).weights, compile_spec(spec).weights
    )


def test_spec_to_dict_is_json_clean_and_typed():
    spec = builtin_case_specs()["Case 1"]
    payload = spec_to_dict(spec)
    assert payload["format"] == "yuma-scenario-spec-v1"
    json.dumps(payload)  # no numpy leaks
    assert payload["weights"][0]["prim"]["type"] == "OneHot"


def test_spec_key_is_stable_and_content_addressed():
    a = builtin_case_specs()["Case 1"]
    b = builtin_case_specs()["Case 1"]
    c = builtin_case_specs()["Case 2"]
    assert spec_key(a) == spec_key(b)
    assert spec_key(a) != spec_key(c)


def test_unknown_primitive_type_is_rejected():
    from yuma_simulation_tpu.foundry import spec_from_dict

    payload = spec_to_dict(builtin_case_specs()["Case 1"])
    payload["weights"][0]["prim"]["type"] = "NotAPrimitive"
    with pytest.raises(SpecError, match="unknown primitive"):
        spec_from_dict(payload)


def test_missing_payload_keys_raise_spec_error_not_key_error():
    from yuma_simulation_tpu.foundry import spec_from_dict

    payload = spec_to_dict(builtin_case_specs()["Case 1"])
    del payload["base_validator"]
    with pytest.raises(SpecError, match="malformed"):
        spec_from_dict(payload)
    clause_less = spec_to_dict(builtin_case_specs()["Case 1"])
    del clause_less["weights"][0]["start"]
    with pytest.raises(SpecError, match="malformed"):
        spec_from_dict(clause_less)


def test_compile_is_deterministic():
    spec = builtin_case_specs()["Case 9"]
    a, b = compile_spec(spec), compile_spec(spec)
    np.testing.assert_array_equal(a.weights, b.weights)
    np.testing.assert_array_equal(a.stakes, b.stakes)
    assert a.weights is not b.weights  # independent arrays


def test_clause_bounds_clamp_to_scenario():
    clause = Clause(OneHot((0, 0)), start=4, stop=99)
    assert clause.bounds(6) == (4, 6)
